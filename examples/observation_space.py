#!/usr/bin/env python
"""Print the observation space an agent will see for a given config
(reference: ``examples/observation_space.py``).

The observation space depends on the env backend AND the agent's
cnn/mlp key selection (the factory wraps, resizes and dict-ifies
accordingly), so this composes the REAL config and builds the REAL env:

    python examples/observation_space.py exp=ppo env=dummy env.id=discrete_dummy
    python examples/observation_space.py exp=dreamer_v3 env=atari_dummy
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(args) -> None:
    import jax

    # examples always run on the host CPU — no reason to touch a tunneled chip
    jax.config.update("jax_platforms", "cpu")

    from sheeprl_tpu.config import compose
    from sheeprl_tpu.envs.factory import make_env

    cfg = compose(list(args))
    cfg.env.capture_video = False
    env = make_env(cfg, cfg.seed, 0)()
    print()
    print(f"Observation space of `{cfg.env.id}` environment for `{cfg.algo.name}` agent:")
    print(env.observation_space)
    print()
    print(f"Action space: {env.action_space}")
    env.close()


if __name__ == "__main__":
    main(sys.argv[1:])
