#!/usr/bin/env python
"""Decode a trained Dreamer-V3 world model's imagination.

The TPU-framework port of the reference's capability demo
(``notebooks/dreamer_v3_imagination.ipynb:1-452``): load a checkpoint, play
``--initial-steps`` env steps with the real player while recording the
latent states, then — starting ``--imagination-steps`` before the end —
roll the world model forward in pure imagination (actor-sampled or replayed
actions) and decode every latent back to pixels. Writes three GIFs plus a
side-by-side PNG strip:

- ``real.gif``            the frames the environment actually produced
- ``reconstructed.gif``   decoder(representation-model latents) — how well
                          the world model *encodes* what it saw
- ``imagination.gif``     decoder(transition-model rollout) — what the
                          world model *predicts* with no observations
- ``strip.png``           the three rows side by side for a quick look

Usage::

    python examples/dreamer_v3_imagination.py <ckpt.ckpt> [--out DIR]
        [--initial-steps 200] [--imagination-steps 45] [--replay-actions]

Works with any Dreamer-V3 checkpoint that has a pixel decoder (the
``rgb`` key), e.g. one produced by the test suite or
``exp=dreamer_v3_100k_atari_dummy``.
"""

from __future__ import annotations

import argparse
import os
import pathlib
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("checkpoint", type=pathlib.Path)
    ap.add_argument("--out", type=pathlib.Path, default=pathlib.Path("imagination_out"))
    ap.add_argument("--initial-steps", type=int, default=200)
    ap.add_argument("--imagination-steps", type=int, default=45)
    ap.add_argument(
        "--replay-actions",
        action="store_true",
        help="feed the actions the agent actually took instead of sampling from the actor",
    )
    ap.add_argument("--cpu", action="store_true", help="pin JAX to the host CPU")
    args = ap.parse_args()
    if args.imagination_steps > args.initial_steps:
        raise SystemExit("--imagination-steps must be <= --initial-steps")

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import gymnasium as gym
    import jax.numpy as jnp
    import numpy as np
    from PIL import Image

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs
    from sheeprl_tpu.config import dotdict, load_yaml
    from sheeprl_tpu.envs.factory import make_env
    from sheeprl_tpu.parallel import Fabric
    from sheeprl_tpu.utils.checkpoint import load_state

    ckpt = args.checkpoint.absolute()
    cfg = dotdict(load_yaml(ckpt.parent.parent / "config.yaml"))
    cfg.env.num_envs = 1
    cfg.env.capture_video = False
    state = load_state(ckpt)

    fabric = Fabric(devices=1)
    env = make_env(cfg, cfg.seed, 0, None, "imagination")()
    action_space = env.action_space
    observation_space = env.observation_space
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape if is_continuous else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    if "rgb" not in cfg.algo.cnn_keys.decoder:
        raise SystemExit("checkpoint has no rgb decoder — nothing to visualize")

    world_model, actor, critic, params, player = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        gym.spaces.Dict(observation_space.spaces),
        state["world_model"],
        state["actor"],
        state["critic"],
        state["target_critic"],
    )
    rssm = world_model.rssm
    wmp = params["world_model"]

    decode = jax.jit(lambda latent: world_model.decode(wmp, latent)["rgb"])
    imagine = jax.jit(lambda prior, rec, act, key: rssm.imagination(wmp, prior, rec, act, key))
    from sheeprl_tpu.algos.dreamer_v3.agent import actor_sample

    act_fn = jax.jit(
        lambda latent, key: jnp.concatenate(actor_sample(actor, params["actor"], latent, key)[0], axis=-1)
    )

    # -- play: record frames + the player's latent trajectory ----------------
    rng = jax.random.PRNGKey(cfg.seed)
    player.init_states(params)
    obs = env.reset(seed=cfg.seed)[0]
    real_frames, recs, stochs, acts = [], [], [], []
    for _ in range(args.initial_steps):
        jobs = prepare_obs(fabric, {k: np.asarray(v) for k, v in obs.items()}, cnn_keys=cnn_keys, num_envs=1)
        rng, key = jax.random.split(rng)
        action_list = player.get_actions(params, jobs, key)
        actions = np.asarray(jnp.concatenate(action_list, axis=-1))
        if is_continuous:
            real_actions = actions.reshape(action_space.shape)
        else:
            real_actions = np.stack([np.asarray(a).argmax(axis=-1) for a in action_list], axis=-1).squeeze()
        recs.append(np.asarray(player.recurrent_state))
        stochs.append(np.asarray(player.stochastic_state))
        acts.append(actions)
        real_frames.append(np.asarray(obs["rgb"]))
        obs, reward, terminated, truncated, info = env.step(real_actions)
        if terminated or truncated:
            obs = env.reset()[0]
            player.init_states(params, [0])
    env.close()

    start = args.initial_steps - args.imagination_steps

    # -- reconstruction: decode the REPRESENTATION latents the player saw ----
    recon_frames = []
    for i in range(start, args.initial_steps):
        latent = jnp.concatenate([jnp.asarray(stochs[i]), jnp.asarray(recs[i])], axis=-1)
        recon_frames.append(np.asarray(decode(latent))[0])

    # -- imagination: roll the TRANSITION model forward, no observations -----
    imag_frames = []
    prior = jnp.asarray(stochs[start])
    rec = jnp.asarray(recs[start])
    for i in range(args.imagination_steps):
        latent = jnp.concatenate([prior, rec], axis=-1)
        if args.replay_actions:
            action = jnp.asarray(acts[start + i])
        else:
            rng, key = jax.random.split(rng)
            action = act_fn(latent, key)
        rng, key = jax.random.split(rng)
        prior, rec = imagine(prior, rec, action, key)
        imag_frames.append(np.asarray(decode(jnp.concatenate([prior, rec], axis=-1)))[0])

    # -- render ---------------------------------------------------------------
    def to_uint8(frame: np.ndarray) -> np.ndarray:
        # decoder output is in [-0.5, 0.5] pixel space; real frames are uint8
        if frame.dtype == np.uint8:
            return frame
        return np.clip((frame + 0.5) * 255.0, 0, 255).astype(np.uint8)

    def save_gif(path: pathlib.Path, frames) -> None:
        imgs = [Image.fromarray(to_uint8(f)) for f in frames]
        imgs[0].save(path, format="GIF", append_images=imgs[1:], save_all=True, duration=100, loop=0)

    args.out.mkdir(parents=True, exist_ok=True)
    real_window = real_frames[start : args.initial_steps]
    save_gif(args.out / "real.gif", real_window)
    save_gif(args.out / "reconstructed.gif", recon_frames)
    save_gif(args.out / "imagination.gif", imag_frames)

    # PNG strip: rows = real / reconstructed / imagined, every 5th frame
    cols = [
        np.concatenate([to_uint8(real_window[i]), to_uint8(recon_frames[i]), to_uint8(imag_frames[i])], axis=0)
        for i in range(0, args.imagination_steps, max(1, args.imagination_steps // 9))
    ]
    Image.fromarray(np.concatenate(cols, axis=1)).save(args.out / "strip.png")
    print(f"wrote {args.out}/real.gif, reconstructed.gif, imagination.gif, strip.png")


if __name__ == "__main__":
    main()
