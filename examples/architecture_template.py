#!/usr/bin/env python
"""Template for a decoupled player/trainer algorithm on a TPU mesh.

The reference ships a torch-collectives multi-process skeleton
(``examples/architecture_template.py``: buffer/player/trainer processes
wired with TorchCollective broadcasts/gathers). The TPU-native architecture
this framework uses is different and simpler, and this runnable template
demonstrates it end to end on a toy problem:

- ONE process per host; the device mesh (``parallel.Fabric``) carries data
  parallelism inside XLA (``shard_map`` + ``psum``/``pmean``), not via
  explicit gather/broadcast calls;
- the ENV-SIDE policy runs on the host CPU from a packed parameter snapshot
  (``utils.burst.HostSnapshot``) — no per-step device round-trip;
- training dispatches on a trainer thread (``utils.burst.TrainerThread``)
  with a bounded queue as backpressure, so the env loop never blocks on the
  accelerator. Checkpoint-grade handles are always readable from
  ``trainer.carry`` (at most one dispatch stale).

This is exactly the topology of ``sac.py``'s hybrid path and the Dreamer
``HybridPlayerHarness`` — stripped to ~100 lines you can grow a new
algorithm from. Run it anywhere (CPU included):

    python examples/architecture_template.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
from sheeprl_tpu.parallel.compat import shard_map


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")  # template runs anywhere

    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from sheeprl_tpu.parallel import Fabric
    from sheeprl_tpu.utils.burst import HostSnapshot, TrainerThread

    # -- 1. mesh + model ------------------------------------------------------
    fabric = Fabric(devices=1, mesh_axes=("dp",))

    def net(params, x):
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        return h @ params["w2"] + params["b2"]

    key = jax.random.PRNGKey(0)
    params = {
        "w1": jax.random.normal(key, (4, 32)) * 0.3,
        "b1": jnp.zeros(32),
        "w2": jax.random.normal(key, (32, 1)) * 0.3,
        "b2": jnp.zeros(1),
    }
    tx = optax.adam(3e-3)
    opt = tx.init(params)
    params, opt = fabric.put_replicated(params), fabric.put_replicated(opt)

    # -- 2. the jitted train step: shard_map over the mesh, pmean gradients --
    def _step(params, opt, batch_x, batch_y):
        def loss_fn(p):
            return jnp.mean((net(p, batch_x) - batch_y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.lax.pmean(grads, "dp")
        loss = jax.lax.pmean(loss, "dp")
        updates, opt = tx.update(grads, opt)
        return optax.apply_updates(params, updates), opt, loss

    train_step = jax.jit(
        shard_map(
            _step,
            mesh=fabric.mesh,
            in_specs=(P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )

    # -- 3. host-side "player" from a packed snapshot -------------------------
    snapshot = HostSnapshot(lambda p: p, params, wire_dtype=jnp.float32)
    host_params = snapshot.pull(params)
    host_policy = jax.jit(net)  # runs on the snapshot, on the host device

    # -- 4. trainer thread: jobs in, newest handles out -----------------------
    GRAD_CHUNK = 16  # gradient steps per burst (the Ratio grant analogue)

    def trainer_step(carry, batch):
        params, opt = carry
        for _ in range(GRAD_CHUNK):
            params, opt, loss = train_step(params, opt, *batch)
        return (params, opt), loss

    trainer = TrainerThread(
        trainer_step,
        (params, opt),
        on_step=lambda carry, _loss: snapshot.refresh(carry[0]),
    )

    # -- 5. the env loop: act on the host, stage data, submit bursts ---------
    rng = np.random.default_rng(0)
    target = lambda x: np.sin(x.sum(-1, keepdims=True))
    staged_x, staged_y = [], []
    for it in range(1, 201):
        fresh = snapshot.poll()
        if fresh is not None:
            host_params = fresh  # adopt the newest trainer weights

        x = rng.normal(size=(8, 4)).astype(np.float32)
        _action = np.asarray(host_policy(host_params, x))  # the "policy"
        staged_x.append(x)
        staged_y.append(target(x).astype(np.float32))

        if len(staged_x) == 8:  # one burst every 8 iterations
            batch = (jnp.concatenate(staged_x), jnp.concatenate(staged_y))
            staged_x, staged_y = [], []
            trainer.submit(batch)
            if it % 40 == 0 and trainer.metrics is not None:
                print(f"iter {it:4d}  loss={float(trainer.metrics):.4f}")

    (params, opt) = trainer.close()
    x = jnp.asarray(rng.normal(size=(256, 4)), dtype=jnp.float32)
    final = float(jnp.mean((net(params, x) - jnp.asarray(target(np.asarray(x)))) ** 2))
    print(f"final eval MSE: {final:.4f}")
    assert final < 0.5, "the toy problem should have converged"


if __name__ == "__main__":
    main()
