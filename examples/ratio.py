#!/usr/bin/env python
"""What ``replay_ratio`` means in practice (reference: ``examples/ratio.py``).

The ``Ratio`` governor grants gradient steps so that, over the whole run,
``gradient_steps / policy_steps`` converges to the configured replay ratio —
regardless of ``num_envs``/``world_size`` chunking. This script simulates a
run and prints when training fires and the realized ratio, plus the
equivalent Hafner-style "train ratio" (gradient steps x replayed frames per
step).

    python examples/ratio.py [replay_ratio]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheeprl_tpu.utils.utils import Ratio

if __name__ == "__main__":
    replay_ratio = float(sys.argv[1]) if len(sys.argv) > 1 else 0.0625
    num_envs = 1
    world_size = 1
    per_rank_batch_size = 16
    per_rank_sequence_length = 64
    replayed_steps = world_size * per_rank_batch_size * per_rank_sequence_length
    gradient_steps = 0
    total_policy_steps = 2**10
    r = Ratio(ratio=replay_ratio, pretrain_steps=0)
    policy_steps_per_iter = num_envs * world_size
    for i in range(0, total_policy_steps, policy_steps_per_iter):
        if i >= 128:  # learning_starts
            per_rank_repeats = r(i / world_size)
            if per_rank_repeats > 0:
                print(
                    f"Training the agent with {per_rank_repeats} repeats on every rank "
                    f"({per_rank_repeats * world_size} global repeats) at global iteration {i}"
                )
            gradient_steps += per_rank_repeats * world_size
    print("Replay ratio", replay_ratio)
    print("Hafner train ratio", replay_ratio * replayed_steps)
    print("Final ratio", gradient_steps / total_policy_steps)
