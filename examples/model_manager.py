#!/usr/bin/env python
"""Register trained agents in an MLflow model registry.

The runnable-script port of the reference's ``examples/model_manager.ipynb``
capability: push a checkpoint's models into an MLflow run + registry
entries (via the framework's ``registration`` CLI verb), then run the
registry round-trip — ``register_best_models`` promotes the best run per
configured metric, the notebook's closing step.

Requires the optional ``mlflow`` dependency and a tracking server::

    pip install mlflow && mlflow ui          # serves http://localhost:5000
    python examples/model_manager.py <ckpt.ckpt> \
        [--tracking-uri http://localhost:5000] [--name my-agent]

The registry/selection logic itself is covered without a server by
``tests/test_utils/test_mlflow_manager.py`` (faked mlflow module), and the
same flow is available directly as::

    python -m sheeprl_tpu registration checkpoint_path=<ckpt> \
        model_manager.models.agent.model_name=my-agent
"""

from __future__ import annotations

import argparse
import os
import pathlib
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# What each family's ``log_models_from_checkpoint`` actually logs — the
# registration loop only registers keys present in the run's model_info, so
# the injected model_manager.models entries must use these names.
_FAMILY_MODELS = {
    "dreamer": ("world_model", "actor", "critic"),
    "p2e": ("world_model", "actor", "critic"),
    "ppo": ("agent",),
    "a2c": ("agent",),
    "sac": ("agent",),
    "droq": ("agent",),
}


def _model_keys(algo_name: str) -> tuple:
    for prefix, keys in _FAMILY_MODELS.items():
        if algo_name.startswith(prefix):
            return keys
    return ("agent",)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("checkpoint", type=pathlib.Path)
    ap.add_argument("--tracking-uri", default="http://localhost:5000")
    ap.add_argument("--name", default=None, help="registered-model name prefix (default: <algo>)")
    args = ap.parse_args()

    try:
        import mlflow  # noqa: F401
    except ImportError:
        raise SystemExit(
            "mlflow is an optional extra and is not installed: pip install mlflow, start a "
            "tracking server (`mlflow ui`), and re-run. The registry logic is unit-tested "
            "without a server in tests/test_utils/test_mlflow_manager.py."
        )

    from sheeprl_tpu.cli import registration
    from sheeprl_tpu.config import dotdict, load_yaml
    from sheeprl_tpu.utils.mlflow import MlflowModelManager

    ckpt = args.checkpoint.absolute()
    ckpt_cfg = dotdict(load_yaml(ckpt.parent.parent / "config.yaml"))
    keys = _model_keys(ckpt_cfg.algo.name)
    prefix = args.name or ckpt_cfg.algo.name

    # 1) push the checkpointed models into an MLflow run + registry entries —
    #    the same path as `python -m sheeprl_tpu registration ...`
    registration(
        [
            f"checkpoint_path={ckpt}",
            f"logger.tracking_uri={args.tracking_uri}",
            *(f"model_manager.models.{k}.model_name={prefix}-{k}" for k in keys),
        ]
    )

    # 2) registry round-trip: promote the best run of this experiment per
    #    the test-reward metric (the reference notebook's closing step).
    #    Checkpoint registration logs each model as `<key>.json`; training
    #    runs log a `<key>` artifact directory — match both so the demo's
    #    own run and historical training runs are eligible.
    manager = MlflowModelManager(None, args.tracking_uri)
    for path in (f"{keys[0]}.json", keys[0]):
        best = manager.register_best_models(
            ckpt_cfg["exp_name"],
            {keys[0]: {"path": path, "name": f"{prefix}-best", "description": "best run by test reward"}},
        )
        if best is not None:
            print(f"registered best-run models ({path}): {best}")
            break
    else:
        print("no eligible run carried a test-reward metric yet — train with metric logging on first")
    print("open the MLflow UI to inspect versions/stages")


if __name__ == "__main__":
    main()
