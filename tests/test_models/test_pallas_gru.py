"""Pallas fused GRU gate kernel vs the jnp reference chain (interpret mode
on the CPU test mesh; compiled lowering on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops.pallas_gru import gru_gates, gru_gates_reference


@pytest.mark.parametrize("shape", [(4, 16), (7, 32), (300, 8)], ids=["small", "odd-batch", "multi-block"])
def test_forward_matches_reference(shape):
    B, H = shape
    rng = np.random.default_rng(0)
    fused = jnp.asarray(rng.normal(size=(B, 3 * H)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(B, H)).astype(np.float32))
    got = np.asarray(gru_gates(fused, h))
    want = np.asarray(gru_gates_reference(fused, h))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_gradients_match_reference():
    rng = np.random.default_rng(1)
    fused = jnp.asarray(rng.normal(size=(5, 24)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(5, 8)).astype(np.float32))

    g_got = jax.grad(lambda f, h: jnp.sum(gru_gates(f, h) ** 2), argnums=(0, 1))(fused, h)
    g_want = jax.grad(lambda f, h: jnp.sum(gru_gates_reference(f, h) ** 2), argnums=(0, 1))(fused, h)
    for a, b in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_cell_pallas_path_matches_default():
    """The LayerNormGRUCell with use_pallas forced on must be numerically
    identical to the default path (so TPU/CPU checkpoints interchange)."""
    from sheeprl_tpu.models import LayerNormGRUCell

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(6, 12)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(6, 16)).astype(np.float32))
    cell_ref = LayerNormGRUCell(hidden_size=16, layer_norm=True, use_pallas=False)
    cell_pls = LayerNormGRUCell(hidden_size=16, layer_norm=True, use_pallas=True)
    params = cell_ref.init(jax.random.PRNGKey(0), h, x)
    out_ref, _ = cell_ref.apply(params, h, x)
    out_pls, _ = cell_pls.apply(params, h, x)
    np.testing.assert_allclose(np.asarray(out_pls), np.asarray(out_ref), rtol=1e-6, atol=1e-6)


def test_kernel_inside_scan():
    """Scan-compatibility: the kernel is the body of the RSSM time loop."""
    rng = np.random.default_rng(3)
    T, B, H = 12, 4, 8
    fused_seq = jnp.asarray(rng.normal(size=(T, B, 3 * H)).astype(np.float32))
    h0 = jnp.zeros((B, H), jnp.float32)

    def step(h, fused):
        h = gru_gates(fused, h)
        return h, h

    _, got = jax.lax.scan(step, h0, fused_seq)

    def step_ref(h, fused):
        h = gru_gates_reference(fused, h)
        return h, h

    _, want = jax.lax.scan(step_ref, h0, fused_seq)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)


def test_forward_bf16_io():
    """bf16 inputs (bf16-mixed precision configs) must lower and match the
    reference chain at bf16 tolerance — the kernel computes in f32 and casts
    back at the boundary."""
    rng = np.random.default_rng(4)
    B, H = 8, 16
    fused = jnp.asarray(rng.normal(size=(B, 3 * H)).astype(np.float32), dtype=jnp.bfloat16)
    h = jnp.asarray(rng.normal(size=(B, H)).astype(np.float32), dtype=jnp.bfloat16)
    got = np.asarray(gru_gates(fused, h), dtype=np.float32)
    want = np.asarray(gru_gates_reference(fused.astype(jnp.float32), h.astype(jnp.float32)))
    assert got.dtype == np.float32 and gru_gates(fused, h).dtype == jnp.bfloat16
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
