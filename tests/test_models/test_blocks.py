import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.models import (
    CNN,
    MLP,
    DeCNN,
    LayerNormGRUCell,
    MultiDecoder,
    MultiEncoder,
    NatureCNN,
    get_activation,
)


def test_mlp_shapes():
    m = MLP(hidden_sizes=(32, 32), output_dim=5, activation="tanh")
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((4, 10)))
    out = m.apply(params, jnp.zeros((4, 10)))
    assert out.shape == (4, 5)


def test_mlp_no_output_dim():
    m = MLP(hidden_sizes=(16,), activation="relu", layer_norm=True)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((2, 8)))
    out = m.apply(params, jnp.zeros((2, 8)))
    assert out.shape == (2, 16)


def test_mlp_flatten():
    m = MLP(hidden_sizes=(8,), output_dim=3, flatten_dim=1)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((2, 4, 5)))
    out = m.apply(params, jnp.zeros((2, 4, 5)))
    assert out.shape == (2, 3)


def test_cnn_nhwc():
    m = CNN(hidden_channels=(8, 16), layer_args={"kernel_size": 3, "stride": 2, "padding": 1})
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((2, 16, 16, 3)))
    out = m.apply(params, jnp.zeros((2, 16, 16, 3)))
    assert out.shape == (2, 4, 4, 16)


def test_decnn_doubles_spatial():
    # Dreamer-style stride-2 kernel-4 pad-1 doubling
    m = DeCNN(hidden_channels=(8,), layer_args={"kernel_size": 4, "stride": 2, "padding": 1})
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((2, 8, 8, 4)))
    out = m.apply(params, jnp.zeros((2, 8, 8, 4)))
    assert out.shape == (2, 16, 16, 8)


def test_nature_cnn():
    m = NatureCNN(features_dim=512)
    params = m.init(jax.random.PRNGKey(0), jnp.zeros((2, 64, 64, 4)))
    out = m.apply(params, jnp.zeros((2, 64, 64, 4)))
    assert out.shape == (2, 512)


def test_layer_norm_gru_cell():
    cell = LayerNormGRUCell(hidden_size=16, layer_norm=True)
    params = cell.init(jax.random.PRNGKey(0), jnp.zeros((3, 16)), jnp.zeros((3, 8)))
    h, out = cell.apply(params, jnp.ones((3, 16)), jnp.ones((3, 8)))
    assert h.shape == (3, 16)
    assert np.allclose(h, out)


def test_gru_cell_scan():
    cell = LayerNormGRUCell(hidden_size=8)
    params = cell.init(jax.random.PRNGKey(0), jnp.zeros((2, 8)), jnp.zeros((2, 4)))
    xs = jnp.ones((5, 2, 4))

    def step(h, x):
        return cell.apply(params, h, x)

    h_final, hs = jax.lax.scan(step, jnp.zeros((2, 8)), xs)
    assert hs.shape == (5, 2, 8)


def test_multi_encoder_decoder():
    import flax.linen as nn

    class CnnEnc(nn.Module):
        @nn.compact
        def __call__(self, obs):
            x = obs["rgb"]
            return x.reshape(x.shape[0], -1)

    class MlpEnc(nn.Module):
        @nn.compact
        def __call__(self, obs):
            return obs["state"]

    enc = MultiEncoder(CnnEnc(), MlpEnc())
    obs = {"rgb": jnp.zeros((2, 4, 4, 1)), "state": jnp.zeros((2, 3))}
    params = enc.init(jax.random.PRNGKey(0), obs)
    out = enc.apply(params, obs)
    assert out.shape == (2, 16 + 3)


def test_get_activation_torch_compat():
    assert get_activation("torch.nn.Tanh") is get_activation("tanh")
    assert get_activation("torch.nn.SiLU") is get_activation("silu")
    with pytest.raises(ValueError):
        get_activation("nosuch")


def test_dreamer_v2_cnn_encoder_pad_trick_matches_plain_valid_conv():
    """The exact-VALID end-pad trick in the V2/V1 encoder must be a no-op on
    values for every input geometry, including non-square frames (crafter/
    diambra accept tuple screen sizes)."""
    import flax.linen as nn

    from sheeprl_tpu.algos.dreamer_v2.agent import CNNEncoder

    class PlainStack(nn.Module):
        channels_multiplier: int = 4

        @nn.compact
        def __call__(self, x):
            for i, mult in enumerate((1, 2, 4, 8)):
                x = nn.Conv(
                    mult * self.channels_multiplier,
                    kernel_size=(4, 4),
                    strides=(2, 2),
                    padding="VALID",
                    use_bias=True,
                    name=f"conv_{i}",
                )(x)
                x = nn.elu(x)
            return x.reshape(x.shape[0], -1)

    for h, w in ((64, 64), (96, 64)):
        x = jnp.asarray(np.random.RandomState(h + w).rand(2, h, w, 3), jnp.float32)
        enc = CNNEncoder(keys=["rgb"], channels_multiplier=4, layer_norm=False, activation="elu")
        ref = PlainStack()
        p_ref = ref.init(jax.random.PRNGKey(0), x)
        out_ref = ref.apply(p_ref, x)
        # Graft the plain stack's kernels into the encoder so outputs are comparable.
        graft = {"params": {k: dict(p_ref["params"][k]) for k in p_ref["params"]}}
        out_enc = enc.apply(graft, {"rgb": x})
        np.testing.assert_array_equal(np.asarray(out_enc), np.asarray(out_ref))
