"""CLI semantics: checkpoint → resume round-trips, resume mismatch errors,
evaluate-from-checkpoint (reference: ``tests/test_algos/test_cli.py:121-300``)."""

import glob
import os

import pytest

from sheeprl_tpu.cli import evaluation, run

PPO_TINY = [
    "exp=ppo",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]

DREAMER_TINY = [
    "exp=dreamer_v3",
    "algo=dreamer_v3_XS",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=1",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.world_model.reward_model.bins=17",
    "algo.critic.bins=17",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "env.screen_size=64",
    "algo.learning_starts=0",
    "algo.replay_ratio=0.5",
    "buffer.size=64",
]


def _ckpts(root):
    return sorted(glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)


def test_ppo_checkpoint_resume_round_trip(tmp_path):
    """Train 4 iterations checkpointing mid-run, resume from the mid-run
    checkpoint, and finish: the resumed run must fast-forward its counters
    and produce the final-step checkpoint (reference ``test_cli.py:121``)."""
    run(
        PPO_TINY
        + [
            f"log_root={tmp_path}/first",
            "algo.total_steps=64",
            "checkpoint.every=32",
            "checkpoint.save_last=False",
        ]
    )
    first_ckpts = _ckpts(f"{tmp_path}/first")
    assert first_ckpts, "no periodic checkpoint was written"
    mid_ckpt = first_ckpts[0]  # policy_step 32 of 64 → iterations remain

    run(
        PPO_TINY
        + [
            f"log_root={tmp_path}/resumed",
            f"checkpoint.resume_from={mid_ckpt}",
            "checkpoint.save_last=True",
        ]
    )
    resumed_ckpts = _ckpts(f"{tmp_path}/resumed")
    assert resumed_ckpts, "the resumed run saved no checkpoint"
    # the old run's total_steps (64) governs the resumed run's end
    assert any("ckpt_64" in c for c in resumed_ckpts)


PPO_ANAKIN_TINY = [
    "exp=ppo_anakin",
    "env=gym",
    "env.num_envs=2",
    "env.capture_video=False",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]


def test_ppo_anakin_checkpoint_and_evaluation(tmp_path):
    """On-device training → checkpoint → `evaluation()`: the Anakin
    checkpoint shares the host PPO layout, and the policy trained on the
    pure-JAX CartPole evaluates on the real gymnasium CartPole."""
    run(
        PPO_ANAKIN_TINY
        + [
            f"log_root={tmp_path}/anakin",
            "algo.total_steps=64",
            "checkpoint.every=32",
            "checkpoint.save_last=True",
        ]
    )
    ckpts = _ckpts(f"{tmp_path}/anakin")
    assert ckpts, "the anakin run saved no checkpoint"
    evaluation([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False"])


def test_resume_env_mismatch_errors(tmp_path):
    run(PPO_TINY + [f"log_root={tmp_path}", "dry_run=True", "checkpoint.save_last=True"])
    ckpt = _ckpts(tmp_path)[-1]
    with pytest.raises(ValueError, match="different environment"):
        run(PPO_TINY + [f"log_root={tmp_path}", "env.id=continuous_dummy", f"checkpoint.resume_from={ckpt}"])


def test_resume_algo_mismatch_errors(tmp_path):
    run(PPO_TINY + [f"log_root={tmp_path}", "dry_run=True", "checkpoint.save_last=True"])
    ckpt = _ckpts(tmp_path)[-1]
    with pytest.raises(ValueError, match="different algorithm"):
        run(
            [a if a != "exp=ppo" else "exp=a2c" for a in PPO_TINY if "update_epochs" not in a and "per_rank_batch" not in a]
            + [f"log_root={tmp_path}", f"checkpoint.resume_from={ckpt}"]
        )


def test_evaluate_from_checkpoint(tmp_path, capsys):
    """Eval verb: load checkpoint, rebuild agent from the saved config, run a
    greedy episode (reference ``test_cli.py:277``)."""
    run(PPO_TINY + [f"log_root={tmp_path}", "dry_run=True", "checkpoint.save_last=True"])
    ckpt = _ckpts(tmp_path)[-1]
    evaluation([f"checkpoint_path={ckpt}", "env.capture_video=False"])
    out = capsys.readouterr().out
    assert "Test - Reward:" in out


def test_evaluate_decoupled_checkpoints(tmp_path, capsys):
    """The decoupled mains share their coupled twin's evaluation — the
    reference registers both names (``sheeprl/algos/ppo/evaluate.py:58``,
    ``sac/evaluate.py:15``), and a decoupled checkpoint must evaluate."""
    args = [a if a != "exp=ppo" else "exp=ppo_decoupled" for a in PPO_TINY]
    run(args + [f"log_root={tmp_path}", "dry_run=True", "checkpoint.save_last=True"])
    ckpt = _ckpts(tmp_path)[-1]
    evaluation([f"checkpoint_path={ckpt}", "env.capture_video=False"])
    assert "Test - Reward:" in capsys.readouterr().out

    from sheeprl_tpu.utils.registry import evaluation_registry

    assert "sac_decoupled" in evaluation_registry


def test_dreamer_v3_checkpoint_resume_round_trip(tmp_path):
    """Dreamer resume restores Ratio/Moments/counters and keeps training
    (VERDICT item 7: the off-policy resume path was untested)."""
    run(
        DREAMER_TINY
        + [
            f"log_root={tmp_path}/first",
            "algo.total_steps=16",
            "checkpoint.every=8",
            "checkpoint.save_last=False",
            "buffer.checkpoint=True",
        ]
    )
    first_ckpts = _ckpts(f"{tmp_path}/first")
    assert first_ckpts
    run(
        DREAMER_TINY
        + [
            f"log_root={tmp_path}/resumed",
            f"checkpoint.resume_from={first_ckpts[0]}",
            "checkpoint.save_last=True",
            "buffer.checkpoint=True",
        ]
    )
    assert _ckpts(f"{tmp_path}/resumed")


# -- every registered evaluation is executable --------------------------------

from tests.test_algos.test_algos import (  # noqa: E402
    A2C_FAST,
    DREAMER_FAST,
    DREAMER_V1_FAST,
    DREAMER_V2_FAST,
    P2E_DV1_FAST,
    P2E_DV2_FAST,
    P2E_DV3_FAST,
    PPO_REC_FAST,
    SAC_AE_FAST,
    SAC_DECOUPLED_FAST,
    SAC_FAST,
    _std_args,
)

# conftest auto-marks the compile-heavy families (dreamer/p2e/sac_ae/droq)
# slow via the parametrized nodeid; the MLP cases stay in the fast lane.
_EVAL_CASES = [
    ("a2c", A2C_FAST),
    ("ppo_recurrent", PPO_REC_FAST),
    ("sac", SAC_FAST),
    ("sac_decoupled", SAC_DECOUPLED_FAST),
    ("droq", SAC_FAST),
    ("sac_ae", SAC_AE_FAST),
    ("dreamer_v1", DREAMER_V1_FAST),
    ("dreamer_v2", DREAMER_V2_FAST),
    ("dreamer_v3", DREAMER_FAST),
    ("p2e_dv1_exploration", P2E_DV1_FAST),
    ("p2e_dv2_exploration", P2E_DV2_FAST),
    ("p2e_dv3_exploration", P2E_DV3_FAST),
]


@pytest.mark.parametrize("algo, fast", _EVAL_CASES, ids=[c[0] for c in _EVAL_CASES])
def test_every_registered_evaluation_runs(tmp_path, capsys, algo, fast):
    """Checkpoint → `evaluation()` round-trip for EVERY algorithm family's
    registered evaluation entry (the reference registers one per family —
    previously only ppo/ppo_decoupled were ever executed)."""
    run(_std_args(tmp_path, algo, extra=list(fast)) + ["checkpoint.save_last=True"])
    ckpt = _ckpts(tmp_path)[-1]
    evaluation([f"checkpoint_path={ckpt}", "env.capture_video=False"])
    assert "Test - Reward:" in capsys.readouterr().out
