"""Real-time-axis fidelity for the Dreamer-V1/V2 and P2E train steps
(VERDICT round-2 weak #6: the smoke configs pin the time axis to 1-2 steps,
so the dynamic-learning scans these algorithms hinge on barely run).

Each test drives the family's jitted G-step update with seq_len=8 batches
containing mid-sequence episode boundaries (is_first/terminated), and
asserts finite losses, moved params and (for P2E) updated ensembles.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.config import compose
from sheeprl_tpu.optim.builders import build_optimizer
from sheeprl_tpu.parallel.fabric import Fabric

SEQ_LEN = 8
BATCH = 2
GRANTED = 2

_TINY = [
    "env=dummy",
    "env.num_envs=2",
    f"algo.per_rank_batch_size={BATCH}",
    f"algo.per_rank_sequence_length={SEQ_LEN}",
    "algo.horizon=5",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.stochastic_size=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "env.screen_size=64",
]

OBS_SPACE = gym.spaces.Dict(
    {
        "rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8),
        "state": gym.spaces.Box(-20, 20, (10,), np.float32),
    }
)


def _batch(rng, with_truncated=True):
    G, T, B = GRANTED, SEQ_LEN, BATCH
    data = {
        "rgb": rng.integers(0, 255, (G, T, B, 64, 64, 3)).astype(np.float32),
        "state": rng.normal(size=(G, T, B, 10)).astype(np.float32),
        "actions": np.eye(3, dtype=np.float32)[rng.integers(0, 3, (G, T, B))],
        "rewards": rng.normal(size=(G, T, B, 1)).astype(np.float32),
        "terminated": np.zeros((G, T, B, 1), np.float32),
        "is_first": np.zeros((G, T, B, 1), np.float32),
    }
    if with_truncated:
        data["truncated"] = np.zeros((G, T, B, 1), np.float32)
    # mid-sequence episode boundary: the scans must reset their carries
    data["terminated"][:, 2, 0] = 1.0
    data["is_first"][:, 3, 0] = 1.0
    return data


def _snapshot(params, keys):
    """Host copies taken BEFORE the (donating) train step."""
    return {k: [np.asarray(leaf).copy() for leaf in jax.tree.leaves(params[k])] for k in keys}


def _assert_finite_and_moved(metrics_values, snapshot, params2):
    for value in metrics_values:
        assert np.isfinite(np.asarray(value)).all()
    for k, old in snapshot.items():
        new = jax.tree.leaves(params2[k])
        assert any(not np.array_equal(a, np.asarray(b)) for a, b in zip(old, new)), k


@pytest.mark.slow
def test_dreamer_v1_train_step_full_sequence(tmp_path):
    from sheeprl_tpu.algos.dreamer_v1.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import make_train_step

    cfg = compose(["exp=dreamer_v1", *_TINY, f"log_root={tmp_path}"])
    fabric = Fabric(devices=1)
    world_model, actor, critic, params, _ = build_agent(fabric, (3,), False, cfg, OBS_SPACE)
    txs = {
        "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
        "actor": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
    }
    opts = {
        "world": txs["world"].init(params["world_model"]),
        "actor": txs["actor"].init(params["actor"]),
        "critic": txs["critic"].init(params["critic"]),
    }
    train_fn = make_train_step(world_model, actor, critic, cfg, fabric.mesh, (3,), False, txs)
    data = _batch(np.random.default_rng(0), with_truncated=False)
    snap = _snapshot(params, ("world_model", "actor", "critic"))
    params2, opts2, metrics = train_fn(params, opts, data, jax.random.PRNGKey(0))
    _assert_finite_and_moved(metrics, snap, params2)


@pytest.mark.slow
def test_dreamer_v2_train_step_full_sequence(tmp_path):
    from sheeprl_tpu.algos.dreamer_v2.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v2.dreamer_v2 import make_train_step

    cfg = compose(["exp=dreamer_v2", *_TINY, "algo.world_model.discrete_size=4", f"log_root={tmp_path}"])
    fabric = Fabric(devices=1)
    world_model, actor, critic, params, _ = build_agent(fabric, (3,), False, cfg, OBS_SPACE)
    txs = {
        "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
        "actor": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
    }
    opts = {
        "world": txs["world"].init(params["world_model"]),
        "actor": txs["actor"].init(params["actor"]),
        "critic": txs["critic"].init(params["critic"]),
    }
    train_fn = make_train_step(world_model, actor, critic, cfg, fabric.mesh, (3,), False, txs)
    data = _batch(np.random.default_rng(1), with_truncated=False)
    snap = _snapshot(params, ("world_model", "actor", "critic"))
    params2, opts2, metrics = train_fn(params, opts, data, jax.random.PRNGKey(0), jnp.int32(0))
    _assert_finite_and_moved(metrics, snap, params2)
    # two granted steps: the V2 target critic EMA-mixed away from the critic
    tc = np.asarray(jax.tree.leaves(params2["target_critic"])[0])
    cc = np.asarray(jax.tree.leaves(params2["critic"])[0])
    assert not np.allclose(tc, cc)


def _p2e_cfg(tmp_path, exp):
    return compose(
        [
            f"exp={exp}",
            *_TINY,
            "algo.world_model.discrete_size=4" if "dv1" not in exp else "seed=5",
            "algo.ensembles.n=3",
            f"log_root={tmp_path}",
        ]
    )


@pytest.mark.slow
def test_p2e_dv1_train_step_full_sequence(tmp_path):
    from sheeprl_tpu.algos.p2e_dv1.agent import build_agent
    from sheeprl_tpu.algos.p2e_dv1.p2e_dv1_exploration import make_train_step

    cfg = _p2e_cfg(tmp_path, "p2e_dv1_exploration")
    fabric = Fabric(devices=1)
    world_model, ens_module, actor, critic, params, _ = build_agent(fabric, (3,), False, cfg, OBS_SPACE)
    names = ("world", "actor_task", "critic_task", "actor_exploration", "critic_exploration", "ensembles")
    pkeys = ("world_model", "actor_task", "critic_task", "actor_exploration", "critic_exploration", "ensembles")
    txs = {
        "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
        "actor_task": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_task": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
        "actor_exploration": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_exploration": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
        "ensembles": build_optimizer(cfg.algo.ensembles.optimizer, max_grad_norm=cfg.algo.ensembles.clip_gradients),
    }
    opts = {n: txs[n].init(params[p]) for n, p in zip(names, pkeys)}
    train_fn = make_train_step(world_model, ens_module, actor, critic, cfg, fabric.mesh, (3,), False, txs)
    data = _batch(np.random.default_rng(2), with_truncated=False)
    snap = _snapshot(params, ("world_model", "actor_task", "actor_exploration", "ensembles"))
    params2, opts2, metrics = train_fn(params, opts, data, jax.random.PRNGKey(0))
    values = metrics.values() if isinstance(metrics, dict) else metrics
    _assert_finite_and_moved(values, snap, params2)


@pytest.mark.slow
def test_p2e_dv2_train_step_full_sequence(tmp_path):
    from sheeprl_tpu.algos.p2e_dv2.agent import build_agent
    from sheeprl_tpu.algos.p2e_dv2.p2e_dv2_exploration import make_train_step

    cfg = _p2e_cfg(tmp_path, "p2e_dv2_exploration")
    fabric = Fabric(devices=1)
    world_model, ens_module, actor, critic, params, _ = build_agent(fabric, (3,), False, cfg, OBS_SPACE)
    names = ("world", "actor_task", "critic_task", "actor_exploration", "critic_exploration", "ensembles")
    pkeys = ("world_model", "actor_task", "critic_task", "actor_exploration", "critic_exploration", "ensembles")
    txs = {
        "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
        "actor_task": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_task": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
        "actor_exploration": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_exploration": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
        "ensembles": build_optimizer(cfg.algo.ensembles.optimizer, max_grad_norm=cfg.algo.ensembles.clip_gradients),
    }
    opts = {n: txs[n].init(params[p]) for n, p in zip(names, pkeys)}
    train_fn = make_train_step(world_model, ens_module, actor, critic, cfg, fabric.mesh, (3,), False, txs)
    data = _batch(np.random.default_rng(3), with_truncated=False)
    snap = _snapshot(params, ("world_model", "actor_task", "actor_exploration", "ensembles"))
    params2, opts2, metrics = train_fn(params, opts, data, jax.random.PRNGKey(0), jnp.int32(0))
    values = metrics.values() if isinstance(metrics, dict) else metrics
    _assert_finite_and_moved(values, snap, params2)


@pytest.mark.slow
def test_p2e_dv3_train_step_full_sequence(tmp_path):
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
    from sheeprl_tpu.algos.p2e_dv3.agent import build_agent
    from sheeprl_tpu.algos.p2e_dv3.p2e_dv3_exploration import make_train_step

    cfg = compose(
        [
            "exp=p2e_dv3_exploration",
            *_TINY,
            "algo.world_model.discrete_size=4",
            "algo.world_model.reward_model.bins=17",
            "algo.critic.bins=17",
            "algo.ensembles.n=3",
            f"log_root={tmp_path}",
        ]
    )
    fabric = Fabric(devices=1)
    world_model, ens_module, actor, critic, critics_spec, params, _ = build_agent(
        fabric, (3,), False, cfg, OBS_SPACE
    )
    txs = {
        "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
        "actor_task": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_task": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
        "actor_exploration": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "ensembles": build_optimizer(cfg.algo.ensembles.optimizer, max_grad_norm=cfg.algo.ensembles.clip_gradients),
        "critics_exploration": {
            k: build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients)
            for k in critics_spec
        },
    }
    opts = {
        "world": txs["world"].init(params["world_model"]),
        "actor_task": txs["actor_task"].init(params["actor_task"]),
        "critic_task": txs["critic_task"].init(params["critic_task"]),
        "actor_exploration": txs["actor_exploration"].init(params["actor_exploration"]),
        "ensembles": txs["ensembles"].init(params["ensembles"]),
        "critics_exploration": {
            k: txs["critics_exploration"][k].init(params["critics_exploration"][k]["module"])
            for k in critics_spec
        },
    }
    train_fn = make_train_step(
        world_model, ens_module, actor, critic, critics_spec, cfg, fabric.mesh, (3,), False, txs
    )
    data = _batch(np.random.default_rng(4))
    moments0 = {"task": init_moments(), "exploration": {k: init_moments() for k in critics_spec}}
    snap = _snapshot(params, ("world_model", "actor_task", "actor_exploration", "ensembles"))
    crit_snap = {
        name: [np.asarray(leaf).copy() for leaf in jax.tree.leaves(params["critics_exploration"][name]["module"])]
        for name in critics_spec
    }
    params2, opts2, moments2, metrics = train_fn(
        params, opts, moments0, data, jax.random.PRNGKey(0), jnp.int32(0)
    )
    values = metrics.values() if isinstance(metrics, dict) else metrics
    _assert_finite_and_moved(values, snap, params2)
    # exploration critics (per-reward-type modules) moved too
    for name, old in crit_snap.items():
        new = jax.tree.leaves(params2["critics_exploration"][name]["module"])
        assert any(not np.array_equal(a, np.asarray(b)) for a, b in zip(old, new)), name
