"""ppo_sebulba end-to-end: dry runs through the real CLI on host (dummy)
envs, checkpoint → resume round trip, a real multi-rollout run exercising the
bounded queue under several actors, and (slow lane) return parity vs the
host-loop PPO on CartPole."""

import glob
import os

import pytest

from sheeprl_tpu.cli import run

SEBULBA_FAST = [
    "exp=ppo_sebulba",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]


def _ckpts(root):
    return sorted(glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)


@pytest.mark.parametrize("devices", [1, 2])
def test_ppo_sebulba_dry_run(tmp_path, devices):
    """devices=1 time-slices one chip between the actor and learner sides;
    devices=2 splits them into disjoint single-device slices."""
    run(
        SEBULBA_FAST
        + [
            "dry_run=True",
            "checkpoint.save_last=False",
            f"fabric.devices={devices}",
            f"log_root={tmp_path}/logs",
        ]
    )


def test_ppo_sebulba_continuous(tmp_path):
    run(
        SEBULBA_FAST
        + [
            "dry_run=True",
            "checkpoint.save_last=False",
            "fabric.devices=1",
            "env.id=continuous_dummy",
            f"log_root={tmp_path}/logs",
        ]
    )


def test_ppo_sebulba_many_actors_small_queue(tmp_path):
    """More actors than queue slots for several learner iterations: the
    bounded queue must back-pressure (not drop/deadlock) and the run must
    consume exactly total_steps."""
    run(
        SEBULBA_FAST
        + [
            "fabric.devices=1",
            "algo.total_steps=128",
            "algo.sebulba.num_actor_threads=3",
            "algo.sebulba.queue_depth=1",
            "algo.sebulba.publish_every=2",
            "checkpoint.save_last=False",
            f"log_root={tmp_path}/logs",
        ]
    )


def test_ppo_sebulba_env_groups_amortized_inference(tmp_path):
    """env_groups > 1: one inference dispatch drives several rollout columns
    that are sliced into independent learner items — the learner's per-update
    batch stays rollout_steps * env.num_envs, so the run must consume exactly
    total_steps at the configured item shape."""
    run(
        SEBULBA_FAST
        + [
            "fabric.devices=1",
            "algo.total_steps=128",
            "algo.sebulba.num_actor_threads=1",
            "algo.sebulba.env_groups=3",
            "checkpoint.save_last=False",
            f"log_root={tmp_path}/logs",
        ]
    )


def test_ppo_sebulba_checkpoint_resume_round_trip(tmp_path):
    """Train with a mid-run checkpoint, resume from it, finish: counters
    fast-forward and the final-step checkpoint appears (the same contract as
    the host-loop round trip, learner-side saves + RNG-stream restore)."""
    run(
        SEBULBA_FAST
        + [
            f"log_root={tmp_path}/first",
            "fabric.devices=1",
            "algo.total_steps=64",
            "checkpoint.every=32",
            "checkpoint.save_last=False",
        ]
    )
    first_ckpts = _ckpts(f"{tmp_path}/first")
    assert first_ckpts, "no periodic checkpoint was written"

    run(
        SEBULBA_FAST
        + [
            f"log_root={tmp_path}/resumed",
            "fabric.devices=1",
            f"checkpoint.resume_from={first_ckpts[0]}",
            "checkpoint.save_last=True",
        ]
    )
    resumed = _ckpts(f"{tmp_path}/resumed")
    assert resumed, "the resumed run saved no checkpoint"
    assert any("ckpt_64" in c for c in resumed)  # old run's total_steps governs


def test_ppo_sebulba_evaluation_from_checkpoint(tmp_path):
    """The sebulba checkpoint shares the PPO layout: `evaluation()` loads it
    through the shared ppo evaluate entrypoint."""
    from sheeprl_tpu.cli import evaluation

    run(
        SEBULBA_FAST
        + [
            f"log_root={tmp_path}/logs",
            "fabric.devices=1",
            "algo.total_steps=32",
            "checkpoint.save_last=True",
        ]
    )
    ckpt = _ckpts(f"{tmp_path}/logs")[-1]
    evaluation([f"checkpoint_path={ckpt}", "env.capture_video=False", "fabric.accelerator=cpu"])


@pytest.mark.slow
def test_ppo_sebulba_return_parity_with_host_loop_on_cartpole(tmp_path):
    """Same recipe, same budget on real CartPole: the pipelined run's returns
    must match the host loop's (the decoupling adds bounded staleness, not a
    different algorithm). Asserted on the best trailing-window mean — both
    runs must clear an absolute floor no non-learning agent reaches, and
    sebulba must be within 40% of host-loop PPO."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    from benchmarks.learning_bench import capture_returns

    budget = 24576
    common = [
        "env=gym",
        "env.id=CartPole-v1",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "fabric.devices=1",
        "metric.log_level=1",
        "metric.log_every=70000",
        "algo.run_test=False",
        f"algo.total_steps={budget}",
        "algo.rollout_steps=128",
        "algo.per_rank_batch_size=64",
        "algo.max_grad_norm=0.5",
        "algo.vf_coef=0.5",
        "algo.normalize_advantages=True",
        "algo.optimizer.lr=3e-4",
        "algo.mlp_keys.encoder=[state]",
        "checkpoint.save_last=False",
        "seed=7",
    ]

    def best_window(returns, w=10):
        if len(returns) < w:
            return 0.0
        return max(sum(returns[i : i + w]) / w for i in range(len(returns) - w + 1))

    host = capture_returns(["exp=ppo", f"log_root={tmp_path}/host"] + common)
    seb = capture_returns(["exp=ppo_sebulba", f"log_root={tmp_path}/sebulba"] + common)
    host_best, seb_best = best_window(host), best_window(seb)
    assert host_best >= 100, f"host-loop PPO failed to learn CartPole: best10={host_best} n={len(host)}"
    assert seb_best >= 100, f"ppo_sebulba failed to learn CartPole: best10={seb_best} n={len(seb)}"
    assert seb_best >= 0.6 * host_best, (
        f"ppo_sebulba returns not at parity: best10={seb_best} vs host {host_best}"
    )
