"""The examples/ scripts are user-facing capability demos — keep them
runnable. The imagination demo is the port of the reference's
``notebooks/dreamer_v3_imagination.ipynb`` capability (decode imagined
rollouts from a trained world model), so it gets a real checkpoint-driven
test; the others are cheap smoke runs."""

import glob
import os
import subprocess
import sys

import pytest

from sheeprl_tpu.cli import run

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DREAMER_TINY = [
    "exp=dreamer_v3",
    "algo=dreamer_v3_XS",
    "env=atari_dummy",
    "env.num_envs=1",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "checkpoint.save_last=True",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=2",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.world_model.reward_model.bins=17",
    "algo.critic.bins=17",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[]",
    "algo.mlp_keys.decoder=[]",
    "algo.total_steps=24",
    "algo.learning_starts=8",
]


def _run_example(script, *args):
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", script), *args],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=_REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_ratio_example():
    proc = _run_example("ratio.py", "0.5")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Final ratio" in proc.stdout


def test_observation_space_example():
    proc = _run_example("observation_space.py", "exp=dreamer_v3", "env=atari_dummy")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Discrete(18)" in proc.stdout


@pytest.mark.slow
def test_architecture_template_converges():
    proc = _run_example("architecture_template.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "final eval MSE" in proc.stdout


@pytest.mark.slow
def test_dreamer_v3_imagination_demo(tmp_path):
    """Train a tiny Dreamer-V3, then decode imagination from its checkpoint:
    the example must produce the three GIFs + the PNG strip."""
    run(DREAMER_TINY + [f"log_root={tmp_path}/logs"])
    ckpt = sorted(glob.glob(f"{tmp_path}/logs/**/ckpt_*.ckpt", recursive=True))[-1]
    out = tmp_path / "imag"
    proc = _run_example(
        "dreamer_v3_imagination.py", ckpt, "--cpu",
        "--initial-steps", "24", "--imagination-steps", "8", "--out", str(out),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for name in ("real.gif", "reconstructed.gif", "imagination.gif", "strip.png"):
        assert (out / name).stat().st_size > 0


def test_model_manager_example_dep_gates_cleanly():
    """mlflow is an optional extra: without it the example must exit with
    the actionable install message, not a traceback. (In an env where the
    extra IS installed the gate doesn't fire — skip.)"""
    import importlib.util

    if importlib.util.find_spec("mlflow") is not None:
        pytest.skip("mlflow installed: the dep gate does not fire")
    proc = _run_example("model_manager.py", "/nonexistent/ckpt.ckpt")
    assert proc.returncode != 0
    assert "mlflow is an optional extra" in (proc.stdout + proc.stderr)
    assert "Traceback" not in proc.stderr
