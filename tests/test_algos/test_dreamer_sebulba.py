"""dreamer_sebulba end-to-end: async actor/learner dry runs through the real
CLI (1/2 devices), the replay-ratio governor's measured grad-steps-per-env-
step bound, the hard named error on an over-budget sequence ring, shared-
layout evaluation from a checkpoint, and a checkpoint → SIGKILL →
``resume_from=latest`` round trip restoring the ring (contents + per-env
heads + device train-key), both host RNG streams, and the Ratio counters."""

import ast
import glob
import os
import signal
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import sheeprl_tpu
from sheeprl_tpu.cli import run

REPO_ROOT = str(Path(sheeprl_tpu.__file__).parents[1])

XS_MODEL = [
    "algo=dreamer_v3_XS",
    "algo.name=dreamer_sebulba",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=2",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.world_model.reward_model.bins=17",
    "algo.critic.bins=17",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "env.screen_size=64",
]

SEBULBA_FAST = [
    "exp=dreamer_sebulba",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "buffer.size=128",
    "metric.log_level=0",
    "algo.run_test=False",
    *XS_MODEL,
    "algo.learning_starts=4",
    "algo.total_steps=32",
    "algo.sebulba.rollout_block=4",
    "checkpoint.save_last=False",
    "checkpoint.every=0",
]


def _ckpts(root):
    return sorted(glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)


def _stats(capfd):
    out, _err = capfd.readouterr()
    lines = [l for l in out.splitlines() if l.startswith("DREAMER_SEBULBA_STATS ")]
    assert lines, f"no DREAMER_SEBULBA_STATS line in output:\n{out[-2000:]}"
    return ast.literal_eval(lines[-1][len("DREAMER_SEBULBA_STATS "):])


@pytest.fixture()
def sebulba_debug(monkeypatch):
    monkeypatch.setenv("SHEEPRL_SEBULBA_DEBUG", "1")


@pytest.mark.parametrize("devices", [1, 2])
def test_dreamer_sebulba_dry_run(tmp_path, devices):
    """devices=1 time-slices one chip between the actor and learner sides;
    devices=2 splits them into disjoint single-device slices."""
    run(SEBULBA_FAST + [f"fabric.devices={devices}", f"log_root={tmp_path}/logs"])


def test_dreamer_sebulba_replay_ratio_governor(tmp_path, sebulba_debug, capfd):
    """The governor must hold the ACHIEVED grad-steps-per-env-step at the
    configured algo.replay_ratio (up to the prefill window and integer grant
    quantization), decoupled from how fast the actors produce."""
    ratio = 2.0
    run(
        SEBULBA_FAST
        + [
            "fabric.devices=1",
            "env.num_envs=1",
            f"algo.replay_ratio={ratio}",
            "algo.learning_starts=8",
            "algo.total_steps=64",
            f"log_root={tmp_path}/logs",
        ]
    )
    stats = _stats(capfd)
    env_steps = stats["Pipeline/env_steps_consumed"]
    grad_steps = stats["Pipeline/grad_steps"]
    assert env_steps >= 64
    expected = ratio * (env_steps - stats["prefill_policy_steps"])
    assert abs(grad_steps - expected) <= ratio + 1, (grad_steps, expected, stats)
    assert stats["Pipeline/replay_ratio_actual"] == pytest.approx(grad_steps / env_steps, abs=1e-3)


def test_dreamer_sebulba_over_budget_ring_is_hard_named_error(tmp_path):
    """The ring is this topology's ONLY storage tier: an over-budget SEQUENCE
    ring (heads + validity working set + gathered sample window, not just
    flat rows) must refuse at startup with a named error — never an OOM at
    the first append, never a silent host spillover."""
    with pytest.raises(RuntimeError, match="dreamer_sebulba streams sequence heads"):
        run(
            SEBULBA_FAST
            + [
                "fabric.devices=1",
                "buffer.hbm_budget_gb=1e-9",
                f"log_root={tmp_path}/logs",
            ]
        )


def test_dreamer_sebulba_evaluation_from_checkpoint(tmp_path):
    """dreamer_sebulba checkpoints share the dreamer family layout
    (world_model/actor/critic/target_critic at top level): the shared
    dreamer_v3 evaluate entrypoint loads them."""
    from sheeprl_tpu.cli import evaluation

    run(
        SEBULBA_FAST[:-2]
        + [
            "fabric.devices=1",
            "checkpoint.save_last=True",
            "checkpoint.every=0",
            f"log_root={tmp_path}/logs",
        ]
    )
    ckpt = _ckpts(f"{tmp_path}/logs")[-1]
    evaluation([f"checkpoint_path={ckpt}", "env.capture_video=False", "fabric.accelerator=cpu"])


KILL_ARGS = [
    "exp=dreamer_sebulba",
    "env=dummy",
    "env.num_envs=1",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "buffer.size=256",
    "buffer.checkpoint=True",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    *XS_MODEL,
    "algo.learning_starts=4",
    "algo.total_steps=48",
    "algo.sebulba.rollout_block=4",
    "checkpoint.every=16",
    "checkpoint.save_last=True",
    "seed=11",
    "log_root=logs",
]


def _launch(tmp_path, extra_args=(), extra_env=None):
    env = {
        **os.environ,
        "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
    }
    env.pop("SHEEPRL_FAULT_KILL", None)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu", *KILL_ARGS, *extra_args],
        cwd=tmp_path,
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )


@pytest.mark.fault
def test_dreamer_sebulba_checkpoint_kill_resume_from_latest(tmp_path):
    """Checkpoint → SIGKILL mid-save → ``resume_from=latest``: counters
    continue monotonically, BOTH host RNG streams and the Ratio state ride
    the checkpoint, and the sequence ring (contents, per-env heads, device
    train-key) is restored — proven by the final ring holding every consumed
    row of the whole 48-row schedule, which only a restored ring can."""
    proc = _launch(tmp_path, extra_env={"SHEEPRL_FAULT_KILL": "checkpoint.pre_commit:2"})
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]

    ckpt_dirs = glob.glob(
        str(tmp_path / "logs/dreamer_sebulba/discrete_dummy/*/version_*/checkpoint")
    )
    assert len(ckpt_dirs) == 1
    from sheeprl_tpu.fault.manager import latest_complete

    first_complete = latest_complete(ckpt_dirs[0])
    assert first_complete is not None and first_complete.name.startswith("ckpt_16")

    proc2 = _launch(tmp_path, extra_args=["checkpoint.resume_from=latest"])
    assert proc2.returncode == 0, (proc2.stdout[-2000:], proc2.stderr[-2000:])
    assert "checkpoint.resume_from=latest ->" in proc2.stdout

    from sheeprl_tpu.fault.manager import find_latest_run_checkpoint
    from sheeprl_tpu.utils.checkpoint import load_state

    final = find_latest_run_checkpoint(tmp_path / "logs/dreamer_sebulba/discrete_dummy")
    state = load_state(final)
    # counters continued monotonically to the full schedule
    assert state["iter_num"] >= 48
    assert int(os.path.basename(str(final)).split("_")[1]) >= 48
    # both host RNG streams and the Ratio governor rode the checkpoint
    assert state.get("rng") is not None and state.get("actor_rng") is not None
    assert state["ratio"]["_prev"] is not None
    import jax

    for leaf in jax.tree.leaves(
        {k: state[k] for k in ("world_model", "actor", "critic", "target_critic")}
    ):
        assert np.isfinite(np.asarray(leaf)).all()
    # ring state: every consumed regular row of the WHOLE run is in the ring
    # (the two actors split them, so only the SUM across per-env heads is
    # deterministic) — the resumed process must have restored the pre-kill
    # rows, not re-allocated
    rb = state["rb"][0] if isinstance(state["rb"], list) else state["rb"]
    from sheeprl_tpu.replay import DeviceReplayState

    assert isinstance(rb, DeviceReplayState) and rb.kind == "sequence"
    assert int(np.asarray(rb.arrays["valid"]).sum()) >= 48
    assert np.asarray(rb.arrays["pos"]).shape == (2,)  # one head per env column
    # the in-ring device train-key stream advanced past its seed and was
    # carried across the kill
    import jax.random as jrandom

    assert not np.array_equal(np.asarray(rb.arrays["key"]), np.asarray(jrandom.PRNGKey(11 + 31)))
