"""Dreamer-V3 train-step fidelity: the dynamic-learning ``lax.scan`` must be
exercised over a REAL time axis (VERDICT weak #4: the smoke configs pinned
``per_rank_sequence_length=1``, so the scan the whole design hinges on ran
for one step)."""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.config import compose

SEQ_LEN = 8
BATCH = 2
GRANTED = 2


def _tiny_cfg(tmp_path):
    return compose(
        [
            "exp=dreamer_v3",
            "algo=dreamer_v3_XS",
            "env=dummy",
            "env.num_envs=2",
            f"algo.per_rank_batch_size={BATCH}",
            f"algo.per_rank_sequence_length={SEQ_LEN}",
            "algo.horizon=5",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=2",
            "algo.world_model.recurrent_model.recurrent_state_size=16",
            "algo.world_model.representation_model.hidden_size=8",
            "algo.world_model.transition_model.hidden_size=8",
            "algo.world_model.discrete_size=4",
            "algo.world_model.stochastic_size=4",
            "algo.world_model.reward_model.bins=17",
            "algo.critic.bins=17",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
            "env.screen_size=64",
            f"log_root={tmp_path}",
        ]
    )


@pytest.mark.slow
def test_dreamer_v3_train_step_full_sequence(tmp_path):
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
    from sheeprl_tpu.optim.builders import build_optimizer
    from sheeprl_tpu.parallel.fabric import Fabric

    cfg = _tiny_cfg(tmp_path)
    fabric = Fabric(devices=1)
    obs_space = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8),
            "state": gym.spaces.Box(-20, 20, (10,), np.float32),
        }
    )
    world_model, actor, critic, params, _ = build_agent(fabric, (3,), False, cfg, obs_space)
    txs = {
        "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
        "actor": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
    }
    opts = {
        "world": txs["world"].init(params["world_model"]),
        "actor": txs["actor"].init(params["actor"]),
        "critic": txs["critic"].init(params["critic"]),
    }
    train_fn = make_train_step(world_model, actor, critic, cfg, fabric.mesh, (3,), False, txs)

    rng = np.random.default_rng(0)
    G, T, B = GRANTED, SEQ_LEN, BATCH
    data = {
        "rgb": rng.integers(0, 255, (G, T, B, 64, 64, 3)).astype(np.float32),
        "state": rng.normal(size=(G, T, B, 10)).astype(np.float32),
        "actions": np.eye(3, dtype=np.float32)[rng.integers(0, 3, (G, T, B))],
        "rewards": rng.normal(size=(G, T, B, 1)).astype(np.float32),
        "terminated": np.zeros((G, T, B, 1), np.float32),
        "truncated": np.zeros((G, T, B, 1), np.float32),
        "is_first": np.zeros((G, T, B, 1), np.float32),
    }
    # scatter some episode boundaries so the is_first state resets run
    data["is_first"][:, 3, 0] = 1.0
    data["terminated"][:, 2, 0] = 1.0

    moments0 = init_moments()
    old_actor_leaf = np.asarray(jax.tree.leaves(params["actor"])[0]).copy()
    params2, opts2, moments, metrics = train_fn(
        params, opts, moments0, data, jax.random.PRNGKey(0), jnp.int32(0)
    )

    for name, value in zip(
        (
            "world_model_loss", "observation_loss", "reward_loss", "state_loss", "continue_loss",
            "kl", "post_entropy", "prior_entropy", "policy_loss", "value_loss",
        ),
        metrics,
    ):
        assert np.isfinite(np.asarray(value)).all(), f"{name} is not finite over a {T}-step scan"
    # the scan actually trained: params moved and the Moments EMA left zero
    new_actor_leaf = np.asarray(jax.tree.leaves(params2["actor"])[0])
    assert not np.allclose(old_actor_leaf, new_actor_leaf)
    assert float(np.abs(np.asarray(moments["high"]))) > 0.0 or float(np.abs(np.asarray(moments["low"]))) > 0.0

    # two granted steps must produce a target-critic EMA different from the
    # plain copy (cum=0 hard-syncs, cum=1 EMA-mixes)
    tc = np.asarray(jax.tree.leaves(params2["target_critic"])[0])
    cc = np.asarray(jax.tree.leaves(params2["critic"])[0])
    assert not np.allclose(tc, cc)


@pytest.mark.slow
def test_dreamer_v3_cli_run_with_real_sequence(tmp_path):
    """End-to-end CLI run with per_rank_sequence_length=8 (not the seq-1
    degenerate): buffer sampling, scan, checkpoint all compose."""
    from sheeprl_tpu.cli import run

    run(
        [
            "exp=dreamer_v3",
            "algo=dreamer_v3_XS",
            "env=dummy",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "buffer.memmap=False",
            "fabric.devices=1",
            "metric.log_level=0",
            "algo.run_test=False",
            "checkpoint.save_last=False",
            f"log_root={tmp_path}",
            "algo.per_rank_batch_size=2",
            "algo.per_rank_sequence_length=8",
            "algo.horizon=4",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=2",
            "algo.world_model.recurrent_model.recurrent_state_size=16",
            "algo.world_model.representation_model.hidden_size=8",
            "algo.world_model.transition_model.hidden_size=8",
            "algo.world_model.discrete_size=4",
            "algo.world_model.stochastic_size=4",
            "algo.world_model.reward_model.bins=17",
            "algo.critic.bins=17",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
            "env.screen_size=64",
            "algo.learning_starts=12",
            "algo.replay_ratio=0.25",
            "algo.total_steps=40",
            "buffer.size=128",
        ]
    )
