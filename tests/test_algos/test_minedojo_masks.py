"""MineDojo action-mask enforcement in the Dreamer actors.

The mask-aware actors must never sample an action the environment marked
invalid (reference semantics: ``sheeprl/algos/dreamer_v3/agent.py:848-930``,
``sheeprl/algos/dreamer_v2/agent.py:577-660``): head 0 honours
``mask_action_type`` unconditionally; head 1 honours ``mask_craft_smelt``
only when head 0 sampled the craft action (15); head 2 honours
``mask_equip_place`` for equip/place (16/17) and ``mask_destroy`` for
destroy (18).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sheeprl_tpu.algos.dreamer_v2.agent as dv2_agent
import sheeprl_tpu.algos.dreamer_v3.agent as dv3_agent

ACTIONS_DIM = (19, 6, 5)
B = 8
LATENT = 16


def _make(module):
    actor = module(
        actions_dim=ACTIONS_DIM,
        is_continuous=False,
        distribution="discrete",
        dense_units=16,
        mlp_layers=1,
    )
    params = actor.init(jax.random.PRNGKey(0), jnp.zeros((B, LATENT)))
    return actor, params


def _full_mask(valid_types):
    """All-arg-valid mask allowing only ``valid_types`` in head 0."""
    m0 = np.zeros((B, ACTIONS_DIM[0]), np.float32)
    m0[:, valid_types] = 1.0
    return {
        "mask_action_type": jnp.asarray(m0),
        "mask_craft_smelt": jnp.ones((B, ACTIONS_DIM[1]), jnp.float32),
        "mask_destroy": jnp.ones((B, ACTIONS_DIM[2]), jnp.float32),
        "mask_equip_place": jnp.ones((B, ACTIONS_DIM[2]), jnp.float32),
    }


@pytest.mark.parametrize("agent_mod,actor_cls_name", [(dv3_agent, "MinedojoActor"), (dv2_agent, "MinedojoActor")])
@pytest.mark.parametrize("greedy", [False, True])
def test_masked_action_types_never_sampled(agent_mod, actor_cls_name, greedy):
    actor, params = _make(getattr(agent_mod, actor_cls_name))
    mask = _full_mask(valid_types=[0, 3, 15])
    state = jax.random.normal(jax.random.PRNGKey(1), (B, LATENT))
    for seed in range(20):
        acts, _ = agent_mod.actor_sample(
            actor, params, state, jax.random.PRNGKey(seed), greedy=greedy, mask=mask
        )
        chosen = np.argmax(np.asarray(acts[0]), axis=-1)
        assert set(chosen.tolist()) <= {0, 3, 15}


@pytest.mark.parametrize("agent_mod", [dv3_agent, dv2_agent])
def test_craft_arg_masked_when_crafting(agent_mod):
    actor, params = _make(agent_mod.MinedojoActor)
    # Force the functional action to craft (15): head-1 must then respect
    # mask_craft_smelt.
    mask = _full_mask(valid_types=[15])
    m1 = np.zeros((B, ACTIONS_DIM[1]), np.float32)
    m1[:, [1, 4]] = 1.0
    mask["mask_craft_smelt"] = jnp.asarray(m1)
    state = jax.random.normal(jax.random.PRNGKey(2), (B, LATENT))
    for seed in range(20):
        acts, _ = agent_mod.actor_sample(
            actor, params, state, jax.random.PRNGKey(seed), greedy=False, mask=mask
        )
        assert np.all(np.argmax(np.asarray(acts[0]), -1) == 15)
        assert set(np.argmax(np.asarray(acts[1]), -1).tolist()) <= {1, 4}


@pytest.mark.parametrize("agent_mod", [dv3_agent, dv2_agent])
@pytest.mark.parametrize("forced_type,mask_key,valid", [(16, "mask_equip_place", [2]), (17, "mask_equip_place", [2]), (18, "mask_destroy", [0, 3])])
def test_arg_head_masked_by_functional_action(agent_mod, forced_type, mask_key, valid):
    actor, params = _make(agent_mod.MinedojoActor)
    mask = _full_mask(valid_types=[forced_type])
    m2 = np.zeros((B, ACTIONS_DIM[2]), np.float32)
    m2[:, valid] = 1.0
    mask[mask_key] = jnp.asarray(m2)
    state = jax.random.normal(jax.random.PRNGKey(3), (B, LATENT))
    for seed in range(20):
        acts, _ = agent_mod.actor_sample(
            actor, params, state, jax.random.PRNGKey(seed), greedy=False, mask=mask
        )
        assert np.all(np.argmax(np.asarray(acts[0]), -1) == forced_type)
        assert set(np.argmax(np.asarray(acts[2]), -1).tolist()) <= set(valid)


@pytest.mark.parametrize("agent_mod", [dv3_agent, dv2_agent])
def test_plain_actor_ignores_mask(agent_mod):
    """The base Actor keeps reference behaviour: masks are ignored."""
    actor, params = _make(agent_mod.Actor)
    mask = _full_mask(valid_types=[0])
    state = jax.random.normal(jax.random.PRNGKey(4), (B, LATENT))
    seen = set()
    for seed in range(30):
        acts, _ = agent_mod.actor_sample(
            actor, params, state, jax.random.PRNGKey(seed), greedy=False, mask=mask
        )
        seen |= set(np.argmax(np.asarray(acts[0]), -1).tolist())
    # A freshly-initialized near-uniform policy over 19 types must stray
    # outside {0} if the mask is (correctly) not applied.
    assert len(seen) > 1


def test_extract_obs_masks():
    obs = {"rgb": jnp.zeros((1, 4)), "mask_action_type": jnp.ones((1, 19)), "inventory": jnp.zeros((1, 2))}
    mask = dv3_agent.extract_obs_masks(obs)
    assert set(mask) == {"mask_action_type"}
    assert dv3_agent.extract_obs_masks({"rgb": jnp.zeros((1, 4))}) is None


def test_minedojo_exp_config_selects_minedojo_actor():
    from sheeprl_tpu.config import compose

    cfg = compose(["exp=dreamer_v3_minedojo"])
    assert cfg.algo.actor.cls.rsplit(".", 1)[-1] == "MinedojoActor"
