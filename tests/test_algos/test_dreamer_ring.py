"""Device sequence-ring index math for Dreamer-V3 burst mode
(`ring_append_rows` / `ring_sample_windows`): per-env ragged appends and the
SequentialReplayBuffer window-validity rule on device.
"""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import ring_append_rows, ring_sample_windows

CAP = 10


def test_ragged_append_advances_only_masked_envs():
    pos = jnp.asarray([0, 5], jnp.int32)
    valid = jnp.asarray([0, 5], jnp.int32)
    # 3 slots: all-envs row, env-1-only reset row, all-envs row.
    mask = jnp.asarray([[1, 1], [0, 1], [1, 1]], jnp.int32)
    row, new_pos, new_valid = ring_append_rows(pos, valid, mask, CAP)
    # env 0 writes rows 0,2 at positions 0,1; slot 1 dropped (capacity).
    assert row[:, 0].tolist() == [0, CAP, 1]
    # env 1 writes 3 consecutive rows from its own head at 5.
    assert row[:, 1].tolist() == [5, 6, 7]
    assert new_pos.tolist() == [2, 8]
    assert new_valid.tolist() == [2, 8]


def test_append_wraps_and_caps_valid():
    pos = jnp.asarray([8], jnp.int32)
    valid = jnp.asarray([9], jnp.int32)
    mask = jnp.ones((4, 1), jnp.int32)
    row, new_pos, new_valid = ring_append_rows(pos, valid, mask, CAP)
    assert row[:, 0].tolist() == [8, 9, 0, 1]
    assert new_pos.tolist() == [2]
    assert new_valid.tolist() == [CAP]


def test_padding_slots_are_dropped():
    pos = jnp.asarray([3], jnp.int32)
    valid = jnp.asarray([3], jnp.int32)
    mask = jnp.asarray([[1], [0], [0]], jnp.int32)
    row, new_pos, _ = ring_append_rows(pos, valid, mask, CAP)
    assert row[:, 0].tolist() == [3, CAP, CAP]
    assert new_pos.tolist() == [4]


def test_windows_never_cross_write_head_when_full():
    seq = 4
    pos = jnp.asarray([6], jnp.int32)  # full ring: oldest data starts at 6
    valid = jnp.asarray([CAP], jnp.int32)
    env_idx = jnp.zeros((512,), jnp.int32)
    for s in range(20):
        t_idx = np.asarray(ring_sample_windows(jax.random.PRNGKey(s), env_idx, pos, valid, CAP, seq))
        # Unroll each window from its start: the write head (position 6 as a
        # window INTERIOR crossing) must never be straddled — i.e. no window
        # contains the transition 5 -> 6 (newest -> oldest).
        starts = t_idx[0]
        for st in np.unique(starts):
            window = [(st + i) % CAP for i in range(seq)]
            # 6 may only appear as the FIRST element (oldest row).
            if 6 in window:
                assert window[0] == 6 or 6 not in window[1:] or window.index(6) == 0
            # stronger: the pair (5, 6) must never be adjacent inside a window
            for a, b in zip(window[:-1], window[1:]):
                assert not (a == (pos[0] - 1) % CAP and b == pos[0])


def test_windows_stay_in_valid_prefix_when_not_full():
    seq = 3
    pos = jnp.asarray([7], jnp.int32)
    valid = jnp.asarray([7], jnp.int32)  # rows 0..6 valid
    env_idx = jnp.zeros((256,), jnp.int32)
    t_idx = np.asarray(ring_sample_windows(jax.random.PRNGKey(0), env_idx, pos, valid, CAP, seq))
    assert t_idx.min() >= 0
    assert t_idx.max() <= 6  # last valid start = 7 - 3 = 4 -> max index 6
