"""Device sequence-ring index math for Dreamer-V3 burst mode
(`ring_append_rows` / `ring_sample_windows`): per-env ragged appends and the
SequentialReplayBuffer window-validity rule on device.
"""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import ring_append_rows, ring_sample_windows

CAP = 10


def test_ragged_append_advances_only_masked_envs():
    pos = jnp.asarray([0, 5], jnp.int32)
    valid = jnp.asarray([0, 5], jnp.int32)
    # 3 slots: all-envs row, env-1-only reset row, all-envs row.
    mask = jnp.asarray([[1, 1], [0, 1], [1, 1]], jnp.int32)
    row, new_pos, new_valid = ring_append_rows(pos, valid, mask, CAP)
    # env 0 writes rows 0,2 at positions 0,1; slot 1 dropped (capacity).
    assert row[:, 0].tolist() == [0, CAP, 1]
    # env 1 writes 3 consecutive rows from its own head at 5.
    assert row[:, 1].tolist() == [5, 6, 7]
    assert new_pos.tolist() == [2, 8]
    assert new_valid.tolist() == [2, 8]


def test_append_wraps_and_caps_valid():
    pos = jnp.asarray([8], jnp.int32)
    valid = jnp.asarray([9], jnp.int32)
    mask = jnp.ones((4, 1), jnp.int32)
    row, new_pos, new_valid = ring_append_rows(pos, valid, mask, CAP)
    assert row[:, 0].tolist() == [8, 9, 0, 1]
    assert new_pos.tolist() == [2]
    assert new_valid.tolist() == [CAP]


def test_padding_slots_are_dropped():
    pos = jnp.asarray([3], jnp.int32)
    valid = jnp.asarray([3], jnp.int32)
    mask = jnp.asarray([[1], [0], [0]], jnp.int32)
    row, new_pos, _ = ring_append_rows(pos, valid, mask, CAP)
    assert row[:, 0].tolist() == [3, CAP, CAP]
    assert new_pos.tolist() == [4]


def test_windows_never_cross_write_head_when_full():
    seq = 4
    pos = jnp.asarray([6], jnp.int32)  # full ring: oldest data starts at 6
    valid = jnp.asarray([CAP], jnp.int32)
    env_idx = jnp.zeros((512,), jnp.int32)
    for s in range(20):
        t_idx = np.asarray(ring_sample_windows(jax.random.PRNGKey(s), env_idx, pos, valid, CAP, seq))
        # Unroll each window from its start: the write head (position 6 as a
        # window INTERIOR crossing) must never be straddled — i.e. no window
        # contains the transition 5 -> 6 (newest -> oldest).
        starts = t_idx[0]
        for st in np.unique(starts):
            window = [(st + i) % CAP for i in range(seq)]
            # 6 may only appear as the FIRST element (oldest row).
            if 6 in window:
                assert window[0] == 6 or 6 not in window[1:] or window.index(6) == 0
            # stronger: the pair (5, 6) must never be adjacent inside a window
            for a, b in zip(window[:-1], window[1:]):
                assert not (a == (pos[0] - 1) % CAP and b == pos[0])


def test_windows_stay_in_valid_prefix_when_not_full():
    seq = 3
    pos = jnp.asarray([7], jnp.int32)
    valid = jnp.asarray([7], jnp.int32)  # rows 0..6 valid
    env_idx = jnp.zeros((256,), jnp.int32)
    t_idx = np.asarray(ring_sample_windows(jax.random.PRNGKey(0), env_idx, pos, valid, CAP, seq))
    assert t_idx.min() >= 0
    assert t_idx.max() <= 6  # last valid start = 7 - 3 = 4 -> max index 6


# -- episode-rule sampling (Dreamer-V2 buffer.type=episode on the ring) -------


def _episode_ring(first_rows, cap, n_envs=1):
    """A (cap, n_envs, 1) is_first channel with 1s at the given rows."""
    f = np.zeros((cap, n_envs, 1), np.float32)
    for r in first_rows:
        f[r, :] = 1.0
    return jnp.asarray(f)


def test_episode_windows_never_contain_interior_boundary():
    from sheeprl_tpu.data.ring import ring_sample_windows_episode

    cap, seq = 16, 4
    # episodes start at rows 0, 5, 9 in a 12-row valid prefix
    is_first = _episode_ring([0, 5, 9], cap)
    pos = jnp.asarray([12], jnp.int32)
    valid = jnp.asarray([12], jnp.int32)
    env_idx = jnp.zeros((512,), jnp.int32)
    firsts = {0, 5, 9}
    for s in range(10):
        t_idx = np.asarray(
            ring_sample_windows_episode(jax.random.PRNGKey(s), env_idx, pos, valid, is_first, cap, seq)
        )
        for b in range(t_idx.shape[1]):
            window = t_idx[:, b].tolist()
            # boundary rows may appear only as the window's FIRST element
            for w in window[1:]:
                assert w not in firsts, (window, s)
            # and the sequential prefix rule still holds (valid rows 0..11,
            # max start 12-4=8 -> max index 11)
            assert max(window) <= 11


def test_episode_windows_cover_all_valid_starts():
    from sheeprl_tpu.data.ring import ring_sample_windows_episode

    cap, seq = 16, 3
    is_first = _episode_ring([0, 6], cap)
    pos = jnp.asarray([12], jnp.int32)
    valid = jnp.asarray([12], jnp.int32)
    env_idx = jnp.zeros((2048,), jnp.int32)
    t_idx = np.asarray(
        ring_sample_windows_episode(jax.random.PRNGKey(1), env_idx, pos, valid, is_first, cap, seq)
    )
    starts = set(t_idx[0].tolist())
    # valid starts: episode A rows 0..3 (windows end before 6), episode B rows
    # 6..9 (end before head 12); rows 4,5 would straddle the boundary at 6
    assert starts == {0, 1, 2, 3, 6, 7, 8, 9}, starts


def test_episode_sampling_falls_back_when_no_boundary_free_window():
    from sheeprl_tpu.data.ring import ring_sample_windows_episode

    cap, seq = 16, 4
    # every episode is 2 rows long -> no boundary-free window of length 4
    is_first = _episode_ring([0, 2, 4, 6, 8, 10, 12, 14], cap)
    pos = jnp.asarray([16], jnp.int32)
    valid = jnp.asarray([16], jnp.int32)
    env_idx = jnp.zeros((128,), jnp.int32)
    t_idx = np.asarray(
        ring_sample_windows_episode(jax.random.PRNGKey(2), env_idx, pos, valid, is_first, cap, seq)
    )
    # falls back to the sequential rule rather than emitting NaN/garbage
    assert t_idx.min() >= 0 and t_idx[0].max() <= 16 - seq


def test_episode_windows_respect_wrapped_ring():
    from sheeprl_tpu.data.ring import ring_sample_windows_episode

    cap, seq = 10, 3
    # full ring, head at 6; episode boundary at row 9 (inside the wrapped
    # valid range 6,7,...,9,0,...,5)
    is_first = _episode_ring([9], cap)
    pos = jnp.asarray([6], jnp.int32)
    valid = jnp.asarray([cap], jnp.int32)
    env_idx = jnp.zeros((1024,), jnp.int32)
    t_idx = np.asarray(
        ring_sample_windows_episode(jax.random.PRNGKey(3), env_idx, pos, valid, is_first, cap, seq)
    )
    for b in range(t_idx.shape[1]):
        window = t_idx[:, b].tolist()
        for w in window[1:]:
            assert w != 9  # never interior
        for a, bb in zip(window[:-1], window[1:]):
            assert not (a == 5 and bb == 6)  # never straddles the head
