"""sac_sebulba end-to-end: async actor/learner dry runs through the real CLI
(1/2 devices, env-sharded learner mesh, PER), the replay-ratio governor's
measured grad-steps-per-env-step bound, queue back-pressure under more actors
than slots, a checkpoint → SIGKILL → ``resume_from=latest`` round trip that
restores both RNG streams and the ring state, and (slow lane) Pendulum return
parity vs the coupled SAC host loop."""

import ast
import glob
import os
import signal
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import sheeprl_tpu
from sheeprl_tpu.cli import run

REPO_ROOT = str(Path(sheeprl_tpu.__file__).parents[1])

SEBULBA_FAST = [
    "exp=sac_sebulba",
    "env=dummy",
    "env.id=continuous_dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "buffer.size=64",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.per_rank_batch_size=8",
    "algo.hidden_size=16",
    "algo.mlp_keys.encoder=[state]",
    "algo.learning_starts=4",
    "algo.total_steps=32",
    "checkpoint.save_last=False",
    "checkpoint.every=0",
]


def _ckpts(root):
    return sorted(glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)


def _stats(capfd):
    """Parse the SAC_SEBULBA_STATS debug line the run prints."""
    out, _err = capfd.readouterr()
    lines = [l for l in out.splitlines() if l.startswith("SAC_SEBULBA_STATS ")]
    assert lines, f"no SAC_SEBULBA_STATS line in output:\n{out[-2000:]}"
    return ast.literal_eval(lines[-1][len("SAC_SEBULBA_STATS "):])


@pytest.fixture()
def sebulba_debug(monkeypatch):
    monkeypatch.setenv("SHEEPRL_SEBULBA_DEBUG", "1")


@pytest.mark.parametrize("devices", [1, 2])
def test_sac_sebulba_dry_run(tmp_path, devices):
    """devices=1 time-slices one chip between the actor and learner sides;
    devices=2 splits them into disjoint single-device slices."""
    run(SEBULBA_FAST + [f"fabric.devices={devices}", f"log_root={tmp_path}/logs"])


def test_sac_sebulba_env_sharded_learner(tmp_path, capfd):
    """actor_devices=0 on a 2-device mesh keeps BOTH devices on the learner
    side: with num_envs divisible the ring storage env-shards over `dp`
    (per-device HBM = total/2) and the run must still consume total_steps."""
    run(
        SEBULBA_FAST
        + [
            "fabric.devices=2",
            "algo.sebulba.actor_devices=0",
            "metric.log_level=1",
            "metric.log_every=70000",
            f"log_root={tmp_path}/logs",
        ]
    )
    out, _ = capfd.readouterr()
    assert "shard_envs=True" in out


def test_sac_sebulba_replay_ratio_governor(tmp_path, sebulba_debug, capfd):
    """The governor must hold the ACHIEVED grad-steps-per-env-step at the
    configured algo.replay_ratio (up to the prefill window and integer
    grant quantization), decoupled from how fast the actors produce."""
    ratio = 2.0
    run(
        SEBULBA_FAST
        + [
            "fabric.devices=1",
            "env.num_envs=1",
            f"algo.replay_ratio={ratio}",
            "algo.learning_starts=8",
            "algo.total_steps=128",
            "algo.sebulba.rollout_block=4",
            f"log_root={tmp_path}/logs",
        ]
    )
    stats = _stats(capfd)
    env_steps = stats["Pipeline/env_steps_consumed"]
    grad_steps = stats["Pipeline/grad_steps"]
    assert env_steps >= 128
    # grants start after the prefill window: expected ≈ ratio * (consumed -
    # prefill); allow the first-grant quantization one step of slack
    expected = ratio * (env_steps - stats["prefill_policy_steps"])
    assert abs(grad_steps - expected) <= ratio + 1, (grad_steps, expected, stats)
    # and the logged gauge agrees with the raw counters
    assert stats["Pipeline/replay_ratio_actual"] == pytest.approx(grad_steps / env_steps, abs=1e-3)


def test_sac_sebulba_backpressure_small_queue(tmp_path, sebulba_debug, capfd):
    """More actors than queue slots for many learner iterations: the bounded
    queue must back-pressure (not drop/deadlock), the run must consume
    total_steps, and the stall/starvation gauges must be populated."""
    run(
        SEBULBA_FAST
        + [
            "fabric.devices=1",
            "algo.total_steps=96",
            "algo.sebulba.num_actor_threads=3",
            "algo.sebulba.queue_depth=1",
            "algo.sebulba.publish_every=2",
            f"log_root={tmp_path}/logs",
        ]
    )
    stats = _stats(capfd)
    assert stats["Pipeline/env_steps_consumed"] >= 96
    assert stats["Pipeline/rollouts_produced"] >= stats["Pipeline/rollouts_consumed"] > 0
    # 3 fast actors against a depth-1 queue MUST have blocked at least once
    assert stats["Pipeline/actor_stall_s"] > 0
    assert stats["Pipeline/max_queue_depth"] <= 1
    for key in ("Pipeline/learner_starved_s", "Pipeline/param_staleness", "Pipeline/replay_ratio_actual"):
        assert key in stats


def test_sac_sebulba_prioritized(tmp_path):
    """PER on the async path: proportional in-graph sampling + IS weights,
    fresh streamed transitions entering at max priority."""
    run(
        SEBULBA_FAST
        + [
            "fabric.devices=1",
            "buffer.priority.enabled=true",
            f"log_root={tmp_path}/logs",
        ]
    )


def test_sac_sebulba_evaluation_from_checkpoint(tmp_path):
    """The sac_sebulba checkpoint shares the SAC "agent" layout: the shared
    sac evaluate entrypoint loads it."""
    from sheeprl_tpu.cli import evaluation

    run(
        SEBULBA_FAST[:-2]
        + [
            "fabric.devices=1",
            "checkpoint.save_last=True",
            "checkpoint.every=0",
            f"log_root={tmp_path}/logs",
        ]
    )
    ckpt = _ckpts(f"{tmp_path}/logs")[-1]
    evaluation([f"checkpoint_path={ckpt}", "env.capture_video=False", "fabric.accelerator=cpu"])


KILL_ARGS = [
    "exp=sac_sebulba",
    "env=dummy",
    "env.id=continuous_dummy",
    "env.num_envs=1",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "buffer.size=64",
    "buffer.checkpoint=True",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.per_rank_batch_size=8",
    "algo.hidden_size=16",
    "algo.mlp_keys.encoder=[state]",
    "algo.learning_starts=4",
    "algo.total_steps=48",
    "algo.sebulba.rollout_block=4",
    "checkpoint.every=16",
    "checkpoint.save_last=True",
    "seed=11",
    "log_root=logs",
]


def _launch(tmp_path, extra_args=(), extra_env=None):
    env = {
        **os.environ,
        "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
    }
    env.pop("SHEEPRL_FAULT_KILL", None)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu", *KILL_ARGS, *extra_args],
        cwd=tmp_path,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )


@pytest.mark.fault
def test_sac_sebulba_checkpoint_kill_resume_from_latest(tmp_path):
    """Checkpoint → SIGKILL mid-save → ``resume_from=latest``: the resumed
    run continues the counters AND restores the two RNG streams (actor base
    key + in-ring train-key stream) and the full ring state — proven by the
    final ring holding every row of the whole 48-step schedule, which only a
    restored ring can."""
    proc = _launch(tmp_path, extra_env={"SHEEPRL_FAULT_KILL": "checkpoint.pre_commit:2"})
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]

    ckpt_dirs = glob.glob(str(tmp_path / "logs/sac_sebulba/continuous_dummy/*/version_*/checkpoint"))
    assert len(ckpt_dirs) == 1
    from sheeprl_tpu.fault.manager import latest_complete

    first_complete = latest_complete(ckpt_dirs[0])
    assert first_complete is not None and first_complete.name.startswith("ckpt_16")

    proc2 = _launch(tmp_path, extra_args=["checkpoint.resume_from=latest"])
    assert proc2.returncode == 0, (proc2.stdout[-2000:], proc2.stderr[-2000:])
    assert "checkpoint.resume_from=latest ->" in proc2.stdout

    from sheeprl_tpu.fault.manager import find_latest_run_checkpoint
    from sheeprl_tpu.utils.checkpoint import load_state

    final = find_latest_run_checkpoint(tmp_path / "logs/sac_sebulba/continuous_dummy")
    state = load_state(final)
    # counters continued monotonically to the full schedule
    assert state["iter_num"] >= 48
    assert int(os.path.basename(str(final)).split("_")[1]) >= 48
    # both RNG streams rode the checkpoint
    assert state.get("rng") is not None and state.get("actor_rng") is not None
    import jax

    for leaf in jax.tree.leaves(state["agent"]):
        assert np.isfinite(np.asarray(leaf)).all()
    # ring state: every env-step row of the WHOLE run is in the ring — the
    # resumed process must have restored the pre-kill rows, not re-allocated
    rb = state["rb"][0] if isinstance(state["rb"], list) else state["rb"]
    from sheeprl_tpu.replay import DeviceReplayState

    assert isinstance(rb, DeviceReplayState)
    assert int(rb.arrays["valid"]) >= 48
    # the in-ring train-key stream advanced past its seed (fresh PRNGKey(seed
    # + 29)) and was carried across the kill
    import jax.random as jrandom

    assert not np.array_equal(np.asarray(rb.arrays["key"]), np.asarray(jrandom.PRNGKey(11 + 29)))


@pytest.mark.slow
def test_sac_sebulba_return_parity_with_coupled_loop_on_pendulum(tmp_path):
    """Same recipe, same budget on real Pendulum: the async run's returns
    must match the coupled host loop's (the decoupling adds bounded
    staleness, not a different algorithm). Both must clear an absolute floor
    no non-learning agent reaches (random Pendulum ≈ -1200)."""
    sys.path.insert(0, REPO_ROOT)
    from benchmarks.learning_bench import capture_returns

    budget = 16384
    common = [
        "env=gym",
        "env.id=Pendulum-v1",
        "env.num_envs=1",
        "env.sync_env=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "buffer.size=65536",
        "buffer.checkpoint=False",
        "fabric.devices=1",
        "metric.log_level=1",
        "metric.log_every=70000",
        "algo.run_test=False",
        f"algo.total_steps={budget}",
        "algo.replay_ratio=1.0",
        "algo.learning_starts=512",
        "algo.per_rank_batch_size=256",
        "algo.hidden_size=64",
        "algo.mlp_keys.encoder=[state]",
        "checkpoint.every=0",
        "checkpoint.save_last=False",
        "seed=7",
    ]

    def best_window(returns, w=10):
        if len(returns) < w:
            return -1e9
        return max(sum(returns[i : i + w]) / w for i in range(len(returns) - w + 1))

    host = capture_returns(
        ["exp=sac", "algo.hybrid_player.enabled=False", f"log_root={tmp_path}/host"] + common
    )
    seb = capture_returns(["exp=sac_sebulba", f"log_root={tmp_path}/sebulba"] + common)
    host_best, seb_best = best_window(host), best_window(seb)
    assert host_best >= -500, f"coupled SAC failed to learn Pendulum: best10={host_best} n={len(host)}"
    assert seb_best >= -500, f"sac_sebulba failed to learn Pendulum: best10={seb_best} n={len(seb)}"
