"""Vmapped population training on the Anakin path.

Coverage mirrors the PR acceptance criteria:

- P=1 population block is BIT-identical to the plain ``make_anakin_block``
  (same seed, same hparams — the size-1 vmap is unrolled so XLA emits the
  exact single-run program);
- P=4 dry runs through the real CLI on 1/2 devices (envs sharded under the
  population axis), plus the ``algo=ppo_anakin algo.population.size=P``
  trigger route;
- sweep-spec resolution: grid order/product, random determinism per seed,
  per-hparam stream independence, every rejection path;
- PBT truncation step: determinism under a fixed key,
  all-members-identical stays identical, copy/perturb/clamp semantics;
- population checkpoint → SIGKILL mid-save → ``resume_from=latest`` round
  trip (params, hparams and every RNG stream restored — proven by resuming
  under a DIFFERENT seed, which would re-draw a random sweep if the driver
  re-resolved instead of restoring);
- block-length regression: a run with a remainder block compiles the
  population block at most twice (body + remainder) with P>1;
- slow lane: best-of-population CartPole trailing return clears the
  single-run threshold.
"""

import glob
import os
import signal
import subprocess
import sys

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.config import compose

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FAST = [
    "env=gym",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "metric.log_level=0",
    "checkpoint.save_last=False",
    "algo.run_test=False",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]


def _args(tmp_path, *extra, devices=1, dry=True):
    args = [
        "exp=ppo_anakin_population",
        *FAST,
        f"fabric.devices={devices}",
        f"log_root={tmp_path}/logs",
    ]
    if dry:
        args.append("dry_run=True")
    args.extend(extra)
    return args


# --------------------------------------------------------------------------- #
# P=1 bit-parity vs the plain fused block
# --------------------------------------------------------------------------- #


def _parity_cfg():
    return compose(
        [
            "exp=ppo_anakin",
            *FAST,
            "fabric.devices=1",
        ]
    )


def _fresh_inputs(cfg, fabric, params_np, tx, benv):
    """Rebuild the block inputs from fixed keys (block args are donated, so
    every dispatch needs its own buffers)."""
    params = jax.tree.map(jnp.asarray, params_np)
    opt_state = tx.init(params)
    env_state, obs = jax.jit(benv.reset)(jax.random.PRNGKey(5))
    num_envs = int(cfg.env.num_envs)
    ep_ret = jnp.zeros((num_envs,), jnp.float32)
    ep_len = jnp.zeros((num_envs,), jnp.int32)
    env_keys = jax.random.split(jax.random.PRNGKey(6), fabric.world_size)
    train_key = jax.random.PRNGKey(7)
    return params, opt_state, env_state, obs, ep_ret, ep_len, env_keys, train_key


def _run_parity_check():
    """The P=1 population dispatch (traced hparams, member axis, fitness
    ferry) must produce BIT-identical params / optimizer state / env state /
    losses to the plain single-run block under the same keys and hparams.

    Executed in a FRESH subprocess (see the test below): bit-parity across
    two *different* XLA programs is only well-defined when both compile
    under identical compiler state. In-process suite history — warm tracing
    caches from earlier runs, persistent-cache AOT loads (XLA:CPU's
    serialize/load path codegens the shared core differently than the
    in-process JIT, the same cpu_aot_loader wobble PR 3 documented) —
    perturbs one program's codegen at ulp level, and two training
    iterations of action *sampling* amplify one flipped logit ulp into a
    fully divergent trajectory. A clean process compiles both programs side
    by side, which is exactly the invariant the production driver relies
    on: the P=1 program IS the single-run program."""
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo_anakin import make_anakin_block
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import _base_hparams, make_population_block
    from sheeprl_tpu.envs.jax_envs import BatchedJaxEnv, make_jax_env
    from sheeprl_tpu.optim.builders import build_optimizer
    from sheeprl_tpu.parallel import Fabric

    cfg = _parity_cfg()
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(42)
    jenv = make_jax_env("CartPole-v1")
    obs_key = "state"
    obs_space = gym.spaces.Dict({obs_key: jenv.observation_space})
    agent, params, _ = build_agent(fabric, (2,), False, cfg, obs_space, None)
    params_np = jax.device_get(params)

    lr0 = float(cfg.algo.optimizer.lr)
    tx = optax.inject_hyperparams(
        lambda learning_rate: build_optimizer(
            {**cfg.algo.optimizer, "lr": learning_rate}, max_grad_norm=cfg.algo.max_grad_norm
        )
    )(learning_rate=lr0)

    num_envs = int(cfg.env.num_envs)
    benv = BatchedJaxEnv(jenv, num_envs)
    iters = 2
    clip0 = float(cfg.algo.clip_coef)
    ent0 = float(cfg.algo.ent_coef)

    # -- single-run fused block ------------------------------------------- #
    block = make_anakin_block(
        agent, tx, cfg, fabric.mesh, benv, num_envs, iters, obs_key, ferry_episodes=True, guard=False
    )
    sp, so, ss, sob, sret, slen, skeys, tkey = _fresh_inputs(cfg, fabric, params_np, tx, benv)
    s_params, s_opt, s_env, s_obs, s_ret, s_len, _, s_metrics = block(
        sp, so, ss, sob, sret, slen, skeys, tkey,
        jnp.asarray(clip0, jnp.float32), jnp.asarray(ent0, jnp.float32),
        jenv.default_params(),
    )
    s_params = jax.device_get(s_params)
    s_metrics = jax.device_get(s_metrics)
    s_obs = np.asarray(s_obs)

    # -- P=1 population dispatch over the SAME inputs ---------------------- #
    pblock = make_population_block(
        agent, tx, cfg, fabric.mesh, benv, num_envs, iters, obs_key,
        pop_size=1, ferry_episodes=True, guard=False, pbt=None,
    )
    pp, po, ps, pob, pret, plen, pkeys, tkey = _fresh_inputs(cfg, fabric, params_np, tx, benv)
    stack = lambda tree: jax.tree.map(lambda x: x[None], tree)
    hparams = {k: jnp.full((1,), v, jnp.float32) for k, v in _base_hparams(cfg).items()}
    env_params = stack(jenv.default_params())
    p_params, p_opt, p_env, p_obs, p_ret, p_len, _, p_hparams, p_env_params, p_fit, p_metrics = pblock(
        stack(pp), stack(po), stack(ps), stack(pob), stack(pret), stack(plen), stack(pkeys),
        tkey[None], hparams, env_params, jnp.ones((3,), jnp.float32), jnp.asarray(False),
        jax.random.PRNGKey(0),
    )
    p_params = jax.device_get(p_params)
    p_metrics = jax.device_get(p_metrics)

    for a, b in zip(jax.tree.leaves(s_params), jax.tree.leaves(p_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b)[0])
    np.testing.assert_array_equal(s_obs, np.asarray(p_obs)[0])
    np.testing.assert_array_equal(np.asarray(s_ret), np.asarray(p_ret)[0])
    np.testing.assert_array_equal(np.asarray(s_len), np.asarray(p_len)[0])
    for k in ("pg", "v", "ent"):
        np.testing.assert_array_equal(np.asarray(s_metrics[k]), np.asarray(p_metrics[k])[0])
    np.testing.assert_array_equal(np.asarray(s_metrics["ep_done"]), np.asarray(p_metrics["ep_done"])[0])
    np.testing.assert_array_equal(np.asarray(s_metrics["ep_ret"]), np.asarray(p_metrics["ep_ret"])[0])
    # the hparams AND env params ride through unchanged without PBT,
    # fitness is finite
    for k, v in hparams.items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(p_hparams[k]))
    for a, b in zip(jax.tree.leaves(env_params), jax.tree.leaves(p_env_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.isfinite(np.asarray(p_fit)).all() and np.asarray(p_fit).shape == (1,)
    print("PARITY_OK")


def test_population_block_p1_bit_parity_with_single_block():
    """Run the bit-parity check in a fresh subprocess (no persistent XLA
    cache, no warm tracing caches) — see :func:`_run_parity_check` for why
    cross-program BIT-parity demands a clean compiler state."""
    env = {
        **os.environ,
        "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0 and "PARITY_OK" in proc.stdout, (
        proc.stdout[-3000:],
        proc.stderr[-3000:],
    )


# --------------------------------------------------------------------------- #
# CLI dry runs — envs sharded under the population axis
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("devices", [1, 2])
def test_population_dry_run(tmp_path, devices):
    run(_args(tmp_path, "algo.population.size=4", "algo.population.hparams={}", devices=devices))


def test_population_acrobot_dry_run(tmp_path):
    """The third dynamics regime of the zoo (underactuated double pendulum,
    sparse cost) trains through the population path: obs dim 6, 3 actions,
    truncation bootstrap in-graph."""
    run(
        _args(
            tmp_path,
            "env.id=Acrobot-v1",
            "algo.population.size=2",
            "algo.population.hparams={}",
        )
    )


def test_population_grid_sweep_dry_run(tmp_path):
    run(
        _args(
            tmp_path,
            "algo.population.size=4",
            "algo.population.hparams={lr: [1e-3, 5e-4], ent_coef: [0.0, 0.01]}",
        )
    )


def test_population_trigger_from_anakin_main(tmp_path):
    """`algo=ppo_anakin algo.population.size=P` routes into the population
    driver and stamps the population algo name (so eval/serve/resume resolve
    the population-aware entry points)."""
    run(
        [
            "exp=ppo_anakin",
            *FAST,
            "fabric.devices=1",
            f"log_root={tmp_path}/logs",
            "dry_run=True",
            "algo.population.size=2",
            "algo.population.hparams={}",
        ]
    )
    assert glob.glob(str(tmp_path / "logs/ppo_anakin_population/CartPole-v1/*"))


def test_population_rejects_host_env(tmp_path):
    with pytest.raises(ValueError, match="pure-JAX"):
        run(
            _args(
                tmp_path,
                "env.id=discrete_dummy",
                "algo.population.size=2",
                "algo.population.hparams={}",
            )
        )


# --------------------------------------------------------------------------- #
# Sweep-spec resolution
# --------------------------------------------------------------------------- #


def _sweep_cfg(*extra):
    return compose(["exp=ppo_anakin", *FAST, "fabric.devices=1", *extra])


def test_sweep_grid_order_and_broadcast():
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import resolve_sweep

    cfg = _sweep_cfg(
        "algo.population.sweep=grid",
        "algo.population.hparams={lr: [1e-3, 5e-4], ent_coef: [0.0, 0.01]}",
    )
    hp, swept = resolve_sweep(cfg, 4, seed=0)
    # cartesian product in HPARAM_KEYS order: lr is the outer axis
    np.testing.assert_allclose(hp["lr"], [1e-3, 1e-3, 5e-4, 5e-4], rtol=1e-6)
    np.testing.assert_allclose(hp["ent_coef"], [0.0, 0.01, 0.0, 0.01], rtol=1e-6)
    # unswept keys broadcast the run config's scalar
    np.testing.assert_allclose(hp["gamma"], np.full(4, float(cfg.algo.gamma)), rtol=1e-6)
    assert swept == ("lr", "ent_coef")
    # grid is seed-independent
    hp2, _ = resolve_sweep(cfg, 4, seed=99)
    for k in hp:
        np.testing.assert_array_equal(hp[k], hp2[k])


def test_sweep_random_deterministic_per_seed():
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import resolve_sweep

    cfg = _sweep_cfg(
        "algo.population.sweep=random",
        "algo.population.hparams={lr: {low: 1e-4, high: 1e-2, log: true}, ent_coef: {choices: [0.0, 0.01, 0.1]}}",
    )
    hp1, swept = resolve_sweep(cfg, 16, seed=3)
    hp2, _ = resolve_sweep(cfg, 16, seed=3)
    hp3, _ = resolve_sweep(cfg, 16, seed=4)
    assert swept == ("lr", "ent_coef")
    for k in hp1:
        np.testing.assert_array_equal(hp1[k], hp2[k])
    assert not np.array_equal(hp1["lr"], hp3["lr"])
    assert ((hp1["lr"] >= 1e-4) & (hp1["lr"] <= 1e-2)).all()
    assert np.isin(hp1["ent_coef"], np.asarray([0.0, 0.01, 0.1], np.float32)).all()


def test_sweep_random_streams_are_per_hparam():
    """Adding a second swept hparam must not reshuffle the first one's draws
    (streams are keyed by (seed, name))."""
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import resolve_sweep

    lone = _sweep_cfg(
        "algo.population.sweep=random",
        "algo.population.hparams={lr: {low: 1e-4, high: 1e-2, log: true}}",
    )
    both = _sweep_cfg(
        "algo.population.sweep=random",
        "algo.population.hparams={lr: {low: 1e-4, high: 1e-2, log: true}, gamma: {low: 0.9, high: 0.999}}",
    )
    hp_lone, _ = resolve_sweep(lone, 8, seed=5)
    hp_both, _ = resolve_sweep(both, 8, seed=5)
    np.testing.assert_array_equal(hp_lone["lr"], hp_both["lr"])


def test_sweep_rejections():
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import resolve_sweep

    with pytest.raises(ValueError, match="cartesian product"):
        resolve_sweep(_sweep_cfg("algo.population.hparams={lr: [1e-3, 5e-4]}"), 3, seed=0)
    with pytest.raises(ValueError, match="cannot expand the range"):
        resolve_sweep(
            _sweep_cfg("algo.population.hparams={lr: {low: 1e-4, high: 1e-2}}"), 4, seed=0
        )
    with pytest.raises(ValueError, match="Unknown population hparam"):
        resolve_sweep(_sweep_cfg("algo.population.hparams={vf_coef: [0.5, 1.0]}"), 2, seed=0)
    with pytest.raises(ValueError, match="low > 0"):
        resolve_sweep(
            _sweep_cfg(
                "algo.population.sweep=random",
                "algo.population.hparams={lr: {low: 0.0, high: 1e-2, log: true}}",
            ),
            2,
            seed=0,
        )
    with pytest.raises(ValueError, match="grid' or 'random"):
        resolve_sweep(_sweep_cfg("algo.population.sweep=bayes"), 2, seed=0)


# --------------------------------------------------------------------------- #
# Scenario matrix: env_params resolution
# --------------------------------------------------------------------------- #


def test_matrix_grid_is_joint_cartesian():
    """hparam and env-param choices share ONE grid — hparam axes outer
    (HPARAM_KEYS order), env-param axes inner (default_params field order);
    unswept env fields broadcast their defaults in the field dtype."""
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import resolve_matrix
    from sheeprl_tpu.envs.jax_envs import make_jax_env

    env = make_jax_env("CartPole-v1")
    cfg = _sweep_cfg(
        "algo.population.sweep=grid",
        "algo.population.hparams={lr: [1e-3, 5e-4]}",
        "algo.population.env_params={length: [0.25, 0.5]}",
    )
    hp, swept, ep, env_swept = resolve_matrix(cfg, 4, seed=0, env=env)
    assert swept == ("lr",) and env_swept == ("length",)
    np.testing.assert_allclose(hp["lr"], [1e-3, 1e-3, 5e-4, 5e-4], rtol=1e-6)
    np.testing.assert_allclose(ep["length"], [0.25, 0.5, 0.25, 0.5], rtol=1e-6)
    # unswept env fields broadcast the default, dtype preserved
    np.testing.assert_allclose(ep["gravity"], np.full(4, 9.8, np.float32), rtol=1e-6)
    assert ep["max_episode_steps"].dtype == np.int32
    np.testing.assert_array_equal(ep["max_episode_steps"], np.full(4, 500, np.int32))
    # grid is seed-independent
    _, _, ep2, _ = resolve_matrix(cfg, 4, seed=77, env=env)
    for k in ep:
        np.testing.assert_array_equal(ep[k], ep2[k])
    # joint product must equal size exactly
    with pytest.raises(ValueError, match="share ONE grid"):
        resolve_matrix(cfg, 3, seed=0, env=env)


def test_matrix_random_streams_never_reshuffle():
    """Env-param streams are keyed by (seed, 'env_params.<name>'): adding an
    hparam axis or another env axis never changes an existing field's draws,
    and an env field named like an hparam gets its own stream. Integer
    fields (max_episode_steps) round to their dtype."""
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import resolve_matrix
    from sheeprl_tpu.envs.jax_envs import make_jax_env

    env = make_jax_env("Pendulum-v1")
    lone = _sweep_cfg(
        "algo.population.sweep=random",
        "algo.population.env_params={g: {low: 2.0, high: 20.0}}",
    )
    more = _sweep_cfg(
        "algo.population.sweep=random",
        "algo.population.hparams={lr: {low: 1e-4, high: 1e-2, log: true}}",
        "algo.population.env_params={g: {low: 2.0, high: 20.0}, max_episode_steps: {low: 100, high: 400}}",
    )
    _, _, ep1, env_swept1 = resolve_matrix(lone, 8, seed=5, env=env)
    hp2, swept2, ep2, env_swept2 = resolve_matrix(more, 8, seed=5, env=env)
    assert env_swept1 == ("g",)
    assert swept2 == ("lr",) and env_swept2 == ("g", "max_episode_steps")
    np.testing.assert_array_equal(ep1["g"], ep2["g"])
    assert ((ep2["g"] >= 2.0) & (ep2["g"] <= 20.0)).all()
    assert ep2["max_episode_steps"].dtype == np.int32
    assert ((ep2["max_episode_steps"] >= 100) & (ep2["max_episode_steps"] <= 400)).all()
    # the hparam lr stream is untouched by env axes (same key as hparam-only)
    hp_only, _, _, _ = resolve_matrix(
        _sweep_cfg(
            "algo.population.sweep=random",
            "algo.population.hparams={lr: {low: 1e-4, high: 1e-2, log: true}}",
        ),
        8,
        seed=5,
        env=env,
    )
    np.testing.assert_array_equal(hp2["lr"], hp_only["lr"])


def test_matrix_rejections():
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import resolve_matrix
    from sheeprl_tpu.envs.jax_envs import make_jax_env

    env = make_jax_env("CartPole-v1")
    with pytest.raises(ValueError, match="Unknown env param"):
        resolve_matrix(
            _sweep_cfg("algo.population.env_params={mass_of_moon: [1, 2]}"), 2, seed=0, env=env
        )
    with pytest.raises(ValueError, match="no pure-JAX env"):
        resolve_matrix(
            _sweep_cfg("algo.population.env_params={length: [0.25, 0.5]}"), 2, seed=0, env=None
        )
    with pytest.raises(ValueError, match="cannot expand the range"):
        resolve_matrix(
            _sweep_cfg("algo.population.env_params={length: {low: 0.25, high: 1.0}}"),
            2,
            seed=0,
            env=env,
        )


def test_population_scenario_matrix_dry_run(tmp_path):
    """A scenario-swept population through the real CLI: 2 members, 2 CartPole
    pole lengths, one dispatch."""
    run(
        _args(
            tmp_path,
            "algo.population.size=2",
            "algo.population.hparams={}",
            "algo.population.env_params={length: [0.25, 1.0]}",
        )
    )


def test_make_jax_env_kwarg_sweep_clash():
    """An env constructor kwarg duplicating a swept env-params field raises a
    named error pointing at the sweep key (the constructor value would be
    silently shadowed by the per-member values otherwise)."""
    from sheeprl_tpu.envs.jax_envs import make_jax_env

    with pytest.raises(ValueError, match=r"algo\.population\.env_params\.max_episode_steps"):
        make_jax_env("CartPole-v1", swept_params=("max_episode_steps",), max_episode_steps=100)


def test_per_scenario_fitness_ferry_hand_computed():
    """Per-member fitness IS per-scenario fitness: a P=2 CartPole block with
    two pole lengths ferries one fitness per scenario. The hand-computed
    twin: CartPole pays exactly +1 every env-step under EVERY dynamics
    variant (SAME_STEP auto-reset included), so each scenario's per-iteration
    fitness is exactly rollout_steps and the block fitness is its mean —
    while the member trajectories themselves must diverge (each member's
    envs really stepped under its own pole length)."""
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import _base_hparams, make_population_block
    from sheeprl_tpu.envs.jax_envs import BatchedJaxEnv, make_jax_env
    from sheeprl_tpu.optim.builders import build_optimizer
    from sheeprl_tpu.parallel import Fabric

    cfg = _parity_cfg()
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(21)
    jenv = make_jax_env("CartPole-v1")
    obs_space = gym.spaces.Dict({"state": jenv.observation_space})
    agent, params, _ = build_agent(fabric, (2,), False, cfg, obs_space, None)

    lr0 = float(cfg.algo.optimizer.lr)
    tx = optax.inject_hyperparams(
        lambda learning_rate: build_optimizer(
            {**cfg.algo.optimizer, "lr": learning_rate}, max_grad_norm=cfg.algo.max_grad_norm
        )
    )(learning_rate=lr0)
    num_envs = int(cfg.env.num_envs)
    benv = BatchedJaxEnv(jenv, num_envs)
    P, iters, T = 2, 3, int(cfg.algo.rollout_steps)

    pblock = make_population_block(
        agent, tx, cfg, fabric.mesh, benv, num_envs, iters, "state",
        pop_size=P, ferry_episodes=True, guard=False, pbt=None,
    )
    stack = lambda tree: jax.tree.map(lambda x: jnp.broadcast_to(x, (P,) + x.shape), tree)
    p = jax.tree.map(jnp.asarray, jax.device_get(params))
    defaults = jenv.default_params()
    env_params = stack(defaults)._replace(
        length=jnp.asarray([0.25, 1.0], jnp.float32)  # two scenarios
    )
    reset_keys = jax.random.split(jax.random.PRNGKey(31), P)
    env_state, obs = jax.jit(jax.vmap(benv.reset))(reset_keys, env_params)
    hparams = {k: jnp.full((P,), v, jnp.float32) for k, v in _base_hparams(cfg).items()}
    out = pblock(
        stack(p), stack(tx.init(p)), env_state, obs,
        jnp.zeros((P, num_envs), jnp.float32), jnp.zeros((P, num_envs), jnp.int32),
        stack(jax.random.split(jax.random.PRNGKey(32), fabric.world_size)),
        jax.random.split(jax.random.PRNGKey(33), P),
        hparams, env_params, jnp.ones((3,), jnp.float32), jnp.asarray(False),
        jax.random.PRNGKey(34),
    )
    _, _, _, p_obs, _, _, _, _, _, fitness, metrics = out
    fit_iters = np.asarray(metrics["fit"])
    assert fit_iters.shape == (P, iters)
    # hand-computed: +1 per step -> per-iteration fitness == rollout_steps
    np.testing.assert_allclose(fit_iters, np.full((P, iters), T, np.float32), rtol=0, atol=0)
    np.testing.assert_allclose(np.asarray(fitness), np.full((P,), T, np.float32), rtol=0, atol=0)
    # the scenarios really applied: trajectories diverge between members
    assert not np.array_equal(np.asarray(p_obs)[0], np.asarray(p_obs)[1])


# --------------------------------------------------------------------------- #
# PBT truncation selection
# --------------------------------------------------------------------------- #


def _pbt_fixture(pop=4, value_per_member=None):
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import HPARAM_KEYS

    base = np.arange(pop, dtype=np.float32) if value_per_member is None else value_per_member
    params = {"w": jnp.asarray(base)[:, None] * jnp.ones((1, 3), jnp.float32)}
    opt = {"mu": jnp.asarray(base) * 10.0}
    hparams = {k: jnp.asarray(base + 1.0 + i, jnp.float32) for i, k in enumerate(HPARAM_KEYS)}
    return params, opt, hparams


def _stacked_env_params(pop):
    """(P,)-stacked Pendulum scenario matrix with per-member distinct values
    on the swept-in-tests fields (length, max_episode_steps)."""
    from sheeprl_tpu.envs.jax_envs import make_jax_env

    defaults = make_jax_env("Pendulum-v1").default_params()
    stacked = jax.tree.map(lambda x: jnp.broadcast_to(x, (pop,) + x.shape).copy(), defaults)
    return stacked._replace(
        length=jnp.asarray(np.linspace(0.5, 2.0, pop), jnp.float32),
        max_episode_steps=jnp.asarray(100 + 50 * np.arange(pop), jnp.int32),
    )


def test_pbt_step_deterministic_and_truncates():
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import PBTConfig, make_pbt_step

    pbt = PBTConfig(num_copy=1, perturb=("lr",), factors=(0.8, 1.25))
    step = jax.jit(make_pbt_step(4, pbt))
    params, opt, hparams = _pbt_fixture()
    env_params = _stacked_env_params(4)
    fitness = jnp.asarray([3.0, 1.0, 2.0, 0.0])  # member 0 best, member 3 worst
    key = jax.random.PRNGKey(12)

    out1 = jax.device_get(step((params, opt, hparams, env_params, fitness, key)))
    out2 = jax.device_get(step((params, opt, hparams, env_params, fitness, key)))
    for a, b in zip(jax.tree.leaves(out1), jax.tree.leaves(out2)):
        np.testing.assert_array_equal(a, b)

    new_params, new_opt, new_hparams, new_env_params = out1
    # env params pass through UNTOUCHED with the default empty env_perturb
    for a, b in zip(jax.tree.leaves(env_params), jax.tree.leaves(new_env_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the worst member copied the best member's params + optimizer state
    np.testing.assert_array_equal(new_params["w"][3], np.asarray(params["w"])[0])
    np.testing.assert_array_equal(new_opt["mu"][3], np.asarray(opt["mu"])[0])
    # survivors untouched, bitwise
    for m in (0, 1, 2):
        np.testing.assert_array_equal(new_params["w"][m], np.asarray(params["w"])[m])
        np.testing.assert_array_equal(new_opt["mu"][m], np.asarray(opt["mu"])[m])
        for k in hparams:
            np.testing.assert_array_equal(new_hparams[k][m], np.asarray(hparams[k])[m])
    # the replaced member inherited the source lr times a perturb factor...
    src_lr = float(np.asarray(hparams["lr"])[0])
    assert np.isclose(float(new_hparams["lr"][3]), [0.8 * src_lr, 1.25 * src_lr], rtol=1e-6).any()
    # ...and the un-perturbed hparams verbatim
    for k in hparams:
        if k == "lr":
            continue
        np.testing.assert_array_equal(new_hparams[k][3], np.asarray(hparams[k])[0])


def test_pbt_all_identical_stays_identical():
    """Equal fitness + identical members: stable ranking maps the population
    onto itself — params/optimizer stay bitwise identical, and with an empty
    perturb set the hparams do too."""
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import PBTConfig, make_pbt_step

    pbt = PBTConfig(num_copy=1, perturb=(), factors=(0.8, 1.25))
    step = jax.jit(make_pbt_step(4, pbt))
    params, opt, hparams = _pbt_fixture(value_per_member=np.zeros(4, np.float32))
    env_params = _stacked_env_params(4)
    fitness = jnp.zeros((4,))
    out = jax.device_get(step((params, opt, hparams, env_params, fitness, jax.random.PRNGKey(0))))
    new_params, new_opt, new_hparams, _ = out
    np.testing.assert_array_equal(new_params["w"], np.asarray(params["w"]))
    np.testing.assert_array_equal(new_opt["mu"], np.asarray(opt["mu"]))
    for k in hparams:
        np.testing.assert_array_equal(new_hparams[k], np.asarray(hparams[k]))


def test_pbt_perturb_clamps_discount_hparams():
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import PBTConfig, make_pbt_step

    pbt = PBTConfig(num_copy=1, perturb=("gamma",), factors=(1.25,))
    step = jax.jit(make_pbt_step(2, pbt))
    params = {"w": jnp.zeros((2, 1))}
    opt = {"mu": jnp.zeros((2,))}
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import HPARAM_KEYS

    hparams = {k: jnp.full((2,), 0.5, jnp.float32) for k in HPARAM_KEYS}
    hparams["gamma"] = jnp.asarray([0.999, 0.999], jnp.float32)
    fitness = jnp.asarray([1.0, 0.0])
    _, _, new_hparams, _ = jax.device_get(
        step((params, opt, hparams, _stacked_env_params(2), fitness, jax.random.PRNGKey(1)))
    )
    assert float(new_hparams["gamma"][1]) <= 0.9999  # 0.999 * 1.25 clamped


def test_pbt_env_perturb_moves_swept_scenarios():
    """``perturb_env_params=true``: swept env-params fields are inherited
    from the source member and multiplied by a perturb factor; integer
    fields round to their dtype and clamp >= 1; non-swept fields never
    move. With the default empty ``env_perturb`` the scenario stays with
    the SLOT (curriculum semantics) — covered by the tests above."""
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import PBTConfig, make_pbt_step

    pbt = PBTConfig(
        num_copy=1, perturb=(), factors=(0.8, 1.25), env_perturb=("length", "max_episode_steps")
    )
    step = jax.jit(make_pbt_step(2, pbt))
    params, opt = {"w": jnp.zeros((2, 1))}, {"mu": jnp.zeros((2,))}
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import HPARAM_KEYS

    hparams = {k: jnp.full((2,), 0.5, jnp.float32) for k in HPARAM_KEYS}
    env_params = _stacked_env_params(2)
    fitness = jnp.asarray([1.0, 0.0])  # member 1 copies member 0
    _, _, _, new_ep = jax.device_get(step((params, opt, hparams, env_params, fitness, jax.random.PRNGKey(3))))
    src_len = float(np.asarray(env_params.length)[0])
    got = float(new_ep.length[1])
    assert np.isclose(got, [0.8 * src_len, 1.25 * src_len], rtol=1e-6).any()
    # integer field: rounded to int32, clamped >= 1, moved off the slot value
    assert new_ep.max_episode_steps.dtype == np.int32
    src_steps = int(np.asarray(env_params.max_episode_steps)[0])
    assert int(new_ep.max_episode_steps[1]) in (int(round(0.8 * src_steps)), int(round(1.25 * src_steps)))
    # survivor untouched, non-perturbed fields bitwise across the board
    np.testing.assert_array_equal(np.asarray(new_ep.length)[0], np.asarray(env_params.length)[0])
    np.testing.assert_array_equal(np.asarray(new_ep.g), np.asarray(env_params.g))


def test_resolve_pbt_validation():
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import resolve_pbt

    on = ("algo.population.pbt.enabled=True",)
    pbt, every = resolve_pbt(_sweep_cfg(*on), 8, swept=("lr",))
    assert pbt is not None and pbt.num_copy == 2 and pbt.perturb == ("lr",) and every == 1
    assert resolve_pbt(_sweep_cfg(), 8, swept=()) == (None, 0)
    with pytest.raises(ValueError, match="size >= 2"):
        resolve_pbt(_sweep_cfg(*on), 1, swept=())
    with pytest.raises(ValueError, match="truncation_frac"):
        resolve_pbt(_sweep_cfg(*on, "algo.population.pbt.truncation_frac=0.7"), 8, swept=())
    with pytest.raises(ValueError, match="Unknown pbt.perturb"):
        resolve_pbt(_sweep_cfg(*on, "algo.population.pbt.perturb=[vf_coef]"), 8, swept=())
    with pytest.raises(ValueError, match="positive multipliers"):
        resolve_pbt(_sweep_cfg(*on, "algo.population.pbt.perturb_factors=[-1.0]"), 8, swept=("lr",))


def test_pbt_e2e_run(tmp_path):
    """PBT-enabled population run through the real CLI: multiple blocks, the
    gate fires every block, run completes."""
    run(
        _args(
            tmp_path,
            "algo.population.size=4",
            "algo.population.hparams={lr: {low: 1e-4, high: 1e-2, log: true}}",
            "algo.population.sweep=random",
            "algo.population.pbt.enabled=True",
            "algo.total_steps=64",
            "checkpoint.every=16",
            dry=False,
        )
    )


# --------------------------------------------------------------------------- #
# Block-length compile regression (the get_block_fn / ferry-bound small fix)
# --------------------------------------------------------------------------- #


def test_population_block_compiles_at_most_twice_across_lengths(tmp_path):
    """total_iters=3 with iters_per_block=2 dispatches a 2-iteration body and
    a 1-iteration remainder: the population block must compile exactly twice
    (once per length) and never again — the compile cache keys by length with
    P>1 and traced hparams exactly as it does for scalar hparams."""
    from sheeprl_tpu.analysis.tracecheck import tracecheck

    tracecheck.reset()
    run(
        _args(
            tmp_path,
            "algo.population.size=2",
            "algo.population.hparams={}",
            "algo.rollout_steps=4",
            "algo.total_steps=24",  # 3 iterations of 4 steps x 2 envs
            "checkpoint.every=16",  # -> iters_per_block=2: blocks of 2 then 1
            dry=False,
        )
    )
    rep = tracecheck.report()["ppo_anakin_pop.block"]
    assert rep["calls"] == 2, rep
    assert rep["compiles"] == 2, rep
    assert rep["post_warmup_compiles"] == 0, rep


def test_ferry_bound_divides_by_population_size():
    """The metric-ferry budget covers P x the episode arrays of a single run:
    a wide population must shrink iters_per_block accordingly."""
    from sheeprl_tpu.algos.ppo.ppo_anakin import FERRY_ELEMS_BOUND, resolve_iters_per_block

    cfg = _sweep_cfg("metric.log_every=100000000", "checkpoint.every=0", "metric.log_level=1")
    T = int(cfg.algo.rollout_steps)
    num_envs = int(cfg.env.num_envs)
    total_iters = 10**9
    single = resolve_iters_per_block(cfg, total_iters, T * num_envs, ferry_episodes=True)
    pop = resolve_iters_per_block(
        cfg, total_iters, T * num_envs, ferry_episodes=True, population_size=64
    )
    assert single == max(1, FERRY_ELEMS_BOUND // (T * num_envs))
    assert pop == max(1, FERRY_ELEMS_BOUND // (T * num_envs * 64))
    assert pop <= single // 64 + 1


# --------------------------------------------------------------------------- #
# Checkpoint → SIGKILL → resume_from=latest
# --------------------------------------------------------------------------- #

POP_KILL_ARGS = [
    "exp=ppo_anakin_population",
    "env=gym",
    "env.id=CartPole-v1",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.rollout_steps=4",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
    "algo.total_steps=48",
    "algo.population.size=3",
    "algo.population.sweep=random",
    "algo.population.hparams={lr: {low: 0.0001, high: 0.01, log: true}}",
    "checkpoint.every=16",
    "checkpoint.save_last=True",
    "seed=11",
    "log_root=logs",
]


def _launch(tmp_path, extra_args=(), extra_env=None):
    env = {
        **os.environ,
        "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
    }
    env.pop("SHEEPRL_FAULT_KILL", None)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu", *POP_KILL_ARGS, *extra_args],
        cwd=tmp_path,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )


@pytest.mark.fault
def test_population_checkpoint_kill_resume_from_latest(tmp_path):
    """Checkpoint → SIGKILL mid-save → ``resume_from=latest`` restores the
    WHOLE population: member-stacked params, the per-member hparams (resumed
    under a DIFFERENT seed — a re-resolved random sweep would draw different
    values, so equality proves restore), every member RNG stream and the
    population key, and the counters continue monotonically."""
    from sheeprl_tpu.fault.manager import find_latest_run_checkpoint, latest_complete
    from sheeprl_tpu.utils.checkpoint import load_state

    proc = _launch(tmp_path, extra_env={"SHEEPRL_FAULT_KILL": "checkpoint.pre_commit:2"})
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]

    ckpt_dirs = glob.glob(
        str(tmp_path / "logs/ppo_anakin_population/CartPole-v1/*/version_*/checkpoint")
    )
    assert len(ckpt_dirs) == 1
    first_complete = latest_complete(ckpt_dirs[0])
    assert first_complete is not None and first_complete.name.startswith("ckpt_16")
    pre = load_state(first_complete)
    assert int(pre["population_size"]) == 3
    pre_hparams = {k: np.asarray(v) for k, v in pre["hparams"].items()}
    pre_rngs = np.asarray(pre["rng"])
    assert pre_rngs.shape[0] == 3

    # resume under a different seed: restored state must win over re-derivation
    proc2 = _launch(tmp_path, extra_args=["checkpoint.resume_from=latest", "seed=123"])
    assert proc2.returncode == 0, (proc2.stdout[-2000:], proc2.stderr[-2000:])
    assert "checkpoint.resume_from=latest ->" in proc2.stdout

    final = find_latest_run_checkpoint(tmp_path / "logs/ppo_anakin_population/CartPole-v1")
    state = load_state(final)
    assert int(os.path.basename(str(final)).split("_")[1]) >= 48
    assert state["iter_num"] >= 6
    assert int(state["population_size"]) == 3
    # every member's params restored and trained on: leading axis 3, finite
    for leaf in jax.tree.leaves(state["agent"]):
        arr = np.asarray(leaf)
        assert arr.shape[0] == 3
        assert np.isfinite(arr).all()
    # hparams survived the kill (random sweep under seed=123 would differ)
    for k, v in state["hparams"].items():
        np.testing.assert_array_equal(np.asarray(v), pre_hparams[k])
    # member RNG streams continued from the restored values, not reseeded:
    # every member key advanced past the first checkpoint's snapshot
    post_rngs = np.asarray(state["rng"])
    assert post_rngs.shape == pre_rngs.shape
    assert not np.array_equal(post_rngs, pre_rngs)
    # the population (PBT/perturbation) stream rode along too
    assert state.get("pop_key") is not None
    assert state.get("fitness") is not None and np.asarray(state["fitness"]).shape == (3,)


@pytest.mark.fault
def test_population_scenario_matrix_kill_resume_restores_env_params(tmp_path):
    """Scenario-matrix run: checkpoint → SIGKILL → ``resume_from=latest``
    restores the env-params matrix from the checkpoint. Resumed under a
    DIFFERENT seed: a re-resolved random scenario sweep would draw different
    pole lengths, so bitwise equality of the resumed matrix with the
    pre-kill snapshot proves resume does NOT re-resolve."""
    from sheeprl_tpu.fault.manager import find_latest_run_checkpoint, latest_complete
    from sheeprl_tpu.utils.checkpoint import load_state

    scenario = [
        "algo.population.env_params={length: {low: 0.25, high: 1.0}, gravity: {low: 4.9, high: 19.6}}",
    ]
    proc = _launch(
        tmp_path, extra_args=scenario, extra_env={"SHEEPRL_FAULT_KILL": "checkpoint.pre_commit:2"}
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]

    ckpt_dirs = glob.glob(
        str(tmp_path / "logs/ppo_anakin_population/CartPole-v1/*/version_*/checkpoint")
    )
    assert len(ckpt_dirs) == 1
    pre = load_state(latest_complete(ckpt_dirs[0]))
    assert pre.get("env_params") is not None
    pre_ep = {k: np.asarray(v) for k, v in pre["env_params"].items()}
    assert pre_ep["length"].shape == (3,)
    assert len(np.unique(pre_ep["length"])) == 3  # scenarios actually vary

    proc2 = _launch(tmp_path, extra_args=[*scenario, "checkpoint.resume_from=latest", "seed=321"])
    assert proc2.returncode == 0, (proc2.stdout[-2000:], proc2.stderr[-2000:])

    final = find_latest_run_checkpoint(tmp_path / "logs/ppo_anakin_population/CartPole-v1")
    state = load_state(final)
    assert state["iter_num"] >= 6
    # the scenario matrix survived the kill bitwise — including the unswept
    # broadcast fields (re-resolution under seed=321 would have redrawn the
    # swept ones)
    for k, v in state["env_params"].items():
        np.testing.assert_array_equal(np.asarray(v), pre_ep[k])


def test_population_resume_conflicting_size_uses_checkpoint_population(tmp_path):
    """A population checkpoint only resumes as the SAME population. Through
    the CLI, ``resume_from`` merges the checkpoint run's saved config over
    the command line, so a conflicting ``algo.population.size`` is OVERRIDDEN
    and the run continues with the checkpointed members."""
    from sheeprl_tpu.fault.manager import find_latest_run_checkpoint
    from sheeprl_tpu.utils.checkpoint import load_state

    run(
        _args(
            tmp_path,
            "algo.population.size=2",
            "algo.population.hparams={}",
            "algo.rollout_steps=4",
            "algo.total_steps=16",
            "checkpoint.every=8",
            "checkpoint.save_last=True",
            dry=False,
        )
    )
    run(
        _args(
            tmp_path,
            "algo.population.size=4",  # ignored: the checkpoint's size=2 wins
            "algo.population.hparams={}",
            "algo.rollout_steps=4",
            "algo.total_steps=32",
            "checkpoint.resume_from=latest",
            "checkpoint.save_last=True",
            dry=False,
        )
    )
    final = find_latest_run_checkpoint(tmp_path / "logs/ppo_anakin_population/CartPole-v1")
    state = load_state(final)
    assert int(state["population_size"]) == 2
    for leaf in jax.tree.leaves(state["agent"]):
        assert np.asarray(leaf).shape[0] == 2


def test_population_resume_size_mismatch_guard(tmp_path):
    """The in-driver guard (defense in depth for resume paths that bypass the
    CLI config merge, e.g. a direct ``population_main`` embedding or a
    hand-edited saved config) rejects a size-mismatched resume outright."""
    from sheeprl_tpu.algos.ppo.ppo_anakin_population import population_main
    from sheeprl_tpu.fault.manager import find_latest_run_checkpoint
    from sheeprl_tpu.parallel import Fabric

    run(
        _args(
            tmp_path,
            "algo.population.size=2",
            "algo.population.hparams={}",
            "algo.rollout_steps=4",
            "algo.total_steps=16",
            "checkpoint.every=0",
            "checkpoint.save_last=True",
            dry=False,
        )
    )
    ckpt = find_latest_run_checkpoint(tmp_path / "logs/ppo_anakin_population/CartPole-v1")
    cfg = compose(
        _args(
            tmp_path,
            "algo.population.size=4",
            "algo.population.hparams={}",
            f"checkpoint.resume_from={ckpt}",
        )
    )
    with pytest.raises(ValueError, match="population of 2"):
        population_main(Fabric(devices=1, accelerator="cpu"), cfg)


# --------------------------------------------------------------------------- #
# Eval from a population checkpoint (best member)
# --------------------------------------------------------------------------- #


def test_population_serve_builder_slices_best_member_and_hot_swaps(tmp_path):
    """The serve policy builder must hand SINGLE-member params to the AOT
    engine — at construction AND on every hot swap: a watched population run
    keeps publishing member-STACKED ``state["agent"]`` trees, so
    ``params_from_state`` has to slice the served member before rebuilding
    (stacked ``(P, ...)`` leaves would break every compiled dispatch)."""
    from sheeprl_tpu.envs.factory import make_env
    from sheeprl_tpu.fault.manager import find_latest_run_checkpoint
    from sheeprl_tpu.parallel import Fabric
    from sheeprl_tpu.utils.checkpoint import load_state
    from sheeprl_tpu.utils.registry import get_entrypoint, resolve_policy_builder

    run(
        _args(
            tmp_path,
            "algo.population.size=3",
            "algo.population.hparams={}",
            "algo.rollout_steps=4",
            "algo.total_steps=16",
            "checkpoint.every=0",
            "checkpoint.save_last=True",
            dry=False,
        )
    )
    ckpt = find_latest_run_checkpoint(tmp_path / "logs/ppo_anakin_population/CartPole-v1")
    state = load_state(ckpt)
    best = int(state["best_member"])
    cfg = compose(_args(tmp_path, "algo.population.size=3", "algo.population.hparams={}"))
    cfg["checkpoint_path"] = str(ckpt)

    fabric = Fabric(devices=1, accelerator="cpu")
    env = make_env(cfg, 0, 0, None, "serve", vector_env_idx=0)()
    builder = get_entrypoint(resolve_policy_builder("ppo_anakin_population"))
    policy = builder(fabric, cfg, env.observation_space, env.action_space, state["agent"], full_state=state)
    env.close()

    # construction sliced the checkpointed best member
    for leaf, stacked in zip(jax.tree.leaves(policy.params), jax.tree.leaves(state["agent"])):
        np.testing.assert_array_equal(np.asarray(leaf), np.asarray(stacked)[best])
    # the hot-swap path receives a STACKED tree (what a CheckpointWatcher
    # publishes) and must rebuild single-member params with matching avals
    swapped = policy.params_from_state(state["agent"])
    for new, old in zip(jax.tree.leaves(swapped), jax.tree.leaves(policy.params)):
        assert np.asarray(new).shape == np.asarray(old).shape
        np.testing.assert_array_equal(np.asarray(new), np.asarray(old))


def test_population_eval_from_checkpoint(tmp_path, capsys):
    run(
        _args(
            tmp_path,
            "algo.population.size=2",
            "algo.population.hparams={}",
            "algo.rollout_steps=4",
            "algo.total_steps=16",
            "checkpoint.every=0",
            "checkpoint.save_last=True",
            dry=False,
        )
    )
    ckpts = glob.glob(
        str(tmp_path / "logs/ppo_anakin_population/CartPole-v1/*/version_*/checkpoint/*.ckpt")
    )
    assert ckpts
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpts[-1]}", "fabric.accelerator=cpu", "env.capture_video=False"])
    out = capsys.readouterr().out
    assert "Test - Reward:" in out


# --------------------------------------------------------------------------- #
# Slow lane: the population actually learns
# --------------------------------------------------------------------------- #


@pytest.mark.slow
def test_population_best_member_learns_cartpole(tmp_path):
    """Best-of-population CartPole: the headline Rewards/rew_avg stream (the
    best member's completed episodes) must clear the single-run threshold
    (PR 1: trailing-20 mean >= 475 for the single Anakin run)."""
    sys.path.insert(0, REPO_ROOT)
    from benchmarks.learning_bench import capture_returns

    returns = capture_returns(
        [
            "exp=ppo_anakin_population",
            "env=gym",
            "env.id=CartPole-v1",
            "env.num_envs=4",
            "env.sync_env=True",
            "env.capture_video=False",
            "buffer.memmap=False",
            "fabric.devices=1",
            "metric.log_level=1",
            "metric.log_every=2048",
            "algo.run_test=False",
            "algo.mlp_keys.encoder=[state]",
            "algo.total_steps=65536",
            "algo.population.size=4",
            "algo.population.hparams={lr: [0.0005, 0.001, 0.002, 0.003]}",
            "checkpoint.every=0",
            "checkpoint.save_last=False",
            f"log_root={tmp_path}/logs",
            "seed=5",
        ]
    )
    assert len(returns) >= 20, f"too few finished episodes: {len(returns)}"
    trailing = returns[-20:]
    assert sum(trailing) / len(trailing) >= 475, (
        f"best-of-population trailing-20 mean {sum(trailing) / len(trailing):.1f} < 475 "
        f"(n={len(returns)})"
    )


if __name__ == "__main__":
    _run_parity_check()
