"""Dreamer-V1 reconstruction loss with the continue head enabled.

Guards the `use_continues=True` path: the continue term must be a reduced,
negated NLL so the world-model loss stays scalar under `jax.value_and_grad`
(reference semantics: ``sheeprl/algos/dreamer_v1/loss.py:41-98``).
"""

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v1.loss import reconstruction_loss
from sheeprl_tpu.distributions import BernoulliSafeMode, Independent, Normal


def _make_inputs(key, T=4, B=3):
    ks = jax.random.split(key, 6)
    obs = {"state": jax.random.normal(ks[0], (T, B, 5))}
    rewards = jax.random.normal(ks[1], (T, B, 1))
    continue_targets = (jax.random.uniform(ks[2], (T, B, 1)) > 0.3).astype(jnp.float32) * 0.99
    post_mean = jax.random.normal(ks[3], (T, B, 8))
    prior_mean = jax.random.normal(ks[4], (T, B, 8))
    continue_logits = jax.random.normal(ks[5], (T, B, 1))
    return obs, rewards, continue_targets, post_mean, prior_mean, continue_logits


def test_continue_loss_is_scalar_and_negated_nll():
    obs, rewards, continue_targets, post_mean, prior_mean, continue_logits = _make_inputs(
        jax.random.PRNGKey(0)
    )
    qo = {"state": Independent(Normal(obs["state"] + 0.1, 1.0), 1)}
    qr = Independent(Normal(rewards * 0.5, 1.0), 1)
    qc = Independent(BernoulliSafeMode(logits=continue_logits), 1)
    posteriors = Independent(Normal(post_mean, jnp.ones_like(post_mean)), 1)
    priors = Independent(Normal(prior_mean, jnp.ones_like(prior_mean)), 1)

    rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
        qo, obs, qr, rewards, posteriors, priors, 3.0, 1.0, qc, continue_targets, 10.0
    )
    # Every returned term must be scalar (the reference reduces with .mean()).
    for term in (rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss):
        assert term.shape == ()
    # NLL of a Bernoulli is positive, and the scale factor is 10.
    expected = -10.0 * qc.log_prob(continue_targets).mean()
    assert jnp.allclose(continue_loss, expected)
    assert continue_loss > 0


def test_wm_style_loss_differentiable_with_continues():
    """value_and_grad over a reconstruction loss that includes the continue
    term — the exact shape of the Dreamer-V1 world-model update when
    ``algo.world_model.use_continues=True``."""
    obs, rewards, continue_targets, post_mean, prior_mean, _ = _make_inputs(jax.random.PRNGKey(1))
    w = jnp.ones((1,))

    def loss_fn(w):
        qo = {"state": Independent(Normal(obs["state"] * w, 1.0), 1)}
        qr = Independent(Normal(rewards * w, 1.0), 1)
        qc = Independent(BernoulliSafeMode(logits=jnp.broadcast_to(w, rewards.shape)), 1)
        posteriors = Independent(Normal(post_mean * w, jnp.ones_like(post_mean)), 1)
        priors = Independent(Normal(prior_mean, jnp.ones_like(prior_mean)), 1)
        rec_loss, *_ = reconstruction_loss(
            qo, obs, qr, rewards, posteriors, priors, 3.0, 1.0, qc, continue_targets, 10.0
        )
        return rec_loss

    loss, grads = jax.value_and_grad(loss_fn)(w)
    assert loss.shape == ()
    assert jnp.isfinite(loss)
    assert jnp.all(jnp.isfinite(grads))
