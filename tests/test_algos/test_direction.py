"""Direction-of-progress tests: every family's jitted train step, iterated on
ONE fixed synthetic batch, must DRIVE ITS PRIMARY LOSS DOWN.

The dry-run tests assert finite losses; a sign-flipped gradient is finite.
Repeating the real train step on frozen data is pure optimization, so the
supervised-like term of each family (value/critic loss for model-free,
world-model reconstruction loss for the Dreamer/P2E families) must decrease
— the cheapest test that catches inverted losses, wrong ``stop_gradient``
placement, or optimizer-update sign errors (VERDICT r4 item 7; reference
smoke-test shape: ``/root/reference/tests/test_algos/test_algos.py:16-53``,
which this exceeds — the reference never asserts direction).

All tests run single-device on the CPU mesh at tiny widths; the Dreamer/P2E
six use mlp-only observation keys so no conv graphs compile.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sheeprl_tpu.config import compose
from sheeprl_tpu.optim.builders import build_optimizer
from sheeprl_tpu.parallel.fabric import Fabric


def _fab() -> Fabric:
    return Fabric(devices=1, accelerator="cpu", mesh_axes=("dp",))


def _decreased(series, name, ratio=0.9, warmup=0):
    """Mean of the last 5 readings must be below ``ratio`` x the mean of the
    5 readings after ``warmup`` iterations.

    ``ratio`` is calibrated PER FAMILY against the measured short-run
    trajectory on this exact fixed batch (deterministic to ~4 decimals across
    runs): the model-free families drop >10% in 20 iterations, while the
    Dreamer/P2E world models at these tiny widths descend at only ~1-4‰ per
    Adam step of ``lr≈1e-4`` — a correct gradient, just a short run. Each
    threshold sits at roughly HALF the observed decrease, so a plateau or a
    sign-flipped gradient (which climbs) still fails loudly while float
    jitter cannot. ``warmup`` skips the optimizer warm-up transient (p2e_dv3
    rises for ~9 iterations while Adam's moments fill) so head is measured on
    the optimization trend, not the transient."""
    head = float(np.mean(series[warmup : warmup + 5]))
    tail = float(np.mean(series[-5:]))
    assert np.isfinite(head) and np.isfinite(tail), f"{name}: non-finite losses {series}"
    # Losses can be negative (NLL-based); "decreased" must hold on the raw
    # values, not magnitudes.
    assert tail < head * ratio if head > 0 else tail < head, (
        f"{name} did not decrease on fixed data: head5={head:.5f} last5={tail:.5f} "
        f"(ratio={ratio}, warmup={warmup}) series={series}"
    )


# ---------------------------------------------------------------------------
# Model-free families
# ---------------------------------------------------------------------------


def _box_obs_space(dim=6):
    return gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (dim,), np.float32)})


def test_ppo_value_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.ppo.agent import PPOAgent
    from sheeprl_tpu.algos.ppo.ppo import make_train_step

    cfg = compose(["exp=ppo", "env.num_envs=4", "algo.rollout_steps=16", "algo.per_rank_batch_size=8"])
    agent = PPOAgent(
        actions_dim=(2,),
        is_continuous=False,
        cnn_keys=(),
        mlp_keys=("state",),
        encoder_cfg=dict(cfg.algo.encoder),
        actor_cfg=dict(cfg.algo.actor),
        critic_cfg=dict(cfg.algo.critic),
    )
    obs = {"state": jnp.zeros((4, 4), dtype=jnp.float32)}
    params = agent.init(jax.random.PRNGKey(0), obs)
    fabric = _fab()
    tx = optax.inject_hyperparams(
        lambda learning_rate: build_optimizer(
            {**cfg.algo.optimizer, "lr": learning_rate}, max_grad_norm=cfg.algo.max_grad_norm
        )
    )(learning_rate=float(cfg.algo.optimizer.lr))
    opt_state = tx.init(params)
    B = 64
    train_fn = make_train_step(agent, tx, cfg, fabric.mesh, B, donate=False)

    rng = np.random.default_rng(0)
    data = {
        "state": jnp.asarray(rng.normal(size=(B, 4)), dtype=jnp.float32),
        "actions": jnp.asarray(rng.integers(0, 2, size=(B, 2)), dtype=jnp.float32),
        "logprobs": jnp.full((B, 1), -0.69, dtype=jnp.float32),
        "values": jnp.zeros((B, 1), dtype=jnp.float32),
        "returns": jnp.asarray(rng.normal(size=(B, 1)), dtype=jnp.float32),
        "advantages": jnp.asarray(rng.normal(size=(B, 1)), dtype=jnp.float32),
        "rewards": jnp.zeros((B, 1), dtype=jnp.float32),
        "dones": jnp.zeros((B, 1), dtype=jnp.uint8),
    }
    data = fabric.shard_data(data)
    v_losses = []
    for i in range(20):
        params, opt_state, pg, v, ent = train_fn(
            params, opt_state, data, jax.random.fold_in(jax.random.PRNGKey(1), i),
            jnp.float32(0.2), jnp.float32(0.0),
        )
        v_losses.append(float(v))
    _decreased(v_losses, "ppo value_loss")


def test_a2c_value_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.a2c.agent import build_agent
    from sheeprl_tpu.algos.a2c.a2c import make_train_step

    cfg = compose(["exp=a2c", "env.num_envs=2", "algo.rollout_steps=8", "algo.per_rank_batch_size=16"])
    fabric = _fab()
    obs_space = _box_obs_space(4)
    agent, params, _player = build_agent(fabric, (2,), False, cfg, obs_space)
    tx = build_optimizer(cfg.algo.optimizer, max_grad_norm=cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    B = 16
    train_fn = make_train_step(agent, tx, cfg, fabric.mesh, B)

    rng = np.random.default_rng(0)
    data = {
        "state": jnp.asarray(rng.normal(size=(B, 4)), dtype=jnp.float32),
        "actions": jnp.asarray(rng.integers(0, 2, size=(B, 1)), dtype=jnp.float32),
        "returns": jnp.asarray(rng.normal(size=(B, 1)), dtype=jnp.float32),
        "advantages": jnp.asarray(rng.normal(size=(B, 1)), dtype=jnp.float32),
        "rewards": jnp.zeros((B, 1), dtype=jnp.float32),
        "values": jnp.zeros((B, 1), dtype=jnp.float32),
        "dones": jnp.zeros((B, 1), dtype=jnp.uint8),
    }
    data = fabric.shard_data(data)
    v_losses = []
    for i in range(20):
        params, opt_state, pg, v = train_fn(params, opt_state, data, jax.random.fold_in(jax.random.PRNGKey(1), i))
        v_losses.append(float(v))
    _decreased(v_losses, "a2c value_loss")


def test_sac_critic_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.sac import make_train_step

    cfg = compose(["exp=sac", "env.num_envs=1"])
    fabric = _fab()
    obs_space = _box_obs_space(3)
    action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
    agent, params, _player = build_agent(fabric, cfg, obs_space, action_space)
    actor_tx = build_optimizer(cfg.algo.actor.optimizer)
    critic_tx = build_optimizer(cfg.algo.critic.optimizer)
    alpha_tx = build_optimizer(cfg.algo.alpha.optimizer)
    aopt, copt, lopt = actor_tx.init(params["actor"]), critic_tx.init(params["critic"]), alpha_tx.init(params["log_alpha"])
    train_fn = make_train_step(agent, actor_tx, critic_tx, alpha_tx, cfg, fabric.mesh, donate=False)

    rng = np.random.default_rng(0)
    G, B = 1, 64
    data = {
        "observations": jnp.asarray(rng.normal(size=(G, B, 3)), dtype=jnp.float32),
        "next_observations": jnp.asarray(rng.normal(size=(G, B, 3)), dtype=jnp.float32),
        "actions": jnp.asarray(rng.uniform(-1, 1, size=(G, B, 1)), dtype=jnp.float32),
        "rewards": jnp.asarray(rng.normal(size=(G, B, 1)), dtype=jnp.float32),
        "terminated": jnp.zeros((G, B, 1), dtype=jnp.float32),
    }
    qf_losses = []
    for i in range(25):
        params, aopt, copt, lopt, qf, al, ll = train_fn(
            params, aopt, copt, lopt, data, jax.random.fold_in(jax.random.PRNGKey(1), i), jnp.float32(0.0)
        )
        qf_losses.append(float(qf))
    _decreased(qf_losses, "sac critic_loss")


def test_droq_critic_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.droq.agent import build_agent
    from sheeprl_tpu.algos.droq.droq import make_train_step

    cfg = compose(["exp=droq", "env.num_envs=1"])
    fabric = _fab()
    obs_space = _box_obs_space(3)
    action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
    agent, params, _player = build_agent(fabric, cfg, obs_space, action_space)
    actor_tx = build_optimizer(cfg.algo.actor.optimizer)
    critic_tx = build_optimizer(cfg.algo.critic.optimizer)
    alpha_tx = build_optimizer(cfg.algo.alpha.optimizer)
    aopt, copt, lopt = actor_tx.init(params["actor"]), critic_tx.init(params["critic"]), alpha_tx.init(params["log_alpha"])
    train_fn = make_train_step(agent, actor_tx, critic_tx, alpha_tx, cfg, fabric.mesh)

    rng = np.random.default_rng(0)
    G, B = 1, 64
    critic_data = {
        "observations": jnp.asarray(rng.normal(size=(G, B, 3)), dtype=jnp.float32),
        "next_observations": jnp.asarray(rng.normal(size=(G, B, 3)), dtype=jnp.float32),
        "actions": jnp.asarray(rng.uniform(-1, 1, size=(G, B, 1)), dtype=jnp.float32),
        "rewards": jnp.asarray(rng.normal(size=(G, B, 1)), dtype=jnp.float32),
        "terminated": jnp.zeros((G, B, 1), dtype=jnp.float32),
    }
    actor_data = {k: v[0] for k, v in critic_data.items()}
    qf_losses = []
    for i in range(25):
        params, aopt, copt, lopt, qf, al, ll = train_fn(
            params, aopt, copt, lopt, critic_data, actor_data, jax.random.fold_in(jax.random.PRNGKey(1), i)
        )
        qf_losses.append(float(qf))
    _decreased(qf_losses, "droq critic_loss")


def test_sac_ae_reconstruction_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.sac_ae.agent import build_agent
    from sheeprl_tpu.algos.sac_ae.sac_ae import make_train_step

    cfg = compose(
        [
            "exp=sac_ae",
            "env.num_envs=1",
            "env.screen_size=64",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.encoder.features_dim=16",
            "algo.dense_units=16",
            "algo.cnn_channels_multiplier=2",
            "algo.hidden_size=16",
        ]
    )
    fabric = _fab()
    obs_space = gym.spaces.Dict(
        {
            "state": gym.spaces.Box(-np.inf, np.inf, (3,), np.float32),
            "rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8),
        }
    )
    action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
    agent, params, _player = build_agent(fabric, cfg, obs_space, action_space)
    txs = {
        "qf": build_optimizer(cfg.algo.critic.optimizer),
        "actor": build_optimizer(cfg.algo.actor.optimizer),
        "alpha": build_optimizer(cfg.algo.alpha.optimizer),
        "encoder": build_optimizer(cfg.algo.encoder.optimizer),
        "decoder": build_optimizer(cfg.algo.decoder.optimizer),
    }
    opts = {
        "qf": txs["qf"].init({"encoder": params["encoder"], "qfs": params["qfs"]}),
        "actor": txs["actor"].init({"actor": params["actor"], "actor_enc_head": params["actor_enc_head"]}),
        "alpha": txs["alpha"].init(params["log_alpha"]),
        "encoder": txs["encoder"].init({"e": params["encoder"]}),
        "decoder": txs["decoder"].init({"d": params["decoder"]}),
    }
    train_fn = make_train_step(agent, txs, cfg, fabric.mesh)

    rng = np.random.default_rng(0)
    G, B = 1, 8
    data = {
        "state": jnp.asarray(rng.normal(size=(G, B, 3)), dtype=jnp.float32),
        "rgb": jnp.asarray(rng.integers(0, 255, size=(G, B, 64, 64, 3)), dtype=jnp.float32),
        "actions": jnp.asarray(rng.uniform(-1, 1, size=(G, B, 1)), dtype=jnp.float32),
        "rewards": jnp.asarray(rng.normal(size=(G, B, 1)), dtype=jnp.float32),
        "terminated": jnp.zeros((G, B, 1), dtype=jnp.float32),
    }
    data = {**data, "next_state": data["state"], "next_rgb": data["rgb"]}
    rec_losses = []
    for i in range(20):
        params, opts, qf, al, ll, rec = train_fn(
            params, opts, data, jax.random.fold_in(jax.random.PRNGKey(1), i), jnp.int32(i)
        )
        rec_losses.append(float(rec))
    _decreased(rec_losses, "sac_ae reconstruction_loss")


def test_ppo_recurrent_value_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent
    from sheeprl_tpu.algos.ppo_recurrent.ppo_recurrent import make_train_step

    cfg = compose(
        ["exp=ppo_recurrent", "env.num_envs=2", "algo.rollout_steps=8", "algo.per_rank_batch_size=4"]
    )
    fabric = _fab()
    obs_space = _box_obs_space(4)
    agent, params, _player = build_agent(fabric, (2,), False, cfg, obs_space)
    tx = optax.inject_hyperparams(
        lambda learning_rate: build_optimizer(
            {**cfg.algo.optimizer, "lr": learning_rate}, max_grad_norm=cfg.algo.max_grad_norm
        )
    )(learning_rate=float(cfg.algo.optimizer.lr))
    opt_state = tx.init(params)

    T, S = 8, 4  # seq_len x sequences
    hidden = int(cfg.algo.rnn.lstm.hidden_size)
    train_fn = make_train_step(agent, tx, cfg, fabric.mesh, S)
    rng = np.random.default_rng(0)
    data = {
        "state": jnp.asarray(rng.normal(size=(T, S, 4)), dtype=jnp.float32),
        "actions": jnp.asarray(np.eye(2, dtype=np.float32)[rng.integers(0, 2, (T, S))]),
        "prev_actions": jnp.zeros((T, S, 2), dtype=jnp.float32),
        "logprobs": jnp.full((T, S, 1), -0.69, dtype=jnp.float32),
        "values": jnp.zeros((T, S, 1), dtype=jnp.float32),
        "returns": jnp.asarray(rng.normal(size=(T, S, 1)), dtype=jnp.float32),
        "advantages": jnp.asarray(rng.normal(size=(T, S, 1)), dtype=jnp.float32),
        "rewards": jnp.zeros((T, S, 1), dtype=jnp.float32),
        "dones": jnp.zeros((T, S, 1), dtype=jnp.float32),
        "mask": jnp.ones((T, S), dtype=jnp.float32),
        "prev_hx": jnp.zeros((1, S, hidden), dtype=jnp.float32),
        "prev_cx": jnp.zeros((1, S, hidden), dtype=jnp.float32),
    }
    v_losses = []
    for i in range(20):
        params, opt_state, pg, v, ent = train_fn(
            params, opt_state, data, jax.random.fold_in(jax.random.PRNGKey(1), i),
            jnp.float32(0.2), jnp.float32(0.0),
        )
        v_losses.append(float(v))
    _decreased(v_losses, "ppo_recurrent value_loss")


# ---------------------------------------------------------------------------
# Dreamer / P2E families (mlp-only observations: no conv graphs to compile)
# ---------------------------------------------------------------------------

_DREAMER_TINY = [
    "env=dummy",
    "env.num_envs=2",
    "algo.per_rank_batch_size=4",
    "algo.per_rank_sequence_length=4",
    "algo.horizon=4",
    "algo.dense_units=16",
    "algo.mlp_layers=1",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=16",
    "algo.world_model.transition_model.hidden_size=16",
    "algo.cnn_keys.encoder=[]",
    "algo.cnn_keys.decoder=[]",
    "algo.mlp_keys.encoder=[state]",
    "algo.mlp_keys.decoder=[state]",
]


def _dreamer_obs_space(dim=8):
    return gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (dim,), np.float32)})


def _dreamer_data(rng, actions_dim, T=4, B=4, dim=8, with_is_first=True):
    n_act = int(np.sum(actions_dim))
    data = {
        "state": jnp.asarray(rng.normal(size=(1, T, B, dim)), dtype=jnp.float32),
        "actions": jnp.asarray(
            np.eye(n_act, dtype=np.float32)[rng.integers(0, n_act, (1, T, B))], dtype=jnp.float32
        ),
        "rewards": jnp.asarray(rng.normal(size=(1, T, B, 1)), dtype=jnp.float32),
        "terminated": jnp.zeros((1, T, B, 1), dtype=jnp.float32),
        "truncated": jnp.zeros((1, T, B, 1), dtype=jnp.float32),
    }
    if with_is_first:
        data["is_first"] = jnp.zeros((1, T, B, 1), dtype=jnp.float32).at[:, 0].set(1.0)
    return data


def _dreamer_txs_opts(cfg, params):
    txs = {
        "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
        "actor": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
    }
    opts = {
        "world": txs["world"].init(params["world_model"]),
        "actor": txs["actor"].init(params["actor"]),
        "critic": txs["critic"].init(params["critic"]),
    }
    return txs, opts


@pytest.mark.slow
def test_dreamer_v3_world_model_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments

    cfg = compose(
        ["exp=dreamer_v3", "algo=dreamer_v3_XS"]
        + _DREAMER_TINY
        + ["algo.world_model.discrete_size=4", "algo.world_model.stochastic_size=4",
           "algo.world_model.reward_model.bins=17", "algo.critic.bins=17"]
    )
    fabric = _fab()
    actions_dim = (3,)
    world_model, actor, critic, params, _player = build_agent(
        fabric, actions_dim, False, cfg, _dreamer_obs_space(), None, None, None, None
    )
    txs, opts = _dreamer_txs_opts(cfg, params)
    train_fn = make_train_step(world_model, actor, critic, cfg, fabric.mesh, actions_dim, False, txs)
    moments = init_moments()

    data = _dreamer_data(np.random.default_rng(0), actions_dim)
    key = jax.random.PRNGKey(1)  # constant: fixed data AND fixed sampling noise
    wm_losses = []
    for i in range(25):
        params, opts, moments, metrics = train_fn(params, opts, moments, data, key, jnp.int32(i))
        wm_losses.append(float(metrics[0]))
    # measured tail/head 0.955 on the fixed batch (25 iters, lr 1e-4)
    _decreased(wm_losses, "dreamer_v3 world_model_loss", ratio=0.98)


@pytest.mark.slow
def test_dreamer_v2_world_model_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.dreamer_v2.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v2.dreamer_v2 import make_train_step

    cfg = compose(
        ["exp=dreamer_v2"]
        + _DREAMER_TINY
        + ["algo.world_model.discrete_size=4", "algo.world_model.stochastic_size=4"]
    )
    fabric = _fab()
    actions_dim = (3,)
    world_model, actor, critic, params, _player = build_agent(
        fabric, actions_dim, False, cfg, _dreamer_obs_space(), None, None, None
    )
    txs, opts = _dreamer_txs_opts(cfg, params)
    train_fn = make_train_step(world_model, actor, critic, cfg, fabric.mesh, actions_dim, False, txs)

    data = _dreamer_data(np.random.default_rng(0), actions_dim)
    key = jax.random.PRNGKey(1)  # constant: fixed data AND fixed sampling noise
    wm_losses = []
    for i in range(25):
        params, opts, metrics = train_fn(params, opts, data, key, jnp.int32(i))
        wm_losses.append(float(metrics[0]))
    # measured tail/head 0.961
    _decreased(wm_losses, "dreamer_v2 world_model_loss", ratio=0.98)


@pytest.mark.slow
def test_dreamer_v1_world_model_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.dreamer_v1.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import make_train_step

    cfg = compose(["exp=dreamer_v1"] + _DREAMER_TINY + ["algo.world_model.stochastic_size=4"])
    fabric = _fab()
    actions_dim = (3,)
    world_model, actor, critic, params, _player = build_agent(
        fabric, actions_dim, False, cfg, _dreamer_obs_space(), None, None, None
    )
    txs, opts = _dreamer_txs_opts(cfg, params)
    train_fn = make_train_step(world_model, actor, critic, cfg, fabric.mesh, actions_dim, False, txs)

    data = _dreamer_data(np.random.default_rng(0), actions_dim, with_is_first=False)
    key = jax.random.PRNGKey(1)  # constant: fixed data AND fixed sampling noise
    wm_losses = []
    for i in range(25):
        params, opts, metrics = train_fn(params, opts, data, key)
        wm_losses.append(float(metrics[0]))
    # measured tail/head 0.926
    _decreased(wm_losses, "dreamer_v1 world_model_loss", ratio=0.96)


def _p2e_tiny(exp):
    return (
        [f"exp={exp}"]
        + _DREAMER_TINY
        + [
            "algo.ensembles.n=2",
            "algo.ensembles.dense_units=16",
            "algo.ensembles.mlp_layers=1",
        ]
    )


@pytest.mark.slow
def test_p2e_dv1_world_model_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.p2e_dv1.agent import build_agent
    from sheeprl_tpu.algos.p2e_dv1.p2e_dv1_exploration import make_train_step

    cfg = compose(_p2e_tiny("p2e_dv1_exploration") + ["algo.world_model.stochastic_size=4"])
    fabric = _fab()
    actions_dim = (3,)
    world_model, ens_module, actor, critic, params, _player = build_agent(
        fabric, actions_dim, False, cfg, _dreamer_obs_space(), None, None, None, None, None, None
    )
    txs = {
        "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
        "actor_task": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_task": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
        "actor_exploration": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_exploration": build_optimizer(
            cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients
        ),
        "ensembles": build_optimizer(cfg.algo.ensembles.optimizer, max_grad_norm=cfg.algo.ensembles.clip_gradients),
    }
    opts = {
        "world": txs["world"].init(params["world_model"]),
        "actor_task": txs["actor_task"].init(params["actor_task"]),
        "critic_task": txs["critic_task"].init(params["critic_task"]),
        "actor_exploration": txs["actor_exploration"].init(params["actor_exploration"]),
        "critic_exploration": txs["critic_exploration"].init(params["critic_exploration"]),
        "ensembles": txs["ensembles"].init(params["ensembles"]),
    }
    train_fn = make_train_step(world_model, ens_module, actor, critic, cfg, fabric.mesh, actions_dim, False, txs)

    data = _dreamer_data(np.random.default_rng(0), actions_dim, with_is_first=False)
    key = jax.random.PRNGKey(1)  # constant: fixed data AND fixed sampling noise
    wm_losses = []
    for i in range(25):
        params, opts, metrics = train_fn(params, opts, data, key)
        wm_losses.append(float(metrics["Loss/world_model_loss"]))
    # measured tail/head 0.922
    _decreased(wm_losses, "p2e_dv1 world_model_loss", ratio=0.96)


@pytest.mark.slow
def test_p2e_dv2_world_model_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.p2e_dv2.agent import build_agent
    from sheeprl_tpu.algos.p2e_dv2.p2e_dv2_exploration import make_train_step

    cfg = compose(
        _p2e_tiny("p2e_dv2_exploration")
        + ["algo.world_model.discrete_size=4", "algo.world_model.stochastic_size=4"]
    )
    fabric = _fab()
    actions_dim = (3,)
    built = build_agent(
        fabric, actions_dim, False, cfg, _dreamer_obs_space(),
        None, None, None, None, None, None, None, None,
    )
    world_model, ens_module, actor, critic, params, _player = built
    txs = {
        "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
        "actor_task": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_task": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
        "actor_exploration": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_exploration": build_optimizer(
            cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients
        ),
        "ensembles": build_optimizer(cfg.algo.ensembles.optimizer, max_grad_norm=cfg.algo.ensembles.clip_gradients),
    }
    opts = {k: txs[k].init(params[_P2E_PARAM_KEYS[k]]) for k in txs}
    train_fn = make_train_step(world_model, ens_module, actor, critic, cfg, fabric.mesh, actions_dim, False, txs)

    data = _dreamer_data(np.random.default_rng(0), actions_dim)
    key = jax.random.PRNGKey(1)  # constant: fixed data AND fixed sampling noise
    wm_losses = []
    for i in range(25):
        params, opts, metrics = train_fn(params, opts, data, key, jnp.int32(i))
        wm_losses.append(float(metrics["Loss/world_model_loss"]))
    # measured tail/head 0.971
    _decreased(wm_losses, "p2e_dv2 world_model_loss", ratio=0.985)


_P2E_PARAM_KEYS = {
    "world": "world_model",
    "actor_task": "actor_task",
    "critic_task": "critic_task",
    "actor_exploration": "actor_exploration",
    "critic_exploration": "critic_exploration",
    "ensembles": "ensembles",
}


@pytest.mark.slow
def test_p2e_dv3_world_model_loss_decreases_on_fixed_batch():
    from sheeprl_tpu.algos.p2e_dv3.agent import build_agent
    from sheeprl_tpu.algos.p2e_dv3.p2e_dv3_exploration import make_train_step
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments

    cfg = compose(
        _p2e_tiny("p2e_dv3_exploration")
        + [
            "algo.world_model.discrete_size=4",
            "algo.world_model.stochastic_size=4",
            "algo.world_model.reward_model.bins=17",
            "algo.critic.bins=17",
        ]
    )
    fabric = _fab()
    actions_dim = (3,)
    world_model, ens_module, actor, critic, critics_spec, params, _player = build_agent(
        fabric, actions_dim, False, cfg, _dreamer_obs_space(),
        None, None, None, None, None, None, None,
    )
    txs = {
        "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
        "actor_task": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_task": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
        "actor_exploration": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "ensembles": build_optimizer(cfg.algo.ensembles.optimizer, max_grad_norm=cfg.algo.ensembles.clip_gradients),
        "critics_exploration": {
            k: build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients)
            for k in critics_spec
        },
    }
    opts = {
        "world": txs["world"].init(params["world_model"]),
        "actor_task": txs["actor_task"].init(params["actor_task"]),
        "critic_task": txs["critic_task"].init(params["critic_task"]),
        "actor_exploration": txs["actor_exploration"].init(params["actor_exploration"]),
        "ensembles": txs["ensembles"].init(params["ensembles"]),
        "critics_exploration": {
            k: txs["critics_exploration"][k].init(params["critics_exploration"][k]["module"]) for k in critics_spec
        },
    }
    moments = {"task": init_moments(), "exploration": {k: init_moments() for k in critics_spec}}
    train_fn = make_train_step(
        world_model, ens_module, actor, critic, critics_spec, cfg, fabric.mesh, actions_dim, False, txs
    )

    data = _dreamer_data(np.random.default_rng(0), actions_dim)
    key = jax.random.PRNGKey(1)  # constant: fixed data AND fixed sampling noise
    wm_losses = []
    for i in range(25):
        params, opts, moments, metrics = train_fn(params, opts, moments, data, key, jnp.int32(i))
        wm_losses.append(float(metrics["Loss/world_model_loss"]))
    # rises for ~9 iters while Adam moments fill, then descends: measured
    # tail/head 0.971 from iteration 10
    _decreased(wm_losses, "p2e_dv3 world_model_loss", ratio=0.985, warmup=10)
