"""Algorithm smoke tests: every registered algorithm runs one dry-run
iteration end-to-end through the real CLI on dummy envs — mirroring the
reference suite (``tests/test_algos/test_algos.py:16-566``), with the device
count parametrized over the virtual CPU mesh instead of ``LT_DEVICES``."""

import os

import pytest

from sheeprl_tpu.cli import run


def _std_args(tmp_path, algo, env="dummy", devices=1, extra=()):
    args = [
        f"exp={algo}",
        f"env={env}",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "dry_run=True",
        "buffer.memmap=False",
        f"fabric.devices={devices}",
        "metric.log_level=0",
        "checkpoint.save_last=False",
        f"log_root={tmp_path}/logs",
        "algo.run_test=False",
    ]
    args.extend(extra)
    return args


PPO_FAST = [
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]


@pytest.mark.parametrize("devices", [1, 2])
def test_ppo_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "ppo", devices=devices, extra=PPO_FAST))


def test_ppo_cnn_keys(tmp_path):
    run(
        _std_args(
            tmp_path,
            "ppo",
            extra=PPO_FAST[:-1]
            + ["algo.mlp_keys.encoder=[state]", "algo.cnn_keys.encoder=[rgb]", "env.screen_size=64"],
        )
    )


def test_ppo_continuous(tmp_path):
    run(_std_args(tmp_path, "ppo", extra=PPO_FAST + ["env.id=continuous_dummy"]))


# On-device (Anakin) PPO: the rollout runs in-graph over a pure-JAX env, so
# these dry-runs go through the real CLI on the jax CartPole/Pendulum twins
# (env=gym ids) instead of the host dummies.
@pytest.mark.parametrize("devices", [1, 2])
def test_ppo_anakin_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "ppo_anakin", env="gym", devices=devices, extra=PPO_FAST))


def test_ppo_anakin_continuous(tmp_path):
    run(_std_args(tmp_path, "ppo_anakin", env="gym", extra=PPO_FAST + ["env.id=Pendulum-v1"]))


def test_ppo_anakin_rejects_host_env(tmp_path):
    with pytest.raises(ValueError, match="pure-JAX"):
        run(_std_args(tmp_path, "ppo_anakin", env="gym", extra=PPO_FAST + ["env.id=discrete_dummy"]))


def test_ppo_multidiscrete(tmp_path):
    run(_std_args(tmp_path, "ppo", extra=PPO_FAST + ["env.id=multidiscrete_dummy"]))


A2C_FAST = [
    "algo.rollout_steps=8",
    "algo.mlp_keys.encoder=[state]",
]


@pytest.mark.parametrize("devices", [1, 2])
def test_a2c_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "a2c", devices=devices, extra=A2C_FAST))


def test_a2c_continuous(tmp_path):
    run(_std_args(tmp_path, "a2c", extra=A2C_FAST + ["env.id=continuous_dummy"]))


def test_a2c_multidiscrete(tmp_path):
    run(_std_args(tmp_path, "a2c", extra=A2C_FAST + ["env.id=multidiscrete_dummy"]))


SAC_FAST = [
    "algo.per_rank_batch_size=8",
    "algo.mlp_keys.encoder=[state]",
    "env.id=continuous_dummy",
]


@pytest.mark.parametrize("devices", [1, 2])
def test_sac_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "sac", devices=devices, extra=SAC_FAST))


def test_sac_sample_next_obs(tmp_path):
    # dry_run forces a size-1 buffer, which cannot serve shifted next-obs
    # indices — run a real (tiny) loop instead, like the reference suite.
    args = _std_args(
        tmp_path,
        "sac",
        extra=SAC_FAST
        + [
            "buffer.sample_next_obs=True",
            "buffer.size=64",
            "algo.total_steps=4",
            "algo.learning_starts=4",
        ],
    )
    args.remove("dry_run=True")
    run(args)


SAC_AE_FAST = [
    "algo.per_rank_batch_size=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "algo.hidden_size=16",
    "algo.cnn_channels_multiplier=2",
    "env.id=continuous_dummy",
    "env.screen_size=64",
]


@pytest.mark.parametrize("devices", [1, 2])
def test_sac_ae_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "sac_ae", devices=devices, extra=SAC_AE_FAST))


@pytest.mark.parametrize("devices", [1, 2])
def test_droq_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "droq", devices=devices, extra=SAC_FAST))


PPO_REC_FAST = [
    "algo.rollout_steps=8",
    "algo.per_rank_sequence_length=4",
    "algo.per_rank_num_batches=2",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]


@pytest.mark.parametrize("devices", [1, 2])
def test_ppo_recurrent_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "ppo_recurrent", devices=devices, extra=PPO_REC_FAST))


def test_ppo_recurrent_continuous(tmp_path):
    run(_std_args(tmp_path, "ppo_recurrent", extra=PPO_REC_FAST + ["env.id=continuous_dummy"]))


DREAMER_FAST = [
    "algo=dreamer_v3_XS",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=1",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.world_model.reward_model.bins=17",
    "algo.critic.bins=17",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "env.screen_size=64",
]


@pytest.mark.parametrize("devices", [1, 2])
def test_dreamer_v3_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "dreamer_v3", devices=devices, extra=DREAMER_FAST))


def test_dreamer_v3_continuous(tmp_path):
    run(_std_args(tmp_path, "dreamer_v3", extra=DREAMER_FAST + ["env.id=continuous_dummy"]))


def test_dreamer_v3_decoupled_rssm(tmp_path):
    run(_std_args(tmp_path, "dreamer_v3", extra=DREAMER_FAST + ["algo.world_model.decoupled_rssm=True"]))


DREAMER_V2_FAST = [
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=2",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "env.screen_size=64",
]


@pytest.mark.parametrize("devices", [1, 2])
def test_dreamer_v2_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "dreamer_v2", devices=devices, extra=DREAMER_V2_FAST))


def test_dreamer_v2_continuous(tmp_path):
    run(_std_args(tmp_path, "dreamer_v2", extra=DREAMER_V2_FAST + ["env.id=continuous_dummy"]))


def test_dreamer_v2_episode_buffer(tmp_path):
    run(
        _std_args(
            tmp_path,
            "dreamer_v2",
            extra=DREAMER_V2_FAST + ["buffer.type=episode", "algo.per_rank_sequence_length=1"],
        )
    )


def test_dreamer_v2_use_continues(tmp_path):
    run(_std_args(tmp_path, "dreamer_v2", extra=DREAMER_V2_FAST + ["algo.world_model.use_continues=True"]))


DREAMER_V1_FAST = [
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=2",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.stochastic_size=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "env.screen_size=64",
]


@pytest.mark.parametrize("devices", [1, 2])
def test_dreamer_v1_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "dreamer_v1", devices=devices, extra=DREAMER_V1_FAST))


def test_dreamer_v1_continuous(tmp_path):
    run(_std_args(tmp_path, "dreamer_v1", extra=DREAMER_V1_FAST + ["env.id=continuous_dummy"]))


# drop the algo-group override (it would clobber p2e_dv3's algo config)
P2E_DV3_FAST = [a for a in DREAMER_FAST if a != "algo=dreamer_v3_XS"] + [
    "algo.ensembles.n=3",
    "algo.per_rank_sequence_length=1",
]
P2E_DV2_FAST = DREAMER_V2_FAST + ["algo.ensembles.n=3", "algo.per_rank_sequence_length=1"]
P2E_DV1_FAST = DREAMER_V1_FAST + ["algo.ensembles.n=3", "algo.per_rank_sequence_length=1"]


def _latest_ckpt(root):
    import glob

    return sorted(glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True))[-1]


@pytest.mark.parametrize(
    "algo, fast",
    [("p2e_dv1", P2E_DV1_FAST), ("p2e_dv2", P2E_DV2_FAST), ("p2e_dv3", P2E_DV3_FAST)],
)
def test_p2e_exploration_then_finetuning(tmp_path, algo, fast):
    """Exploration dry-run → checkpoint → finetuning-from-checkpoint
    round-trip (mirrors reference ``tests/test_algos/test_algos.py`` p2e
    coverage + the ``cli`` finetuning config plumbing)."""
    _exploration_ckpt_then_finetune(
        tmp_path, algo, fast, _std_args(tmp_path, f"{algo}_exploration", extra=fast)
    )


def test_p2e_dv3_exploration_two_devices(tmp_path):
    run(_std_args(tmp_path, "p2e_dv3_exploration", devices=2, extra=P2E_DV3_FAST))


@pytest.mark.parametrize("devices", [1, 2])
def test_ppo_decoupled_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "ppo_decoupled", devices=devices, extra=PPO_FAST))


def test_ppo_decoupled_multi_iteration(tmp_path):
    """Several player/trainer exchanges + a periodic player-side checkpoint
    (the decoupled topology's param-publish and on_checkpoint_player paths)."""
    args = _std_args(tmp_path, "ppo_decoupled", extra=PPO_FAST)
    args.remove("dry_run=True")
    args.remove("checkpoint.save_last=False")
    args += ["algo.total_steps=64", "checkpoint.every=32", "checkpoint.save_last=True"]
    run(args)
    import glob

    assert len(glob.glob(f"{tmp_path}/logs/**/ckpt_*.ckpt", recursive=True)) >= 2


SAC_DECOUPLED_FAST = [
    "env.id=continuous_dummy",
    "algo.mlp_keys.encoder=[state]",
    "algo.per_rank_batch_size=4",
]


@pytest.mark.parametrize("devices", [1, 2])
def test_sac_decoupled_dry_run(tmp_path, devices):
    run(_std_args(tmp_path, "sac_decoupled", devices=devices, extra=SAC_DECOUPLED_FAST))


def test_ppo_share_data_two_devices(tmp_path):
    """buffer.share_data: in-graph all_gather + common-permutation sharded
    sampling (reference ppo.py:40-47,362-366)."""
    run(_std_args(tmp_path, "ppo", devices=2, extra=PPO_FAST + ["buffer.share_data=True"]))


def test_ppo_profiler_trace(tmp_path):
    """jax.profiler trace hook produces a trace directory (SURVEY §5)."""
    args = _std_args(tmp_path, "ppo", extra=PPO_FAST)
    args.remove("dry_run=True")
    args += [
        "algo.total_steps=64",
        "metric.profiler.enabled=True",
        "metric.profiler.start_iter=1",
        "metric.profiler.num_iters=2",
    ]
    run(args)
    import glob

    assert glob.glob(f"{tmp_path}/logs/**/profiler/**/*", recursive=True), "no profiler trace captured"


@pytest.mark.parametrize("precision", ["bf16-mixed", "bf16-true"])
def test_ppo_bf16_precision(tmp_path, precision):
    """The precision policy path (the reference CI runs everything under
    bf16-true): GAE and the scans must keep dtype-stable carries."""
    run(_std_args(tmp_path, "ppo", devices=2, extra=PPO_FAST + [f"fabric.precision={precision}"]))


def test_sac_bf16_precision(tmp_path):
    run(
        _std_args(
            tmp_path,
            "sac",
            extra=[
                "env.id=continuous_dummy",
                "algo.mlp_keys.encoder=[state]",
                "algo.per_rank_batch_size=4",
                "fabric.precision=bf16-mixed",
            ],
        )
    )


def test_dreamer_v3_bf16_precision(tmp_path):
    run(_std_args(tmp_path, "dreamer_v3", extra=DREAMER_FAST + ["fabric.precision=bf16-mixed"]))


def test_dreamer_v1_bf16_precision(tmp_path):
    """Normal-posterior RSSM under bf16-mixed: samples carry bf16 through the
    scan while distribution math is promoted to f32 (distributions/core.py
    ``_lift``)."""
    run(_std_args(tmp_path, "dreamer_v1", extra=DREAMER_V1_FAST + ["fabric.precision=bf16-mixed"]))


def test_dreamer_v2_bf16_precision(tmp_path):
    run(_std_args(tmp_path, "dreamer_v2", extra=DREAMER_V2_FAST + ["fabric.precision=bf16-mixed"]))


def test_unknown_algorithm_errors(tmp_path):
    with pytest.raises(Exception):
        run([f"exp=not_an_algo", f"log_root={tmp_path}/logs"])


def _hybrid_burst_args(tmp_path, algo, fast):
    """Force the TPU-native hybrid/burst path on over the CPU mesh: host
    player + device sequence ring + trainer-thread bursts, multiple
    iterations past learning_starts, then the greedy test rollout."""
    args = _std_args(tmp_path, algo, extra=fast)
    args.remove("dry_run=True")
    args.remove("algo.run_test=False")
    args += [
        "dry_run=False",
        "algo.run_test=True",
        "algo.hybrid_player.enabled=true",
        "algo.hybrid_player.train_every=4",
        "algo.hybrid_player.snapshot_every=2",
        "algo.total_steps=96",
        "algo.learning_starts=32",
        "algo.per_rank_sequence_length=4",
        "buffer.size=2000",
    ]
    return args


def test_dreamer_v3_hybrid_burst(tmp_path):
    run(_hybrid_burst_args(tmp_path, "dreamer_v3", DREAMER_FAST))


def test_dreamer_v1_hybrid_burst(tmp_path):
    run(_hybrid_burst_args(tmp_path, "dreamer_v1", DREAMER_V1_FAST))


def test_dreamer_v2_hybrid_burst(tmp_path):
    run(_hybrid_burst_args(tmp_path, "dreamer_v2", DREAMER_V2_FAST))


def test_p2e_dv3_exploration_hybrid_burst(tmp_path):
    run(_hybrid_burst_args(tmp_path, "p2e_dv3_exploration", P2E_DV3_FAST))


def test_p2e_dv1_exploration_hybrid_burst(tmp_path):
    run(_hybrid_burst_args(tmp_path, "p2e_dv1_exploration", P2E_DV1_FAST))


def _exploration_ckpt_then_finetune(tmp_path, algo, fast, exploration_args):
    """Run an exploration phase with save_last, then finetune from its
    checkpoint (shared by the host-path and burst-path round-trip tests)."""
    expl = list(exploration_args)
    expl.remove("checkpoint.save_last=False")
    expl.append("checkpoint.save_last=True")
    run(expl)
    ckpt = _latest_ckpt(f"{tmp_path}/logs")
    run(
        _std_args(tmp_path, f"{algo}_finetuning", extra=fast)
        + [f"checkpoint.exploration_ckpt_path={ckpt}"]
    )


@pytest.mark.parametrize(
    "algo, fast",
    [("p2e_dv1", P2E_DV1_FAST), ("p2e_dv3", P2E_DV3_FAST)],
)
def test_p2e_burst_checkpoint_feeds_finetuning(tmp_path, algo, fast):
    """A checkpoint written by the burst path (trainer-thread carry) must be
    consumable by the host-path finetuning main — cross-phase parity of the
    checkpoint layout. dv1 and dv3 cover the two carry shapes
    ((params, opts) and (params, opts, moments, cum))."""
    args = _hybrid_burst_args(tmp_path, f"{algo}_exploration", fast)
    args.append("algo.run_test=False")  # the greedy rollout adds nothing here
    _exploration_ckpt_then_finetune(tmp_path, algo, fast, args)


def test_p2e_dv2_exploration_hybrid_burst(tmp_path):
    run(_hybrid_burst_args(tmp_path, "p2e_dv2_exploration", P2E_DV2_FAST))


def test_dreamer_v2_hybrid_burst_episode_buffer(tmp_path):
    """buffer.type=episode rides the burst path via the ring's episode-rule
    sampling (windows never mix episodes) — a full run incl. the greedy
    test rollout (howto/tpu_parallelism.md)."""
    args = _hybrid_burst_args(tmp_path, "dreamer_v2", DREAMER_V2_FAST)
    args += ["buffer.type=episode", "algo.per_rank_sequence_length=2"]
    run(args)


def test_dreamer_v2_hybrid_burst_prioritize_ends_errors(tmp_path):
    """prioritize_ends is a host-path sampling bias: explicitly enabling the
    hybrid player with it is a config conflict — it must error, not silently
    forfeit either the bias or the burst speedup."""
    args = _hybrid_burst_args(tmp_path, "dreamer_v2", DREAMER_V2_FAST)
    args += ["buffer.type=episode", "buffer.prioritize_ends=True", "algo.per_rank_sequence_length=2"]
    with pytest.raises(ValueError, match="prioritize_ends"):
        run(args)


def test_dreamer_v2_episode_burst_checkpoint_resumes_on_host_path(tmp_path):
    """A burst-written episode-buffer checkpoint must stay resumable with
    its UNCHANGED config (incl. explicit enabled=true): the resume warns
    and downgrades to host-path sampling rather than erroring — the ring
    cannot be mirrored from an episode container."""
    args = _hybrid_burst_args(tmp_path, "dreamer_v2", DREAMER_V2_FAST)
    args += [
        "buffer.type=episode",
        "algo.per_rank_sequence_length=2",
        "buffer.checkpoint=True",
        "algo.run_test=False",
    ]
    args.remove("checkpoint.save_last=False")
    args.append("checkpoint.save_last=True")
    run(args)
    ckpt = _latest_ckpt(f"{tmp_path}/logs")
    with pytest.warns(UserWarning, match="Resuming an episode buffer"):
        run(args + [f"checkpoint.resume_from={ckpt}", "algo.total_steps=128"])
