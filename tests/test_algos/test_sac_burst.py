"""Unit tests for SAC's device-resident burst training path
(`make_burst_train_step`): ring append semantics, the valid-mask no-op gate,
and finite losses from granted steps.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.sac import make_burst_train_step
from sheeprl_tpu.config import compose
from sheeprl_tpu.optim.builders import build_optimizer
from sheeprl_tpu.parallel import Fabric

CAPACITY = 8
N_ENVS = 2
STAGE_MAX = 4
GRAD_CHUNK = 2
OBS_DIM = 3
ACT_DIM = 2


@pytest.fixture(scope="module")
def setup():
    cfg = compose(
        [
            "exp=sac",
            "env=gym",
            "env.id=Pendulum-v1",
            "algo.per_rank_batch_size=8",
            "algo.hidden_size=16",
        ]
    )
    fabric = Fabric(devices=1, accelerator="cpu")
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-1, 1, (OBS_DIM,))})
    act_space = gym.spaces.Box(-1, 1, (ACT_DIM,))
    agent, params, _ = build_agent(fabric, cfg, obs_space, act_space, None)
    txs = {
        "actor": build_optimizer(cfg.algo.actor.optimizer),
        "critic": build_optimizer(cfg.algo.critic.optimizer),
        "alpha": build_optimizer(cfg.algo.alpha.optimizer),
    }
    opts = (
        txs["actor"].init(params["actor"]),
        txs["critic"].init(params["critic"]),
        txs["alpha"].init(params["log_alpha"]),
    )
    burst_fn = make_burst_train_step(
        agent, txs["actor"], txs["critic"], txs["alpha"], cfg, fabric.mesh,
        capacity=CAPACITY, n_envs=N_ENVS, stage_max=STAGE_MAX, grad_chunk=GRAD_CHUNK,
    )
    rb = {
        "observations": jnp.zeros((CAPACITY, N_ENVS, OBS_DIM), jnp.float32),
        "next_observations": jnp.zeros((CAPACITY, N_ENVS, OBS_DIM), jnp.float32),
        "actions": jnp.zeros((CAPACITY, N_ENVS, ACT_DIM), jnp.float32),
        "rewards": jnp.zeros((CAPACITY, N_ENVS, 1), jnp.float32),
        "terminated": jnp.zeros((CAPACITY, N_ENVS, 1), jnp.float32),
    }
    return agent, params, opts, burst_fn, rb


def _staged(fill, count):
    out = {
        "observations": np.zeros((STAGE_MAX, N_ENVS, OBS_DIM), np.float32),
        "next_observations": np.zeros((STAGE_MAX, N_ENVS, OBS_DIM), np.float32),
        "actions": np.zeros((STAGE_MAX, N_ENVS, ACT_DIM), np.float32),
        "rewards": np.zeros((STAGE_MAX, N_ENVS, 1), np.float32),
        "terminated": np.zeros((STAGE_MAX, N_ENVS, 1), np.float32),
    }
    for i in range(count):
        out["observations"][i] = fill + i
    return out


def _call(burst_fn, params, opts, rb, staged, pos, count, total, valid_steps, seed=0):
    aopt, copt, lopt = opts
    flags = np.zeros((GRAD_CHUNK,), np.float32)
    valid = np.zeros((GRAD_CHUNK,), np.float32)
    flags[:valid_steps] = 1.0
    valid[:valid_steps] = 1.0
    # The ring buffer argument is donated by design — hand in a fresh copy so
    # the module-scoped fixture survives across tests.
    rb_copy = jax.tree.map(lambda x: jnp.array(x), rb)
    return burst_fn(
        params, aopt, copt, lopt, rb_copy,
        {k: jnp.asarray(v) for k, v in staged.items()},
        jnp.int32(pos), jnp.int32(count), jnp.int32(total),
        jax.random.PRNGKey(seed), jnp.asarray(flags), jnp.asarray(valid),
    )


def test_ring_append_and_wraparound(setup):
    _, params, opts, burst_fn, rb = setup
    # Append 3 rows at pos 6 of an 8-slot ring: rows land at 6, 7, 0.
    out = _call(burst_fn, params, opts, rb, _staged(10.0, 3), pos=6, count=3, total=8, valid_steps=0)
    new_rb = out[4]
    obs = np.asarray(new_rb["observations"])
    assert np.allclose(obs[6, :, 0], 10.0)
    assert np.allclose(obs[7, :, 0], 11.0)
    assert np.allclose(obs[0, :, 0], 12.0)
    # Rows beyond `count` (the padding) must be dropped, not written.
    assert np.allclose(obs[1:6], 0.0)


def test_padding_rows_dropped(setup):
    _, params, opts, burst_fn, rb = setup
    out = _call(burst_fn, params, opts, rb, _staged(5.0, 1), pos=0, count=1, total=4, valid_steps=0)
    obs = np.asarray(out[4]["observations"])
    assert np.allclose(obs[0, :, 0], 5.0)
    assert np.allclose(obs[1:], 0.0)


def test_invalid_steps_leave_params_untouched(setup):
    _, params, opts, burst_fn, rb = setup
    out = _call(burst_fn, params, opts, rb, _staged(1.0, 2), pos=0, count=2, total=4, valid_steps=0)
    new_params = out[0]
    for old, new in zip(jax.tree.leaves(params), jax.tree.leaves(new_params)):
        assert np.array_equal(np.asarray(old), np.asarray(new))


def test_valid_steps_update_params_with_finite_losses(setup):
    _, params, opts, burst_fn, rb = setup
    staged = _staged(0.5, STAGE_MAX)
    staged["rewards"][:] = 1.0
    out = _call(burst_fn, params, opts, rb, staged, pos=0, count=STAGE_MAX, total=STAGE_MAX, valid_steps=GRAD_CHUNK)
    new_params, qf_l, a_l, al_l = out[0], out[5], out[6], out[7]
    assert np.isfinite(float(qf_l)) and np.isfinite(float(a_l)) and np.isfinite(float(al_l))
    changed = any(
        not np.array_equal(np.asarray(o), np.asarray(n))
        for o, n in zip(jax.tree.leaves(params["actor"]), jax.tree.leaves(new_params["actor"]))
    )
    assert changed


def test_partial_validity_gates_per_step(setup):
    """One granted + one padded step: params move once, the padded step is a
    no-op (same result as a chunk of exactly one granted step)."""
    _, params, opts, burst_fn, rb = setup
    staged = _staged(0.5, STAGE_MAX)
    out_partial = _call(
        burst_fn, params, opts, rb, staged, pos=0, count=STAGE_MAX, total=STAGE_MAX, valid_steps=1, seed=3
    )
    out_full = _call(
        burst_fn, params, opts, rb, staged, pos=0, count=STAGE_MAX, total=STAGE_MAX, valid_steps=GRAD_CHUNK, seed=3
    )
    # The first granted step is identical; the second full step moves params
    # further, so partial != full but partial != initial either.
    p0 = jax.tree.leaves(params["actor"])
    pp = jax.tree.leaves(out_partial[0]["actor"])
    pf = jax.tree.leaves(out_full[0]["actor"])
    assert any(not np.array_equal(np.asarray(a), np.asarray(b)) for a, b in zip(p0, pp))
    assert any(not np.array_equal(np.asarray(a), np.asarray(b)) for a, b in zip(pp, pf))
