"""Metric aggregation (reference: ``sheeprl/utils/metric.py:17-195``)."""

from __future__ import annotations

import math

import numpy as np
import pytest

from sheeprl_tpu.utils.metric import (
    CatMetric,
    MaxMetric,
    MeanMetric,
    MetricAggregator,
    MetricAggregatorException,
    MinMetric,
    RankIndependentMetricAggregator,
    SumMetric,
    build_aggregator,
)


class TestMetrics:
    def test_mean_over_arrays_and_scalars(self):
        m = MeanMetric()
        m.update(2.0)
        m.update(np.array([4.0, 6.0]))
        assert m.compute() == 4.0
        m.reset()
        assert math.isnan(m.compute())

    def test_sum_max_min(self):
        s, hi, lo = SumMetric(), MaxMetric(), MinMetric()
        for v in (1.0, 5.0, 3.0):
            s.update(v), hi.update(v), lo.update(v)
        assert (s.compute(), hi.compute(), lo.compute()) == (9.0, 5.0, 1.0)


class TestMetricAggregator:
    def test_update_compute_reset(self):
        agg = MetricAggregator({"a": MeanMetric(), "b": SumMetric()})
        agg.update("a", 1.0)
        agg.update("a", 3.0)
        agg.update("b", 10.0)
        out = agg.compute()
        assert out["a"] == 2.0 and out["b"] == 10.0
        agg.reset()
        assert "a" not in agg.compute()  # NaN mean is dropped after reset

    def test_unknown_key_silent_by_default_raises_when_asked(self):
        agg = MetricAggregator({"a": MeanMetric()})
        agg.update("missing", 1.0)  # silently skipped
        strict = MetricAggregator({"a": MeanMetric()}, raise_on_missing=True)
        with pytest.raises(MetricAggregatorException):
            strict.update("missing", 1.0)

    def test_contains_and_keys(self):
        agg = MetricAggregator({"a": MeanMetric()})
        assert "a" in agg and "b" not in agg
        assert set(agg.keys()) == {"a"}


class TestRankIndependentAggregator:
    def test_full_surface_delegates(self):
        agg = RankIndependentMetricAggregator({"a": MeanMetric(), "c": CatMetric()})
        assert "a" in agg and "b" not in agg
        assert set(agg.keys()) == {"a", "c"}
        assert agg.to("cpu") is agg
        agg.update("a", 2.0)
        agg.update("a", 4.0)
        assert agg.compute()["a"] == 3.0
        agg.reset()
        assert "a" not in agg.compute()

    def test_sync_is_forced_off(self):
        agg = RankIndependentMetricAggregator({"a": MeanMetric(sync_on_compute=True)})
        assert not agg._aggregator.metrics["a"].sync_on_compute

    def test_disabled_tracks_class_flag(self):
        agg = RankIndependentMetricAggregator({"a": MeanMetric()})
        assert agg.disabled == MetricAggregator.disabled


class TestBuildAggregator:
    CFG = {
        "metrics": {
            "Loss/policy_loss": {"_target_": "torchmetrics.MeanMetric", "sync_on_compute": False},
            "Game/ep_len_avg": {"_target_": "torchmetrics.SumMetric"},
        }
    }

    def test_maps_torchmetrics_leaf_names(self):
        agg = build_aggregator(self.CFG)
        assert isinstance(agg, MetricAggregator)
        assert isinstance(agg.metrics["Loss/policy_loss"], MeanMetric)
        assert isinstance(agg.metrics["Game/ep_len_avg"], SumMetric)

    def test_keys_filter(self):
        agg = build_aggregator(self.CFG, keys_filter={"Game/ep_len_avg"})
        assert set(agg.keys()) == {"Game/ep_len_avg"}

    def test_rank_independent_variant(self):
        agg = build_aggregator(self.CFG, rank_independent=True)
        assert isinstance(agg, RankIndependentMetricAggregator)
        agg.update("Loss/policy_loss", 1.5)
        assert agg.compute()["Loss/policy_loss"] == 1.5
