"""Unit tests for the shared hybrid-player burst machinery
(``utils/burst.py``): packed host snapshots, ring init/mirror, and the
BurstRunner staging/dispatch semantics — with a fake burst_fn so the queue
and thread lifecycle are exercised without compiling a train step.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.utils.burst import BurstRunner, HostSnapshot, dreamer_ring_keys, init_device_ring


class _FakeFabric:
    replicated = jax.sharding.SingleDeviceSharding(jax.devices()[0])

    def put_replicated(self, tree):
        return jax.tree.map(jnp.asarray, tree)


class TestHostSnapshot:
    def test_pull_round_trips_subset(self):
        params = {"world_model": {"encoder": jnp.arange(8.0), "decoder": jnp.ones(4)}, "actor": jnp.ones(3) * 2}
        subset = lambda p: {"enc": p["world_model"]["encoder"], "actor": p["actor"]}
        snap = HostSnapshot(subset, params)
        host = snap.pull(params)
        np.testing.assert_allclose(np.asarray(host["enc"]), np.arange(8.0), rtol=1e-2)
        np.testing.assert_allclose(np.asarray(host["actor"]), 2.0, rtol=1e-2)

    def test_refresh_then_poll_returns_once(self):
        params = {"w": jnp.ones(4)}
        snap = HostSnapshot(lambda p: p, params)
        assert snap.poll() is None
        snap.refresh({"w": jnp.full((4,), 3.0)})
        polled = snap.poll()
        np.testing.assert_allclose(np.asarray(polled["w"]), 3.0, rtol=1e-2)
        assert snap.poll() is None  # consumed


class TestInitDeviceRing:
    KEYS = {"obs": ((2,), jnp.float32), "rewards": ((1,), jnp.float32)}

    def test_fresh_ring_is_zeroed(self):
        rb_dev, pos, valid = init_device_ring(_FakeFabric(), self.KEYS, capacity=5, n_envs=3)
        assert rb_dev["obs"].shape == (5, 3, 2)
        assert float(rb_dev["obs"].sum()) == 0.0
        assert pos.tolist() == [0, 0, 0] and valid.tolist() == [0, 0, 0]

    def test_mirror_restores_contents_and_heads(self):
        rb = EnvIndependentReplayBuffer(4, n_envs=2, obs_keys=("obs",), buffer_cls=SequentialReplayBuffer)
        data = {
            "obs": np.arange(12, dtype=np.float32).reshape(3, 2, 2),
            "rewards": np.ones((3, 2, 1), np.float32),
        }
        rb.add(data)
        rb_dev, pos, valid = init_device_ring(_FakeFabric(), self.KEYS, capacity=4, n_envs=2, rb=rb)
        np.testing.assert_array_equal(np.asarray(rb_dev["obs"])[:3, 0], data["obs"][:, 0])
        np.testing.assert_array_equal(np.asarray(rb_dev["obs"])[:3, 1], data["obs"][:, 1])
        assert pos.tolist() == [3, 3]
        assert valid.tolist() == [3, 3]


def test_dreamer_ring_keys_layout():
    import gymnasium as gym

    space = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8),
            "state": gym.spaces.Box(-1, 1, (7,), np.float32),
        }
    )
    keys = dreamer_ring_keys(space, ["rgb"], ["state"], (2, 3), with_is_first=False)
    assert keys["rgb"] == ((64, 64, 3), jnp.uint8)
    assert keys["state"] == ((7,), jnp.float32)
    assert keys["actions"] == ((5,), jnp.float32)
    assert "is_first" not in keys
    assert "is_first" in dreamer_ring_keys(space, ["rgb"], [], (2,), with_is_first=True)


class _RecordingBurstFn:
    """Fake burst_fn: counts granted steps, appends rows into a numpy mirror."""

    def __init__(self):
        self.calls = []
        self.fail = False

    def __call__(self, carry, rb, staged, mask, pos, valid_n, key, validmask):
        if self.fail:
            raise RuntimeError("burst boom")
        granted = float(np.asarray(validmask).sum())
        self.calls.append(
            {
                "granted": granted,
                "rows": int(np.asarray(mask).sum()),
                "upload_rows": int(np.asarray(mask).shape[0]),
                "staged_shape": {k: staged[k].shape for k in staged},
            }
        )
        return carry + granted, rb, (jnp.float32(granted),)


def _runner(burst_fn, n_envs=2, capacity=8, grad_chunk=2, stage_max=6, seq_len=2):
    keys = {"obs": ((1,), jnp.float32)}
    rb_dev = {"obs": jnp.zeros((capacity, n_envs, 1), jnp.float32)}
    return BurstRunner(
        burst_fn, jnp.float32(0.0), rb_dev, keys,
        n_envs=n_envs, capacity=capacity, grad_chunk=grad_chunk,
        stage_max=stage_max, seq_len=seq_len, params_of=lambda c: c,
    )


def _wait(pred, timeout=5.0):
    t0 = time.time()
    while not pred():
        if time.time() - t0 > timeout:
            raise AssertionError("timed out waiting for burst worker")
        time.sleep(0.01)


class TestBurstRunner:
    def test_flush_holds_grants_until_windows_exist(self):
        fn = _RecordingBurstFn()
        r = _runner(fn, seq_len=4)
        r.stage_step({"obs": np.ones((1, 2, 1), np.float32)})
        # 1 row < seq_len 4 -> append-only burst, no grants consumed
        assert r.flush(jax.random.PRNGKey(0), grant_backlog=5) == 0
        _wait(lambda: len(fn.calls) == 1)
        assert fn.calls[0]["granted"] == 0.0
        for _ in range(4):
            r.stage_step({"obs": np.ones((1, 2, 1), np.float32)})
        assert r.flush(jax.random.PRNGKey(1), grant_backlog=5) == 2  # capped at grad_chunk
        _wait(lambda: len(fn.calls) == 2)
        assert fn.calls[1]["granted"] == 2.0
        assert r.close() is not None

    def test_ring_heads_advance_with_ragged_resets(self):
        fn = _RecordingBurstFn()
        r = _runner(fn)
        r.stage_step({"obs": np.ones((1, 2, 1), np.float32)})
        r.stage_reset({"obs": np.ones((1, 1, 1), np.float32)}, [1])  # env 1 only
        r.flush(jax.random.PRNGKey(0), grant_backlog=0)
        assert r.dev_pos.tolist() == [1, 2]
        assert r.dev_valid.tolist() == [1, 2]
        assert r.staged_count == 0
        r.close()

    def test_patch_last_edits_most_recent_row(self):
        fn = _RecordingBurstFn()
        r = _runner(fn)
        r.stage_step({"obs": np.ones((1, 2, 1), np.float32)})
        r.patch_last(0, {"obs": 9.0})
        row, _mask = r._staged[-1]
        assert row["obs"][0, 0] == 9.0 and row["obs"][1, 0] == 1.0
        r.close()

    def test_worker_crash_escalates_through_the_ladder(self):
        """A persistently-failing burst step exhausts the restart budget and
        surfaces as a TYPED supervision error on a later flush — the
        supervised replacement of the old park-and-resurface semantics."""
        import warnings

        from sheeprl_tpu.fault.supervisor import AllWorkersDeadError

        fn = _RecordingBurstFn()
        fn.fail = True
        keys = {"obs": ((1,), jnp.float32)}
        rb_dev = {"obs": jnp.zeros((8, 2, 1), jnp.float32)}
        r = BurstRunner(
            fn, jnp.float32(0.0), rb_dev, keys,
            n_envs=2, capacity=8, grad_chunk=2, stage_max=6, seq_len=2,
            params_of=lambda c: c,
            supervisor_cfg={"backoff": 0.0, "max_restarts": 1, "escalation": "degrade"},
        )
        r.stage_step({"obs": np.ones((1, 2, 1), np.float32)})
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)  # restart/degrade announcements
            with pytest.raises(AllWorkersDeadError):
                for _ in range(100):  # crash -> restart -> crash -> degraded -> typed error
                    r.flush(jax.random.PRNGKey(0), grant_backlog=0)
                    time.sleep(0.05)

    def test_kill_thread_chaos_is_restarted_not_silent(self):
        """The satellite regression: ``ThreadKilled`` (a BaseException the old
        raw daemon worker died SILENTLY on — submits then blocked forever)
        now restarts through the supervisor, the in-flight burst is
        re-dispatched, and every staged burst still lands."""
        from sheeprl_tpu.fault import inject

        fn = _RecordingBurstFn()
        keys = {"obs": ((1,), jnp.float32)}
        rb_dev = {"obs": jnp.zeros((8, 2, 1), jnp.float32)}
        r = BurstRunner(
            fn, jnp.float32(0.0), rb_dev, keys,
            n_envs=2, capacity=8, grad_chunk=2, stage_max=6, seq_len=1,
            params_of=lambda c: c, supervisor_cfg={"backoff": 0.0},
        )
        inject.arm("burst.trainer.step", action="kill-thread", at=2)
        try:
            with pytest.warns(UserWarning, match="burst-trainer.*restarting"):
                for i in range(3):
                    r.stage_step({"obs": np.ones((1, 2, 1), np.float32)})
                    r.flush(jax.random.PRNGKey(i), grant_backlog=1)
                # hit 2 kills the worker BEFORE dispatching burst 2; the
                # restarted generation re-dispatches it from shared state.
                # Detection runs at the CALLER's cadence (supervisor design),
                # so drive check() while waiting — the env loop's flush/submit
                # calls play this role in the real wiring.
                t0 = time.time()
                while len(fn.calls) < 3:
                    assert time.time() - t0 < 10.0, f"bursts never recovered: {len(fn.calls)}/3"
                    r._thread.check()
                    time.sleep(0.02)
        finally:
            inject.reset()
        assert r._thread.supervisor.worker("burst-trainer").restarts == 1
        assert [c["rows"] for c in fn.calls] == [2, 2, 2]  # nothing lost
        r.close()

    def test_supervised_snapshot_refresh_recovers_from_kill(self):
        """A killed device→host pull no longer freezes the host policy at its
        last version: the supervised refresh worker restarts and re-runs the
        retained pending pull."""
        from sheeprl_tpu.fault import inject
        from sheeprl_tpu.fault.supervisor import Supervisor

        params = {"w": jnp.ones(4)}
        snap = HostSnapshot(lambda p: p, params)
        sup = Supervisor(backoff=0.0, name="snap-test")
        snap.attach_supervisor(sup)
        inject.arm("burst.snapshot.refresh", action="kill-thread", at=1)
        try:
            assert snap.refresh_async({"w": jnp.full((4,), 5.0)})
            polled = None
            with pytest.warns(UserWarning, match="snapshot-refresh.*restarting"):
                t0 = time.time()
                while polled is None:
                    assert time.time() - t0 < 10.0, "refresh never recovered"
                    sup.check()
                    time.sleep(0.02)
                    polled = snap.poll()
        finally:
            inject.reset()
            sup.join()
        np.testing.assert_allclose(np.asarray(polled["w"]), 5.0, rtol=1e-2)

    def test_stage_buckets_size_each_upload(self):
        fn = _RecordingBurstFn()
        keys = {"obs": ((1,), jnp.float32)}
        rb_dev = {"obs": jnp.zeros((16, 2, 1), jnp.float32)}
        r = BurstRunner(
            fn, jnp.float32(0.0), rb_dev, keys,
            n_envs=2, capacity=16, grad_chunk=2, stage_max=12, seq_len=1,
            params_of=lambda c: c, stage_buckets=(3, 6),
        )
        # 2 staged rows -> smallest bucket (3); 5 rows -> next bucket (6);
        # 8 rows -> the implicit stage_max fallback bucket (12). Data beyond
        # the staged rows must be zero padding, never stale rows.
        for i, n_rows in enumerate((2, 5, 8)):
            for _ in range(n_rows):
                r.stage_step({"obs": np.ones((1, 2, 1), np.float32)})
            r.flush(jax.random.PRNGKey(n_rows), grant_backlog=0)
            _wait(lambda: len(fn.calls) == i + 1)
        sizes = [(c["rows"] // 2, c["upload_rows"]) for c in fn.calls]
        assert sizes == [(2, 3), (5, 6), (8, 12)]
        assert all(c["staged_shape"]["obs"] == (c["upload_rows"], 2, 1) for c in fn.calls)
        r.close()

    def test_bucket_normalization_caps_and_sorts(self):
        fn = _RecordingBurstFn()
        keys = {"obs": ((1,), jnp.float32)}
        rb_dev = {"obs": jnp.zeros((16, 1, 1), jnp.float32)}
        r = BurstRunner(
            fn, jnp.float32(0.0), rb_dev, keys,
            n_envs=1, capacity=16, grad_chunk=1, stage_max=5, seq_len=1,
            params_of=lambda c: c, stage_buckets=(9, 3, 0, 3),  # >cap, dup, junk
        )
        assert r._stage_buckets == [3, 5]
        r.close()

    def test_carry_readable_while_running(self):
        fn = _RecordingBurstFn()
        r = _runner(fn, seq_len=1)
        r.stage_step({"obs": np.ones((1, 2, 1), np.float32)})
        r.flush(jax.random.PRNGKey(0), grant_backlog=2)
        _wait(lambda: len(fn.calls) == 1)
        assert float(np.asarray(r.carry)) == 2.0  # fake carry counts granted steps
        r.close()
