"""Sebulba pipeline primitives (parallel/pipeline.py): bounded-queue
back-pressure, versioned param pub-sub with the documented staleness bound,
ring-buffered staging, and Fabric device-slice partitioning."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.parallel.fabric import Fabric
from sheeprl_tpu.parallel.pipeline import (
    DoubleBufferedStager,
    ParamServer,
    PipelineStats,
    RolloutQueue,
    staleness_bound,
)


# ---------------------------------------------------------------------------
# RolloutQueue
# ---------------------------------------------------------------------------


def test_queue_backpressure_bounds_depth_under_slow_learner():
    """A deliberately slow consumer must bound the queue at its depth and the
    producer's blocked time must be charged to actor_stall_s."""
    stats = PipelineStats()
    q = RolloutQueue(depth=2, stats=stats)
    stop = threading.Event()
    produced = []

    def producer():
        for i in range(10):
            if not q.put(i, stop_event=stop):
                return
            produced.append(i)

    t = threading.Thread(target=producer)
    t.start()
    consumed = []
    for _ in range(10):
        time.sleep(0.02)  # slow learner
        consumed.append(q.get(timeout=5.0))
    t.join(timeout=5.0)
    assert consumed == list(range(10))  # FIFO, nothing lost
    assert stats.max_depth_seen <= 2
    assert stats.actor_stall_s > 0.0  # the producer was genuinely back-pressured
    assert stats.rollouts_produced == 10 and stats.rollouts_consumed == 10


def test_queue_put_unblocks_on_stop_event():
    q = RolloutQueue(depth=1)
    stop = threading.Event()
    assert q.put("a", stop_event=stop)
    result = {}

    def blocked_put():
        result["ok"] = q.put("b", stop_event=stop)

    t = threading.Thread(target=blocked_put)
    t.start()
    time.sleep(0.1)
    assert t.is_alive()  # blocked on the full queue
    stop.set()
    t.join(timeout=5.0)
    assert result["ok"] is False  # dropped, not deadlocked


def test_queue_get_records_starvation():
    stats = PipelineStats()
    q = RolloutQueue(depth=1, stats=stats)

    def late_put():
        time.sleep(0.05)
        q.put("x")

    t = threading.Thread(target=late_put)
    t.start()
    assert q.get(timeout=5.0) == "x"
    t.join()
    assert stats.learner_starved_s > 0.0


# ---------------------------------------------------------------------------
# ParamServer
# ---------------------------------------------------------------------------


def test_param_server_newest_wins_and_cadence():
    ps = ParamServer({"w": 0}, publish_every=2)
    assert ps.version == 0
    assert not ps.maybe_publish(1, {"w": 1})  # update 1 of 2: no publish
    assert ps.maybe_publish(2, {"w": 2})
    assert ps.version == 1
    v, p = ps.pull()
    assert (v, p["w"]) == (1, 2)  # newest wins, intermediate never visible


def test_param_server_caches_per_device():
    dev = jax.devices("cpu")[0]
    ps = ParamServer({"w": jnp.ones((4,))})
    ps.publish({"w": jnp.full((4,), 2.0)})
    v1, p1 = ps.pull(dev)
    v2, p2 = ps.pull(dev)
    assert v1 == v2 == 1
    assert p1 is p2  # second pull of the same version is the cached placement
    np.testing.assert_allclose(np.asarray(p1["w"]), 2.0)


def test_staleness_bound_holds_under_slow_learner():
    """Single fast actor against a deliberately slow learner publishing every
    K updates: the version gap between the learner's live params and the
    params a consumed rollout was collected under must respect
    staleness_bound(). With one actor the bound is exact (FIFO: only items
    enqueued before ours — at most queue_depth + 1 in flight — can train
    ahead of it); with several actors it is the steady-state bound, racy to
    assert under arbitrary thread scheduling."""
    depth, K = 2, 2
    bound = staleness_bound(depth, 1, K)
    stats = PipelineStats()
    q = RolloutQueue(depth, stats=stats)
    ps = ParamServer({"step": 0}, publish_every=K, stats=stats)
    ps.publish({"step": 0})
    stop = threading.Event()

    def actor():
        while not stop.is_set():
            v, _p = ps.pull()  # newest-wins: staleness 0 at rollout start
            if not q.put({"version": v}, stop_event=stop):
                return

    t = threading.Thread(target=actor)
    t.start()
    max_staleness = 0
    for update in range(1, 40):
        item = q.get(timeout=5.0)
        time.sleep(0.005)  # deliberately slow learner
        ps.maybe_publish(update, {"step": update})
        staleness = ps.version - item["version"]
        max_staleness = max(max_staleness, staleness)
    stop.set()
    q.drain()
    t.join(timeout=5.0)
    assert max_staleness <= bound, f"staleness {max_staleness} exceeded bound {bound}"
    assert max_staleness > 0  # the pipeline actually ran ahead of the actor


def test_staleness_bound_formula():
    assert staleness_bound(2, 2, 1) == 5
    assert staleness_bound(2, 3, 2) == 3
    assert staleness_bound(1, 1, 4) == 1


# ---------------------------------------------------------------------------
# DoubleBufferedStager
# ---------------------------------------------------------------------------


def test_stager_source_arrays_immediately_reusable():
    """The caller's arrays (replay-buffer views) may be overwritten right
    after stage(); the staged device values must not change."""
    fabric = Fabric(devices=1, accelerator="cpu")
    stager = DoubleBufferedStager(fabric.data_sharding, slots=3)
    src = {"a": np.arange(8, dtype=np.float32), "b": np.ones((8, 2), np.float32)}
    staged = stager.stage(src)
    src["a"][:] = -1.0  # scribble over the source, as the next rollout would
    src["b"][:] = -1.0
    np.testing.assert_allclose(np.asarray(staged["a"]), np.arange(8, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(staged["b"]), 1.0)


def test_stager_ring_keeps_in_flight_rollouts_intact():
    """Holding as many staged rollouts as the ring has slots must be safe —
    the slab behind each is only recycled after `slots` later stagings."""
    fabric = Fabric(devices=1, accelerator="cpu")
    slots = 4
    stager = DoubleBufferedStager(fabric.data_sharding, slots=slots)
    held = []
    for i in range(slots):
        held.append(stager.stage({"x": np.full((4,), float(i), np.float32)}))
    for i, staged in enumerate(held):
        np.testing.assert_allclose(np.asarray(staged["x"]), float(i))


def test_stager_passes_device_leaves_through():
    """Already-on-device leaves (GAE outputs on the actor device) skip the
    slab copy and still land under the target sharding."""
    fabric = Fabric(devices=1, accelerator="cpu")
    stager = DoubleBufferedStager(fabric.data_sharding, slots=2)
    dev_leaf = jnp.arange(6, dtype=jnp.float32)
    staged = stager.stage({"host": np.zeros((6,), np.float32), "dev": dev_leaf})
    np.testing.assert_allclose(np.asarray(staged["dev"]), np.arange(6))
    assert staged["dev"].sharding.is_equivalent_to(fabric.data_sharding, ndim=1)


# ---------------------------------------------------------------------------
# Fabric.partition (device-slice split)
# ---------------------------------------------------------------------------


def test_partition_disjoint_slices():
    fabric = Fabric(devices=4, accelerator="cpu")
    actor, learner = fabric.partition(1)
    assert len(actor.devices) == 1 and len(learner.devices) == 3
    assert set(actor.devices).isdisjoint(learner.devices)
    assert learner.devices[0] is fabric.devices[0]  # learner keeps device 0
    assert learner.mesh.axis_names == ("dp",)
    assert learner.callbacks == fabric.callbacks and actor.callbacks == []


def test_partition_auto_single_device_time_slices():
    fabric = Fabric(devices=1, accelerator="cpu")
    actor, learner = fabric.partition("auto")
    assert len(learner.devices) == 1 and len(actor.devices) == 1
    assert actor.devices[0] is learner.devices[0]  # shared chip


def test_partition_auto_multi_device_dedicates_one_actor_chip():
    fabric = Fabric(devices=2, accelerator="cpu")
    actor, learner = fabric.partition("auto")
    assert len(actor.devices) == 1 and len(learner.devices) == 1
    assert actor.devices[0] is not learner.devices[0]


def test_partition_rejects_consuming_all_devices():
    fabric = Fabric(devices=2, accelerator="cpu")
    with pytest.raises(ValueError, match="learner device"):
        fabric.partition(2)


def test_partition_reresolves_auto_wire_dtype():
    """The gradient collective runs on the LEARNER mesh: an auto-resolved
    bf16 wire (full fabric had 2 devices) must drop back to f32 when the
    carved learner mesh is a single device (no wire), and stay bf16 when the
    learner keeps several."""
    from sheeprl_tpu.parallel.comm import get_grad_reduce_dtype

    f = Fabric.from_config({"devices": 2, "accelerator": "cpu"})
    assert get_grad_reduce_dtype() == jnp.bfloat16
    f.partition("auto")  # learner = 1 device
    assert get_grad_reduce_dtype() is None

    f8 = Fabric.from_config({"devices": 8, "accelerator": "cpu"})
    f8.partition(1)  # learner = 7 devices: the wire is real
    assert get_grad_reduce_dtype() == jnp.bfloat16


def test_partition_inherits_precision():
    fabric = Fabric(devices=2, accelerator="cpu", precision="bf16-mixed")
    actor, learner = fabric.partition(1)
    assert actor.precision == fabric.precision
    assert learner.precision == fabric.precision
