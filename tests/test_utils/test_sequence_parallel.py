"""Sequence-parallel attention schedules vs the single-device reference, on
the 8-device virtual CPU mesh (the ``sp`` axis analogue of an ICI ring)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sheeprl_tpu.ops.attention import reference_attention
from sheeprl_tpu.parallel.sequence import make_ring_attention, make_ulysses_attention

N_DEV = 8
B, T, H, D = 2, 64, 8, 16


@pytest.fixture(scope="module")
def mesh():
    devices = np.asarray(jax.devices()[:N_DEV])
    return Mesh(devices, ("sp",))


def _qkv(seed):
    rng = np.random.default_rng(seed)
    return tuple(rng.normal(size=(B, T, H, D)).astype(np.float32) * 0.5 for _ in range(3))


def _shard(mesh, *arrays):
    sharding = NamedSharding(mesh, P(None, "sp"))
    return tuple(jax.device_put(a, sharding) for a in arrays)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_ring_attention_matches_reference(mesh, causal):
    q, k, v = _qkv(0)
    want = np.asarray(reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
    fn = make_ring_attention(mesh, causal=causal)
    got = np.asarray(fn(*_shard(mesh, q, k, v)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_ulysses_attention_matches_reference(mesh, causal):
    q, k, v = _qkv(1)
    want = np.asarray(reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
    fn = make_ulysses_attention(mesh, causal=causal)
    got = np.asarray(fn(*_shard(mesh, q, k, v)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_output_stays_sharded(mesh):
    q, k, v = _shard(mesh, *_qkv(2))
    out = make_ring_attention(mesh)(q, k, v)
    assert out.sharding.spec == P(None, "sp")
    assert out.shape == (B, T, H, D)


def test_ulysses_requires_divisible_heads(mesh):
    rng = np.random.default_rng(3)
    bad = tuple(rng.normal(size=(B, T, 6, D)).astype(np.float32) for _ in range(3))  # 6 heads over 8 devices
    fn = make_ulysses_attention(mesh)
    with pytest.raises(ValueError, match="divisible"):
        fn(*_shard(mesh, *bad))


def test_ring_attention_gradients_flow(mesh):
    """The ring schedule must stay differentiable (actor-through-imagination
    style backprop for a transformer world model)."""
    q, k, v = _shard(mesh, *_qkv(4))
    fn = make_ring_attention(mesh, causal=True)

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        arr = np.asarray(g)
        assert np.isfinite(arr).all()
        assert np.abs(arr).max() > 0
