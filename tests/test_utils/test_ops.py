import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops import gae, lambda_returns, symexp, symlog, two_hot_decoder, two_hot_encoder


def _reference_gae(rewards, values, dones, next_value, gamma, lam):
    """Direct port of the reference python loop (utils.py:64-101) for oracle
    comparison."""
    T = rewards.shape[0]
    lastgaelam = 0
    nextvalues = next_value
    not_dones = 1.0 - dones
    nextnonterminal = not_dones[-1]
    advantages = np.zeros_like(rewards)
    for t in reversed(range(T)):
        if t < T - 1:
            nextnonterminal = not_dones[t]
            nextvalues = values[t + 1]
        delta = rewards[t] + nextvalues * nextnonterminal * gamma - values[t]
        advantages[t] = lastgaelam = delta + nextnonterminal * lastgaelam * gamma * lam
    return advantages + values, advantages


def test_gae_matches_reference_loop():
    rng = np.random.default_rng(0)
    T, B = 16, 4
    rewards = rng.normal(size=(T, B, 1)).astype(np.float32)
    values = rng.normal(size=(T, B, 1)).astype(np.float32)
    dones = (rng.random((T, B, 1)) < 0.15).astype(np.float32)
    next_value = rng.normal(size=(B, 1)).astype(np.float32)
    ret_ref, adv_ref = _reference_gae(rewards, values, dones, next_value, 0.99, 0.95)
    ret, adv = jax.jit(lambda *a: gae(*a, gamma=0.99, gae_lambda=0.95))(rewards, values, dones, next_value)
    np.testing.assert_allclose(np.asarray(adv), adv_ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(ret), ret_ref, rtol=1e-4, atol=1e-4)


def test_symlog_symexp_inverse():
    x = jnp.array([-100.0, -1.0, 0.0, 0.5, 300.0])
    np.testing.assert_allclose(np.asarray(symexp(symlog(x))), np.asarray(x), rtol=1e-5, atol=1e-5)


def test_two_hot_roundtrip():
    x = jnp.array([[0.0], [1.3], [-7.25], [299.0], [-300.0]])
    enc = two_hot_encoder(x, support_range=300)
    assert enc.shape == (5, 601)
    np.testing.assert_allclose(np.asarray(enc.sum(-1)), 1.0, rtol=1e-5)
    dec = two_hot_decoder(enc, support_range=300)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(x), atol=1e-4)


def test_two_hot_even_buckets_raises():
    with pytest.raises(ValueError):
        two_hot_encoder(jnp.zeros((1, 1)), support_range=1, num_buckets=4)


def test_lambda_returns_terminal():
    T, B = 8, 2
    rewards = jnp.ones((T, B, 1))
    values = jnp.zeros((T, B, 1))
    continues = jnp.ones((T, B, 1)) * 0.99
    lr = lambda_returns(rewards, values, continues, lmbda=0.95)
    assert lr.shape == (T, B, 1)
    # earlier steps accumulate more discounted reward
    assert float(lr[0, 0, 0]) > float(lr[-1, 0, 0])
