"""Model-manager logic against a faked ``mlflow`` module (the real package is
an optional extra; the reference runs its suite against a live `mlflow ui` —
here the registry/selection logic is what needs coverage, not the server).
"""

import sys
import types
from types import SimpleNamespace

import pytest


class _FakeClient:
    def __init__(self, runs, artifacts):
        self._runs = runs
        self._artifacts = artifacts
        self.registered = []

    def get_experiment_by_name(self, name):
        return SimpleNamespace(experiment_id="exp0") if name == "exp" else None

    def search_runs(self, experiment_ids, page_token=None):
        if isinstance(self._runs, dict):  # paginated: token -> page
            return self._runs[page_token]
        return self._runs

    def list_artifacts(self, run_id):
        self.artifact_calls = getattr(self, "artifact_calls", 0) + 1
        return [SimpleNamespace(path=p) for p in self._artifacts.get(run_id, [])]

    def update_model_version(self, name, version, description):
        pass


def _run(run_id, metrics):
    return SimpleNamespace(info=SimpleNamespace(run_id=run_id), data=SimpleNamespace(metrics=metrics))


@pytest.fixture()
def manager(monkeypatch):
    fake = types.ModuleType("mlflow")
    fake.set_tracking_uri = lambda uri: None
    fake.register_model = lambda uri, name, tags=None: SimpleNamespace(version=1, source=uri, name=name)
    fake.MlflowClient = lambda: None
    monkeypatch.setitem(sys.modules, "mlflow", fake)
    import sheeprl_tpu.utils.mlflow as m

    monkeypatch.setattr(m, "_IS_MLFLOW_AVAILABLE", True)

    runs = [
        _run("r1", {"Test/cumulative_reward": 10.0}),
        _run("r2", {"Test/cumulative_reward": 99.0}),  # best, has artifact
        _run("r3", {"Test/cumulative_reward": 500.0}),  # best metric, NO artifact
        _run("r4", {}),  # no metric
    ]
    artifacts = {"r1": ["agent"], "r2": ["agent"], "r4": ["agent"]}
    mgr = m.MlflowModelManager.__new__(m.MlflowModelManager)
    mgr.fabric = None
    mgr.client = _FakeClient(runs, artifacts)
    return mgr


MODELS_INFO = {"agent": {"path": "agent", "name": "best_agent", "description": "d", "tags": {}}}


def test_register_best_models_picks_best_scored_run_with_artifact(manager):
    out = manager.register_best_models("exp", MODELS_INFO)
    # r3 has the best metric but no artifact; r2 wins among eligible runs.
    assert out["agent"].source == "runs:/r2/agent"


def test_register_best_models_min_mode(manager):
    out = manager.register_best_models("exp", MODELS_INFO, mode="min")
    assert out["agent"].source == "runs:/r1/agent"


def test_register_best_models_no_experiment(manager):
    assert manager.register_best_models("nope", MODELS_INFO) is None


def test_register_best_models_no_eligible_run(manager):
    out = manager.register_best_models("exp", {"agent": {"path": "missing", "name": "x", "tags": {}}})
    assert out is None


def test_register_best_models_bad_mode(manager):
    with pytest.raises(ValueError):
        manager.register_best_models("exp", MODELS_INFO, mode="avg")


class _Page(list):
    def __init__(self, runs, token):
        super().__init__(runs)
        self.token = token


def test_register_best_models_paginates(manager):
    # best run sits on the SECOND page; artifact lookups are skipped for
    # runs that can't beat the current best
    manager.client._runs = {
        None: _Page([_run("r1", {"Test/cumulative_reward": 10.0})], "page2"),
        "page2": _Page([_run("r2", {"Test/cumulative_reward": 99.0}), _run("r5", {"Test/cumulative_reward": 1.0})], None),
    }
    out = manager.register_best_models("exp", MODELS_INFO)
    assert out["agent"].source == "runs:/r2/agent"
    assert manager.client.artifact_calls == 2  # r1 + r2; r5 is pre-filtered
