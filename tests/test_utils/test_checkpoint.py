"""Checkpoint IO: orbax-array + pickled-structure format round-trips."""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sheeprl_tpu.utils.checkpoint import load_state, save_state


def _state():
    params = {"dense": {"kernel": jnp.ones((3, 4)), "bias": jnp.zeros(4)}}
    tx = optax.adam(1e-3)
    return {
        "agent": params,
        "optimizer": tx.init(params),
        "iter_num": 7,
        "ratio": {"ratio": 0.5, "prev": 3.0},
        "scheduler": None,
        "batch_size": 16,
    }


def test_round_trip_preserves_structure_and_values(tmp_path):
    path = tmp_path / "ckpt_7_0.ckpt"
    state = _state()
    save_state(path, state)
    # arrays live in the orbax sidecar, the state file stays tiny
    assert (tmp_path / "ckpt_7_0.ckpt.arrays").is_dir()
    assert path.stat().st_size < 10_000

    loaded = load_state(path)
    assert loaded["iter_num"] == 7 and loaded["batch_size"] == 16
    assert loaded["ratio"] == {"ratio": 0.5, "prev": 3.0}
    assert loaded["scheduler"] is None
    np.testing.assert_array_equal(loaded["agent"]["dense"]["kernel"], np.ones((3, 4)))
    # optax namedtuple structure survives exactly: tree.map against a live
    # template must not raise (the round-1 fragility this format removes)
    template = optax.adam(1e-3).init({"dense": {"kernel": jnp.ones((3, 4)), "bias": jnp.zeros(4)}})
    jax.tree.map(lambda t, s: np.asarray(s, dtype=np.asarray(t).dtype), template, loaded["optimizer"])


def test_replay_buffer_sidecar(tmp_path):
    from sheeprl_tpu.data.buffers import ReplayBuffer

    rb = ReplayBuffer(8, 2, obs_keys=("state",))
    rb.add(
        {
            "state": np.ones((1, 2, 3), np.float32),
            "terminated": np.zeros((1, 2, 1), np.float32),
            "truncated": np.zeros((1, 2, 1), np.float32),
        }
    )
    path = tmp_path / "ckpt_1_0.ckpt"
    save_state(path, {"iter_num": 1, "rb": rb})
    assert (tmp_path / "ckpt_1_0.ckpt.rb").exists()

    loaded = load_state(path)
    assert isinstance(loaded["rb"], ReplayBuffer)
    np.testing.assert_array_equal(loaded["rb"]["state"][0], np.ones((2, 3), np.float32))


def test_legacy_pickle_checkpoints_still_load(tmp_path):
    path = tmp_path / "old.ckpt"
    legacy = {"agent": {"w": np.arange(4)}, "iter_num": 3}
    with open(path, "wb") as f:
        pickle.dump(legacy, f)
    loaded = load_state(path)
    assert loaded["iter_num"] == 3
    np.testing.assert_array_equal(loaded["agent"]["w"], np.arange(4))


def test_overwrite_same_path(tmp_path):
    path = tmp_path / "ckpt.ckpt"
    save_state(path, {"agent": {"w": jnp.zeros(2)}, "iter_num": 1})
    save_state(path, {"agent": {"w": jnp.ones(2)}, "iter_num": 2})
    loaded = load_state(path)
    assert loaded["iter_num"] == 2
    np.testing.assert_array_equal(loaded["agent"]["w"], np.ones(2))
