"""REAL multi-process distributed tests (VERDICT: every ``process_count > 1``
branch was unexercised). Two OS processes, each owning one virtual CPU
device, form a 2-process JAX distributed runtime: the global mesh spans both
processes, `psum` rides the (gRPC) cross-process transport, and the fabric's
control-plane helpers (``broadcast_obj``, ``barrier``, ``local_device``) run
their multi-process paths.

This is the CPU analogue of a 2-host TPU pod: one process per host,
``jax.distributed.initialize`` wiring DCN (SURVEY §2.4).
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")
from sheeprl_tpu.parallel.distributed import maybe_init

maybe_init()  # env-var driven: SHEEPRL_COORDINATOR/NUM_PROCESSES/PROCESS_ID

import jax.numpy as jnp
import numpy as np
from sheeprl_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
pid = jax.process_index()

from sheeprl_tpu.parallel.fabric import Fabric

fabric = Fabric(devices=2)
assert fabric.world_size == 2
# local_device must be addressable by THIS process (the code-review finding)
assert fabric.local_device.process_index == pid

# control plane: object broadcast from process 0 + barrier
obj = fabric.broadcast_obj(np.asarray([42.0 + pid]), src=0)
assert float(np.asarray(obj)[0]) == 42.0, obj
fabric.barrier()

# data plane: a psum over the 2-process mesh via shard_map, fed through the
# fabric's multi-host shard_data/put_replicated paths
def local_sum(x, w):
    return jax.lax.psum(x * w, "dp")

sharded = shard_map(
    local_sum, mesh=fabric.mesh, in_specs=(P("dp"), P()), out_specs=P(), check_vma=False
)
host_local = np.full((1,), float(pid + 1), np.float32)  # proc0: [1], proc1: [2]
global_arr = fabric.shard_data(host_local)
weight = fabric.put_replicated(np.full((1,), 2.0, np.float32))
total = jax.jit(sharded)(global_arr, weight)
np.testing.assert_allclose(np.asarray(total), [6.0])

print(f"proc {pid} OK")
"""


# The real v5e-pod shape: each process owns FOUR devices, so the global
# mesh is 2 hosts x 4 local devices = 8, and the fabric's
# ``shard_data``/``put_replicated`` global-array assembly runs its
# multi-DEVICE-per-process paths (host-local (4, ...) blocks -> one global
# (8, ...) array whose addressable shards stay local).
_WORKER_2x4 = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")
from sheeprl_tpu.parallel.distributed import maybe_init

maybe_init()

import jax.numpy as jnp
import numpy as np
from sheeprl_tpu.parallel.compat import shard_map
from jax.sharding import PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 4, jax.local_device_count()
pid = jax.process_index()

from sheeprl_tpu.parallel.fabric import Fabric

fabric = Fabric(devices=8)
assert fabric.world_size == 8
assert fabric.local_device.process_index == pid

# control plane from a non-zero source rank
obj = fabric.broadcast_obj(np.asarray([7.0 + pid]), src=1)
assert float(np.asarray(obj)[0]) == 8.0, obj
fabric.barrier()

# shard_data: this process contributes rows [4*pid, 4*pid+4) of the global
# batch; all_gather reassembles the full batch so the placement is checked
# value-for-value, not just by shape.
host_local = np.stack(
    [np.full((2,), 4 * pid + d, np.float32) for d in range(4)]
)  # (4, 2) local block
global_arr = fabric.shard_data(host_local)
assert global_arr.shape == (8, 2), global_arr.shape

def gather(x):
    return jax.lax.all_gather(x, "dp", tiled=True)

gathered = jax.jit(
    shard_map(gather, mesh=fabric.mesh, in_specs=P("dp"), out_specs=P(), check_vma=False)
)(global_arr)
np.testing.assert_allclose(np.asarray(jax.device_get(gathered))[:, 0], np.arange(8, dtype=np.float32))

# put_replicated + cross-process psum == the single-process analytic value
def local_sum(x, w):
    return jax.lax.psum(x * w, "dp")

weight = fabric.put_replicated(np.full((2,), 3.0, np.float32))
total = jax.jit(
    shard_map(local_sum, mesh=fabric.mesh, in_specs=(P("dp"), P()), out_specs=P(), check_vma=False)
)(global_arr, weight)
np.testing.assert_allclose(np.asarray(total), np.full((1, 2), 3.0 * sum(range(8))))

print(f"proc {pid} OK")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(worker_src: str, devices_per_process: int) -> None:
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices_per_process}",
                "SHEEPRL_COORDINATOR": f"127.0.0.1:{port}",
                "SHEEPRL_NUM_PROCESSES": "2",
                "SHEEPRL_PROCESS_ID": str(pid),
            }
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", worker_src],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            )
        )
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-4000:]}"
        assert f"proc {pid} OK" in out


@pytest.mark.slow
def test_two_process_mesh_psum_and_control_plane(tmp_path):
    _run_workers(_WORKER, devices_per_process=1)


@pytest.mark.slow
def test_two_process_four_devices_each_global_assembly(tmp_path):
    """2 processes x 4 virtual devices each — the v5e-pod shape. Exercises
    ``shard_data``/``put_replicated`` global-array assembly across
    multi-device processes and checks a cross-process ``psum`` against the
    analytic single-process value (VERDICT r3 weak-item 6)."""
    _run_workers(_WORKER_2x4, devices_per_process=4)
