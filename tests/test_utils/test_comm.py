"""fabric.grad_reduce_dtype: the bf16 gradient-collective wire dtype
(parallel/comm.py). The bf16 path must (a) actually reduce in bf16 — halving
the dominant DP collective's bytes, the point of the knob — while returning
f32 grads close to the exact mean, and (b) train end-to-end through a real
main on a 2-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.parallel.comm import get_grad_reduce_dtype, pmean_grads, set_grad_reduce_dtype
from sheeprl_tpu.parallel.fabric import Fabric
from sheeprl_tpu.parallel.compat import shard_map


@pytest.fixture(autouse=True)
def _restore_dtype():
    yield
    set_grad_reduce_dtype("float32")


def _reduce(tree):
    fabric = Fabric(devices=2)

    def body(t):
        return pmean_grads(t, "dp")

    fn = jax.jit(
        shard_map(body, mesh=fabric.mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False)
    )
    return fn(tree)


def test_f32_default_is_exact_mean():
    set_grad_reduce_dtype("float32")
    x = jnp.asarray(np.stack([np.full((3,), 1.0, np.float32), np.full((3,), 3.0, np.float32)]))
    out = _reduce({"g": x})
    np.testing.assert_allclose(np.asarray(out["g"]), 2.0)


def test_bf16_reduces_on_the_wire_but_returns_f32():
    set_grad_reduce_dtype("bfloat16")
    assert get_grad_reduce_dtype() == jnp.bfloat16
    x = jnp.asarray(np.stack([np.full((64,), 1.0, np.float32), np.full((64,), 3.0, np.float32)]))
    out = _reduce({"g": x})
    assert out["g"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out["g"]), 2.0, rtol=1e-2)

    # The wire-dtype cast must be emitted ahead of the collective. On TPU
    # the all-reduce itself then runs in bf16; XLA:CPU *promotes* bf16
    # all-reduces to f32 (no native bf16 reduction on host), so on this
    # backend we assert the bf16 converts feeding the collective instead —
    # the dtype decision is made at trace time, the promotion at lowering.
    def body(t):
        return pmean_grads(t, "dp")

    fabric = Fabric(devices=2)
    lowered = jax.jit(
        shard_map(body, mesh=fabric.mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False)
    ).lower({"g": x})
    hlo = lowered.compile().as_text()
    bf16_converts = [l for l in hlo.splitlines() if "bf16[" in l and "convert" in l]
    assert bf16_converts, "no bf16 wire-dtype converts in compiled HLO"


def test_bf16_close_to_f32_on_realistic_grads():
    rng = np.random.default_rng(0)
    shards = jnp.asarray(rng.normal(scale=1e-2, size=(2, 4096)).astype(np.float32))
    set_grad_reduce_dtype("float32")
    exact = np.asarray(_reduce(shards))
    set_grad_reduce_dtype("bfloat16")
    approx = np.asarray(_reduce(shards))
    # bf16 has ~8 mantissa bits: error is bounded relative to the INPUT
    # magnitude (1e-2 scale), not the mean — near-cancelling shard pairs make
    # the mean arbitrarily small while the rounding stays input-sized.
    np.testing.assert_allclose(approx, exact, rtol=1e-2, atol=3e-4)


def test_invalid_dtype_rejected():
    with pytest.raises(ValueError, match="grad_reduce_dtype"):
        set_grad_reduce_dtype("int8")


def test_ppo_trains_with_bf16_reduction(tmp_path):
    """End-to-end through the real CLI on 2 devices — from_config must apply
    the setting before the train step traces."""
    from sheeprl_tpu.cli import run

    run(
        [
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "dry_run=True",
            "buffer.memmap=False",
            "fabric.devices=2",
            "fabric.grad_reduce_dtype=bfloat16",
            "metric.log_level=0",
            "checkpoint.save_last=False",
            f"log_root={tmp_path}/logs",
            "algo.run_test=False",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.mlp_keys.encoder=[state]",
        ]
    )


def test_from_config_auto_defaults_bf16_on_multi_device_mesh():
    """Round-4 backlog: bf16 is the DEFAULT wire dtype wherever there is an
    actual wire (mesh > 1 device); `fabric.grad_reduce_dtype=float32` is the
    exactness escape hatch."""
    Fabric.from_config({"devices": 2, "accelerator": "cpu"})
    assert get_grad_reduce_dtype() == jnp.bfloat16


def test_from_config_auto_stays_f32_on_single_device():
    """A 1-device 'collective' is a no-op: auto must not round gradients to
    bf16 for nothing."""
    Fabric.from_config({"devices": 1, "accelerator": "cpu"})
    assert get_grad_reduce_dtype() is None


def test_from_config_escape_hatch_forces_f32():
    Fabric.from_config({"devices": 2, "accelerator": "cpu", "grad_reduce_dtype": "float32"})
    assert get_grad_reduce_dtype() is None


def test_auto_default_retrace_guard():
    """The bf16 default must obey the same retrace guard as an explicit
    setting: once a train step traced under the auto-resolved bf16 wire, a
    mid-run flip warns about stale compiled steps."""
    import warnings as _w

    Fabric.from_config({"devices": 2, "accelerator": "cpu"})  # auto -> bf16, fresh run
    _reduce({"g": jnp.ones((2, 4), jnp.float32)})  # traces under bf16
    with pytest.warns(UserWarning, match="grad_reduce_dtype changed"):
        set_grad_reduce_dtype("float32")  # mid-run flip: warns
    with _w.catch_warnings():
        _w.simplefilter("error")
        # a NEW run boundary (from_config) must stay silent again
        Fabric.from_config({"devices": 2, "accelerator": "cpu"})


def test_run_boundary_does_not_false_warn(recwarn):
    """Back-to-back runs with different wire dtypes in one process (the
    dryrun harness pattern) must NOT trip the mid-run-flip warning —
    from_config marks a run boundary; only a genuine mid-run change warns."""
    import warnings

    set_grad_reduce_dtype("float32", fresh_run=True)
    _reduce({"g": jnp.ones((2, 4), jnp.float32)})  # traces under f32
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        set_grad_reduce_dtype("bfloat16", fresh_run=True)  # new run: silent

    _reduce({"g": jnp.ones((2, 4), jnp.float32)})  # traces under bf16
    with pytest.warns(UserWarning, match="grad_reduce_dtype changed"):
        set_grad_reduce_dtype("float32")  # mid-run flip: warns
