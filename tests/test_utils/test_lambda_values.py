"""Numeric cross-checks of the lambda-return recursions against slow Python
reference implementations (the formulas in
``sheeprl/algos/dreamer_v{1,2,3}/utils.py``)."""

import numpy as np

from sheeprl_tpu.algos.dreamer_v1.utils import compute_lambda_values as lambda_v1
from sheeprl_tpu.algos.dreamer_v2.utils import compute_lambda_values as lambda_v2
from sheeprl_tpu.algos.dreamer_v3.utils import compute_lambda_values as lambda_v3


def _slow_v2(rewards, values, continues, bootstrap, lmbda):
    horizon = rewards.shape[0]
    agg = bootstrap[0]
    next_vals = np.concatenate([values[1:], bootstrap], axis=0)
    inputs = rewards + continues * next_vals * (1 - lmbda)
    out = []
    for i in reversed(range(horizon)):
        agg = inputs[i] + continues[i] * lmbda * agg
        out.append(agg)
    return np.stack(list(reversed(out)), axis=0)


def _slow_v3(rewards, values, continues, lmbda):
    horizon = rewards.shape[0]
    interm = rewards + continues * values * (1 - lmbda)
    agg = values[-1]
    out = []
    for i in reversed(range(horizon)):
        agg = interm[i] + continues[i] * lmbda * agg
        out.append(agg)
    return np.stack(list(reversed(out)), axis=0)


def _slow_v1(rewards, values, continues, last_values, lmbda):
    horizon = rewards.shape[0]
    agg = np.zeros_like(last_values)
    out = []
    for step in reversed(range(horizon - 1)):
        if step == horizon - 2:
            next_values = last_values
        else:
            next_values = values[step + 1] * (1 - lmbda)
        delta = rewards[step] + next_values * continues[step]
        agg = delta + lmbda * continues[step] * agg
        out.append(agg)
    return np.stack(list(reversed(out)), axis=0)


def _rand(shape, rng):
    return rng.normal(size=shape).astype(np.float32)


def test_lambda_v2_matches_reference_formula():
    rng = np.random.default_rng(0)
    H, B = 7, 3
    rewards, values = _rand((H, B, 1), rng), _rand((H, B, 1), rng)
    continues = (rng.uniform(size=(H, B, 1)) > 0.1).astype(np.float32) * 0.99
    bootstrap = _rand((1, B, 1), rng)
    got = np.asarray(lambda_v2(rewards, values, continues, bootstrap, lmbda=0.95))
    want = _slow_v2(rewards, values, continues, bootstrap, 0.95)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_lambda_v3_matches_reference_formula():
    rng = np.random.default_rng(1)
    H, B = 6, 4
    rewards, values = _rand((H, B, 1), rng), _rand((H, B, 1), rng)
    continues = (rng.uniform(size=(H, B, 1)) > 0.1).astype(np.float32) * 0.997
    got = np.asarray(lambda_v3(rewards, values, continues, lmbda=0.95))
    want = _slow_v3(rewards, values, continues, 0.95)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_lambda_v1_matches_reference_formula():
    rng = np.random.default_rng(2)
    H, B = 8, 2
    rewards, values = _rand((H, B, 1), rng), _rand((H, B, 1), rng)
    continues = np.full((H, B, 1), 0.99, dtype=np.float32)
    last_values = values[-1]
    got = np.asarray(lambda_v1(rewards, values, continues, last_values, lmbda=0.95))
    want = _slow_v1(rewards, values, continues, last_values, 0.95)
    assert got.shape == (H - 1, B, 1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
