"""Unit tests for the config/env-driven jax.distributed bring-up
(``fabric.distributed.*`` + the ``SHEEPRL_*`` env vars) wired through BOTH
CLI entrypoints. ``jax.distributed.initialize`` is monkeypatched — the REAL
2-process bring-up is covered by ``test_multiprocess.py``."""

import pytest

import sheeprl_tpu.parallel.distributed as dist


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    """Each test starts un-initialized with a recording initialize stub."""
    calls = []

    def fake_initialize(coordinator_address=None, num_processes=None, process_id=None):
        calls.append(
            {"coordinator_address": coordinator_address, "num_processes": num_processes, "process_id": process_id}
        )

    monkeypatch.setattr(dist.jax.distributed, "initialize", fake_initialize)
    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.delenv("SHEEPRL_COORDINATOR", raising=False)
    monkeypatch.delenv("SHEEPRL_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("SHEEPRL_PROCESS_ID", raising=False)
    yield calls


def test_single_host_default_is_a_noop(_fresh):
    assert dist.maybe_init() is False
    assert dist.maybe_init({"enabled": None}) is False
    assert _fresh == []


def test_config_block_drives_init(_fresh):
    cfg = {"enabled": None, "coordinator": "10.0.0.1:1234", "num_processes": 4, "process_id": 2}
    assert dist.maybe_init(cfg) is True
    assert _fresh == [
        {"coordinator_address": "10.0.0.1:1234", "num_processes": 4, "process_id": 2}
    ]


def test_env_vars_win_over_config(_fresh, monkeypatch):
    """The pod runtime sets per-host env vars over one shared config file:
    env must win."""
    monkeypatch.setenv("SHEEPRL_COORDINATOR", "10.0.0.9:4321")
    monkeypatch.setenv("SHEEPRL_NUM_PROCESSES", "8")
    monkeypatch.setenv("SHEEPRL_PROCESS_ID", "5")
    cfg = {"coordinator": "10.0.0.1:1234", "num_processes": 4, "process_id": 2}
    assert dist.maybe_init(cfg) is True
    assert _fresh == [
        {"coordinator_address": "10.0.0.9:4321", "num_processes": 8, "process_id": 5}
    ]


def test_env_vars_alone_drive_init(_fresh, monkeypatch):
    monkeypatch.setenv("SHEEPRL_COORDINATOR", "127.0.0.1:9999")
    monkeypatch.setenv("SHEEPRL_NUM_PROCESSES", "2")
    monkeypatch.setenv("SHEEPRL_PROCESS_ID", "0")
    assert dist.maybe_init() is True
    assert _fresh[0]["coordinator_address"] == "127.0.0.1:9999"


def test_enabled_false_never_inits(_fresh, monkeypatch):
    """An operator can pin a host single-process even in a pod env."""
    monkeypatch.setenv("SHEEPRL_COORDINATOR", "127.0.0.1:9999")
    assert dist.maybe_init({"enabled": False}) is False
    assert _fresh == []


def test_enabled_true_without_coordinator_is_typed(_fresh):
    """Silently training solo on N-1 hosts is the failure mode; require the
    coordinator loudly."""
    with pytest.raises(ValueError, match="fabric.distributed.enabled=true"):
        dist.maybe_init({"enabled": True})
    assert _fresh == []


def test_second_call_is_a_noop(_fresh):
    cfg = {"coordinator": "10.0.0.1:1234", "num_processes": 2}
    assert dist.maybe_init(cfg) is True
    assert dist.maybe_init(cfg) is False
    assert len(_fresh) == 1


def test_cli_entrypoints_pass_the_config_block():
    """Both CLI bodies hand fabric.distributed to maybe_init (train via
    run_algorithm, serve via serve_algorithm) — source-level wiring check
    that survives refactors of either function."""
    import inspect

    from sheeprl_tpu import cli

    for fn in (cli.run_algorithm, cli.serve_algorithm):
        src = inspect.getsource(fn)
        assert "maybe_init" in src and "distributed" in src, fn.__name__
