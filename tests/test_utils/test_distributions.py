import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.distributions import (
    BernoulliSafeMode,
    Categorical,
    Independent,
    MSEDistribution,
    Normal,
    OneHotCategorical,
    OneHotCategoricalStraightThrough,
    SymlogDistribution,
    TanhNormal,
    TruncatedNormal,
    TwoHotEncodingDistribution,
    kl_divergence,
)


def test_normal_logprob_matches_scipy():
    from scipy.stats import norm

    d = Normal(jnp.array(1.0), jnp.array(2.0))
    x = jnp.array(0.3)
    np.testing.assert_allclose(float(d.log_prob(x)), norm.logpdf(0.3, 1.0, 2.0), rtol=1e-5)


def test_independent_reduces():
    d = Independent(Normal(jnp.zeros((4, 3)), jnp.ones((4, 3))), 1)
    assert d.log_prob(jnp.zeros((4, 3))).shape == (4,)
    assert d.entropy().shape == (4,)


def test_categorical_logprob_entropy():
    logits = jnp.array([[1.0, 2.0, 0.5]])
    d = Categorical(logits)
    probs = np.asarray(d.probs)[0]
    assert pytest.approx(float(d.entropy()[0]), rel=1e-3) == -np.sum(probs * np.log(probs))
    lp = float(d.log_prob(jnp.array([1]))[0])
    assert pytest.approx(lp, rel=1e-3) == np.log(probs[1])


def test_onehot_sample_and_mode():
    logits = jnp.array([[0.0, 5.0, 0.0]])
    d = OneHotCategorical(logits)
    s = d.sample(jax.random.PRNGKey(0))
    assert s.shape == (1, 3)
    assert float(s.sum()) == 1.0
    assert int(d.mode.argmax()) == 1


def test_onehot_unimix():
    logits = jnp.array([[100.0, 0.0, 0.0]])
    d = OneHotCategorical(logits, unimix=0.01)
    probs = np.asarray(d.probs)[0]
    assert probs[1] > 0.001  # uniform mix keeps mass everywhere


def test_straight_through_gradient_flows():
    logits = jnp.array([[0.5, -0.5]])

    def f(lo):
        d = OneHotCategoricalStraightThrough(logits=lo)
        return (d.rsample(jax.random.PRNGKey(0)) * jnp.array([1.0, 2.0])).sum()

    g = jax.grad(f)(logits)
    assert np.any(np.asarray(g) != 0)


def test_tanh_normal_bounds_and_logprob():
    d = TanhNormal(jnp.zeros((5,)), jnp.ones((5,)))
    a, lp = d.sample_and_log_prob(jax.random.PRNGKey(0))
    assert np.all(np.abs(np.asarray(a)) <= 1.0)
    assert lp.shape == (5,)
    lp2 = d.log_prob(a)
    np.testing.assert_allclose(np.asarray(lp), np.asarray(lp2), rtol=1e-3, atol=1e-4)


def test_truncated_normal_support():
    d = TruncatedNormal(jnp.zeros(()), jnp.ones(()) * 2.0, -1.0, 1.0)
    s = d.sample(jax.random.PRNGKey(0), (1000,))
    assert np.all(np.abs(np.asarray(s)) <= 1.0)
    assert np.isfinite(float(d.log_prob(jnp.array(0.5))))
    assert float(d.log_prob(jnp.array(3.0))) == -np.inf


def test_symlog_mse_distributions():
    mode = jnp.ones((2, 4))
    target = jnp.ones((2, 4)) * 2
    sd = SymlogDistribution(mode, dims=1)
    md = MSEDistribution(mode, dims=1)
    assert sd.log_prob(target).shape == (2,)
    assert md.log_prob(target).shape == (2,)
    assert float(md.log_prob(mode)[0]) == 0.0


def test_two_hot_distribution_mean_logprob():
    logits = jnp.zeros((3, 255))
    d = TwoHotEncodingDistribution(logits, dims=1, low=-20, high=20)
    assert d.mean.shape == (3, 1)
    lp = d.log_prob(jnp.array([[0.0], [1.0], [-3.0]]))
    assert lp.shape == (3,)
    # uniform logits → logprob = -log(255) spread over two buckets
    np.testing.assert_allclose(np.asarray(lp), -np.log(255), rtol=1e-4)


def test_bernoulli_safe_mode():
    d = BernoulliSafeMode(jnp.zeros((4,)))
    assert np.all(np.asarray(d.mode) == 0)


def test_kl_onehot():
    p = OneHotCategorical(jnp.array([[1.0, 0.0]]))
    q = OneHotCategorical(jnp.array([[1.0, 0.0]]))
    np.testing.assert_allclose(np.asarray(kl_divergence(p, q)), 0.0, atol=1e-6)
    r = OneHotCategorical(jnp.array([[0.0, 1.0]]))
    assert float(kl_divergence(p, r)[0]) > 0


def test_kl_independent_normal():
    p = Independent(Normal(jnp.zeros((2, 3)), jnp.ones((2, 3))), 1)
    q = Independent(Normal(jnp.ones((2, 3)), jnp.ones((2, 3))), 1)
    kl = kl_divergence(p, q)
    np.testing.assert_allclose(np.asarray(kl), 1.5, rtol=1e-5)


def test_validate_args_static_checks():
    """distribution.validate_args enables static (trace-safe) argument
    validation (reference: cfg.distribution.validate_args)."""
    import jax.numpy as jnp
    import pytest

    from sheeprl_tpu.distributions import Normal, OneHotCategorical, TruncatedNormal, set_validate_args

    set_validate_args(True)
    try:
        with pytest.raises(ValueError, match="broadcastable"):
            Normal(jnp.zeros((2, 3)), jnp.ones((4,)))
        with pytest.raises(ValueError, match="floating"):
            Normal(jnp.zeros(3, dtype=jnp.int32), jnp.ones(3))
        with pytest.raises(ValueError, match="at least 1 dim"):
            OneHotCategorical(logits=jnp.float32(0.0))
        with pytest.raises(ValueError, match="low"):
            TruncatedNormal(jnp.zeros(2), jnp.ones(2), low=1.0, high=-1.0)
        # valid constructions still pass
        Normal(jnp.zeros(3), jnp.ones(3))
    finally:
        set_validate_args(False)
    # disabled: no checks
    Normal(jnp.zeros(3, dtype=jnp.int32), jnp.ones(3))
