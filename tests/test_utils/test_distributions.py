import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.distributions import (
    BernoulliSafeMode,
    Categorical,
    Independent,
    MSEDistribution,
    Normal,
    OneHotCategorical,
    OneHotCategoricalStraightThrough,
    SymlogDistribution,
    TanhNormal,
    TruncatedNormal,
    TwoHotEncodingDistribution,
    kl_divergence,
)


def test_normal_logprob_matches_scipy():
    from scipy.stats import norm

    d = Normal(jnp.array(1.0), jnp.array(2.0))
    x = jnp.array(0.3)
    np.testing.assert_allclose(float(d.log_prob(x)), norm.logpdf(0.3, 1.0, 2.0), rtol=1e-5)


def test_independent_reduces():
    d = Independent(Normal(jnp.zeros((4, 3)), jnp.ones((4, 3))), 1)
    assert d.log_prob(jnp.zeros((4, 3))).shape == (4,)
    assert d.entropy().shape == (4,)


def test_categorical_logprob_entropy():
    logits = jnp.array([[1.0, 2.0, 0.5]])
    d = Categorical(logits)
    probs = np.asarray(d.probs)[0]
    assert pytest.approx(float(d.entropy()[0]), rel=1e-3) == -np.sum(probs * np.log(probs))
    lp = float(d.log_prob(jnp.array([1]))[0])
    assert pytest.approx(lp, rel=1e-3) == np.log(probs[1])


def test_onehot_sample_and_mode():
    logits = jnp.array([[0.0, 5.0, 0.0]])
    d = OneHotCategorical(logits)
    s = d.sample(jax.random.PRNGKey(0))
    assert s.shape == (1, 3)
    assert float(s.sum()) == 1.0
    assert int(d.mode.argmax()) == 1


def test_onehot_unimix():
    logits = jnp.array([[100.0, 0.0, 0.0]])
    d = OneHotCategorical(logits, unimix=0.01)
    probs = np.asarray(d.probs)[0]
    assert probs[1] > 0.001  # uniform mix keeps mass everywhere


def test_straight_through_gradient_flows():
    logits = jnp.array([[0.5, -0.5]])

    def f(lo):
        d = OneHotCategoricalStraightThrough(logits=lo)
        return (d.rsample(jax.random.PRNGKey(0)) * jnp.array([1.0, 2.0])).sum()

    g = jax.grad(f)(logits)
    assert np.any(np.asarray(g) != 0)


def test_tanh_normal_bounds_and_logprob():
    d = TanhNormal(jnp.zeros((5,)), jnp.ones((5,)))
    a, lp = d.sample_and_log_prob(jax.random.PRNGKey(0))
    assert np.all(np.abs(np.asarray(a)) <= 1.0)
    assert lp.shape == (5,)
    lp2 = d.log_prob(a)
    np.testing.assert_allclose(np.asarray(lp), np.asarray(lp2), rtol=1e-3, atol=1e-4)


def test_truncated_normal_support():
    d = TruncatedNormal(jnp.zeros(()), jnp.ones(()) * 2.0, -1.0, 1.0)
    s = d.sample(jax.random.PRNGKey(0), (1000,))
    assert np.all(np.abs(np.asarray(s)) <= 1.0)
    assert np.isfinite(float(d.log_prob(jnp.array(0.5))))
    assert float(d.log_prob(jnp.array(3.0))) == -np.inf


def test_symlog_mse_distributions():
    mode = jnp.ones((2, 4))
    target = jnp.ones((2, 4)) * 2
    sd = SymlogDistribution(mode, dims=1)
    md = MSEDistribution(mode, dims=1)
    assert sd.log_prob(target).shape == (2,)
    assert md.log_prob(target).shape == (2,)
    assert float(md.log_prob(mode)[0]) == 0.0


def test_two_hot_distribution_mean_logprob():
    logits = jnp.zeros((3, 255))
    d = TwoHotEncodingDistribution(logits, dims=1, low=-20, high=20)
    assert d.mean.shape == (3, 1)
    lp = d.log_prob(jnp.array([[0.0], [1.0], [-3.0]]))
    assert lp.shape == (3,)
    # uniform logits → logprob = -log(255) spread over two buckets
    np.testing.assert_allclose(np.asarray(lp), -np.log(255), rtol=1e-4)


def test_bernoulli_safe_mode():
    d = BernoulliSafeMode(jnp.zeros((4,)))
    assert np.all(np.asarray(d.mode) == 0)


def test_kl_onehot():
    p = OneHotCategorical(jnp.array([[1.0, 0.0]]))
    q = OneHotCategorical(jnp.array([[1.0, 0.0]]))
    np.testing.assert_allclose(np.asarray(kl_divergence(p, q)), 0.0, atol=1e-6)
    r = OneHotCategorical(jnp.array([[0.0, 1.0]]))
    assert float(kl_divergence(p, r)[0]) > 0


def test_kl_independent_normal():
    p = Independent(Normal(jnp.zeros((2, 3)), jnp.ones((2, 3))), 1)
    q = Independent(Normal(jnp.ones((2, 3)), jnp.ones((2, 3))), 1)
    kl = kl_divergence(p, q)
    np.testing.assert_allclose(np.asarray(kl), 1.5, rtol=1e-5)


def test_validate_args_static_checks():
    """distribution.validate_args enables static (trace-safe) argument
    validation (reference: cfg.distribution.validate_args)."""
    import jax.numpy as jnp
    import pytest

    from sheeprl_tpu.distributions import Normal, OneHotCategorical, TruncatedNormal, set_validate_args

    set_validate_args(True)
    try:
        with pytest.raises(ValueError, match="broadcastable"):
            Normal(jnp.zeros((2, 3)), jnp.ones((4,)))
        with pytest.raises(ValueError, match="floating"):
            Normal(jnp.zeros(3, dtype=jnp.int32), jnp.ones(3))
        with pytest.raises(ValueError, match="at least 1 dim"):
            OneHotCategorical(logits=jnp.float32(0.0))
        with pytest.raises(ValueError, match="low"):
            TruncatedNormal(jnp.zeros(2), jnp.ones(2), low=1.0, high=-1.0)
        # valid constructions still pass
        Normal(jnp.zeros(3), jnp.ones(3))
    finally:
        set_validate_args(False)
    # disabled: no checks
    Normal(jnp.zeros(3, dtype=jnp.int32), jnp.ones(3))


def test_bf16_params_promote_math_but_not_samples():
    """Mixed-precision policy (bf16-mixed trunks): distribution math runs in
    f32, samples keep the parameter dtype so scan carries keep bf16 avals;
    f32 parameters are untouched."""
    from sheeprl_tpu.distributions import (
        BernoulliSafeMode,
        TanhNormal,
        TwoHotEncodingDistribution,
    )

    key = jax.random.PRNGKey(0)
    logits16 = jax.random.normal(key, (4, 8)).astype(jnp.bfloat16)

    d = OneHotCategoricalStraightThrough(logits=logits16, unimix=0.01)
    s = d.rsample(key)
    assert s.dtype == jnp.bfloat16
    assert d.logits.dtype == jnp.float32
    assert d.log_prob(s).dtype == jnp.float32
    assert d.entropy().dtype == jnp.float32

    # f32 math matches an all-f32 construction to f32-roundoff of the inputs
    d32 = OneHotCategoricalStraightThrough(logits=logits16.astype(jnp.float32), unimix=0.01)
    np.testing.assert_allclose(np.asarray(d.logits), np.asarray(d32.logits), rtol=1e-6)

    n = Normal(jnp.zeros(3, jnp.bfloat16), jnp.ones(3, jnp.bfloat16))
    assert n.sample(key).dtype == jnp.bfloat16
    assert n.log_prob(n.sample(key)).dtype == jnp.float32

    t = TwoHotEncodingDistribution(jnp.zeros((4, 255), jnp.bfloat16))
    assert t.mean.dtype == jnp.float32
    assert t.log_prob(jnp.ones((4, 1))).dtype == jnp.float32

    b = BernoulliSafeMode(jnp.zeros((4,), jnp.bfloat16))
    assert b.mode.dtype == jnp.bfloat16
    assert b.log_prob(jnp.ones(4)).dtype == jnp.float32

    a, lp = TanhNormal(jnp.zeros(3, jnp.bfloat16), jnp.ones(3, jnp.bfloat16)).sample_and_log_prob(key)
    assert a.dtype == jnp.bfloat16 and lp.dtype == jnp.float32

    # greedy (mode/mean) and sampled paths must produce the SAME aval, or the
    # policy jit retraces between train and eval
    from sheeprl_tpu.distributions import TruncatedNormal

    for d in (
        Normal(jnp.zeros(3, jnp.bfloat16), jnp.ones(3, jnp.bfloat16)),
        TanhNormal(jnp.zeros(3, jnp.bfloat16), jnp.ones(3, jnp.bfloat16)),
        TruncatedNormal(jnp.zeros(3, jnp.bfloat16), jnp.ones(3, jnp.bfloat16)),
        OneHotCategoricalStraightThrough(logits=logits16),
    ):
        assert d.mode.dtype == d.sample(key).dtype == jnp.bfloat16, type(d).__name__
        assert d.mean.dtype in (jnp.bfloat16, jnp.float32)

    # saturation: a far-out-in-the-tail draw must NOT produce inf/NaN
    # log-probs — the tanh correction runs in f32 even when samples are bf16
    big = TanhNormal(jnp.full(4, 4.0, jnp.bfloat16), jnp.full(4, 0.1, jnp.bfloat16))
    act, lp = big.sample_and_log_prob(key)
    assert bool(jnp.all(jnp.isfinite(lp))), np.asarray(lp)
    assert bool(jnp.all(jnp.isfinite(big.log_prob(act)))), np.asarray(big.log_prob(act))

    # pure-f32 configs: bit-identical to before (no hidden casts)
    f = OneHotCategoricalStraightThrough(logits=jnp.zeros((2, 4)), unimix=0.01)
    assert f.rsample(key).dtype == jnp.float32 and f.logits.dtype == jnp.float32
