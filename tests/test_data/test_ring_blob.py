"""Packed burst-blob layout/pack/unpack round-trips (data/ring.py).

The burst flush ships ONE uint8 blob per dispatch (host-side
``pack_burst_blob`` → device-side ``unpack_burst_blob`` inside the jit);
these tests pin the byte-level contract the two sides share.
"""

import jax
import numpy as np

from sheeprl_tpu.data.ring import (
    BlobLayout,
    effective_stage_buckets,
    make_blob_layouts,
    make_layout,
    pack_burst_blob,
    unpack_burst_blob,
)


def _roundtrip(layout, values):
    blob = pack_burst_blob(layout, values)
    assert blob.dtype == np.uint8 and blob.shape == (layout.nbytes,)
    out = jax.jit(lambda b: unpack_burst_blob(b, layout))(blob)
    return {k: np.asarray(v) for k, v in out.items()}


def test_mixed_dtype_roundtrip_bit_exact():
    rs = np.random.RandomState(0)
    layout = make_layout(
        [
            ("pix", (3, 2, 4, 4, 3), np.uint8),
            ("act", (3, 2, 6), np.float32),
            ("idx", (2,), np.int32),
            ("key", (2,), np.uint32),
        ]
    )
    values = {
        "pix": rs.randint(0, 256, (3, 2, 4, 4, 3)).astype(np.uint8),
        "act": rs.randn(3, 2, 6).astype(np.float32),
        "idx": np.array([7, -3], np.int32),
        "key": np.array([0xDEADBEEF, 0x12345678], np.uint32),
    }
    out = _roundtrip(layout, values)
    for k, v in values.items():
        np.testing.assert_array_equal(out[k], v)


def test_scalar_and_special_float_segments():
    layout = make_layout([("pos", (), np.int32), ("x", (4,), np.float32)])
    values = {
        "pos": np.asarray(41, np.int32),
        # NaN/inf/-0.0 must survive: the transport is a bitcast, not a cast.
        "x": np.array([np.nan, np.inf, -0.0, 1e-38], np.float32),
    }
    out = _roundtrip(layout, values)
    assert out["pos"].shape == () and int(out["pos"]) == 41
    np.testing.assert_array_equal(
        out["x"].view(np.uint32), values["x"].view(np.uint32)
    )


def test_pack_casts_to_segment_dtype():
    layout = make_layout([("r", (3,), np.float32)])
    # float64 rewards from the host are cast (not bitcast) before packing.
    out = _roundtrip(layout, {"r": np.array([1.5, -2.0, 0.25], np.float64)})
    np.testing.assert_array_equal(out["r"], np.array([1.5, -2.0, 0.25], np.float32))


def test_offsets_are_4_byte_aligned():
    layout = make_layout([("a", (3,), np.uint8), ("b", (2,), np.float32), ("c", (5,), np.uint8), ("d", (1,), np.int32)])
    for name, off, shape, dtype in layout.segments:
        if np.dtype(dtype).itemsize > 1:
            assert off % 4 == 0, (name, off)
    assert layout.nbytes % 4 == 0


def test_every_runner_bucket_has_a_layout():
    # The invariant the packed flush depends on: whatever bucket
    # effective_stage_buckets yields, make_blob_layouts built a layout for it
    # when fed the same normalized set.
    ring_keys = {"rgb": ((4, 4, 3), np.uint8), "actions": ((2,), np.float32)}
    raw = (18, 34)  # raw dreamer_stage_sizes-style tuple, no stage_max entry
    stage_max = 67
    buckets = effective_stage_buckets(raw, stage_max)
    assert buckets[-1] == stage_max
    layouts = make_blob_layouts(ring_keys, n_envs=2, grad_chunk=8, buckets=buckets)
    for b in buckets:
        assert b in layouts


def test_blob_lengths_distinct_across_buckets():
    # The blob length is the device-side trace/layout key: every distinct
    # bucket must map to a distinct length (a layout lookup by length that
    # could alias two buckets would unpack with the wrong shapes).
    ring_keys = {"x": ((1,), np.float32), "pix": ((2, 2, 3), np.uint8)}
    layouts = make_blob_layouts(ring_keys, n_envs=2, grad_chunk=4, buckets=(3, 9, 20))
    assert isinstance(layouts[3], BlobLayout)
    lengths = [l.nbytes for l in layouts.values()]
    assert len(lengths) == len(set(lengths)) == 3
    # and lengths grow with the bucket (segments scale with S)
    assert lengths == sorted(lengths)


def test_dreamer_layout_matches_runner_values():
    # The exact segment set BurstRunner.flush packs, at a realistic shape.
    ring_keys = {"rgb": ((8, 8, 3), np.uint8), "actions": ((4,), np.float32), "is_first": ((1,), np.float32)}
    n_envs, grad_chunk = 2, 4
    layouts = make_blob_layouts(ring_keys, n_envs, grad_chunk, (5,))
    layout = layouts[5]
    rs = np.random.RandomState(1)
    values = {
        "rgb": rs.randint(0, 256, (5, n_envs, 8, 8, 3)).astype(np.uint8),
        "actions": rs.randn(5, n_envs, 4).astype(np.float32),
        "is_first": rs.randint(0, 2, (5, n_envs, 1)).astype(np.float32),
        "__mask__": rs.randint(0, 2, (5, n_envs)).astype(np.int32),
        "__pos__": np.array([11, 3], np.int64),  # runner heads are int64; pack casts
        "__valid_n__": np.array([40, 40], np.int64),
        "__key__": np.asarray(jax.random.PRNGKey(7), np.uint32),
        "__validmask__": np.array([1, 1, 0, 0], np.float32),
    }
    out = _roundtrip(layout, values)
    np.testing.assert_array_equal(out["rgb"], values["rgb"])
    np.testing.assert_array_equal(out["__mask__"], values["__mask__"])
    np.testing.assert_array_equal(out["__pos__"], values["__pos__"].astype(np.int32))
    np.testing.assert_array_equal(out["__key__"], values["__key__"])
    np.testing.assert_array_equal(out["__validmask__"], values["__validmask__"])
