"""Buffer unit tests mirroring the reference's ``tests/test_data`` coverage:
wrap-around, sample validity, next-obs shift, sequence windows, per-env
independence, episode eviction, memmap modes."""

import numpy as np
import pytest

from sheeprl_tpu.data import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    MemmapArray,
    ReplayBuffer,
    SequentialReplayBuffer,
)


def _step(t, n_envs=2, extra=None):
    data = {
        "observations": np.full((1, n_envs, 3), t, dtype=np.float32),
        "rewards": np.full((1, n_envs, 1), t, dtype=np.float32),
        "truncated": np.zeros((1, n_envs, 1), dtype=np.uint8),
        "terminated": np.zeros((1, n_envs, 1), dtype=np.uint8),
    }
    if extra:
        data.update(extra)
    return data


class TestReplayBuffer:
    def test_add_and_len(self):
        rb = ReplayBuffer(8, 2)
        for t in range(4):
            rb.add(_step(t))
        assert not rb.full
        assert rb["observations"].shape == (8, 2, 3)

    def test_wraparound(self):
        rb = ReplayBuffer(4, 1)
        for t in range(6):
            rb.add(_step(t, n_envs=1))
        assert rb.full
        # positions 0,1 were overwritten by t=4,5
        assert rb["observations"][0, 0, 0] == 4
        assert rb["observations"][1, 0, 0] == 5
        assert rb["observations"][2, 0, 0] == 2

    def test_add_bigger_than_buffer(self):
        rb = ReplayBuffer(4, 1)
        data = {
            "observations": np.arange(10, dtype=np.float32).reshape(10, 1, 1),
        }
        rb.add(data)
        assert rb.full

    def test_sample_shapes(self):
        rb = ReplayBuffer(8, 2)
        for t in range(8):
            rb.add(_step(t))
        s = rb.sample(5, n_samples=3)
        assert s["observations"].shape == (3, 5, 3)

    def test_sample_next_obs_shift(self):
        rb = ReplayBuffer(16, 1)
        for t in range(10):
            rb.add(_step(t, n_envs=1))
        s = rb.sample(64, sample_next_obs=True)
        assert np.all(s["next_observations"][..., 0] == s["observations"][..., 0] + 1)

    def test_sample_empty_raises(self):
        rb = ReplayBuffer(8, 1)
        with pytest.raises(ValueError):
            rb.sample(1)

    def test_invalid_sizes(self):
        with pytest.raises(ValueError):
            ReplayBuffer(0, 1)
        with pytest.raises(ValueError):
            ReplayBuffer(4, 0)

    def test_validate_args(self):
        rb = ReplayBuffer(8, 2)
        with pytest.raises(ValueError):
            rb.add({"x": [1, 2, 3]}, validate_args=True)
        with pytest.raises(RuntimeError):
            rb.add({"x": np.zeros((3,))}, validate_args=True)

    def test_memmap(self, tmp_path):
        rb = ReplayBuffer(8, 2, memmap=True, memmap_dir=tmp_path / "buf")
        for t in range(4):
            rb.add(_step(t))
        assert rb.is_memmap
        assert (tmp_path / "buf" / "observations.memmap").exists()
        s = rb.sample(4)
        assert s["observations"].shape == (1, 4, 3)

    def test_sample_tensors_device(self):
        rb = ReplayBuffer(8, 2)
        for t in range(8):
            rb.add(_step(t))
        s = rb.sample_tensors(4)
        import jax

        assert isinstance(s["observations"], jax.Array)


class TestSequentialReplayBuffer:
    def test_sequence_shapes(self):
        rb = SequentialReplayBuffer(32, 2)
        for t in range(32):
            rb.add(_step(t))
        s = rb.sample(4, sequence_length=8, n_samples=3)
        assert s["observations"].shape == (3, 8, 4, 3)

    def test_sequences_contiguous(self):
        rb = SequentialReplayBuffer(32, 1)
        for t in range(32):
            rb.add(_step(t, n_envs=1))
        s = rb.sample(6, sequence_length=5)
        obs = s["observations"][0, :, :, 0]  # (seq, batch)
        diffs = np.diff(obs, axis=0) % 32
        assert np.all(diffs == 1)

    def test_too_long_sequence_raises(self):
        rb = SequentialReplayBuffer(8, 1)
        for t in range(4):
            rb.add(_step(t, n_envs=1))
        with pytest.raises(ValueError):
            rb.sample(1, sequence_length=6)

    def test_full_buffer_avoids_write_head(self):
        rb = SequentialReplayBuffer(16, 1)
        for t in range(24):  # full + wrapped
            rb.add(_step(t, n_envs=1))
        s = rb.sample(10, sequence_length=4)
        obs = s["observations"][0, :, :, 0]
        diffs = np.diff(obs, axis=0)
        # all sequences strictly consecutive in t as well (no wrap over head)
        assert np.all(diffs == 1)


class TestEnvIndependentReplayBuffer:
    def test_add_subset_envs(self):
        rb = EnvIndependentReplayBuffer(16, n_envs=3, buffer_cls=SequentialReplayBuffer)
        data = _step(0, n_envs=2)
        rb.add(data, indices=[0, 2])
        assert not rb.buffer[0].empty
        assert rb.buffer[1].empty
        assert not rb.buffer[2].empty

    def test_sample_concat(self):
        rb = EnvIndependentReplayBuffer(16, n_envs=2, buffer_cls=SequentialReplayBuffer)
        for t in range(16):
            rb.add(_step(t))
        s = rb.sample(6, sequence_length=4)
        assert s["observations"].shape[2] == 6  # batch axis for sequential

    def test_bad_indices_length(self):
        rb = EnvIndependentReplayBuffer(8, n_envs=2)
        with pytest.raises(ValueError):
            rb.add(_step(0, n_envs=2), indices=[0])


class TestEpisodeBuffer:
    def _episode(self, length, n_envs=1, end=True):
        term = np.zeros((length, n_envs, 1), dtype=np.uint8)
        if end:
            term[-1] = 1
        return {
            "observations": np.tile(np.arange(length, dtype=np.float32)[:, None, None], (1, n_envs, 1)),
            "terminated": term,
            "truncated": np.zeros((length, n_envs, 1), dtype=np.uint8),
        }

    def test_open_episode_not_sampled(self):
        eb = EpisodeBuffer(64, minimum_episode_length=4)
        eb.add(self._episode(5, end=False))
        with pytest.raises(RuntimeError):
            eb.sample(1, sequence_length=4)

    def test_episode_saved_and_sampled(self):
        eb = EpisodeBuffer(64, minimum_episode_length=4)
        eb.add(self._episode(10))
        s = eb.sample(3, sequence_length=4)
        assert s["observations"].shape == (1, 4, 3, 1)

    def test_eviction(self):
        eb = EpisodeBuffer(20, minimum_episode_length=4)
        for _ in range(4):
            eb.add(self._episode(8))
        assert len(eb) <= 20
        assert len(eb.buffer) <= 3

    def test_too_short_episode_raises(self):
        eb = EpisodeBuffer(64, minimum_episode_length=8)
        with pytest.raises(RuntimeError):
            eb.add(self._episode(3))

    def test_prioritize_ends(self):
        eb = EpisodeBuffer(64, minimum_episode_length=2, prioritize_ends=True)
        eb.add(self._episode(10))
        s = eb.sample(8, sequence_length=2)
        assert s["observations"].shape == (1, 2, 8, 1)


class TestMemmapArray:
    def test_roundtrip(self, tmp_path):
        arr = MemmapArray(np.float32, (4, 3), filename=tmp_path / "a.memmap")
        arr[:] = np.ones((4, 3), dtype=np.float32)
        assert np.all(arr[2] == 1)

    def test_from_array(self, tmp_path):
        src = np.arange(12, dtype=np.int32).reshape(4, 3)
        arr = MemmapArray.from_array(src, filename=tmp_path / "b.memmap")
        assert np.all(arr.array == src)

    def test_pickle_transfers_non_ownership(self, tmp_path):
        import pickle

        arr = MemmapArray(np.float32, (2, 2), filename=tmp_path / "c.memmap")
        arr[:] = 7.0
        clone = pickle.loads(pickle.dumps(arr))
        assert not clone.has_ownership
        assert arr.has_ownership
        assert np.all(clone.array == 7.0)

    def test_owner_deletes_file(self, tmp_path):
        path = tmp_path / "d.memmap"
        arr = MemmapArray(np.float32, (2,), filename=path)
        assert path.exists()
        del arr
        import gc

        gc.collect()
        assert not path.exists()
