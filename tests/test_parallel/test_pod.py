"""Pod launcher drills — unit coverage of the launch/fence surface plus the
slow chaos e2es through the real CLI: a worker SIGKILLed mid-run gang-restarts
the WHOLE pod from the newest complete checkpoint and converges to the same
final counters as the fault-free twin; a SIGSTOPped worker expires its
heartbeat lease and is counted as a HANG (not a kill); SIGTERM on the
launcher drains outermost-first and exits 0."""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import jax.numpy as jnp
import pytest

from sheeprl_tpu.fault.manager import CheckpointManager, find_latest_run_checkpoint, load_resume_state
from sheeprl_tpu.parallel.pod import PodLauncher, StepFenceError, beat_step, drain_requested, pod_worker_active


class _Cfg(dict):
    """Minimal compose()-shaped cfg: dict access + the resolved root_dir."""

    root_dir = "ppo/discrete_dummy"


def _cfg(tmp_path, **pod):
    return _Cfg({"fabric": {"pod": {"workers": 2, "devices_per_worker": 1, **pod}}, "log_root": str(tmp_path / "logs")})


# --------------------------------------------------------------------------- #
# fast unit coverage (tier-1)
# --------------------------------------------------------------------------- #


def test_launcher_rejects_fewer_than_two_workers(tmp_path):
    with pytest.raises(ValueError, match="fabric.pod.workers >= 2"):
        PodLauncher(_cfg(tmp_path, workers=1), [])


def test_worker_command_pins_and_resume_ownership(tmp_path):
    """The launcher OWNS the resume pin: a user token is stripped from the
    worker argv and re-issued by the launcher (so gang restarts can replace
    it), recursion is blocked, and the CPU proxy mesh spans every worker."""
    argv = ["exp=ppo", "checkpoint.resume_from=/old/ckpt", "algo.total_steps=64"]
    l = PodLauncher(_cfg(tmp_path, workers=2, devices_per_worker=2), argv)
    assert l.user_resume == "/old/ckpt"
    cmd = l.worker_command(0)
    assert cmd.count("checkpoint.resume_from=/old/ckpt") == 1  # launcher-issued, not doubled
    assert "fabric.pod.workers=0" in cmd  # a worker must never recurse into a pod
    assert "fabric.devices=4" in cmd  # 2 workers x 2 virtual devices
    assert "algo.total_steps=64" in cmd


def test_worker_env_shape_and_xla_flag_rewrite(tmp_path, monkeypatch):
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=8 --xla_foo=1")
    l = PodLauncher(_cfg(tmp_path, workers=2, devices_per_worker=3), [])
    env = l.worker_env(1)
    assert env["SHEEPRL_COORDINATOR"] == f"127.0.0.1:{l._port}"
    assert env["SHEEPRL_NUM_PROCESSES"] == "2" and env["SHEEPRL_PROCESS_ID"] == "1"
    assert env["SHEEPRL_POD_RANK"] == "1" and env["SHEEPRL_POD_HEARTBEAT"]
    # the stale host-device-count flag is REPLACED, other flags survive
    assert env["XLA_FLAGS"].count("xla_force_host_platform_device_count") == 1
    assert "--xla_force_host_platform_device_count=3" in env["XLA_FLAGS"]
    assert "--xla_foo=1" in env["XLA_FLAGS"]


def test_gang_restart_resolves_latest_and_fences_monotone(tmp_path):
    l = PodLauncher(_cfg(tmp_path), ["exp=ppo"])
    ckpt_dir = Path(l.ckpt_root) / "run_name" / "version_0" / "checkpoint"
    ckpt_dir.mkdir(parents=True)
    m = CheckpointManager()
    m.save(ckpt_dir / "ckpt_48_0.ckpt", {"agent": {"w": jnp.ones(2)}, "iter_num": 3}, step=48)
    m.close()

    l.fences.append(0)
    old_port = l._port
    l._on_gang_restart(2)
    assert l.fences == [0, 48]
    assert l._resume is not None and l._resume.endswith("ckpt_48_0.ckpt")
    assert l._port != old_port  # the dead coordinator may still hold its socket
    assert f"checkpoint.resume_from={l._resume}" in l.worker_command(0)

    # a resolution BEHIND the fence (here: the checkpoint vanished entirely,
    # resolving to a fresh start at step 0) must refuse to double-count
    import shutil

    shutil.rmtree(ckpt_dir)
    with pytest.raises(StepFenceError, match="BEHIND the previous fence 48"):
        l._on_gang_restart(3)


def test_worker_helpers_are_noops_outside_a_pod():
    assert not pod_worker_active()
    assert not drain_requested()
    beat_step(123)  # no heartbeat path bound: must not raise


def test_cli_pod_flag_parsing():
    from sheeprl_tpu.cli import _extract_pod_flag

    assert _extract_pod_flag(["run", "exp=ppo"])[1] is None
    assert _extract_pod_flag(["--pod", "exp=ppo"]) == (["exp=ppo"], 2)
    assert _extract_pod_flag(["--pod", "4", "exp=ppo"]) == (["exp=ppo"], 4)
    assert _extract_pod_flag(["--pod=3", "exp=ppo"]) == (["exp=ppo"], 3)


# --------------------------------------------------------------------------- #
# slow chaos drills: real 2-process pods through the CLI
# --------------------------------------------------------------------------- #

# world_envs = num_envs * workers = 4; policy_steps_per_iter = 16;
# total_steps=160 -> 10 iterations, checkpoint every iteration. Deterministic
# final counters: every run (fault-free or chaos) must land on iter_num == 10.
OVERRIDES = [
    "exp=ppo",
    "env=dummy",
    "env.id=discrete_dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "metric.log_level=0",
    "algo.rollout_steps=4",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
    "algo.total_steps=160",
    "checkpoint.every=16",
    "algo.run_test=False",
    "seed=11",
    "fabric.pod.backoff=0.1",
    "fabric.pod.lease_s=20",
    "fabric.pod.grace_s=120",
]
FINAL_ITERS = 10


def _pod_popen(tmp, tag, extra=()):
    cmd = [sys.executable, "-m", "sheeprl_tpu", "run", "--pod", "2", *OVERRIDES, f"log_root={tmp}/{tag}/logs", *extra]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)


def _pod_run(tmp, tag, extra=(), timeout=560):
    proc = _pod_popen(tmp, tag, extra)
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        pytest.fail(f"pod run '{tag}' did not finish in {timeout}s:\n{out[-4000:]}")
    return proc.returncode, out


def _summary(out):
    lines = [l for l in out.splitlines() if l.startswith("POD_SUMMARY ")]
    assert lines, f"no POD_SUMMARY in output:\n{out[-4000:]}"
    return json.loads(lines[-1][len("POD_SUMMARY ") :])


def _final_iters(tmp, tag):
    ckpt = find_latest_run_checkpoint(Path(str(tmp)) / tag / "logs" / "ppo" / "discrete_dummy")
    assert ckpt is not None, f"no complete checkpoint for '{tag}'"
    return int(load_resume_state(ckpt)["iter_num"])


@pytest.fixture(scope="module")
def pod_tmp(tmp_path_factory):
    return tmp_path_factory.mktemp("pod_drills")


@pytest.fixture(scope="module")
def fault_free_twin(pod_tmp):
    """The clean reference run: shared by the chaos drills (and the warm-up
    of the persistent XLA compile cache for everything after it)."""
    rc, out = _pod_run(pod_tmp, "clean")
    summary = _summary(out)
    return rc, summary, _final_iters(pod_tmp, "clean")


@pytest.mark.slow
@pytest.mark.chaos
def test_fault_free_pod_completes(fault_free_twin):
    rc, s, iters = fault_free_twin
    assert rc == 0 and s["finished"] and not s["drained"] and s["error"] is None
    assert s["pod_restarts"] == 0 and s["kills"] == 0 and s["hangs"] == 0
    assert iters == FINAL_ITERS


@pytest.mark.slow
@pytest.mark.chaos
def test_kill_host_gang_restarts_and_counters_match_twin(pod_tmp, fault_free_twin):
    """Acceptance drill: SIGKILL one worker mid-run (seeded chaos schedule).
    The gang restarts from the newest complete checkpoint, the step fences
    stay monotone, and the run converges to the fault-free twin's counters —
    no lost and no double-counted steps."""
    _, _, twin_iters = fault_free_twin
    rc, out = _pod_run(
        pod_tmp,
        "kill",
        extra=[
            "fault.chaos.enabled=True",
            # progress-keyed: the 6th observed heartbeat step advance is
            # ~iteration 3 of 10, after checkpoints exist, however warm the
            # XLA compile cache makes the run
            "fault.chaos.events=[train.pod.step:kill-host:6]",
        ],
    )
    s = _summary(out)
    assert rc == 0, f"chaos pod run failed rc={rc}:\n{out[-4000:]}"
    assert s["finished"] and s["error"] is None
    assert s["pod_restarts"] >= 1 and s["kills"] >= 1 and s["hangs"] == 0
    assert s["fences"] == sorted(s["fences"])  # monotone: never double-counts
    assert s["restarts"] and all(r["mttr_s"] > 0 for r in s["restarts"])
    assert _final_iters(pod_tmp, "kill") == twin_iters


@pytest.mark.slow
@pytest.mark.chaos
def test_hang_host_counts_distinctly_and_recovers(pod_tmp, fault_free_twin):
    """SIGSTOP drill: a wedged (alive but silent) worker expires its
    heartbeat lease -> counted as a HANG, distinct from kills, SIGKILLed by
    the supervisor, and the gang restarts to completion."""
    rc, out = _pod_run(
        pod_tmp,
        "hang",
        extra=[
            "fabric.pod.lease_s=8",
            "fabric.pod.grace_s=30",
            "fault.chaos.enabled=True",
            "fault.chaos.events=[train.pod.step:hang-host:6]",
        ],
    )
    s = _summary(out)
    assert rc == 0, f"hang pod run failed rc={rc}:\n{out[-4000:]}"
    assert s["finished"] and s["error"] is None
    assert s["hangs"] == 1  # the wedged host is a HANG, not a kill
    assert s["pod_restarts"] >= 1
    assert _final_iters(pod_tmp, "hang") == FINAL_ITERS


@pytest.mark.slow
@pytest.mark.chaos
def test_sigterm_drains_outermost_first(pod_tmp, fault_free_twin):
    """SIGTERM on the launcher: supervision stops first, each worker
    checkpoints at its next iteration boundary and exits 0, the launcher
    reports a drained (not errored) pod and exits 0."""
    proc = _pod_popen(pod_tmp, "drain")
    root = Path(str(pod_tmp)) / "drain" / "logs" / "ppo" / "discrete_dummy"
    try:
        deadline = time.monotonic() + 420
        while time.monotonic() < deadline:
            if find_latest_run_checkpoint(root) is not None:
                break
            if proc.poll() is not None:
                out, _ = proc.communicate()
                pytest.fail(f"pod exited rc={proc.returncode} before first checkpoint:\n{out[-4000:]}")
            time.sleep(0.5)
        else:
            pytest.fail("no checkpoint appeared within 420s")
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, f"drained pod must exit 0, got {proc.returncode}:\n{out[-4000:]}"
    s = _summary(out)
    assert s["drained"] and s["error"] is None
    assert find_latest_run_checkpoint(root) is not None
