"""Coordinator-connect retry drills for ``maybe_init``: bounded exponential
backoff around ``jax.distributed.initialize`` and the typed
CoordinatorConnectError naming the coordinator address on exhaustion — a pod
worker that races process 0's coordinator socket must retry, and a worker
that can NEVER reach it must fail with an address an operator can act on.
``initialize`` is monkeypatched; nothing distributed actually starts."""

import pytest

import sheeprl_tpu.parallel.distributed as dist
from sheeprl_tpu.parallel.distributed import CoordinatorConnectError, maybe_init


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    # never leak the module-level "already initialized" latch, and make sure
    # the pod env vars of an outer test run don't steer resolution
    monkeypatch.setattr(dist, "_initialized", False)
    for var in ("SHEEPRL_COORDINATOR", "SHEEPRL_NUM_PROCESSES", "SHEEPRL_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    yield


CFG = {
    "coordinator": "10.1.2.3:7777",
    "num_processes": 2,
    "process_id": 1,
    "connect_retries": 2,
    "connect_backoff_s": 0.5,
}


def test_exhaustion_raises_typed_error_naming_coordinator(monkeypatch):
    attempts = []
    sleeps = []
    monkeypatch.setattr(
        dist.jax.distributed,
        "initialize",
        lambda **kw: (attempts.append(kw), (_ for _ in ()).throw(RuntimeError("connection refused")))[1],
    )
    monkeypatch.setattr(dist.time, "sleep", sleeps.append)
    with pytest.warns(UserWarning, match="retrying in 0.5s"):
        with pytest.raises(CoordinatorConnectError) as ei:
            maybe_init(CFG)
    err = ei.value
    assert err.coordinator == "10.1.2.3:7777" and err.attempts == 3
    assert "10.1.2.3:7777" in str(err) and "3 attempt(s)" in str(err)
    assert "connection refused" in str(err)
    assert isinstance(err.__cause__, RuntimeError)
    assert len(attempts) == 3
    # exponential backoff between attempts: base, base*2
    assert sleeps == [0.5, 1.0]
    assert dist._initialized is False


def test_success_after_transient_failures(monkeypatch):
    calls = {"n": 0}
    sleeps = []

    def flaky(**kw):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("coordinator not listening yet")
        assert kw["coordinator_address"] == "10.1.2.3:7777"
        assert kw["num_processes"] == 2 and kw["process_id"] == 1

    monkeypatch.setattr(dist.jax.distributed, "initialize", flaky)
    monkeypatch.setattr(dist.time, "sleep", sleeps.append)
    with pytest.warns(UserWarning, match="attempt 2/3"):
        assert maybe_init(CFG) is True
    assert calls["n"] == 3 and sleeps == [0.5, 1.0]
    assert dist._initialized is True


def test_zero_retries_fails_on_first_attempt(monkeypatch):
    monkeypatch.setattr(
        dist.jax.distributed,
        "initialize",
        lambda **kw: (_ for _ in ()).throw(OSError("no route to host")),
    )
    monkeypatch.setattr(dist.time, "sleep", lambda s: pytest.fail("must not sleep with 0 retries"))
    with pytest.raises(CoordinatorConnectError, match="1 attempt"):
        maybe_init({**CFG, "connect_retries": 0})


def test_init_timeout_forwarded(monkeypatch):
    seen = {}
    monkeypatch.setattr(dist.jax.distributed, "initialize", lambda **kw: seen.update(kw))
    assert maybe_init({**CFG, "init_timeout_s": 45}) is True
    assert seen["initialization_timeout"] == 45
