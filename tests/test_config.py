import pytest

from sheeprl_tpu.config import ConfigError, compose, dotdict, instantiate, to_yaml


def test_compose_ppo_defaults():
    cfg = compose(["exp=ppo"])
    assert cfg.algo.name == "ppo"
    assert cfg.env.id == "CartPole-v1"
    assert cfg.algo.optimizer.lr == pytest.approx(1e-3)
    assert isinstance(cfg.algo.optimizer.eps, float)
    # interpolation
    assert cfg.exp_name == "ppo_CartPole-v1"
    assert cfg.buffer.size == cfg.algo.rollout_steps


def test_compose_group_and_value_overrides():
    cfg = compose(["exp=ppo", "env=dummy", "algo.rollout_steps=4", "seed=7"])
    assert cfg.env.id == "discrete_dummy"
    assert cfg.algo.rollout_steps == 4
    assert cfg.seed == 7
    assert cfg.buffer.size == 4  # interpolation follows the override


def test_missing_exp_raises():
    with pytest.raises(ConfigError):
        compose([])


def test_unresolved_mandatory_raises():
    # algo default has name: ??? — composing a bare algo must fail
    with pytest.raises(ConfigError):
        compose(["exp=default"])


def test_interpolation_nested_and_now():
    cfg = compose(["exp=ppo"])
    assert "ppo_CartPole-v1" in cfg.run_name
    assert cfg.algo.encoder.dense_units == cfg.algo.dense_units


def test_instantiate_nested():
    spec = {"_target_": "collections.OrderedDict", "a": 1}
    obj = instantiate(spec)
    assert obj["a"] == 1


def test_to_yaml_roundtrip():
    cfg = compose(["exp=ppo"])
    text = to_yaml(cfg)
    assert "algo:" in text and "rollout_steps" in text


def test_cli_override_types():
    cfg = compose(["exp=ppo", "algo.optimizer.lr=5e-4", "env.num_envs=2", "algo.anneal_lr=True"])
    assert cfg.algo.optimizer.lr == pytest.approx(5e-4)
    assert cfg.env.num_envs == 2
    assert cfg.algo.anneal_lr is True


def _all_exp_names():
    from pathlib import Path

    import sheeprl_tpu

    exp_dir = Path(sheeprl_tpu.__file__).parent / "configs" / "exp"
    return sorted(p.stem for p in exp_dir.glob("*.yaml") if p.stem != "default")


@pytest.mark.parametrize("exp", _all_exp_names())
def test_every_exp_config_composes(exp):
    """Every shipped exp overlay must compose and fully resolve (the named
    runs — 100k_ms_pacman, XL_crafter, the DOA++ P2E pair, ... — are the
    BASELINE north-star commands; a broken overlay means an unlaunchable
    flagship run)."""
    overrides = [f"exp={exp}"]
    # Finetuning overlays mandate an exploration checkpoint path.
    if "finetuning" in exp or "fntn" in exp:
        overrides.append("checkpoint.exploration_ckpt_path=/tmp/fake.ckpt")
    cfg = compose(overrides)
    assert cfg.algo.name
    assert cfg.env.id is not None
    # The resolved tree must serialize (catches dangling interpolations).
    assert "algo:" in to_yaml(cfg)
