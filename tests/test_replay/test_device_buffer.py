"""DeviceReplayBuffer unit tests: allocation/sharding, staged flush packing,
checkpoint round trips, host-tier crossovers, and spillover resolution."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.ring import unpack_burst_blob
from sheeprl_tpu.parallel import Fabric
from sheeprl_tpu.replay import (
    DeviceReplayBuffer,
    DeviceReplayState,
    estimate_ring_bytes,
    resolve_device_resident,
    restore_host_buffer,
)

CAP = 8
N_ENVS = 2
SPECS = {
    "observations": ((3,), jnp.float32),
    "actions": ((2,), jnp.float32),
    "rewards": ((1,), jnp.float32),
}


def _mk(fabric, **kw):
    return DeviceReplayBuffer(fabric, SPECS, CAP, N_ENVS, **kw)


@pytest.fixture(scope="module")
def fabric1():
    return Fabric(devices=1, accelerator="cpu")


@pytest.fixture(scope="module")
def fabric2():
    return Fabric(devices=2, accelerator="cpu")


def _row(t):
    return {
        "observations": np.full((1, N_ENVS, 3), t, np.float32),
        "actions": np.full((1, N_ENVS, 2), t + 0.5, np.float32),
        "rewards": np.full((1, N_ENVS, 1), -t, np.float32),
    }


def test_flush_packs_one_blob_and_tracks_heads(fabric1):
    drb = _mk(fabric1)
    drb.add(_row(0))
    blob = drb.make_job()
    assert blob.dtype == np.uint8 and blob.ndim == 1
    u = jax.jit(lambda b: unpack_burst_blob(b, drb.layout))(jnp.asarray(blob))
    assert int(u["__count__"]) == 1
    np.testing.assert_array_equal(np.asarray(u["observations"])[0], _row(0)["observations"][0])
    assert drb.pos == 1 and not drb.full
    # count-0 job (backlog drain): heads unmoved
    drb.make_job()
    assert drb.pos == 1
    # wrap: host mirror follows the same rule as the host buffer
    for t in range(1, CAP):
        drb.add(_row(t))
        drb.make_job()
    assert drb.pos == 0 and drb.full


def test_staging_overflow_raises(fabric1):
    drb = _mk(fabric1)
    drb.add(_row(0))
    with pytest.raises(RuntimeError, match="staging area"):
        drb.add(_row(1))


def test_checkpoint_roundtrip_bitexact(fabric1):
    drb = _mk(fabric1, prioritized=True, seed=3)
    # write some real data through a tiny jitted append so the DEVICE state
    # (not just host mirrors) is exercised
    cap = drb.capacity

    @jax.jit
    def append(state, staged):
        idx = state["pos"]
        storage = {k: state["storage"][k].at[idx].set(staged[k][0]) for k in state["storage"]}
        return {
            **state,
            "storage": storage,
            "pos": (state["pos"] + 1) % cap,
            "valid": jnp.minimum(state["valid"] + 1, cap),
        }

    for t in range(3):
        drb.state = append(drb.state, {k: jnp.asarray(v) for k, v in _row(t).items()})
        drb.add(_row(t))
        drb.make_job()

    snap = drb.state_dict()
    assert isinstance(snap, DeviceReplayState) and snap.kind == "uniform"
    # pickle round trip (the checkpoint sidecar pickles state["rb"])
    import pickle

    snap = pickle.loads(pickle.dumps(snap))

    drb2 = _mk(fabric1, prioritized=True, seed=999)
    drb2.load_state_dict(snap)
    for k in SPECS:
        np.testing.assert_array_equal(
            np.asarray(drb.state["storage"][k]), np.asarray(drb2.state["storage"][k])
        )
    for k in ("pos", "valid", "key", "tree", "max_p"):
        np.testing.assert_array_equal(np.asarray(drb.state[k]), np.asarray(drb2.state[k]))
    assert drb2.pos == drb.pos and drb2.full == drb.full


def test_checkpoint_with_staged_rows_refuses(fabric1):
    drb = _mk(fabric1)
    drb.add(_row(0))
    with pytest.raises(RuntimeError, match="unflushed"):
        drb.state_dict()


def test_shape_mismatch_refuses(fabric1):
    drb = _mk(fabric1)
    snap = drb.state_dict()
    other = DeviceReplayBuffer(fabric1, SPECS, CAP * 2, N_ENVS)
    with pytest.raises(ValueError, match="mismatch"):
        other.load_state_dict(snap)


def test_two_device_sharded_storage_and_roundtrip(fabric2):
    """2-device env-sharded ring: per-device HBM holds only its env shard,
    and the checkpoint round trip reassembles the global array."""
    drb = _mk(fabric2, shard_envs=True)
    assert drb.local_envs == N_ENVS // 2
    shards = drb.state["storage"]["observations"].addressable_shards
    assert len(shards) == 2
    assert shards[0].data.shape == (CAP, 1, 3)

    host = ReplayBuffer(CAP, N_ENVS, obs_keys=("observations",))
    for t in range(CAP + 3):  # wrapped
        host.add(
            {k: v for k, v in _row(t).items()}
        )
    drb.load_host_buffer(host)
    snap = drb.state_dict()
    np.testing.assert_array_equal(
        snap.arrays["storage/observations"], np.asarray(host.buffer["observations"])
    )
    assert int(snap.arrays["valid"]) == CAP and drb.full

    drb2 = _mk(fabric2, shard_envs=True)
    drb2.load_state_dict(snap)
    np.testing.assert_array_equal(
        np.asarray(drb2.state["storage"]["observations"]), np.asarray(host.buffer["observations"])
    )


def test_prioritized_mirror_gets_uniform_priorities(fabric1):
    host = ReplayBuffer(CAP, N_ENVS, obs_keys=("observations",))
    for t in range(3):
        host.add({k: v for k, v in _row(t).items()})
    drb = _mk(fabric1, prioritized=True)
    drb.load_host_buffer(host)
    tree = np.asarray(drb.state["tree"])
    P = tree.shape[0] // 2
    # rows [0, 3) x N_ENVS leaves live, everything else zero
    assert tree[P : P + 3 * N_ENVS].tolist() == [1.0] * (3 * N_ENVS)
    assert tree[P + 3 * N_ENVS :].sum() == 0
    assert float(tree[1]) == 3.0 * N_ENVS


def test_restore_host_buffer_crossover(fabric1):
    """Resident checkpoint resumed on the host tier: the snapshot fills the
    host ReplayBuffer (plus zero-filled keys the ring never stored)."""
    drb = _mk(fabric1)
    for t in range(CAP + 2):  # wrapped ring
        drb.add(_row(t))
        drb.make_job()
    host_pos, host_full = drb.pos, drb.full
    # give the device state real content via the host mirrors only (the
    # crossover reads snapshot arrays, which here are the jitted zeros +
    # heads — enough to verify geometry and key fill)
    snap = drb.state_dict()

    rb = ReplayBuffer(CAP, N_ENVS, obs_keys=("observations",))
    restore_host_buffer(snap, rb, fill_missing={"truncated": ((1,), np.uint8)})
    assert rb._pos == host_pos and rb.full == host_full
    assert rb.buffer["truncated"].shape == (CAP, N_ENVS, 1)
    # a later add must find congruent storage (no KeyError / shape clash)
    rb.add({**_row(0), "truncated": np.zeros((1, N_ENVS, 1), np.uint8)})


def test_restore_host_buffer_memmap_backing(fabric1, tmp_path):
    """The host-tier crossover must honor memmap backing — the spillover
    tier exists precisely because the data does not fit RAM/HBM."""
    from sheeprl_tpu.data.memmap import MemmapArray

    drb = _mk(fabric1)
    drb.add(_row(0))
    drb.make_job()
    snap = drb.state_dict()
    rb = ReplayBuffer(CAP, N_ENVS, obs_keys=("observations",), memmap=True, memmap_dir=tmp_path)
    restore_host_buffer(snap, rb, fill_missing={"truncated": ((1,), np.uint8)})
    assert isinstance(rb.buffer["observations"], MemmapArray)
    assert isinstance(rb.buffer["truncated"], MemmapArray)
    assert rb._pos == 1


def test_restore_host_env_buffer_sequence_crossover(fabric1):
    """A Dreamer resident (sequence-ring) checkpoint resumed onto the host
    tier fills the per-env buffers with per-env heads intact."""
    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
    from sheeprl_tpu.replay import restore_host_env_buffer

    storage = np.arange(CAP * N_ENVS * 3, dtype=np.float32).reshape(CAP, N_ENVS, 3)
    snap = DeviceReplayState(
        "sequence",
        {
            "storage/observations": storage,
            "pos": np.array([3, 0]),
            "valid": np.array([3, CAP]),
            "key": np.zeros(2, np.uint32),
        },
        {"capacity": CAP, "n_envs": N_ENVS, "seq_len": 2},
    )
    rb = EnvIndependentReplayBuffer(
        CAP, n_envs=N_ENVS, obs_keys=("observations",), buffer_cls=SequentialReplayBuffer
    )
    restore_host_env_buffer(snap, rb, fill_missing={"truncated": ((1,), np.float32)})
    subs = rb.buffer
    assert subs[0]._pos == 3 and not subs[0].full
    assert subs[1]._pos == 0 and subs[1].full
    np.testing.assert_array_equal(np.asarray(subs[0].buffer["observations"])[:, 0], storage[:, 0])
    np.testing.assert_array_equal(np.asarray(subs[1].buffer["observations"])[:, 0], storage[:, 1])
    # per-env sequential sampling works immediately after the crossover
    rb.seed(0)
    out = rb.sample(batch_size=4, sequence_length=2)
    assert out["observations"].shape[1] == 2  # (n_samples, T, B, ...)
    # wrong-kind snapshots are rejected loudly
    with pytest.raises(ValueError, match="sequence"):
        restore_host_buffer(snap, ReplayBuffer(CAP, N_ENVS))


def test_spillover_resolution():
    small = {"observations": ((4,), jnp.float32)}
    ok, shard, _ = resolve_device_resident("auto", small, 100, 2, 1, 1.0)
    assert ok and not shard
    ok, shard, reason = resolve_device_resident("auto", small, 10**9, 2, 1, 0.5)
    assert not ok and "spilling" in reason
    with pytest.warns(UserWarning, match="device_resident=true"):
        ok, _, _ = resolve_device_resident(True, small, 10**9, 2, 1, 0.5)
    assert not ok
    ok, _, _ = resolve_device_resident(False, small, 10, 2, 1, 1.0)
    assert not ok
    with pytest.raises(ValueError):
        resolve_device_resident("bogus", small, 10, 2, 1, 1.0)
    # sharding halves the per-device footprint; PER forces replication
    est_rep = estimate_ring_bytes(small, 1000, 4, 2, shard_envs=False)
    est_shard = estimate_ring_bytes(small, 1000, 4, 2, shard_envs=True)
    assert est_shard * 2 == est_rep
    _, shard, _ = resolve_device_resident("auto", small, 100, 4, 2, 1.0, prioritized=True)
    assert not shard
    _, shard, _ = resolve_device_resident("auto", small, 100, 4, 2, 1.0)
    assert shard


# -- decoupled (Sebulba) append path ----------------------------------------


def _np_ring_expect(blocks):
    """Reference ring built with plain numpy from a list of row-lists."""
    ring = {k: np.zeros((CAP, N_ENVS) + shape, np.float32) for k, (shape, _d) in SPECS.items()}
    pos, valid = 0, 0
    for rows in blocks:
        for row in rows:
            for k in SPECS:
                ring[k][pos] = row[k].reshape((N_ENVS,) + SPECS[k][0])
            pos = (pos + 1) % CAP
            valid = min(valid + 1, CAP)
    return ring, pos, valid


def test_pack_rows_is_pure_and_thread_reusable(fabric1):
    """pack_rows must not touch the buffer (concurrent actor threads each
    pack their own blob): identical bytes twice, heads unmoved."""
    drb = _mk(fabric1, stage_rows=3)
    rows = [{k: v[0] for k, v in _row(t).items()} for t in range(2)]
    b1 = drb.pack_rows(rows)
    b2 = drb.pack_rows(rows)
    np.testing.assert_array_equal(b1, b2)
    assert b1.dtype == np.uint8 and b1.nbytes == drb.append_layout.nbytes
    assert drb.pos == 0 and not drb.full and drb.empty
    with pytest.raises(ValueError, match="exceed the append blob"):
        drb.pack_rows([{k: v[0] for k, v in _row(t).items()} for t in range(4)])


def test_append_step_multi_row_parity_and_wraparound(fabric1):
    """The jitted multi-row append must match a plain numpy ring through
    partial blobs and a wrap-around, and note_append must mirror the heads."""
    drb = _mk(fabric1, stage_rows=3)
    append = drb.make_append_step()
    blocks = [
        [{k: v[0] for k, v in _row(t).items()} for t in range(3)],        # rows 0-2
        [{k: v[0] for k, v in _row(t).items()} for t in range(3, 5)],     # partial (2 of 3)
        [{k: v[0] for k, v in _row(t).items()} for t in range(5, 8)],     # rows 5-7
        [{k: v[0] for k, v in _row(t).items()} for t in range(8, 10)],    # wraps: rows 8-9
    ]
    for rows in blocks:
        blob = fabric1.put_replicated(drb.pack_rows(rows))
        drb.state = append(drb.state, blob)
        drb.note_append(len(rows))
    expect, pos, valid = _np_ring_expect(blocks)
    for k in SPECS:
        np.testing.assert_array_equal(np.asarray(drb.state["storage"][k]), expect[k])
    assert int(drb.state["pos"]) == pos == drb.pos
    assert int(drb.state["valid"]) == valid
    assert drb.full


def test_append_step_env_sharded(fabric2):
    """Env-sharded storage: the append scatters each device's env shard in
    place and the reassembled checkpoint equals the replicated reference."""
    drb_sh = _mk(fabric2, shard_envs=True, stage_rows=2)
    drb_rep = _mk(fabric2, shard_envs=False, stage_rows=2)
    app_sh = drb_sh.make_append_step()
    app_rep = drb_rep.make_append_step()
    for t0 in range(0, 6, 2):
        rows = [{k: v[0] for k, v in _row(t).items()} for t in range(t0, t0 + 2)]
        blob = fabric2.put_replicated(drb_sh.pack_rows(rows))
        drb_sh.state = app_sh(drb_sh.state, blob)
        drb_sh.note_append(2)
        blob = fabric2.put_replicated(drb_rep.pack_rows(rows))
        drb_rep.state = app_rep(drb_rep.state, blob)
        drb_rep.note_append(2)
    sh, rep = drb_sh.state_dict(), drb_rep.state_dict()
    for k in SPECS:
        np.testing.assert_array_equal(sh.arrays[f"storage/{k}"], rep.arrays[f"storage/{k}"])
    assert int(sh.arrays["valid"]) == 6


def test_append_step_prioritized_fresh_rows_at_max_p(fabric1):
    """PER: every fresh (row, env) leaf enters at the running max priority;
    leaves beyond the blob's count keep their value (and the padding slots
    beyond capacity stay zero)."""
    drb = _mk(fabric1, prioritized=True, stage_rows=3)
    append = drb.make_append_step()
    blob = fabric1.put_replicated(drb.pack_rows([{k: v[0] for k, v in _row(t).items()} for t in range(2)]))
    drb.state = append(drb.state, blob)
    drb.note_append(2)
    tree = np.asarray(drb.state["tree"])
    P = tree.shape[0] // 2
    assert tree[P : P + 2 * N_ENVS].tolist() == [1.0] * (2 * N_ENVS)  # max_p starts at 1
    assert tree[P + 2 * N_ENVS :].sum() == 0
    assert float(tree[1]) == 2.0 * N_ENVS  # root = total mass


def test_ctl_job_layout_split(fabric1):
    """The control blob carries ONLY the extra segments; a buffer without
    extra_spec refuses to build one."""
    drb = _mk(fabric1, extra_spec=[("__flags__", (4,), np.float32), ("__beta__", (), np.float32)])
    ctl = drb.make_ctl_job({"__flags__": np.arange(4, dtype=np.float32), "__beta__": np.float32(0.5)})
    assert int(ctl.nbytes) == drb.ctl_layout.nbytes < drb.layout.nbytes
    u = jax.jit(lambda b: unpack_burst_blob(b, drb.ctl_layout))(ctl)
    np.testing.assert_array_equal(np.asarray(u["__flags__"]), np.arange(4, dtype=np.float32))
    assert float(u["__beta__"]) == 0.5
    bare = _mk(fabric1)
    assert bare.ctl_layout is None
    with pytest.raises(RuntimeError, match="extra_spec"):
        bare.make_ctl_job({})
