"""AsyncSequenceRing unit tests: the ragged per-env-head append program
(concurrent-actor blobs with env-column offsets) against a numpy oracle —
partial masks, wraparound, interleaved actors — plus the append-free train
sampler's head-validity plumbing, pack_rows purity, checkpoint round trip,
and the sequence-shape spillover accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data.ring import build_seq_train_step, pack_burst_blob, make_seq_ctl_layout
from sheeprl_tpu.parallel.fabric import Fabric
from sheeprl_tpu.replay import AsyncSequenceRing, estimate_ring_bytes, resolve_device_resident

CAP, LOCAL, ACTORS = 8, 2, 2
RING_ENVS = LOCAL * ACTORS
KEYS = {"obs": ((3,), jnp.float32), "rewards": ((1,), jnp.float32)}


def _ring(fabric=None, capacity=CAP, stage_rows=4, seq_len=2):
    fabric = fabric or Fabric(devices=1, accelerator="cpu")
    return AsyncSequenceRing(
        fabric, KEYS, capacity=capacity, n_envs=RING_ENVS, local_envs=LOCAL,
        seq_len=seq_len, stage_rows=stage_rows, seed=3,
    )


def _row(val, envs=LOCAL):
    return {
        "obs": np.full((envs, 3), val, np.float32),
        "rewards": np.full((envs, 1), val, np.float32),
    }


class _Oracle:
    """Per-env-head numpy ring twin."""

    def __init__(self, capacity=CAP, n_envs=RING_ENVS):
        self.storage = {
            k: np.zeros((capacity, n_envs) + shape, np.dtype(jnp.dtype(d))) for k, (shape, d) in KEYS.items()
        }
        self.pos = np.zeros(n_envs, np.int64)
        self.valid = np.zeros(n_envs, np.int64)
        self.capacity = capacity

    def append(self, rows, offset):
        for row, mask in rows:
            for e_local in range(len(mask)):
                if not mask[e_local]:
                    continue
                e = offset + e_local
                for k in self.storage:
                    self.storage[k][self.pos[e], e] = row[k][e_local]
                self.pos[e] = (self.pos[e] + 1) % self.capacity
                self.valid[e] = min(self.valid[e] + 1, self.capacity)


def _commit(ring, rows, offset):
    blob = ring.pack_rows(rows, offset)
    ring.append(jnp.asarray(blob))
    ring.note_append(
        np.concatenate([np.zeros(offset, np.int64), sum(m for _r, m in rows), np.zeros(RING_ENVS - offset - LOCAL, np.int64)]),
        blob.nbytes,
    )


def _assert_matches(ring, oracle):
    state = jax.device_get(ring.state)
    np.testing.assert_array_equal(np.asarray(state["pos"]), oracle.pos)
    np.testing.assert_array_equal(np.asarray(state["valid"]), oracle.valid)
    for k in KEYS:
        np.testing.assert_allclose(np.asarray(state["storage"][k]), oracle.storage[k])
    np.testing.assert_array_equal(ring.host_pos, oracle.pos)
    np.testing.assert_array_equal(ring.host_valid, oracle.valid)


def test_ragged_append_matches_oracle_interleaved_actors():
    """Two actors' blobs — regular rows + ragged reset rows — commit
    interleaved; every env column's head advances exactly per its masks."""
    ring = _ring()
    oracle = _Oracle()
    ones = np.ones(LOCAL, np.int32)
    ragged = np.array([1, 0], np.int32)

    a0 = [(_row(1.0), ones), (_row(2.0), ragged)]  # env 0 gets an extra reset row
    a1 = [(_row(10.0), ones)]
    _commit(ring, a0, 0)
    oracle.append(a0, 0)
    _commit(ring, a1, LOCAL)
    oracle.append(a1, LOCAL)
    _assert_matches(ring, oracle)

    # heads advanced raggedly: actor-0's env 0 is one ahead of env 1
    assert ring.host_pos.tolist() == [2, 1, 1, 1]


def test_ragged_append_wraparound():
    """Rings wrap per env head; valid saturates at capacity."""
    ring = _ring(capacity=4, stage_rows=3)
    oracle = _Oracle(capacity=4)
    ones = np.ones(LOCAL, np.int32)
    for i in range(4):  # 4 blobs x 3 rows = 12 rows > capacity 4
        rows = [(_row(float(3 * i + j)), ones) for j in range(3)]
        _commit(ring, rows, 0)
        oracle.append(rows, 0)
        rows1 = [(_row(float(100 + 3 * i + j)), ones) for j in range(3)]
        _commit(ring, rows1, LOCAL)
        oracle.append(rows1, LOCAL)
    _assert_matches(ring, oracle)
    assert ring.host_valid.tolist() == [4, 4, 4, 4]


def test_pack_rows_is_pure():
    """pack_rows touches nothing on the ring (concurrent-writer safety)."""
    ring = _ring()
    before = jax.device_get(ring.state)
    blob1 = ring.pack_rows([(_row(5.0), np.ones(LOCAL, np.int32))], 0)
    blob2 = ring.pack_rows([(_row(5.0), np.ones(LOCAL, np.int32))], 0)
    np.testing.assert_array_equal(blob1, blob2)
    after = jax.device_get(ring.state)
    for k in KEYS:
        np.testing.assert_array_equal(before["storage"][k], after["storage"][k])
    assert ring.host_pos.sum() == 0 and ring._metrics["flushes"] == 0


def test_pack_rows_overflow_raises():
    ring = _ring(stage_rows=2)
    rows = [(_row(1.0), np.ones(LOCAL, np.int32))] * 3
    with pytest.raises(ValueError, match="exceed the append blob capacity"):
        ring.pack_rows(rows, 0)


def test_train_step_key_advances_and_heads_pass_through():
    """The append-free train program advances ONLY the in-ring key; storage
    and heads pass through, and granted steps sample with per-env validity."""
    fabric = Fabric(devices=1, accelerator="cpu")
    ring = _ring(fabric)
    ones = np.ones(LOCAL, np.int32)
    for off in (0, LOCAL):
        _commit(ring, [(_row(1.0), ones), (_row(2.0), ones)], off)

    calls = []

    def gradient_step(carry, xs):
        batch, key = xs
        calls.append(jax.tree.map(lambda x: x.shape, batch))
        return carry + 1, (jnp.mean(batch["obs"]),)

    train_fn, ctl_layout = build_seq_train_step(
        gradient_step, fabric.mesh,
        {"capacity": CAP, "n_envs": RING_ENVS, "grad_chunk": 2, "seq_len": 2, "batch_size": 4},
    )
    validmask = np.zeros(2, np.float32)
    validmask[:1] = 1.0
    ctl = fabric.put_replicated(pack_burst_blob(ctl_layout, {"__validmask__": validmask}))
    key_before = np.asarray(jax.device_get(ring.state["key"]))
    carry, new_key, metrics = train_fn(jnp.int32(0), ring.state, ctl)
    assert int(carry) == 1  # one granted step ran, one padding step skipped
    # the advanced train-key is the ONLY ring state the program returns —
    # storage/heads are read-only inputs (returning them would force a full
    # ring copy per dispatch); the caller splices the key back
    assert not np.array_equal(np.asarray(jax.device_get(new_key)), key_before)
    ring.set_key(new_key)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(ring.state["key"])), np.asarray(jax.device_get(new_key))
    )
    # the sampled batch is (T, B) over the whole ring env axis
    assert calls[0]["obs"] == (2, 4, 3)


def test_train_step_holds_until_every_env_has_a_window():
    """The in-graph belt: granted steps are zeroed while ANY env is shorter
    than a sample window (mirrors the host-side ready() gate)."""
    fabric = Fabric(devices=1, accelerator="cpu")
    ring = _ring(fabric)
    # only actor 0's columns have data; actor 1's are empty
    _commit(ring, [(_row(1.0), np.ones(LOCAL, np.int32))] * 2, 0)
    assert not ring.ready()

    def gradient_step(carry, xs):
        return carry + 1, (jnp.zeros(()),)

    train_fn, ctl_layout = build_seq_train_step(
        gradient_step, fabric.mesh,
        {"capacity": CAP, "n_envs": RING_ENVS, "grad_chunk": 2, "seq_len": 2, "batch_size": 4},
    )
    ctl = fabric.put_replicated(
        pack_burst_blob(ctl_layout, {"__validmask__": np.ones(2, np.float32)})
    )
    carry, _new_key, _m = train_fn(jnp.int32(0), ring.state, ctl)
    assert int(carry) == 0  # every step masked off in-graph


def test_checkpoint_roundtrip_restores_heads_and_key():
    ring = _ring()
    ones = np.ones(LOCAL, np.int32)
    _commit(ring, [(_row(7.0), ones), (_row(8.0), np.array([0, 1], np.int32))], 0)
    _commit(ring, [(_row(9.0), ones)], LOCAL)
    snap = ring.state_dict()
    assert snap.kind == "sequence"

    ring2 = _ring()
    ring2.load_state_dict(snap)
    s1, s2 = jax.device_get(ring.state), jax.device_get(ring2.state)
    for k in KEYS:
        np.testing.assert_array_equal(s1["storage"][k], s2["storage"][k])
    np.testing.assert_array_equal(s1["pos"], s2["pos"])
    np.testing.assert_array_equal(s1["valid"], s2["valid"])
    np.testing.assert_array_equal(s1["key"], s2["key"])
    np.testing.assert_array_equal(ring.host_pos, ring2.host_pos)

    with pytest.raises(ValueError, match="shape mismatch"):
        _ring(capacity=16).load_state_dict(snap)


def test_sequence_spillover_accounting():
    """The sequence shape (heads + validity working set + the gathered f32
    sample window) must RAISE the estimate over flat rows, and the
    resolve gate must reflect it — an over-budget sequence ring is refused
    even when its flat rows alone would fit."""
    flat = estimate_ring_bytes(KEYS, 1024, RING_ENVS)
    seq = estimate_ring_bytes(KEYS, 1024, RING_ENVS, sequence={"seq_len": 64, "batch_size": 16})
    assert seq > flat
    # window-validity working set alone is capacity * n_envs * 4
    assert seq - flat >= 1024 * RING_ENVS * 4

    # budget chosen between the two estimates: flat fits, sequence does not
    budget_gb = (flat + (seq - flat) / 2) / (1 << 30)
    ok_flat, _, _ = resolve_device_resident("auto", KEYS, 1024, RING_ENVS, 1, budget_gb)
    assert ok_flat
    ok_seq, _, reason = resolve_device_resident(
        "auto", KEYS, 1024, RING_ENVS, 1, budget_gb, sequence={"seq_len": 64, "batch_size": 16}
    )
    assert not ok_seq and "GiB/device" in reason
