"""Bit-exact sampling-parity tests: the device-resident index mappings
(:mod:`sheeprl_tpu.replay.indices`) against the host buffers under a SHARED
seed.

Method: both sides consume the SAME numpy PCG64 draw stream — the host
buffer through its normal ``sample`` path, the device side by issuing the
identical ``rng.integers`` calls and pushing the raw draws through the
in-graph eligible-row arithmetic. Identical draws + identical arithmetic
must yield identical index streams (and therefore identical sampled values),
covering wrap-around, write-head exclusion, and the next-obs shift.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data.buffers import ReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.replay import indices

CAP = 8
N_ENVS = 3


def _filled_uniform(n_rows: int, n_envs: int = N_ENVS):
    """Host buffer whose cell values uniquely encode (global step, env)."""
    rb = ReplayBuffer(CAP, n_envs, obs_keys=("observations",))
    for t in range(n_rows):
        row = np.full((1, n_envs, 1), t * 100, np.float32) + np.arange(n_envs).reshape(1, -1, 1)
        rb.add({"observations": row})
    return rb


def _device_uniform_stream(rb, seed, batch, sample_next_obs):
    """Replicate ReplayBuffer.sample's draw calls, map in-graph, gather."""
    rng = np.random.default_rng(seed)
    pos, full = rb._pos, rb._full
    n_elig = int(indices.uniform_eligible(jnp.int32(pos), jnp.int32(full), CAP, sample_next_obs))
    if full:
        draws = rng.integers(0, n_elig, size=(batch,), dtype=np.intp)
        rows = np.asarray(
            indices.map_uniform_draw(jnp.asarray(draws), jnp.int32(pos), jnp.int32(1), CAP, sample_next_obs)
        )
    else:
        rows = rng.integers(0, n_elig, size=(batch,), dtype=np.intp)
    env = rng.integers(0, rb.n_envs, size=(batch,), dtype=np.intp)
    storage = jnp.asarray(np.asarray(rb.buffer["observations"]))
    out = {"observations": np.asarray(storage[rows, env])}
    if sample_next_obs:
        nxt = np.asarray(indices.next_rows(jnp.asarray(rows), CAP))
        out["next_observations"] = np.asarray(storage[nxt, env])
    return out


@pytest.mark.parametrize("n_rows", [5, CAP, CAP + 3])  # partial, exactly-full, wrapped
@pytest.mark.parametrize("sample_next_obs", [False, True])
def test_uniform_parity_bit_exact(n_rows, sample_next_obs):
    seed, batch = 1234, 64
    rb = _filled_uniform(n_rows)
    rb.seed(seed)
    host = rb.sample(batch_size=batch, sample_next_obs=sample_next_obs)
    dev = _device_uniform_stream(rb, seed, batch, sample_next_obs)
    np.testing.assert_array_equal(host["observations"].reshape(batch, 1), dev["observations"])
    if sample_next_obs:
        np.testing.assert_array_equal(
            host["next_observations"].reshape(batch, 1), dev["next_observations"]
        )


def test_uniform_parity_write_head_wrap_edge():
    """pos == 0 on a full ring with next-obs sampling: the host builds its
    eligible rows from a NEGATIVE young_stop; the mapping must agree."""
    seed, batch = 7, 128
    rb = _filled_uniform(2 * CAP)  # wraps exactly back to pos == 0
    assert rb._pos == 0 and rb.full
    rb.seed(seed)
    host = rb.sample(batch_size=batch, sample_next_obs=True)
    dev = _device_uniform_stream(rb, seed, batch, True)
    np.testing.assert_array_equal(host["observations"].reshape(batch, 1), dev["observations"])
    np.testing.assert_array_equal(host["next_observations"].reshape(batch, 1), dev["next_observations"])


def test_uniform_excludes_write_head_when_full():
    """Semantics (not just parity): with next-obs pairing on a full ring the
    newest row (whose shifted pair would cross the head) is never drawn."""
    rb = _filled_uniform(CAP + 3)
    rb.seed(0)
    rng = np.random.default_rng(0)
    n_elig = int(indices.uniform_eligible(jnp.int32(rb._pos), jnp.int32(1), CAP, True))
    draws = rng.integers(0, n_elig, size=(4096,), dtype=np.intp)
    rows = np.asarray(indices.map_uniform_draw(jnp.asarray(draws), jnp.int32(rb._pos), jnp.int32(1), CAP, True))
    excluded = (rb._pos - 1) % CAP
    assert excluded not in set(rows.tolist())
    assert set(rows.tolist()) <= set(range(CAP)) - {excluded}


def _filled_seq(n_rows: int, n_envs: int):
    rb = SequentialReplayBuffer(CAP, n_envs, obs_keys=("observations",))
    for t in range(n_rows):
        row = np.full((1, n_envs, 1), t * 100, np.float32) + np.arange(n_envs).reshape(1, -1, 1)
        rb.add({"observations": row})
    return rb


@pytest.mark.parametrize("n_rows", [6, CAP, CAP + 5])
@pytest.mark.parametrize("n_envs", [1, N_ENVS])
def test_sequential_parity_bit_exact(n_rows, n_envs):
    seed, batch, seq_len = 99, 32, 3
    rb = _filled_seq(n_rows, n_envs)
    rb.seed(seed)
    host = rb.sample(batch_size=batch, sequence_length=seq_len)  # (1, T, B, 1)

    rng = np.random.default_rng(seed)
    pos, full = rb._pos, rb._full
    n_elig = int(indices.sequence_eligible(jnp.int32(pos), jnp.int32(full), CAP, seq_len))
    draws = rng.integers(0, n_elig, size=(batch,), dtype=np.intp)
    if full:
        starts = np.asarray(
            indices.map_sequence_draw(jnp.asarray(draws), jnp.int32(pos), jnp.int32(1), CAP, seq_len)
        )
    else:
        starts = draws
    if n_envs == 1:
        env = np.zeros((batch,), np.intp)
    else:
        env = rng.integers(0, n_envs, size=(batch,), dtype=np.intp)
    rows = np.asarray(indices.window_rows(jnp.asarray(starts), seq_len, CAP))  # (T, B)
    storage = np.asarray(rb.buffer["observations"])
    dev = storage[rows, env[None, :]]  # (T, B, 1)
    np.testing.assert_array_equal(host["observations"][0], dev)


def test_sequential_windows_never_cross_write_head():
    rb = _filled_seq(CAP + 5, 1)
    seq_len = 3
    pos = rb._pos
    n_elig = int(indices.sequence_eligible(jnp.int32(pos), jnp.int32(1), CAP, seq_len))
    draws = jnp.arange(n_elig)
    starts = np.asarray(indices.map_sequence_draw(draws, jnp.int32(pos), jnp.int32(1), CAP, seq_len))
    rows = np.asarray(indices.window_rows(jnp.asarray(starts), seq_len, CAP))  # (T, n_elig)
    # a window crosses the head iff it contains the transition (pos-1) -> pos
    for b in range(rows.shape[1]):
        w = rows[:, b].tolist()
        for a, c in zip(w[:-1], w[1:]):
            assert not (a == (pos - 1) % CAP and c == pos % CAP)


def test_prioritize_ends_clamp_matches_host_rule():
    """The widened-domain draw with overshoot clamp, vs the EpisodeBuffer
    arithmetic (`upper += seq_len; min(start, ep_len - seq_len)`) on the
    same draw stream."""
    seq_len, n_starts = 4, 10
    rng = np.random.default_rng(3)
    draws = rng.integers(0, n_starts + seq_len, size=(512,))
    ours = np.asarray(indices.prioritized_end_starts(jnp.asarray(draws), jnp.int32(n_starts), seq_len))
    oracle = np.minimum(draws, n_starts - 1)  # == min(start, ep_len - seq_len) at ring level
    np.testing.assert_array_equal(ours, oracle)
    # ends get extra mass: the clamp maps seq_len + 1 draw values onto the newest start
    assert (ours == n_starts - 1).sum() > (ours == 0).sum()
