"""In-graph sum-tree correctness against a plain numpy oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.replay import sumtree


class NumpyTree:
    """Oracle: flat priority array + cumsum search."""

    def __init__(self, n):
        self.p = np.zeros(n, np.float64)

    def update(self, idx, prio):
        self.p[np.asarray(idx)] = np.asarray(prio)  # fancy-assign: last wins

    def total(self):
        return self.p.sum()

    def sample(self, u):
        cs = np.cumsum(self.p)
        mass = np.minimum(np.asarray(u), 1.0 - 1e-7) * cs[-1]
        return np.searchsorted(cs, mass, side="right")


def test_leaf_count_pow2():
    assert sumtree.leaf_count(1) == 1
    assert sumtree.leaf_count(5) == 8
    assert sumtree.leaf_count(8) == 8
    assert sumtree.leaf_count(9) == 16
    with pytest.raises(ValueError):
        sumtree.leaf_count(0)


def test_total_and_internal_consistency():
    rng = np.random.default_rng(0)
    n = 21
    tree = sumtree.init(n)
    idx = jnp.arange(n)
    prios = rng.random(n).astype(np.float32)
    tree = sumtree.update(tree, idx, jnp.asarray(prios))
    assert np.isclose(float(sumtree.total(tree)), prios.sum(), rtol=1e-6)
    # every internal node equals the sum of its children
    t = np.asarray(tree)
    P = t.shape[0] // 2
    for i in range(1, P):
        assert np.isclose(t[i], t[2 * i] + t[2 * i + 1], rtol=1e-5)


def test_duplicate_updates_last_wins():
    tree = sumtree.init(8)
    tree = sumtree.update(tree, jnp.array([3, 3, 3]), jnp.array([1.0, 2.0, 7.0]))
    assert float(sumtree.get(tree, jnp.array([3]))[0]) == 7.0
    assert float(sumtree.total(tree)) == 7.0


@pytest.mark.parametrize("n", [4, 13, 64])
def test_sample_matches_oracle(n):
    rng = np.random.default_rng(n)
    prios = (rng.random(n) + 0.01).astype(np.float32)
    # zero out a few leaves — they must never be sampled
    prios[:: max(2, n // 4)] = 0.0
    tree = sumtree.update(sumtree.init(n), jnp.arange(n), jnp.asarray(prios))
    oracle = NumpyTree(n)
    oracle.update(np.arange(n), prios)
    u = rng.random(4096).astype(np.float32)
    got = np.asarray(sumtree.sample(tree, jnp.asarray(u)))
    want = oracle.sample(u)
    # float32 prefix sums can disagree with float64 exactly at interval
    # boundaries; allow only boundary-adjacent disagreements (< 0.1%)
    mismatch = got != want
    assert mismatch.mean() < 1e-3
    assert np.all(np.abs(got[mismatch] - want[mismatch]) <= 1) if mismatch.any() else True
    # never a zero-priority leaf, never out of range
    assert np.all(prios[got] > 0)


def test_sample_respects_proportions():
    n = 8
    prios = np.array([1, 0, 0, 0, 0, 0, 0, 3], np.float32)
    tree = sumtree.update(sumtree.init(n), jnp.arange(n), jnp.asarray(prios))
    key = jax.random.PRNGKey(0)
    u = jax.random.uniform(key, (20000,))
    got = np.asarray(sumtree.sample(tree, u))
    frac7 = (got == 7).mean()
    assert set(np.unique(got).tolist()) == {0, 7}
    assert abs(frac7 - 0.75) < 0.02


def test_update_is_jittable_and_incremental():
    n = 16
    step = jax.jit(lambda t, i, p: sumtree.update(t, i, p))
    tree = sumtree.init(n)
    oracle = NumpyTree(n)
    rng = np.random.default_rng(5)
    for _ in range(10):
        idx = rng.integers(0, n, size=(4,))
        prios = rng.random(4).astype(np.float32)
        # in-batch duplicates must resolve identically (last write wins)
        tree = step(tree, jnp.asarray(idx), jnp.asarray(prios))
        oracle.update(idx, prios)
    np.testing.assert_allclose(np.asarray(tree)[n:], oracle.p, rtol=1e-6)
    assert np.isclose(float(sumtree.total(tree)), oracle.total(), rtol=1e-6)


def test_importance_weights_formula():
    n = 4
    prios = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    tree = sumtree.update(sumtree.init(n), jnp.arange(n), jnp.asarray(prios))
    beta = 0.5
    idx = jnp.array([0, 3])
    w = np.asarray(sumtree.importance_weights(tree, idx, jnp.int32(n), jnp.float32(beta)))
    want = (n * prios[[0, 3]] / prios.sum()) ** (-beta)
    np.testing.assert_allclose(w, want, rtol=1e-5)
