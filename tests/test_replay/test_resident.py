"""End-to-end device-resident replay runs through the real CLI: SAC dry runs
(uniform + PER, 1/2 devices, env-sharded), checkpoint → resume round trips,
and the DreamerV3 resident path (auto-marked slow by conftest)."""

import glob
import os

import pytest

from sheeprl_tpu.cli import run


def _sac_args(tmp_path, devices=1, extra=()):
    args = [
        "exp=sac",
        "env=dummy",
        "env.id=continuous_dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "buffer.size=64",
        "buffer.device_resident=true",
        f"fabric.devices={devices}",
        "algo.per_rank_batch_size=8",
        "algo.hidden_size=16",
        "algo.mlp_keys.encoder=[state]",
        "algo.learning_starts=4",
        "algo.total_steps=16",
        "algo.run_test=False",
        "metric.log_level=0",
        "checkpoint.save_last=False",
        "checkpoint.every=0",
        f"log_root={tmp_path}/logs",
    ]
    args.extend(extra)
    return args


@pytest.mark.parametrize("devices", [1, 2])
def test_sac_resident_run(tmp_path, devices):
    """devices=2 with num_envs=2 exercises the env-sharded storage path."""
    run(_sac_args(tmp_path, devices=devices))


def test_sac_resident_prioritized(tmp_path):
    run(_sac_args(tmp_path, extra=["buffer.priority.enabled=true"]))


def test_sac_resident_checkpoint_resume(tmp_path):
    """Resident ring state (storage + heads + key + PER tree) survives a
    checkpoint → resume round trip through the real checkpoint machinery."""
    run(
        _sac_args(
            tmp_path,
            extra=[
                "buffer.priority.enabled=true",
                "checkpoint.every=8",
                "checkpoint.save_last=True",
                "algo.total_steps=16",
            ],
        )
    )
    ckpts = sorted(
        glob.glob(f"{tmp_path}/logs/**/*.ckpt", recursive=True), key=os.path.getmtime
    )
    assert ckpts, "resident run must produce a checkpoint"
    run(
        _sac_args(
            tmp_path,
            extra=[
                "buffer.priority.enabled=true",
                "algo.total_steps=24",
                f"checkpoint.resume_from={ckpts[-1]}",
            ],
        )
    )


def test_sac_resident_resume_onto_host_tier(tmp_path):
    """Crossover: a resident checkpoint resumed with the knob OFF lands on
    the host-sampling path and keeps the replay data."""
    run(
        _sac_args(
            tmp_path,
            extra=["checkpoint.every=8", "checkpoint.save_last=True", "algo.total_steps=16"],
        )
    )
    ckpts = sorted(
        glob.glob(f"{tmp_path}/logs/**/*.ckpt", recursive=True), key=os.path.getmtime
    )
    assert ckpts
    args = _sac_args(
        tmp_path, extra=["algo.total_steps=24", f"checkpoint.resume_from={ckpts[-1]}"]
    )
    args[args.index("buffer.device_resident=true")] = "buffer.device_resident=false"
    run(args)


def test_sac_spillover_falls_back_to_host(tmp_path):
    """buffer.device_resident=auto with a tiny HBM budget must run the host
    path (graceful spillover), not fail."""
    args = _sac_args(
        tmp_path,
        extra=["buffer.hbm_budget_gb=1e-9", "algo.total_steps=8"],
    )
    args[args.index("buffer.device_resident=true")] = "buffer.device_resident=auto"
    run(args)


DREAMER_RESIDENT = [
    "exp=dreamer_v3",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "buffer.size=32",
    "buffer.device_resident=true",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo=dreamer_v3_XS",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=2",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.world_model.reward_model.bins=17",
    "algo.critic.bins=17",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "env.screen_size=64",
    "algo.learning_starts=4",
]


def test_dreamer_v3_resident_checkpoint_resume(tmp_path):
    """Resident sequence ring (per-env heads, uint8 pixels) end-to-end:
    train, checkpoint, resume. Slow lane (conftest auto-marks dreamer)."""
    run(
        DREAMER_RESIDENT
        + [
            f"log_root={tmp_path}/logs",
            "algo.total_steps=16",
            "checkpoint.every=8",
            "checkpoint.save_last=True",
        ]
    )
    ckpts = sorted(
        glob.glob(f"{tmp_path}/logs/**/*.ckpt", recursive=True), key=os.path.getmtime
    )
    assert ckpts
    run(
        DREAMER_RESIDENT
        + [
            f"log_root={tmp_path}/logs",
            "algo.total_steps=24",
            "checkpoint.every=0",
            "checkpoint.save_last=False",
            f"checkpoint.resume_from={ckpts[-1]}",
        ]
    )
