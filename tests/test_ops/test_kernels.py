"""Pallas kernel tier vs the lax references (howto/kernels.md).

Every registered kernel: forward allclose + gradients via ``custom_vjp``
against ``jax.grad`` of the reference (f32 and bf16, interpret mode on the
CPU test mesh), registry dispatch semantics (auto/pallas/lax, per-kernel
override, named errors), and one-entry jit caches under both backends."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops import kernels as K
from sheeprl_tpu.replay import sumtree as st

EXPECTED_KERNELS = (
    "gae",
    "gru_gates",
    "ragged_ring_scatter",
    "sumtree_sample",
    "two_hot_symexp_decode",
    "two_hot_symlog_loss",
)


def _rng(seed=0):
    return np.random.default_rng(seed)


def _norm_logits(rng, shape, dtype=np.float32):
    logits = jnp.asarray(rng.normal(size=shape).astype(dtype))
    return logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)


def _gae_inputs(rng, T=16, B=6, trailing=(1,), dtype=np.float32):
    shape = (T, B) + trailing
    r = jnp.asarray(rng.normal(size=shape).astype(dtype))
    v = jnp.asarray(rng.normal(size=shape).astype(dtype))
    d = (jnp.asarray(rng.uniform(size=shape)) < 0.15).astype(jnp.float32)
    nv = jnp.asarray(rng.normal(size=shape[1:]).astype(dtype))
    return r, v, d, nv


def _tree(rng, leaves=64, filled=40):
    tree = st.init(leaves)
    pri = jnp.asarray(rng.uniform(0.1, 2.0, size=(filled,)).astype(np.float32))
    return st.update(tree, jnp.arange(filled), pri)


def _ring_case(rng, C=8, E=5, S=4, e=3, feat=(2,), dtype=np.float32):
    from sheeprl_tpu.data.ring import ring_append_rows

    pos = jnp.asarray([1, C - 1, 3], jnp.int32)  # includes a wrapping head
    valid = jnp.asarray([1, C - 1, 3], jnp.int32)
    mask = jnp.asarray([[1, 1, 1], [1, 0, 1], [0, 0, 1], [1, 0, 0]], jnp.int32)
    row, _, _ = ring_append_rows(pos, valid, mask, C)
    storage = jnp.asarray(rng.normal(size=(C, E) + feat).astype(dtype))
    staged = jnp.asarray(rng.normal(size=(S, e) + feat).astype(dtype))
    return storage, staged, row, pos


# ---------------------------------------------------------------------------
# registry dispatch semantics
# ---------------------------------------------------------------------------


def test_registry_names():
    assert K.names() == EXPECTED_KERNELS


def test_auto_resolves_to_lax_on_cpu():
    # the CPU test mesh: auto must keep the plain-lax references
    with K.use_backend("auto"):
        for name in K.names():
            assert K.resolve(name) == "lax"
            assert K.dispatch(name) is K.get(name).reference


def test_global_backend_switch():
    with K.use_backend("pallas"):
        assert all(K.resolve(n) == "pallas" for n in K.names())
        assert K.dispatch("gru_gates") is K.get("gru_gates").pallas
    with K.use_backend("lax"):
        assert all(K.resolve(n) == "lax" for n in K.names())


def test_per_kernel_override_beats_global():
    with K.use_backend("pallas", gae="lax"):
        assert K.resolve("gae") == "lax"
        assert K.resolve("gru_gates") == "pallas"
    with K.use_backend("lax", sumtree_sample="pallas"):
        assert K.resolve("sumtree_sample") == "pallas"
        assert K.resolve("gae") == "lax"


def test_per_call_backend_beats_everything():
    with K.use_backend("lax", gae="lax"):
        assert K.resolve("gae", backend="pallas") == "pallas"


def test_unknown_backend_named_error():
    with pytest.raises(K.UnknownOpsBackendError, match="tpu-magic"):
        K.configure(backend="tpu-magic")
    with pytest.raises(K.UnknownOpsBackendError, match="gae"):
        K.configure(overrides={"gae": "cuda"})
    with pytest.raises(K.UnknownOpsBackendError):
        K.resolve("gae", backend="nope")


def test_unknown_kernel_named_error():
    with pytest.raises(K.UnknownKernelError, match="flash_attention"):
        K.get("flash_attention")
    with pytest.raises(K.UnknownKernelError):
        K.configure(overrides={"flash_attention": "pallas"})


def test_configure_from_config_and_env_shape():
    cfg = {"backend": "lax", "kernels": {"gae": "pallas"}}
    with K.use_backend():  # snapshot/restore
        K.configure_from_config(cfg)
        assert K.backend() == "lax"
        assert K.resolve("gae") == "pallas"
        K.configure_from_config(None)  # missing block is a no-op
        assert K.backend() == "lax"


def test_ops_gae_export_goes_through_registry():
    import sheeprl_tpu.ops as ops

    assert ops.gae is K.gae


def test_pallas_gru_shim_is_the_pallas_variant():
    from sheeprl_tpu.ops import pallas_gru

    assert pallas_gru.gru_gates is K.gru_gates_pallas
    assert pallas_gru.gru_gates_reference is K.gru_gates_reference


# ---------------------------------------------------------------------------
# forward + gradient parity (interpret mode on CPU)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16], ids=["f32", "bf16"])
def test_gru_gates_parity(dtype):
    rng = _rng(1)
    fused = jnp.asarray(rng.normal(size=(7, 48)).astype(np.float32), dtype=dtype)
    h = jnp.asarray(rng.normal(size=(7, 16)).astype(np.float32), dtype=dtype)
    got = K.gru_gates(fused, h, backend="pallas")
    want = K.gru_gates_reference(fused, h)
    assert got.dtype == want.dtype
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-6
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=tol, atol=tol
    )


def test_gru_gates_grad_parity():
    rng = _rng(2)
    fused = jnp.asarray(rng.normal(size=(5, 24)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(5, 8)).astype(np.float32))
    g_got = jax.grad(lambda f, c: jnp.sum(K.gru_gates(f, c, backend="pallas") ** 2), (0, 1))(fused, h)
    g_want = jax.grad(lambda f, c: jnp.sum(K.gru_gates_reference(f, c) ** 2), (0, 1))(fused, h)
    for a, b in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16], ids=["f32", "bf16"])
@pytest.mark.parametrize("shape", [(6, 255), (3, 4, 63)], ids=["flat", "batched"])
def test_two_hot_symlog_loss_parity(dtype, shape):
    rng = _rng(3)
    logits = _norm_logits(rng, shape).astype(dtype)
    value = jnp.asarray(rng.normal(size=shape[:-1] + (1,)).astype(np.float32), dtype=dtype) * 4
    got = K.two_hot_symlog_loss(logits, value, backend="pallas")
    want = K.two_hot_symlog_loss_reference(logits, value)
    assert got.shape == want.shape and got.dtype == want.dtype
    if dtype == jnp.bfloat16:
        # the kernel computes in f32 and casts at the boundary, so its truth
        # is the f32 reference (bf16-quantized bins can shift the two-hot
        # indices in the all-bf16 lax chain; see the GRU bf16 test)
        want = K.two_hot_symlog_loss_reference(
            logits.astype(jnp.float32), value.astype(jnp.float32)
        )
        np.testing.assert_allclose(np.asarray(got, np.float32), np.asarray(want), rtol=2e-2, atol=5e-2)
    else:
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_two_hot_symlog_loss_grad_parity():
    rng = _rng(4)
    logits = _norm_logits(rng, (6, 63))
    value = jnp.asarray(rng.normal(size=(6, 1)).astype(np.float32)) * 4
    g_got = jax.grad(lambda l, v: K.two_hot_symlog_loss(l, v, backend="pallas").sum(), (0, 1))(logits, value)
    g_want = jax.grad(lambda l, v: K.two_hot_symlog_loss_reference(l, v).sum(), (0, 1))(logits, value)
    for a, b in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16], ids=["f32", "bf16"])
def test_two_hot_symexp_decode_parity(dtype):
    rng = _rng(5)
    logits = _norm_logits(rng, (6, 255)).astype(dtype)
    got = K.two_hot_symexp_decode(logits, backend="pallas")
    want = K.two_hot_symexp_decode_reference(logits)
    assert got.shape == want.shape and got.dtype == want.dtype
    if dtype == jnp.bfloat16:
        want = K.two_hot_symexp_decode_reference(logits.astype(jnp.float32))
        np.testing.assert_allclose(np.asarray(got, np.float32), np.asarray(want), rtol=5e-2, atol=5e-2)
    else:
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_two_hot_symexp_decode_grad_parity():
    rng = _rng(6)
    logits = _norm_logits(rng, (6, 63))
    g_got = jax.grad(lambda l: K.two_hot_symexp_decode(l, backend="pallas").sum())(logits)
    g_want = jax.grad(lambda l: K.two_hot_symexp_decode_reference(l).sum())(logits)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16], ids=["f32", "bf16"])
@pytest.mark.parametrize("trailing", [(1,), ()], ids=["TB1", "TB"])
def test_gae_parity(dtype, trailing):
    rng = _rng(7)
    r, v, d, nv = _gae_inputs(rng, trailing=trailing, dtype=np.float32)
    r, v, nv = (x.astype(dtype) for x in (r, v, nv))
    ret_p, adv_p = K.gae(r, v, d, nv, 0.99, 0.95, backend="pallas")
    ret_l, adv_l = K.gae(r, v, d, nv, 0.99, 0.95, backend="lax")
    assert ret_p.dtype == ret_l.dtype == jnp.float32  # f32 accumulation both ways
    np.testing.assert_allclose(np.asarray(ret_p), np.asarray(ret_l), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(adv_p), np.asarray(adv_l), rtol=1e-6, atol=1e-6)


def test_gae_grad_parity():
    rng = _rng(8)
    r, v, d, nv = _gae_inputs(rng)

    def loss(backend, r_, v_, nv_):
        ret, adv = K.gae(r_, v_, d, nv_, 0.99, 0.95, backend=backend)
        return (ret * adv).sum()

    g_got = jax.grad(lambda *a: loss("pallas", *a), (0, 1, 2))(r, v, nv)
    g_want = jax.grad(lambda *a: loss("lax", *a), (0, 1, 2))(r, v, nv)
    for a, b in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_sumtree_sample_parity():
    rng = _rng(9)
    tree = _tree(rng)
    u = jnp.asarray(rng.uniform(size=(17,)).astype(np.float32))
    n_valid = jnp.asarray(40, jnp.int32)
    beta = jnp.asarray(0.4, jnp.float32)
    leaf_p, w_p = K.sumtree_sample(tree, u, n_valid, beta, backend="pallas")
    leaf_l, w_l = K.sumtree_sample(tree, u, n_valid, beta, backend="lax")
    np.testing.assert_array_equal(np.asarray(leaf_p), np.asarray(leaf_l))
    np.testing.assert_allclose(np.asarray(w_p), np.asarray(w_l), rtol=1e-6, atol=1e-7)


def test_sumtree_sample_grad_parity():
    rng = _rng(10)
    tree = _tree(rng)
    u = jnp.asarray(rng.uniform(size=(9,)).astype(np.float32))
    n_valid = jnp.asarray(40, jnp.int32)

    def loss(backend, tree_, beta_):
        return K.sumtree_sample(tree_, u, n_valid, beta_, backend=backend)[1].sum()

    beta = jnp.asarray(0.4, jnp.float32)
    g_got = jax.grad(lambda t, b: loss("pallas", t, b), (0, 1))(tree, beta)
    g_want = jax.grad(lambda t, b: loss("lax", t, b), (0, 1))(tree, beta)
    for a, b in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize(
    "dtype", [np.float32, jnp.bfloat16, np.uint8], ids=["f32", "bf16", "u8"]
)
@pytest.mark.parametrize("feat", [(2,), ()], ids=["feature", "scalar"])
def test_ragged_ring_scatter_parity(dtype, feat):
    rng = _rng(11)
    storage, staged, row, pos = _ring_case(rng, feat=feat)
    if dtype == np.uint8:
        storage = (jnp.abs(storage) * 20).astype(jnp.uint8)
        staged = (jnp.abs(staged) * 20).astype(jnp.uint8)
    else:
        storage, staged = storage.astype(dtype), staged.astype(dtype)
    off = jnp.asarray(1, jnp.int32)
    got = K.ragged_ring_scatter(storage, staged, row, pos, off, backend="pallas")
    want = K.ragged_ring_scatter(storage, staged, row, pos, off, backend="lax")
    # a scatter copies values: parity is exact for every dtype
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_ragged_ring_scatter_all_dropped_column():
    """An env whose every slot is masked out must keep its column untouched
    (the dropped slots park on (pos-1) % C and write the old value back)."""
    rng = _rng(12)
    from sheeprl_tpu.data.ring import ring_append_rows

    C, S, e = 6, 3, 2
    pos = jnp.asarray([0, 4], jnp.int32)
    valid = jnp.asarray([0, 4], jnp.int32)
    mask = jnp.asarray([[0, 1], [0, 1], [0, 0]], jnp.int32)
    row, _, _ = ring_append_rows(pos, valid, mask, C)
    storage = jnp.asarray(rng.normal(size=(C, e, 3)).astype(np.float32))
    staged = jnp.asarray(rng.normal(size=(S, e, 3)).astype(np.float32))
    got = K.ragged_ring_scatter(storage, staged, row, pos, 0, backend="pallas")
    want = K.ragged_ring_scatter(storage, staged, row, pos, 0, backend="lax")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got[:, 0]), np.asarray(storage[:, 0]))


def test_ragged_ring_scatter_grad_parity():
    rng = _rng(13)
    storage, staged, row, pos = _ring_case(rng)
    off = jnp.asarray(1, jnp.int32)

    def loss(backend, s, t):
        return (K.ragged_ring_scatter(s, t, row, pos, off, backend=backend) ** 2).sum()

    g_got = jax.grad(lambda s, t: loss("pallas", s, t), (0, 1))(storage, staged)
    g_want = jax.grad(lambda s, t: loss("lax", s, t), (0, 1))(storage, staged)
    for a, b in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# jit stability: one cache entry per kernel under both backends
# ---------------------------------------------------------------------------


def _kernel_calls():
    rng = _rng(14)
    fused = jnp.asarray(rng.normal(size=(7, 24)).astype(np.float32))
    h = jnp.asarray(rng.normal(size=(7, 8)).astype(np.float32))
    logits = _norm_logits(rng, (6, 63))
    value = jnp.asarray(rng.normal(size=(6, 1)).astype(np.float32))
    r, v, d, nv = _gae_inputs(rng, T=8, B=4)
    tree = _tree(rng, leaves=32, filled=20)
    u = jnp.asarray(rng.uniform(size=(5,)).astype(np.float32))
    storage, staged, row, pos = _ring_case(rng)
    return {
        "gru_gates": (lambda b: lambda f_, h_: K.gru_gates(f_, h_, backend=b), (fused, h)),
        "two_hot_symlog_loss": (
            lambda b: lambda l_, v_: K.two_hot_symlog_loss(l_, v_, backend=b), (logits, value)
        ),
        "two_hot_symexp_decode": (
            lambda b: lambda l_: K.two_hot_symexp_decode(l_, backend=b), (logits,)
        ),
        "gae": (lambda b: lambda *a: K.gae(*a, 0.99, 0.95, backend=b), (r, v, d, nv)),
        "sumtree_sample": (
            lambda b: lambda t_, u_: K.sumtree_sample(t_, u_, jnp.asarray(20, jnp.int32), jnp.asarray(0.4, jnp.float32), backend=b),
            (tree, u),
        ),
        "ragged_ring_scatter": (
            lambda b: lambda s_, t_: K.ragged_ring_scatter(s_, t_, row, pos, 1, backend=b),
            (storage, staged),
        ),
    }


@pytest.mark.parametrize("backend", ["lax", "pallas"])
def test_cache_size_one_per_kernel(backend):
    for name, (make, args) in _kernel_calls().items():
        jitted = jax.jit(make(backend))
        jax.block_until_ready(jitted(*args))
        jax.block_until_ready(jitted(*args))
        assert jitted._cache_size() == 1, f"{name} retraced under backend={backend}"
