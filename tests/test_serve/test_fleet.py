"""FleetRouter unit drills over IN-PROCESS replica servers (real sockets,
real protocol, no subprocesses): least-loaded spread, monotone
fleet_version annotation, session stickiness + counted client-visible
re-homing, fleet-wide load shedding, replica-endpoint timeouts against a
deliberately hung server, and the typed PolicyClient timeout. The
process-lifecycle half (SIGKILL/respawn under load) lives in
``test_fleet_chaos.py``."""

import collections
import json
import socket
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.fault import inject
from sheeprl_tpu.serve.fleet import FleetReplicaError, FleetRouter, ReplicaEndpoint
from sheeprl_tpu.serve.scheduler import ServeTimeoutError
from sheeprl_tpu.serve.server import PolicyServer


@pytest.fixture(autouse=True)
def _inject_isolation():
    inject.reset()
    yield
    inject.reset()


def _wait(predicate, timeout=10.0, poll=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


def _stand_up_fleet(policy, n=2, stateful=False, **router_cfg):
    """N in-process PolicyServers with socket front ends + a router over
    them (no process supervisor — lifecycle drills live in the chaos
    module)."""
    servers = []
    endpoints = []
    for i in range(n):
        cfg = {"buckets": [1, 4], "port": 0, "max_wait_ms": 1.0}
        if stateful:
            cfg["session"] = {"buckets": [1, 4], "max_sessions": 32}
        server = PolicyServer(policy, cfg).start()
        host, port = server.address
        servers.append(server)
        endpoints.append(ReplicaEndpoint(f"replica-{i}", host, port, request_timeout_s=10.0))
    cfg = {"health_poll_s": 0.05, "health_timeout_s": 2.0, "retry_budget": 2, **router_cfg}
    router = FleetRouter(endpoints, fleet_cfg=cfg, port=None).start()
    assert router.wait_ready(timeout_s=30)
    return router, servers, endpoints


def _teardown(router, servers):
    router.stop()
    for s in servers:
        s.stop()


def test_least_loaded_routing_spreads_and_annotates(toy_policy):
    """Serial traffic spreads over the fleet (rotating tie-break), every
    response names its replica and carries a per-connection monotone
    fleet_version."""
    router, servers, _eps = _stand_up_fleet(toy_policy, n=2)
    try:
        used = collections.Counter()
        last_version = -10
        for i in range(12):
            resp = router.serve_request({"obs": {"x": [[1.0, float(i)]]}, "n": 1})
            assert "error" not in resp, resp
            assert resp["actions"] is not None
            used[resp["replica"]] += 1
            assert resp["fleet_version"] >= last_version
            last_version = resp["fleet_version"]
        assert set(used) == {"replica-0", "replica-1"}
        assert min(used.values()) >= 4  # spread, not pinned
    finally:
        _teardown(router, servers)


def test_aggregated_health_reflects_fleet_state(toy_policy):
    router, servers, _eps = _stand_up_fleet(toy_policy, n=2)
    try:
        health = router.health()
        assert health["status"] == "ok" and health["ready"] is True
        assert health["fleet"]["replicas"] == 2 and health["fleet"]["ready"] == 2
        assert set(health["replicas"]) == {"replica-0", "replica-1"}
        for entry in health["replicas"].values():
            assert entry["ready"] is True and entry["status"] == "ok"
            assert "version" in entry and "step" in entry
        # one replica down -> degraded; both -> down
        servers[0].stop()
        assert _wait(lambda: router.health()["status"] == "degraded", timeout=15)
        servers[1].stop()
        assert _wait(lambda: router.health()["status"] == "down", timeout=15)
        assert router.health()["ready"] is False
    finally:
        router.stop()


def test_sessions_stick_to_one_replica(toy_stateful_policy):
    """A session's stream (actions[:, 0] counting 0,1,2,...) must ride ONE
    replica even while stateless traffic rotates."""
    router, servers, _eps = _stand_up_fleet(toy_stateful_policy, n=2, stateful=True)
    try:
        obs = {"obs": {"x": [[1.0, 2.0]]}, "n": 1}
        homes = set()
        for step in range(6):
            resp = router.serve_request({**obs, "session_id": "user-a"})
            assert "error" not in resp, resp
            assert resp["actions"][0][0] == float(step)  # contiguous stream
            homes.add(resp["replica"])
            router.serve_request(obs)  # interleaved stateless traffic
        assert len(homes) == 1
    finally:
        _teardown(router, servers)


def test_session_rehome_on_replica_death_is_counted_and_visible(toy_stateful_policy):
    """Home replica dies -> the session re-homes to a survivor with the
    re-init COUNTED (sessions_rehomed) and CLIENT-VISIBLE (rehomed flag +
    the stream restarting from its init state) — never silently wrong
    state."""
    router, servers, eps = _stand_up_fleet(toy_stateful_policy, n=2, stateful=True)
    victim = None
    try:
        obs = {"obs": {"x": [[1.0, 2.0]]}, "n": 1}
        for step in range(3):
            resp = router.serve_request({**obs, "session_id": "user-a"})
            assert resp["actions"][0][0] == float(step)
        home = resp["replica"]
        victim = next(s for s, ep in zip(servers, eps) if ep.name == home)
        victim.stop()
        assert _wait(lambda: not next(ep for ep in eps if ep.name == home).ready, timeout=15)
        resp = router.serve_request({**obs, "session_id": "user-a"})
        assert "error" not in resp, resp
        assert resp["replica"] != home
        assert resp.get("rehomed") is True
        assert resp["actions"][0][0] == 0.0  # visible re-init, not silent state
        assert router.counters["sessions_rehomed"] == 1
        # the stream continues contiguously on the new home, no more rehomes
        resp = router.serve_request({**obs, "session_id": "user-a"})
        assert resp["actions"][0][0] == 1.0 and "rehomed" not in resp
        assert router.counters["sessions_rehomed"] == 1
    finally:
        router.stop()
        for s in servers:
            if s is not victim:
                s.stop()


def test_midflight_failover_retries_within_budget(toy_policy):
    """A replica that dies between the probe and the request: the router
    retries toward a survivor inside the per-request budget instead of
    erroring the caller."""
    # one immediate tick marks everyone ready, then the loop sleeps for 30s:
    # the router's view is frozen stale for the whole test window
    router, servers, eps = _stand_up_fleet(toy_policy, n=2, health_poll_s=30.0)
    try:
        # kill replica-0's socket WITHOUT the health loop noticing
        servers[0].stop()
        with router._lock:
            eps[0].ready = True  # stale view: the router still believes in it
            eps[1].inflight = 1  # least-loaded MUST pick the dead replica first
        resp = router.serve_request({"obs": {"x": [[1.0, 2.0]]}, "n": 1})
        with router._lock:
            eps[1].inflight = 0
        assert "error" not in resp, resp
        assert resp["replica"] == "replica-1"
        assert router.counters["retries"] >= 1
        assert router.counters["replica_errors"] >= 1
    finally:
        router.stop()
        servers[1].stop()


def test_fleet_wide_shed_propagates_overload_error(toy_policy):
    """No READY replica -> ServeOverloadedError backpressure, counted, not
    an unbounded router queue; recovery restores service."""
    router, servers, _eps = _stand_up_fleet(toy_policy, n=2)
    try:
        for s in servers:
            s.stop()
        assert _wait(lambda: router.health()["status"] == "down", timeout=15)
        resp = router.serve_request({"obs": {"x": [[1.0, 2.0]]}, "n": 1})
        assert "ServeOverloadedError" in resp["error"]
        assert router.counters["shed"] == 1
    finally:
        router.stop()


def test_max_inflight_sheds_instead_of_queueing(toy_policy):
    """Every READY replica at max_inflight -> immediate backpressure."""
    router, servers, eps = _stand_up_fleet(toy_policy, n=2, max_inflight=1)
    try:
        with router._lock:
            for ep in eps:
                ep.inflight = 1  # saturate the router's view
        resp = router.serve_request({"obs": {"x": [[1.0, 2.0]]}, "n": 1})
        assert "ServeOverloadedError" in resp["error"]
        assert router.counters["shed"] == 1
    finally:
        with router._lock:
            for ep in eps:
                ep.inflight = 0
        _teardown(router, servers)


def test_replica_endpoint_times_out_against_hung_server():
    """The client-side half of the hung-replica bugfix: a server that
    accepts but never answers fails the call with a TYPED error inside the
    timeout instead of pinning the caller forever."""
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    try:
        ep = ReplicaEndpoint("hung", "127.0.0.1", listener.getsockname()[1], request_timeout_s=0.3)
        start = time.monotonic()
        with pytest.raises(FleetReplicaError, match="no response within") as excinfo:
            ep.request({"obs": {"x": [[1.0, 2.0]]}, "n": 1})
        assert excinfo.value.timed_out is True
        assert time.monotonic() - start < 5.0  # bounded, not forever
        ep.close()
    finally:
        listener.close()


def test_policy_client_timeout_s_is_typed_and_bounded(toy_policy):
    """PolicyClient.timeout_s: a hung scheduler worker (chaos hang at the
    batch point) raises the typed ServeTimeoutError inside the bound; the
    pre-fix behavior (timeout=None) waited forever."""
    server = PolicyServer(toy_policy, {"buckets": [1, 4], "port": None, "client_timeout_s": 0.3}).start()
    try:
        assert server.client.timeout_s == 0.3
        inject.arm("serve.scheduler.batch", action="hang", at=1, hang_s=2.0)
        start = time.monotonic()
        with pytest.raises(ServeTimeoutError):
            server.client.act({"x": np.ones((1, 2), np.float32)}, n=1)
        assert time.monotonic() - start < 2.0
        inject.release_hangs()
    finally:
        inject.reset()
        server.stop()


def test_staleness_alarm_flips_health_to_degraded(toy_policy):
    """serve.max_staleness_s: weights older than the threshold flip the
    probe to degraded (stale flagged, Serve/weights_stale counted); a fresh
    publish recovers to ok."""
    server = PolicyServer(toy_policy, {"buckets": [1], "port": None, "max_staleness_s": 0.1}).start()
    try:
        assert _wait(lambda: server.health()["status"] == "degraded", timeout=10)
        health = server.health()
        assert health["weights"]["stale"] is True
        assert health["ready"] is True  # degraded still serves; it is VISIBLE
        assert server.stats.snapshot()["Serve/weights_stale"] == 1
        server.weights.publish_params(toy_policy.params)
        health = server.health()
        assert health["status"] == "ok" and health["weights"]["stale"] is False
        # a second wedge counts a second transition
        assert _wait(lambda: server.health()["status"] == "degraded", timeout=10)
        assert server.stats.snapshot()["Serve/weights_stale"] == 2
    finally:
        server.stop()


def test_router_drain_rejects_new_requests(toy_policy):
    router, servers, _eps = _stand_up_fleet(toy_policy, n=2)
    try:
        router._draining = True
        resp = router.serve_request({"obs": {"x": [[1.0, 2.0]]}, "n": 1})
        assert "ServeClosedError" in resp["error"]
    finally:
        router._draining = False
        _teardown(router, servers)
