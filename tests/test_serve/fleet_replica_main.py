"""Standalone toy replica process for the fleet tests (NOT a test module).

Runs one real :class:`~sheeprl_tpu.serve.server.PolicyServer` — socket front
end, supervised scheduler, optional shared-dir checkpoint watcher, SIGTERM
graceful drain, exit 0 — around the same toy policies the serve conftest
uses, so fleet drills pay toy-compile startup (a couple of seconds) instead
of a full CLI checkpoint load per replica. The protocol, health probe, drain
and watcher behavior are the production code paths; only the policy is toy.

Usage::

    python fleet_replica_main.py --port 0 [--stateful] [--watch DIR]
        [--watch-poll 0.05] [--buckets 1,4] [--max-wait-ms 1]
        [--queue-bound 64] [--request-timeout 30]

Prints ``REPLICA_READY host:port`` once the socket is up (port 0 support for
single-replica tests; fleet tests pass fixed ports so respawns rebind).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading

# runnable from anywhere: the repo root (two levels up) onto sys.path
sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))


def build_policy(stateful: bool):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.serve.policy import ServePolicy, StatefulServePolicy

    if stateful:
        # counter policy: action row = [count, w·obs_sum]; any reset, drop,
        # reorder or cross-session mixup is visible in the action values
        w = jnp.asarray(np.arange(4, dtype=np.float32).reshape(2, 2))

        def step_fn(p, obs, state, key, greedy):
            del key, greedy
            count = state["count"][:, 0]
            y = (obs["x"] @ p["w"]).sum(-1)
            return jnp.stack([count, y], axis=-1), {"count": state["count"] + 1.0}

        def init_fn(p, n):
            del p
            return {"count": jnp.zeros((n, 1), jnp.float32)}

        return StatefulServePolicy(
            name="toy_stateful",
            params={"w": w},
            obs_spec={"x": ((2,), np.float32)},
            action_dim=2,
            step_fn=step_fn,
            init_fn=init_fn,
            prepare=lambda obs, n: {"x": np.asarray(obs["x"], np.float32).reshape(n, 2)},
            params_from_state=lambda state: jax.tree.map(jnp.asarray, state),
        )

    # linear map policy: actions scale with the params, so a weight swap is
    # observable in the action values themselves
    w = jnp.asarray(np.arange(6, dtype=np.float32).reshape(2, 3))

    def greedy_fn(p, obs):
        return obs["x"] @ p["w"]

    def sample_fn(p, obs, key):
        noise = jax.random.normal(key, (obs["x"].shape[0], 3), dtype=jnp.float32)
        return obs["x"] @ p["w"] + 1e-3 * noise

    return ServePolicy(
        name="toy",
        params={"w": w},
        obs_spec={"x": ((2,), np.float32)},
        action_dim=3,
        greedy_fn=greedy_fn,
        sample_fn=sample_fn,
        prepare=lambda obs, n: {"x": np.asarray(obs["x"], dtype=np.float32).reshape(n, 2)},
        params_from_state=lambda state: jax.tree.map(jnp.asarray, state),
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--stateful", action="store_true")
    parser.add_argument("--watch", default=None)
    parser.add_argument("--watch-poll", type=float, default=0.05)
    parser.add_argument("--buckets", default="1,4")
    parser.add_argument("--max-wait-ms", type=float, default=1.0)
    parser.add_argument("--queue-bound", type=int, default=64)
    parser.add_argument("--request-timeout", type=float, default=30.0)
    parser.add_argument("--max-staleness", type=float, default=None)
    args = parser.parse_args()

    from sheeprl_tpu.utils.utils import pin_cpu_platform

    pin_cpu_platform("cpu")

    from sheeprl_tpu.serve.server import PolicyServer, install_drain_handlers

    policy = build_policy(args.stateful)
    buckets = [int(b) for b in args.buckets.split(",") if b.strip()]
    cfg = {
        "buckets": buckets,
        "host": args.host,
        "port": args.port,
        "max_wait_ms": args.max_wait_ms,
        "queue_bound": args.queue_bound,
        "request_timeout_s": args.request_timeout,
        "watch_poll_s": args.watch_poll,
        # a respawned replica must rejoin on the newest complete save
        "watch_publish_current": True,
        "supervisor": {"backoff": 0.02},
    }
    if args.max_staleness is not None:
        cfg["max_staleness_s"] = args.max_staleness
    if args.stateful:
        cfg["session"] = {"buckets": buckets, "ttl_s": 300.0, "max_sessions": 64}
    drain = threading.Event()
    restore = install_drain_handlers(drain)
    server = PolicyServer(policy, cfg, watch_dir=args.watch)
    server.start()
    host, port = server.address
    print(f"REPLICA_READY {host}:{port}", flush=True)
    try:
        while not drain.is_set():
            drain.wait(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()  # graceful drain: nothing admitted is dropped
        restore()
        print(json.dumps({**server.stats.snapshot(), **server.engine.stats()}), flush=True)
        if drain.is_set():
            print("serve: drained cleanly", flush=True)


if __name__ == "__main__":
    main()
