"""Deadline/size admission, backpressure, drain-on-stop, Serve/* metrics."""

import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.serve.engine import BucketEngine
from sheeprl_tpu.serve.scheduler import RequestScheduler, ServeClosedError, ServeOverloadedError, ServeStats
from sheeprl_tpu.serve.weights import WeightStore


class SlowEngine:
    """Engine stub: records batch sizes, optionally sleeps per dispatch (so
    tests can pile requests up behind a busy worker), returns row indices."""

    def __init__(self, policy, delay_s=0.0):
        self.policy = policy
        self.delay_s = delay_s
        self.batches = []
        self.buckets = (64,)
        self.release = threading.Event()
        self.release.set()

    def infer(self, params, obs, key=None, greedy=True):
        self.release.wait(timeout=10.0)
        if self.delay_s:
            time.sleep(self.delay_s)
        n = self.policy.validate_batch(obs)
        self.batches.append(n)
        return np.arange(n, dtype=np.float32)[:, None] * np.ones((1, 3), np.float32)


def _row(i):
    return {"x": np.full((1, 2), float(i), np.float32)}


def _sched(policy, engine, **kw):
    store = WeightStore(policy.params, policy.params_from_state)
    defaults = dict(max_wait_s=0.01, queue_bound=64)
    defaults.update(kw)
    return RequestScheduler(engine, store, **defaults).start(), store


def test_concurrent_requests_coalesce(toy_policy):
    """Requests landing inside one max-wait window share ONE dispatch."""
    engine = SlowEngine(toy_policy)
    engine.release.clear()  # hold the worker until both requests are queued
    sched, _ = _sched(toy_policy, engine, max_wait_s=0.05, max_batch=8)
    reqs = [sched.submit(_row(i)) for i in range(3)]
    engine.release.set()
    results = [sched.result(r, timeout=5.0) for r in reqs]
    sched.stop()
    assert engine.batches == [3]
    # each caller got its own rows back, in submit order
    for i, (actions, _) in enumerate(results):
        assert np.allclose(actions, i)
    assert sched.stats.snapshot()["Serve/batches"] == 1


def test_max_wait_deadline_honored(toy_policy):
    """A lone request is served once the deadline fires — not held for a
    full batch — and its latency stays near max_wait."""
    engine = SlowEngine(toy_policy)
    sched, _ = _sched(toy_policy, engine, max_wait_s=0.02, max_batch=64)
    req = sched.submit(_row(0))
    actions, _ = sched.result(req, timeout=5.0)
    sched.stop()
    assert engine.batches == [1]
    # worker poll granularity (50ms first-request poll) is the slack bound
    assert req.latency_s < 0.02 + 0.2


def test_max_batch_admission_and_holdover(toy_policy):
    """With the worker held, 6 queued single-row requests against
    max_batch=4 split 4 + 2, never reordered."""
    engine = SlowEngine(toy_policy)
    engine.release.clear()
    sched, _ = _sched(toy_policy, engine, max_wait_s=0.01, max_batch=4)
    reqs = [sched.submit(_row(i)) for i in range(6)]
    engine.release.set()
    results = [sched.result(r, timeout=5.0)[0] for r in reqs]
    sched.stop()
    assert engine.batches == [4, 2]
    assert np.allclose(results[0], 0) and np.allclose(results[3], 3)  # first batch rows 0..3
    assert np.allclose(results[4], 0) and np.allclose(results[5], 1)  # second batch rows 0..1


def test_backpressure_past_queue_bound(toy_policy):
    """queue_bound pending requests block further submits; a bounded-timeout
    submit raises ServeOverloadedError and counts as rejected."""
    engine = SlowEngine(toy_policy)
    engine.release.clear()  # worker never drains
    sched, _ = _sched(toy_policy, engine, queue_bound=2, max_wait_s=0.0)
    sched.submit(_row(0))
    # worker may have pulled the first into its in-flight batch; fill to the
    # bound regardless
    deadline = time.perf_counter() + 2.0
    queued = 0
    while queued < 2 and time.perf_counter() < deadline:
        try:
            sched.submit(_row(queued), timeout=0.05)
            queued += 1
        except ServeOverloadedError:
            break
    with pytest.raises(ServeOverloadedError):
        sched.submit(_row(99), timeout=0.05)
    assert sched.stats.snapshot()["Serve/rejected"] >= 1
    engine.release.set()
    sched.stop()


def test_stop_drains_admitted_requests(toy_policy):
    """Shutdown never drops: everything admitted resolves."""
    engine = SlowEngine(toy_policy, delay_s=0.01)
    engine.release.clear()
    sched, _ = _sched(toy_policy, engine, max_wait_s=0.0, max_batch=2, queue_bound=64)
    reqs = [sched.submit(_row(i)) for i in range(10)]
    engine.release.set()
    sched.stop(drain=True)
    for r in reqs:
        actions, _ = sched.result(r, timeout=5.0)
        assert actions is not None
    assert sum(engine.batches) == 10
    with pytest.raises(ServeClosedError):
        sched.submit(_row(0))


def test_real_engine_end_to_end(toy_policy):
    """Scheduler over the real AOT engine: results match the direct path."""
    import jax

    engine = BucketEngine(toy_policy, buckets=(1, 4), mode="greedy")
    sched, _ = _sched(toy_policy, engine, max_wait_s=0.002)
    obs = {"x": np.random.default_rng(0).standard_normal((3, 2)).astype(np.float32)}
    req = sched.submit(obs)
    actions, version = sched.result(req, timeout=5.0)
    sched.stop()
    assert version == 0
    assert np.array_equal(actions, np.asarray(jax.jit(toy_policy.greedy_fn)(toy_policy.params, obs)))


def test_serve_stats_snapshot_keys(toy_policy):
    stats = ServeStats()
    stats.observe_latency(0.002)
    stats.observe_latency(0.004)
    stats.observe_version(3)
    snap = stats.snapshot()
    for key in (
        "Serve/requests",
        "Serve/rows",
        "Serve/batches",
        "Serve/rows_per_batch",
        "Serve/rejected",
        "Serve/queue_depth",
        "Serve/weight_version",
        "Serve/swap_count",
        "Serve/p50_latency_ms",
        "Serve/p99_latency_ms",
    ):
        assert key in snap, key
    assert snap["Serve/weight_version"] == 3
    assert snap["Serve/swap_count"] == 3
    assert 2.0 <= snap["Serve/p50_latency_ms"] <= 4.0
    p50, p99 = stats.latency_percentiles()
    assert p50 <= p99
