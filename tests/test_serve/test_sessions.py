"""graft-sessions units: the session cache (TTL sweep, LRU spill cap,
generation-tagged re-init), the session engine (stream continuity through
bucket-padded batched stepping, donor padding, zero retraces, ordered
chunking), the scheduler's session admission rules, hot-swap semantics, and
the bit-parity-of-batched-stepping claim for the real ppo_recurrent policy."""

import threading
import time

import jax
import numpy as np
import pytest

from sheeprl_tpu.serve.engine import check_chunk_order, chunk_plan
from sheeprl_tpu.serve.server import PolicyServer
from sheeprl_tpu.serve.sessions import SessionCache, SessionEngine


def _spec():
    return {"count": jax.ShapeDtypeStruct((1,), np.float32)}


# -- SessionCache ------------------------------------------------------------- #


def test_cache_touch_new_live_and_reset():
    cache = SessionCache(_spec(), max_sessions=4, ttl_s=100.0)
    row, fresh = cache.touch("a", now=0.0)
    assert fresh and 0 <= row < 4
    cache.mark_stepped(["a"])  # a dispatch initialized the row
    row2, fresh2 = cache.touch("a", now=1.0)
    assert row2 == row and not fresh2
    # client reset: same row, fresh state, counted separately from swaps
    row3, fresh3 = cache.touch("a", reset=True, now=2.0)
    assert row3 == row and fresh3
    snap = cache.snapshot()
    assert snap["live"] == 1 and snap["opened"] == 1
    assert snap["client_resets"] == 1 and snap["resets"] == 0
    assert cache.drop("a") and not cache.drop("a")
    assert cache.live == 0


def test_cache_fresh_is_sticky_until_stepped():
    """A dispatch failure between admission and step must NOT launder a
    never-initialized session into a 'live' one reading stale slab content:
    fresh stays set until mark_stepped confirms a dispatch ran."""
    cache = SessionCache(_spec(), max_sessions=4, ttl_s=100.0)
    _, fresh = cache.touch("a", now=0.0)
    assert fresh
    _, fresh = cache.touch("a", now=1.0)  # no dispatch happened in between
    assert fresh
    cache.mark_stepped(["a"])
    _, fresh = cache.touch("a", now=2.0)
    assert not fresh
    cache.mark_stepped(["ghost"])  # unknown ids are ignored


def test_cache_lru_spill_cap():
    cache = SessionCache(_spec(), max_sessions=2, ttl_s=100.0)
    cache.touch("a", now=0.0)
    cache.touch("b", now=1.0)
    cache.touch("a", now=2.0)  # refresh a: b is now the LRU
    _, fresh_c = cache.touch("c", now=3.0)  # full -> evict b
    assert fresh_c
    snap = cache.snapshot()
    assert snap["live"] == 2 and snap["evicted_lru"] == 1 and snap["peak"] == 2
    # b comes back as a NEW session
    _, fresh_b = cache.touch("b", now=4.0)
    assert fresh_b and cache.snapshot()["opened"] == 4


def test_lru_eviction_never_evicts_a_session_of_the_same_batch(toy_stateful_policy):
    """Review regression: a same-`now` admission round larger than the spill
    cap must never evict a session touched in THIS batch (that would hand
    one slab row to two live sessions in one dispatch — last-write-wins
    scatter == silent cross-user state corruption). Old sessions outside the
    batch are still fair game; with every candidate protected the call fails
    loudly instead."""
    eng = SessionEngine(toy_stateful_policy, buckets=(1, 4), mode="greedy", max_sessions=2, ttl_s=100.0)
    params = toy_stateful_policy.params
    # an OLD session outside the batch is the eviction victim
    eng.step_sessions(params, {"x": np.ones((1, 2), np.float32)}, ["old"])
    obs2 = {"x": np.ones((2, 2), np.float32)}
    acts = eng.step_sessions(params, obs2, ["a", "b"])  # full cache: evicts "old", not "a"
    assert acts[0, 0] == 0 and acts[1, 0] == 0
    snap = eng.cache.snapshot()
    assert snap["evicted_lru"] == 1 and snap["live"] == 2
    acts = eng.step_sessions(params, obs2, ["a", "b"])  # both streams intact
    np.testing.assert_array_equal(acts[:, 0], [1.0, 1.0])
    # a batch with MORE distinct sessions than the cap cannot be cached at
    # all: loud error, not silent row sharing
    with pytest.raises(RuntimeError, match="max_sessions"):
        eng.step_sessions(params, {"x": np.ones((3, 2), np.float32)}, ["c", "d", "e"])


def test_cache_ttl_sweep():
    cache = SessionCache(_spec(), max_sessions=4, ttl_s=10.0)
    cache.touch("a", now=0.0)
    cache.touch("b", now=5.0)
    assert cache.sweep(now=11.0) == 1  # a idle > ttl, b not
    snap = cache.snapshot()
    assert snap["live"] == 1 and snap["evicted_ttl"] == 1
    _, fresh = cache.touch("a", now=12.0)
    assert fresh  # evicted sessions restart fresh


def test_cache_generation_versioned_reinit():
    cache = SessionCache(_spec(), max_sessions=4, ttl_s=100.0)
    row_a, _ = cache.touch("a", now=0.0)
    cache.touch("b", now=0.0)
    cache.mark_stepped(["a", "b"])
    cache.invalidate_all()
    # sessions stay ADMITTED (same rows, same LRU) but re-init lazily,
    # each counted once as an involuntary reset
    row, fresh = cache.touch("a", now=1.0)
    assert row == row_a and fresh
    _, fresh_b = cache.touch("b", now=1.0)
    assert fresh_b
    cache.mark_stepped(["a"])
    _, fresh_again = cache.touch("a", now=2.0)
    assert not fresh_again
    snap = cache.snapshot()
    assert snap["resets"] == 2 and snap["live"] == 2 and snap["generation"] == 1


def test_cache_state_bytes():
    cache = SessionCache(_spec(), max_sessions=8, ttl_s=1.0)
    # 8 rows + 1 donor, one f32 per row
    assert cache.state_bytes == 9 * 4
    assert cache.snapshot()["state_bytes"] == 36


# -- SessionEngine ------------------------------------------------------------ #


def test_engine_stream_continuity_padding_and_reset(toy_stateful_policy):
    from sheeprl_tpu.analysis.tracecheck import tracecheck

    tracecheck.reset()
    eng = SessionEngine(toy_stateful_policy, buckets=(1, 4), mode="greedy", max_sessions=8, ttl_s=100.0)
    cache = eng.cache
    obs1 = {"x": np.ones((1, 2), np.float32)}
    params = toy_stateful_policy.params
    # session a alone, then interleaved with b, then batched with padding
    for t in range(3):
        acts = eng.step_sessions(params, obs1, ["a"])
        assert acts[0, 0] == t
    assert eng.step_sessions(params, obs1, ["b"])[0, 0] == 0
    obs2 = {"x": np.ones((2, 2), np.float32)}
    acts = eng.step_sessions(params, obs2, ["a", "b"])  # padded to bucket 4
    assert acts[0, 0] == 3 and acts[1, 0] == 1
    # reset restarts the stream; the other session is untouched
    assert eng.step_sessions(params, obs1, ["a"], resets=[True])[0, 0] == 0
    assert eng.step_sessions(params, obs1, ["b"])[0, 0] == 2
    # sessionless one-shot rows ride the donor: always step 0
    assert eng.step_sessions(params, obs1, [None])[0, 0] == 0
    assert cache.snapshot()["live"] == 2
    # zero post-warmup retraces; exactly one compile per bucket program
    rep = tracecheck.report()
    for b in (1, 4):
        assert rep[f"serve.session[{b}].step"]["compiles"] == 1
        assert rep[f"serve.session[{b}].step"]["post_warmup_compiles"] == 0
    assert rep["serve.session.infer"]["compiles"] == 2  # one signature per bucket
    assert rep["serve.session.infer"]["post_warmup_compiles"] == 0
    stats = eng.stats()
    assert stats["padded_rows"] > 0 and 0 < stats["batch_fill_ratio"] < 1
    tracecheck.reset()


def test_engine_chunk_beyond_ladder_preserves_order(toy_stateful_policy):
    eng = SessionEngine(toy_stateful_policy, buckets=(1, 2), mode="greedy", max_sessions=8, ttl_s=100.0)
    params = toy_stateful_policy.params
    # 5 distinct sessions through a top bucket of 2: chunked 2+2+1, in order
    for t in range(3):
        obs = {"x": np.arange(10, dtype=np.float32).reshape(5, 2)}
        acts = eng.step_sessions(params, obs, [f"s{i}" for i in range(5)])
        assert acts.shape == (5, 2)
        # every session advanced exactly once per sweep, rows in submit order
        np.testing.assert_array_equal(acts[:, 0], np.full(5, float(t)))
        expected_y = (obs["x"] @ np.arange(4, dtype=np.float32).reshape(2, 2)).sum(-1)
        np.testing.assert_allclose(acts[:, 1], expected_y)


def test_engine_rejects_row_count_mismatch(toy_stateful_policy):
    eng = SessionEngine(toy_stateful_policy, buckets=(2,), mode="greedy", max_sessions=4, ttl_s=100.0)
    obs = {"x": np.ones((2, 2), np.float32)}
    with pytest.raises(ValueError, match="session rows"):
        eng.infer_sessions(toy_stateful_policy.params, obs, [0], [True])


def test_chunk_order_guard_trips_on_reordered_plan(toy_stateful_policy, ppo_policy, monkeypatch):
    """The explicit ordering assertion (stateless parity tests could never
    catch a reorder — their references are built from the same plan): a
    shuffled/banged-up chunk plan must fail loudly on BOTH engines."""
    import sheeprl_tpu.serve.engine as engine_mod
    import sheeprl_tpu.serve.sessions as sessions_mod
    from sheeprl_tpu.serve.engine import BucketEngine

    def shuffled(n, cap):
        spans = [(start, min(start + cap, n)) for start in range(0, n, cap)]
        return spans[::-1]

    # session engine
    eng = SessionEngine(toy_stateful_policy, buckets=(2,), mode="greedy", max_sessions=8, ttl_s=100.0)
    rows, fresh = zip(*[eng.cache.touch(f"s{i}") for i in range(5)])
    monkeypatch.setattr(sessions_mod, "chunk_plan", shuffled)
    with pytest.raises(RuntimeError, match="out of order"):
        eng.infer_sessions(
            toy_stateful_policy.params, {"x": np.ones((5, 2), np.float32)}, list(rows), list(fresh)
        )
    # stateless engine, same guard
    beng = BucketEngine(ppo_policy, buckets=(1, 2), mode="greedy")
    monkeypatch.setattr(engine_mod, "chunk_plan", shuffled)
    with pytest.raises(RuntimeError, match="out of order"):
        beng.infer(ppo_policy.params, {"state": np.zeros((5, 4), np.float32)})


def test_chunk_plan_and_guard_units():
    assert chunk_plan(5, 2) == [(0, 2), (2, 4), (4, 5)]
    check_chunk_order(chunk_plan(7, 3), 7)  # no raise
    with pytest.raises(RuntimeError, match="out of order"):
        check_chunk_order([(2, 4), (0, 2)], 4)
    with pytest.raises(RuntimeError, match="covers"):
        check_chunk_order([(0, 2)], 4)


# -- scheduler admission + server assembly ------------------------------------ #


def _serve_cfg(**kw):
    cfg = {"max_wait_ms": 1.0, "port": None, "session": {"buckets": [1, 4], "max_sessions": 8, "ttl_s": 100.0}}
    cfg.update(kw)
    return cfg


def test_server_session_roundtrip_and_counters(toy_stateful_policy):
    with PolicyServer(toy_stateful_policy, _serve_cfg()) as server:
        obs = {"x": np.ones(2, np.float32)}
        for t in range(4):
            actions, version = server.client.act(obs, session_id="u1", timeout=30.0)
            assert actions[0, 0] == t and version == 0
        # reset starts the episode over
        actions, _ = server.client.act(obs, session_id="u1", reset=True, timeout=30.0)
        assert actions[0, 0] == 0
        # sessionless one-shot on a stateful server: fresh throwaway state
        for _ in range(2):
            actions, _ = server.client.act(obs, timeout=30.0)
            assert actions[0, 0] == 0
        health = server.health()
        assert health["sessions"]["live"] == 1 and health["sessions"]["state_bytes"] > 0
        snap = server.stats.snapshot()
        assert snap["Serve/sessions_live"] == 1
        assert snap["Serve/sessions_opened"] == 1
        assert snap["Serve/sessions_client_resets"] == 1
        assert snap["Serve/sessions_reset"] == 0


def test_concurrent_same_session_never_shares_a_batch(toy_stateful_policy):
    """Two in-flight requests for one session must serve as TWO ordered
    steps (the second is held over), never one batch stepping a session
    twice from the same state."""
    with PolicyServer(toy_stateful_policy, _serve_cfg(max_wait_ms=50.0)) as server:
        obs = {"x": np.ones(2, np.float32)}
        results = []

        def call():
            actions, _ = server.client.act(obs, session_id="dup", timeout=30.0)
            results.append(float(actions[0, 0]))

        threads = [threading.Thread(target=call) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert sorted(results) == [0.0, 1.0, 2.0, 3.0]


def test_session_request_validation(toy_stateful_policy, toy_policy):
    with PolicyServer(toy_stateful_policy, _serve_cfg()) as server:
        with pytest.raises(ValueError, match="one state row"):
            server.scheduler.submit(
                {"x": np.ones((2, 2), np.float32)}, session_id="u1"
            )
    with PolicyServer(toy_policy, {"buckets": [1, 4], "max_wait_ms": 1.0, "port": None}) as server:
        with pytest.raises(ValueError, match="stateless"):
            server.scheduler.submit({"x": np.ones((1, 2), np.float32)}, session_id="u1")


def test_stateful_policy_refuses_naive_engine(toy_stateful_policy):
    with pytest.raises(ValueError, match="session engine"):
        PolicyServer(toy_stateful_policy, _serve_cfg(), engine="naive")


# -- hot swap semantics ------------------------------------------------------- #


def test_hot_swap_keeps_sessions_live(toy_stateful_policy):
    """A swapped tree with matching state avals steps live sessions without
    interruption: streams continue, Serve/sessions_reset stays 0."""
    with PolicyServer(toy_stateful_policy, _serve_cfg()) as server:
        obs = {"x": np.ones(2, np.float32)}
        for t in range(3):
            actions, _ = server.client.act(obs, session_id="u1", timeout=30.0)
            assert actions[0, 0] == t
        new_params = jax.tree.map(lambda x: x + 1.0, toy_stateful_policy.params)
        version = server.weights.publish_params(new_params)
        assert version == 1
        actions, got_version = server.client.act(obs, session_id="u1", timeout=30.0)
        assert got_version == 1
        assert actions[0, 0] == 3  # the stream continued across the swap
        assert actions[0, 1] != 6.0  # ...under the NEW weights (w+1)
        snap = server.stats.snapshot()
        assert snap["Serve/sessions_reset"] == 0 and snap["Serve/swap_count"] == 1


def test_incompatible_swap_versioned_reinit(toy_stateful_policy):
    """If a swap changes the derived state avals, the cache re-inits
    versioned: sessions stay admitted, streams restart, each counted as a
    Serve/sessions_reset."""
    eng = SessionEngine(toy_stateful_policy, buckets=(1,), mode="greedy", max_sessions=4, ttl_s=100.0)
    cache = eng.cache
    params = toy_stateful_policy.params
    obs = {"x": np.ones((1, 2), np.float32)}
    for t in range(2):
        assert eng.step_sessions(params, obs, ["u1"])[0, 0] == t
    assert eng.check_swap(params) is True  # same avals: no-op
    assert cache.snapshot()["generation"] == 0
    # an init_fn whose avals drift under the new params => incompatible
    orig_init = toy_stateful_policy.init_fn
    toy_stateful_policy.init_fn = lambda p, n: {"count": jax.numpy.zeros((n, 2), jax.numpy.float32)}
    try:
        assert eng.check_swap(params) is False
    finally:
        toy_stateful_policy.init_fn = orig_init
    assert eng.step_sessions(params, obs, ["u1"])[0, 0] == 0  # versioned re-init
    assert cache.snapshot()["resets"] == 1


def test_failed_dispatch_rebuilds_slab_and_reinits(toy_stateful_policy, monkeypatch):
    """Review regression: once a dispatch consumes the DONATED slab, a
    failure before its outputs materialize leaves the old buffer deleted (on
    donation-honoring backends) — the engine must rebuild a zeroed slab and
    version-reinit instead of wedging every future dispatch on a dead
    array. One counted round of re-inits, then business as usual."""
    eng = SessionEngine(toy_stateful_policy, buckets=(1,), mode="greedy", max_sessions=4, ttl_s=100.0)
    params = toy_stateful_policy.params
    obs = {"x": np.ones((1, 2), np.float32)}
    for t in range(2):
        assert eng.step_sessions(params, obs, ["u1"])[0, 0] == t
    orig_dispatch = eng._dispatch
    generation_before = eng.cache.generation

    def boom(*args, **kwargs):
        raise RuntimeError("injected dispatch failure")

    monkeypatch.setattr(eng, "_dispatch", boom)
    with pytest.raises(RuntimeError, match="injected"):
        eng.step_sessions(params, obs, ["u1"])
    monkeypatch.setattr(eng, "_dispatch", orig_dispatch)
    assert eng.cache.generation == generation_before + 1  # slab rebuilt + invalidated
    # the session survives ADMITTED, restarts its stream fresh (counted)
    assert eng.step_sessions(params, obs, ["u1"])[0, 0] == 0
    assert eng.cache.snapshot()["resets"] == 1
    assert eng.step_sessions(params, obs, ["u1"])[0, 0] == 1  # ...and keeps going


def test_stateful_inflight_drops_at_commit_never_double_steps(toy_stateful_policy):
    """Review regression: once a stateful batch's dispatch has COMMITTED to
    the slab, a worker death in the resolve loop must NOT hand the batch to
    recover_inflight — re-serving would step every session twice for one
    client-observed step. The cost is a visible caller timeout, never a
    silently corrupted stream."""
    from sheeprl_tpu.serve.scheduler import RequestScheduler, _Request
    from sheeprl_tpu.serve.weights import WeightStore

    eng = SessionEngine(toy_stateful_policy, buckets=(1, 4), mode="greedy", max_sessions=4, ttl_s=100.0)
    store = WeightStore(toy_stateful_policy.params, toy_stateful_policy.params_from_state)
    sched = RequestScheduler(eng, store, max_wait_s=0.001, sessions=eng.cache)
    obs = {"x": np.ones((1, 2), np.float32)}

    class _DiesOnResolve(_Request):  # _Request is __slots__-only
        def resolve(self, *a, **k):
            raise RuntimeError("worker died mid-resolve")

    req = _DiesOnResolve(obs, 1, session_id="u1")
    sched._inflight = [req]
    with pytest.raises(RuntimeError, match="mid-resolve"):
        sched._serve_batch([req])
    assert sched._inflight is None  # committed: must never be re-served
    assert sched.recover_inflight() == 0
    # the session was stepped EXACTLY once: the next request continues at 1
    req2 = _Request(obs, 1, session_id="u1")
    sched._serve_batch([req2])
    assert req2.actions[0][0] == 1


# -- TTL eviction under load -------------------------------------------------- #


def test_ttl_eviction_under_load(toy_stateful_policy):
    """Sessions idle past ttl_s are swept WHILE other traffic flows: the
    active session keeps its stream, the idle one frees its row and restarts
    fresh on return."""
    cfg = _serve_cfg()
    cfg["session"] = {"buckets": [1, 4], "max_sessions": 8, "ttl_s": 0.3, "sweep_every_s": 0.05}
    with PolicyServer(toy_stateful_policy, cfg) as server:
        obs = {"x": np.ones(2, np.float32)}
        server.client.act(obs, session_id="idle", timeout=30.0)
        # keep "active" hot past the idle session's TTL
        deadline = time.monotonic() + 1.0
        steps = 0
        while time.monotonic() < deadline:
            actions, _ = server.client.act(obs, session_id="active", timeout=30.0)
            assert actions[0, 0] == steps  # never reset by the sweep
            steps += 1
            time.sleep(0.02)
        health = server.health()
        assert health["sessions"]["ttl_evictions"] >= 1
        assert health["sessions"]["live"] == 1  # only "active" survived
        # the evicted session returns as a fresh stream
        actions, _ = server.client.act(obs, session_id="idle", timeout=30.0)
        assert actions[0, 0] == 0


# -- batched stepping == offline sequential stepping (real recurrent policy) -- #


def test_recurrent_sessions_bit_parity_unit(recurrent_policy):
    """Row i of a padded multi-session batch must be BIT-identical to the
    offline sequential eval loop for that session — the property that makes
    cross-session batching and padding correctness-free. (The e2e asserts
    the same through the TCP front end; this unit isolates the engine.)"""
    import gymnasium as gym

    from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent
    from sheeprl_tpu.algos.ppo_recurrent.utils import prepare_obs
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel import Fabric
    from tests.test_serve.conftest import RECURRENT_TINY

    cfg = compose(RECURRENT_TINY)
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(42)
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (4,), np.float32)})
    _, params, player = build_agent(fabric, (2,), False, cfg, obs_space, None)

    K, T = 3, 5
    rngs = [np.random.default_rng(i) for i in range(K)]
    obs_seqs = [[r.uniform(-1, 1, size=(4,)).astype(np.float32) for _ in range(T)] for r in rngs]
    ref = []
    for c in range(K):
        states = player.reset_states(1)
        prev = np.zeros((1, 1, 2), np.float32)
        key = jax.random.PRNGKey(cfg.seed or 0)
        seq = []
        for t in range(T):
            jobs = prepare_obs(fabric, {"state": obs_seqs[c][t]}, num_envs=1)
            key, subkey = jax.random.split(key)
            acts, _, _, states = player(params, jobs, jax.device_put(prev), states, subkey, greedy=True)
            prev = np.concatenate([np.asarray(a) for a in acts], axis=-1).reshape(1, 1, -1)
            seq.append(np.concatenate([np.asarray(a).argmax(axis=-1) for a in acts], axis=-1).reshape(-1))
        ref.append(seq)

    eng = SessionEngine(recurrent_policy, buckets=(1, 4), mode="greedy", max_sessions=8, ttl_s=100.0)
    for t in range(T):
        obs = {"state": np.stack([recurrent_policy.prepare({"state": obs_seqs[c][t]}, 1)["state"][0] for c in range(K)])}
        acts = eng.step_sessions(recurrent_policy.params, obs, [f"c{c}" for c in range(K)])
        for c in range(K):
            np.testing.assert_array_equal(np.asarray(acts[c]), np.asarray(ref[c][t]))
