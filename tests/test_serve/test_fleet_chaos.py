"""Fleet-tier chaos drills — the ROADMAP sentence made into tests.

The acceptance drill: 3 supervised replica PROCESSES under sustained
closed-loop load from 7 clients (4 stateless + 3 stateful sessions), one
replica SIGKILLed mid-run from the seeded ``fault.chaos.events`` schedule →
supervised respawn on the shared checkpoint dir, with **zero dropped and
zero errored admitted requests fleet-wide**, session re-inits exactly
counted AND client-visible, router aggregated health walking ok → degraded
→ ok, and a rolling checkpoint swap landing mid-drill with per-client
monotone weight versions across the whole fleet.

Also here: the hang-replica drill (SIGSTOP → probe-lease expiry → counted
as a HANG, distinct from kills → SIGKILL + respawn), the stateful-session
SIGTERM graceful drain (PR 10's drain proof was stateless-only), and the
real ``serve --fleet`` CLI end-to-end (slow-marked)."""

import glob
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from sheeprl_tpu.fault import inject
from sheeprl_tpu.fault.manager import CheckpointManager
from sheeprl_tpu.fault.procsup import ProcessSupervisor
from sheeprl_tpu.serve.fleet import FleetRouter, ReplicaEndpoint, free_port

pytestmark = pytest.mark.chaos

REPO_ROOT = str(Path(__file__).parents[2])
REPLICA_MAIN = str(Path(__file__).parent / "fleet_replica_main.py")


@pytest.fixture(autouse=True)
def _inject_isolation():
    inject.reset()
    yield
    inject.reset()


def _wait(predicate, timeout=30.0, poll=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


def _spawner(port, extra=()):
    cmd = [sys.executable, REPLICA_MAIN, "--port", str(port), *extra]

    def spawn():
        return subprocess.Popen(cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    return spawn


class _RouterClient:
    """One persistent JSON-lines connection to the router front end."""

    def __init__(self, addr):
        self.sock = socket.create_connection(addr, timeout=60.0)
        self.rfile = self.sock.makefile("rb")

    def request(self, payload):
        self.sock.sendall((json.dumps(payload) + "\n").encode())
        line = self.rfile.readline()
        if not line:
            raise ConnectionResetError("router closed the connection")
        return json.loads(line.decode())

    def close(self):
        try:
            self.rfile.close()
            self.sock.close()
        except OSError:
            pass


def _stand_up_fleet(n, ckpt_dir, extra=(), lease_s=2.0, request_timeout_s=15.0, max_restarts=3):
    sup = ProcessSupervisor(
        lease_s=lease_s, grace_s=60.0, backoff=0.05, max_restarts=max_restarts, name="serve-fleet"
    )
    endpoints = []
    for i in range(n):
        port = free_port()
        name = f"replica-{i}"
        args = list(extra)
        if ckpt_dir is not None:
            args += ["--watch", str(ckpt_dir)]
        sup.spawn(name, _spawner(port, args))
        endpoints.append(ReplicaEndpoint(name, "127.0.0.1", port, request_timeout_s=request_timeout_s))
    router = FleetRouter(
        endpoints,
        fleet_cfg={
            "health_poll_s": 0.05,
            "health_timeout_s": 2.0,
            "retry_budget": 3,
            "request_timeout_s": request_timeout_s,
        },
        procsup=sup,
        owns_replicas=True,
        port=0,
    ).start()
    return router, sup, endpoints


def test_fleet_chaos_drill_kill_one_of_three_zero_dropped(tmp_path):
    """THE acceptance drill (ISSUE 14): kill 1 of 3 replicas under sustained
    multi-client load and drop zero admitted requests fleet-wide, with a
    rolling weight swap landing mid-drill."""
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    router, sup, eps = _stand_up_fleet(3, ckpt_dir, extra=["--stateful"])
    try:
        assert router.wait_ready(timeout_s=120)
        addr = router.address

        # background health sampler: the ok -> degraded -> ok trajectory
        statuses = []
        sample_stop = threading.Event()

        def sampler():
            while not sample_stop.is_set():
                statuses.append(router.health()["status"])
                sample_stop.wait(0.05)

        sampler_thread = threading.Thread(target=sampler, daemon=True)
        sampler_thread.start()

        stop_clients = threading.Event()
        errors = []  # (client, detail) — must stay EMPTY
        stateless_results = [[] for _ in range(4)]  # per client: [(fleet_version, replica)]
        session_results = [[] for _ in range(3)]  # per session: [(count, rehomed, replica, fleet_version)]

        def stateless_client(i):
            client = _RouterClient(addr)
            try:
                while not stop_clients.is_set():
                    resp = client.request({"obs": {"x": [[1.0, float(i)]]}, "n": 1})
                    if "error" in resp:
                        errors.append((f"stateless-{i}", resp["error"]))
                    else:
                        stateless_results[i].append((resp["fleet_version"], resp["replica"]))
                    time.sleep(0.02)
                for _ in range(5):  # post-drill settle requests
                    resp = client.request({"obs": {"x": [[1.0, float(i)]]}, "n": 1})
                    if "error" in resp:
                        errors.append((f"stateless-{i}", resp["error"]))
                    else:
                        stateless_results[i].append((resp["fleet_version"], resp["replica"]))
            except Exception as e:  # any transport failure IS a dropped request
                errors.append((f"stateless-{i}", repr(e)))
            finally:
                client.close()

        def session_client(i):
            client = _RouterClient(addr)
            sid = f"user-{i}"
            try:
                while not stop_clients.is_set():
                    resp = client.request({"obs": {"x": [[1.0, 2.0]]}, "n": 1, "session_id": sid})
                    if "error" in resp:
                        errors.append((sid, resp["error"]))
                    else:
                        session_results[i].append(
                            (resp["actions"][0][0], bool(resp.get("rehomed")), resp["replica"], resp["fleet_version"])
                        )
                    time.sleep(0.02)
                for _ in range(5):
                    resp = client.request({"obs": {"x": [[1.0, 2.0]]}, "n": 1, "session_id": sid})
                    if "error" in resp:
                        errors.append((sid, resp["error"]))
                    else:
                        session_results[i].append(
                            (resp["actions"][0][0], bool(resp.get("rehomed")), resp["replica"], resp["fleet_version"])
                        )
            except Exception as e:
                errors.append((sid, repr(e)))
            finally:
                client.close()

        threads = [threading.Thread(target=stateless_client, args=(i,)) for i in range(4)]
        threads += [threading.Thread(target=session_client, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()

        # let every session open and settle on a home
        assert _wait(lambda: all(len(r) >= 3 for r in session_results), timeout=30)
        homes_before_kill = {f"user-{i}": session_results[i][-1][2] for i in range(3)}

        # ARM the process-tier chaos from the seeded schedule: SIGKILL one
        # replica (the first live one: replica-0) 20 router ticks from now
        inject.arm_from_cfg(
            {"fault": {"chaos": {"enabled": True, "seed": 7, "events": ["serve.fleet.tick:kill-replica:20"]}}}
        )
        assert _wait(lambda: sup.replica("replica-0").kills >= 1, timeout=30), sup.describe()
        killed = "replica-0"

        # rolling swap lands MID-DRILL: a new complete save in the shared dir
        CheckpointManager().save(
            ckpt_dir / "ckpt_10_0.ckpt", {"agent": {"w": 2 * np.ones((2, 2), np.float32)}}, step=10
        )
        assert _wait(lambda: router.health()["fleet"]["fleet_version"] >= 10, timeout=30)
        # the killed replica respawns on the SAME checkpoint dir and adopts
        # the newest save (publish_current): the whole fleet converges on 10
        assert _wait(
            lambda: all(ep.ready for ep in eps) and all(ep.step >= 10 for ep in eps), timeout=60
        ), router.health()
        time.sleep(0.5)  # post-recovery traffic under the swapped weights
        stop_clients.set()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads)
        sample_stop.set()
        sampler_thread.join(timeout=5)

        # ZERO dropped, ZERO errors for every admitted request fleet-wide
        assert errors == []
        assert all(len(r) > 0 for r in stateless_results)

        # per-client monotone weight versions fleet-wide, reaching the swap
        for rows in stateless_results:
            versions = [v for v, _r in rows]
            assert versions == sorted(versions)
            assert versions[-1] >= 10
        for rows in session_results:
            versions = [v for _c, _h, _r, v in rows]
            assert versions == sorted(versions)
            assert versions[-1] >= 10

        # session streams: contiguous counts; exactly one REHOMED re-init
        # (count back to 0, flagged) for sessions that lived on the killed
        # replica, none anywhere else — never silently wrong state
        rehomed_sessions = set()
        for i in range(3):
            sid = f"user-{i}"
            rows = session_results[i]
            rehomes = [k for k, (_c, rehomed, _r, _v) in enumerate(rows) if rehomed]
            assert len(rehomes) <= 1, f"{sid}: multiple rehomes {rehomes}"
            expected = 0.0
            for k, (count, rehomed, _r, _v) in enumerate(rows):
                if rehomed:
                    expected = 0.0  # client-visible counted re-init
                    rehomed_sessions.add(sid)
                assert count == expected, f"{sid} step {k}: count {count} != {expected} (rows={rows[:k+1]})"
                expected += 1.0
        victims = {sid for sid, home in homes_before_kill.items() if home == killed}
        assert rehomed_sessions == victims

        # router counters: rehomed == sessions that lived on the killed
        # replica; supervised respawn happened; SIGKILL detected AS a kill
        health = router.health()
        assert health["fleet"]["sessions_rehomed"] == len(victims)
        handle = sup.replica(killed)
        assert handle.restarts >= 1 and handle.kills >= 1 and handle.hangs == 0
        assert handle.last_signal == "SIGKILL" or handle.restarts >= 1

        # aggregated health walked ok -> degraded -> ok
        assert statuses[0] == "ok"
        assert "degraded" in statuses or "down" in statuses
        assert health["status"] == "ok"
    finally:
        router.stop()


def test_hang_replica_lease_expiry_is_counted_as_hang_not_kill(tmp_path):
    """hang-replica chaos (SIGSTOP): the replica is ALIVE but silent — the
    probe lease expires, the supervisor counts a HANG (not a kill),
    SIGKILLs the wedged process itself and respawns it; traffic keeps
    flowing on the survivor throughout."""
    # lease/probe timeouts stay SHORT (they drive the hang detection);
    # the request timeout stays generous — a respawning replica's jax
    # import spikes this box's CPU and a tight request budget turns that
    # into spurious failovers on the healthy survivor
    router, sup, eps = _stand_up_fleet(2, None, lease_s=1.0, request_timeout_s=10.0)
    try:
        assert router.wait_ready(timeout_s=120)
        inject.arm_from_cfg(
            {"fault": {"chaos": {"enabled": True, "events": ["serve.fleet.tick:hang-replica:5"]}}}
        )
        # the wedged replica is detected and respawned
        assert _wait(
            lambda: sup.replica("replica-0").hangs >= 1 or sup.replica("replica-1").hangs >= 1,
            timeout=30,
        ), sup.describe()
        hung = next(h for h in sup.replicas() if h.hangs >= 1)
        assert hung.kills == 0  # distinct detection: a hang is not an external kill
        # traffic flows throughout (the survivor carries it; the hung one rejoins)
        for _ in range(10):
            resp = router.serve_request({"obs": {"x": [[1.0, 2.0]]}, "n": 1})
            assert "error" not in resp, resp
            time.sleep(0.05)
        assert _wait(lambda: all(ep.ready for ep in eps), timeout=60)
        assert _wait(lambda: router.health()["status"] == "ok", timeout=30)
        assert hung.restarts >= 1
    finally:
        router.stop()


def test_stateful_sigterm_graceful_drain_exits_zero():
    """Satellite: PR 10's drain proof was stateless-only. A STATEFUL session
    server under SIGTERM must settle every admitted in-flight session batch
    (contiguous per-session streams to the last served step), keep its
    session counters coherent, and exit 0."""
    proc = subprocess.Popen(
        [sys.executable, REPLICA_MAIN, "--port", "0", "--stateful", "--max-wait-ms", "5"],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    clients = []
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("REPLICA_READY"), line
        host, port = line.split()[1].split(":")
        addr = (host, int(port))

        n_sessions = 3
        per_session = [[] for _ in range(n_sessions)]
        closed_errors = [0]
        lock = threading.Lock()
        stop = threading.Event()

        def session_loop(i):
            client = _RouterClient(addr)  # plain JSON-lines: same protocol
            clients.append(client)
            try:
                while True:
                    resp = client.request({"obs": {"x": [[1.0, 2.0]]}, "n": 1, "session_id": f"user-{i}"})
                    if "error" in resp:
                        # after the drain flag the ONLY acceptable error is
                        # the typed closed-for-admission one
                        assert "ServeClosedError" in resp["error"], resp
                        with lock:
                            closed_errors[0] += 1
                        if stop.is_set():
                            return
                    else:
                        per_session[i].append(resp["actions"][0][0])
            except (ConnectionResetError, BrokenPipeError, OSError):
                return  # server fully gone after drain: EOF is clean

        threads = [threading.Thread(target=session_loop, args=(i,)) for i in range(n_sessions)]
        for t in threads:
            t.start()
        assert _wait(lambda: all(len(s) >= 5 for s in per_session), timeout=30)

        proc.send_signal(signal.SIGTERM)  # mid-flight: requests are in the air
        stop.set()
        out, _ = proc.communicate(timeout=60)
        for t in threads:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in threads)
    finally:
        for c in clients:
            c.close()
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)

    assert proc.returncode == 0, f"non-zero exit after SIGTERM:\n{out}"
    assert "received SIGTERM — graceful drain" in out
    assert "serve: drained cleanly" in out
    # every settled response extended its session stream CONTIGUOUSLY — the
    # drain served in-flight session batches, it did not drop or reorder them
    for i, counts in enumerate(per_session):
        assert counts == [float(k) for k in range(len(counts))], f"user-{i}: {counts}"
    # the final stats line is coherent: sessions opened == live clients, and
    # the served totals cover every client-observed response
    stats_line = next(l for l in out.splitlines() if l.startswith("{"))
    stats = json.loads(stats_line)
    assert stats["Serve/sessions_live"] == n_sessions
    assert stats["Serve/sessions_opened"] == n_sessions
    assert stats["Serve/rows"] >= sum(len(s) for s in per_session)
    assert stats["Serve/sessions_reset"] == 0  # no silent re-inits during drain


PPO_TINY = [
    "exp=ppo",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]


def _probe(addr, timeout=5.0):
    with socket.create_connection(addr, timeout=timeout) as s:
        s.sendall(b'{"health": true}\n')
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.decode())


@pytest.mark.slow
def test_serve_fleet_cli_e2e_sigterm_drains_everything(tmp_path):
    """The real CLI verb: ``serve --fleet 2`` on a trained checkpoint stands
    up 2 supervised replica processes + the router, serves requests with
    replica/fleet_version annotations, and SIGTERM drains the router then
    every replica and exits 0."""
    from sheeprl_tpu.cli import run

    run(PPO_TINY + [f"log_root={tmp_path}/train", "dry_run=True", "checkpoint.save_last=True"])
    ckpts = sorted(glob.glob(f"{tmp_path}/train/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)
    assert ckpts
    port = free_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "sheeprl_tpu",
            "serve",
            "--fleet",
            "2",
            f"checkpoint_path={ckpts[-1]}",
            "fabric.accelerator=cpu",
            f"serve.port={port}",
            "serve.buckets=[1,2]",
            "serve.log_every_s=60",
            "serve.fleet.health_poll_s=0.2",
        ],
        cwd=REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        # replicas inherit the stdout pipe: a failure-path kill must sweep
        # the whole process group or communicate() blocks on their write end
        start_new_session=True,
    )
    try:
        addr = ("127.0.0.1", port)
        deadline = time.monotonic() + 300
        while True:  # router is up once the socket answers; replicas follow
            try:
                health = _probe(addr)
                if health.get("ready"):
                    break
            except (ConnectionRefusedError, OSError):
                pass
            assert proc.poll() is None, f"fleet died early:\n{proc.stdout.read()}"
            assert time.monotonic() < deadline, "fleet never became ready"
            time.sleep(0.5)
        assert health["fleet"]["replicas"] == 2
        assert _wait(lambda: _probe(addr)["fleet"]["ready"] == 2, timeout=240)
        # one real request through router -> replica -> checkpointed policy
        # (the dummy env observes a 10-dim "state" row)
        with socket.create_connection(addr, timeout=30) as s:
            s.sendall((json.dumps({"obs": {"state": [[0.1] * 10]}, "n": 1}) + "\n").encode())
            buf = b""
            while not buf.endswith(b"\n"):
                buf += s.recv(65536)
        resp = json.loads(buf.decode())
        assert "actions" in resp and "replica" in resp and "fleet_version" in resp, resp
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (OSError, ProcessLookupError):
                proc.kill()
            proc.communicate(timeout=30)
    assert proc.returncode == 0, f"non-zero exit after SIGTERM:\n{out}"
    assert "received SIGTERM — graceful drain" in out
    assert "serve: drained cleanly" in out
