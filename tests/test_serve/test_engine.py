"""AOT bucket engine semantics: ladder selection, padding bit-parity against
the plain jitted policy at every batch size across bucket boundaries, chunking
past the largest bucket, sample-mode determinism, slab-reuse hygiene."""

import jax
import numpy as np
import pytest

from sheeprl_tpu.serve.engine import BucketEngine, JitEngine


def _obs(policy, n, seed=0):
    rng = np.random.default_rng(seed)
    return {k: rng.standard_normal((n, *shape)).astype(dtype) for k, (shape, dtype) in policy.obs_spec.items()}


def test_bucket_selection(toy_policy):
    eng = BucketEngine(toy_policy, buckets=(1, 8, 32), mode="greedy", warmup=False)
    assert eng.bucket_for(1) == 1
    assert eng.bucket_for(2) == 8
    assert eng.bucket_for(8) == 8
    assert eng.bucket_for(9) == 32
    assert eng.bucket_for(32) == 32
    assert eng.bucket_for(33) == 32  # caller chunks
    with pytest.raises(ValueError):
        eng.bucket_for(0)


def test_bad_ladder_and_mode(toy_policy):
    with pytest.raises(ValueError):
        BucketEngine(toy_policy, buckets=(0, 4))
    with pytest.raises(ValueError):
        BucketEngine(toy_policy, buckets=(1, 4), mode="nope")
    eng = BucketEngine(toy_policy, buckets=(1, 4), mode="greedy")
    with pytest.raises(ValueError):
        eng.infer(toy_policy.params, _obs(toy_policy, 2), greedy=False)


@pytest.mark.parametrize("policy_fixture", ["ppo_policy", "sac_policy"])
def test_bucket_padding_bit_parity(policy_fixture, request):
    """The acceptance bar: greedy actions from the AOT bucketed path are
    BIT-identical to the plain jitted policy for every batch size across
    bucket boundaries (1, bucket, bucket±1 — padding and unpadding add
    nothing). Past the largest bucket the engine chunks, and XLA's codegen
    reassociates float math differently at large batch shapes (observed:
    ~1e-7 on the SAC MLP at n=33 vs the whole-batch program), so there the
    claim is bit-parity against the identically-chunked reference plus tight
    allclose against the whole-batch one."""
    policy = request.getfixturevalue(policy_fixture)
    buckets = (1, 4, 16)
    cap = max(buckets)
    eng = BucketEngine(policy, buckets=buckets, mode="greedy")
    ref = jax.jit(policy.greedy_fn)
    sizes = sorted({1, 2, 3, 4, 5, 15, 16, 17, 33, 40})
    for n in sizes:
        obs = _obs(policy, n, seed=n)
        got = eng.infer(policy.params, obs)
        whole = np.asarray(ref(policy.params, obs))
        assert got.shape == (n, policy.action_dim)
        assert got.dtype == whole.dtype
        if n <= cap:
            assert np.array_equal(got, whole), f"bucketed path diverged at batch size {n}"
        else:
            chunked = np.concatenate(
                [np.asarray(ref(policy.params, {k: v[s : s + cap] for k, v in obs.items()}))
                 for s in range(0, n, cap)],
                axis=0,
            )
            assert np.array_equal(got, chunked), f"chunking machinery diverged at batch size {n}"
            np.testing.assert_allclose(got, whole, rtol=1e-5, atol=1e-6)


def test_slab_reuse_after_large_batch(ppo_policy):
    """A big batch leaves stale rows in the slab; a following small batch
    must be unaffected (tail zeroing + row independence)."""
    eng = BucketEngine(ppo_policy, buckets=(4,), mode="greedy")
    ref = jax.jit(ppo_policy.greedy_fn)
    big = _obs(ppo_policy, 4, seed=1)
    eng.infer(ppo_policy.params, big)
    small = _obs(ppo_policy, 2, seed=2)
    got = eng.infer(ppo_policy.params, small)
    assert np.array_equal(got, np.asarray(ref(ppo_policy.params, small)))


def test_chunking_matches_unchunked(toy_policy):
    """n > largest bucket runs as chunks through the top bucket and matches
    the whole-batch reference row for row."""
    eng = BucketEngine(toy_policy, buckets=(1, 4), mode="greedy")
    obs = _obs(toy_policy, 11, seed=3)
    got = eng.infer(toy_policy.params, obs)
    want = np.asarray(jax.jit(toy_policy.greedy_fn)(toy_policy.params, obs))
    assert got.shape == want.shape
    assert np.array_equal(got, want)


def test_sample_mode_deterministic_per_key(toy_policy):
    eng = BucketEngine(toy_policy, buckets=(1, 4), mode="sample")
    obs = _obs(toy_policy, 3, seed=4)
    key = jax.random.PRNGKey(7)
    a = eng.infer(toy_policy.params, obs, key=key, greedy=False)
    b = eng.infer(toy_policy.params, obs, key=key, greedy=False)
    assert np.array_equal(a, b)
    c = eng.infer(toy_policy.params, obs, key=jax.random.PRNGKey(8), greedy=False)
    assert not np.array_equal(a, c)
    with pytest.raises(ValueError):
        eng.infer(toy_policy.params, obs, greedy=False)  # no key


def test_hot_swapped_params_zero_recompile(toy_policy):
    """A params tree rebuilt via params_from_state runs through the ALREADY
    compiled executables — and the outputs track the new weights."""
    eng = BucketEngine(toy_policy, buckets=(1, 4), mode="greedy")
    obs = _obs(toy_policy, 2, seed=5)
    before = eng.infer(toy_policy.params, obs)
    swapped = toy_policy.params_from_state({"w": np.asarray(toy_policy.params["w"]) * 2.0})
    after = eng.infer(swapped, obs)
    assert np.allclose(after, before * 2.0, rtol=1e-6)


def test_obs_validation(toy_policy):
    eng = BucketEngine(toy_policy, buckets=(1,), mode="greedy", warmup=False)
    with pytest.raises(ValueError):
        eng.infer(toy_policy.params, {"y": np.zeros((1, 2), np.float32)})
    with pytest.raises(ValueError):
        eng.infer(toy_policy.params, {"x": np.zeros((1, 3), np.float32)})


def test_jit_engine_matches(toy_policy):
    naive = JitEngine(toy_policy, mode="greedy")
    aot = BucketEngine(toy_policy, buckets=(1, 4), mode="greedy")
    for n in (1, 3, 4, 6):
        obs = _obs(toy_policy, n, seed=10 + n)
        assert np.array_equal(naive.infer(toy_policy.params, obs), aot.infer(toy_policy.params, obs))
    assert naive.stats()["padded_rows"] == 0


def test_engine_fill_stats(toy_policy):
    eng = BucketEngine(toy_policy, buckets=(4,), mode="greedy")
    eng.infer(toy_policy.params, _obs(toy_policy, 3))
    s = eng.stats()
    # warmup dispatch (4 padded rows) + one 3-row call padded to 4
    assert s["rows"] == 3
    assert s["padded_rows"] >= 1
    assert 0.0 < s["batch_fill_ratio"] < 1.0
