"""The graft-sessions acceptance bar, through the real CLI + TCP front end:
N interleaved stateful clients produce per-client action sequences
BIT-identical to the offline sequential eval loop for the same checkpoint —
for ppo_recurrent (LSTM hidden + prev-action carry) AND dreamer_v3
(posterior + recurrent state + one-hot carry) — across a hot weight swap
that keeps every session live, with ``serve.session[N].step`` compiles ==
#buckets and 0 post-warmup retraces under strict tracecheck."""

import glob
import json
import os
import socket
import threading
import time

import jax
import numpy as np
import pytest

from sheeprl_tpu.cli import find_run_config, run, serve
from sheeprl_tpu.config import dotdict, load_yaml
from sheeprl_tpu.fault.manager import CheckpointManager
from sheeprl_tpu.parallel import Fabric
from sheeprl_tpu.utils.checkpoint import load_state


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _request(addr, payload, timeout=60.0, retry_deadline=None):
    """One JSON-lines round trip; retries connection refusal until
    ``retry_deadline`` (server still compiling its bucket ladder)."""
    while True:
        try:
            with socket.create_connection(addr, timeout=timeout) as sock:
                sock.sendall((json.dumps(payload) + "\n").encode())
                buf = b""
                while not buf.endswith(b"\n"):
                    chunk = sock.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
            return json.loads(buf.decode())
        except (ConnectionRefusedError, OSError):
            if retry_deadline is None or time.perf_counter() > retry_deadline:
                raise
            time.sleep(0.1)


def _wait_version(addr, version, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        health = _request(addr, {"health": True})
        if health["weights"]["version"] >= version:
            return health
        time.sleep(0.05)
    raise AssertionError(f"weight version never reached {version}")


def _serve_and_stream(ckpt, obs_key, obs_seqs, publish_swap, K, T1, T2, buckets=(1, 4)):
    """Drive the REAL serve verb: K session clients step phase 1 under the
    checkpoint weights, a swap publishes, phase 2 continues the SAME
    sessions under the new weights. Returns (streams, versions, tracecheck
    report snapshot). Strict tracecheck is armed around the whole server
    lifetime — any post-warmup retrace raises inside the serve thread and
    surfaces as a failed request."""
    from sheeprl_tpu.analysis.tracecheck import tracecheck

    port = _free_port()
    total = K * (T1 + T2)
    tracecheck.reset()
    tracecheck.configure(mode="strict", transfer_guard=True)
    try:
        t = threading.Thread(
            target=serve,
            args=(
                [
                    f"checkpoint_path={ckpt}",
                    "fabric.accelerator=cpu",
                    f"serve.port={port}",
                    f"serve.session.buckets=[{','.join(str(b) for b in buckets)}]",
                    "serve.max_wait_ms=2.0",
                    "serve.watch=True",
                    "serve.watch_poll_s=0.05",
                    f"serve.max_requests={total}",
                    "serve.log_every_s=60",
                ],
            ),
            daemon=True,
        )
        t.start()
        addr = ("127.0.0.1", port)
        boot_deadline = time.perf_counter() + 240.0
        streams = [[] for _ in range(K)]
        versions = [[] for _ in range(K)]

        def phase(t0, t1, first_retries=False):
            for step in range(t0, t1):
                for c in range(K):
                    resp = _request(
                        addr,
                        {"obs": {obs_key: obs_seqs[c][step].tolist()}, "session_id": f"client-{c}"},
                        retry_deadline=boot_deadline if first_retries and step == t0 and c == 0 else None,
                    )
                    assert "actions" in resp, resp
                    streams[c].append(np.asarray(resp["actions"])[0])
                    versions[c].append(resp["version"])

        phase(0, T1, first_retries=True)
        publish_swap()
        _wait_version(addr, 1)
        health = _request(addr, {"health": True})
        assert health["sessions"]["live"] == K
        assert health["sessions"]["resets"] == 0  # the swap kept sessions live
        phase(T1, T1 + T2)
        t.join(timeout=120.0)
        assert not t.is_alive(), "serve loop did not exit at max_requests"
        report = {k: v for k, v in tracecheck.report().items() if k.startswith("serve.session")}
    finally:
        tracecheck.configure(mode="warn", transfer_guard=False)
        tracecheck.reset()

    for c in range(K):
        assert versions[c][:T1] == [0] * T1  # phase 1 under the checkpoint
        assert versions[c][T1:] == [1] * T2  # phase 2 under the swapped weights
    # serve.session[N].step compiles == #buckets, 0 post-warmup retraces
    for b in buckets:
        assert report[f"serve.session[{b}].step"]["compiles"] == 1
    assert sum(report[f"serve.session[{b}].step"]["compiles"] for b in buckets) == len(buckets)
    for name, entry in report.items():
        assert entry["post_warmup_compiles"] == 0, (name, entry)
    assert report["serve.session.infer"]["compiles"] == len(buckets)
    return streams


def _perturb(tree):
    return jax.tree.map(lambda x: np.asarray(x) + np.asarray(1e-3, np.asarray(x).dtype), tree)


def _train_and_find_ckpt(tmp_path, args):
    run(args + [f"log_root={tmp_path}/train", "dry_run=True", "checkpoint.save_last=True"])
    ckpts = sorted(glob.glob(f"{tmp_path}/train/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)
    assert ckpts, "the training run saved no checkpoint"
    return ckpts[-1]


def _obs_streams(obs_space, obs_key, K, T):
    rngs = [np.random.default_rng(c) for c in range(K)]
    shape = obs_space[obs_key].shape
    return [[r.uniform(-1, 1, size=shape).astype(np.float32) for _ in range(T)] for r in rngs]


PPO_REC_TINY = [
    "exp=ppo_recurrent",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.rollout_steps=8",
    "algo.per_rank_sequence_length=4",
    "algo.per_rank_num_batches=2",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]


def test_sessions_e2e_ppo_recurrent_bit_parity_across_swap(tmp_path):
    from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent
    from sheeprl_tpu.algos.ppo_recurrent.utils import prepare_obs
    from sheeprl_tpu.envs.factory import make_env

    ckpt = _train_and_find_ckpt(tmp_path, PPO_REC_TINY)
    cfg = dotdict(load_yaml(find_run_config(ckpt)))
    state = load_state(ckpt)
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(cfg.seed)
    env = make_env(cfg, cfg.seed, 0, None, "sessions_e2e", vector_env_idx=0)()
    obs_space, act_space = env.observation_space, env.action_space
    env.close()
    n_actions = int(act_space.n)

    K, T1, T2 = 3, 4, 4
    obs_seqs = _obs_streams(obs_space, "state", K, T1 + T2)
    perturbed_agent = _perturb(state["agent"])

    # offline sequential eval loop per client: phase 1 under the checkpoint,
    # phase 2 continuing the SAME carried state under the perturbed weights
    _, params0, player = build_agent(fabric, (n_actions,), False, cfg, obs_space, state["agent"])
    _, params1, _ = build_agent(fabric, (n_actions,), False, cfg, obs_space, perturbed_agent)
    ref = []
    for c in range(K):
        states = player.reset_states(1)
        prev = np.zeros((1, 1, n_actions), np.float32)
        key = jax.random.PRNGKey(cfg.seed or 0)
        seq = []
        for t in range(T1 + T2):
            params = params0 if t < T1 else params1
            jobs = prepare_obs(fabric, {"state": obs_seqs[c][t]}, num_envs=1)
            key, subkey = jax.random.split(key)
            acts, _, _, states = player(params, jobs, jax.device_put(prev), states, subkey, greedy=True)
            prev = np.concatenate([np.asarray(a) for a in acts], axis=-1).reshape(1, 1, -1)
            seq.append(np.concatenate([np.asarray(a).argmax(axis=-1) for a in acts], axis=-1).reshape(-1))
        ref.append(seq)

    ckpt_dir = os.path.dirname(ckpt)

    def publish_swap():
        CheckpointManager().save(
            os.path.join(ckpt_dir, "ckpt_900000_0.ckpt"), {"agent": perturbed_agent}, step=900000
        )

    streams = _serve_and_stream(ckpt, "state", obs_seqs, publish_swap, K, T1, T2)
    for c in range(K):
        for t in range(T1 + T2):
            np.testing.assert_array_equal(
                np.asarray(streams[c][t]), np.asarray(ref[c][t]),
                err_msg=f"client {c} step {t}: served != offline eval loop",
            )


DREAMER_TINY = [
    "exp=dreamer_v3",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo=dreamer_v3_XS",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=1",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.world_model.reward_model.bins=17",
    "algo.critic.bins=17",
    "algo.cnn_keys.encoder=[]",
    "algo.cnn_keys.decoder=[]",
    "algo.mlp_keys.encoder=[state]",
    "algo.mlp_keys.decoder=[state]",
]


def test_sessions_e2e_dreamer_v3_bit_parity_across_swap(tmp_path):
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs
    from sheeprl_tpu.envs.factory import make_env

    ckpt = _train_and_find_ckpt(tmp_path, DREAMER_TINY)
    cfg = dotdict(load_yaml(find_run_config(ckpt)))
    state = load_state(ckpt)
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(cfg.seed)
    env = make_env(cfg, cfg.seed, 0, None, "sessions_e2e")()
    obs_space, act_space = env.observation_space, env.action_space
    env.close()
    n_actions = int(act_space.n)

    K, T1, T2 = 3, 4, 4
    obs_seqs = _obs_streams(obs_space, "state", K, T1 + T2)
    model_keys = ("world_model", "actor", "critic", "target_critic")
    perturbed = {k: _perturb(state[k]) for k in model_keys}

    _, _, _, params0, player = build_agent(
        fabric, (n_actions,), False, cfg, obs_space, *[state[k] for k in model_keys]
    )
    _, _, _, params1, _ = build_agent(
        fabric, (n_actions,), False, cfg, obs_space, *[perturbed[k] for k in model_keys]
    )
    ref = []
    for c in range(K):
        player.num_envs = 1
        player.init_states(params0)
        key = jax.random.PRNGKey(cfg.seed or 0)
        seq = []
        for t in range(T1 + T2):
            params = params0 if t < T1 else params1
            jobs = prepare_obs(fabric, {"state": obs_seqs[c][t]}, num_envs=1)
            key, subkey = jax.random.split(key)
            acts = player.get_actions(params, jobs, subkey, greedy=True)
            seq.append(np.stack([np.asarray(a).argmax(axis=-1) for a in acts], axis=-1).reshape(-1))
        ref.append(seq)

    ckpt_dir = os.path.dirname(ckpt)

    def publish_swap():
        # dreamer checkpoints are agent-less (model trees at the top level):
        # the watcher publishes the FULL state and the dreamer builder's
        # params_from_state consumes exactly that layout
        CheckpointManager().save(os.path.join(ckpt_dir, "ckpt_900000_0.ckpt"), dict(perturbed), step=900000)

    streams = _serve_and_stream(ckpt, "state", obs_seqs, publish_swap, K, T1, T2)
    for c in range(K):
        for t in range(T1 + T2):
            np.testing.assert_array_equal(
                np.asarray(streams[c][t]), np.asarray(ref[c][t]),
                err_msg=f"client {c} step {t}: served != offline eval loop",
            )
