"""graft-flywheel: the serve→train production loop.

Transport semantics (pairing, shedding, torn tails, attribution), the
backward-compat guard for feedback-less clients, the typed config error for
unsupported algorithms, the fleet-path multi-replica spool accounting, the
SAC learner-ingest actually learning from spooled rows, and the learner
supervision lease (SIGSTOP → missed beats → SIGKILL + respawn) — all
in-process and deterministic. The real-CLI publish/adopt loop and the
isolation chaos drill live in ``test_flywheel_chaos.py``.
"""

import json
import os
import socket
import struct
import time

import numpy as np
import pytest

from sheeprl_tpu.serve.flywheel import (
    FRAME_MAGIC,
    _FRAME,
    FlywheelConfigError,
    SpoolReader,
    TrajectoryLog,
    flywheel_row_width,
    read_learner_status,
    split_rows,
    write_learner_status,
)
from sheeprl_tpu.serve.server import PolicyServer, request_over_socket

OBS_SPEC = {"x": ((2,), np.float32)}  # matches the toy policy


def _log(tmp_path, **kw):
    kw.setdefault("replica", "r0")
    return TrajectoryLog(tmp_path, OBS_SPEC, 3, **kw)


def _obs(*rows):
    return {"x": np.asarray(rows, np.float32)}


# -- transport: pairing, spooling, round trip -------------------------------- #


def test_feedback_pairs_previous_action_and_round_trips(tmp_path):
    """reward/done grade the PREVIOUS action on the stream; the spooled row
    is (prev_obs, prev_action, reward, done, next_obs=current obs), and the
    reader hands back exactly what was logged."""
    log = _log(tmp_path, block_rows=4, flush_s=0.01)
    a0 = np.asarray([[1.0, 2.0, 3.0]], np.float32)
    log.observe(_obs([1.0, 2.0]), 1, a0, None, None, "s")
    log.observe(_obs([3.0, 4.0]), 1, [[4.0, 5.0, 6.0]], 0.5, 1.0, "s")
    log.close()
    assert log.counters["rows_logged"] == 1
    assert log.counters["rows_spooled"] == 1
    reader = SpoolReader(tmp_path, log.row_width)
    batches = reader.poll()
    assert len(batches) == 1
    replica, rows = batches[0]
    assert replica == "r0"
    cols = split_rows(rows, 2, 3)
    assert np.allclose(cols["observations"], [[1.0, 2.0]])
    assert np.allclose(cols["actions"], a0)
    assert np.allclose(cols["rewards"], [[0.5]])
    assert np.allclose(cols["terminated"], [[1.0]])
    assert np.allclose(cols["next_observations"], [[3.0, 4.0]])
    assert reader.consumed_rows == {"r0": 1}


def test_streams_pair_independently(tmp_path):
    """Two interleaved streams never cross-pair: each transition's action
    comes from its own stream's previous request."""
    log = _log(tmp_path, flush_s=0.01)
    log.observe(_obs([1.0, 0.0]), 1, [[1.0, 1.0, 1.0]], None, None, "a")
    log.observe(_obs([2.0, 0.0]), 1, [[2.0, 2.0, 2.0]], None, None, "b")
    log.observe(_obs([3.0, 0.0]), 1, [[3.0, 3.0, 3.0]], 1.0, 0.0, "b")
    log.observe(_obs([4.0, 0.0]), 1, [[4.0, 4.0, 4.0]], 2.0, 0.0, "a")
    log.close()
    rows = np.concatenate([r for _, r in SpoolReader(tmp_path, log.row_width).poll()])
    cols = split_rows(rows, 2, 3)
    by_reward = {float(r): i for i, r in enumerate(cols["rewards"][:, 0])}
    assert np.allclose(cols["actions"][by_reward[1.0]], [2.0, 2.0, 2.0])  # stream b
    assert np.allclose(cols["actions"][by_reward[2.0]], [1.0, 1.0, 1.0])  # stream a


def test_feedback_missing_and_orphans_counted(tmp_path):
    log = _log(tmp_path)
    # feedback with nothing pending: orphan
    log.observe(_obs([0.0, 0.0]), 1, [[0.0] * 3], 1.0, 0.0, "s")
    assert log.counters["feedback_orphans"] == 1
    # two feedback-less requests: the first pending action is never graded
    log.observe(_obs([0.0, 0.0]), 1, [[0.0] * 3], None, None, "s")
    assert log.counters["feedback_missing"] == 1
    # row-count mismatch cannot pair either
    log.observe(_obs([0.0, 0.0], [1.0, 1.0]), 2, [[0.0] * 3] * 2, [1.0, 1.0], None, "s")
    assert log.counters["feedback_orphans"] == 3
    assert log.counters["rows_logged"] == 0
    log.close()


def test_max_streams_lru_eviction_counts_missing(tmp_path):
    log = _log(tmp_path, max_streams=2)
    for i in range(4):
        log.observe(_obs([0.0, 0.0]), 1, [[0.0] * 3], None, None, f"s{i}")
    assert log.counters["feedback_missing"] == 2  # s0, s1 evicted ungraded
    snap = log.snapshot()
    assert snap["pending_streams"] == 2
    log.close()


def test_full_transport_sheds_instead_of_blocking(tmp_path, monkeypatch):
    """With the writer wedged (the slow-disk / SIGSTOP shape), staged blocks
    past the ring are SHED: observe keeps returning immediately and counts
    what it dropped."""
    import threading

    log = _log(tmp_path, block_rows=2, queue_blocks=2, flush_s=3600.0)
    release = threading.Event()
    monkeypatch.setattr(log, "_write_frame", lambda rows: release.wait(30.0))
    while not log._q.full():  # pre-fill the transport out of the free ring
        log._q.put_nowait((log._free.popleft(), 2))
    t0 = time.monotonic()
    for i in range(10):
        log.observe(_obs([float(i), 0.0]), 1, [[0.0] * 3], 1.0, 0.0, "s")
    assert time.monotonic() - t0 < 1.0  # never blocked on the wedged writer
    assert log.counters["rows_shed"] >= 2
    assert log.counters["blocks_shed"] >= 1
    release.set()
    log.close(abandon=True)


def test_observe_never_raises(tmp_path):
    log = _log(tmp_path)
    log.observe({"wrong": "garbage"}, 1, None, 1.0, None, "s")  # type: ignore[arg-type]
    assert log.counters["errors"] == 1
    log.close()


def test_partial_block_flushes_within_flush_s(tmp_path):
    """A quiet tail of traffic (less than a block) still reaches disk within
    ~flush_s — the learner must not wait for a full block."""
    log = _log(tmp_path, block_rows=256, flush_s=0.05)
    log.observe(_obs([1.0, 2.0]), 1, [[1.0] * 3], None, None, "s")
    log.observe(_obs([3.0, 4.0]), 1, [[2.0] * 3], 1.0, 0.0, "s")
    reader = SpoolReader(tmp_path, log.row_width)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if reader.poll():
            break
        time.sleep(0.02)
    else:
        pytest.fail("partial block never flushed")
    log.close()


# -- reader: torn tails, corruption, generations ----------------------------- #


def test_torn_tail_waited_out_then_parsed(tmp_path):
    width = flywheel_row_width(2, 3)
    header = json.dumps(
        {"magic": "sheeprl-flywheel/1", "replica": "r0", "row_width": width, "obs_dim": 2, "act_dim": 3}
    )
    payload = np.arange(width, dtype=np.float32).tobytes()
    frame = _FRAME.pack(FRAME_MAGIC, 1, len(payload)) + payload
    path = tmp_path / "r0.1.spool"
    path.write_bytes((header + "\n").encode() + frame[: len(frame) // 2])
    reader = SpoolReader(tmp_path, width)
    assert reader.poll() == []  # torn: wait, do not advance
    assert reader.pending_bytes() > 0
    path.write_bytes((header + "\n").encode() + frame)
    batches = reader.poll()
    assert len(batches) == 1 and len(batches[0][1]) == 1
    assert reader.total_consumed == 1


def test_corrupt_frame_quarantines_file(tmp_path):
    width = flywheel_row_width(2, 3)
    header = json.dumps({"magic": "sheeprl-flywheel/1", "replica": "bad", "row_width": width})
    junk = struct.pack("<III", 0xDEADBEEF, 1, 4) + b"\x00" * 4
    (tmp_path / "bad.1.spool").write_bytes((header + "\n").encode() + junk)
    reader = SpoolReader(tmp_path, width)
    assert reader.poll() == []
    assert reader.corrupt_files == 1
    assert reader.poll() == []  # stays quarantined


def test_new_generation_gets_fresh_spool_file(tmp_path):
    """Same replica name re-opened (a respawn in-process) never appends to
    the old file — each generation is its own spool."""
    a = _log(tmp_path)
    b = _log(tmp_path)
    assert a.path != b.path
    a.close()
    b.close()


# -- learner status ----------------------------------------------------------- #


def test_learner_status_round_trip_and_staleness(tmp_path):
    assert read_learner_status(tmp_path) is None
    write_learner_status(tmp_path, {"consumed_rows": 7, "grad_steps": 3})
    status = read_learner_status(tmp_path)
    assert status["consumed_rows"] == 7
    assert status["staleness_s"] >= 0.0


# -- backward compat: the feedback-less world keeps working ------------------ #


def test_feedbackless_client_serves_normally_rows_counted_missing(sac_policy, tmp_path):
    """A client that never heard of the flywheel serves exactly as before on
    a flywheel server — no errors, no latency coupling, its ungradeable rows
    counted ``feedback_missing`` and nothing spooled for them."""
    cfg = {
        "buckets": [1, 4],
        "max_wait_ms": 1.0,
        "port": None,
        "flywheel": {"enabled": True, "dir": str(tmp_path / "fly"), "replica": "r0", "flush_s": 0.01},
    }
    rng = np.random.default_rng(0)
    with PolicyServer(sac_policy, cfg) as server:
        for _ in range(3):
            actions, version = server.client.act({"state": rng.standard_normal(3).astype(np.float32)}, n=1)
            assert actions.shape == (1, 1) and version == 0
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and server.flywheel.counters["feedback_missing"] < 2:
            time.sleep(0.01)
        snap = server.flywheel.snapshot()
    assert snap["rows_logged"] == 0
    assert snap["feedback_missing"] == 2  # 3 requests -> 2 ungraded predecessors
    assert snap["errors"] == 0


def test_unknown_obs_keys_still_rejected_with_named_error(sac_policy, tmp_path):
    """The existing protocol guard survives the flywheel fields: a request
    with wrong observation keys still gets the named ValueError over the
    wire, and the connection keeps serving feedback requests after it."""
    cfg = {
        "buckets": [1, 4],
        "max_wait_ms": 1.0,
        "port": 0,
        "flywheel": {"enabled": True, "dir": str(tmp_path / "fly"), "replica": "r0"},
    }
    with PolicyServer(sac_policy, cfg) as server:
        addr = server.address
        with socket.create_connection(addr, timeout=10.0) as sock:
            f = sock.makefile("rw")
            f.write(json.dumps({"obs": {"bogus": [1.0]}, "n": 1, "reward": 1.0}) + "\n")
            f.flush()
            resp = json.loads(f.readline())
            assert "error" in resp and "state" in resp["error"]  # the named per-request rejection
            f.write(json.dumps({"obs": {"state": [0.1, 0.2, 0.3]}, "n": 1, "reward": 0.5, "done": 0.0}) + "\n")
            f.flush()
            assert "actions" in json.loads(f.readline())
        # the scheduler's own spec guard is unchanged by the feedback fields:
        # mismatched prepared keys get the SAME named ValueError as before
        with pytest.raises(ValueError, match="observation keys"):
            server.scheduler.submit({"bogus": np.zeros((1, 3), np.float32)}, reward=1.0, done=0.0, stream="s")


def test_socket_feedback_pairs_per_connection(sac_policy, tmp_path):
    """Session-less socket clients pair feedback per CONNECTION: two
    connections interleaving never cross-grade each other's actions."""
    fly_dir = tmp_path / "fly"
    cfg = {
        "buckets": [1, 4],
        "max_wait_ms": 1.0,
        "port": 0,
        "flywheel": {"enabled": True, "dir": str(fly_dir), "replica": "r0", "flush_s": 0.01},
    }
    with PolicyServer(sac_policy, cfg) as server:
        addr = server.address
        obs = [0.1, 0.2, 0.3]
        conns = [socket.create_connection(addr, timeout=10.0) for _ in range(2)]
        files = [c.makefile("rw") for c in conns]
        for i, f in enumerate(files):  # first request on each: nothing pending
            f.write(json.dumps({"obs": {"state": obs}, "n": 1}) + "\n")
            f.flush()
            assert "actions" in json.loads(f.readline())
        for i, f in enumerate(files):  # second request grades the first
            f.write(json.dumps({"obs": {"state": obs}, "n": 1, "reward": float(i + 1), "done": 0.0}) + "\n")
            f.flush()
            assert "actions" in json.loads(f.readline())
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and server.flywheel.counters["rows_logged"] < 2:
            time.sleep(0.01)
        snap = server.flywheel.snapshot()
        for c in conns:
            c.close()
    assert snap["rows_logged"] == 2
    assert snap["feedback_orphans"] == 0
    rows = np.concatenate(
        [r for _, r in SpoolReader(fly_dir, flywheel_row_width(3, 1)).poll()]
    )
    assert sorted(split_rows(rows, 3, 1)["rewards"][:, 0].tolist()) == [1.0, 2.0]


def test_flywheel_stats_and_health_block(sac_policy, tmp_path):
    cfg = {
        "buckets": [1, 4],
        "max_wait_ms": 1.0,
        "port": None,
        "flywheel": {"enabled": True, "dir": str(tmp_path / "fly"), "replica": "r7", "flush_s": 0.01},
    }
    with PolicyServer(sac_policy, cfg) as server:
        obs = {"state": np.asarray([0.1, 0.2, 0.3], np.float32)}
        server.client.act(obs, n=1)
        server.client.act(obs, n=1, reward=1.0, done=0.0)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and server.flywheel.counters["rows_spooled"] < 1:
            time.sleep(0.01)
        stats = server.stats.snapshot()
        health = server.health()
    assert stats["Serve/flywheel_rows"] == 1
    assert stats["Serve/flywheel_shed"] == 0
    assert stats["Serve/flywheel_spooled"] == 1
    assert stats["Serve/flywheel_errors"] == 0
    fl = health["flywheel"]
    assert fl["replica"] == "r7"
    assert fl["rows_logged"] == 1 and fl["rows_shed"] == 0 and fl["errors"] == 0
    assert "learner" not in fl  # no learner wired at the PolicyServer layer


def test_flywheel_off_means_zero_surface(toy_policy):
    with PolicyServer(toy_policy, {"buckets": [1, 4], "max_wait_ms": 1.0, "port": None}) as server:
        server.client.act({"x": np.ones(2, np.float32)}, n=1)
        assert server.flywheel is None
        health = server.health()
        stats = server.stats.snapshot()
    assert "flywheel" not in health
    assert not any(k.startswith("Serve/flywheel") for k in stats)


# -- the typed config error --------------------------------------------------- #


def test_flywheel_config_error_for_unsupported_algo(toy_policy, tmp_path):
    """An algo with no registered learner-ingest builder fails FAST at build
    time (before any socket binds), naming the algos that do support it."""
    with pytest.raises(FlywheelConfigError) as exc:
        PolicyServer(
            toy_policy,
            {"buckets": [1], "port": None, "flywheel": {"enabled": True, "dir": str(tmp_path)}},
        )
    msg = str(exc.value)
    assert "'toy'" in msg
    assert "sac" in msg  # the supported list is enumerated


def test_flywheel_config_error_without_dir(sac_policy):
    with pytest.raises(FlywheelConfigError, match="serve.flywheel.dir"):
        PolicyServer(sac_policy, {"buckets": [1], "port": None, "flywheel": {"enabled": True}})


# -- fleet path: N replicas, one spool dir, one accounting -------------------- #


def test_fleet_replicas_attributed_and_kill_loses_only_inflight(tmp_path):
    """Three replicas stream into one dir; the reader attributes rows per
    replica. One replica 'dies' (abandon: staged + queued rows dropped, the
    SIGKILL shape) — the learner loses ONLY that replica's in-flight rows,
    bounded by the transport ring, and the survivors' accounting is exact."""
    logs = {f"replica-{i}": _log(tmp_path, replica=f"replica-{i}", block_rows=4, flush_s=0.01) for i in range(3)}
    sent = {name: 0 for name in logs}
    for round_i in range(10):
        for name, log in logs.items():
            log.observe(_obs([float(round_i), 0.0]), 1, [[0.0] * 3], float(round_i), 0.0, "s")
            if round_i > 0:
                sent[name] += 1  # first request per stream only opens the pairing
    # replica-1 is killed mid-run: staged + queued rows are gone
    logs["replica-1"].close(abandon=True)
    logs["replica-0"].close()
    logs["replica-2"].close()
    reader = SpoolReader(tmp_path, logs["replica-0"].row_width)
    reader.poll()
    assert reader.consumed_rows.get("replica-0", 0) == sent["replica-0"]
    assert reader.consumed_rows.get("replica-2", 0) == sent["replica-2"]
    lost = sent["replica-1"] - reader.consumed_rows.get("replica-1", 0)
    assert lost >= 0
    # the loss is COUNTED on the replica side and bounded by the ring
    c = logs["replica-1"].counters
    assert c["rows_logged"] - c["rows_spooled"] - c["rows_shed"] == lost
    assert reader.total_consumed == sum(reader.consumed_rows.values())


def test_replica_command_forwards_flywheel_identity():
    """Fleet replicas get the shared dir, their fleet name as spool identity,
    and learner=False — the fleet parent owns the single learner."""
    from sheeprl_tpu.config import dotdict
    from sheeprl_tpu.serve.fleet import replica_command

    cfg = dotdict(
        {
            "serve": {"flywheel": {"enabled": True, "dir": "/tmp/fly", "block_rows": 64}},
            "fabric": {"accelerator": "cpu"},
        }
    )
    cmd = replica_command(cfg, "/ckpt/ckpt_2_0.ckpt", "127.0.0.1", 1234, name="replica-1")
    assert "serve.flywheel.enabled=True" in cmd
    assert "serve.flywheel.dir=/tmp/fly" in cmd
    assert "serve.flywheel.replica=replica-1" in cmd
    assert "serve.flywheel.learner=False" in cmd
    assert "serve.flywheel.block_rows=64" in cmd
    # without the flywheel nothing leaks into the replica invocation
    cmd = replica_command(dotdict({"serve": {}, "fabric": {}}), "/ckpt/c.ckpt", "127.0.0.1", 1)
    assert not any("flywheel" in c for c in cmd)


# -- the SAC learner-ingest ---------------------------------------------------- #


@pytest.fixture(scope="module")
def sac_ingest_setup():
    import gymnasium as gym

    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel import Fabric
    from sheeprl_tpu.utils.registry import get_entrypoint, resolve_flywheel_ingest

    cfg = compose(
        [
            "exp=sac",
            "env=gym",
            "env.id=Pendulum-v1",
            "env.capture_video=False",
            "fabric.devices=1",
            "metric.log_level=0",
            "algo.mlp_keys.encoder=[state]",
            "algo.hidden_size=16",
        ]
    )
    cfg["serve"] = {
        "flywheel": {
            "ingest_rows": 4,
            "grad_max": 2,
            "replay_ratio": 1.0,
            "learning_starts_rows": 8,
            "buffer_size": 64,
        }
    }
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(3)
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (3,), np.float32)})
    act_space = gym.spaces.Box(-2.0, 2.0, (1,), np.float32)
    builder = get_entrypoint(resolve_flywheel_ingest("sac"))
    return builder(fabric, cfg, obs_space, act_space, None)


def test_sac_ingest_learns_from_spooled_rows(sac_ingest_setup):
    """Spool-shaped rows drive real grad steps through the resident train
    step: updates start only past learning_starts_rows, grants follow the
    replay ratio, and the published params actually move."""
    import jax

    ingest = sac_ingest_setup
    assert ingest.row_width == flywheel_row_width(3, 1)
    # copy=True: the resident fn DONATES params, so a zero-copy view of the
    # pre-update buffer would silently alias the post-update values
    before = jax.tree.map(lambda x: np.array(x, copy=True), ingest.params["actor"])
    rng = np.random.default_rng(0)

    def batch(m):
        rows = rng.standard_normal((m, ingest.row_width)).astype(np.float32)
        rows[:, 4] = 0.0  # terminated column: mid-episode transitions
        return rows

    ingest.ingest(batch(4))
    assert ingest.consumed == 4
    assert ingest.grad_steps == 0  # below learning_starts_rows
    ingest.ingest(batch(8))
    assert ingest.consumed == 12
    assert ingest.grad_steps > 0
    after = jax.tree.map(np.asarray, ingest.params["actor"])
    changed = jax.tree_util.tree_leaves(
        jax.tree.map(lambda a, b: not np.allclose(a, b), before, after)
    )
    assert any(changed), "actor params did not move after production-row grad steps"


def test_sac_ingest_agent_state_matches_checkpoint_tree(sac_ingest_setup):
    """The publishable tree has the checkpoint's ``state['agent']`` keys —
    the serving tier's ``params_from_state`` must hot-swap it unchanged."""
    tree = sac_ingest_setup.agent_state()
    assert {"actor", "critic", "target_critic", "log_alpha"} <= set(tree)


# -- learner supervision (in-process, fake learner) --------------------------- #


def _fake_learner_cmd(status_dir, beat: bool):
    """A stand-in learner: beats learner_status.json like the real one."""
    import sys

    body = (
        "import json,os,sys,time\n"
        f"d={str(status_dir)!r}\n"
        "i=0\n"
        "while True:\n"
        f"    beat={beat}\n"
        "    if beat:\n"
        "        tmp=os.path.join(d,'learner_status.json.tmp')\n"
        "        json.dump({'consumed_rows':i,'grad_steps':i,'published_step':-1},open(tmp,'w'))\n"
        "        os.replace(tmp,os.path.join(d,'learner_status.json'))\n"
        "    i+=1\n"
        "    time.sleep(0.05)\n"
    )
    return [sys.executable, "-c", body]


def test_learner_lease_expiry_sigkills_and_respawns(tmp_path, monkeypatch):
    """The supervision ladder end-to-end against a real (fake) subprocess:
    SIGSTOP stops the status beats, the lease expires, the learner is
    SIGKILLed + respawned (counted as a hang), and probe() reports it."""
    import sheeprl_tpu.serve.flywheel as flywheel_mod
    from sheeprl_tpu.config import dotdict
    from sheeprl_tpu.serve.flywheel import LearnerSupervisor

    monkeypatch.setattr(flywheel_mod, "learner_command", lambda cfg, d: _fake_learner_cmd(d, beat=True))
    cfg = dotdict(
        {
            "serve": {"flywheel": {"lease_s": 0.6, "grace_s": 2.0, "supervisor": {"max_restarts": 3, "backoff": 0.1}}},
            "checkpoint_path": "unused",
            "fabric": {"accelerator": "cpu"},
        }
    )
    sup = LearnerSupervisor(cfg, tmp_path)
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and sup.probe()["consumed_rows"] == 0:
            sup.tick()
            time.sleep(0.05)
        assert sup.probe()["alive"]
        pid = sup.handle.pid()
        os.kill(pid, 19)  # SIGSTOP: beats stop, serving would carry on
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and sup.probe()["hangs"] == 0:
            sup.tick()
            time.sleep(0.05)
        probe = sup.probe()
        assert probe["hangs"] == 1, probe
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            sup.tick()
            probe = sup.probe()
            if probe["alive"] and sup.handle.pid() != pid:
                break
            time.sleep(0.05)
        assert sup.handle.pid() != pid, "learner was not respawned after the SIGKILL"
        assert probe["restarts"] >= 1
        assert probe["fatal"] is None
    finally:
        sup.stop(grace_s=2.0)
    assert not sup.handle.is_alive()


def test_learner_chaos_handlers_registered_and_cleared(tmp_path, monkeypatch):
    """kill-learner / hang-learner dispatch to the CURRENT learner handle
    via the inject registry; stop() clears them."""
    import sheeprl_tpu.serve.flywheel as flywheel_mod
    from sheeprl_tpu.config import dotdict
    from sheeprl_tpu.fault import inject
    from sheeprl_tpu.serve.flywheel import LearnerSupervisor

    monkeypatch.setattr(flywheel_mod, "learner_command", lambda cfg, d: _fake_learner_cmd(d, beat=True))
    cfg = dotdict(
        {
            "serve": {"flywheel": {"lease_s": 5.0, "grace_s": 5.0}},
            "checkpoint_path": "unused",
            "fabric": {"accelerator": "cpu"},
        }
    )
    inject.reset()
    sup = LearnerSupervisor(cfg, tmp_path)
    try:
        pid = sup.handle.pid()
        inject._learner_chaos["kill"]()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and sup.handle.is_alive():
            time.sleep(0.05)
        assert not sup.handle.is_alive() or sup.handle.pid() != pid
    finally:
        sup.stop(grace_s=2.0)
    assert inject._learner_chaos["kill"] is None  # cleared by stop()


def test_learner_command_round_trip():
    from sheeprl_tpu.config import dotdict
    from sheeprl_tpu.serve.flywheel import learner_command

    cfg = dotdict(
        {
            "checkpoint_path": "/ckpt/ckpt_2_0.ckpt",
            "seed": 5,
            "fabric": {"accelerator": "cpu"},
            "serve": {"flywheel": {"publish_rows": 16, "poll_s": 0.1}},
        }
    )
    cmd = learner_command(cfg, "/tmp/fly")
    assert "--from-serve" in cmd and "/tmp/fly" in cmd
    assert "checkpoint_path=/ckpt/ckpt_2_0.ckpt" in cmd
    assert "serve.flywheel.publish_rows=16" in cmd
    assert "serve.flywheel.poll_s=0.1" in cmd
    assert "seed=5" in cmd
