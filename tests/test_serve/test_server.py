"""Server assembly + CLI: run-config discovery (typed errors), the in-process
client, the JSON-lines socket front end, and the `serve` verb end-to-end —
served greedy actions bit-identical to the eval player path for the same
checkpoint."""

import glob
import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.cli import find_run_config, run, serve
from sheeprl_tpu.serve.server import PolicyServer, request_over_socket
from sheeprl_tpu.utils.checkpoint import CheckpointError


# -- find_run_config: the resolver eval/serve/registration share ------------- #


def test_find_run_config_canonical(tmp_path):
    run_dir = tmp_path / "run"
    (run_dir / "checkpoint").mkdir(parents=True)
    (run_dir / "config.yaml").write_text("seed: 1\n")
    ckpt = run_dir / "checkpoint" / "ckpt_10_0.ckpt"
    ckpt.mkdir()
    assert find_run_config(ckpt) == run_dir / "config.yaml"


def test_find_run_config_manifest_anchor(tmp_path):
    """A checkpoint nested deeper than the canonical layout still resolves:
    the fault-runtime manifest marks its directory as the run's checkpoint/
    dir, whose parent holds the config."""
    from sheeprl_tpu.fault.manager import MANIFEST_NAME

    run_dir = tmp_path / "run"
    deep = run_dir / "checkpoint" / "extra"
    deep.mkdir(parents=True)
    (run_dir / "checkpoint" / MANIFEST_NAME).write_text("{}")
    (run_dir / "config.yaml").write_text("seed: 1\n")
    ckpt = deep / "ckpt_10_0.ckpt"
    ckpt.mkdir()
    assert find_run_config(ckpt) == run_dir / "config.yaml"


def test_find_run_config_upward_walk(tmp_path):
    """A checkpoint copied out of its run dir resolves against the nearest
    ancestor config.yaml."""
    copied = tmp_path / "copied"
    copied.mkdir()
    (copied / "config.yaml").write_text("seed: 1\n")
    ckpt = copied / "ckpt_10_0.ckpt"
    ckpt.mkdir()
    assert find_run_config(ckpt) == copied / "config.yaml"


def test_find_run_config_typed_error_names_paths(tmp_path):
    ckpt = tmp_path / "orphan" / "ckpt_10_0.ckpt"
    ckpt.mkdir(parents=True)
    with pytest.raises(CheckpointError) as exc:
        find_run_config(ckpt)
    msg = str(exc.value)
    assert "ckpt_10_0.ckpt" in msg
    assert "config.yaml" in msg  # the searched candidates are enumerated


# -- PolicyServer assembly --------------------------------------------------- #


def test_policy_server_client_roundtrip(toy_policy):
    """In-process client over the assembled tier: raw obs in, actions +
    version out, stats populated."""
    with PolicyServer(toy_policy, {"buckets": [1, 4], "max_wait_ms": 1.0, "port": None}) as server:
        obs = {"x": np.ones(2, np.float32)}
        actions, version = server.client.act(obs, n=1, timeout=10.0)
        assert actions.shape == (1, 3)
        assert version == 0
        expected = np.ones((1, 2), np.float32) @ np.asarray(toy_policy.params["w"])
        assert np.allclose(actions, expected)
    snap = server.stats.snapshot()
    assert snap["Serve/requests"] == 1 and snap["Serve/rows"] == 1


def test_socket_front_end(toy_policy):
    """JSON-lines protocol: single-row, multi-row, and a malformed request
    that must produce a per-request error without killing the connection."""
    with PolicyServer(toy_policy, {"buckets": [1, 4], "max_wait_ms": 1.0, "port": 0}) as server:
        addr = server.address
        assert addr is not None
        resp = request_over_socket(addr, {"x": [1.0, 1.0]}, n=1)
        assert resp["version"] == 0
        assert np.allclose(resp["actions"], [[3.0, 5.0, 7.0]])  # ones @ arange(6).reshape(2,3)
        resp = request_over_socket(addr, {"x": [[1.0, 0.0], [0.0, 1.0]]}, n=2)
        assert np.asarray(resp["actions"]).shape == (2, 3)
        # bad key -> per-request error, then the same connection still works
        with socket.create_connection(addr, timeout=10.0) as sock:
            f = sock.makefile("rw")
            f.write(json.dumps({"obs": {"wrong": [1.0]}, "n": 1}) + "\n")
            f.flush()
            assert "error" in json.loads(f.readline())
            f.write(json.dumps({"obs": {"x": [1.0, 1.0]}, "n": 1}) + "\n")
            f.flush()
            assert "actions" in json.loads(f.readline())


def test_resolve_builder_state_guards_agentless_checkpoints():
    """Review regression: a checkpoint with no 'agent' tree must fail FAST on
    a builder that can only consume one (None there means random init — a
    silent untrained server), while full_state-declaring builders (dreamer
    family, population) legitimately take the whole state."""
    from sheeprl_tpu.serve.server import resolve_builder_state

    def plain_builder(fabric, cfg, obs_space, act_space, agent_state):
        raise AssertionError("never called")

    def full_state_builder(fabric, cfg, obs_space, act_space, agent_state, full_state=None):
        raise AssertionError("never called")

    state = {"world_model": {}, "actor": {}}
    with pytest.raises(RuntimeError, match="refusing to serve"):
        resolve_builder_state(plain_builder, state, "/some/ckpt", "ppo")
    agent_state, kwargs = resolve_builder_state(full_state_builder, state, "/some/ckpt", "dreamer_v3")
    assert agent_state is None and kwargs == {"full_state": state}
    agent_state, kwargs = resolve_builder_state(plain_builder, {"agent": {"w": 1}}, "/some/ckpt", "ppo")
    assert agent_state == {"w": 1} and kwargs == {}


# -- the serve verb end-to-end ---------------------------------------------- #

PPO_TINY = [
    "exp=ppo",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_serve_cli_end_to_end_bit_identical_to_eval(tmp_path):
    """The acceptance bar, through the real CLI: train a tiny PPO run, serve
    its checkpoint over the socket front end, and every served greedy action
    is BIT-identical to what the eval player path (``player.get_actions``
    + the eval loop's host-side argmax conversion) computes from the same
    checkpoint for the same observation."""
    import jax

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.utils import prepare_obs
    from sheeprl_tpu.config import dotdict, load_yaml
    from sheeprl_tpu.envs.factory import make_env
    from sheeprl_tpu.parallel import Fabric
    from sheeprl_tpu.utils.checkpoint import load_state

    run(PPO_TINY + [f"log_root={tmp_path}/train", "dry_run=True", "checkpoint.save_last=True"])
    ckpts = sorted(glob.glob(f"{tmp_path}/train/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)
    assert ckpts, "the training run saved no checkpoint"
    ckpt = ckpts[-1]

    # eval-path reference actions from the SAME checkpoint
    cfg = dotdict(load_yaml(find_run_config(ckpt)))
    state = load_state(ckpt)
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(cfg.seed)
    env = make_env(cfg, cfg.seed, 0, None, "serve_test", vector_env_idx=0)()
    obs_space, act_space = env.observation_space, env.action_space
    env.close()
    _, params, player = build_agent(fabric, (act_space.n,), False, cfg, obs_space, state["agent"])
    rng = np.random.default_rng(0)
    raw_rows = [rng.uniform(-1, 1, size=obs_space["state"].shape).astype(np.float32) for _ in range(4)]
    expected = []
    key = jax.random.PRNGKey(0)  # greedy ignores it — same contract as eval
    for row in raw_rows:
        jobs = prepare_obs(fabric, {"state": row}, num_envs=1)
        acts = player.get_actions(params, jobs, key, greedy=True)
        expected.append(np.concatenate([np.asarray(a).argmax(axis=-1) for a in acts], axis=-1))

    # the serve verb: resolver + registry + AOT engine + socket front end
    port = _free_port()
    t = threading.Thread(
        target=serve,
        args=(
            [
                f"checkpoint_path={ckpt}",
                "fabric.accelerator=cpu",
                f"serve.port={port}",
                "serve.buckets=[1,2]",
                "serve.max_wait_ms=1.0",
                f"serve.max_requests={len(raw_rows)}",
                "serve.log_every_s=60",
            ],
        ),
        daemon=True,
    )
    t.start()
    addr = ("127.0.0.1", port)
    deadline = time.perf_counter() + 120.0
    responses = []
    for i, row in enumerate(raw_rows):
        while True:  # first request retries until the server is up
            try:
                resp = request_over_socket(addr, {"state": row.tolist()}, n=1)
                break
            except (ConnectionRefusedError, OSError):
                if i > 0 or time.perf_counter() > deadline:
                    raise
                time.sleep(0.1)
        assert "actions" in resp, resp
        responses.append(resp)
    t.join(timeout=120.0)
    assert not t.is_alive(), "serve loop did not exit at max_requests"

    for resp, want in zip(responses, expected):
        got = np.asarray(resp["actions"])
        assert got.shape == (1, 1)
        assert np.array_equal(got[0], want), f"served action {got[0]} != eval action {want}"
        assert resp["version"] == 0
