"""Flywheel acceptance drills — the real CLI loop and the isolation proof.

Two slow-marked multi-process tests over the actual ``serve --flywheel``
verb (real TCP, real learner subprocess, real checkpoint watcher):

- the PRODUCTION LOOP e2e: live feedback clients stream graded transitions
  into the spool, the supervised learner consumes them, publishes a NEW
  checkpoint step back into the served dir, and the watcher adopts it with
  a client-visible monotone version bump and zero errors/resets;
- the ISOLATION chaos drill: the learner is SIGSTOPped (hang → lease
  expiry → SIGKILL + respawn) and then SIGKILLed outright mid-run while
  closed-loop feedback traffic never stops — zero admitted requests are
  dropped or errored, and the health probe counts the hang and the
  restarts while serving latency stays alive throughout.

Both run with ``SHEEPRL_TPU_SYNC_SANITIZE=1`` armed, per the acceptance
gate. They are ``slow``-marked (excluded from tier-1) and run in the CI
flywheel lane alongside ``tests/test_serve/test_flywheel.py``.
"""

import glob
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from sheeprl_tpu.serve.flywheel import STATUS_NAME, read_learner_status
from sheeprl_tpu.serve.fleet import free_port

pytestmark = pytest.mark.chaos

REPO_ROOT = str(Path(__file__).parents[2])

# Tiny SAC on the continuous dummy env (10-dim "state" row, 2-dim action):
# just enough training to write a real checkpoint for the flywheel to serve
# from and publish over.
SAC_TINY = [
    "exp=sac",
    "env=dummy",
    "env.id=continuous_dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "dry_run=True",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "checkpoint.save_last=True",
    "algo.run_test=False",
    "algo.per_rank_batch_size=8",
    "algo.mlp_keys.encoder=[state]",
    "algo.hidden_size=16",
]


def _wait(predicate, timeout=30.0, poll=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


def _probe(addr, timeout=5.0):
    with socket.create_connection(addr, timeout=timeout) as s:
        s.sendall(b'{"health": true}\n')
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.decode())


class _FeedbackClient:
    """One persistent JSON-lines connection driving the closed production
    loop: every turn grades the PREVIOUS action on this connection's stream
    with a reward/done, so each request past the first completes a
    transition into the spool."""

    def __init__(self, addr):
        self.sock = socket.create_connection(addr, timeout=60.0)
        self.rfile = self.sock.makefile("rb")
        self.turn = 0
        self.versions = []

    def act(self, obs_row):
        payload = {"obs": {"state": [obs_row]}, "n": 1}
        if self.turn > 0:
            payload["reward"] = 1.0
            payload["done"] = 1.0 if self.turn % 8 == 0 else 0.0
        self.sock.sendall((json.dumps(payload) + "\n").encode())
        line = self.rfile.readline()
        if not line:
            raise ConnectionResetError("server closed the connection")
        resp = json.loads(line.decode())
        self.turn += 1
        if "version" in resp:
            self.versions.append(int(resp["version"]))
        return resp

    def close(self):
        try:
            self.rfile.close()
            self.sock.close()
        except OSError:
            pass


@pytest.fixture(scope="module")
def trained_ckpt(tmp_path_factory):
    """One tiny trained SAC checkpoint shared by both drills (train once)."""
    from sheeprl_tpu.cli import run

    root = tmp_path_factory.mktemp("flywheel_ckpt")
    run(SAC_TINY + [f"log_root={root}/train"])
    ckpts = sorted(glob.glob(f"{root}/train/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)
    assert ckpts, "tiny SAC train produced no checkpoint"
    return ckpts[-1]


@pytest.fixture()
def sac_ckpt(trained_ckpt, tmp_path):
    """A per-test COPY of the trained checkpoint in a fresh directory: each
    drill gets its own spool dir, learner status, and publish target (the
    first drill's published checkpoints and dead-learner status file must
    not leak into the second)."""
    import shutil

    dest = tmp_path / "checkpoint"
    dest.mkdir()
    for sidecar in glob.glob(f"{trained_ckpt}*"):
        if os.path.isdir(sidecar):  # .ckpt.arrays is a directory sidecar
            shutil.copytree(sidecar, dest / Path(sidecar).name)
        else:
            shutil.copy2(sidecar, dest / Path(sidecar).name)
    # the run's config.yaml (serve needs it next to the checkpoint)
    run_dir = Path(trained_ckpt).parent
    for _ in range(3):
        if (run_dir / "config.yaml").exists():
            shutil.copy2(run_dir / "config.yaml", dest / "config.yaml")
            break
        run_dir = run_dir.parent
    return str(dest / Path(trained_ckpt).name)


def _serve_flywheel(ckpt, port, extra=()):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "SHEEPRL_TPU_SYNC_SANITIZE": "1"}
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "sheeprl_tpu",
            "serve",
            "--flywheel",
            f"checkpoint_path={ckpt}",
            "fabric.accelerator=cpu",
            f"serve.port={port}",
            "serve.buckets=[1,4]",
            "serve.max_wait_ms=1.0",
            "serve.watch=True",
            "serve.watch_poll_s=0.25",
            "serve.log_every_s=60",
            # small-knob learner: ingest in 4-row takes, start learning at 8
            # rows, publish every 16 consumed rows
            "serve.flywheel.block_rows=8",
            "serve.flywheel.flush_s=0.1",
            "serve.flywheel.ingest_rows=4",
            "serve.flywheel.grad_max=2",
            "serve.flywheel.replay_ratio=1.0",
            "serve.flywheel.learning_starts_rows=8",
            "serve.flywheel.buffer_size=64",
            "serve.flywheel.publish_rows=16",
            "serve.flywheel.poll_s=0.1",
            *extra,
        ],
        cwd=REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        # the learner inherits the stdout pipe: a failure-path kill must
        # sweep the whole process group or communicate() blocks
        start_new_session=True,
    )


def _wait_ready(proc, addr, deadline_s=300.0):
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            health = _probe(addr)
            if health.get("ready"):
                return health
        except (ConnectionRefusedError, OSError):
            pass
        assert proc.poll() is None, f"serve died early:\n{proc.stdout.read()}"
        assert time.monotonic() < deadline, "serve never became ready"
        time.sleep(0.5)


def _reap(proc):
    if proc.poll() is None:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (OSError, ProcessLookupError):
            proc.kill()
        proc.communicate(timeout=30)


@pytest.mark.slow
def test_flywheel_cli_e2e_learner_publishes_and_server_adopts(sac_ckpt):
    """THE production loop, end to end over the real CLI: feedback clients
    → spool → learner ingests ≥ N rows → publishes a NEW checkpoint step →
    the watcher adopts it → clients see a monotone version bump, with zero
    errors and zero session resets anywhere."""
    port = free_port()
    proc = _serve_flywheel(sac_ckpt, port)
    out = ""
    try:
        addr = ("127.0.0.1", port)
        _wait_ready(proc, addr)
        base_step = int(_probe(addr)["weights"]["step"])
        base_version = int(_probe(addr)["weights"]["version"])

        client = _FeedbackClient(addr)
        adopted = False
        deadline = time.monotonic() + 300
        errors = 0
        while time.monotonic() < deadline:
            resp = client.act([0.1] * 10)
            if "error" in resp:
                errors += 1
            health = _probe(addr)
            learner = health["flywheel"].get("learner") or {}
            if (
                learner.get("published_step", -1) > base_step
                and int(health["weights"]["step"]) > base_step
                and int(health["weights"]["version"]) > base_version
            ):
                adopted = True
                break
            time.sleep(0.05)
        final = _probe(addr)
        client.close()

        assert adopted, f"learner never published / watcher never adopted: {final}"
        assert errors == 0
        learner = final["flywheel"]["learner"]
        assert learner["consumed_rows"] >= 16, learner
        assert learner["grad_steps"] > 0, learner
        assert learner["published_step"] > base_step, learner
        # the loop closed: spooled production rows, zero shed, zero errors
        assert final["flywheel"]["rows_logged"] >= learner["consumed_rows"]
        assert final["flywheel"]["rows_shed"] == 0
        assert final["flywheel"]["errors"] == 0
        # clients saw the swap as a monotone version bump, never a reset
        assert client.versions == sorted(client.versions)
        assert client.versions[-1] > client.versions[0]
        assert final.get("sessions", {}).get("resets", 0) in (0,)

        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        _reap(proc)
    assert proc.returncode == 0, f"non-zero exit after SIGTERM:\n{out}"
    assert "serve: drained cleanly" in out
    assert "flywheel: published step" in out
    # the learner drained too (supervised SIGTERM, final publish, exit 0)
    assert "flywheel learner: done" in out


@pytest.mark.slow
def test_flywheel_chaos_drill_learner_sigstop_then_kill_serving_unaffected(sac_ckpt):
    """The isolation guarantee, proven: SIGSTOP the learner (missed lease →
    counted HANG → SIGKILL + respawn), then SIGKILL its replacement
    (counted death → respawn), all under continuous feedback traffic — and
    not one admitted request errors or drops."""
    port = free_port()
    proc = _serve_flywheel(
        sac_ckpt,
        port,
        extra=(
            # tight enough to detect the SIGSTOP within the drill's budget;
            # compile pauses can stall the beat past it too, so the drill
            # baselines the counters at steady state and asserts INCREMENTS,
            # with a restart budget that can absorb compile-pause kills
            "serve.flywheel.lease_s=6.0",
            "serve.flywheel.grace_s=240.0",
            "serve.flywheel.supervisor.backoff=0.1",
            "serve.flywheel.supervisor.max_restarts=20",
        ),
    )
    out = ""
    traffic_stop = threading.Event()
    traffic = {"requests": 0, "errors": 0}
    try:
        addr = ("127.0.0.1", port)
        _wait_ready(proc, addr)
        spool_dir = str(Path(sac_ckpt).parent / "flywheel")

        def _pump():
            client = _FeedbackClient(addr)
            try:
                while not traffic_stop.is_set():
                    try:
                        resp = client.act([0.2] * 10)
                    except OSError:
                        # a reset after the test gave up (failure-path
                        # SIGKILL) is teardown, not a serving error
                        if not traffic_stop.is_set():
                            traffic["errors"] += 1
                        return
                    traffic["requests"] += 1
                    if "error" in resp or "actions" not in resp:
                        traffic["errors"] += 1
                    time.sleep(0.01)
            finally:
                client.close()

        pump = threading.Thread(target=_pump, daemon=True)
        pump.start()

        # phase 0: steady state — the learner is up, beating, consuming
        # production rows AND past its lazy first compile (grad_steps > 0),
        # so the beats from here on are regular
        def _learner():
            return _probe(addr)["flywheel"].get("learner") or {}

        assert _wait(
            lambda: _learner().get("consumed_rows", 0) > 0 and _learner().get("grad_steps", 0) > 0,
            timeout=300,
        ), _probe(addr)
        status = read_learner_status(spool_dir)
        assert status is not None and "pid" in status, f"no {STATUS_NAME} in {spool_dir}"
        pid0 = int(status["pid"])
        hangs0 = int(_learner().get("hangs", 0))

        # phase 1: SIGSTOP — the status file goes quiet, the probe lease
        # expires, the supervisor counts a HANG, SIGKILLs, respawns
        os.kill(pid0, signal.SIGSTOP)
        assert _wait(lambda: _learner().get("hangs", 0) > hangs0, timeout=120), _learner()
        assert _wait(
            lambda: (
                (read_learner_status(spool_dir) or {}).get("pid") not in (None, pid0)
                and _learner().get("alive")
            ),
            timeout=180,
        ), _learner()
        pid1 = int(read_learner_status(spool_dir)["pid"])
        assert pid1 != pid0
        # hang recovery settled; deaths re-baselined (a hang counts a death
        # too when the wedged process is SIGKILLed)
        deaths1 = int(_learner().get("deaths", 0))

        # phase 2: SIGKILL the replacement outright — counted as a DEATH
        # (distinct from the hang), respawned again
        try:
            os.kill(pid1, signal.SIGKILL)
        except ProcessLookupError:
            pass  # already gone (supervisor churn) — its death still counts
        assert _wait(lambda: _learner().get("deaths", 0) > deaths1, timeout=120), _learner()
        assert _wait(
            lambda: (read_learner_status(spool_dir) or {}).get("pid") not in (None, pid1)
            and _learner().get("alive"),
            timeout=180,
        ), _learner()

        # serving never noticed: traffic kept flowing the whole time
        traffic_stop.set()
        pump.join(timeout=30)
        final = _probe(addr)
        assert traffic["requests"] > 0
        assert traffic["errors"] == 0, traffic
        learner = final["flywheel"]["learner"]
        assert learner["hangs"] >= 1, learner
        assert learner["deaths"] >= 1, learner
        assert learner["restarts"] >= 2, learner
        assert learner["fatal"] is None, learner
        assert final["ready"] is True
        assert final["status"] == "ok", final
        assert final["flywheel"]["errors"] == 0

        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        traffic_stop.set()
        _reap(proc)
    assert proc.returncode == 0, f"non-zero exit after SIGTERM:\n{out}"
    assert "serve: drained cleanly" in out
    # zero admitted requests dropped: every request the pump sent came back
    # answered (errors==0 above), and the final stats snapshot the CLI
    # prints on the way out shows nothing was rejected either
    stats_lines = [ln for ln in out.splitlines() if ln.startswith("{") and "Serve/requests" in ln]
    assert stats_lines, out
    stats = json.loads(stats_lines[-1])
    assert stats["Serve/rejected"] == 0, stats
    assert stats["Serve/requests"] >= traffic["requests"]
