"""Hot-swap semantics: versioned store, manifest-driven checkpoint watcher,
and the load-bearing claim — swaps under live traffic drop and tear nothing."""

import threading
import time

import jax
import numpy as np
import pytest

from sheeprl_tpu.fault.manager import CheckpointManager
from sheeprl_tpu.serve.engine import BucketEngine
from sheeprl_tpu.serve.scheduler import RequestScheduler
from sheeprl_tpu.serve.weights import CheckpointWatcher, WeightStore


def test_weight_store_versions_monotone(toy_policy):
    store = WeightStore(toy_policy.params, toy_policy.params_from_state)
    assert store.version == 0
    v0, p0 = store.pull()
    assert v0 == 0 and p0 is toy_policy.params
    v1 = store.publish_params(jax.tree.map(lambda x: x + 1, toy_policy.params))
    v2 = store.publish_state({"w": np.ones((2, 3), np.float32)})
    assert (v1, v2) == (1, 2)
    v, params = store.pull()
    assert v == 2
    assert np.allclose(np.asarray(params["w"]), 1.0)


def test_weight_store_without_converter(toy_policy):
    store = WeightStore(toy_policy.params)
    with pytest.raises(RuntimeError):
        store.publish_state({"w": np.ones((2, 3), np.float32)})


def _save(manager, ckpt_dir, step, scale):
    state = {"agent": {"w": np.full((2, 3), float(scale), np.float32)}}
    manager.save(ckpt_dir / f"ckpt_{step}_0.ckpt", state, step=step)


def test_checkpoint_watcher_publishes_new_saves(tmp_path, toy_policy):
    """Manifest-published saves flow into the store in step order; the save
    that existed BEFORE the watcher started is not re-published (the server
    was built from it)."""
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    manager = CheckpointManager()
    _save(manager, ckpt_dir, 10, scale=1.0)

    store = WeightStore(toy_policy.params, toy_policy.params_from_state)
    watcher = CheckpointWatcher(ckpt_dir, store, poll_s=30.0)
    watcher._prime()  # what start() does; poll manually for determinism
    assert watcher.poll_once() is False  # nothing new
    assert store.version == 0

    _save(manager, ckpt_dir, 20, scale=2.0)
    assert watcher.poll_once() is True
    assert store.version == 1
    _, params = store.pull()
    assert np.allclose(np.asarray(params["w"]), 2.0)
    # same checkpoint again: no re-publish
    assert watcher.poll_once() is False
    assert watcher.published == 1


def test_checkpoint_watcher_thread_end_to_end(tmp_path, toy_policy):
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    manager = CheckpointManager()
    store = WeightStore(toy_policy.params, toy_policy.params_from_state)
    watcher = CheckpointWatcher(ckpt_dir, store, poll_s=0.05).start()
    try:
        _save(manager, ckpt_dir, 5, scale=3.0)
        deadline = time.perf_counter() + 10.0
        while store.version < 1 and time.perf_counter() < deadline:
            time.sleep(0.02)
        assert store.version == 1
    finally:
        watcher.stop()


def test_hot_swap_under_load(toy_policy):
    """Traffic hammers the scheduler from several threads while weights swap
    repeatedly: every request resolves (zero dropped), versions are monotone
    in serve order (zero torn — each batch under exactly one snapshot), and
    post-final-swap actions reflect the final weights."""
    engine = BucketEngine(toy_policy, buckets=(1, 4, 16), mode="greedy")
    store = WeightStore(toy_policy.params, toy_policy.params_from_state)
    sched = RequestScheduler(engine, store, max_wait_s=0.001, queue_bound=256).start()

    n_threads, n_requests = 4, 60
    results = [[] for _ in range(n_threads)]
    errors = []

    def client(idx):
        rng = np.random.default_rng(idx)
        for _ in range(n_requests):
            obs = {"x": rng.standard_normal((1, 2)).astype(np.float32)}
            try:
                req = sched.submit(obs, timeout=10.0)
                actions, version = sched.result(req, timeout=10.0)
                results[idx].append((req.t_resolve, version, obs, actions))
            except Exception as e:  # noqa: BLE001 - the test asserts emptiness
                errors.append(e)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    n_swaps = 5
    for s in range(1, n_swaps + 1):
        time.sleep(0.02)
        store.publish_state({"w": np.full((2, 3), float(s), np.float32)})
    for t in threads:
        t.join(timeout=30.0)
    # deterministic final probe: served strictly after the last publish
    probe_obs = {"x": np.ones((1, 2), np.float32)}
    probe = sched.submit(probe_obs, timeout=10.0)
    _, probe_version = sched.result(probe, timeout=10.0)
    sched.stop()

    assert not errors, errors
    assert probe_version == n_swaps
    flat = sorted((item for r in results for item in r), key=lambda it: it[0])
    assert len(flat) == n_threads * n_requests  # zero dropped
    versions = [v for _, v, _, _ in flat]
    assert all(a <= b for a, b in zip(versions, versions[1:])), "versions regressed mid-stream"
    assert sched.stats.snapshot()["Serve/swap_count"] == n_swaps
    # zero torn: each response matches a SINGLE version's weights exactly
    for _, version, obs, actions in flat:
        w = np.asarray(toy_policy.params["w"]) if version == 0 else np.full((2, 3), float(version), np.float32)
        assert np.allclose(actions, obs["x"] @ w, rtol=1e-5), f"actions torn across versions at v{version}"

def test_watcher_strikes_a_save_that_loads_but_cannot_rebuild(tmp_path, toy_policy):
    """Review regression: a checkpoint that LOADS fine but whose tree
    params_from_state cannot rebuild (wrong layout — e.g. a foreign save
    with no 'agent' key feeding the full state to a stateless rebuilder)
    must strike and quarantine like any other bad save, not wedge the
    publish loop retrying it forever; a NEWER good save still swaps in."""
    import jax.numpy as jnp

    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    mgr = CheckpointManager()

    def strict_rebuild(agent_state):
        return {"w": jnp.asarray(agent_state["w"], jnp.float32)}  # KeyError on foreign layouts

    store = WeightStore(toy_policy.params, strict_rebuild)
    watcher = CheckpointWatcher(ckpt_dir, store, poll_s=0.05, quarantine_after=2)
    # loads fine, rebuilds never: no "agent" key -> full-state fallback
    # reaches strict_rebuild, which KeyErrors
    mgr.save(ckpt_dir / "ckpt_10_0.ckpt", {"foreign": {"w": np.ones((2, 3), np.float32)}}, step=10)
    with pytest.warns(UserWarning, match="could not load"):
        assert watcher.poll_once() is False
    with pytest.warns(UserWarning, match="QUARANTINED"):
        assert watcher.poll_once() is False
    assert watcher.quarantined and store.version == 0
    # a newer GOOD save publishes despite the quarantined one in between
    mgr.save(ckpt_dir / "ckpt_20_0.ckpt", {"agent": {"w": 2 * np.ones((2, 3), np.float32)}}, step=20)
    assert watcher.poll_once() is True
    assert store.version == 1
