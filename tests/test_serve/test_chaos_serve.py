"""Serve-tier chaos drills (acceptance proof (b)): sustained offered load
across a scheduler-worker kill AND a torn-checkpoint publish with dropped ==
0 and errors == 0 for every admitted request, the health probe reflecting
each state transition (ok -> restarts visible -> quarantine visible ->
draining); watcher poll errors counted and survivable; watcher thread kill
-> supervised restart; SIGTERM -> graceful drain (in-process handler unit +
the real CLI verb in a subprocess exiting 0)."""

import glob
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.fault import inject
from sheeprl_tpu.fault.manager import CheckpointManager
from sheeprl_tpu.serve.server import PolicyServer, install_drain_handlers

pytestmark = pytest.mark.chaos

REPO_ROOT = str(Path(__file__).parents[2])


@pytest.fixture(autouse=True)
def _inject_isolation():
    inject.reset()
    yield
    inject.reset()


def _probe(addr, timeout=5.0):
    with socket.create_connection(addr, timeout=timeout) as s:
        s.sendall(b'{"health": true}\n')
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.decode())


def _wait(predicate, timeout=10.0, poll=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


def test_serve_chaos_under_load_zero_dropped(toy_policy, tmp_path, recwarn):
    """Acceptance proof (b): offered load sustained across (1) a
    kill-the-scheduler-worker injection and (2) a torn checkpoint publish:
    every admitted request resolves (dropped == 0, errors == 0), weight
    versions stay monotone in serve order per client, and the health probe
    reflects each transition."""
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    mgr = CheckpointManager()
    cfg = {
        "buckets": [1, 4],
        "port": 0,
        "max_wait_ms": 1.0,
        "watch_poll_s": 0.05,
        "watcher_quarantine_after": 2,
        "supervisor": {"backoff": 0.02},
    }
    server = PolicyServer(toy_policy, cfg, watch_dir=str(ckpt_dir)).start()
    addr = server.address
    assert _probe(addr)["status"] == "ok"
    assert _probe(addr)["ready"] is True

    inject.arm("serve.scheduler.batch", action="kill-thread", at=4)
    results = [[] for _ in range(4)]
    errors = []

    def client_loop(i):
        for j in range(40):
            try:
                actions, version = server.client.act(
                    {"x": np.full((1, 2), float(i), np.float32)}, n=1, timeout=60
                )
                results[i].append((np.asarray(actions), version))
            except Exception as e:  # admitted requests must NEVER error
                errors.append((i, j, repr(e)))

    threads = [threading.Thread(target=client_loop, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    # mid-load: one good publish, then a TORN one (rotted below the digest;
    # planted atomically so the 50ms poller can never catch it loadable)
    mgr.save(ckpt_dir / "ckpt_10_0.ckpt", {"agent": {"w": np.ones((2, 3), np.float32)}}, step=10)
    assert _wait(lambda: server.weights.version >= 1)
    inject.plant_torn_checkpoint(
        ckpt_dir, "ckpt_20_0.ckpt", {"agent": {"w": 2 * np.ones((2, 3), np.float32)}}, step=20
    )
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads)

    # zero dropped, zero errors: every admitted request resolved with actions
    assert errors == []
    assert [len(r) for r in results] == [40, 40, 40, 40]
    for rows in results:
        versions = [v for _a, v in rows]
        assert versions == sorted(versions)  # monotone in serve order per client

    # health reflects the kill (restart counted) and the torn publish
    # (strikes counted, path quarantined), while serving stayed ok
    assert _wait(lambda: _probe(addr)["scheduler"]["restarts"] >= 1)
    assert _wait(lambda: len(_probe(addr)["watcher"]["quarantined"]) == 1, timeout=15)
    health = _probe(addr)
    assert health["status"] == "ok"
    assert health["watcher"]["errors"] >= 2  # the 2 strikes that led to quarantine
    assert health["watcher"]["published"] == 1  # the good save; the torn one never swapped in
    assert health["weights"]["version"] == 1
    assert health["weights"]["staleness_s"] >= 0.0

    # a NEWER good save publishes despite the quarantined one in between
    mgr.save(ckpt_dir / "ckpt_30_0.ckpt", {"agent": {"w": 3 * np.ones((2, 3), np.float32)}}, step=30)
    assert _wait(lambda: server.weights.version >= 2)

    server.stop()
    post = server.health()
    assert post["status"] == "draining" and post["ready"] is False


def test_watcher_poll_error_counted_and_survived(toy_policy, tmp_path):
    """A poll failure (exception, not thread death) is swallowed, COUNTED in
    Serve/watcher_errors, and the loop keeps publishing afterwards."""
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    cfg = {"buckets": [1], "port": None, "watch_poll_s": 0.05}
    server = PolicyServer(toy_policy, cfg, watch_dir=str(ckpt_dir)).start()
    inject.arm("serve.watcher.poll", action="raise", at=2)
    with pytest.warns(UserWarning, match="watcher error"):
        assert _wait(lambda: server.stats.watcher_errors == 1)
    assert server.watcher.alive()
    CheckpointManager().save(
        ckpt_dir / "ckpt_10_0.ckpt", {"agent": {"w": np.ones((2, 3), np.float32)}}, step=10
    )
    assert _wait(lambda: server.weights.version >= 1)
    assert server.stats.snapshot()["Serve/watcher_errors"] == 1
    server.stop()


def test_watcher_thread_kill_restarted_by_supervisor(toy_policy, tmp_path):
    """ThreadKilled escapes the per-poll except Exception, the generation
    dies, the supervisor restarts it, and hot swaps keep working."""
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    cfg = {"buckets": [1], "port": None, "watch_poll_s": 0.05, "supervisor": {"backoff": 0.02}}
    server = PolicyServer(toy_policy, cfg, watch_dir=str(ckpt_dir)).start()
    inject.arm("serve.watcher.poll", action="kill-thread", at=2)
    with pytest.warns(UserWarning, match="serve-ckpt-watcher.*restarting"):
        assert _wait(lambda: server.supervisor.worker("serve-ckpt-watcher").restarts >= 1)
    assert _wait(lambda: server.watcher.alive())
    CheckpointManager().save(
        ckpt_dir / "ckpt_10_0.ckpt", {"agent": {"w": np.ones((2, 3), np.float32)}}, step=10
    )
    assert _wait(lambda: server.weights.version >= 1)
    health = server.health()
    assert health["watcher"]["restarts"] >= 1 and health["status"] == "ok"
    server.stop()


def test_supervised_scheduler_own_stop_is_not_respawned(toy_policy):
    """scheduler.stop() WITHOUT supervisor.request_stop() first (the
    documented standalone API): the worker's clean drain-and-exit must read
    as retired — the monitor must not respawn it into a drain race nor
    declare the pool dead."""
    from sheeprl_tpu.fault.supervisor import Supervisor
    from sheeprl_tpu.serve.engine import BucketEngine
    from sheeprl_tpu.serve.scheduler import RequestScheduler
    from sheeprl_tpu.serve.weights import WeightStore

    engine = BucketEngine(toy_policy, buckets=(1, 4), mode="greedy")
    store = WeightStore(toy_policy.params, toy_policy.params_from_state)
    sup = Supervisor(max_restarts=3, backoff=0.02, lease_s=None)
    sup.start_monitor(poll_s=0.02)
    sched = RequestScheduler(engine, store, max_wait_s=0.001).start(supervisor=sup)
    req = sched.submit({"x": np.ones((1, 2), np.float32)})
    sched.result(req, timeout=10)
    sched.stop(drain=True)
    assert _wait(lambda: sup.worker("serve-scheduler").state == "stopped")
    time.sleep(0.2)  # several monitor ticks: no respawn, no fatal verdict
    h = sup.worker("serve-scheduler")
    assert h.restarts == 0 and h.deaths == 0 and not h.is_alive()
    assert sup.fatal is None
    sup.stop_monitor()


def test_watcher_tolerates_plain_pipeline_stats(tmp_path, toy_policy):
    """stats: PipelineStats (no Serve/* fields) is annotation-legal: a load
    strike must count nothing rather than AttributeError the poll loop to
    death — the silent-death mode this PR exists to eliminate."""
    from sheeprl_tpu.parallel.pipeline import PipelineStats
    from sheeprl_tpu.serve.weights import CheckpointWatcher, WeightStore

    ckpt_dir = tmp_path / "checkpoint"
    store = WeightStore(toy_policy.params, toy_policy.params_from_state)
    watcher = CheckpointWatcher(ckpt_dir, store, poll_s=0.05, stats=PipelineStats(), quarantine_after=2)
    watcher.start()  # plant AFTER start: a pre-existing save would be primed away
    inject.plant_torn_checkpoint(ckpt_dir, "ckpt_10_0.ckpt", {"agent": {"w": np.ones((2, 3), np.float32)}})
    with pytest.warns(UserWarning, match="could not load"):
        assert _wait(lambda: watcher._strikes != {})
    assert watcher.alive()  # the loop survived the un-countable strike
    assert _wait(lambda: watcher.quarantined)
    watcher.stop()


def test_scheduler_recover_inflight_preserves_admission_order(toy_policy):
    """Unit-level zero-drop invariant: a batch collected by a dead worker
    generation re-enters at the HEAD of the next generation's admission."""
    from sheeprl_tpu.serve.engine import BucketEngine
    from sheeprl_tpu.serve.scheduler import RequestScheduler, _Request
    from sheeprl_tpu.serve.weights import WeightStore

    engine = BucketEngine(toy_policy, buckets=(1, 4), mode="greedy")
    store = WeightStore(toy_policy.params, toy_policy.params_from_state)
    sched = RequestScheduler(engine, store, max_wait_s=0.001)
    inflight = [_Request({"x": np.ones((1, 2), np.float32)}, 1) for _ in range(2)]
    sched._inflight = list(inflight)
    assert sched.recover_inflight() == 2
    assert sched._next_request(timeout=0.01) is inflight[0]
    assert sched._next_request(timeout=0.01) is inflight[1]
    assert sched.recover_inflight() == 0  # idempotent once handed over


def test_install_drain_handlers_flags_event_and_restores():
    event = threading.Event()
    before_term = signal.getsignal(signal.SIGTERM)
    before_int = signal.getsignal(signal.SIGINT)
    restore = install_drain_handlers(event)
    try:
        signal.raise_signal(signal.SIGTERM)
        assert event.wait(2.0)
    finally:
        restore()
    assert signal.getsignal(signal.SIGTERM) is before_term
    assert signal.getsignal(signal.SIGINT) is before_int


PPO_TINY = [
    "exp=ppo",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_serve_cli_sigterm_graceful_drain_exits_zero(tmp_path):
    """The real CLI verb in a subprocess: SIGTERM mid-serve stops accepting,
    settles what was admitted, prints the drain line, and exits 0."""
    run(PPO_TINY + [f"log_root={tmp_path}/train", "dry_run=True", "checkpoint.save_last=True"])
    ckpts = sorted(glob.glob(f"{tmp_path}/train/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)
    assert ckpts
    port = _free_port()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "sheeprl_tpu",
            "serve",
            f"checkpoint_path={ckpts[-1]}",
            "fabric.accelerator=cpu",
            f"serve.port={port}",
            "serve.buckets=[1,2]",
            "serve.log_every_s=60",
        ],
        cwd=REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        addr = ("127.0.0.1", port)
        deadline = time.monotonic() + 180
        while True:  # wait for the socket front end (AOT compiles first)
            try:
                health = _probe(addr)
                break
            except (ConnectionRefusedError, OSError):
                assert proc.poll() is None, f"server died early:\n{proc.stdout.read()}"
                assert time.monotonic() < deadline, "server never came up"
                time.sleep(0.5)
        assert health["status"] == "ok"
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
    assert proc.returncode == 0, f"non-zero exit after SIGTERM:\n{out}"
    assert "received SIGTERM — graceful drain" in out
    assert "serve: drained cleanly" in out
