"""Shared serve-tier fixtures: a pure-function toy policy (scheduler/weights
semantics without the algo stack) and real PPO/SAC policies built through the
registered builders over synthetic spaces."""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.config import compose
from sheeprl_tpu.parallel import Fabric
from sheeprl_tpu.serve.policy import ServePolicy


@pytest.fixture()
def toy_policy():
    """Linear map policy: tiny, deterministic, swap-observable (actions scale
    with the params), no flax/env dependency."""
    w = jnp.asarray(np.arange(6, dtype=np.float32).reshape(2, 3))
    params = {"w": w}

    def greedy_fn(p, obs):
        return obs["x"] @ p["w"]

    def sample_fn(p, obs, key):
        noise = jax.random.normal(key, (obs["x"].shape[0], 3), dtype=jnp.float32)
        return obs["x"] @ p["w"] + 1e-3 * noise

    return ServePolicy(
        name="toy",
        params=params,
        obs_spec={"x": ((2,), np.float32)},
        action_dim=3,
        greedy_fn=greedy_fn,
        sample_fn=sample_fn,
        prepare=lambda obs, n: {"x": np.asarray(obs["x"], dtype=np.float32).reshape(n, 2)},
        params_from_state=lambda state: jax.tree.map(jnp.asarray, state),
    )


def _fabric():
    f = Fabric(devices=1, accelerator="cpu")
    f.seed_everything(42)
    return f


@pytest.fixture(scope="module")
def ppo_policy():
    """Real PPO policy (discrete CartPole spaces) through the registered
    builder, random init params."""
    from sheeprl_tpu.algos.ppo.evaluate import serve_policy_ppo

    cfg = compose(
        [
            "exp=ppo",
            "env=gym",
            "env.capture_video=False",
            "fabric.devices=1",
            "metric.log_level=0",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (4,), np.float32)})
    act_space = gym.spaces.Discrete(2)
    return serve_policy_ppo(_fabric(), cfg, obs_space, act_space, None)


@pytest.fixture(scope="module")
def sac_policy():
    """Real SAC policy (continuous Pendulum spaces) through the registered
    builder, random init params."""
    from sheeprl_tpu.algos.sac.evaluate import serve_policy_sac

    cfg = compose(
        [
            "exp=sac",
            "env=gym",
            "env.id=Pendulum-v1",
            "env.capture_video=False",
            "fabric.devices=1",
            "metric.log_level=0",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (3,), np.float32)})
    act_space = gym.spaces.Box(-2.0, 2.0, (1,), np.float32)
    return serve_policy_sac(_fabric(), cfg, obs_space, act_space, None)
