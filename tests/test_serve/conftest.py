"""Shared serve-tier fixtures: a pure-function toy policy (scheduler/weights
semantics without the algo stack), a toy STATEFUL counter policy (session
semantics — every action row carries its session's step count, so stream
continuity/reset/loss are directly observable), and real PPO/SAC/recurrent
policies built through the registered builders over synthetic spaces."""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.config import compose
from sheeprl_tpu.parallel import Fabric
from sheeprl_tpu.serve.policy import ServePolicy, StatefulServePolicy


@pytest.fixture()
def toy_policy():
    """Linear map policy: tiny, deterministic, swap-observable (actions scale
    with the params), no flax/env dependency."""
    w = jnp.asarray(np.arange(6, dtype=np.float32).reshape(2, 3))
    params = {"w": w}

    def greedy_fn(p, obs):
        return obs["x"] @ p["w"]

    def sample_fn(p, obs, key):
        noise = jax.random.normal(key, (obs["x"].shape[0], 3), dtype=jnp.float32)
        return obs["x"] @ p["w"] + 1e-3 * noise

    return ServePolicy(
        name="toy",
        params=params,
        obs_spec={"x": ((2,), np.float32)},
        action_dim=3,
        greedy_fn=greedy_fn,
        sample_fn=sample_fn,
        prepare=lambda obs, n: {"x": np.asarray(obs["x"], dtype=np.float32).reshape(n, 2)},
        params_from_state=lambda state: jax.tree.map(jnp.asarray, state),
    )


@pytest.fixture()
def toy_stateful_policy():
    """Counter policy: per-session state is a step counter; action row =
    ``[count, w·obs_sum]``. A served stream's ``actions[:, 0]`` must read
    ``0, 1, 2, ...`` — any reset, drop, reorder or cross-session mixup is
    immediately visible in the action values themselves."""
    w = jnp.asarray(np.arange(4, dtype=np.float32).reshape(2, 2))
    params = {"w": w}

    def step_fn(p, obs, state, key, greedy):
        del key, greedy
        count = state["count"][:, 0]
        y = (obs["x"] @ p["w"]).sum(-1)
        return jnp.stack([count, y], axis=-1), {"count": state["count"] + 1.0}

    def init_fn(p, n):
        del p
        return {"count": jnp.zeros((n, 1), jnp.float32)}

    return StatefulServePolicy(
        name="toy_stateful",
        params=params,
        obs_spec={"x": ((2,), np.float32)},
        action_dim=2,
        step_fn=step_fn,
        init_fn=init_fn,
        prepare=lambda obs, n: {"x": np.asarray(obs["x"], np.float32).reshape(n, 2)},
        params_from_state=lambda state: jax.tree.map(jnp.asarray, state),
    )


def _fabric():
    f = Fabric(devices=1, accelerator="cpu")
    f.seed_everything(42)
    return f


@pytest.fixture(scope="module")
def ppo_policy():
    """Real PPO policy (discrete CartPole spaces) through the registered
    builder, random init params."""
    from sheeprl_tpu.algos.ppo.evaluate import serve_policy_ppo

    cfg = compose(
        [
            "exp=ppo",
            "env=gym",
            "env.capture_video=False",
            "fabric.devices=1",
            "metric.log_level=0",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (4,), np.float32)})
    act_space = gym.spaces.Discrete(2)
    return serve_policy_ppo(_fabric(), cfg, obs_space, act_space, None)


@pytest.fixture(scope="module")
def sac_policy():
    """Real SAC policy (continuous Pendulum spaces) through the registered
    builder, random init params."""
    from sheeprl_tpu.algos.sac.evaluate import serve_policy_sac

    cfg = compose(
        [
            "exp=sac",
            "env=gym",
            "env.id=Pendulum-v1",
            "env.capture_video=False",
            "fabric.devices=1",
            "metric.log_level=0",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (3,), np.float32)})
    act_space = gym.spaces.Box(-2.0, 2.0, (1,), np.float32)
    return serve_policy_sac(_fabric(), cfg, obs_space, act_space, None)


RECURRENT_TINY = [
    "exp=ppo_recurrent",
    "env=gym",
    "env.capture_video=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "algo.mlp_keys.encoder=[state]",
]


@pytest.fixture(scope="module")
def recurrent_policy():
    """Real stateful ppo_recurrent policy (discrete CartPole spaces) through
    the registered builder, random init params."""
    from sheeprl_tpu.algos.ppo_recurrent.evaluate import serve_policy_ppo_recurrent

    cfg = compose(RECURRENT_TINY)
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (4,), np.float32)})
    act_space = gym.spaces.Discrete(2)
    return serve_policy_ppo_recurrent(_fabric(), cfg, obs_space, act_space, None)
