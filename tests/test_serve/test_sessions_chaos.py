"""graft-sessions chaos drills: live sessions across a scheduler-worker kill
(per-client action streams continue with ZERO resets, dropped == 0 — the
counter policy makes continuity directly observable in the action values) and
across a torn-checkpoint publish (quarantine leaves sessions untouched); the
health probe's sessions block asserted through each."""

import json
import socket
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.fault import inject
from sheeprl_tpu.fault.manager import CheckpointManager
from sheeprl_tpu.serve.server import PolicyServer

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _inject_isolation():
    inject.reset()
    yield
    inject.reset()


def _wait(predicate, timeout=10.0, poll=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


def _probe(addr, timeout=5.0):
    with socket.create_connection(addr, timeout=timeout) as s:
        s.sendall(b'{"health": true}\n')
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.decode())


SESSION_CFG = {
    "max_wait_ms": 1.0,
    "port": 0,
    "session": {"buckets": [1, 4], "max_sessions": 16, "ttl_s": 100.0},
    "supervisor": {"backoff": 0.02},
}


def test_scheduler_kill_with_live_sessions_streams_continue(toy_stateful_policy, tmp_path):
    """A scheduler-worker kill mid-stream with live sessions: the supervisor
    restarts it, the recovered in-flight batch re-serves against the
    server-owned cache, and every client's action stream reads 0..N-1 with
    no gap and no restart — zero sessions dropped, zero involuntary
    resets."""
    server = PolicyServer(toy_stateful_policy, dict(SESSION_CFG)).start()
    addr = server.address
    inject.arm("serve.scheduler.batch", action="kill-thread", at=4)
    K, STEPS = 4, 30
    streams = [[] for _ in range(K)]
    errors = []

    def client_loop(i):
        for j in range(STEPS):
            try:
                actions, _version = server.client.act(
                    {"x": np.full(2, float(i), np.float32)}, session_id=f"user-{i}", timeout=60
                )
                streams[i].append(float(np.asarray(actions)[0, 0]))
            except Exception as e:  # admitted session steps must NEVER error
                errors.append((i, j, repr(e)))

    threads = [threading.Thread(target=client_loop, args=(i,)) for i in range(K)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads)

    assert errors == []
    for i in range(K):
        # the whole claim in one line: the served step counter never skipped,
        # never repeated, never reset — across the worker kill
        assert streams[i] == [float(s) for s in range(STEPS)]

    assert _wait(lambda: _probe(addr)["scheduler"]["restarts"] >= 1)
    health = _probe(addr)
    assert health["status"] == "ok"
    assert health["sessions"]["live"] == K
    assert health["sessions"]["peak"] == K
    assert health["sessions"]["resets"] == 0
    assert health["sessions"]["evictions"] == 0
    assert health["sessions"]["state_bytes"] > 0
    snap = server.stats.snapshot()
    assert snap["Serve/sessions_reset"] == 0 and snap["Serve/sessions_live"] == K
    server.stop()
    post = server.health()
    assert post["status"] == "draining" and post["sessions"]["live"] == K


def test_torn_checkpoint_publish_leaves_sessions_untouched(toy_stateful_policy, tmp_path):
    """A good publish swaps in under live sessions (streams continue, reset
    count 0); an atomically-planted TORN publish strikes out and is
    quarantined while the sessions keep stepping the last good weights."""
    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    mgr = CheckpointManager()
    cfg = dict(SESSION_CFG)
    cfg.update({"watch_poll_s": 0.05, "watcher_quarantine_after": 2})
    server = PolicyServer(toy_stateful_policy, cfg, watch_dir=str(ckpt_dir)).start()
    addr = server.address
    obs = {"x": np.ones(2, np.float32)}
    K = 3
    for t in range(3):
        for i in range(K):
            actions, _ = server.client.act(obs, session_id=f"user-{i}", timeout=60)
            assert actions[0, 0] == t

    # good publish: compatible avals -> sessions ride the swap live
    mgr.save(ckpt_dir / "ckpt_10_0.ckpt", {"agent": {"w": np.ones((2, 2), np.float32)}}, step=10)
    assert _wait(lambda: server.weights.version >= 1)
    for i in range(K):
        actions, version = server.client.act(obs, session_id=f"user-{i}", timeout=60)
        assert version == 1
        assert actions[0, 0] == 3  # stream continued under the new weights

    # torn publish: rot below the manifest digest, planted atomically
    inject.plant_torn_checkpoint(
        ckpt_dir, "ckpt_20_0.ckpt", {"agent": {"w": 2 * np.ones((2, 2), np.float32)}}, step=20
    )
    assert _wait(lambda: len(_probe(addr)["watcher"]["quarantined"]) == 1, timeout=15)
    for i in range(K):
        actions, version = server.client.act(obs, session_id=f"user-{i}", timeout=60)
        assert version == 1  # still the last good weights
        assert actions[0, 0] == 4  # ...and the stream never blinked

    health = _probe(addr)
    assert health["status"] == "ok"
    assert health["watcher"]["published"] == 1
    assert health["sessions"]["live"] == K and health["sessions"]["resets"] == 0
    snap = server.stats.snapshot()
    assert snap["Serve/sessions_reset"] == 0
    server.stop()
