"""Smoke tests for the dep-gated env backends against faked SDK modules.

MineDojo/MineRL/DIAMBRA/Super-Mario wheels can't be installed in CI, so these
tests inject minimal fake module trees into ``sys.modules`` and drive the real
wrapper code through reset/step/conversion paths: action catalogue assembly,
sticky attack/jump, pitch limiting, observation flattening, and the custom
MineRL spec tables. The fakes implement only the SDK surface the wrappers
touch (reference behavior: ``sheeprl/envs/{minedojo,minerl,diambra,
super_mario_bros}.py``).
"""

from __future__ import annotations

import importlib
import sys
import types
from types import SimpleNamespace

import gymnasium as gym
import numpy as np
import pytest


def _load_backend(monkeypatch, fakes, flags, target):
    """Install fake SDK modules, force the availability flags, and (re)import
    the backend module. The caller's monkeypatch undoes the sys.modules and
    flag edits; the reimported backend is evicted so later tests never see a
    module bound to the fakes."""
    for name, mod in fakes.items():
        monkeypatch.setitem(sys.modules, name, mod)
    imports = importlib.import_module("sheeprl_tpu.utils.imports")
    for flag in flags:
        monkeypatch.setattr(imports, flag, True)
    evicted = [target] + [m for m in list(sys.modules) if m.startswith(target + ".")]
    for name in evicted:
        sys.modules.pop(name, None)
    module = importlib.import_module(target)
    return module


@pytest.fixture
def evict_backend_modules():
    """Drop reimported backend modules after the test so the fakes don't leak."""
    yield
    for name in list(sys.modules):
        if name.startswith("sheeprl_tpu.envs.minedojo") or name.startswith("sheeprl_tpu.envs.minerl"):
            sys.modules.pop(name, None)
        if name.startswith("sheeprl_tpu.envs.diambra") or name.startswith("sheeprl_tpu.envs.super_mario_bros"):
            sys.modules.pop(name, None)


# ---------------------------------------------------------------------------
# MineDojo
# ---------------------------------------------------------------------------

_MD_ITEMS = ["air", "stone", "wooden_pickaxe", "crafting_table", "dirt"]
_MD_CRAFT = ["stick", "planks", "torch"]


class _FakeMineDojoSim:
    """Raw MineDojo sim: 8-slot ARNN action vector in, nested obs dict out."""

    def __init__(self, height, width, pitch=0.0):
        self.observation_space = {"rgb": gym.spaces.Box(0, 255, (height, width, 3), np.uint8)}
        self._shape = (height, width, 3)
        self._pitch = pitch
        self.received = []
        self.unwrapped = SimpleNamespace(_prev_obs=None)

    def _obs(self):
        slots = ["air", "stone", "crafting table"]
        return {
            "rgb": np.zeros(self._shape, np.uint8),
            "inventory": {"name": np.array(slots), "quantity": np.array([1.0, 3.0, 1.0])},
            "delta_inv": {
                k: []
                for k in (
                    "inc_name_by_craft", "inc_quantity_by_craft", "dec_name_by_craft", "dec_quantity_by_craft",
                    "inc_name_by_other", "inc_quantity_by_other", "dec_name_by_other", "dec_quantity_by_other",
                )
            },
            "equipment": {"name": ["air"]},
            "life_stats": {
                "life": np.array([20.0]),
                "food": np.array([20.0]),
                "oxygen": np.array([300.0]),
            },
            "location_stats": {
                "pos": np.array([0.0, 64.0, 0.0]),
                "pitch": np.array([self._pitch]),
                "yaw": np.array([0.0]),
                "biome_id": np.array([1]),
            },
            "masks": {
                "action_type": np.ones(8, bool),
                "equip": np.array([False, True, False]),
                "destroy": np.array([False, True, True]),
                "craft_smelt": np.ones(len(_MD_CRAFT), bool),
            },
        }

    def reset(self):
        return self._obs()

    def step(self, action):
        self.received.append(np.asarray(action).copy())
        return self._obs(), 1.0, False, {}

    def close(self):
        pass


def _fake_minedojo_tree(sim_holder, pitch=0.0):
    minedojo = types.ModuleType("minedojo")
    tasks = types.ModuleType("minedojo.tasks")
    sim = types.ModuleType("minedojo.sim")
    tasks.ALL_TASKS_SPECS = {"harvest": object()}
    sim.ALL_ITEMS = list(_MD_ITEMS)
    sim.ALL_CRAFT_SMELT_ITEMS = list(_MD_CRAFT)

    def make(task_id, image_size, **kwargs):
        env = _FakeMineDojoSim(*image_size, pitch=pitch)
        sim_holder.append(env)
        return env

    minedojo.make = make
    minedojo.tasks = tasks
    minedojo.sim = sim
    return {"minedojo": minedojo, "minedojo.tasks": tasks, "minedojo.sim": sim}


def _make_minedojo(monkeypatch, pitch=0.0, **kwargs):
    sims = []
    module = _load_backend(
        monkeypatch, _fake_minedojo_tree(sims, pitch), ["_IS_MINEDOJO_AVAILABLE"], "sheeprl_tpu.envs.minedojo"
    )
    env = module.MineDojoWrapper(id="harvest_milk", **kwargs)
    return env, sims[0]


@pytest.mark.usefixtures("evict_backend_modules")
class TestMineDojoMocked:
    def test_spaces_and_obs_conversion(self, monkeypatch):
        env, _ = _make_minedojo(monkeypatch)
        assert env.action_space.nvec.tolist() == [19, len(_MD_CRAFT), len(_MD_ITEMS)]
        obs, info = env.reset()
        assert set(obs) == set(env.observation_space.spaces)
        n = len(_MD_ITEMS)
        assert obs["inventory"].shape == (n,)
        # slot quantities land on the normalized item ids ("crafting table" -> crafting_table)
        assert obs["inventory"][_MD_ITEMS.index("stone")] == 3.0
        assert obs["inventory"][_MD_ITEMS.index("crafting_table")] == 1.0
        assert obs["inventory"][_MD_ITEMS.index("air")] == 1.0  # air counts as 1, not quantity
        assert obs["equipment"][_MD_ITEMS.index("air")] == 1
        assert obs["life_stats"].tolist() == [20.0, 20.0, 300.0]
        # equip/destroy slot masks are scattered to item ids
        assert obs["mask_equip_place"][_MD_ITEMS.index("stone")]
        assert obs["mask_destroy"][_MD_ITEMS.index("crafting_table")]
        assert obs["mask_action_type"].shape == (19,)
        assert info["location_stats"]["y"] == 64.0

    def test_action_conversion_attack_and_craft(self, monkeypatch):
        env, sim = _make_minedojo(monkeypatch)
        env.reset()
        env.step(np.array([14, 0, 0]))  # attack
        assert sim.received[-1][5] == 3
        env.step(np.array([15, 2, 0]))  # craft, arg=2
        assert sim.received[-1][5] == 4 and sim.received[-1][6] == 2
        env.step(np.array([1, 2, 0]))  # forward: craft arg must be zeroed
        assert sim.received[-1][6] == 0 and sim.received[-1][0] == 1

    def test_sticky_attack(self, monkeypatch):
        env, sim = _make_minedojo(monkeypatch, break_speed_multiplier=1, sticky_attack=3)
        env.reset()
        env.step(np.array([14, 0, 0]))
        env.step(np.array([0, 0, 0]))  # no-op keeps attacking while sticky
        assert sim.received[-1][5] == 3
        env.step(np.array([12, 0, 0]))  # another functional action clears the counter
        assert sim.received[-1][5] == 1
        env.step(np.array([0, 0, 0]))
        assert sim.received[-1][5] == 0

    def test_sticky_jump_keeps_moving_forward(self, monkeypatch):
        env, sim = _make_minedojo(monkeypatch, break_speed_multiplier=1, sticky_jump=2)
        env.reset()
        env.step(np.array([5, 0, 0]))  # jump+forward
        env.step(np.array([0, 0, 0]))
        assert sim.received[-1][2] == 1 and sim.received[-1][0] == 1
        env.step(np.array([0, 0, 0]))
        assert sim.received[-1][2] == 0

    def test_equip_uses_inventory_slot(self, monkeypatch):
        env, sim = _make_minedojo(monkeypatch)
        env.reset()
        env.step(np.array([16, 0, _MD_ITEMS.index("stone")]))  # equip stone
        assert sim.received[-1][5] == 5
        assert sim.received[-1][7] == 1  # stone sits in raw slot 1

    def test_pitch_limit_clamps_camera(self, monkeypatch):
        env, sim = _make_minedojo(monkeypatch, pitch=60.0, pitch_limits=(-60, 60))
        env.reset()
        env.step(np.array([9, 0, 0]))  # pitch up would exceed +60
        assert sim.received[-1][3] == 12
        env.step(np.array([8, 0, 0]))  # pitch down is allowed
        assert sim.received[-1][3] == 11

    def test_task_table_restored_after_make(self, monkeypatch):
        sims = []
        fakes = _fake_minedojo_tree(sims)
        module = _load_backend(monkeypatch, fakes, ["_IS_MINEDOJO_AVAILABLE"], "sheeprl_tpu.envs.minedojo")
        module.MineDojoWrapper(id="harvest_milk")
        # the wrapper restores a (deep)copy so repeated construction still works
        assert set(fakes["minedojo.tasks"].ALL_TASKS_SPECS) == {"harvest"}


# ---------------------------------------------------------------------------
# MineRL (wrapper + custom spec tables)
# ---------------------------------------------------------------------------


class _HeroEnum:
    def __init__(self, values):
        self.values = np.array(values)


class _Handler:
    def __init__(self, kind, *args, **kwargs):
        self.kind, self.args, self.kwargs = kind, args, kwargs


class _FakeDictSpace:
    def __init__(self, entries):
        self._entries = dict(entries)

    def __iter__(self):
        return iter(self._entries)

    def __getitem__(self, key):
        return self._entries[key]

    @property
    def spaces(self):
        return self._entries


class _FakeInventorySpace:
    def __init__(self, items):
        self._items = list(items)

    def __iter__(self):
        return iter(self._items)


_ACTION_HANDLER_NAMES = {
    "PlaceBlock": "place",
    "EquipAction": "equip",
    "CraftAction": "craft",
    "CraftNearbyAction": "nearbyCraft",
    "SmeltItemNearby": "nearbySmelt",
}


class _FakeMineRLEnv:
    """Assembles spaces from the spec's handler tables, like the real backend."""

    def __init__(self, spec):
        self.spec = spec
        self.received = []
        obs_handlers = spec.create_observables()
        act_handlers = spec.create_actionables()
        self.rewards = spec.create_rewardables()
        self.agent_start = spec.create_agent_start()
        self.quit_handlers = spec.create_agent_handlers()

        obs_entries = {"pov": object()}
        self._inventory_items = []
        self._equip_items = []
        for h in obs_handlers:
            if h.kind == "CompassObservation":
                obs_entries["compass"] = object()
            elif h.kind == "FlatInventoryObservation":
                self._inventory_items = list(h.args[0])
                obs_entries["inventory"] = _FakeInventorySpace(self._inventory_items)
            elif h.kind == "EquippedItemObservation":
                self._equip_items = list(h.kwargs["items"])
                obs_entries["equipped_items"] = {"mainhand": {"type": _HeroEnum(self._equip_items)}}
        self.observation_space = _FakeDictSpace(obs_entries)

        act_entries = {}
        for h in act_handlers:
            if h.kind == "KeybasedCommandAction":
                act_entries[h.args[0]] = object()
            elif h.kind == "CameraAction":
                act_entries["camera"] = object()
            elif h.kind in _ACTION_HANDLER_NAMES:
                act_entries[_ACTION_HANDLER_NAMES[h.kind]] = _HeroEnum(h.args[0])
        self.action_space = _FakeDictSpace(act_entries)

    def _obs(self):
        obs = {
            "pov": np.zeros((64, 64, 3), np.uint8),
            "life_stats": {"life": 20.0, "food": 20.0, "air": 300.0},
            "inventory": {item: (2.0 if item == "dirt" else 0.0) for item in self._inventory_items},
        }
        if "equipped_items" in self.observation_space.spaces:
            obs["equipped_items"] = {"mainhand": {"type": "air"}}
        if "compass" in self.observation_space.spaces:
            obs["compass"] = {"angle": np.array([12.0])}
        return obs

    def reset(self):
        return self._obs()

    def step(self, action):
        self.received.append(action)
        return self._obs(), 0.0, False, {}

    def render(self, mode="rgb_array"):
        return np.zeros((64, 64, 3), np.uint8)

    def close(self):
        pass


def _fake_minerl_tree():
    minerl = types.ModuleType("minerl")
    herobraine = types.ModuleType("minerl.herobraine")
    env_spec = types.ModuleType("minerl.herobraine.env_spec")
    hero = types.ModuleType("minerl.herobraine.hero")
    hero_spaces = types.ModuleType("minerl.herobraine.hero.spaces")
    handler_mod = types.ModuleType("minerl.herobraine.hero.handler")
    handlers_mod = types.ModuleType("minerl.herobraine.hero.handlers")
    mc = types.ModuleType("minerl.herobraine.hero.mc")

    class FakeEnvSpec:
        def __init__(self, name, max_episode_steps=None, **kwargs):
            self.name = name
            self.max_episode_steps = max_episode_steps

        def make(self):
            return _FakeMineRLEnv(self)

    env_spec.EnvSpec = FakeEnvSpec
    hero_spaces.Enum = _HeroEnum
    handler_mod.Handler = object

    def _handler_getattr(kind):
        def factory(*args, **kwargs):
            return _Handler(kind, *args, **kwargs)

        return factory

    handlers_mod.__getattr__ = lambda kind: _handler_getattr(kind)
    keyboard = ["forward", "back", "left", "right", "jump", "sneak", "sprint", "attack"]
    mc.INVERSE_KEYMAP = {k: str(i) for i, k in enumerate(keyboard + ["use", "drop"])}
    mc.ALL_ITEMS = ["air", "compass", "dirt", "coal", "torch", "log", "planks", "stick", "crafting_table",
                    "wooden_axe", "wooden_pickaxe", "stone", "cobblestone", "furnace", "stone_axe",
                    "stone_pickaxe", "iron_ore", "iron_ingot", "iron_axe", "iron_pickaxe", "diamond"]

    minerl.herobraine = herobraine
    herobraine.env_spec = env_spec
    herobraine.hero = hero
    hero.spaces = hero_spaces
    hero.handler = handler_mod
    hero.handlers = handlers_mod
    hero.mc = mc
    return {
        "minerl": minerl,
        "minerl.herobraine": herobraine,
        "minerl.herobraine.env_spec": env_spec,
        "minerl.herobraine.hero": hero,
        "minerl.herobraine.hero.spaces": hero_spaces,
        "minerl.herobraine.hero.handler": handler_mod,
        "minerl.herobraine.hero.handlers": handlers_mod,
        "minerl.herobraine.hero.mc": mc,
    }


def _make_minerl(monkeypatch, id="custom_obtain_diamond", **kwargs):
    module = _load_backend(monkeypatch, _fake_minerl_tree(), ["_IS_MINERL_AVAILABLE"], "sheeprl_tpu.envs.minerl")
    return module.MineRLWrapper(id=id, **kwargs)


@pytest.mark.usefixtures("evict_backend_modules")
class TestMineRLMocked:
    def test_obtain_diamond_action_catalogue(self, monkeypatch):
        env = _make_minerl(monkeypatch)
        # no-op + 8 keyboard + 4 camera + 6 place + 7 equip + 4 craft
        # + 7 nearbyCraft + 2 smelt, from the spec tables
        assert env.action_space.n == 39
        assert env.actions_map[0] == {}
        jump = [a for a in env.actions_map.values() if "jump" in a]
        assert jump and all(a.get("forward") == 1 for a in jump)
        cameras = [a for a in env.actions_map.values() if "camera" in a]
        assert len(cameras) == 4
        crafts = sorted(a["craft"] for a in env.actions_map.values() if "craft" in a)
        assert crafts == ["crafting_table", "planks", "stick", "torch"]

    def test_obs_conversion_multihot(self, monkeypatch):
        env = _make_minerl(monkeypatch)
        obs, _ = env.reset()
        assert set(obs) == {"rgb", "life_stats", "inventory", "max_inventory", "equipment"}
        assert obs["inventory"].shape == (env.inventory_size,)
        assert obs["inventory"][env.inventory_item_to_id["dirt"]] == 2.0
        assert obs["equipment"][env.equip_item_to_id["air"]] == 1
        assert obs["life_stats"].tolist() == [20.0, 20.0, 300.0]

    def test_obs_conversion_compact_inventory(self, monkeypatch):
        env = _make_minerl(monkeypatch, multihot_inventory=False)
        assert env.inventory_size == 18  # the obtain spec's inventory table
        obs, _ = env.reset()
        assert obs["inventory"].shape == (18,)

    def test_navigate_has_compass_and_no_equipment(self, monkeypatch):
        env = _make_minerl(monkeypatch, id="custom_navigate", extreme=False)
        obs, _ = env.reset()
        assert "compass" in obs and obs["compass"].shape == (1,)
        assert "equipment" not in obs
        # navigate's catalogue: no-op + 8 keyboard + 4 camera + 1 place(dirt)
        assert env.action_space.n == 14

    def test_sticky_attack_and_jump(self, monkeypatch):
        env = _make_minerl(monkeypatch, break_speed_multiplier=1, sticky_attack=2, sticky_jump=2)
        env.reset()
        attack_idx = next(i for i, a in env.actions_map.items() if a == {"attack": 1})
        env.step(attack_idx)
        env.step(0)
        assert env._env.received[-1]["attack"] == 1  # sticky keeps attacking
        env.step(0)
        env.step(0)
        assert env._env.received[-1]["attack"] == 0  # counter expired

    def test_pitch_limit_zeroes_camera(self, monkeypatch):
        env = _make_minerl(monkeypatch, pitch_limits=(-30, 30))
        env.reset()
        pitch_down = next(
            i for i, a in env.actions_map.items() if "camera" in a and np.array_equal(a["camera"], [-15, 0])
        )
        env.step(pitch_down)
        env.step(pitch_down)
        assert np.array_equal(env._env.received[-1]["camera"], [-15, 0])
        env.step(pitch_down)  # would cross -30
        assert np.array_equal(env._env.received[-1]["camera"], [0, 0])

    def test_navigate_success_thresholds(self, monkeypatch):
        _load_backend(monkeypatch, _fake_minerl_tree(), ["_IS_MINERL_AVAILABLE"], "sheeprl_tpu.envs.minerl")
        specs = importlib.import_module("sheeprl_tpu.envs.minerl_envs.specs")
        nav = specs.CustomNavigate(dense=False)
        assert nav.determine_success_from_rewards([100.0])
        assert not nav.determine_success_from_rewards([50.0])
        dense = specs.CustomNavigate(dense=True)
        assert not dense.determine_success_from_rewards([100.0])  # dense bar is 160
        sys.modules.pop("sheeprl_tpu.envs.minerl_envs.specs", None)


# ---------------------------------------------------------------------------
# DIAMBRA
# ---------------------------------------------------------------------------


class _ArenaSettings(dict):
    def __init__(self, **kwargs):
        super().__init__(**{k: v for k, v in kwargs.items() if v is not None})

    def __setattr__(self, key, value):
        self[key] = value

    def __getattr__(self, key):
        try:
            return self[key]
        except KeyError:
            raise AttributeError(key)


class _FakeArenaEnv(gym.Env):
    def __init__(self):
        self.observation_space = gym.spaces.Dict(
            {
                "frame": gym.spaces.Box(0, 255, (64, 64, 1), np.uint8),
                "stage": gym.spaces.Discrete(4),
                "side": gym.spaces.MultiDiscrete([2, 2]),
                "health": gym.spaces.Box(0.0, 1.0, (1,), np.float32),
            }
        )
        self.action_space = gym.spaces.Discrete(8)
        self.received = []

    def _obs(self):
        return {
            "frame": np.zeros((64, 64, 1), np.uint8),
            "stage": 2,
            "side": np.array([0, 1]),
            "health": np.array([0.5], np.float32),
        }

    def reset(self, *, seed=None, options=None):
        return self._obs(), {}

    def step(self, action):
        self.received.append(action)
        return self._obs(), 1.0, False, False, {"env_done": False}

    def render(self):
        return np.zeros((64, 64, 3), np.uint8)


def _fake_diambra_tree(made):
    diambra = types.ModuleType("diambra")
    arena = types.ModuleType("diambra.arena")
    arena.EnvironmentSettings = _ArenaSettings
    arena.WrappersSettings = _ArenaSettings
    arena.SpaceTypes = SimpleNamespace(DISCRETE="discrete", MULTI_DISCRETE="multi_discrete")
    arena.Roles = SimpleNamespace(P1="P1", P2="P2")

    def make(id, settings, wrappers, rank=0, render_mode="rgb_array", log_level=0):
        env = _FakeArenaEnv()
        made.append((env, settings, wrappers))
        return env

    arena.make = make
    diambra.arena = arena
    return {"diambra": diambra, "diambra.arena": arena}


def _make_diambra(monkeypatch, **kwargs):
    made = []
    module = _load_backend(
        monkeypatch,
        _fake_diambra_tree(made),
        ["_IS_DIAMBRA_AVAILABLE", "_IS_DIAMBRA_ARENA_AVAILABLE"],
        "sheeprl_tpu.envs.diambra",
    )
    env = module.DiambraWrapper(id="doapp", **kwargs)
    return env, made[0]


@pytest.mark.usefixtures("evict_backend_modules")
class TestDiambraMocked:
    def test_scalar_keys_become_int32_boxes(self, monkeypatch):
        env, _ = _make_diambra(monkeypatch)
        assert isinstance(env.observation_space["stage"], gym.spaces.Box)
        assert env.observation_space["stage"].shape == (1,)
        assert env.observation_space["side"].shape == (2,)
        obs, info = env.reset()
        assert obs["stage"].shape == (1,) and obs["stage"][0] == 2
        assert obs["side"].tolist() == [0, 1]
        assert info["env_domain"] == "DIAMBRA"

    def test_discrete_action_unboxed_for_the_sdk(self, monkeypatch):
        env, (inner, _, _) = _make_diambra(monkeypatch)
        env.reset()
        obs, reward, done, truncated, info = env.step(np.array([3]))
        assert inner.received[-1] == 3 and not isinstance(inner.received[-1], np.ndarray)
        assert info["env_domain"] == "DIAMBRA"

    def test_performance_mode_sets_settings_frame_shape(self, monkeypatch):
        _, (_, settings, _) = _make_diambra(monkeypatch, grayscale=True, increase_performance=True)
        assert settings["frame_shape"] == (64, 64, 1)
        _, (_, _, wrappers) = _make_diambra(monkeypatch, grayscale=False, increase_performance=False)
        assert wrappers["frame_shape"] == (64, 64, 0)

    def test_repeat_action_forces_step_ratio(self, monkeypatch):
        with pytest.warns(UserWarning, match="step_ratio"):
            _, (_, settings, wrappers) = _make_diambra(monkeypatch, repeat_action=4)
        assert settings["step_ratio"] == 1
        assert wrappers["repeat_action"] == 4

    def test_invalid_action_space_rejected(self, monkeypatch):
        with pytest.raises(ValueError, match="action_space"):
            _make_diambra(monkeypatch, action_space="CONTINUOUS")


# ---------------------------------------------------------------------------
# Super Mario Bros
# ---------------------------------------------------------------------------


class _FakeNesEnv:
    def __init__(self):
        self.observation_space = SimpleNamespace(low=0, high=255, shape=(240, 256, 3), dtype=np.uint8)
        self.received = []
        self.time_up = False

    def reset(self):
        return np.zeros((240, 256, 3), np.uint8)

    def step(self, action):
        self.received.append(action)
        done = self.time_up
        return np.zeros((240, 256, 3), np.uint8), 1.0, done, {"time": 1 if self.time_up else 0}

    def render(self, mode="rgb_array"):
        return np.zeros((240, 256, 3), np.uint8)

    def close(self):
        pass


class _FakeJoypadSpace:
    def __init__(self, env, moves):
        self._env = env
        self.moves = moves
        self.observation_space = env.observation_space
        self.action_space = SimpleNamespace(n=len(moves))

    def __getattr__(self, name):
        return getattr(self._env, name)


def _fake_mario_tree(made):
    gsmb = types.ModuleType("gym_super_mario_bros")
    actions = types.ModuleType("gym_super_mario_bros.actions")
    nes_py = types.ModuleType("nes_py")
    nes_wrappers = types.ModuleType("nes_py.wrappers")
    actions.SIMPLE_MOVEMENT = [["NOOP"], ["right"], ["right", "A"], ["right", "B"], ["right", "A", "B"], ["A"], ["left"]]
    actions.RIGHT_ONLY = [["NOOP"], ["right"], ["right", "A"], ["right", "B"], ["right", "A", "B"]]
    actions.COMPLEX_MOVEMENT = actions.SIMPLE_MOVEMENT + [["left", "A"], ["left", "B"], ["left", "A", "B"], ["down"], ["up"]]

    def make(id):
        env = _FakeNesEnv()
        made.append(env)
        return env

    gsmb.make = make
    gsmb.actions = actions
    nes_wrappers.JoypadSpace = _FakeJoypadSpace
    nes_py.wrappers = nes_wrappers
    return {
        "gym_super_mario_bros": gsmb,
        "gym_super_mario_bros.actions": actions,
        "nes_py": nes_py,
        "nes_py.wrappers": nes_wrappers,
    }


def _make_mario(monkeypatch, **kwargs):
    made = []
    module = _load_backend(
        monkeypatch, _fake_mario_tree(made), ["_IS_SUPER_MARIO_BROS_AVAILABLE"], "sheeprl_tpu.envs.super_mario_bros"
    )
    env = module.SuperMarioBrosWrapper(id="SuperMarioBros-1-1-v0", **kwargs)
    return env, made[0]


@pytest.mark.usefixtures("evict_backend_modules")
class TestMarioMocked:
    def test_rgb_dict_obs_and_action_space(self, monkeypatch):
        env, _ = _make_mario(monkeypatch)
        assert env.action_space.n == 7  # simple movement
        assert env.observation_space["rgb"].shape == (240, 256, 3)
        obs, _ = env.reset()
        assert obs["rgb"].shape == (240, 256, 3) and obs["rgb"].dtype == np.uint8

    def test_action_space_presets(self, monkeypatch):
        env, _ = _make_mario(monkeypatch, action_space="right_only")
        assert env.action_space.n == 5
        env, _ = _make_mario(monkeypatch, action_space="complex")
        assert env.action_space.n == 12

    def test_numpy_action_unboxed(self, monkeypatch):
        env, inner = _make_mario(monkeypatch)
        env.reset()
        env.step(np.array([2]))
        assert inner.received[-1] == 2 and not isinstance(inner.received[-1], np.ndarray)

    def test_time_up_reports_truncation(self, monkeypatch):
        env, inner = _make_mario(monkeypatch)
        env.reset()
        _, _, terminated, truncated, _ = env.step(1)
        assert not terminated and not truncated
        inner.time_up = True
        _, _, terminated, truncated, _ = env.step(1)
        assert truncated and not terminated
