"""Env backend tests. Only backends whose optional dependency is installed
run; the rest are skipped (mirroring the reference's extras-gated suite)."""

import numpy as np
import pytest

from sheeprl_tpu.utils.imports import (
    _IS_ATARI_AVAILABLE,
    _IS_CRAFTER_AVAILABLE,
    _IS_DMC_AVAILABLE,
)

dmc = pytest.importorskip("sheeprl_tpu.envs.dmc") if _IS_DMC_AVAILABLE else None


def _dmc_can_render() -> bool:
    """True iff this host can actually rasterize mujoco pixels headlessly.

    dm_control being installed does not imply a working GL stack: a container
    with neither libEGL nor libOSMesa nor an X display can run DMC physics
    (state observations) but every ``physics.render`` call raises. Probing
    once here lets the state-only tests run everywhere while the pixel tests
    skip with an accurate reason instead of failing on an environment gap.
    """
    if not _IS_DMC_AVAILABLE:
        return False
    try:
        from dm_control import suite

        env = suite.load("cartpole", "balance")
        env.reset()
        env.physics.render(8, 8, camera_id=0)
        return True
    except Exception:
        return False


_DMC_RENDER_OK = _dmc_can_render()
_NO_RENDER_REASON = (
    "dm_control is importable but no headless GL backend (EGL/OSMesa/X) exists on this host, "
    "so mujoco pixel rendering is unavailable; state-only DMC coverage still runs"
)


@pytest.mark.skipif(not _IS_DMC_AVAILABLE, reason="dm_control not installed")
class TestDMC:
    def test_state_only(self):
        env = dmc.DMCWrapper("cartpole", "balance", from_pixels=False, from_vectors=True, seed=0)
        obs, _ = env.reset()
        assert set(obs.keys()) == {"state"}
        assert env.action_space.low.min() == -1.0 and env.action_space.high.max() == 1.0
        obs, reward, terminated, truncated, info = env.step(env.action_space.sample())
        assert obs["state"].shape == env.observation_space["state"].shape
        assert "discount" in info
        env.close()

    @pytest.mark.skipif(not _DMC_RENDER_OK, reason=_NO_RENDER_REASON)
    def test_pixels_channel_last(self):
        env = dmc.DMCWrapper(
            "cartpole", "balance", from_pixels=True, from_vectors=True, height=32, width=32, seed=0
        )
        obs, _ = env.reset()
        assert obs["rgb"].shape == (32, 32, 3) and obs["rgb"].dtype == np.uint8
        env.close()

    def test_action_denormalization(self):
        env = dmc.DMCWrapper("cartpole", "balance", from_pixels=False, seed=0)
        a = env._denormalize_action(np.ones(env.action_space.shape, np.float32))
        assert np.allclose(a, env._true_action_space.high)
        a = env._denormalize_action(-np.ones(env.action_space.shape, np.float32))
        assert np.allclose(a, env._true_action_space.low)
        env.close()

    @pytest.mark.skipif(not _DMC_RENDER_OK, reason=_NO_RENDER_REASON)
    def test_through_factory(self, tmp_path):
        """North-star config path: env=dmc through make_env (resize +
        channel-last pixel transform + dict obs)."""
        from sheeprl_tpu.config import compose
        from sheeprl_tpu.envs.factory import make_env

        cfg = compose(
            [
                "exp=dreamer_v3",
                "env=dmc",
                "env.capture_video=False",
                "env.wrapper.domain_name=cartpole",
                "env.wrapper.task_name=balance",
                "algo.cnn_keys.encoder=[rgb]",
                "algo.mlp_keys.encoder=[state]",
                "env.screen_size=64",
                f"log_root={tmp_path}",
            ]
        )
        env = make_env(cfg, 0, 0, None)()
        obs, _ = env.reset()
        assert obs["rgb"].shape == (64, 64, 3)
        assert obs["state"].dtype == np.float32
        env.step(env.action_space.sample())
        env.close()


@pytest.mark.skipif(not _IS_CRAFTER_AVAILABLE, reason="crafter not installed")
def test_crafter_wrapper():
    from sheeprl_tpu.envs.crafter import CrafterWrapper

    env = CrafterWrapper("crafter_reward", 64, seed=0)
    obs, _ = env.reset()
    assert obs["rgb"].shape == (64, 64, 3)
    env.step(env.action_space.sample())
    env.close()


@pytest.mark.skipif(not _IS_ATARI_AVAILABLE, reason="ale_py not installed")
def test_atari_through_factory(tmp_path):
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.envs.factory import make_env

    cfg = compose(
        [
            "exp=dreamer_v3",
            "env=atari",
            "env.capture_video=False",
            "env.id=MsPacmanNoFrameskip-v4",
            "algo.cnn_keys.encoder=[rgb]",
            f"log_root={tmp_path}",
        ]
    )
    env = make_env(cfg, 0, 0, None)()
    obs, _ = env.reset()
    assert obs["rgb"].shape[-1] in (1, 3)
    env.close()


def test_unavailable_backend_raises():
    """Guarded imports raise a clear ModuleNotFoundError when the optional
    dependency is missing (reference: each backend's import guard)."""
    from sheeprl_tpu.utils import imports as imp

    missing = [
        (imp._IS_CRAFTER_AVAILABLE, "sheeprl_tpu.envs.crafter"),
        (imp._IS_DIAMBRA_AVAILABLE, "sheeprl_tpu.envs.diambra"),
        (imp._IS_MINEDOJO_AVAILABLE, "sheeprl_tpu.envs.minedojo"),
        (imp._IS_MINERL_AVAILABLE, "sheeprl_tpu.envs.minerl"),
        (imp._IS_SUPER_MARIO_BROS_AVAILABLE, "sheeprl_tpu.envs.super_mario_bros"),
    ]
    import importlib

    for available, module in missing:
        if not available:
            with pytest.raises(ModuleNotFoundError):
                importlib.import_module(module)
