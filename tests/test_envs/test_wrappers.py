"""Wrapper behavior tests (reference analogue: ``tests/test_envs/``)."""

import gymnasium as gym
import numpy as np
import pytest

from sheeprl_tpu.envs.dummy import ContinuousDummyEnv, DiscreteDummyEnv, MultiDiscreteDummyEnv
from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    ActionsAsObservationWrapper,
    DilatedDeque,
    FrameStack,
    MaskVelocityWrapper,
    RestartOnException,
    RewardAsObservationWrapper,
)


def test_dilated_deque_snapshot_strides():
    dq = DilatedDeque(size=2, dilation=2)
    for i in range(4):
        dq.push(np.array([i]))
    # entries [0,1,2,3], stride-2 picks indices 1,3
    assert dq.snapshot().tolist() == [1, 3]
    dq.fill(np.array([7]))
    assert dq.snapshot().tolist() == [7, 7]


def test_frame_stack_channel_last():
    env = FrameStack(DiscreteDummyEnv(n_steps=16), num_stack=3, cnn_keys=["rgb"])
    obs, _ = env.reset()
    assert obs["rgb"].shape == (64, 64, 9)
    assert env.observation_space["rgb"].shape == (64, 64, 9)
    # reset primes the stack with copies of frame 0
    assert (obs["rgb"] == 0).all()
    obs, *_ = env.step(0)  # t becomes 1 → newest channel-block is 1
    assert (obs["rgb"][..., :3] == 0).all() and (obs["rgb"][..., 6:] == 1).all()


def test_frame_stack_dilation():
    env = FrameStack(DiscreteDummyEnv(n_steps=64), num_stack=2, cnn_keys=["rgb"], dilation=2)
    env.reset()
    for t in (1, 2, 3, 4):
        obs, *_ = env.step(0)
    # history holds [1,2,3,4]; stride-2 snapshot = frames 2 and 4
    assert (obs["rgb"][..., :3] == 2).all() and (obs["rgb"][..., 3:] == 4).all()


def test_frame_stack_requires_cnn_keys():
    with pytest.raises(RuntimeError, match="at least one valid cnn key"):
        FrameStack(DiscreteDummyEnv(), num_stack=2, cnn_keys=[])


def test_action_repeat_accumulates_and_stops_early():
    class CountingEnv(gym.Env):
        observation_space = gym.spaces.Box(-1, 1, (1,))
        action_space = gym.spaces.Discrete(2)

        def __init__(self):
            self.t = 0

        def reset(self, seed=None, options=None):
            self.t = 0
            return np.zeros(1), {}

        def step(self, action):
            self.t += 1
            return np.zeros(1), 1.0, self.t >= 5, False, {}

    env = ActionRepeat(CountingEnv(), amount=3)
    env.reset()
    assert env.action_repeat == 3
    _, reward, done, *_ = env.step(0)
    assert reward == 3.0 and not done
    env.step(0)  # t: 4,5 → terminates after 2 inner steps
    assert env.env.t == 5


def test_action_repeat_rejects_nonpositive():
    with pytest.raises(ValueError):
        ActionRepeat(DiscreteDummyEnv(), amount=0)


@pytest.mark.parametrize(
    "env_fn, noop, expected_dim",
    [
        (lambda: DiscreteDummyEnv(action_dim=3), 0, 3),
        (lambda: MultiDiscreteDummyEnv(action_dims=[2, 3]), [0, 0], 5),
        (lambda: ContinuousDummyEnv(action_dim=2), 0.0, 2),
    ],
)
def test_actions_as_observation_spaces(env_fn, noop, expected_dim):
    env = ActionsAsObservationWrapper(env_fn(), num_stack=4, noop=noop)
    obs, _ = env.reset()
    assert obs["action_stack"].shape == (expected_dim * 4,)
    assert env.observation_space["action_stack"].shape == (expected_dim * 4,)
    action = env.action_space.sample()
    obs, *_ = env.step(action)
    assert obs["action_stack"].shape == (expected_dim * 4,)


def test_actions_as_observation_one_hot_content():
    env = ActionsAsObservationWrapper(DiscreteDummyEnv(action_dim=3), num_stack=2, noop=1)
    obs, _ = env.reset()
    # noop = action 1 → [0,1,0] twice
    assert obs["action_stack"].tolist() == [0, 1, 0, 0, 1, 0]
    obs, *_ = env.step(2)
    assert obs["action_stack"].tolist() == [0, 1, 0, 0, 0, 1]


def test_actions_as_observation_noop_validation():
    with pytest.raises(ValueError):
        ActionsAsObservationWrapper(DiscreteDummyEnv(), num_stack=2, noop=[0])
    with pytest.raises(ValueError):
        ActionsAsObservationWrapper(ContinuousDummyEnv(), num_stack=2, noop=[0.0])
    with pytest.raises(RuntimeError):
        ActionsAsObservationWrapper(MultiDiscreteDummyEnv(action_dims=[2, 2]), num_stack=2, noop=[0])
    with pytest.raises(ValueError):
        ActionsAsObservationWrapper(DiscreteDummyEnv(), num_stack=0, noop=0)


def test_reward_as_observation_dict_and_flat():
    env = RewardAsObservationWrapper(DiscreteDummyEnv())
    obs, _ = env.reset()
    assert obs["reward"].tolist() == [0.0]
    obs, *_ = env.step(0)
    assert "reward" in obs and obs["reward"].shape == (1,)

    flat = RewardAsObservationWrapper(gym.make("CartPole-v1"))
    obs, _ = flat.reset()
    assert set(obs.keys()) == {"obs", "reward"}


def test_mask_velocity():
    env = MaskVelocityWrapper(gym.make("CartPole-v1"))
    obs, _ = env.reset(seed=0)
    assert obs[1] == 0.0 and obs[3] == 0.0

    class NoSpec(gym.Env):
        observation_space = gym.spaces.Box(-1, 1, (4,))
        action_space = gym.spaces.Discrete(2)

    with pytest.raises(NotImplementedError):
        MaskVelocityWrapper(NoSpec())


def test_restart_on_exception_recovers_and_flags():
    class Flaky(gym.Env):
        observation_space = gym.spaces.Box(-1, 1, (1,))
        action_space = gym.spaces.Discrete(2)
        crashes = 0

        def reset(self, seed=None, options=None):
            return np.zeros(1), {}

        def step(self, action):
            Flaky.crashes += 1
            if Flaky.crashes == 1:
                raise RuntimeError("boom")
            return np.zeros(1), 1.0, False, False, {}

    env = RestartOnException(lambda: Flaky(), wait=0.0, maxfails=3)
    env.reset()
    obs, reward, done, truncated, info = env.step(0)
    assert info.get("restart_on_exception") is True
    assert reward == 0.0 and not done and not truncated
    # subsequent steps hit the healthy path
    _, reward, _, _, info = env.step(0)
    assert reward == 1.0 and "restart_on_exception" not in info


def test_restart_on_exception_gives_up():
    def make():
        class AlwaysCrash(gym.Env):
            observation_space = gym.spaces.Box(-1, 1, (1,))
            action_space = gym.spaces.Discrete(2)

            def reset(self, seed=None, options=None):
                return np.zeros(1), {}

            def step(self, action):
                raise RuntimeError("boom")

        return AlwaysCrash()

    env = RestartOnException(make, wait=0.0, maxfails=1)
    env.reset()
    env.step(0)  # first crash tolerated
    with pytest.raises(RuntimeError, match="crashed too many times"):
        env.step(0)
