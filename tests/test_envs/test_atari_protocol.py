"""AtariProtocolDummyEnv: the deterministic ALE-protocol stand-in used by
the Dreamer benchmarks (frame-skip + 2-frame max-pool, 3-lives game-over
episodes, noop starts, scripted rewards). These tests pin the protocol
surface so the bench env cannot silently drift from Atari's dynamics."""

import numpy as np
import pytest

from sheeprl_tpu.envs.dummy import AtariProtocolDummyEnv


def _rollout(env, actions, seed=3):
    obs, info = env.reset(seed=seed)
    frames, rewards, lives = [obs["rgb"]], [], [info["lives"]]
    terminated = False
    for a in actions:
        obs, r, terminated, truncated, info = env.step(a)
        frames.append(obs["rgb"])
        rewards.append(r)
        lives.append(info["lives"])
        if terminated:
            break
    return frames, rewards, lives, terminated


def test_protocol_surface():
    env = AtariProtocolDummyEnv(screen_size=64, frame_skip=4)
    assert env.action_space.n == 18
    assert env.frame_skip == 4
    obs, info = env.reset(seed=0)
    assert obs["rgb"].shape == (64, 64, 3) and obs["rgb"].dtype == np.uint8
    assert info["lives"] == 3
    obs, r, term, trunc, info = env.step(5)
    assert obs["rgb"].shape == (64, 64, 3)
    assert isinstance(r, float) and not trunc


def test_grayscale_channel():
    env = AtariProtocolDummyEnv(screen_size=64, grayscale=True)
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (64, 64, 1)


def test_deterministic_given_seed_and_actions():
    actions = [int(a) for a in np.random.default_rng(0).integers(0, 18, 200)]
    f1, r1, l1, t1 = _rollout(AtariProtocolDummyEnv(), actions)
    f2, r2, l2, t2 = _rollout(AtariProtocolDummyEnv(), actions)
    assert r1 == r2 and l1 == l2 and t1 == t2
    np.testing.assert_array_equal(f1[-1], f2[-1])


def test_actions_change_observations_and_rewards():
    a_seq = [3] * 50
    b_seq = [11] * 50
    fa, ra, _, _ = _rollout(AtariProtocolDummyEnv(), a_seq)
    fb, rb, _, _ = _rollout(AtariProtocolDummyEnv(), b_seq)
    assert not np.array_equal(fa[10], fb[10])
    assert ra != rb  # the scripted schedule is action-coupled


def test_life_loss_structure_then_game_over():
    env = AtariProtocolDummyEnv(life_len=40, frame_skip=4)
    _, _, lives, terminated = _rollout(env, [0] * 200)
    assert terminated
    # lives only ever decrease, hitting 0 exactly at termination
    assert lives[0] == 3 and lives[-1] == 0
    assert all(b <= a for a, b in zip(lives, lives[1:]))
    # life losses are spread across the episode, not front-loaded
    assert lives.index(2) >= 2


def test_episode_length_varies_per_episode():
    """Noop starts + per-life jitter give Atari-like variable episode
    lengths across resets (the dynamics walker-walk benches lack)."""
    env = AtariProtocolDummyEnv(life_len=40)
    lengths = []
    for _ in range(3):
        _, rewards, _, term = _rollout(env, [2] * 300, seed=None)
        assert term
        lengths.append(len(rewards))
    assert len(set(lengths)) > 1


def test_factory_pipeline_no_double_action_repeat(tmp_path):
    """Through the real factory + atari_dummy config: the env's built-in
    frame-skip must NOT be wrapped in another ActionRepeat, and the pixel
    pipeline must deliver channel-last 64x64 uint8."""
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.envs.factory import make_env
    from sheeprl_tpu.envs.wrappers import ActionRepeat

    cfg = compose(
        [
            "exp=dreamer_v3",
            "env=atari_dummy",
            "env.capture_video=False",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.cnn_keys.decoder=[rgb]",
            "algo.mlp_keys.decoder=[]",
        ]
    )
    env = make_env(cfg, seed=7, rank=0)()
    inner = env
    while hasattr(inner, "env"):
        assert not isinstance(inner, ActionRepeat), "frame-skip applied twice"
        inner = inner.env
    obs, _ = env.reset(seed=7)
    assert obs["rgb"].shape == (64, 64, 3) and obs["rgb"].dtype == np.uint8
    obs, r, term, trunc, info = env.step(np.int64(4))
    assert obs["rgb"].shape == (64, 64, 3)
    env.close()
