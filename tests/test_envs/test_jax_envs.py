"""Pure-JAX env parity against gymnasium + auto-reset/vmap semantics.

Parity strategy: jax PRNG and numpy PRNG cannot produce the same reset
states, so the gymnasium twin is *state-synced* from the jax env at every
episode start (``env.unwrapped.state = ...``) and both are driven with the
same seeded action sequence. The jax envs compute in float32 vs gymnasium's
float64, so trace comparisons carry a small per-episode drift tolerance;
single-step checks (re-synced every step) are tight.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.envs.jax_envs import (
    JAX_ENV_REGISTRY,
    BatchedJaxEnv,
    JaxAcrobot,
    JaxCartPole,
    JaxMountainCar,
    JaxPendulum,
    is_jax_env,
    make_jax_env,
)

TRACE_STEPS = 200


def test_registry():
    assert is_jax_env("CartPole-v1") and is_jax_env("Pendulum-v1") and is_jax_env("Acrobot-v1")
    assert is_jax_env("MountainCar-v0")
    assert not is_jax_env("MsPacmanNoFrameskip-v4")
    assert isinstance(make_jax_env("CartPole-v1"), JaxCartPole)
    assert isinstance(make_jax_env("Pendulum-v1"), JaxPendulum)
    assert isinstance(make_jax_env("Acrobot-v1"), JaxAcrobot)
    assert isinstance(make_jax_env("MountainCar-v0"), JaxMountainCar)
    with pytest.raises(ValueError, match="No pure-JAX environment"):
        make_jax_env("Walker2d-v4")


def test_register_jax_env_auto_discovery():
    """Adding an env is one ``@register_jax_env`` decorated module in the
    package: the package ``__init__`` auto-imports siblings and re-exports
    every registered class (no hand-maintained import list)."""
    import sheeprl_tpu.envs.jax_envs as pkg

    assert set(JAX_ENV_REGISTRY) >= {"CartPole-v1", "Pendulum-v1", "Acrobot-v1"}
    for cls in JAX_ENV_REGISTRY.values():
        # every registered env class is re-exported from the package
        assert getattr(pkg, cls.__name__) is cls
        assert cls.__name__ in pkg.__all__


def _sync_cartpole(genv, state):
    genv.unwrapped.state = np.asarray(state.physics, dtype=np.float64)


def _sync_pendulum(genv, state):
    genv.unwrapped.state = np.array([float(state.theta), float(state.theta_dot)], dtype=np.float64)


def test_cartpole_trace_parity():
    """Seeded 200-step trace: obs/reward/termination match gymnasium, with
    state re-sync (both PRNGs differ) at each episode start only."""
    jenv = JaxCartPole()
    genv = gym.make("CartPole-v1")
    genv.reset(seed=0)
    key = jax.random.PRNGKey(0)
    key, sub = jax.random.split(key)
    state, obs = jenv.reset(sub)
    _sync_cartpole(genv, state)
    rng = np.random.RandomState(1)
    for t in range(TRACE_STEPS):
        a = int(rng.randint(2))
        state, jobs, jr, jdone, jinfo = jenv.step(state, jnp.asarray(a))
        gobs, gr, gterm, gtrunc, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=1e-4, rtol=1e-4)
        assert float(jr) == float(gr) == 1.0
        assert bool(jinfo["terminated"]) == gterm
        assert bool(jdone) == (gterm or gtrunc)
        if jdone:
            key, sub = jax.random.split(key)
            state, obs = jenv.reset(sub)
            genv.reset()
            _sync_cartpole(genv, state)
    genv.close()


def test_cartpole_single_step_parity_tight():
    """Dynamics-exact check: re-sync every step, so no drift accumulates."""
    jenv = JaxCartPole()
    genv = gym.make("CartPole-v1")
    genv.reset(seed=0)
    state, _ = jenv.reset(jax.random.PRNGKey(7))
    rng = np.random.RandomState(2)
    for t in range(50):
        _sync_cartpole(genv, state)
        a = int(rng.randint(2))
        state, jobs, _, jdone, _ = jenv.step(state, jnp.asarray(a))
        gobs, _, gterm, _, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=1e-5, rtol=1e-5)
        if jdone:
            state, _ = jenv.reset(jax.random.PRNGKey(100 + t))
            genv.reset()
    genv.close()


def test_pendulum_trace_parity():
    """200-step trace = exactly one episode (no termination, truncated at
    200). float32-vs-float64 drift bounds the tolerance."""
    jenv = JaxPendulum()
    genv = gym.make("Pendulum-v1")
    genv.reset(seed=0)
    state, obs = jenv.reset(jax.random.PRNGKey(3))
    _sync_pendulum(genv, state)
    rng = np.random.RandomState(3)
    for t in range(TRACE_STEPS):
        a = rng.uniform(-2, 2, size=(1,)).astype(np.float32)
        state, jobs, jr, jdone, jinfo = jenv.step(state, jnp.asarray(a))
        gobs, gr, gterm, gtrunc, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=2e-2, rtol=2e-2)
        np.testing.assert_allclose(float(jr), float(gr), atol=5e-2)
        assert not bool(jinfo["terminated"]) and not gterm
        assert bool(jdone) == (gterm or gtrunc)
        assert bool(jdone) == (t == TRACE_STEPS - 1)
    genv.close()


def test_pendulum_single_step_parity_tight():
    jenv = JaxPendulum()
    genv = gym.make("Pendulum-v1")
    genv.reset(seed=0)
    state, _ = jenv.reset(jax.random.PRNGKey(4))
    rng = np.random.RandomState(4)
    for _ in range(50):
        _sync_pendulum(genv, state)
        a = rng.uniform(-2, 2, size=(1,)).astype(np.float32)
        state, jobs, jr, _, _ = jenv.step(state, jnp.asarray(a))
        gobs, gr, _, _, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(float(jr), float(gr), atol=1e-4)
    genv.close()


def _sync_acrobot(genv, state):
    genv.unwrapped.state = np.asarray(state.physics, dtype=np.float64)


def test_acrobot_trace_parity():
    """Seeded trace: obs/reward/termination match gymnasium with state
    re-sync at episode starts only. The double pendulum is chaotic, so f32
    vs f64 drift grows exponentially along an episode — the trace is kept
    short of the horizon where roundoff noise dominates, and the tolerance
    is looser than the single-step check below."""
    jenv = JaxAcrobot()
    genv = gym.make("Acrobot-v1")
    genv.reset(seed=0)
    key = jax.random.PRNGKey(6)
    key, sub = jax.random.split(key)
    state, obs = jenv.reset(sub)
    _sync_acrobot(genv, state)
    rng = np.random.RandomState(6)
    for t in range(60):
        a = int(rng.randint(3))
        state, jobs, jr, jdone, jinfo = jenv.step(state, jnp.asarray(a))
        gobs, gr, gterm, gtrunc, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=2e-2, rtol=2e-2)
        assert float(jr) == float(gr)
        assert bool(jinfo["terminated"]) == gterm
        assert bool(jdone) == (gterm or gtrunc)
        if jdone:
            key, sub = jax.random.split(key)
            state, obs = jenv.reset(sub)
            genv.reset()
            _sync_acrobot(genv, state)
    genv.close()


def test_acrobot_single_step_parity_tight():
    """Dynamics-exact check: re-sync every step so no drift accumulates —
    one RK4 step in float32 must match gymnasium's float64 step tightly."""
    jenv = JaxAcrobot()
    genv = gym.make("Acrobot-v1")
    genv.reset(seed=0)
    state, _ = jenv.reset(jax.random.PRNGKey(8))
    rng = np.random.RandomState(8)
    for t in range(50):
        _sync_acrobot(genv, state)
        a = int(rng.randint(3))
        state, jobs, jr, jdone, _ = jenv.step(state, jnp.asarray(a))
        gobs, gr, gterm, _, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=1e-4, rtol=1e-4)
        assert float(jr) == float(gr)
        assert bool(jdone) == bool(gterm)  # no truncation inside 50 steps
        if jdone:
            state, _ = jenv.reset(jax.random.PRNGKey(200 + t))
            genv.reset()
            _sync_acrobot(genv, state)
    genv.close()


def test_acrobot_truncation_and_termination_reward():
    """-1 per step, 0 on the terminating step; the 500-step limit raises
    truncated, not terminated."""
    jenv = JaxAcrobot(max_episode_steps=5)
    state, _ = jenv.reset(jax.random.PRNGKey(0))
    for t in range(5):
        state, _, rew, done, info = jenv.step(state, jnp.asarray(1))
        if bool(info["terminated"]):
            assert float(rew) == 0.0
            pytest.skip("episode terminated before the tiny time limit")
        assert float(rew) == -1.0
        assert bool(info["truncated"]) == (t == 4)
        assert bool(done) == (t == 4)


def _sync_mountain_car(genv, state):
    genv.unwrapped.state = np.asarray(state.physics, dtype=np.float64)


def test_mountain_car_trace_parity():
    """Seeded 200-step trace (= one truncated episode under a random policy;
    the hill is essentially never escaped by chance): obs/reward/termination
    match gymnasium with state re-sync at episode starts only."""
    jenv = JaxMountainCar()
    genv = gym.make("MountainCar-v0")
    genv.reset(seed=0)
    key = jax.random.PRNGKey(9)
    key, sub = jax.random.split(key)
    state, obs = jenv.reset(sub)
    _sync_mountain_car(genv, state)
    rng = np.random.RandomState(9)
    for t in range(TRACE_STEPS):
        a = int(rng.randint(3))
        state, jobs, jr, jdone, jinfo = jenv.step(state, jnp.asarray(a))
        gobs, gr, gterm, gtrunc, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=1e-4, rtol=1e-4)
        assert float(jr) == float(gr) == -1.0
        assert bool(jinfo["terminated"]) == gterm
        assert bool(jdone) == (gterm or gtrunc)
        if jdone:
            key, sub = jax.random.split(key)
            state, obs = jenv.reset(sub)
            genv.reset()
            _sync_mountain_car(genv, state)
    genv.close()


def test_mountain_car_single_step_parity_tight():
    """Dynamics-exact check: re-sync every step so no drift accumulates —
    includes the left-wall inelastic velocity clamp and both clips."""
    jenv = JaxMountainCar()
    genv = gym.make("MountainCar-v0")
    genv.reset(seed=0)
    state, _ = jenv.reset(jax.random.PRNGKey(10))
    rng = np.random.RandomState(10)
    for t in range(50):
        _sync_mountain_car(genv, state)
        a = int(rng.randint(3))
        state, jobs, jr, jdone, _ = jenv.step(state, jnp.asarray(a))
        gobs, gr, gterm, _, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=1e-5, rtol=1e-5)
        assert float(jr) == float(gr)
        assert not bool(jdone) and not gterm  # 50 random steps never reach the goal
    genv.close()


def test_mountain_car_left_wall_clamps_velocity():
    """Hitting the left wall at speed: position clips to min_position and the
    velocity zeroes (gymnasium's inelastic collision), it does not bounce.
    The state is synthesized at the wall — a random policy essentially never
    gets there (the engine is weaker than gravity), so the trace test above
    does not exercise this branch."""
    from sheeprl_tpu.envs.jax_envs.mountain_car import MountainCarState

    jenv = JaxMountainCar()
    genv = gym.make("MountainCar-v0")
    genv.reset(seed=0)
    state = MountainCarState(
        physics=jnp.asarray([-1.15, -0.07], jnp.float32), t=jnp.zeros((), jnp.int32)
    )
    _sync_mountain_car(genv, state)
    state, jobs, _, _, _ = jenv.step(state, jnp.asarray(0))  # keep pushing left
    gobs, _, _, _, _ = genv.step(0)
    assert float(jobs[0]) == pytest.approx(jenv.min_position)
    assert float(jobs[1]) == 0.0
    np.testing.assert_allclose(np.asarray(jobs), gobs, atol=1e-6)
    genv.close()


def test_truncation_flag_cartpole():
    """A time-limited CartPole sets truncated (not terminated) at the limit,
    mirroring gymnasium's TimeLimit."""
    jenv = JaxCartPole(max_episode_steps=5)
    state, _ = jenv.reset(jax.random.PRNGKey(0))
    for t in range(5):
        state, _, _, done, info = jenv.step(state, jnp.asarray(0))
        if bool(info["terminated"]):
            pytest.skip("episode terminated before the tiny time limit")
        assert bool(info["truncated"]) == (t == 4)
        assert bool(done) == (t == 4)


def test_batched_autoreset_matches_manual_key_stream():
    """BatchedJaxEnv == a hand-rolled per-env loop with the same key
    discipline, bitwise (same ops, same dtypes), including SAME_STEP
    auto-resets: on the done step the returned obs is the NEW episode's
    first obs and info['final_obs'] is the terminal obs."""
    N = 4
    raw = JaxCartPole(max_episode_steps=20)
    benv = BatchedJaxEnv(raw, N)
    master = jax.random.PRNGKey(11)
    bstate, bobs = benv.reset(master)

    # manual replica of the wrapper's key discipline
    keys = jax.random.split(master, N)
    man_state, man_obs, man_keys = [], [], []
    for i in range(N):
        k, sub = jax.random.split(keys[i])
        s, o = raw.reset(sub)
        man_keys.append(k)
        man_state.append(s)
        man_obs.append(o)
    np.testing.assert_array_equal(np.asarray(bobs), np.stack([np.asarray(o) for o in man_obs]))

    rng = np.random.RandomState(5)
    for t in range(60):
        acts = rng.randint(2, size=(N,))
        bstate, bobs, brew, bdone, binfo = benv.step(bstate, jnp.asarray(acts))
        for i in range(N):
            s2, o2, r2, d2, info2 = raw.step(man_state[i], jnp.asarray(acts[i]))
            assert float(brew[i]) == float(r2)
            assert bool(bdone[i]) == bool(d2)
            # terminal obs rides in final_obs on the done step
            np.testing.assert_array_equal(np.asarray(binfo["final_obs"][i]), np.asarray(o2))
            assert bool(binfo["terminated"][i]) == bool(info2["terminated"])
            assert bool(binfo["truncated"][i]) == bool(info2["truncated"])
            if bool(d2):
                k2, sub = jax.random.split(man_keys[i])
                man_state[i], o_reset = raw.reset(sub)
                man_keys[i] = k2
                np.testing.assert_array_equal(np.asarray(bobs[i]), np.asarray(o_reset))
            else:
                man_state[i] = s2
                np.testing.assert_array_equal(np.asarray(bobs[i]), np.asarray(o2))


def test_batched_shapes_and_spaces():
    for env_id, n in [("CartPole-v1", 3), ("Pendulum-v1", 2), ("Acrobot-v1", 2), ("MountainCar-v0", 2)]:
        raw = make_jax_env(env_id)
        benv = BatchedJaxEnv(raw, n)
        assert benv.single_observation_space == raw.observation_space
        assert benv.single_action_space == raw.action_space
        state, obs = jax.jit(benv.reset)(jax.random.PRNGKey(0))
        assert obs.shape == (n, *raw.observation_space.shape)
        if isinstance(raw.action_space, gym.spaces.Box):
            acts = jnp.zeros((n, *raw.action_space.shape), jnp.float32)
        else:
            acts = jnp.zeros((n,), jnp.int32)
        state, obs, rew, done, info = jax.jit(benv.step)(state, acts)
        assert obs.shape == (n, *raw.observation_space.shape)
        assert rew.shape == (n,) and done.shape == (n,)
        assert info["final_obs"].shape == obs.shape


# --------------------------------------------------------------------------- #
# Env-params pytrees (the scenario axis)
# --------------------------------------------------------------------------- #


def _rand_action(env, rng):
    if isinstance(env.action_space, gym.spaces.Box):
        return jnp.asarray(rng.uniform(-1, 1, size=env.action_space.shape).astype(np.float32))
    return jnp.asarray(int(rng.randint(env.action_space.n)))


@pytest.mark.parametrize("env_id", sorted(JAX_ENV_REGISTRY))
def test_default_params_round_trip(env_id):
    """Every registered env: ``default_params()`` is a flat NamedTuple of ()
    jnp scalars (float32 dynamics + int32 horizon), stepping with the
    default pytree passed EXPLICITLY matches stepping with ``params=None``
    bitwise, and the pytree is jit-stable — passing it as a traced argument
    to a jitted step compiles once and reproduces the eager result."""
    env = make_jax_env(env_id)
    params = env.default_params()
    assert isinstance(params, tuple) and hasattr(params, "_fields")
    for leaf in jax.tree.leaves(params):
        assert leaf.shape == () and leaf.dtype in (jnp.float32, jnp.int32)
    assert params.max_episode_steps.dtype == jnp.int32

    state, obs = env.reset(jax.random.PRNGKey(0), params)
    rng = np.random.RandomState(0)
    jstep = jax.jit(env.step)
    for it in range(10):
        a = _rand_action(env, rng)
        s_none, o_none, r_none, d_none, i_none = env.step(state, a)
        s_expl, o_expl, r_expl, d_expl, i_expl = env.step(state, a, params)
        # explicit default pytree == params=None, bitwise (same eager path)
        for a_leaf, b_leaf in zip(
            jax.tree.leaves((s_none, o_none, r_none, d_none, i_none)),
            jax.tree.leaves((s_expl, o_expl, r_expl, d_expl, i_expl)),
        ):
            np.testing.assert_array_equal(np.asarray(a_leaf), np.asarray(b_leaf))
        # the TRACED-params program reproduces eager within float32 ulp —
        # bitwise eager-vs-jit is NOT a contract (XLA fuses/reassociates),
        # which is exactly why the training blocks trace params everywhere
        # rather than splitting const-folded and traced programs
        s_jit, o_jit, r_jit, d_jit, i_jit = jstep(state, a, env.default_params())
        for a_leaf, b_leaf in zip(
            jax.tree.leaves((s_none, o_none, r_none, d_none, i_none)),
            jax.tree.leaves((s_jit, o_jit, r_jit, d_jit, i_jit)),
        ):
            np.testing.assert_allclose(np.asarray(a_leaf), np.asarray(b_leaf), rtol=1e-6, atol=1e-6)
        state = s_none
    # jit-stable pytree: 10 calls, each with a freshly built params pytree,
    # compiled exactly one program
    assert jstep._cache_size() == 1


@pytest.mark.parametrize("env_id", sorted(JAX_ENV_REGISTRY))
def test_params_vmapped_step_matches_single_steps(env_id):
    """The scenario axis contract: ``vmap``-ing ``step`` over a (P,)-stacked
    params pytree (same state/action per lane) equals P single-param steps.
    Bitwise is NOT asserted — vmapped reductions may reassociate at ulp
    level — but each lane must match its scalar twin to float32 tightness,
    and lanes with different dynamics must actually diverge."""
    env = make_jax_env(env_id)
    defaults = env.default_params()
    P = 3
    # scale the gravity constant across lanes (it feeds every env's velocity
    # update from any state, so lanes genuinely diverge); lane 0 = default
    scale = jnp.asarray([1.0, 1.35, 0.75], jnp.float32)
    vary = {"CartPole-v1": "gravity", "Pendulum-v1": "g"}.get(env_id, "gravity")
    stacked = jax.tree.map(lambda x: jnp.broadcast_to(x, (P,) + x.shape), defaults)
    stacked = stacked._replace(**{vary: getattr(defaults, vary) * scale})

    state, _ = env.reset(jax.random.PRNGKey(1), defaults)
    rng = np.random.RandomState(1)
    a = _rand_action(env, rng)
    vstep = jax.jit(jax.vmap(lambda p: env.step(state, a, p)))
    v_out = jax.device_get(vstep(stacked))
    for lane in range(P):
        p_lane = jax.tree.map(lambda x: x[lane], stacked)
        s_out = jax.device_get(env.step(state, a, p_lane))
        for a_leaf, b_leaf in zip(jax.tree.leaves(s_out), jax.tree.leaves(v_out)):
            np.testing.assert_allclose(
                np.asarray(a_leaf), np.asarray(b_leaf)[lane], rtol=1e-6, atol=1e-6
            )
    # different dynamics constants produce different physics
    obs_lanes = np.asarray(v_out[1])
    assert not np.array_equal(obs_lanes[0], obs_lanes[1])


def test_batched_env_params_vmapped_over_members():
    """A member axis of BatchedJaxEnv instances via ``vmap`` over the params
    pytree — exactly how the population block runs the scenario axis: each
    member's envs step under that member's dynamics row."""
    P, N = 3, 2
    env = make_jax_env("CartPole-v1")
    benv = BatchedJaxEnv(env, N)
    defaults = env.default_params()
    stacked = jax.tree.map(lambda x: jnp.broadcast_to(x, (P,) + x.shape), defaults)
    stacked = stacked._replace(length=defaults.length * jnp.asarray([1.0, 2.0, 0.5], jnp.float32))

    keys = jax.random.split(jax.random.PRNGKey(2), P)
    vreset = jax.jit(jax.vmap(benv.reset))
    state, obs = vreset(keys, stacked)
    assert obs.shape == (P, N, *env.observation_space.shape)
    acts = jnp.zeros((P, N), jnp.int32)
    vstep = jax.jit(jax.vmap(benv.step))
    state2, obs2, rew, done, info = vstep(state, acts, stacked)
    assert obs2.shape == (P, N, *env.observation_space.shape)
    # per-member single dispatch agrees with the vmapped member axis
    for m in range(P):
        p_m = jax.tree.map(lambda x: x[m], stacked)
        s_m, o_m = benv.reset(keys[m], p_m)
        s2_m, o2_m, r_m, d_m, _ = benv.step(s_m, acts[m], p_m)
        np.testing.assert_allclose(np.asarray(o2_m), np.asarray(obs2)[m], rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(r_m), np.asarray(rew)[m], rtol=1e-6, atol=1e-6)
