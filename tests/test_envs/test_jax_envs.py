"""Pure-JAX env parity against gymnasium + auto-reset/vmap semantics.

Parity strategy: jax PRNG and numpy PRNG cannot produce the same reset
states, so the gymnasium twin is *state-synced* from the jax env at every
episode start (``env.unwrapped.state = ...``) and both are driven with the
same seeded action sequence. The jax envs compute in float32 vs gymnasium's
float64, so trace comparisons carry a small per-episode drift tolerance;
single-step checks (re-synced every step) are tight.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.envs.jax_envs import (
    JAX_ENV_REGISTRY,
    BatchedJaxEnv,
    JaxAcrobot,
    JaxCartPole,
    JaxPendulum,
    is_jax_env,
    make_jax_env,
)

TRACE_STEPS = 200


def test_registry():
    assert is_jax_env("CartPole-v1") and is_jax_env("Pendulum-v1") and is_jax_env("Acrobot-v1")
    assert not is_jax_env("MsPacmanNoFrameskip-v4")
    assert isinstance(make_jax_env("CartPole-v1"), JaxCartPole)
    assert isinstance(make_jax_env("Pendulum-v1"), JaxPendulum)
    assert isinstance(make_jax_env("Acrobot-v1"), JaxAcrobot)
    with pytest.raises(ValueError, match="No pure-JAX environment"):
        make_jax_env("Walker2d-v4")


def test_register_jax_env_auto_discovery():
    """Adding an env is one ``@register_jax_env`` decorated module in the
    package: the package ``__init__`` auto-imports siblings and re-exports
    every registered class (no hand-maintained import list)."""
    import sheeprl_tpu.envs.jax_envs as pkg

    assert set(JAX_ENV_REGISTRY) >= {"CartPole-v1", "Pendulum-v1", "Acrobot-v1"}
    for cls in JAX_ENV_REGISTRY.values():
        # every registered env class is re-exported from the package
        assert getattr(pkg, cls.__name__) is cls
        assert cls.__name__ in pkg.__all__


def _sync_cartpole(genv, state):
    genv.unwrapped.state = np.asarray(state.physics, dtype=np.float64)


def _sync_pendulum(genv, state):
    genv.unwrapped.state = np.array([float(state.theta), float(state.theta_dot)], dtype=np.float64)


def test_cartpole_trace_parity():
    """Seeded 200-step trace: obs/reward/termination match gymnasium, with
    state re-sync (both PRNGs differ) at each episode start only."""
    jenv = JaxCartPole()
    genv = gym.make("CartPole-v1")
    genv.reset(seed=0)
    key = jax.random.PRNGKey(0)
    key, sub = jax.random.split(key)
    state, obs = jenv.reset(sub)
    _sync_cartpole(genv, state)
    rng = np.random.RandomState(1)
    for t in range(TRACE_STEPS):
        a = int(rng.randint(2))
        state, jobs, jr, jdone, jinfo = jenv.step(state, jnp.asarray(a))
        gobs, gr, gterm, gtrunc, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=1e-4, rtol=1e-4)
        assert float(jr) == float(gr) == 1.0
        assert bool(jinfo["terminated"]) == gterm
        assert bool(jdone) == (gterm or gtrunc)
        if jdone:
            key, sub = jax.random.split(key)
            state, obs = jenv.reset(sub)
            genv.reset()
            _sync_cartpole(genv, state)
    genv.close()


def test_cartpole_single_step_parity_tight():
    """Dynamics-exact check: re-sync every step, so no drift accumulates."""
    jenv = JaxCartPole()
    genv = gym.make("CartPole-v1")
    genv.reset(seed=0)
    state, _ = jenv.reset(jax.random.PRNGKey(7))
    rng = np.random.RandomState(2)
    for t in range(50):
        _sync_cartpole(genv, state)
        a = int(rng.randint(2))
        state, jobs, _, jdone, _ = jenv.step(state, jnp.asarray(a))
        gobs, _, gterm, _, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=1e-5, rtol=1e-5)
        if jdone:
            state, _ = jenv.reset(jax.random.PRNGKey(100 + t))
            genv.reset()
    genv.close()


def test_pendulum_trace_parity():
    """200-step trace = exactly one episode (no termination, truncated at
    200). float32-vs-float64 drift bounds the tolerance."""
    jenv = JaxPendulum()
    genv = gym.make("Pendulum-v1")
    genv.reset(seed=0)
    state, obs = jenv.reset(jax.random.PRNGKey(3))
    _sync_pendulum(genv, state)
    rng = np.random.RandomState(3)
    for t in range(TRACE_STEPS):
        a = rng.uniform(-2, 2, size=(1,)).astype(np.float32)
        state, jobs, jr, jdone, jinfo = jenv.step(state, jnp.asarray(a))
        gobs, gr, gterm, gtrunc, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=2e-2, rtol=2e-2)
        np.testing.assert_allclose(float(jr), float(gr), atol=5e-2)
        assert not bool(jinfo["terminated"]) and not gterm
        assert bool(jdone) == (gterm or gtrunc)
        assert bool(jdone) == (t == TRACE_STEPS - 1)
    genv.close()


def test_pendulum_single_step_parity_tight():
    jenv = JaxPendulum()
    genv = gym.make("Pendulum-v1")
    genv.reset(seed=0)
    state, _ = jenv.reset(jax.random.PRNGKey(4))
    rng = np.random.RandomState(4)
    for _ in range(50):
        _sync_pendulum(genv, state)
        a = rng.uniform(-2, 2, size=(1,)).astype(np.float32)
        state, jobs, jr, _, _ = jenv.step(state, jnp.asarray(a))
        gobs, gr, _, _, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(float(jr), float(gr), atol=1e-4)
    genv.close()


def _sync_acrobot(genv, state):
    genv.unwrapped.state = np.asarray(state.physics, dtype=np.float64)


def test_acrobot_trace_parity():
    """Seeded trace: obs/reward/termination match gymnasium with state
    re-sync at episode starts only. The double pendulum is chaotic, so f32
    vs f64 drift grows exponentially along an episode — the trace is kept
    short of the horizon where roundoff noise dominates, and the tolerance
    is looser than the single-step check below."""
    jenv = JaxAcrobot()
    genv = gym.make("Acrobot-v1")
    genv.reset(seed=0)
    key = jax.random.PRNGKey(6)
    key, sub = jax.random.split(key)
    state, obs = jenv.reset(sub)
    _sync_acrobot(genv, state)
    rng = np.random.RandomState(6)
    for t in range(60):
        a = int(rng.randint(3))
        state, jobs, jr, jdone, jinfo = jenv.step(state, jnp.asarray(a))
        gobs, gr, gterm, gtrunc, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=2e-2, rtol=2e-2)
        assert float(jr) == float(gr)
        assert bool(jinfo["terminated"]) == gterm
        assert bool(jdone) == (gterm or gtrunc)
        if jdone:
            key, sub = jax.random.split(key)
            state, obs = jenv.reset(sub)
            genv.reset()
            _sync_acrobot(genv, state)
    genv.close()


def test_acrobot_single_step_parity_tight():
    """Dynamics-exact check: re-sync every step so no drift accumulates —
    one RK4 step in float32 must match gymnasium's float64 step tightly."""
    jenv = JaxAcrobot()
    genv = gym.make("Acrobot-v1")
    genv.reset(seed=0)
    state, _ = jenv.reset(jax.random.PRNGKey(8))
    rng = np.random.RandomState(8)
    for t in range(50):
        _sync_acrobot(genv, state)
        a = int(rng.randint(3))
        state, jobs, jr, jdone, _ = jenv.step(state, jnp.asarray(a))
        gobs, gr, gterm, _, _ = genv.step(a)
        np.testing.assert_allclose(np.asarray(jobs), gobs, atol=1e-4, rtol=1e-4)
        assert float(jr) == float(gr)
        assert bool(jdone) == bool(gterm)  # no truncation inside 50 steps
        if jdone:
            state, _ = jenv.reset(jax.random.PRNGKey(200 + t))
            genv.reset()
            _sync_acrobot(genv, state)
    genv.close()


def test_acrobot_truncation_and_termination_reward():
    """-1 per step, 0 on the terminating step; the 500-step limit raises
    truncated, not terminated."""
    jenv = JaxAcrobot(max_episode_steps=5)
    state, _ = jenv.reset(jax.random.PRNGKey(0))
    for t in range(5):
        state, _, rew, done, info = jenv.step(state, jnp.asarray(1))
        if bool(info["terminated"]):
            assert float(rew) == 0.0
            pytest.skip("episode terminated before the tiny time limit")
        assert float(rew) == -1.0
        assert bool(info["truncated"]) == (t == 4)
        assert bool(done) == (t == 4)


def test_truncation_flag_cartpole():
    """A time-limited CartPole sets truncated (not terminated) at the limit,
    mirroring gymnasium's TimeLimit."""
    jenv = JaxCartPole(max_episode_steps=5)
    state, _ = jenv.reset(jax.random.PRNGKey(0))
    for t in range(5):
        state, _, _, done, info = jenv.step(state, jnp.asarray(0))
        if bool(info["terminated"]):
            pytest.skip("episode terminated before the tiny time limit")
        assert bool(info["truncated"]) == (t == 4)
        assert bool(done) == (t == 4)


def test_batched_autoreset_matches_manual_key_stream():
    """BatchedJaxEnv == a hand-rolled per-env loop with the same key
    discipline, bitwise (same ops, same dtypes), including SAME_STEP
    auto-resets: on the done step the returned obs is the NEW episode's
    first obs and info['final_obs'] is the terminal obs."""
    N = 4
    raw = JaxCartPole(max_episode_steps=20)
    benv = BatchedJaxEnv(raw, N)
    master = jax.random.PRNGKey(11)
    bstate, bobs = benv.reset(master)

    # manual replica of the wrapper's key discipline
    keys = jax.random.split(master, N)
    man_state, man_obs, man_keys = [], [], []
    for i in range(N):
        k, sub = jax.random.split(keys[i])
        s, o = raw.reset(sub)
        man_keys.append(k)
        man_state.append(s)
        man_obs.append(o)
    np.testing.assert_array_equal(np.asarray(bobs), np.stack([np.asarray(o) for o in man_obs]))

    rng = np.random.RandomState(5)
    for t in range(60):
        acts = rng.randint(2, size=(N,))
        bstate, bobs, brew, bdone, binfo = benv.step(bstate, jnp.asarray(acts))
        for i in range(N):
            s2, o2, r2, d2, info2 = raw.step(man_state[i], jnp.asarray(acts[i]))
            assert float(brew[i]) == float(r2)
            assert bool(bdone[i]) == bool(d2)
            # terminal obs rides in final_obs on the done step
            np.testing.assert_array_equal(np.asarray(binfo["final_obs"][i]), np.asarray(o2))
            assert bool(binfo["terminated"][i]) == bool(info2["terminated"])
            assert bool(binfo["truncated"][i]) == bool(info2["truncated"])
            if bool(d2):
                k2, sub = jax.random.split(man_keys[i])
                man_state[i], o_reset = raw.reset(sub)
                man_keys[i] = k2
                np.testing.assert_array_equal(np.asarray(bobs[i]), np.asarray(o_reset))
            else:
                man_state[i] = s2
                np.testing.assert_array_equal(np.asarray(bobs[i]), np.asarray(o2))


def test_batched_shapes_and_spaces():
    for env_id, n in [("CartPole-v1", 3), ("Pendulum-v1", 2), ("Acrobot-v1", 2)]:
        raw = make_jax_env(env_id)
        benv = BatchedJaxEnv(raw, n)
        assert benv.single_observation_space == raw.observation_space
        assert benv.single_action_space == raw.action_space
        state, obs = jax.jit(benv.reset)(jax.random.PRNGKey(0))
        assert obs.shape == (n, *raw.observation_space.shape)
        if isinstance(raw.action_space, gym.spaces.Box):
            acts = jnp.zeros((n, *raw.action_space.shape), jnp.float32)
        else:
            acts = jnp.zeros((n,), jnp.int32)
        state, obs, rew, done, info = jax.jit(benv.step)(state, acts)
        assert obs.shape == (n, *raw.observation_space.shape)
        assert rew.shape == (n,) and done.shape == (n,)
        assert info["final_obs"].shape == obs.shape
