"""FastSyncVectorEnv coverage — the fallback path for non-array action
spaces (``sheeprl_tpu/envs/vector.py``): gymnasium's ``step`` runs, but the
returned observation batch must still honor the fast path's two-step
lifetime contract (valid until the NEXT ``step()``), and infos must match
gymnasium's ``SyncVectorEnv`` bit-for-bit."""

import copy

import gymnasium as gym
import numpy as np
import pytest
from gymnasium.vector import AutoresetMode, SyncVectorEnv

from sheeprl_tpu.envs.vector import FastSyncVectorEnv


class DictActionEnv(gym.Env):
    """Deterministic env with a Dict action space (not array-indexable, so
    FastSyncVectorEnv must take its gymnasium fallback path). Observations
    count steps; episodes terminate after ``n_steps``; odd steps emit a
    non-empty info."""

    def __init__(self, n_steps: int = 5, offset: int = 0):
        self.action_space = gym.spaces.Dict(
            {"d": gym.spaces.Discrete(3), "c": gym.spaces.Box(-1.0, 1.0, (2,), dtype=np.float32)}
        )
        self.observation_space = gym.spaces.Box(-1e6, 1e6, (4,), dtype=np.float32)
        self._n_steps = n_steps
        self._offset = offset
        self._t = 0

    def _obs(self):
        return np.full((4,), self._t + self._offset, dtype=np.float32)

    def reset(self, seed=None, options=None):
        super().reset(seed=seed)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        assert isinstance(action, dict) and set(action) == {"d", "c"}
        self._t += 1
        terminated = self._t >= self._n_steps
        info = {"odd": True} if self._t % 2 == 1 else {}
        return self._obs(), float(self._t), terminated, False, info


def _thunks():
    # different episode lengths so dones are staggered across sub-envs
    return [lambda: DictActionEnv(n_steps=5, offset=0), lambda: DictActionEnv(n_steps=3, offset=100)]


def _actions(space, seed):
    space.seed(seed)
    return space.sample()


def _assert_infos_equal(a, b, path="infos"):
    assert set(a.keys()) == set(b.keys()), f"{path}: keys {set(a.keys())} != {set(b.keys())}"
    for k in a:
        va, vb = a[k], b[k]
        if isinstance(va, dict):
            _assert_infos_equal(va, vb, f"{path}.{k}")
        elif isinstance(va, np.ndarray) and va.dtype == object:
            assert len(va) == len(vb), f"{path}.{k}"
            for i, (xa, xb) in enumerate(zip(va, vb)):
                if xa is None or xb is None:
                    assert xa is None and xb is None, f"{path}.{k}[{i}]"
                else:
                    np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb), err_msg=f"{path}.{k}[{i}]")
        else:
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb), err_msg=f"{path}.{k}")


def test_fallback_path_is_taken():
    env = FastSyncVectorEnv(_thunks())
    assert not env._fast_actions
    env.close()


def test_fallback_matches_gymnasium_bit_for_bit():
    fast = FastSyncVectorEnv(_thunks())
    ref = SyncVectorEnv(_thunks(), autoreset_mode=AutoresetMode.SAME_STEP, copy=True)

    fobs, finfo = fast.reset(seed=42)
    robs, rinfo = ref.reset(seed=42)
    np.testing.assert_array_equal(fobs, robs)
    _assert_infos_equal(finfo, rinfo)

    for t in range(12):
        action = _actions(fast.single_action_space, seed=1000 + t)
        # gymnasium's Dict-space iterate() consumes the BATCHED dict layout
        actions = {k: np.stack([action[k]] * 2) for k in action}
        fobs, frew, fterm, ftrunc, finfo = fast.step(actions)
        robs, rrew, rterm, rtrunc, rinfo = ref.step(actions)
        np.testing.assert_array_equal(fobs, robs, err_msg=f"step {t} obs")
        np.testing.assert_array_equal(frew, rrew, err_msg=f"step {t} rew")
        np.testing.assert_array_equal(fterm, rterm, err_msg=f"step {t} term")
        np.testing.assert_array_equal(ftrunc, rtrunc, err_msg=f"step {t} trunc")
        _assert_infos_equal(finfo, rinfo)
    fast.close()
    ref.close()


def test_fallback_two_step_observation_lifetime():
    """The batch returned by step(t) must keep its values through step(t+1)
    (the mains read the previous batch after the next step), and consecutive
    steps must return distinct buffers (the ping-pong pair)."""
    env = FastSyncVectorEnv(_thunks())
    env.reset(seed=0)
    action = _actions(env.single_action_space, seed=7)
    actions = {k: np.stack([action[k]] * 2) for k in action}

    obs_t, *_ = env.step(actions)
    snapshot_t = np.copy(obs_t)

    obs_t1, *_ = env.step(actions)
    snapshot_t1 = np.copy(obs_t1)

    # contract: obs_t still valid after ONE further step
    np.testing.assert_array_equal(obs_t, snapshot_t)
    # ping-pong: the two live batches are distinct storage
    assert obs_t is not obs_t1
    assert not np.shares_memory(obs_t, obs_t1)

    env.step(actions)
    # obs_t1 (the previous batch) is still intact now
    np.testing.assert_array_equal(obs_t1, snapshot_t1)
    env.close()


def test_fast_path_matches_gymnasium_bit_for_bit():
    """Control experiment: the array-action fast path against gymnasium on
    the same deterministic envs (Discrete actions)."""
    from sheeprl_tpu.envs.dummy import DiscreteDummyEnv

    def mk():
        return [lambda: DiscreteDummyEnv(dict_obs_space=False, n_steps=4), lambda: DiscreteDummyEnv(dict_obs_space=False, n_steps=6)]

    fast = FastSyncVectorEnv(mk())
    ref = SyncVectorEnv(mk(), autoreset_mode=AutoresetMode.SAME_STEP, copy=True)
    assert fast._fast_actions
    fobs, finfo = fast.reset(seed=3)
    robs, rinfo = ref.reset(seed=3)
    np.testing.assert_array_equal(fobs, robs)
    _assert_infos_equal(finfo, rinfo)
    rng = np.random.RandomState(0)
    for t in range(15):
        acts = rng.randint(0, 2, size=(2,))
        fobs, frew, fterm, ftrunc, finfo = fast.step(acts)
        robs, rrew, rterm, rtrunc, rinfo = ref.step(acts)
        np.testing.assert_array_equal(fobs, robs, err_msg=f"step {t} obs")
        np.testing.assert_array_equal(frew, rrew)
        np.testing.assert_array_equal(fterm, rterm)
        np.testing.assert_array_equal(ftrunc, rtrunc)
        _assert_infos_equal(finfo, rinfo)
    fast.close()
    ref.close()
