"""Test bootstrap: force an 8-device virtual CPU mesh before JAX initializes
(the TPU-world analogue of the reference's ``LT_DEVICES`` fixture,
``tests/test_algos/test_algos.py:16-53``)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# The sandbox may pin an accelerator platform via sitecustomize; force CPU
# (the reference's LT_DEVICES analogue needs a local many-device mesh).
from sheeprl_tpu.utils.utils import pin_cpu_platform  # noqa: E402

pin_cpu_platform("cpu")

# Persistent XLA compilation cache: the dreamer/p2e train steps take tens of
# seconds to compile; caching them across test runs keeps the suite usable.
# Keyed by host CPU features — AOT entries from a feature-mismatched machine
# (e.g. a CI cache restored on a different runner generation) load with
# cpu_aot_loader errors and run slower code (utils.machine_keyed_cache_dir).
from sheeprl_tpu.utils.utils import machine_keyed_cache_dir  # noqa: E402

_CACHE_DIR = machine_keyed_cache_dir(os.environ.get("SHEEPRL_TPU_TEST_CACHE", "/tmp/sheeprl_tpu_xla_cache"))
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Auto-mark compile-heavy end-to-end tests as ``slow`` so the default
    verification loop can run ``-m "not slow"`` in well under 5 minutes."""
    for item in items:
        if any(s in item.nodeid for s in ("dreamer", "p2e", "multi_iteration", "sac_ae", "droq")):
            item.add_marker(pytest.mark.slow)


def pytest_sessionfinish(session, exitstatus):
    """With the graft-sync runtime sanitizer armed (the chaos lane runs
    ``SHEEPRL_TPU_SYNC_SANITIZE=1 pytest -m chaos``), every drill doubled as
    a sanitizer run: fail the session unless the process-wide lock ledger
    validates clean — 0 order cycles, 0 inversions, 0 over-budget holds."""
    if os.environ.get("SHEEPRL_TPU_SYNC_SANITIZE", "").strip() != "1":
        return
    from sheeprl_tpu.analysis.lockstats import lockstats, validate_payload

    report = lockstats.report()
    problems, summary = validate_payload(report)
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    line = (
        "graft-sync sanitizer: {locks} lock(s), {edges} edge(s) — {cycles} cycle(s), "
        "{inversions} inversion(s), {over_budget_locks} over-budget lock(s)".format(**summary)
    )
    if tr is not None:
        tr.write_line(line)
        for p in problems:
            tr.write_line(f"graft-sync sanitizer: {p}", red=True)
    if problems:
        session.exitstatus = 1


@pytest.fixture()
def tmp_logdir(tmp_path):
    return str(tmp_path / "logs")


@pytest.fixture(autouse=True)
def _reset_metric_state():
    """Timers/aggregator flags are class-level; isolate tests. The gradient
    wire dtype is process-wide and now DEFAULTS to bf16 for any multi-device
    `Fabric.from_config` run — reset it so an e2e CLI test can't leak bf16
    reduction into a later unit test's (f32-calibrated) numerics. The
    analysis.tracecheck registry is process-wide too: drop the previous
    test's instrumented entries/events so report() stays per-test."""
    from sheeprl_tpu.analysis.tracecheck import tracecheck
    from sheeprl_tpu.parallel.comm import set_grad_reduce_dtype
    from sheeprl_tpu.utils.metric import MetricAggregator
    from sheeprl_tpu.utils.timer import timer

    tracecheck.reset()
    set_grad_reduce_dtype("float32", fresh_run=True)
    yield
    timer.timers.clear()
    timer.disabled = False
    MetricAggregator.disabled = False
