"""Test bootstrap: force an 8-device virtual CPU mesh before JAX initializes
(the TPU-world analogue of the reference's ``LT_DEVICES`` fixture,
``tests/test_algos/test_algos.py:16-53``)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# The sandbox may pin an accelerator platform via sitecustomize; force CPU
# (the reference's LT_DEVICES analogue needs a local many-device mesh).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_logdir(tmp_path):
    return str(tmp_path / "logs")


@pytest.fixture(autouse=True)
def _reset_metric_state():
    """Timers/aggregator flags are class-level; isolate tests."""
    from sheeprl_tpu.utils.metric import MetricAggregator
    from sheeprl_tpu.utils.timer import timer

    yield
    timer.timers.clear()
    timer.disabled = False
    MetricAggregator.disabled = False
