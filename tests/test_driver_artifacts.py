"""The two driver-facing artifacts that failed in round 1 must never regress:
``bench.py`` must print its JSON line inside the budget, and
``__graft_entry__.dryrun_multichip`` must self-provision its virtual mesh
from a process whose JAX backend is already initialized."""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_prints_json_line():
    env = dict(os.environ)
    env["BENCH_TOTAL_STEPS"] = "512"
    env["BENCH_XLA_CACHE"] = "/tmp/sheeprl_tpu_bench_test_cache"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        cwd=_REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=480,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["metric"] == "ppo_cartpole_env_steps_per_sec"
    assert payload["value"] > 0
    assert set(payload) == {"metric", "value", "unit", "vs_baseline"}


@pytest.mark.slow
def test_dryrun_multichip_from_initialized_backend():
    code = (
        # Initialize a backend first, like the driver. The sandbox's
        # sitecustomize force-sets JAX_PLATFORMS, so pin CPU via jax.config
        # (the shell env alone is not enough).
        "import jax; jax.config.update('jax_platforms', 'cpu'); jax.devices()\n"
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(8)\n"
        "print('DRYRUN-OK')\n"
    )
    # Pin the child to the CPU backend: the driver provides the virtual-CPU
    # mesh environment itself, and the default (tunneled-accelerator) backend
    # can wedge for minutes — this test must stay hermetic.
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=_REPO,
        env=env,
        capture_output=True,
        text=True,
        # Generous: a cold XLA cache (any change to the burst/train programs
        # invalidates it) plus suite-load contention was measured at >540 s;
        # quiet warm runs take ~3 min.
        timeout=1200,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "dreamer_v3(8) OK" in proc.stdout
    assert "ppo(8) OK" in proc.stdout
    assert "DRYRUN-OK" in proc.stdout
