"""graft-sync tier tests: planted-hazard fire/quiet pairs for every GS rule,
the live AB-BA fixture caught by BOTH the static pass (GS002) and the runtime
sanitizer's dump, CLI-contract checks, and the repo-tree-clean gate (the
shipped baseline is EMPTY by policy — real findings get fixed, suppressions
carry inline justifications)."""

import json
import textwrap
import threading
import warnings
from pathlib import Path

import pytest

from sheeprl_tpu.analysis.__main__ import main as analysis_main
from sheeprl_tpu.analysis.lockstats import LockStats, validate_payload
from sheeprl_tpu.analysis.sync import (
    SYNC_RULES,
    analyze_source_sync,
    analyze_sync_sources,
)

REPO_ROOT = Path(__file__).parents[2]


def rules_of(findings):
    return [f.rule for f in findings]


def src(code: str) -> str:
    return textwrap.dedent(code)


# --------------------------------------------------------------------------- #
# GS001 — unguarded shared mutable state
# --------------------------------------------------------------------------- #


GS001_FIRE = src(
    """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def worker(self):
            with self._lock:
                self.n += 1

        def sloppy(self):
            self.n += 1  # no lock: the torn update
    """
)

GS001_QUIET = src(
    """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def worker(self):
            with self._lock:
                self.n += 1

        def read(self):
            return self.n  # unguarded READ of a guarded field is not GS001
    """
)


def test_gs001_unguarded_shared_counter_fires():
    findings = analyze_source_sync(GS001_FIRE, "f.py")
    assert rules_of(findings) == ["GS001"]
    f = findings[0]
    assert "self.n" in f.message and "Counter._lock" in f.message
    assert f.function == "Counter.sloppy"


def test_gs001_consistent_guarding_quiet():
    assert analyze_source_sync(GS001_QUIET, "f.py") == []


def test_gs001_no_lock_class_quiet():
    # a class without a lock has no lockset to violate
    code = src(
        """
        class Plain:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
        """
    )
    assert analyze_source_sync(code, "f.py") == []


def test_gs001_locked_suffix_convention_quiet():
    # CPython's `_locked` suffix: the caller holds the lock by contract
    code = src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.evicted = 0

            def evict(self):
                with self._lock:
                    self._evict_locked()
                    self.evicted += 0  # guarded access establishes the lockset

            def _evict_locked(self):
                self.evicted += 1
        """
    )
    assert analyze_source_sync(code, "f.py") == []


def test_gs001_inherited_lock_resolves_to_declaring_class():
    code = src(
        """
        import threading

        class Base:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0

        class Sub(Base):
            def __init__(self):
                super().__init__()
                self.extra = 0

            def ok(self):
                with self._lock:
                    self.extra += 1

            def bad(self):
                self.extra += 1
        """
    )
    findings = analyze_source_sync(code, "f.py")
    assert rules_of(findings) == ["GS001"]
    assert "Base._lock" in findings[0].message


# --------------------------------------------------------------------------- #
# GS002 — AB-BA lock-order cycles
# --------------------------------------------------------------------------- #


GS002_FIRE = src(
    """
    import threading

    class Left:
        def __init__(self, right):
            self._left_lock = threading.Lock()
            self.right = right

        def forward(self):
            with self._left_lock:
                with self.right._right_lock:
                    pass

    class Right:
        def __init__(self, left):
            self._right_lock = threading.Lock()
            self.left = left

        def backward(self):
            with self._right_lock:
                with self.left._left_lock:
                    pass
    """
)

GS002_QUIET = src(
    """
    import threading

    class Left:
        def __init__(self, right):
            self._left_lock = threading.Lock()
            self.right = right

        def forward(self):
            with self._left_lock:
                with self.right._right_lock:
                    pass

    class Right:
        def __init__(self, left):
            self._right_lock = threading.Lock()
            self.left = left

        def backward(self):
            with self.left._left_lock:  # same global order: left before right
                with self._right_lock:
                    pass
    """
)


def test_gs002_ab_ba_cycle_across_two_classes_fires():
    findings = analyze_source_sync(GS002_FIRE, "f.py")
    assert rules_of(findings) == ["GS002"]
    msg = findings[0].message
    assert "Left._left_lock" in msg and "Right._right_lock" in msg
    assert "cycle" in msg


def test_gs002_consistent_global_order_quiet():
    assert analyze_source_sync(GS002_QUIET, "f.py") == []


def test_gs002_call_mediated_cycle_fires():
    # the cycle closes through a typed-attribute method call, not direct nesting
    code = src(
        """
        import threading

        class Cache:
            def __init__(self):
                self._cache_lock = threading.Lock()
                self.owner = None

            def purge(self):
                with self._cache_lock:
                    self.owner.on_purge()

        class Owner:
            def __init__(self):
                self._owner_lock = threading.Lock()
                self.cache = Cache()

            def on_purge(self):
                with self._owner_lock:
                    pass

            def shutdown(self):
                with self._owner_lock:
                    self.cache.purge()
        """
    )
    findings = analyze_source_sync(code, "f.py")
    assert "GS002" in rules_of(findings)


def test_gs002_nonreentrant_self_acquire_fires():
    code = src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    with self._lock:
                        pass
        """
    )
    findings = analyze_source_sync(code, "f.py")
    assert rules_of(findings) == ["GS002"]
    assert "non-reentrant" in findings[0].message


def test_gs002_call_mediated_self_deadlock_fires():
    # the most common REAL self-deadlock: re-taking your own plain Lock
    # through a method call made under it
    code = src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.helper()

            def helper(self):
                with self._lock:
                    pass
        """
    )
    findings = analyze_source_sync(code, "f.py")
    assert "GS002" in rules_of(findings)
    assert any("self-deadlock" in f.message for f in findings)


def test_gs002_condition_self_reacquire_fires():
    # a default Condition wraps a non-reentrant Lock: nested `with` deadlocks
    code = src(
        """
        import threading

        class C:
            def __init__(self):
                self._cond = threading.Condition()

            def outer(self):
                with self._cond:
                    with self._cond:
                        pass
        """
    )
    findings = analyze_source_sync(code, "f.py")
    assert "GS002" in rules_of(findings)
    assert "Condition" in findings[0].message


def test_gs002_rlock_reentry_quiet():
    code = src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        """
    )
    assert analyze_source_sync(code, "f.py") == []


def test_gs002_mutually_recursive_callers_dont_poison_the_cycle():
    # may_acquire results computed under a recursion cut must not be cached:
    # an unrelated class querying the call cycle FIRST must not hide a real
    # AB-BA cycle from a later query (order-dependence regression)
    code = src(
        """
        import threading

        class A:
            def __init__(self, b):
                self.b = b

            def f(self):
                self.b.g()

        class B:
            def __init__(self, a):
                self._block = threading.Lock()
                self.a = a

            def g(self):
                with self._block:
                    pass
                self.a.f()

        class C:
            def __init__(self, b):
                self.b = b

            def probe(self):
                self.b.g()  # innocent first query of the cycle

        class D:
            def __init__(self, a):
                self._dlock = threading.Lock()
                self.a = a

            def k(self):
                with self._dlock:
                    self.a.f()  # D._dlock -> B._block

        class E:
            def __init__(self, d):
                self._block2 = threading.Lock()
                self.d = d
        """
    )
    # edge D._dlock -> B._block must exist regardless of declaration order;
    # close the cycle with the reverse order in a second module
    reverse = src(
        """
        import threading

        class R:
            def __init__(self, d):
                self.d = d

            def r(self):
                with self.d._block_r:
                    self.d.k()  # (unresolvable attr, ignored)
        """
    )
    from sheeprl_tpu.analysis.syncgraph import Corpus

    corpus = Corpus()
    corpus.add_source(code, "f.py")
    corpus.add_source(reverse, "g.py")
    corpus.finalize()
    edges = corpus.lock_order_edges()
    assert ("D._dlock", "B._block") in edges


def test_gs001_bare_annotation_is_not_a_write():
    code = src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def reader(self):
                with self._lock:
                    return self.n

            def annotate(self):
                self.n: int  # a declaration, not a store
        """
    )
    assert analyze_source_sync(code, "f.py") == []


def test_malformed_budget_env_degrades_to_default():
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore", RuntimeWarning)
        import os

        old = os.environ.get("SHEEPRL_TPU_SYNC_HOLD_BUDGET_S")
        os.environ["SHEEPRL_TPU_SYNC_HOLD_BUDGET_S"] = "5s"
        try:
            stats = LockStats(enabled=True)
            assert stats.budget_s == 5.0
        finally:
            if old is None:
                del os.environ["SHEEPRL_TPU_SYNC_HOLD_BUDGET_S"]
            else:
                os.environ["SHEEPRL_TPU_SYNC_HOLD_BUDGET_S"] = old


def test_gs002_cross_module_cycle_fires():
    # GS002's graph is corpus-wide: each half of the cycle lives in its own file
    left = src(
        """
        import threading

        class Left:
            def __init__(self, right):
                self._left_lock = threading.Lock()
                self.right = right

            def forward(self):
                with self._left_lock:
                    with self.right._right_lock:
                        pass
        """
    )
    right = src(
        """
        import threading

        class Right:
            def __init__(self, left):
                self._right_lock = threading.Lock()
                self.left = left

            def backward(self):
                with self._right_lock:
                    with self.left._left_lock:
                        pass
        """
    )
    findings = analyze_sync_sources([(left, "left.py"), (right, "right.py")])
    assert "GS002" in rules_of(findings)


# --------------------------------------------------------------------------- #
# GS003 — blocking call under a held lock
# --------------------------------------------------------------------------- #


GS003_FIRE = src(
    """
    import queue
    import threading

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = queue.Queue()

        def drain(self):
            with self._lock:
                return self._q.get()  # unbounded wait with the lock held
    """
)

GS003_QUIET = src(
    """
    import queue
    import threading

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = queue.Queue()

        def drain(self):
            with self._lock:
                return self._q.get(timeout=0.1)

        def drain_nowait(self):
            with self._lock:
                return self._q.get_nowait()

        def outside(self):
            return self._q.get()  # blocking, but no lock held
    """
)


def test_gs003_queue_get_under_lock_fires():
    findings = analyze_source_sync(GS003_FIRE, "f.py")
    assert rules_of(findings) == ["GS003"]
    assert "queue.get()" in findings[0].message and "Pump._lock" in findings[0].message


def test_gs003_bounded_or_unlocked_quiet():
    assert analyze_source_sync(GS003_QUIET, "f.py") == []


def test_gs003_join_and_block_until_ready_under_lock_fire():
    code = src(
        """
        import threading
        import jax

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.worker = None

            def stop(self):
                with self._lock:
                    self.worker.join()  # no timeout

            def sync(self, x):
                with self._lock:
                    jax.block_until_ready(x)

            def stop_bounded(self):
                with self._lock:
                    self.worker.join(timeout=5.0)

            def fmt(self, parts):
                with self._lock:
                    return ",".join(parts)  # str.join: not a thread join
        """
    )
    findings = analyze_source_sync(code, "f.py")
    assert rules_of(findings) == ["GS003", "GS003"]


def test_gs003_manual_acquire_release_tracked():
    code = src(
        """
        import queue
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def bad(self):
                self._lock.acquire()
                item = self._q.get()
                self._lock.release()
                return item

            def fine(self):
                self._lock.acquire()
                self._lock.release()
                return self._q.get()
        """
    )
    findings = analyze_source_sync(code, "f.py")
    assert rules_of(findings) == ["GS003"]
    assert findings[0].function == "C.bad"


# --------------------------------------------------------------------------- #
# GS004 — raw Thread outside the supervisor wiring
# --------------------------------------------------------------------------- #


def test_gs004_raw_thread_fires_and_spawn_quiet():
    fire = src(
        """
        import threading

        def run(worker):
            t = threading.Thread(target=worker, daemon=True)
            t.start()
        """
    )
    quiet = src(
        """
        from sheeprl_tpu.fault.supervisor import Supervisor

        def run(worker):
            sup = Supervisor()
            sup.spawn("worker", worker)
        """
    )
    assert rules_of(analyze_source_sync(fire, "f.py")) == ["GS004"]
    assert analyze_source_sync(quiet, "f.py") == []


def test_gs004_supervisor_module_allowlisted():
    code = src(
        """
        import threading

        def spawn(target):
            threading.Thread(target=target, daemon=True).start()
        """
    )
    assert analyze_source_sync(code, "sheeprl_tpu/fault/supervisor.py") == []
    assert rules_of(analyze_source_sync(code, "sheeprl_tpu/serve/other.py")) == ["GS004"]


# --------------------------------------------------------------------------- #
# GS005 — Condition.wait without a predicate loop
# --------------------------------------------------------------------------- #


GS005_FIRE = src(
    """
    import threading

    class Box:
        def __init__(self):
            self._cond = threading.Condition()
            self.ready = False

        def take(self):
            with self._cond:
                if not self.ready:
                    self._cond.wait()  # if-guard races notify + spurious wakeups
    """
)

GS005_QUIET = src(
    """
    import threading

    class Box:
        def __init__(self):
            self._cond = threading.Condition()
            self.ready = False

        def take(self):
            with self._cond:
                while not self.ready:
                    self._cond.wait()

        def take_for(self):
            with self._cond:
                self._cond.wait_for(lambda: self.ready)
    """
)


def test_gs005_bare_wait_fires():
    findings = analyze_source_sync(GS005_FIRE, "f.py")
    assert rules_of(findings) == ["GS005"]
    assert "while" in findings[0].message


def test_gs005_predicate_loop_and_wait_for_quiet():
    assert analyze_source_sync(GS005_QUIET, "f.py") == []


def test_gs005_service_loop_if_guard_still_fires():
    # an OUTER `while not stop:` service loop does not make an if-guarded
    # wait safe: the predicate loop must hold the condition across iterations
    code = src(
        """
        import threading

        class Worker:
            def __init__(self):
                self._cond = threading.Condition()
                self.ready = False
                self.stop = False

            def run(self):
                while not self.stop:
                    with self._cond:
                        if not self.ready:
                            self._cond.wait()
        """
    )
    findings = analyze_source_sync(code, "f.py")
    assert "GS005" in rules_of(findings)


def test_gs003_positional_block_false_quiet():
    # q.get(False) / q.put(x, False) cannot block — no finding
    code = src(
        """
        import queue
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def a(self):
                with self._lock:
                    return self._q.get(False)

            def b(self, x):
                with self._lock:
                    self._q.put(x, False)

            def c(self):
                with self._lock:
                    return self._q.get(True)  # positional blocking form DOES flag
        """
    )
    findings = analyze_source_sync(code, "f.py")
    assert rules_of(findings) == ["GS003"]
    assert findings[0].function == "C.c"


def test_gs001_thread_target_closure_in_init_not_exempt():
    # a closure defined in __init__ but handed to a thread runs
    # post-publication: its writes get no construction-time exemption
    code = src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

                def worker():
                    self.n += 1

                threading.Thread(target=worker, daemon=True).start()  # graft-sync: disable=GS004

            def bump(self):
                with self._lock:
                    self.n += 1
        """
    )
    findings = analyze_source_sync(code, "f.py")
    assert rules_of(findings) == ["GS001"]
    assert "worker" in findings[0].function


# --------------------------------------------------------------------------- #
# suppressions
# --------------------------------------------------------------------------- #


def test_inline_suppression_silences_rule():
    code = GS001_FIRE.replace(
        "self.n += 1  # no lock: the torn update",
        "self.n += 1  # graft-sync: disable=GS001",
    )
    assert analyze_source_sync(code, "f.py") == []


def test_disable_next_line_skips_continuation_comments():
    code = src(
        """
        import threading

        def run(worker):
            # graft-sync: disable-next-line=GS004 — justification line one
            # continuing the justification on a second comment line
            t = threading.Thread(target=worker, daemon=True)
            t.start()
        """
    )
    assert analyze_source_sync(code, "f.py") == []


def test_suppression_is_rule_scoped():
    code = GS001_FIRE.replace(
        "self.n += 1  # no lock: the torn update",
        "self.n += 1  # graft-sync: disable=GS003",
    )
    assert rules_of(analyze_source_sync(code, "f.py")) == ["GS001"]


# --------------------------------------------------------------------------- #
# runtime sanitizer: live AB-BA + hold budget + dump validation
# --------------------------------------------------------------------------- #


def _run_ab_ba(stats: LockStats) -> None:
    """Two threads taking opposite orders with timed acquires: the edges (and
    the live inversion) are recorded without actually deadlocking the test."""
    a = stats.lock("fixture.A")
    b = stats.lock("fixture.B")
    barrier = threading.Barrier(2)

    def t1():
        with a:
            barrier.wait(5)
            got = b.acquire(timeout=0.3)
            if got:
                b.release()

    def t2():
        with b:
            barrier.wait(5)
            got = a.acquire(timeout=0.3)
            if got:
                a.release()

    th1 = threading.Thread(target=t1)
    th2 = threading.Thread(target=t2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        th1.start()
        th2.start()
        th1.join(10)
        th2.join(10)
    assert not th1.is_alive() and not th2.is_alive()


def test_live_ab_ba_caught_by_sanitizer_dump(tmp_path):
    stats = LockStats(enabled=True)
    _run_ab_ba(stats)
    report = stats.report()
    assert report["inversions"], "opposite-order acquires must record an inversion"
    dump = tmp_path / "sync.json"
    stats.dump(str(dump))
    problems, summary = validate_payload(json.loads(dump.read_text()))
    assert summary["cycles"] >= 1 and summary["inversions"] >= 1
    assert any("cycle" in p for p in problems)
    # the CLI judges the same dump with the lint exit-code contract
    assert analysis_main(["sync-validate", str(dump)]) == 1


def test_ab_ba_fixture_caught_statically_too():
    # the SAME deadlock shape, as source: the static tier flags it as GS002
    assert "GS002" in rules_of(analyze_source_sync(GS002_FIRE, "f.py"))


def test_sanitizer_clean_run_validates_green(tmp_path):
    stats = LockStats(enabled=True)
    a = stats.lock("fixture.A")
    b = stats.lock("fixture.B")
    for _ in range(3):  # consistent global order: A before B, always
        with a:
            with b:
                pass
    dump = tmp_path / "sync.json"
    stats.dump(str(dump))
    problems, summary = validate_payload(json.loads(dump.read_text()))
    assert problems == []
    assert summary["edges"] == 1 and summary["cycles"] == 0
    assert analysis_main(["sync-validate", str(dump)]) == 0


def test_sanitizer_over_budget_hold_flagged(tmp_path):
    import time

    stats = LockStats(enabled=True, budget_s=0.01)
    lk = stats.lock("fixture.slow")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with lk:
            time.sleep(0.05)
    dump = tmp_path / "sync.json"
    stats.dump(str(dump))
    problems, summary = validate_payload(json.loads(dump.read_text()))
    assert summary["over_budget_locks"] == 1
    assert any("over-budget" in p for p in problems)
    assert analysis_main(["sync-validate", str(dump)]) == 1


def test_sanitizer_rlock_reentry_records_no_self_edge():
    stats = LockStats(enabled=True)
    rl = stats.rlock("fixture.R")
    with rl:
        with rl:
            pass
    report = stats.report()
    assert report["edges"] == []
    assert report["locks"]["fixture.R"]["acquisitions"] == 1  # one outer hold


def test_sanitizer_condition_wait_tracks_through_wrapper():
    stats = LockStats(enabled=True)
    cond = stats.condition("fixture.cond")
    box = {"ready": False}

    def producer():
        with cond:
            box["ready"] = True
            cond.notify()

    t = threading.Thread(target=producer)
    with cond:
        t.start()
        while not box["ready"]:
            cond.wait(timeout=5)
    t.join(5)
    report = stats.report()
    # the wait's release/re-acquire cycles through the instrumented lock
    assert report["locks"]["fixture.cond"]["acquisitions"] >= 2


def test_sanitizer_cross_thread_release_does_not_corrupt_ledger():
    # a Lock handoff (acquire on one thread, release on another) is legal for
    # threading.Lock; the releasing thread's bookkeeping must not go negative
    # or disable its future recording
    stats = LockStats(enabled=True)
    lk = stats.lock("fixture.handoff")
    other = stats.lock("fixture.other")
    lk.acquire()
    t = threading.Thread(target=lk.release)
    t.start()
    t.join(5)
    # the releasing thread keeps recording normally afterwards
    def use_other():
        with other:
            pass

    t2 = threading.Thread(target=use_other)
    t2.start()
    t2.join(5)
    report = stats.report()
    assert report["locks"]["fixture.other"]["acquisitions"] == 1
    problems, _ = validate_payload(report)
    assert problems == []


def test_factories_are_plain_primitives_when_off():
    stats = LockStats(enabled=False)
    assert type(stats.lock("x")) is type(threading.Lock())
    assert type(stats.rlock("x")) is type(threading.RLock())
    assert isinstance(stats.condition("x"), threading.Condition)


def test_sync_validate_unreadable_dump_exits_2(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert analysis_main(["sync-validate", str(bad)]) == 2
    other = tmp_path / "other.json"
    other.write_text(json.dumps({"tool": "tracecheck"}))
    assert analysis_main(["sync-validate", str(other)]) == 2


# --------------------------------------------------------------------------- #
# CLI contract + the repo-tree-clean gate
# --------------------------------------------------------------------------- #


def test_cli_list_rules(capsys):
    assert analysis_main(["sync", "--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in SYNC_RULES:
        assert rule in out


def test_cli_exit_codes_and_formats(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(GS001_FIRE)
    assert analysis_main(["sync", str(bad)]) == 1
    capsys.readouterr()
    assert analysis_main(["sync", str(bad), "--format=json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["tool"] == "graft-sync"
    assert [f["rule"] for f in payload["findings"]] == ["GS001"]
    assert analysis_main(["sync", str(bad), "--format=github"]) == 1
    gh = capsys.readouterr().out
    assert "::error file=" in gh and "graft-sync GS001" in gh
    assert analysis_main(["sync", str(bad), "--select", "GS004"]) == 0
    assert analysis_main(["sync", str(bad), "--select", "GS999"]) == 2


def test_cli_syntax_error_reported_not_crash(tmp_path, capsys):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    assert analysis_main(["sync", str(bad)]) == 1
    assert "GS000" in capsys.readouterr().out


def test_repo_tree_is_clean():
    """THE shipped-baseline gate: the full CLI run over sheeprl_tpu/ is green
    — every real finding fixed, every suppression inline-justified."""
    rc = analysis_main(["sync", str(REPO_ROOT / "sheeprl_tpu")])
    assert rc == 0


def test_analysis_all_merges_ast_tiers(capsys):
    """`analysis all` runs lint + sync (audit skipped here: the compile pass
    has its own lane) with one merged exit code."""
    rc = analysis_main(["all", str(REPO_ROOT / "sheeprl_tpu"), "--skip-audit"])
    err = capsys.readouterr().err
    assert rc == 0
    assert "lint=0" in err and "sync=0" in err


def test_analysis_all_propagates_findings(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(GS001_FIRE)
    rc = analysis_main(["all", str(bad), "--skip-audit"])
    capsys.readouterr()
    assert rc == 1


def test_analysis_all_rejects_json_format(tmp_path):
    # `all` concatenates per-tier streams; a single JSON document would be a
    # lie, so the verb only offers line-oriented formats
    with pytest.raises(SystemExit):
        analysis_main(["all", str(tmp_path), "--format=json"])


def test_lint_disable_next_line_shares_sync_semantics(tmp_path):
    # ONE suppression implementation across tiers: graft-lint's
    # disable-next-line also skips continuation comment lines now
    from sheeprl_tpu.analysis.lint import analyze_source

    code = src(
        """
        import jax

        def loop(n):
            out = []
            for i in range(n):
                # graft-lint: disable-next-line=GL007 — justification line one
                # wrapping onto a second comment line
                out.append(jax.random.PRNGKey(i))
            return out
        """
    )
    assert [f.rule for f in analyze_source(code, "f.py")] == []
