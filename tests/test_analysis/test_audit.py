"""graft-audit: planted-hazard fixtures (each must FAIL with the right rule
id), the PR 8 sharding-canonicalization regression, budget-manifest
semantics, and the repo-tree-clean gate over the real program registry."""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.analysis.audit import (
    AUDIT_RULES,
    audit_program,
    sharding_cache_fingerprint,
    sharding_fingerprint,
)
from sheeprl_tpu.analysis.budgets import check_budgets, manifest_from_measurements
from sheeprl_tpu.analysis.programs import AuditMesh, AuditProgram
from sheeprl_tpu.parallel.compat import shard_map

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(scope="module")
def mesh():
    return AuditMesh(devices=2).build()


def rules_of(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------------------------- #
# planted hazards — one per rule, each failing with ITS id
# --------------------------------------------------------------------------- #


def test_planted_unaliased_donation_fails_aud001(mesh):
    # y is donated but no output matches its shape/dtype -> XLA cannot alias
    def f(x, y):
        return x * 2.0, jnp.float32(y.sum())

    prog = AuditProgram(
        name="planted.donation",
        fn=jax.jit(f, donate_argnums=(0, 1)),
        args=(jnp.zeros((8, 4), jnp.float32), jnp.ones((3,), jnp.float32)),
        donate_argnums=(0, 1),
        donation_slack_bytes=0,
        check_input_shardings=False,
    )
    findings, _ = audit_program(prog)
    assert "AUD001" in rules_of(findings)


def test_planted_resharded_feedback_output_fails_aud002(mesh):
    # env-carried output declared P("dp") but the program RESHARDS it to
    # replicated (pinned, so the pin check passes — the drift check fires)
    def body(x):
        return jax.lax.all_gather(x, "dp", tiled=True)

    sm = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P(), check_vma=False)
    fn = jax.jit(sm, out_shardings=NamedSharding(mesh, P()))
    prog = AuditProgram(
        name="planted.resharded",
        fn=fn,
        args=(jax.ShapeDtypeStruct((8, 4), jnp.float32, sharding=NamedSharding(mesh, P("dp"))),),
        out_decl={0: P("dp")},  # the REGISTERED declaration the program violates
        mesh=mesh,
    )
    findings, _ = audit_program(prog)
    assert "AUD002" in rules_of(findings)
    assert any("drift" in f.message for f in findings)


def test_planted_f64_leak_fails_aud003(mesh):
    with jax.experimental.enable_x64():
        fn = jax.jit(lambda x: jnp.asarray(x, jnp.float64) * np.float64(2.0))
        prog = AuditProgram(
            name="planted.f64",
            fn=fn,
            args=(jax.ShapeDtypeStruct((16,), jnp.float64),),
            check_input_shardings=False,
        )
        findings, _ = audit_program(prog)
    assert "AUD003" in rules_of(findings)
    assert any("f64" in f.message for f in findings)


def test_planted_f32_collective_under_bf16_policy_fails_aud003(mesh):
    # a gradient-sized f32 all-reduce under a declared bfloat16 wire policy
    def body(g):
        return jax.lax.pmean(g, "dp")

    sm = shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
    prog = AuditProgram(
        name="planted.f32wire",
        fn=jax.jit(sm),
        args=(jax.ShapeDtypeStruct((4096,), jnp.float32, sharding=NamedSharding(mesh, P())),),
        mesh=mesh,
        wire_dtype="bfloat16",
        check_input_shardings=False,
    )
    findings, _ = audit_program(prog)
    assert "AUD003" in rules_of(findings)
    assert any("bfloat16 wire policy" in f.message for f in findings)


def test_planted_oversized_baked_constant_fails_aud004(mesh):
    # weights closed over (not passed as args) fold into the executable —
    # exactly what breaks graft-serve hot swap
    baked = jnp.asarray(np.random.default_rng(0).normal(size=(128, 128)), jnp.float32)

    prog = AuditProgram(
        name="planted.constant",
        fn=jax.jit(lambda x: x @ baked),
        args=(jax.ShapeDtypeStruct((4, 128), jnp.float32),),
        constant_budget=16 * 1024,  # 64 KiB constant vs 16 KiB budget
        check_input_shardings=False,
    )
    findings, _ = audit_program(prog)
    assert "AUD004" in rules_of(findings)
    assert any("baked into the executable" in f.message for f in findings)


def test_broken_program_reports_aud000_not_crash(mesh):
    prog = AuditProgram(
        name="planted.broken",
        fn=jax.jit(lambda x: x.undefined_attr),
        args=(jax.ShapeDtypeStruct((4,), jnp.float32),),
    )
    findings, meas = audit_program(prog)
    assert rules_of(findings) == ["AUD000"]
    assert meas == {}


# --------------------------------------------------------------------------- #
# the PR 8 regression: equivalent-but-differently-keyed canonicalization
# --------------------------------------------------------------------------- #


def _anakin_shaped_program(mesh, pinned: bool):
    """The bug shape PR 8 found in the fused Anakin block: a donated,
    env-carried P(None, 'dp') output fed back into the next dispatch, with
    the placement left to jit inference (pinned=False) or pinned to the
    driver's staging sharding (the fix, pinned=True)."""

    def body(env, params):
        env = env + jax.lax.pmean(params.sum(), "dp")
        return env, params.sum()

    sm = shard_map(
        body, mesh=mesh, in_specs=(P(None, "dp"), P()), out_specs=(P(None, "dp"), P()),
        check_vma=False,
    )
    env_out = NamedSharding(mesh, P(None, "dp"))
    if pinned:
        fn = jax.jit(sm, donate_argnums=(0,), out_shardings=(env_out, NamedSharding(mesh, P())))
    else:
        fn = jax.jit(sm, donate_argnums=(0,))
    return AuditProgram(
        name="pr8.block",
        fn=fn,
        args=(
            jax.ShapeDtypeStruct((4, 8), jnp.float32, sharding=env_out),
            jax.ShapeDtypeStruct((16,), jnp.float32, sharding=NamedSharding(mesh, P())),
        ),
        donate_argnums=(0,),
        feedback_outputs=(0,),
        out_decl={0: P(None, "dp")},
        mesh=mesh,
    )


def test_pr8_unpinned_canonicalization_class_caught_at_audit_time(mesh):
    """The regression test the acceptance criteria names: the PR 8 bug —
    jit canonicalizing a shard_map's P(None, 'dp') outputs to an EQUIVALENT
    placement with a different C++ jit-cache key, silently recompiling the
    whole program on call 2 — would now be caught at audit time, before any
    steady-state test runs."""
    findings, _ = audit_program(_anakin_shaped_program(mesh, pinned=False))
    assert "AUD002" in rules_of(findings)
    assert any("PR 8" in f.message and "fed back" in f.message for f in findings)


def test_pr8_pinned_fix_shape_passes(mesh):
    findings, _ = audit_program(_anakin_shaped_program(mesh, pinned=True))
    assert findings == []


def test_sharding_fingerprint_normalizes_equivalent_placements(mesh):
    """Two avals-equal programs with distinct cache keys: the NORMALIZED
    fingerprint maps the NamedSharding and its GSPMD spelling to the same
    identity (so drift checks compare placement, not spelling), while the
    CACHE-KEY fingerprint keeps them distinct (the PR 8 gap)."""
    named = NamedSharding(mesh, P(None, "dp"))
    gspmd = jax.sharding.GSPMDSharding(list(mesh.devices.flat), named._to_xla_hlo_sharding(2))
    assert named.is_equivalent_to(gspmd, 2)
    assert sharding_fingerprint(named, 2) == sharding_fingerprint(gspmd, 2)
    assert sharding_cache_fingerprint(named, 2) != sharding_cache_fingerprint(gspmd, 2)


# --------------------------------------------------------------------------- #
# budget manifest semantics (AUD005)
# --------------------------------------------------------------------------- #


def _meas(hbm=1000, coll=500, exe=2000):
    return {
        "peak_hbm_bytes": hbm,
        "collective_bytes": {"dp": coll},
        "executable_bytes": exe,
    }


def test_budget_within_tolerance_passes():
    manifest = manifest_from_measurements({"p": _meas()}, "dp=2", tolerance=0.25)
    assert check_budgets({"p": _meas(hbm=1200)}, manifest) == []


def test_budget_breach_fails_each_metric():
    manifest = manifest_from_measurements({"p": _meas()}, "dp=2", tolerance=0.25)
    for bad in (_meas(hbm=2000), _meas(coll=1000), _meas(exe=4000)):
        violations = check_budgets({"p": bad}, manifest)
        assert len(violations) == 1 and violations[0][0] == "p"


def test_new_program_without_entry_fails():
    manifest = manifest_from_measurements({"p": _meas()}, "dp=2")
    violations = check_budgets({"p": _meas(), "new_hot_path": _meas()}, manifest)
    assert any(name == "new_hot_path" and "no budget-manifest entry" in msg for name, msg in violations)


def test_stale_manifest_entry_fails():
    manifest = manifest_from_measurements({"p": _meas(), "removed": _meas()}, "dp=2")
    violations = check_budgets({"p": _meas()}, manifest, audited=["p"], all_registered=["p"])
    assert any(name == "removed" and "stale" in msg for name, msg in violations)


def test_new_collective_axis_without_budget_fails():
    manifest = manifest_from_measurements({"p": _meas()}, "dp=2")
    m = _meas()
    m["collective_bytes"]["fsdp"] = 4096
    violations = check_budgets({"p": m}, manifest)
    assert any("mesh axis 'fsdp'" in msg for _, msg in violations)


# --------------------------------------------------------------------------- #
# the repo-tree-clean gate: every registered hot path lowers green and the
# checked-in manifest covers all of it (mirrors graft-lint's clean gate)
# --------------------------------------------------------------------------- #


def _cli(args, timeout=560):
    env = {**os.environ, "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")}
    return subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu.analysis", "audit", *args],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env, timeout=timeout,
    )


def test_audit_cli_repo_tree_clean_gate():
    """`python -m sheeprl_tpu.analysis audit` runs green over ALL registered
    hot paths on the CPU sandbox (abstract lowering, no execution), with the
    committed budget manifest covering every program."""
    r = _cli(["--format=json"])
    assert r.returncode == 0, f"stdout={r.stdout[-2000:]}\nstderr={r.stderr[-2000:]}"
    payload = json.loads(r.stdout)
    assert payload["findings"] == []
    assert payload["budgets_checked"] is True
    measured = set(payload["measurements"])
    # the committed manifest and the live registry must agree exactly
    with open(os.path.join(REPO_ROOT, ".graft-audit-budgets.json")) as fh:
        manifest = json.load(fh)
    assert set(manifest["programs"]) == measured
    # the tracecheck hot-path inventory the ISSUE names is all present
    for expected in (
        "ppo.train_step", "ppo.gae", "ppo.rollout_step", "ppo_anakin.block",
        "ppo_anakin_pop.block", "ppo_anakin_pop.block[pbt]",
        "sac.train_step", "sac.resident_step", "sac.rollout_step",
        "ppo_sebulba.train_step", "ppo_sebulba.gae", "ppo_sebulba.act", "ppo_sebulba.traj",
        "sac_sebulba.train_step", "sac_sebulba.act", "sac_sebulba.append",
        "dreamer_v3.burst_step",
        "dreamer_sebulba.train_step", "dreamer_sebulba.act", "dreamer_sebulba.append",
        "serve.bucket[1].greedy", "serve.bucket[8].greedy", "serve.bucket[8].sample",
    ):
        assert expected in measured, f"registered hot path {expected} missing from the audit"


def test_audit_cli_select_and_list_programs():
    r = _cli(["--list-programs"], timeout=120)
    assert r.returncode == 0
    assert "ppo.train_step" in r.stdout
    # a selected slice runs only the matching programs and skips the
    # stale-entry check (it cannot see the whole inventory)
    r2 = _cli(["--select", "ppo.gae", "--format=json"], timeout=300)
    assert r2.returncode == 0, r2.stderr[-2000:]
    payload = json.loads(r2.stdout)
    assert list(payload["measurements"]) == ["ppo.gae"]


def test_audit_cli_select_serve_bucket_literal_and_no_match():
    # `[8]` must match LITERALLY (star-only wildcards — a fnmatch char class
    # would silently select nothing for exactly the serve programs)
    r = _cli(["--select", "serve.bucket[8].greedy", "--format=json"], timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert list(json.loads(r.stdout)["measurements"]) == ["serve.bucket[8].greedy"]
    # a selection matching nothing is a USAGE error, never a green gate
    r2 = _cli(["--select", "ppo.gea"], timeout=120)
    assert r2.returncode == 2
    assert "matched no registered program" in r2.stderr


def test_audit_cli_selected_rebaseline_merges_manifest(tmp_path):
    # a --select re-baseline must keep every unselected program's row
    budgets = tmp_path / "budgets.json"
    seed = {
        "version": 1,
        "mesh": "dp=2",
        "tolerance": 0.25,
        "programs": {"untouched.program": {"peak_hbm_bytes": 1, "collective_bytes": {}, "executable_bytes": 1}},
    }
    budgets.write_text(json.dumps(seed))
    r = _cli(
        ["--select", "ppo.gae", "--write-budgets", "--budgets", str(budgets)], timeout=300
    )
    assert r.returncode == 0, r.stderr[-2000:]
    merged = json.loads(budgets.read_text())
    assert "ppo.gae" in merged["programs"]
    assert "untouched.program" in merged["programs"]


def test_audit_rules_catalog_documented():
    assert set(AUDIT_RULES) == {"AUD000", "AUD001", "AUD002", "AUD003", "AUD004", "AUD005"}
