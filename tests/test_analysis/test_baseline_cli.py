"""Suppression comments, baseline semantics, and the CLI exit-code/format
contract (CI runs `python -m sheeprl_tpu.analysis` and relies on all three)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from sheeprl_tpu.analysis.lint import (
    analyze_source,
    apply_baseline,
    fingerprint,
    load_baseline,
    write_baseline,
)

HAZARD = """
import jax

def f(key):
    a = jax.random.normal(key, (3,))
    b = jax.random.uniform(key, (3,))
    return a + b
"""


def lint(src):
    return analyze_source(textwrap.dedent(src), path="snippet.py")


# --------------------------------------------------------------------------- #
# suppressions
# --------------------------------------------------------------------------- #


def test_inline_disable_specific_rule():
    fs = lint(
        """
        import jax

        def f(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))  # graft-lint: disable=GL001
            return a + b
        """
    )
    assert fs == []


def test_inline_disable_all_rules():
    fs = lint(
        """
        import jax

        def f(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))  # graft-lint: disable
            return a + b
        """
    )
    assert fs == []


def test_inline_disable_wrong_rule_does_not_suppress():
    fs = lint(
        """
        import jax

        def f(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))  # graft-lint: disable=GL007
            return a + b
        """
    )
    assert [f.rule for f in fs] == ["GL001"]


def test_disable_next_line():
    fs = lint(
        """
        import jax

        def f(key):
            a = jax.random.normal(key, (3,))
            # graft-lint: disable-next-line=GL001
            b = jax.random.uniform(key, (3,))
            return a + b
        """
    )
    assert fs == []


def test_disable_multiple_rules_one_comment():
    fs = lint(
        """
        import jax
        import numpy as np

        @jax.jit
        def f(x, key):
            a = jax.random.normal(key, (3,))
            b = np.sum(jax.random.uniform(key, (3,)))  # graft-lint: disable=GL001,GL003
            return a + b + x
        """
    )
    assert fs == []


# --------------------------------------------------------------------------- #
# baseline
# --------------------------------------------------------------------------- #


def test_baseline_roundtrip_and_excess(tmp_path):
    fs = lint(HAZARD)
    assert len(fs) == 1
    path = str(tmp_path / "baseline.json")
    write_baseline(path, fs)
    baseline = load_baseline(path)
    assert baseline == {fingerprint(fs[0]): 1}
    # the baselined finding is filtered...
    assert apply_baseline(fs, baseline) == []
    # ...but a SECOND occurrence of the same fingerprint is reported
    assert apply_baseline(fs + fs, baseline) == fs


def test_baseline_is_line_insensitive():
    fs1 = lint(HAZARD)
    fs2 = lint("\n\n\n" + textwrap.dedent(HAZARD))  # same code, shifted lines
    assert fingerprint(fs1[0]) == fingerprint(fs2[0])
    assert fs1[0].line != fs2[0].line


def test_malformed_baseline_raises(tmp_path):
    path = tmp_path / "bad.json"
    path.write_text(json.dumps({"not_findings": {}}))
    with pytest.raises(ValueError):
        load_baseline(str(path))


# --------------------------------------------------------------------------- #
# CLI contract
# --------------------------------------------------------------------------- #


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cli(args, cwd):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # the analyzer must be runnable from any cwd (CI checks out elsewhere)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env=env,
    )


@pytest.fixture(scope="module")
def hazard_tree(tmp_path_factory):
    root = tmp_path_factory.mktemp("tree")
    (root / "bad.py").write_text(textwrap.dedent(HAZARD))
    (root / "good.py").write_text("import jax\n\ndef g(key):\n    return jax.random.normal(key, (2,))\n")
    return root


def test_cli_exit_1_on_findings_text(hazard_tree):
    r = _cli(["bad.py", "--no-baseline"], cwd=hazard_tree)
    assert r.returncode == 1
    assert "GL001" in r.stdout
    assert "1 finding(s)" in r.stderr


def test_cli_exit_0_on_clean(hazard_tree):
    r = _cli(["good.py", "--no-baseline"], cwd=hazard_tree)
    assert r.returncode == 0
    assert r.stdout == ""


def test_cli_json_format(hazard_tree):
    r = _cli(["bad.py", "--no-baseline", "--format=json"], cwd=hazard_tree)
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert payload["tool"] == "graft-lint"
    assert payload["findings"][0]["rule"] == "GL001"
    assert payload["findings"][0]["fingerprint"]


def test_cli_github_format(hazard_tree):
    r = _cli(["bad.py", "--no-baseline", "--format=github"], cwd=hazard_tree)
    assert r.returncode == 1
    assert r.stdout.startswith("::error file=bad.py,line=")
    assert "title=graft-lint GL001" in r.stdout


def test_cli_write_baseline_then_clean(hazard_tree):
    r = _cli(["bad.py", "--write-baseline", "--baseline", "bl.json"], cwd=hazard_tree)
    assert r.returncode == 0
    r2 = _cli(["bad.py", "--baseline", "bl.json"], cwd=hazard_tree)
    assert r2.returncode == 0
    assert "1 baselined" in r2.stderr
    # ignoring the baseline resurfaces it
    r3 = _cli(["bad.py", "--baseline", "bl.json", "--no-baseline"], cwd=hazard_tree)
    assert r3.returncode == 1


def test_cli_select_ignore(hazard_tree):
    r = _cli(["bad.py", "--no-baseline", "--select", "GL002"], cwd=hazard_tree)
    assert r.returncode == 0
    r2 = _cli(["bad.py", "--no-baseline", "--ignore", "GL001"], cwd=hazard_tree)
    assert r2.returncode == 0


def test_cli_syntax_error_surfaces_even_under_select(hazard_tree):
    # a file the analyzer cannot parse is fully unanalyzed; --select must not
    # make it look clean
    (hazard_tree / "broken.py").write_text("def f(:\n")
    r = _cli(["broken.py", "--no-baseline", "--select", "GL001"], cwd=hazard_tree)
    assert r.returncode == 1
    assert "GL000" in r.stdout


def test_cli_unwritable_baseline_exit_2(hazard_tree):
    r = _cli(["bad.py", "--write-baseline", "--baseline", "no/such/dir/b.json"], cwd=hazard_tree)
    assert r.returncode == 2
    assert "cannot write baseline" in r.stderr


def test_cli_unknown_rule_exit_2(hazard_tree):
    r = _cli(["bad.py", "--select", "GL999"], cwd=hazard_tree)
    assert r.returncode == 2


def test_cli_list_rules(hazard_tree):
    r = _cli(["--list-rules"], cwd=hazard_tree)
    assert r.returncode == 0
    for rule in ("GL001", "GL002", "GL003", "GL004", "GL005", "GL006", "GL007"):
        assert rule in r.stdout


def test_repo_tree_is_clean_or_baselined():
    """The acceptance gate: the merged tree lints clean against the checked-in
    baseline (which this PR ships EMPTY — new findings need inline disables
    with a reason, not baseline growth)."""
    r = _cli(["sheeprl_tpu"], cwd=REPO_ROOT)
    assert r.returncode == 0, f"graft-lint found new issues:\n{r.stdout}\n{r.stderr}"
