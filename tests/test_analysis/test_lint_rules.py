"""Per-rule fixtures: every GL rule must FIRE on its hazard and stay QUIET on
the idiomatic counterpart (the precision bar that keeps the baseline empty)."""

import textwrap

from sheeprl_tpu.analysis.lint import analyze_source


def lint(src):
    return analyze_source(textwrap.dedent(src), path="snippet.py")


def rules_of(findings):
    return sorted(f.rule for f in findings)


# --------------------------------------------------------------------------- #
# GL001 — RNG key reuse
# --------------------------------------------------------------------------- #


def test_gl001_fires_on_double_sample():
    fs = lint(
        """
        import jax

        def f(key):
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            return a + b
        """
    )
    assert rules_of(fs) == ["GL001"]


def test_gl001_fires_on_use_after_split():
    fs = lint(
        """
        import jax

        def f(key):
            k1, k2 = jax.random.split(key)
            return jax.random.normal(key, (3,))
        """
    )
    assert rules_of(fs) == ["GL001"]


def test_gl001_fires_on_reuse_across_loop_iterations():
    fs = lint(
        """
        import jax

        def f(key, n):
            out = []
            for i in range(n):
                out.append(jax.random.normal(key, (3,)))
            return out
        """
    )
    assert "GL001" in rules_of(fs)


def test_gl001_quiet_on_split_and_carry():
    fs = lint(
        """
        import jax

        def f(key, n):
            out = []
            for i in range(n):
                key, sub = jax.random.split(key)
                out.append(jax.random.normal(sub, (3,)))
            return out
        """
    )
    assert fs == []


def test_gl001_quiet_on_fold_in_derive():
    # fold_in is the sanctioned multi-derive: same base key, distinct data
    fs = lint(
        """
        import jax

        def f(key, n):
            return [jax.random.normal(jax.random.fold_in(key, i), (3,)) for i in range(n)]
        """
    )
    assert fs == []


def test_gl001_quiet_on_exclusive_branches():
    # the `if prioritized:` pattern in sac.make_resident_train_step: one key,
    # two exclusive consumers
    fs = lint(
        """
        import jax

        def f(key, flag):
            if flag:
                x = jax.random.uniform(key, (3,))
            else:
                x = jax.random.normal(key, (3,))
            return x
        """
    )
    assert fs == []


def test_gl001_quiet_when_branch_returns():
    # dreamer_v2.add_exploration_noise: the consuming branch returns, so the
    # later consumption never sees the spent key
    fs = lint(
        """
        import jax

        def f(key, cont):
            if cont:
                return jax.random.normal(key, (3,))
            keys = jax.random.split(key, 4)
            return keys
        """
    )
    assert fs == []


def test_gl001_keyword_key_argument():
    fs = lint(
        """
        import jax

        def f(key):
            a = jax.random.normal(key=key, shape=(3,))
            b = jax.random.normal(key=key, shape=(3,))
            return a + b
        """
    )
    assert rules_of(fs) == ["GL001"]


# --------------------------------------------------------------------------- #
# GL002 — host syncs in jit-reachable code
# --------------------------------------------------------------------------- #


def test_gl002_fires_on_item_inside_jit():
    fs = lint(
        """
        import jax

        @jax.jit
        def f(x):
            return x.sum().item()
        """
    )
    assert rules_of(fs) == ["GL002"]


def test_gl002_fires_on_float_cast_of_traced():
    fs = lint(
        """
        import jax

        @jax.jit
        def f(x):
            return float(x.mean())
        """
    )
    assert rules_of(fs) == ["GL002"]


def test_gl002_fires_on_np_asarray_in_scan_body():
    fs = lint(
        """
        import jax
        import numpy as np

        def outer(xs):
            def body(carry, x):
                return carry, np.asarray(x)
            return jax.lax.scan(body, 0, xs)
        """
    )
    assert rules_of(fs) == ["GL002"]


def test_gl002_quiet_on_host_code():
    # .item()/float() outside jit-reachable code is normal host logging
    fs = lint(
        """
        def log_loss(loss):
            return float(loss.mean().item())
        """
    )
    assert fs == []


def test_gl002_quiet_on_static_config_float():
    fs = lint(
        """
        import jax

        @jax.jit
        def f(x, cfg_value=None):
            scale = float(3.5)
            return x * scale
        """
    )
    assert fs == []


# --------------------------------------------------------------------------- #
# GL003 — np. on traced values where jnp is required
# --------------------------------------------------------------------------- #


def test_gl003_fires_on_np_op_in_jit():
    fs = lint(
        """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.sum(x)
        """
    )
    assert rules_of(fs) == ["GL003"]


def test_gl003_quiet_on_np_over_static_shape():
    # np on STATIC metadata (tracer .shape is a python tuple) is idiomatic
    fs = lint(
        """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            n = int(np.prod(x.shape))
            return x.reshape(n)
        """
    )
    assert fs == []


def test_gl003_quiet_on_jnp():
    fs = lint(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.sum(x)
        """
    )
    assert fs == []


# --------------------------------------------------------------------------- #
# GL004 — Python control flow on traced values
# --------------------------------------------------------------------------- #


def test_gl004_fires_on_if_traced():
    fs = lint(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            s = jnp.sum(x)
            if s > 0:
                return x
            return -x
        """
    )
    assert rules_of(fs) == ["GL004"]


def test_gl004_fires_on_while_traced():
    fs = lint(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            n = jnp.sum(x)
            while n > 0:
                n = n - 1
            return n
        """
    )
    assert rules_of(fs) == ["GL004"]


def test_gl004_fires_on_for_over_traced_subscript():
    fs = lint(
        """
        import jax

        @jax.jit
        def f(batch):
            total = 0
            for row in batch["obs"]:
                total = total + row
            return total
        """
    )
    assert rules_of(fs) == ["GL004"]


def test_gl004_quiet_on_static_flag_param():
    # `if greedy:` where greedy is an unmodified (static) parameter
    fs = lint(
        """
        import jax

        @jax.jit
        def f(x, greedy):
            if greedy:
                return x
            return -x
        """
    )
    assert fs == []


def test_gl004_quiet_on_static_argnums():
    fs = lint(
        """
        import jax

        def _step(x, greedy, expl):
            if not greedy and expl > 0.0:
                return x * expl
            return x

        step_fn = jax.jit(_step, static_argnums=(1, 2))
        """
    )
    assert fs == []


def test_gl004_quiet_on_config_attribute():
    fs = lint(
        """
        import jax

        @jax.jit
        def f(x, actor):
            if actor.is_continuous:
                return x
            return -x
        """
    )
    assert fs == []


def test_gl004_quiet_on_none_and_isinstance_guards():
    fs = lint(
        """
        import jax

        @jax.jit
        def f(x, mask, amount):
            if mask is not None and not isinstance(amount, float):
                return x
            if isinstance(amount, (int, float)) and amount <= 0.0:
                return -x
            return x
        """
    )
    assert fs == []


def test_gl004_quiet_on_zip_unroll():
    # static unrolling over python lists of arrays is idiomatic jax
    fs = lint(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(dists, keys):
            return [d + k for d, k in zip(dists, keys)]
        """
    )
    assert fs == []


def test_gl004_quiet_on_dict_iteration():
    fs = lint(
        """
        import jax

        @jax.jit
        def f(storage, idx):
            return {k: storage[k][idx] for k in storage}
        """
    )
    assert fs == []


# --------------------------------------------------------------------------- #
# GL005 — read-after-donate
# --------------------------------------------------------------------------- #


def test_gl005_fires_on_read_after_donating_call():
    fs = lint(
        """
        import jax

        def train(step, params, opt, data):
            step_fn = jax.jit(step, donate_argnums=(0, 1))
            new_params, new_opt = step_fn(params, opt, data)
            return params["w"]  # donated buffer!
        """
    )
    assert rules_of(fs) == ["GL005"]


def test_gl005_fires_on_donate_argnames():
    fs = lint(
        """
        import jax

        def train(step, params, opt, data):
            step_fn = jax.jit(step, donate_argnames=("params",))
            new_params = step_fn(data, params=params)
            return params["w"]  # donated by name!
        """
    )
    assert rules_of(fs) == ["GL005"]


def test_gl005_quiet_on_rebind():
    fs = lint(
        """
        import jax

        def train(step, params, opt, data):
            step_fn = jax.jit(step, donate_argnums=(0, 1))
            params, opt = step_fn(params, opt, data)
            return params["w"]  # rebound to the NEW buffers: fine
        """
    )
    assert fs == []


def test_gl005_quiet_without_donation():
    fs = lint(
        """
        import jax

        def train(step, params, opt, data):
            step_fn = jax.jit(step)
            new_params, new_opt = step_fn(params, opt, data)
            return params["w"]
        """
    )
    assert fs == []


# --------------------------------------------------------------------------- #
# GL006 — dict-ordering-sensitive pytrees
# --------------------------------------------------------------------------- #


def test_gl006_fires_on_dictcomp_over_set():
    fs = lint(
        """
        def build(keys_a, keys_b):
            return {k: 0.0 for k in set(keys_a) & set(keys_b)}
        """
    )
    assert rules_of(fs) == ["GL006"]


def test_gl006_fires_on_cross_object_zip():
    fs = lint(
        """
        def build(a, b):
            return dict(zip(a.keys(), b.values()))
        """
    )
    assert rules_of(fs) == ["GL006"]


def test_gl006_quiet_on_sorted_and_same_object():
    fs = lint(
        """
        def build(keys_a, keys_b, a):
            x = {k: 0.0 for k in sorted(set(keys_a) & set(keys_b))}
            y = dict(zip(a.keys(), a.values()))
            return x, y
        """
    )
    assert fs == []


# --------------------------------------------------------------------------- #
# GL007 — PRNGKey in a loop
# --------------------------------------------------------------------------- #


def test_gl007_fires_on_key_in_loop():
    fs = lint(
        """
        import jax

        def f(seed, n):
            out = []
            for i in range(n):
                k = jax.random.PRNGKey(seed + i)
                out.append(jax.random.normal(k, (3,)))
            return out
        """
    )
    assert "GL007" in rules_of(fs)


def test_gl007_quiet_outside_loop():
    fs = lint(
        """
        import jax

        def f(seed):
            key = jax.random.PRNGKey(seed)
            return jax.random.normal(key, (3,))
        """
    )
    assert fs == []


# --------------------------------------------------------------------------- #
# jit-reachability edges
# --------------------------------------------------------------------------- #


def test_reachability_via_decorator_partial():
    fs = lint(
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnums=(1,))
        def f(x, n):
            return x.sum().item()
        """
    )
    assert rules_of(fs) == ["GL002"]


def test_reachability_via_shard_map_edge():
    # the repo idiom: local fn -> shard_map(...) -> jax.jit
    fs = lint(
        """
        import jax
        from sheeprl_tpu.parallel.compat import shard_map

        def make(mesh, spec):
            def local_train(x):
                return x.sum().item()

            return jax.jit(shard_map(local_train, mesh=mesh, in_specs=spec, out_specs=spec))
        """
    )
    assert rules_of(fs) == ["GL002"]


def test_reachability_via_call_graph():
    # helper called FROM a jitted function is jit-reachable transitively
    fs = lint(
        """
        import jax

        def helper(x):
            return x.sum().item()

        @jax.jit
        def f(x):
            return helper(x)
        """
    )
    assert rules_of(fs) == ["GL002"]


def test_reachability_via_collective_body():
    # lax.pmean is only legal under a mapped trace: body is trace context
    fs = lint(
        """
        import jax

        def local_train(grads):
            g = jax.lax.pmean(grads, "dp")
            return g.sum().item()
        """
    )
    assert rules_of(fs) == ["GL002"]


def test_unreachable_host_function_stays_quiet():
    fs = lint(
        """
        import numpy as np

        def stage(batch):
            return {k: np.asarray(v) for k, v in batch.items()}
        """
    )
    assert fs == []


def test_scan_body_reachable_without_jit():
    # lax.scan traces its body even outside jit
    fs = lint(
        """
        import jax
        import numpy as np

        def run(xs):
            def body(c, x):
                return c, np.sum(x)
            return jax.lax.scan(body, 0, xs)
        """
    )
    assert rules_of(fs) == ["GL003"]


# --------------------------------------------------------------------------- #
# GL008 — donating jit over sharded shard_map outputs without pinned
# out_shardings (the PR 8 silent-recompile shape)
# --------------------------------------------------------------------------- #


def test_gl008_fires_on_direct_sharded_donating_jit():
    fs = lint(
        """
        import jax
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def make(mesh):
            def body(x, p):
                return x * 2, p

            st = shard_map(body, mesh=mesh, in_specs=(P("dp"), P()), out_specs=(P("dp"), P()))
            return jax.jit(st, donate_argnums=(0,))
        """
    )
    assert rules_of(fs) == ["GL008"]


def test_gl008_fires_through_wrapper_and_conditional_spec():
    # the resident-ring idiom: spec = P(None, "dp") if cond else P(); a
    # wrapper unpacks the shard_map tuple, rebuilds a dict, and returns it
    fs = lint(
        """
        import jax
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def make(mesh, shard_envs):
            spec = P(None, "dp") if shard_envs else P()

            def body(s, b):
                return s, b.sum()

            st = shard_map(body, mesh=mesh, in_specs=(spec, P()), out_specs=(spec, P()))

            def packed(state, blob):
                storage, tot = st(state["storage"], blob)
                new_state = {"storage": storage}
                return new_state, tot

            return jax.jit(packed, donate_argnums=(0,))
        """
    )
    assert rules_of(fs) == ["GL008"]


def test_gl008_fires_on_conditional_donation():
    # `donate_argnums=(0,) if donate else ()` must be treated as donating
    fs = lint(
        """
        import jax
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def make(mesh, donate):
            def body(x):
                return x * 2

            st = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
            return jax.jit(st, donate_argnums=(0,) if donate else ())
        """
    )
    assert rules_of(fs) == ["GL008"]


def test_gl008_quiet_on_replicated_out_specs():
    fs = lint(
        """
        import jax
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def make(mesh):
            def body(x, p):
                return x.sum(), p

            st = shard_map(body, mesh=mesh, in_specs=(P("dp"), P()), out_specs=(P(), P()))
            return jax.jit(st, donate_argnums=(0,))
        """
    )
    assert rules_of(fs) == []


def test_gl008_quiet_when_pinned():
    fs = lint(
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def make(mesh):
            def body(x, p):
                return x * 2, p

            st = shard_map(body, mesh=mesh, in_specs=(P("dp"), P()), out_specs=(P("dp"), P()))
            out = NamedSharding(mesh, P("dp"))
            return jax.jit(st, donate_argnums=(0,), out_shardings=(out, None))
        """
    )
    assert rules_of(fs) == []


def test_gl008_quiet_without_donation():
    fs = lint(
        """
        import jax
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def make(mesh):
            def body(x, p):
                return x * 2, p

            st = shard_map(body, mesh=mesh, in_specs=(P("dp"), P()), out_specs=(P("dp"), P()))
            return jax.jit(st)
        """
    )
    assert rules_of(fs) == []


def test_gl008_sharded_factory_does_not_indict_replicated_neighbor():
    # name maps are frame-scoped: `st` sharded in one factory must not make
    # the other factory's replicated `st` fire
    fs = lint(
        """
        import jax
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def make_sharded(mesh):
            def body(x):
                return x * 2

            st = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
            out = __import__("jax").sharding.NamedSharding(mesh, P("dp"))
            return jax.jit(st, donate_argnums=(0,), out_shardings=out)

        def make_replicated(mesh):
            def body(x):
                return x.sum()

            st = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P())
            return jax.jit(st, donate_argnums=(0,))
        """
    )
    assert rules_of(fs) == []


def test_gl008_suppressible():
    fs = lint(
        """
        import jax
        from jax.sharding import PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        def make(mesh):
            def body(x):
                return x * 2

            st = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
            return jax.jit(st, donate_argnums=(0,))  # graft-lint: disable=GL008
        """
    )
    assert rules_of(fs) == []
