"""Runtime sentinel semantics: retrace budgets, warmup, transfer guard,
report merging, and the trace-event ledger the comm wire-dtype guard rides."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.analysis.tracecheck import RetraceError, TraceCheck


@pytest.fixture()
def tc():
    t = TraceCheck()
    t.configure(mode="strict", transfer_guard=False)
    return t


def test_single_signature_never_trips(tc):
    f = tc.instrument(jax.jit(lambda x: x * 2), name="f")
    for _ in range(5):
        f(jnp.ones((4,)))
    rep = tc.report()["f"]
    assert rep["calls"] == 5
    assert rep["compiles"] == 1
    assert rep["post_warmup_compiles"] == 0
    assert tc.post_warmup_retraces() == {}


def test_budget_trip_on_post_warmup_shape_drift(tc):
    f = tc.instrument(jax.jit(lambda x: x * 2), name="f", warmup=1, budget=0)
    f(jnp.ones((4,)))  # warmup compile: free
    with pytest.raises(RetraceError, match="retraced after warmup"):
        f(jnp.ones((5,)))  # shape drift -> second compile -> trip


def test_budget_tolerates_declared_variants(tc):
    # budget=1: one legitimate post-warmup variant (e.g. a remainder batch)
    f = tc.instrument(jax.jit(lambda x: x * 2), name="f", warmup=1, budget=1)
    f(jnp.ones((4,)))
    f(jnp.ones((5,)))  # within budget
    with pytest.raises(RetraceError):
        f(jnp.ones((6,)))  # exceeds it


def test_warmup_covers_deliberate_variants(tc):
    f = tc.instrument(jax.jit(lambda x: x * 2), name="f", warmup=3, budget=0)
    f(jnp.ones((4,)))
    f(jnp.ones((5,)))
    f(jnp.ones((6,)))  # all inside warmup
    f(jnp.ones((4,)))  # cached
    assert tc.report()["f"]["post_warmup_compiles"] == 0


def test_weak_type_drift_is_a_retrace(tc):
    # the classic: a python float arg traces weakly-typed, a jnp scalar does
    # not — flipping between them recompiles
    f = tc.instrument(jax.jit(lambda x, s: x * s), name="f", warmup=1, budget=0)
    f(jnp.ones((4,)), jnp.float32(0.5))
    with pytest.raises(RetraceError):
        f(jnp.ones((4,)), 0.5)


def test_warn_mode_warns_instead_of_raising(tc):
    tc.configure(mode="warn")
    f = tc.instrument(jax.jit(lambda x: x * 2), name="f", warmup=1, budget=0)
    f(jnp.ones((4,)))
    with pytest.warns(RuntimeWarning, match="retraced after warmup"):
        f(jnp.ones((5,)))


def test_off_mode_is_passthrough(tc):
    tc.configure(mode="off")
    f = tc.instrument(jax.jit(lambda x: x * 2), name="f")
    f(jnp.ones((4,)))
    f(jnp.ones((5,)))
    assert tc.report()["f"]["calls"] == 0  # nothing recorded


def test_transfer_guard_blocks_post_warmup_numpy(tc):
    tc.configure(transfer_guard=True)
    f = tc.instrument(jax.jit(lambda x: x + 1), name="f", warmup=1)
    f(np.ones((4,), np.float32))  # warmup: implicit transfer tolerated
    with pytest.raises(Exception, match="[Dd]isallowed host-to-device"):
        f(np.ones((4,), np.float32))  # steady state: an error


def test_transfer_guard_allows_device_args(tc):
    tc.configure(transfer_guard=True)
    f = tc.instrument(jax.jit(lambda x: x + 1), name="f", warmup=1)
    x = jax.device_put(np.ones((4,), np.float32))
    f(x)
    f(x)  # post-warmup, on-device: fine
    assert tc.post_warmup_retraces() == {}


def test_transfer_guard_per_entry_opt_out(tc):
    tc.configure(transfer_guard=True)
    f = tc.instrument(jax.jit(lambda x: x + 1), name="rollout", warmup=1, transfer_guard=False)
    # host inputs by contract: never guarded
    f(np.ones((4,), np.float32))
    f(np.ones((4,), np.float32))
    assert tc.report()["rollout"]["calls"] == 2


def test_report_merges_same_name_across_runs(tc):
    # two "runs" instrument the same logical entry point
    f1 = tc.instrument(jax.jit(lambda x: x * 2), name="train_step")
    f1(jnp.ones((4,)))
    f2 = tc.instrument(jax.jit(lambda x: x * 3), name="train_step")
    f2(jnp.ones((4,)))
    rep = tc.report()["train_step"]
    assert rep["calls"] == 2
    assert rep["compiles"] == 2
    assert rep["post_warmup_compiles"] == 0  # each run's first call is its warmup


def test_instrument_transparent_to_donation(tc):
    f = tc.instrument(jax.jit(lambda x: x + 1, donate_argnums=(0,)), name="f")
    x = jax.device_put(jnp.ones((4,)))
    y = f(x)
    assert x.is_deleted()  # donation still happened through the wrapper
    np.testing.assert_allclose(np.asarray(y), 2.0)


def test_non_jit_callable_falls_back_to_signature_tracking(tc):
    # no _cache_size on a plain python fn: distinct abstract signatures count
    calls = []

    def f(x):
        calls.append(x.shape)
        return x

    g = tc.instrument(f, name="g", warmup=1, budget=0)
    g(jnp.ones((4,)))
    with pytest.raises(RetraceError):
        g(jnp.ones((5,)))


def test_thread_safety_under_concurrent_callers(tc):
    tc.configure(mode="strict")
    f = tc.instrument(jax.jit(lambda x: x * 2), name="f", warmup=8)
    errs = []

    def worker():
        try:
            for _ in range(20):
                f(jnp.ones((4,)))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errs == []
    rep = tc.report()["f"]
    assert rep["calls"] == 80
    assert rep["post_warmup_compiles"] == 0


def test_event_ledger(tc):
    tc.record_event("tag", "a")
    tc.record_event("tag", "b")
    assert tc.events("tag") == ["a", "b"]
    assert tc.events("other") == []
    tc.clear_events("tag")
    assert tc.events("tag") == []


def test_reset_clears_entries_and_events(tc):
    f = tc.instrument(jax.jit(lambda x: x), name="f")
    f(jnp.ones((2,)))
    tc.record_event("tag", 1)
    tc.reset()
    assert tc.report() == {}
    assert tc.events("tag") == []


def test_configure_rejects_bad_mode(tc):
    with pytest.raises(ValueError):
        tc.configure(mode="loud")


def test_comm_wire_guard_rides_the_ledger():
    """The PR-3 grad_reduce_dtype retrace guard is now tracecheck-backed:
    tracing pmean_grads records an event, and a mid-run dtype flip warns."""
    from sheeprl_tpu.analysis.tracecheck import tracecheck as global_tc
    from sheeprl_tpu.parallel.comm import _WIRE_TAG, pmean_grads, set_grad_reduce_dtype

    set_grad_reduce_dtype("bfloat16", fresh_run=True)
    assert global_tc.events(_WIRE_TAG) == []

    def reduce_under_shmap():
        mesh = jax.sharding.Mesh(np.array(jax.devices("cpu")[:2]), ("dp",))
        from jax.sharding import PartitionSpec as P

        from sheeprl_tpu.parallel.compat import shard_map

        f = shard_map(
            lambda g: pmean_grads(g, "dp"), mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False,
        )
        return jax.jit(f)(jnp.ones((2, 4)))

    reduce_under_shmap()
    assert len(global_tc.events(_WIRE_TAG)) >= 1  # trace recorded its dtype
    with pytest.warns(UserWarning, match="grad_reduce_dtype changed"):
        set_grad_reduce_dtype("float32")  # mid-run flip
    set_grad_reduce_dtype("float32", fresh_run=True)  # leave clean state


# --------------------------------------------------------------------------- #
# JSON dump artifact (SHEEPRL_TPU_TRACECHECK_DUMP / bench lanes / the
# `python -m sheeprl_tpu.analysis tracecheck <path>` validator)
# --------------------------------------------------------------------------- #


def test_dump_payload_and_file_round_trip(tc, tmp_path):
    import json

    f = tc.instrument(jax.jit(lambda x: x * 2), name="hot", warmup=1, budget=0)
    f(jnp.ones((4,)))
    f(jnp.ones((4,)))
    tc.record_event("wire_dtype", "bfloat16")
    path = tmp_path / "ledger.json"
    payload = tc.dump(str(path))
    assert payload["entries"]["hot"]["compiles"] == 1
    assert payload["post_warmup_retraces"] == {}
    assert payload["events"]["wire_dtype"] == ["'bfloat16'"]
    on_disk = json.loads(path.read_text())
    assert on_disk == payload


def test_dump_cli_validator_exit_contract(tc, tmp_path):
    import subprocess
    import sys

    clean = tc.instrument(jax.jit(lambda x: x * 2), name="clean", warmup=2, budget=0)
    clean(jnp.ones((4,)))
    path = tmp_path / "ok.json"
    tc.dump(str(path))
    r = subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu.analysis", "tracecheck", str(path)],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr

    # a hot path over its post-warmup budget must fail the validator
    tc.configure(mode="warn")
    bad = tc.instrument(jax.jit(lambda x: x * 3), name="bad", warmup=1, budget=0)
    with pytest.warns(RuntimeWarning):
        bad(jnp.ones((4,)))
        bad(jnp.ones((5,)))  # post-warmup retrace
    path2 = tmp_path / "bad.json"
    tc.dump(str(path2))
    r2 = subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu.analysis", "tracecheck", str(path2)],
        capture_output=True, text=True,
    )
    assert r2.returncode == 1
    assert "RETRACE bad" in r2.stdout


def test_dump_env_var_registers_atexit_export(tmp_path):
    import json
    import os
    import subprocess
    import sys
    import textwrap

    path = tmp_path / "exit.json"
    code = textwrap.dedent(
        """
        import jax, jax.numpy as jnp
        # the process-wide singleton reads the env at construction and
        # registers the atexit export (a fresh TraceCheck would register a
        # SECOND atexit dump to the same path and race it)
        from sheeprl_tpu.analysis.tracecheck import tracecheck
        f = tracecheck.instrument(jax.jit(lambda x: x + 1), name="exit_hot")
        f(jnp.ones((2,)))
        """
    )
    env = {**os.environ, "SHEEPRL_TPU_TRACECHECK_DUMP": str(path), "JAX_PLATFORMS": "cpu"}
    r = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    payload = json.loads(path.read_text())
    assert payload["entries"]["exit_hot"]["calls"] == 1
