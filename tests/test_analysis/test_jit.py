"""graft-jit tier tests: planted-hazard fire/quiet pairs for every GJ rule
(incl. scan-carry key threading, vmap'd key axes staying quiet, np.* on
host-only values staying quiet), interprocedural tracedness propagation,
suppression + stale-suppression semantics, CLI-contract checks, and the
repo-tree-clean gates (the shipped baseline is EMPTY by policy — real
findings get fixed, suppressions carry inline justifications)."""

import json
import textwrap
from pathlib import Path

import pytest

from sheeprl_tpu.analysis.__main__ import main as analysis_main
from sheeprl_tpu.analysis.jit import (
    JIT_RULES,
    analyze_jit_sources,
    analyze_source_jit,
)

REPO_ROOT = Path(__file__).parents[2]


def rules_of(findings):
    return [f.rule for f in findings]


def src(code: str) -> str:
    return textwrap.dedent(code)


# --------------------------------------------------------------------------- #
# GJ001 — PRNG key dataflow
# --------------------------------------------------------------------------- #


def test_gj001_key_reuse_fires():
    code = src(
        """
        import jax

        @jax.jit
        def step(key, x):
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))
            return a + b
        """
    )
    findings = analyze_source_jit(code)
    assert rules_of(findings) == ["GJ001"]
    assert "already spent" in findings[0].message


def test_gj001_aliased_reuse_fires():
    # value numbering: an alias shares the key id, so spending the alias
    # after the original is the same reuse graft-lint's name-based GL001
    # cannot see
    code = src(
        """
        import jax

        @jax.jit
        def step(key):
            k2 = key
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(k2, (4,))
            return a + b
        """
    )
    assert rules_of(analyze_source_jit(code)) == ["GJ001"]


def test_gj001_split_then_consume_quiet():
    code = src(
        """
        import jax

        @jax.jit
        def step(key):
            key, sub = jax.random.split(key)
            a = jax.random.normal(sub, (4,))
            key, sub2 = jax.random.split(key)
            b = jax.random.uniform(sub2, (4,))
            return a + b
        """
    )
    assert analyze_source_jit(code) == []


def test_gj001_fold_in_derivation_quiet():
    # fold_in DERIVES a child stream, it does not spend the parent
    code = src(
        """
        import jax

        @jax.jit
        def step(key, n):
            a = jax.random.normal(jax.random.fold_in(key, 0), (4,))
            sub = jax.random.fold_in(key, 1)
            b = jax.random.uniform(sub, (4,))
            return a + b
        """
    )
    assert analyze_source_jit(code) == []


def test_gj001_discarded_split_fires():
    code = src(
        """
        import jax

        @jax.jit
        def step(key):
            jax.random.split(key)
            return key
        """
    )
    findings = analyze_source_jit(code)
    assert rules_of(findings) == ["GJ001"]
    assert "discarded" in findings[0].message


def test_gj001_burn_key_idiom_quiet():
    # `rng, _ = split(rng)` deliberately advances the stream — the split
    # result IS bound; only a wholly-discarded split fires
    code = src(
        """
        import jax

        @jax.jit
        def step(rng):
            rng, _ = jax.random.split(rng)
            return jax.random.normal(rng, (4,))
        """
    )
    assert analyze_source_jit(code) == []


def test_gj001_scan_carry_stale_fires():
    code = src(
        """
        import jax
        from jax import lax

        def body(carry, x):
            key, acc = carry[0], carry[1]
            n = jax.random.normal(key, (2,))
            return (key, acc + n), n

        def run(key, xs):
            out, _ = lax.scan(body, (key, 0.0), xs)
            return out
        """
    )
    findings = analyze_source_jit(code)
    assert rules_of(findings) == ["GJ001"]
    assert "carry" in findings[0].message and findings[0].function == "body"


def test_gj001_scan_carry_threaded_quiet():
    code = src(
        """
        import jax
        from jax import lax

        def body(carry, x):
            key, acc = carry
            key, sub = jax.random.split(key)
            n = jax.random.normal(sub, (2,))
            return (key, acc + n), n

        def run(key, xs):
            out, _ = lax.scan(body, (key, 0.0), xs)
            return out
        """
    )
    assert analyze_source_jit(code) == []


def test_gj001_fori_loop_carry_stale_fires():
    # fori_loop's body is (i, carry) — the carry is parameter 1
    code = src(
        """
        import jax
        from jax import lax

        def body(i, key):
            x = jax.random.normal(key, (2,))
            return key

        def run(key):
            return lax.fori_loop(0, 4, body, key)
        """
    )
    assert rules_of(analyze_source_jit(code)) == ["GJ001"]


def test_gj001_const_key_in_traced_fires_host_quiet():
    code = src(
        """
        import jax

        @jax.jit
        def traced(x):
            k = jax.random.PRNGKey(0)
            return jax.random.normal(k, x.shape)

        def host_seeding(cfg):
            return jax.random.PRNGKey(42)
        """
    )
    findings = analyze_source_jit(code)
    assert rules_of(findings) == ["GJ001"]
    assert findings[0].function == "traced"


def test_gj001_vmapped_key_axis_quiet():
    # a per-env key function under vmap with proper splitting stays quiet
    code = src(
        """
        import jax

        def per_env(key, obs):
            key, sub = jax.random.split(key)
            a = jax.random.categorical(sub, obs)
            return key, a

        batched = jax.vmap(per_env)
        """
    )
    assert analyze_source_jit(code) == []


# --------------------------------------------------------------------------- #
# GJ002 — host sync inside traced code
# --------------------------------------------------------------------------- #


def test_gj002_item_and_casts_fire():
    code = src(
        """
        import jax

        @jax.jit
        def step(x):
            a = x.item()
            b = float(x)
            c = int(x)
            return a + b + c
        """
    )
    assert rules_of(analyze_source_jit(code)) == ["GJ002", "GJ002", "GJ002"]


def test_gj002_numpy_on_tracer_fires():
    code = src(
        """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return np.mean(x)
        """
    )
    findings = analyze_source_jit(code)
    assert rules_of(findings) == ["GJ002"]
    assert "np.mean" in findings[0].message


def test_gj002_numpy_on_host_values_quiet():
    # np.* on concrete host values — module scope, host functions, and
    # trace-time constants inside a traced fn — is legal
    code = src(
        """
        import jax
        import numpy as np

        TABLE = np.arange(10)

        def host_stats(path):
            return np.mean(np.arange(100))

        @jax.jit
        def step(x):
            scale = np.float32(2.0)
            return x * scale
        """
    )
    assert analyze_source_jit(code) == []


def test_gj002_device_get_and_print_fire():
    code = src(
        """
        import jax

        @jax.jit
        def step(x):
            y = jax.device_get(x)
            print(x)
            return y
        """
    )
    assert rules_of(analyze_source_jit(code)) == ["GJ002", "GJ002"]


def test_gj002_print_of_static_quiet():
    code = src(
        """
        import jax

        @jax.jit
        def step(x):
            print("tracing step")
            return x + 1
        """
    )
    assert analyze_source_jit(code) == []


# --------------------------------------------------------------------------- #
# interprocedural tracedness (the corpus model)
# --------------------------------------------------------------------------- #


def test_cross_module_taint_propagates():
    # a helper in another module called WITH a traced argument is analyzed
    # as traced — the finding lands in the helper's file
    mod_a = src(
        """
        import jax
        from pkg import helpers

        @jax.jit
        def step(x):
            return helpers.loss(x)
        """
    )
    mod_b = src(
        """
        import numpy as np

        def loss(x):
            return np.mean(x)
        """
    )
    findings = analyze_jit_sources([(mod_a, "pkg/a.py"), (mod_b, "pkg/helpers.py")])
    assert rules_of(findings) == ["GJ002"]
    assert findings[0].path == "pkg/helpers.py"


def test_static_only_call_does_not_propagate():
    # a helper called only with STATIC arguments runs on concrete host
    # values at trace time — np.* there is legal and must stay quiet
    mod_a = src(
        """
        import jax
        from pkg import helpers

        @jax.jit
        def step(x, cfg):
            scale = helpers.make_scale(cfg)
            return x * scale
        """
    )
    mod_b = src(
        """
        import numpy as np

        def make_scale(cfg):
            return np.float32(np.mean([1.0, 2.0]))
        """
    )
    assert analyze_jit_sources([(mod_a, "pkg/a.py"), (mod_b, "pkg/helpers.py")]) == []


def test_self_method_propagation():
    code = src(
        """
        import jax
        import numpy as np

        class Agent:
            def act(self, obs):
                return self._postprocess(obs)

            def _postprocess(self, obs):
                return np.clip(obs, 0, 1)

        def make(agent):
            return jax.jit(agent.act)

        step = jax.vmap(Agent().act)
        """
    )
    # `Agent().act` / `agent.act` are attribute refs the corpus can't root
    # conservatively — but `self._postprocess` from a traced method would
    # propagate. Make `act` a root through a resolvable path instead:
    code2 = src(
        """
        import jax
        import numpy as np
        from jax import lax

        class Agent:
            def body(self, carry, x):
                y = self.helper(carry)
                return y, y

            def helper(self, v):
                return np.tanh(v)

        def run(agent, xs, v0):
            return lax.scan(agent.body, v0, xs)
        """
    )
    # agent.body is an attribute ref -> unresolvable -> conservative quiet
    assert analyze_source_jit(code2) == []
    code3 = src(
        """
        import jax
        import numpy as np

        class Agent:
            @jax.jit
            def act(self, obs):
                return self.helper(obs)

            def helper(self, obs):
                return np.tanh(obs)
        """
    )
    findings = analyze_source_jit(code3)
    assert rules_of(findings) == ["GJ002"]
    assert findings[0].function == "Agent.helper"


def test_unresolvable_reference_never_guesses():
    code = src(
        """
        import jax

        @jax.jit
        def step(x, fn):
            return fn(x)
        """
    )
    assert analyze_source_jit(code) == []


# --------------------------------------------------------------------------- #
# GJ003 — Python control flow on tracers
# --------------------------------------------------------------------------- #


def test_gj003_if_while_assert_fire():
    code = src(
        """
        import jax

        @jax.jit
        def step(x):
            if x > 0:
                x = x + 1
            while x < 10:
                x = x * 2
            assert x > 0
            return x
        """
    )
    assert rules_of(analyze_source_jit(code)) == ["GJ003", "GJ003", "GJ003"]


def test_gj003_static_tests_quiet():
    code = src(
        """
        import jax

        @jax.jit
        def step(x, mask=None):
            if mask is None:
                return x
            if isinstance(mask, tuple):
                return x
            y = x + 1
            if len(y.shape) == 2:
                y = y[None]
            return y
        """
    )
    assert analyze_source_jit(code) == []


def test_gj003_host_code_quiet():
    code = src(
        """
        def host_loop(xs):
            out = 0
            for x in xs:
                if x > 0:
                    out += x
            return out
        """
    )
    assert analyze_source_jit(code) == []


# --------------------------------------------------------------------------- #
# GJ004 — constant baking
# --------------------------------------------------------------------------- #


def test_gj004_big_module_constant_fires_small_quiet():
    code = src(
        """
        import jax
        import numpy as np

        TABLE = np.zeros((1024, 1024))
        SMALL = np.zeros((8,))

        @jax.jit
        def step(x):
            return x + TABLE + SMALL
        """
    )
    findings = analyze_source_jit(code)
    assert rules_of(findings) == ["GJ004"]
    assert "'TABLE'" in findings[0].message and "MiB" in findings[0].message


def test_gj004_factory_closure_constant_fires():
    # the binding lives in the enclosing factory frame; the nested traced
    # function closes over it
    code = src(
        """
        import jax
        import jax.numpy as jnp

        def make_step():
            table = jnp.ones((512, 512))

            @jax.jit
            def step(x):
                return x + table

            return step
        """
    )
    findings = analyze_source_jit(code)
    assert rules_of(findings) == ["GJ004"]
    assert findings[0].function == "make_step.step"


def test_gj004_unknown_size_conservative_quiet():
    # np.zeros(shape) with a dynamic shape: size not statically computable,
    # so no guessed finding
    code = src(
        """
        import jax
        import numpy as np

        def make(shape):
            table = np.zeros(shape)

            @jax.jit
            def step(x):
                return x + table

            return step
        """
    )
    assert analyze_source_jit(code) == []


def test_gj004_jit_in_loop_fires_outside_quiet():
    code = src(
        """
        import jax

        def retrace(xs):
            for i in range(4):
                f = jax.jit(lambda x: x + i)
                xs = f(xs)
            return xs

        def fine(xs):
            f = jax.jit(lambda x: x + 1)
            for i in range(4):
                xs = f(xs)
            return xs
        """
    )
    findings = analyze_source_jit(code)
    assert rules_of(findings) == ["GJ004"]
    assert findings[0].function == "retrace"


# --------------------------------------------------------------------------- #
# GJ005 — retrace hazards at static arguments
# --------------------------------------------------------------------------- #


def test_gj005_unhashable_static_literal_fires():
    code = src(
        """
        import jax

        g = jax.jit(lambda x, sizes: x, static_argnums=(1,))

        def call(x):
            return g(x, [1, 2, 3])
        """
    )
    findings = analyze_source_jit(code)
    assert rules_of(findings) == ["GJ005"]
    assert "unhashable" in findings[0].message


def test_gj005_loop_varying_static_fires_constant_quiet():
    code = src(
        """
        import jax

        g = jax.jit(lambda x, n: x, static_argnums=(1,))

        def varying(x):
            for n in range(4):
                x = g(x, n)
            return x

        def constant(x):
            for _ in range(4):
                x = g(x, 7)
            return x
        """
    )
    findings = analyze_source_jit(code)
    assert rules_of(findings) == ["GJ005"]
    assert "'n'" in findings[0].message


def test_gj005_static_argnames_keyword_fires():
    code = src(
        """
        import jax

        g = jax.jit(lambda x, mode=0: x, static_argnames=("mode",))

        def call(x, modes):
            for m in modes:
                x = g(x, mode=m)
            return x
        """
    )
    assert rules_of(analyze_source_jit(code)) == ["GJ005"]


def test_gj005_decorated_static_argnums():
    code = src(
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(1,))
        def g(x, sizes):
            return x

        def call(x):
            return g(x, {1: 2})
        """
    )
    assert rules_of(analyze_source_jit(code)) == ["GJ005"]


# --------------------------------------------------------------------------- #
# suppressions + staleness
# --------------------------------------------------------------------------- #


def test_inline_suppression_absorbs():
    code = src(
        """
        import jax

        @jax.jit
        def step(key):
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))  # graft-jit: disable=GJ001 — test fixture
            return a + b
        """
    )
    assert analyze_source_jit(code) == []


def test_disable_next_line_skips_continuation_comments():
    code = src(
        """
        import jax

        @jax.jit
        def step(x):
            # graft-jit: disable-next-line=GJ002 — justification line one
            # wrapping onto a second comment line
            return float(x)
        """
    )
    assert analyze_source_jit(code) == []


def test_rule_scoped_suppression_does_not_absorb_others():
    code = src(
        """
        import jax

        @jax.jit
        def step(x):
            return float(x)  # graft-jit: disable=GJ001
        """
    )
    assert rules_of(analyze_source_jit(code)) == ["GJ002"]


def test_graft_lint_suppression_does_not_absorb_jit():
    # the tiers are parallel: a graft-lint directive says nothing about GJ
    code = src(
        """
        import jax

        @jax.jit
        def step(x):
            return float(x)  # graft-lint: disable=GL002
        """
    )
    assert rules_of(analyze_source_jit(code)) == ["GJ002"]


def test_stale_suppression_collected():
    code = src(
        """
        import jax

        @jax.jit
        def step(x):
            return x + 1  # graft-jit: disable=GJ002 — nothing fires here anymore
        """
    )
    stale = []
    assert analyze_source_jit(code, stale_out=stale) == []
    assert rules_of(stale) == ["SUP001"]
    assert "GJ002 does not fire" in stale[0].message


def test_used_suppression_not_stale():
    code = src(
        """
        import jax

        @jax.jit
        def step(x):
            return float(x)  # graft-jit: disable=GJ002 — intentional
        """
    )
    stale = []
    assert analyze_source_jit(code, stale_out=stale) == []
    assert stale == []


def test_unknown_rule_in_directive_always_stale():
    code = src(
        """
        def f():
            return 1  # graft-jit: disable=GX123
        """
    )
    stale = []
    analyze_source_jit(code, stale_out=stale)
    assert rules_of(stale) == ["SUP001"]
    assert "can never fire" in stale[0].message


def test_filtered_out_rule_not_judged_stale():
    # --select excludes GJ002: a GJ002 directive can't be judged this run
    code = src(
        """
        import jax

        @jax.jit
        def step(x):
            return x + 1  # graft-jit: disable=GJ002
        """
    )
    stale = []
    analyze_source_jit(code, select={"GJ001"}, stale_out=stale)
    assert stale == []


def test_stale_detection_in_lint_and_sync_tiers():
    # the machinery is SHARED: the same staleness semantics in every tier
    from sheeprl_tpu.analysis.lint import analyze_source
    from sheeprl_tpu.analysis.sync import analyze_source_sync

    lint_code = src(
        """
        def f():
            return 1  # graft-lint: disable=GL007 — dead justification
        """
    )
    stale = []
    assert analyze_source(lint_code, "f.py", stale_out=stale) == []
    assert rules_of(stale) == ["SUP001"]

    sync_code = src(
        """
        def f():
            return 1  # graft-sync: disable=GS004 — dead justification
        """
    )
    stale = []
    assert analyze_source_sync(sync_code, "f.py", stale_out=stale) == []
    assert rules_of(stale) == ["SUP001"]


# --------------------------------------------------------------------------- #
# CLI contract
# --------------------------------------------------------------------------- #


def test_cli_list_rules(capsys):
    assert analysis_main(["jit", "--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in JIT_RULES:
        assert rule in out


def test_cli_exit_codes_and_formats(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(
        src(
            """
            import jax

            @jax.jit
            def step(key):
                a = jax.random.normal(key, (4,))
                b = jax.random.uniform(key, (4,))
                return a + b
            """
        )
    )
    assert analysis_main(["jit", str(bad)]) == 1
    capsys.readouterr()
    assert analysis_main(["jit", str(bad), "--format=json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["tool"] == "graft-jit"
    assert payload["rules"] == JIT_RULES
    assert payload["findings"][0]["rule"] == "GJ001"
    assert analysis_main(["jit", str(bad), "--format=github"]) == 1
    gh = capsys.readouterr().out
    assert "::error file=" in gh and "graft-jit GJ001" in gh
    assert analysis_main(["jit", str(bad), "--select", "GJ002"]) == 0
    assert analysis_main(["jit", str(bad), "--select", "GJ999"]) == 2


def test_cli_syntax_error_reported_not_crash(tmp_path, capsys):
    bad = tmp_path / "broken.py"
    bad.write_text("def broken(:\n")
    assert analysis_main(["jit", str(bad)]) == 1
    assert "GJ000" in capsys.readouterr().out


def test_cli_stale_suppression_warns_by_default(tmp_path, capsys):
    f = tmp_path / "stale.py"
    f.write_text("def f():\n    return 1  # graft-jit: disable=GJ002\n")
    assert analysis_main(["jit", str(f)]) == 0
    err = capsys.readouterr().err
    assert "SUP001" in err and "warning" in err


def test_cli_strict_suppressions_promotes_to_findings(tmp_path, capsys):
    f = tmp_path / "stale.py"
    f.write_text("def f():\n    return 1  # graft-jit: disable=GJ002\n")
    assert analysis_main(["jit", str(f), "--strict-suppressions"]) == 1
    out = capsys.readouterr().out
    assert "SUP001" in out


def test_cli_strict_suppressions_lint_and_sync(tmp_path, capsys):
    f = tmp_path / "stale.py"
    f.write_text("def f():\n    return 1  # graft-lint: disable=GL007\n")
    assert analysis_main(["lint", str(f), "--strict-suppressions", "--no-baseline"]) == 1
    capsys.readouterr()
    g = tmp_path / "stale2.py"
    g.write_text("def f():\n    return 1  # graft-sync: disable=GS004\n")
    assert analysis_main(["sync", str(g), "--strict-suppressions"]) == 1


# --------------------------------------------------------------------------- #
# `analysis all` — merged catalog, selection, skip semantics
# --------------------------------------------------------------------------- #


def test_all_list_rules_enumerates_every_tier(capsys):
    assert analysis_main(["all", "--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("GL001", "GJ001", "GS001", "AUD001", "SUP001"):
        assert rule in out
    for tool in ("graft-lint", "graft-jit", "graft-sync", "graft-audit"):
        assert f"{tool}:" in out


def test_all_unknown_select_is_named_exit_2(tmp_path, capsys):
    assert analysis_main(["all", str(tmp_path), "--select", "BOGUS"]) == 2
    err = capsys.readouterr().err
    assert "unknown rule(s): BOGUS" in err and "GJ001" in err


def test_all_select_partitions_tiers(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(
        src(
            """
            import jax

            @jax.jit
            def step(key):
                a = jax.random.normal(key, (4,))
                b = jax.random.uniform(key, (4,))
                return a + b
            """
        )
    )
    rc = analysis_main(["all", str(bad), "--select", "GJ001"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "lint=skipped" in err and "jit=1" in err
    assert "sync=skipped" in err and "audit=skipped" in err


def test_all_includes_jit_tier(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n")
    rc = analysis_main(["all", str(clean), "--skip-audit"])
    err = capsys.readouterr().err
    assert rc == 0
    assert "lint=0" in err and "jit=0" in err and "sync=0" in err


def test_all_propagates_jit_findings(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(
        src(
            """
            import jax

            @jax.jit
            def step(x):
                return float(x)
            """
        )
    )
    rc = analysis_main(["all", str(bad), "--skip-audit"])
    capsys.readouterr()
    assert rc == 1


# --------------------------------------------------------------------------- #
# repo-tree gates
# --------------------------------------------------------------------------- #


def test_repo_tree_is_clean():
    """THE shipped-baseline gate: the full CLI run over sheeprl_tpu/ is green
    — every real finding fixed, every suppression inline-justified."""
    rc = analysis_main(["jit", str(REPO_ROOT / "sheeprl_tpu")])
    assert rc == 0


def test_repo_tree_has_no_stale_suppressions():
    """Every `# graft-lint/sync/jit: disable` directive in the shipped tree
    still absorbs a finding — fixed code cannot carry dead justifications."""
    tree = str(REPO_ROOT / "sheeprl_tpu")
    assert analysis_main(["jit", tree, "--strict-suppressions"]) == 0
    assert analysis_main(["sync", tree, "--strict-suppressions"]) == 0
    assert analysis_main(["lint", tree, "--strict-suppressions"]) == 0


def test_repo_tree_corpus_is_nontrivial():
    """Guard against the analyzer rotting into a no-op: the shipped tree must
    keep producing a substantial traced set (roots via decorators, call-args,
    collectives, audit registry; closure via taint propagation)."""
    import os

    from sheeprl_tpu.analysis.jitgraph import Corpus
    from sheeprl_tpu.analysis.lint import iter_python_files

    corpus = Corpus()
    for path in iter_python_files([str(REPO_ROOT / "sheeprl_tpu")]):
        with open(path, "r", encoding="utf-8") as fh:
            corpus.add_source(fh.read(), os.path.relpath(path, REPO_ROOT))
    corpus.finalize()
    traced = corpus.traced_functions()
    assert len(traced) > 100
    propagated = [f for f in traced if f.trace_reason.startswith("called from")]
    assert len(propagated) > 20


def test_injected_bug_is_caught_in_real_tree():
    """End-to-end: a key reuse planted inside a real nested traced function
    (dreamer_v3's rollout) is found — the corpus reaches it through the
    factory nesting, not just top-level decorated functions."""
    import os

    from sheeprl_tpu.analysis.lint import iter_python_files

    sources = []
    for path in iter_python_files([str(REPO_ROOT / "sheeprl_tpu")]):
        with open(path, "r", encoding="utf-8") as fh:
            sources.append((fh.read(), os.path.relpath(path, REPO_ROOT)))
    idx = next(i for i, (_, p) in enumerate(sources) if p.endswith("dreamer_v3/dreamer_v3.py"))
    text, p = sources[idx]
    target = "k_repr, key = jax.random.split(key)"
    assert target in text
    sources[idx] = (
        text.replace(
            target,
            target + "\n            _a = jax.random.normal(k_repr, (2,)); _b = jax.random.normal(k_repr, (2,))",
            1,
        ),
        p,
    )
    findings = analyze_jit_sources(sources)
    assert [f.rule for f in findings] == ["GJ001"]
    assert findings[0].path.endswith("dreamer_v3/dreamer_v3.py")
