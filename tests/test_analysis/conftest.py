"""Trace-hygiene fixture: strict tracecheck + steady-state transfer guard +
tracer-leak checking around an e2e run."""

import jax
import pytest


@pytest.fixture()
def trace_hygiene():
    """Arm the runtime sentinels for one test:

    - tracecheck ``strict``: a post-warmup retrace on any registered hot path
      raises :class:`~sheeprl_tpu.analysis.tracecheck.RetraceError`;
    - steady-state ``jax.transfer_guard("disallow")``: an implicit transfer
      in a guarded entry point raises instead of silently syncing;
    - ``jax.check_tracer_leaks``: a tracer escaping a trace raises at trace
      time.

    Yields the tracecheck singleton so tests can assert on
    ``post_warmup_retraces()`` / ``report()`` afterwards.
    """
    from sheeprl_tpu.analysis.tracecheck import tracecheck

    tracecheck.reset()
    tracecheck.configure(mode="strict", transfer_guard=True)
    try:
        with jax.check_tracer_leaks():
            yield tracecheck
    finally:
        tracecheck.configure(mode="warn", transfer_guard=False)
        tracecheck.reset()
