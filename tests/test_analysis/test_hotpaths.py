"""Tier-1 e2e dry-runs under the trace-hygiene fixture: strict retrace
budgets + steady-state ``jax.transfer_guard("disallow")`` + tracer-leak
checking, through the real CLI. The acceptance bar: 0 post-warmup retraces on
the ppo / ppo_anakin / ppo_anakin_population / sac / ppo_sebulba hot paths, and a deliberately
planted host sync must be CAUGHT (proving the guard actually polices the
steady state)."""

import numpy as np
import pytest

from sheeprl_tpu.cli import run


def _args(tmp_path, exp, env="dummy", devices=2, extra=()):
    args = [
        f"exp={exp}",
        f"env={env}",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "dry_run=True",
        "buffer.memmap=False",
        f"fabric.devices={devices}",
        "metric.log_level=0",
        "checkpoint.save_last=False",
        f"log_root={tmp_path}/logs",
        "algo.run_test=False",
    ]
    args.extend(extra)
    return args


PPO_FAST = [
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
]
SAC_FAST = [
    "algo.per_rank_batch_size=8",
    "algo.mlp_keys.encoder=[state]",
    "env.id=continuous_dummy",
]


def _assert_quiet(tracecheck, expected_entries):
    retraces = tracecheck.post_warmup_retraces()
    assert retraces == {}, f"post-warmup retraces on hot paths: {retraces}"
    report = tracecheck.report()
    for name in expected_entries:
        assert name in report, f"hot path {name!r} was never registered: {sorted(report)}"
        assert report[name]["calls"] > 0, f"hot path {name!r} was never dispatched"


def test_ppo_steady_state_clean(tmp_path, trace_hygiene):
    """PPO beyond warmup: 2 full iterations (not dry_run), so the train step
    and the rollout program both run guarded steady-state calls."""
    run(
        _args(tmp_path, "ppo", extra=PPO_FAST)[:6]  # keep exp/env/envs/sync/video
        + [
            "buffer.memmap=False",
            "fabric.devices=2",
            "metric.log_level=0",
            "checkpoint.save_last=False",
            f"log_root={tmp_path}/logs",
            "algo.run_test=False",
            "algo.total_steps=32",  # 2 iterations of 8 steps x 2 envs
        ]
        + PPO_FAST
    )
    _assert_quiet(trace_hygiene, ["ppo.train_step", "ppo.gae", "ppo.rollout_step"])
    # the whole rollout program must have compiled exactly once
    assert trace_hygiene.report()["ppo.rollout_step"]["compiles"] == 1


def test_ppo_anakin_dry_run_clean(tmp_path, trace_hygiene):
    run(_args(tmp_path, "ppo_anakin", env="gym", extra=PPO_FAST))
    _assert_quiet(trace_hygiene, ["ppo_anakin.block"])


def test_ppo_anakin_block_raw_transfer_guard(tmp_path, trace_hygiene, monkeypatch):
    """The strict trace-hygiene lane, un-mediated: a literal
    ``jax.transfer_guard("disallow")`` armed around EVERY fused-block
    dispatch — including the maiden trace+compile+execute call that
    tracecheck's own steady-state guard deliberately exempts as warmup.
    Proves the block program performs zero implicit transfers from its very
    first dispatch: inputs are explicitly staged (``device_put`` /
    ``shard_data``), constants are device-resident, and nothing inside the
    compiled program reaches back to the host. This is the dynamic sample of
    what graft-jit's GJ002 proves statically for all paths."""
    import functools

    import jax

    from sheeprl_tpu.algos.ppo import ppo_anakin as anakin_mod

    dispatched = []
    orig_call = anakin_mod.AnakinBlockCache.__call__

    def guarded(self, n_iters):
        fn = orig_call(self, n_iters)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            dispatched.append(n_iters)
            with jax.transfer_guard("disallow"):
                return fn(*args, **kwargs)

        return wrapper

    monkeypatch.setattr(anakin_mod.AnakinBlockCache, "__call__", guarded)
    run(_args(tmp_path, "ppo_anakin", env="gym", extra=PPO_FAST))
    assert dispatched, "the fused block was never dispatched under the raw guard"
    _assert_quiet(trace_hygiene, ["ppo_anakin.block"])


def test_ppo_anakin_steady_state_clean(tmp_path, trace_hygiene):
    """Multiple fused-block dispatches (NOT a dry run): the second call is
    fed by the first call's donated outputs, so this pins the sharding-level
    cache stability of the block program (out_shardings pinned to the
    driver's staging sharding — a canonicalized-but-equivalent output
    placement recompiles without any abstract-signature drift)."""
    run(
        _args(tmp_path, "ppo_anakin", env="gym", extra=PPO_FAST)
        + [
            "dry_run=False",
            "algo.total_steps=64",
            "checkpoint.every=16",
            "checkpoint.save_last=False",
            # the annealing staircase rewrites lr (inside the donated opt
            # state) and the loss coefficients every block — values change,
            # the program must not
            "algo.anneal_lr=True",
            "algo.anneal_clip_coef=True",
            "algo.anneal_ent_coef=True",
        ]
    )
    report = trace_hygiene.report()["ppo_anakin.block"]
    assert report["calls"] >= 2, report
    _assert_quiet(trace_hygiene, ["ppo_anakin.block"])


def test_ppo_anakin_population_steady_state_clean(tmp_path, trace_hygiene):
    """Population block beyond warmup, PBT enabled: multiple block dispatches
    with the lax.cond selection gate toggling, under strict budgets and the
    steady-state transfer guard. In particular this pins the two bugs the
    population path is prone to: a PBT gate flip must not retrace (the gate
    is a traced bool), and the dispatch's env-carried outputs must feed the
    next call without a sharding-level cache miss (out_shardings are pinned
    to the driver's staging sharding for exactly this reason)."""
    run(
        _args(tmp_path, "ppo_anakin_population", env="gym", extra=PPO_FAST)
        + [
            "dry_run=False",
            "algo.total_steps=64",
            "checkpoint.every=16",
            "checkpoint.save_last=False",
            "algo.population.size=3",
            "algo.population.sweep=random",
            "algo.population.hparams={lr: {low: 0.0001, high: 0.01, log: true}}",
            "algo.population.pbt.enabled=True",
            "algo.population.pbt.every_blocks=2",
        ]
    )
    report = trace_hygiene.report()["ppo_anakin_pop.block"]
    assert report["calls"] >= 2, report  # steady-state calls actually happened
    _assert_quiet(trace_hygiene, ["ppo_anakin_pop.block"])


def test_ppo_anakin_population_scenario_matrix_steady_state_clean(tmp_path, trace_hygiene):
    """Scenario matrix + PBT live together beyond warmup: the env-params axis
    is a TRACED block argument, so P scenarios ride one compile; the PBT
    gate toggling (with perturb_env_params moving the scenario rows in-graph)
    must not retrace either — 0 post-warmup retraces under strict budgets
    and the steady-state transfer guard."""
    run(
        _args(tmp_path, "ppo_anakin_population", env="gym", extra=PPO_FAST)
        + [
            "dry_run=False",
            "algo.total_steps=64",
            "checkpoint.every=16",
            "checkpoint.save_last=False",
            "algo.population.size=3",
            "algo.population.sweep=random",
            "algo.population.hparams={lr: {low: 0.0001, high: 0.01, log: true}}",
            "algo.population.env_params={length: {low: 0.25, high: 1.0}}",
            "algo.population.pbt.enabled=True",
            "algo.population.pbt.every_blocks=2",
            "algo.population.pbt.perturb_env_params=True",
        ]
    )
    report = trace_hygiene.report()["ppo_anakin_pop.block"]
    assert report["calls"] >= 2, report
    _assert_quiet(trace_hygiene, ["ppo_anakin_pop.block"])


def test_sac_dry_run_clean(tmp_path, trace_hygiene):
    run(_args(tmp_path, "sac", extra=SAC_FAST))
    _assert_quiet(trace_hygiene, ["sac.train_step", "sac.rollout_step"])


def test_sac_resident_dry_run_clean(tmp_path, trace_hygiene):
    run(_args(tmp_path, "sac", extra=SAC_FAST + ["buffer.device_resident=True"]))
    _assert_quiet(trace_hygiene, ["sac.resident_step", "sac.rollout_step"])


def test_ppo_sebulba_dry_run_clean(tmp_path, trace_hygiene):
    run(_args(tmp_path, "ppo_sebulba", extra=PPO_FAST))
    _assert_quiet(
        trace_hygiene,
        ["ppo_sebulba.train_step", "ppo_sebulba.act", "ppo_sebulba.traj", "ppo_sebulba.gae"],
    )


def test_sac_sebulba_dry_run_clean(tmp_path, trace_hygiene):
    """Async off-policy Sebulba: the actor inference path, the ring append
    path, and the append-free train step must all run guarded steady-state
    calls with 0 post-warmup retraces."""
    run(_args(tmp_path, "sac_sebulba", extra=SAC_FAST + ["algo.learning_starts=0"]))
    _assert_quiet(
        trace_hygiene,
        ["sac_sebulba.train_step", "sac_sebulba.act", "sac_sebulba.append"],
    )


def test_sac_sebulba_actor_restart_clean(tmp_path, trace_hygiene):
    """Chaos under the strict trace budget: an actor killed mid-run is
    restarted by the supervisor and the run completes with ZERO post-warmup
    retraces — the replacement generation must reuse the compiled ``act``
    program (same abstract signature, same jit cache), not recompile it."""
    import warnings

    from sheeprl_tpu.fault import inject

    inject.arm("sac_sebulba.actor0.step", action="raise", at=8)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", UserWarning)  # the restart announcement
            run(
                _args(tmp_path, "sac_sebulba", extra=SAC_FAST + ["algo.learning_starts=0"])
                + [
                    "dry_run=False",
                    "algo.total_steps=48",
                    "algo.sebulba.num_actor_threads=2",
                    "algo.sebulba.rollout_block=4",
                    "buffer.size=96",
                    "fault.supervisor.backoff=0.05",
                ]
            )
    finally:
        inject.reset()
    report = trace_hygiene.report()
    # the kill actually happened and the replacement dispatched act again
    assert report["sac_sebulba.act"]["calls"] >= 2
    _assert_quiet(
        trace_hygiene,
        ["sac_sebulba.train_step", "sac_sebulba.act", "sac_sebulba.append"],
    )
    # one abstract signature, one compile — across the restart
    assert report["sac_sebulba.act"]["compiles"] == 1, report["sac_sebulba.act"]


DREAMER_SEB_FAST = [
    "algo=dreamer_v3_XS",
    "algo.name=dreamer_sebulba",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=2",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.world_model.reward_model.bins=17",
    "algo.critic.bins=17",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "env.screen_size=64",
]


def test_dreamer_sebulba_steady_state_clean(tmp_path, trace_hygiene):
    """Async DreamerV3 beyond warmup: a full multi-block run (many act
    dispatches across 2 actor threads, several ragged append commits with
    and without reset rows, several governed train scans) must report 0
    post-warmup retraces on all three hot paths under the strict budget +
    transfer guard — in particular, episode resets (the in-graph is_first
    init merge) and ragged reset rows must never key fresh compiles."""
    run(
        _args(tmp_path, "dreamer_sebulba", extra=DREAMER_SEB_FAST)
        + [
            "dry_run=False",
            "fabric.devices=1",
            "buffer.size=256",
            "algo.learning_starts=0",
            "algo.total_steps=32",
            "algo.sebulba.rollout_block=4",
        ]
    )
    report = trace_hygiene.report()
    assert report["dreamer_sebulba.act"]["calls"] >= 8
    assert report["dreamer_sebulba.train_step"]["calls"] >= 2
    _assert_quiet(
        trace_hygiene,
        ["dreamer_sebulba.train_step", "dreamer_sebulba.act", "dreamer_sebulba.append"],
    )
    # one abstract signature each: act across both actors and every reset
    # pattern, append across every ragged mask, train across every grant
    for name in ("dreamer_sebulba.act", "dreamer_sebulba.train_step", "dreamer_sebulba.append"):
        assert report[name]["compiles"] == 1, (name, report[name])


def test_serve_engine_hotpaths_clean(trace_hygiene):
    """The serving tier's hot paths: AOT bucket programs are compiled at
    construction, so arbitrary request shapes hammered through ``infer`` must
    produce 0 post-warmup retraces — `serve.infer` sees exactly one abstract
    signature per (bucket, mode) and every `serve.bucket[N]` executable is a
    fixed-shape program by construction (strict mode + the per-entry
    host-slab transfer opt-out)."""
    import gymnasium as gym

    from sheeprl_tpu.algos.ppo.evaluate import serve_policy_ppo
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel import Fabric
    from sheeprl_tpu.serve.engine import BucketEngine

    cfg = compose(
        [
            "exp=ppo",
            "env=gym",
            "env.capture_video=False",
            "fabric.devices=1",
            "metric.log_level=0",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(42)
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (4,), np.float32)})
    policy = serve_policy_ppo(fabric, cfg, obs_space, gym.spaces.Discrete(2), None)

    engine = BucketEngine(policy, buckets=(1, 4, 16), mode="greedy")
    rng = np.random.default_rng(0)
    for n in (1, 2, 3, 4, 5, 7, 15, 16, 17, 33):  # every boundary + chunking
        obs = {"state": rng.standard_normal((n, 4)).astype(np.float32)}
        engine.infer(policy.params, obs)
    _assert_quiet(
        trace_hygiene,
        ["serve.infer", "serve.bucket[1].greedy", "serve.bucket[4].greedy", "serve.bucket[16].greedy"],
    )
    report = trace_hygiene.report()
    # one abstract signature per bucket on the shared entry, none added since
    assert report["serve.infer"]["compiles"] == 3


def test_planted_host_sync_is_caught(tmp_path, trace_hygiene, monkeypatch):
    """Regression-proof the guard itself: break the explicit staging (the
    exact hazard class the suite polices) and the steady-state transfer guard
    must fail the run instead of silently eating a per-iteration sync."""
    from sheeprl_tpu.parallel.fabric import Fabric

    # the learner batch now reaches the train step as raw numpy views
    monkeypatch.setattr(Fabric, "shard_data", lambda self, tree: tree)

    # depending on where placement resolves, the guard reports the planted
    # sync as a host-to-device or an (equally implicit) device-to-device move
    with pytest.raises(Exception, match="Disallowed .* transfer"):
        run(
            [
                "exp=ppo",
                "env=dummy",
                "env.num_envs=2",
                "env.sync_env=True",
                "env.capture_video=False",
                "buffer.memmap=False",
                "fabric.devices=2",
                "metric.log_level=0",
                "checkpoint.save_last=False",
                f"log_root={tmp_path}/logs",
                "algo.run_test=False",
                "algo.total_steps=32",  # 2 iterations: the 2nd is guarded
            ]
            + PPO_FAST
        )


def test_planted_retrace_is_caught(tmp_path, trace_hygiene):
    """And the budget half: a hot path whose signature drifts post-warmup
    (here: a python-float scalar that should be a jnp array) trips strict
    mode with a RetraceError naming the entry point."""
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.analysis.tracecheck import RetraceError

    # transfer_guard=False so the retrace accounting (not the transfer
    # guard, which would fire first on the implicit scalar transfer) trips
    step = trace_hygiene.instrument(
        jax.jit(lambda x, c: x * c), name="drifting_step", warmup=1, transfer_guard=False
    )
    step(jnp.ones((4,)), jnp.float32(0.9))
    with pytest.raises(RetraceError, match="drifting_step"):
        step(jnp.ones((4,)), 0.9)  # weak-type drift = retrace
