"""Acceptance e2e: a short PPO run is SIGKILLed mid-checkpoint-save in a real
subprocess, then relaunched with ``checkpoint.resume_from=latest`` — training
completes with monotonically continuing step counters and no
corrupted-checkpoint errors."""

import glob
import os
import signal
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

import sheeprl_tpu

pytestmark = pytest.mark.fault

REPO_ROOT = str(Path(sheeprl_tpu.__file__).parents[1])

BASE_ARGS = [
    "exp=ppo", "env=dummy", "env.id=discrete_dummy", "env.num_envs=2", "env.sync_env=True",
    "env.capture_video=False", "buffer.memmap=False", "fabric.devices=1", "metric.log_level=0",
    "algo.rollout_steps=4", "algo.per_rank_batch_size=4", "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]", "algo.total_steps=48", "checkpoint.every=8",
    "algo.run_test=False", "seed=11", "log_root=logs",
]


def _launch(tmp_path, extra_args=(), extra_env=None):
    env = {
        **os.environ,
        "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
    }
    env.pop("SHEEPRL_FAULT_KILL", None)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu", *BASE_ARGS, *extra_args],
        cwd=tmp_path,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )


def test_sigkill_mid_save_then_resume_from_latest_completes(tmp_path):
    # -- run 1: SIGKILL inside the 3rd checkpoint save, after the sidecars
    # are published but before the meta commit (the nastiest window)
    proc = _launch(tmp_path, extra_env={"SHEEPRL_FAULT_KILL": "checkpoint.pre_commit:3"})
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]

    ckpt_dirs = glob.glob(str(tmp_path / "logs/ppo/discrete_dummy/*/version_*/checkpoint"))
    assert len(ckpt_dirs) == 1
    committed = sorted(glob.glob(os.path.join(ckpt_dirs[0], "*.ckpt")))
    # two committed checkpoints (steps 8, 16); the third save died mid-publish
    assert [os.path.basename(p) for p in committed] == ["ckpt_16_0.ckpt", "ckpt_8_0.ckpt"]
    leftovers = glob.glob(os.path.join(ckpt_dirs[0], "*.tmp")) + glob.glob(os.path.join(ckpt_dirs[0], "*24*"))
    assert leftovers, "the kill should have left torn artifacts of the 3rd save"

    from sheeprl_tpu.fault.manager import latest_complete, read_manifest

    assert latest_complete(ckpt_dirs[0]).name == "ckpt_16_0.ckpt"
    assert [e["step"] for e in read_manifest(ckpt_dirs[0])] == [8, 16]

    # -- run 2: auto-resume; must complete without corrupted-checkpoint errors
    proc2 = _launch(tmp_path, extra_args=["checkpoint.resume_from=latest"])
    assert proc2.returncode == 0, (proc2.stdout[-2000:], proc2.stderr[-2000:])
    assert "checkpoint.resume_from=latest ->" in proc2.stdout
    assert "ckpt_16_0.ckpt" in proc2.stdout

    from sheeprl_tpu.fault.manager import find_latest_run_checkpoint
    from sheeprl_tpu.utils.checkpoint import load_state

    final = find_latest_run_checkpoint(tmp_path / "logs/ppo/discrete_dummy")
    state = load_state(final)
    # counters continued monotonically past the kill point to the end
    assert state["iter_num"] == 6  # 48 total steps / 8 per iter
    assert int(os.path.basename(str(final)).split("_")[1]) == 48
    assert state.get("rng") is not None
    for leaf in jax.tree.leaves(state["agent"]):
        assert np.isfinite(np.asarray(leaf)).all()

    # the resumed run's checkpoint steps all land AFTER the resume point
    run2_dirs = [d for d in glob.glob(str(tmp_path / "logs/ppo/discrete_dummy/*/version_*/checkpoint")) if d != ckpt_dirs[0]]
    assert len(run2_dirs) == 1
    run2_steps = [e["step"] for e in read_manifest(run2_dirs[0])]
    assert run2_steps and run2_steps == sorted(run2_steps) and min(run2_steps) > 16
