"""Atomic save semantics: a save killed at ANY stage never destroys the
previous checkpoint, and load errors are typed + carry the offending path."""

import numpy as np
import pytest

from sheeprl_tpu.fault import inject
from sheeprl_tpu.utils.checkpoint import CheckpointError, load_state, save_state


@pytest.mark.parametrize("point", ["checkpoint.staged", "checkpoint.pre_commit"])
def test_save_aborted_mid_write_keeps_previous_checkpoint(tmp_path, tiny_state, point):
    path = tmp_path / "ckpt_8_0.ckpt"
    save_state(path, tiny_state(value=1.0, iter_num=1))

    inject.arm(point, action="raise", at=1)
    with pytest.raises(inject.FaultInjected):
        save_state(path, tiny_state(value=9.0, iter_num=2))
    inject.reset()

    # pre-commit abort == the old checkpoint is fully intact
    loaded = load_state(path)
    assert loaded["iter_num"] == 1
    np.testing.assert_array_equal(np.asarray(loaded["agent"]["w"]), np.ones(3))

    # the next save sweeps the stale staging leftovers and goes through
    save_state(path, tiny_state(value=5.0, iter_num=3))
    assert load_state(path)["iter_num"] == 3
    assert not list(tmp_path.glob("*.tmp")) and not list(tmp_path.glob("*.old"))


def test_save_never_rmtrees_live_arrays_before_replacement(tmp_path, tiny_state):
    """The historical bug: rmtree of the live ``.arrays`` dir before the new
    one exists. An abort between staging and publish must leave it whole."""
    path = tmp_path / "ckpt_8_0.ckpt"
    save_state(path, tiny_state(value=2.0))
    arrays_dir = tmp_path / "ckpt_8_0.ckpt.arrays"
    assert arrays_dir.is_dir()

    inject.arm("checkpoint.staged", action="raise", at=1)
    with pytest.raises(inject.FaultInjected):
        save_state(path, tiny_state(value=3.0))
    assert arrays_dir.is_dir()
    np.testing.assert_array_equal(np.asarray(load_state(path)["agent"]["w"]), np.full(3, 2.0))


def test_two_consecutive_torn_saves_keep_committed_checkpoint_loadable(tmp_path, tiny_state):
    """A save killed between sidecar-publish and meta-commit leaves the
    committed meta resolving against the .old grace copy; a FOLLOW-UP save
    killed mid-staging must not destroy that copy."""
    path = tmp_path / "ckpt_8_0.ckpt"
    save_state(path, tiny_state(value=1.0, iter_num=1))

    inject.arm("checkpoint.pre_commit", action="raise", at=1)
    with pytest.raises(inject.FaultInjected):
        save_state(path, tiny_state(value=2.0, iter_num=2))
    inject.reset()
    assert load_state(path)["iter_num"] == 1  # resolves via .arrays.old

    inject.arm("checkpoint.staged", action="raise", at=1)
    with pytest.raises(inject.FaultInjected):
        save_state(path, tiny_state(value=3.0, iter_num=3))
    inject.reset()
    loaded = load_state(path)
    assert loaded["iter_num"] == 1
    np.testing.assert_array_equal(np.asarray(loaded["agent"]["w"]), np.ones(3))

    # and a clean save fully recovers the path
    save_state(path, tiny_state(value=4.0, iter_num=4))
    assert load_state(path)["iter_num"] == 4
    assert not list(tmp_path.glob("*.old"))


def test_load_missing_meta_raises_checkpoint_error(tmp_path):
    missing = tmp_path / "nope.ckpt"
    with pytest.raises(CheckpointError) as exc:
        load_state(missing)
    assert exc.value.path == missing


def test_load_truncated_meta_raises_checkpoint_error(tmp_path, tiny_state):
    path = tmp_path / "ckpt_8_0.ckpt"
    save_state(path, tiny_state())
    inject.truncate_file(path, keep_bytes=4)
    with pytest.raises(CheckpointError, match="truncated"):
        load_state(path)


def test_load_missing_arrays_sidecar_raises_checkpoint_error(tmp_path, tiny_state):
    import shutil

    path = tmp_path / "ckpt_8_0.ckpt"
    save_state(path, tiny_state())
    shutil.rmtree(tmp_path / "ckpt_8_0.ckpt.arrays")
    with pytest.raises(CheckpointError, match="arrays sidecar"):
        load_state(path)


def test_load_missing_rb_sidecar_raises_checkpoint_error(tmp_path):
    from sheeprl_tpu.data.buffers import ReplayBuffer

    rb = ReplayBuffer(4, 1, obs_keys=("state",))
    path = tmp_path / "ckpt_8_0.ckpt"
    save_state(path, {"iter_num": 1, "rb": rb})
    (tmp_path / "ckpt_8_0.ckpt.rb").unlink()
    with pytest.raises(CheckpointError, match="replay-buffer sidecar"):
        load_state(path)


def test_scrambled_meta_raises_checkpoint_error(tmp_path, tiny_state):
    path = tmp_path / "ckpt_8_0.ckpt"
    save_state(path, tiny_state())
    inject.scramble_file(path)
    with pytest.raises(CheckpointError):
        load_state(path)
