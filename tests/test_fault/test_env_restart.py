"""Self-healing vector env: crash retry with thunk recreation, hang
watchdog, bounded attempts, env_restarts metric."""

import numpy as np
import pytest

from sheeprl_tpu.envs.dummy import DiscreteDummyEnv
from sheeprl_tpu.envs.vector import FastSyncVectorEnv
from sheeprl_tpu.fault.inject import FlakyEnv
from sheeprl_tpu.fault.watchdog import SelfHealingEnv


def _flaky_thunks(n_envs, fuse, fail_on="step", mode="raise", hang_seconds=60.0):
    def make(i):
        def thunk():
            return FlakyEnv(DiscreteDummyEnv(), fuse, fail_on=fail_on, mode=mode, hang_seconds=hang_seconds)

        return thunk

    return [make(i) for i in range(n_envs)]


def test_step_crash_heals_and_surfaces_truncation():
    fuse = [1]  # exactly one injected failure across all instances
    envs = FastSyncVectorEnv(_flaky_thunks(2, fuse), restart_attempts=2, restart_backoff=0.0)
    envs.reset(seed=1)
    for _ in range(4):
        obs, rewards, term, trunc, infos = envs.step(np.zeros(2, dtype=np.int64))
    assert envs.env_restarts == 1
    assert fuse[0] == 0
    # training continues: further steps are healthy
    obs, rewards, term, trunc, infos = envs.step(np.zeros(2, dtype=np.int64))
    assert obs["state"].shape[0] == 2
    envs.close()


def test_reset_crash_heals():
    fuse = [1]
    envs = FastSyncVectorEnv(_flaky_thunks(2, fuse, fail_on="reset"), restart_attempts=2, restart_backoff=0.0)
    obs, infos = envs.reset(seed=1)
    assert envs.env_restarts == 1
    assert obs["state"].shape[0] == 2
    envs.close()


def test_attempt_budget_exhaustion_raises():
    calls = {"n": 0}

    def dead_thunk():
        calls["n"] += 1
        if calls["n"] > 1:  # first build OK, every recreation fails
            raise RuntimeError("factory down")
        return DiscreteDummyEnv()

    env = SelfHealingEnv(dead_thunk, attempts=2, backoff=0.0)
    env.reset(seed=0)
    env.env.step = lambda a: (_ for _ in ()).throw(RuntimeError("boom"))
    with pytest.raises(RuntimeError, match="could not be recreated after 2 attempts"):
        env.step(0)


def test_hang_watchdog_times_out_and_heals():
    fuse = [1]
    env = SelfHealingEnv(
        lambda: FlakyEnv(DiscreteDummyEnv(), fuse, fail_on="step", mode="hang", hang_seconds=30.0),
        attempts=2,
        backoff=0.0,
        step_timeout=0.2,
    )
    env.reset(seed=0)
    obs, reward, terminated, truncated, info = env.step(0)
    assert truncated and info.get("env_restarted")
    assert env.restarts == 1
    # healed env steps normally within the timeout
    obs, reward, terminated, truncated, info = env.step(0)
    assert not info.get("env_restarted")


def test_factory_plumbs_restart_config(tmp_path):
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.envs.factory import vectorize_env

    cfg = compose(
        [
            "exp=ppo", "env=dummy", "env.id=discrete_dummy", "env.num_envs=2", "env.sync_env=True",
            "env.capture_video=False", "env.restart_attempts=3", "algo.mlp_keys.encoder=[state]",
        ]
    )
    envs = vectorize_env(cfg, seed=0, rank=0)
    assert isinstance(envs.envs[0], SelfHealingEnv)
    assert envs.env_restarts == 0
    envs.close()

    cfg.env.restart_attempts = 0
    cfg.env.step_timeout = None
    envs = vectorize_env(cfg, seed=0, rank=0)
    assert not isinstance(envs.envs[0], SelfHealingEnv)
    envs.close()
