"""PodSupervisor unit drills: gang restart (never per-worker respawn),
survivor draining, checkpoint-free hook ordering, budget exhaustion on the
POD ladder, hang-vs-kill counters, and the pod knob shape. Children are tiny
``python -c`` processes — no JAX, no training stack, just gang lifecycle."""

import os
import signal
import subprocess
import sys
import time

import pytest

from sheeprl_tpu.fault.podsup import PodSupervisor
from sheeprl_tpu.fault.supervisor import AllWorkersDeadError, WorkerAbortError

SLEEPER = [sys.executable, "-c", "import time; time.sleep(120)"]
CRASHER = [sys.executable, "-c", "import sys; sys.exit(3)"]
FINISHER = [sys.executable, "-c", "pass"]  # exits rc=0: training complete
STUBBORN = [
    sys.executable,
    "-c",
    "import signal, time; signal.signal(signal.SIGTERM, signal.SIG_IGN); time.sleep(120)",
]


def _spawner(cmd, log=None, tag="spawn"):
    def spawn():
        if log is not None:
            log.append(tag)
        return subprocess.Popen(cmd)

    return spawn


def _wait(predicate, timeout=10.0, poll=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture()
def sup():
    s = PodSupervisor(lease_s=None, backoff=0.01, max_restarts=2, join_s=10.0, drain_s=5.0)
    yield s
    s.request_stop()
    s.terminate_all(grace_s=5.0)


def test_kill_one_worker_gang_restarts_all(sup):
    """One SIGKILLed worker condemns the generation: the survivor is DRAINED
    (its exit is teardown, not a counted failure), the on_gang_restart hook
    runs before any respawn, and the WHOLE gang comes back."""
    order = []
    sup.on_gang_restart = lambda gen: order.append(f"hook:{gen}")
    sup.spawn_gang(
        {
            "w0": _spawner(SLEEPER, order, "spawn:w0"),
            "w1": _spawner(SLEEPER, order, "spawn:w1"),
        }
    )
    h0, h1 = sup.replica("w0"), sup.replica("w1")
    assert sup.generation == 1
    os.kill(h0.pid(), signal.SIGKILL)
    assert _wait(lambda: h0.proc.poll() is not None)
    with pytest.warns(UserWarning, match="gang restart"):
        sup.check()  # death detected -> survivors drained -> backoff armed
    assert h0.kills == 1 and h0.deaths == 1
    # the drained survivor is generation teardown: no failure counters
    assert h1.deaths == 0 and h1.kills == 0 and not h1.is_alive()
    assert _wait(lambda: (sup.check() or (h0.is_alive() and h1.is_alive())))
    assert sup.pod_restarts == 1 and sup.generation == 2
    assert h0.restarts == 1 and h1.restarts == 1
    # hook ran after the first generation's spawns and BEFORE the respawns
    assert order == ["spawn:w0", "spawn:w1", "hook:2", "spawn:w0", "spawn:w1"]


def test_all_workers_exit_zero_is_finished(sup):
    """rc == 0 everywhere is training completion — no counters, no restart,
    ``finished()`` flips."""
    sup.spawn_gang({"w0": _spawner(FINISHER), "w1": _spawner(FINISHER)})
    h0, h1 = sup.replica("w0"), sup.replica("w1")
    assert _wait(lambda: h0.proc.poll() is not None and h1.proc.poll() is not None)
    assert not sup.finished()
    sup.check()  # no warning expected: these are normal completions
    assert sup.finished()
    assert sup.pod_restarts == 0 and h0.deaths == 0 and h1.deaths == 0


def test_budget_exhausted_degrade_is_drained_stop():
    """degrade past the pod budget is a DRAINED STOP raising
    AllWorkersDeadError — a pod cannot train on a partial mesh."""
    sup = PodSupervisor(lease_s=None, backoff=0.01, max_restarts=0, escalation="degrade", drain_s=5.0)
    try:
        sup.spawn_gang({"w0": _spawner(CRASHER), "w1": _spawner(SLEEPER)})
        h0, h1 = sup.replica("w0"), sup.replica("w1")
        assert _wait(lambda: h0.proc.poll() is not None)
        with pytest.warns(UserWarning, match="budget \\(0\\) exhausted"):
            with pytest.raises(AllWorkersDeadError):
                sup.check()
        assert h0.state == "degraded" and h1.state == "degraded"
        assert not h1.is_alive()  # survivor drained before the stop
        assert sup.gang_info()["state"] == "degraded"
    finally:
        sup.terminate_all(grace_s=5.0)


def test_abort_escalation_raises_worker_abort():
    sup = PodSupervisor(lease_s=None, backoff=0.01, max_restarts=0, escalation="abort", drain_s=5.0)
    try:
        sup.spawn_gang({"w0": _spawner(CRASHER), "w1": _spawner(SLEEPER)})
        h0 = sup.replica("w0")
        assert _wait(lambda: h0.proc.poll() is not None)
        with pytest.warns(UserWarning, match="gang restart|draining"):
            with pytest.raises(WorkerAbortError, match="exited rc=3"):
                sup.check()
    finally:
        sup.terminate_all(grace_s=5.0)


def test_gang_backoff_grows_exponentially():
    """delay = backoff * 2^pod_restarts — the ladder's backoff is on POD
    restarts, not per-worker ones."""
    clock = FakeClock()
    sup = PodSupervisor(
        lease_s=None, backoff=1.0, max_restarts=5, drain_s=0.0, clock=clock, escalation="restart"
    )
    try:
        sup.spawn_gang({"w0": _spawner(CRASHER), "w1": _spawner(CRASHER)})
        h0, h1 = sup.replica("w0"), sup.replica("w1")
        assert _wait(lambda: h0.proc.poll() is not None and h1.proc.poll() is not None)
        with pytest.warns(UserWarning, match="gang restart in 1s"):
            sup.check()
        assert sup._gang_not_before == pytest.approx(clock.t + 1.0)
        clock.t += 1.0
        sup.check()  # due: respawn generation 2 (crashers die again)
        assert sup.pod_restarts == 1
        assert _wait(lambda: h0.proc.poll() is not None and h1.proc.poll() is not None)
        with pytest.warns(UserWarning, match="gang restart in 2s"):
            sup.check()
        assert sup._gang_not_before == pytest.approx(clock.t + 2.0)
    finally:
        sup.terminate_all(grace_s=5.0)


def test_sigstop_hang_counts_distinctly_and_gang_restarts():
    """A SIGSTOPped worker stops beating: lease expiry with the process alive
    is a HANG (hangs++, kills unchanged) — the supervisor SIGKILLs it and the
    gang ladder takes over."""
    sup = PodSupervisor(lease_s=0.15, grace_s=0.15, backoff=0.01, max_restarts=2, drain_s=5.0)
    try:
        sup.spawn_gang({"w0": _spawner(SLEEPER), "w1": _spawner(SLEEPER)})
        h0, h1 = sup.replica("w0"), sup.replica("w1")
        os.kill(h0.pid(), signal.SIGSTOP)
        deadline = time.monotonic() + 0.4
        while time.monotonic() < deadline:
            time.sleep(0.05)
            sup.beat("w1")  # the healthy worker keeps beating
        with pytest.warns(UserWarning, match="hung: missed its 0.15s"):
            sup.check()
        assert h0.hangs == 1 and h0.kills == 0 and h0.deaths == 1
        assert h1.hangs == 0 and h1.deaths == 0
        assert _wait(lambda: (sup.check() or (h0.is_alive() and h1.is_alive())))
        assert sup.pod_restarts == 1
    finally:
        sup.terminate_all(grace_s=5.0)


def test_drain_sigkills_stragglers():
    """A survivor blocked past drain_s (modeled by a SIGTERM-ignoring child)
    is SIGKILLed — a worker wedged in a dead collective never drains."""
    sup = PodSupervisor(lease_s=None, backoff=0.01, max_restarts=2, drain_s=0.5)
    try:
        sup.spawn_gang({"w0": _spawner(CRASHER), "w1": _spawner(STUBBORN)})
        h0, h1 = sup.replica("w0"), sup.replica("w1")
        assert _wait(lambda: h0.proc.poll() is not None and h1.is_alive())
        time.sleep(0.2)  # let the stubborn child install SIG_IGN
        with pytest.warns(UserWarning, match="did not drain within 0.5s"):
            sup.check()
        assert not h1.is_alive()
        assert h1.deaths == 0  # teardown, not failure
    finally:
        sup.terminate_all(grace_s=5.0)


def test_failed_hook_marks_gang_dirty_again():
    """If on_gang_restart itself fails (e.g. resume resolution), the respawn
    is NOT attempted half-configured — the gang stays dirty and retries."""
    clock = FakeClock()
    boom = {"n": 0}

    def hook(gen):
        boom["n"] += 1
        if boom["n"] == 1:
            raise RuntimeError("no complete checkpoint yet")

    sup = PodSupervisor(
        lease_s=None, backoff=0.1, max_restarts=5, drain_s=0.0, clock=clock,
        escalation="restart", on_gang_restart=hook,
    )
    try:
        sup.spawn_gang({"w0": _spawner(CRASHER)})
        h0 = sup.replica("w0")
        assert _wait(lambda: h0.proc.poll() is not None)
        with pytest.warns(UserWarning, match="gang restart"):
            sup.check()
        clock.t += 0.1
        with pytest.warns(UserWarning, match="hook failed.*no complete checkpoint"):
            sup.check()  # respawn due -> hook raises -> dirty again, no spawn
        assert h0.proc.poll() is not None and sup.pod_restarts == 1
        with pytest.warns(UserWarning, match="gang restart"):
            sup.check()  # re-enters the ladder from the hook failure
        clock.t += 10.0
        assert _wait(lambda: (sup.check() or h0.proc.poll() is not None))
        assert boom["n"] == 2
    finally:
        sup.terminate_all(grace_s=5.0)


def test_from_config_pod_knob_shape():
    """fabric.pod knob shape: explicit keys win, drain_s rides along, lease
    null disables hang detection — the fault.supervisor merge contract."""
    sup = PodSupervisor.from_config(
        {"max_restarts": 7, "lease_s": 0, "drain_s": 2.5, "escalation": "abort"},
        backoff=0.25,
        name="train-pod",
        drain_s=9.0,
    )
    assert sup.max_restarts == 7 and sup.escalation == "abort"
    assert sup.lease_s is None and sup.drain_s == 2.5
    assert sup.backoff == 0.25 and sup.name == "train-pod"
    # default drain_s applies when the cfg omits it
    assert PodSupervisor.from_config({}, drain_s=9.0).drain_s == 9.0
    assert PodSupervisor.from_config({}).drain_s == 5.0
