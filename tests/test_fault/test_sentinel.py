"""Divergence sentinel: jittable guard semantics + end-to-end NaN injection
through the real PPO training loop (skip, rollback, abort)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.fault import DivergenceError, DivergenceSentinel
from sheeprl_tpu.ops import finite_guard, guarded_select


def test_finite_guard_basics():
    assert bool(finite_guard({"a": jnp.ones(3), "ints": jnp.arange(4)}))
    assert not bool(finite_guard({"a": jnp.array([1.0, np.nan])}))
    assert not bool(finite_guard((jnp.ones(2), {"x": jnp.array([np.inf])})))
    # works under jit/scan
    assert not bool(jax.jit(finite_guard)({"a": jnp.array([np.nan])}))


def test_guarded_select_skips_update():
    new = {"w": jnp.full(2, 9.0)}
    old = {"w": jnp.zeros(2)}
    np.testing.assert_array_equal(np.asarray(guarded_select(jnp.bool_(True), new, old)["w"]), 9.0)
    np.testing.assert_array_equal(np.asarray(guarded_select(jnp.bool_(False), new, old)["w"]), 0.0)


def test_sentinel_streak_and_reset():
    s = DivergenceSentinel({"enabled": True, "max_consecutive": 2, "action": "abort"})
    with pytest.warns(UserWarning):
        assert not s.observe(1)
    assert not s.observe(0)  # streak resets on a good iteration
    with pytest.warns(UserWarning):
        assert not s.observe(2)
    with pytest.warns(UserWarning):
        assert s.observe(1)  # second consecutive bad -> tripped
    assert s.total_skipped == 4
    with pytest.raises(DivergenceError, match="abort"):
        s.recover("/nonexistent", lambda state: None)


def test_sentinel_warn_action_continues():
    s = DivergenceSentinel({"enabled": True, "max_consecutive": 1, "action": "warn"})
    with pytest.warns(UserWarning):
        assert s.observe(3)
    with pytest.warns(UserWarning):
        s.recover("/nonexistent", lambda state: None)
    assert s.consecutive == 0


def test_sentinel_rollback_without_checkpoint_aborts(tmp_path):
    s = DivergenceSentinel({"enabled": True, "max_consecutive": 1, "action": "rollback"})
    with pytest.warns(UserWarning):
        assert s.observe(1)
    with pytest.raises(DivergenceError, match="no complete checkpoint"):
        s.recover(tmp_path, lambda state: None)


def test_sentinel_rollback_restores_from_manager(tmp_path):
    from sheeprl_tpu.fault.manager import CheckpointManager

    m = CheckpointManager()
    m.save(tmp_path / "ckpt_8_0.ckpt", {"agent": {"w": jnp.full(3, 42.0)}, "iter_num": 1}, step=8)
    s = DivergenceSentinel({"enabled": True, "max_consecutive": 1, "action": "rollback"})
    with pytest.warns(UserWarning):
        assert s.observe(1)
    restored = {}
    s.recover(tmp_path, lambda state: restored.update(state))
    np.testing.assert_array_equal(np.asarray(restored["agent"]["w"]), np.full(3, 42.0))
    assert s.rollbacks == 1 and s.consecutive == 0


# -- end-to-end through the real PPO loop ------------------------------------
def _ppo_args(tmp_path, extra=()):
    return [
        "exp=ppo", "env=dummy", "env.id=discrete_dummy", "env.num_envs=2", "env.sync_env=True",
        "env.capture_video=False", "buffer.memmap=False", "fabric.devices=1", "metric.log_level=0",
        "algo.rollout_steps=4", "algo.per_rank_batch_size=4", "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]", "algo.total_steps=24", "checkpoint.every=8",
        f"log_root={tmp_path}/logs", "algo.run_test=False", "seed=7",
        *extra,
    ]


def _final_ckpt_state(tmp_path):
    from sheeprl_tpu.fault.manager import find_latest_run_checkpoint
    from sheeprl_tpu.utils.checkpoint import load_state

    path = find_latest_run_checkpoint(os.path.join(str(tmp_path), "logs", "ppo", "discrete_dummy"))
    assert path is not None
    return load_state(path)


def test_nan_injection_skips_update_and_keeps_params_finite(tmp_path):
    """Acceptance: NaN gradients trigger the sentinel — the update is
    skipped, parameters stay finite, training completes."""
    with pytest.warns(UserWarning, match="optimizer update\\(s\\) skipped"):
        run(_ppo_args(tmp_path, ["fault.inject.nan_grads_at=[2]", "fault.sentinel.max_consecutive=3"]))
    state = _final_ckpt_state(tmp_path)
    assert state["iter_num"] == 3
    for leaf in jax.tree.leaves(state["agent"]):
        assert np.isfinite(np.asarray(leaf)).all()


def test_nan_streak_with_abort_raises_divergence_error(tmp_path):
    with pytest.raises(DivergenceError, match="diverged"):
        with pytest.warns(UserWarning, match="optimizer update\\(s\\) skipped"):
            run(
                _ppo_args(
                    tmp_path,
                    [
                        "fault.inject.nan_grads_at=[1,2,3]",
                        "fault.sentinel.max_consecutive=2",
                        "fault.sentinel.action=abort",
                    ],
                )
            )


def test_nan_streak_with_rollback_recovers_and_completes(tmp_path):
    # iteration 1 checkpoints (every=8 == one iteration), then 2 and 3 are
    # poisoned: the sentinel rolls back to the iter-1 checkpoint and the run
    # still finishes with finite parameters
    with pytest.warns(UserWarning, match="rolling back to last good checkpoint"):
        run(
            _ppo_args(
                tmp_path,
                [
                    "fault.inject.nan_grads_at=[2,3]",
                    "fault.sentinel.max_consecutive=2",
                    "fault.sentinel.action=rollback",
                ],
            )
        )
    state = _final_ckpt_state(tmp_path)
    assert state["iter_num"] == 3
    for leaf in jax.tree.leaves(state["agent"]):
        assert np.isfinite(np.asarray(leaf)).all()
