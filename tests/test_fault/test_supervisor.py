"""Supervision-runtime semantics, provable via deterministic chaos: crash →
restart (with re-homing hook + exponential backoff), hang → lease expiry →
abandoned + replaced, budget exhaustion → degrade or abort per escalation,
zero survivors → typed error, shutdown join budget naming abandoned workers,
deadline-guarded queue handoffs, and the chaos-harness primitives
themselves (hang/kill-thread actions, seeded schedules, deep checkpoint
corruption)."""

import queue as _queue
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.fault import inject
from sheeprl_tpu.fault.supervisor import (
    AllWorkersDeadError,
    HungWorkerError,
    Supervisor,
    WorkerAbortError,
)
from sheeprl_tpu.parallel.pipeline import HandoffTimeoutError, RolloutQueue

pytestmark = pytest.mark.chaos


def _pump(sup, until, timeout=5.0, poll=0.01):
    """Drive check() until ``until()`` or timeout; returns until()'s verdict."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sup.check()
        if until():
            return True
        time.sleep(poll)
    return until()


# --------------------------------------------------------------------------- #
# crash / restart / re-homing
# --------------------------------------------------------------------------- #


def test_crash_restarts_with_rehoming_hook(recwarn):
    events = []

    def target(ctx):
        events.append(("run", ctx.generation))
        if ctx.generation == 1:
            raise RuntimeError("boom")
        while not ctx.cancelled:
            ctx.beat()
            time.sleep(0.005)

    sup = Supervisor(max_restarts=2, backoff=0.01, lease_s=5.0)
    h = sup.spawn("w", target, on_restart=lambda ctx: events.append(("rehome", ctx.generation)))
    assert _pump(sup, lambda: h.restarts == 1 and h.is_alive())
    # re-homing ran BEFORE the new generation, with the new generation's ctx
    assert events == [("run", 1), ("rehome", 2), ("run", 2)]
    assert h.deaths == 1 and h.hangs == 0
    assert isinstance(h.last_error, RuntimeError)
    assert sup.join(2.0) == []


def test_restart_backoff_is_exponential():
    t0 = {}

    def target(ctx):
        t0[ctx.generation] = time.monotonic()
        if ctx.generation <= 2:
            raise RuntimeError("again")
        while not ctx.cancelled:
            ctx.beat()
            time.sleep(0.005)

    sup = Supervisor(max_restarts=3, backoff=0.08, lease_s=None)
    h = sup.spawn("w", target)
    with pytest.warns(UserWarning):
        assert _pump(sup, lambda: h.restarts == 2 and h.is_alive())
    # delays: backoff * 2^0 then backoff * 2^1 (scheduling noise tolerated)
    assert t0[2] - t0[1] >= 0.8 * 0.08
    assert t0[3] - t0[2] >= 0.8 * 0.16
    sup.join(2.0)


def test_unexpected_clean_exit_counts_as_death():
    def target(ctx):
        if ctx.generation == 1:
            return  # neither cancelled nor crashed: unexpected
        while not ctx.cancelled:
            ctx.beat()
            time.sleep(0.005)

    sup = Supervisor(max_restarts=1, backoff=0.01, lease_s=None)
    h = sup.spawn("w", target)
    with pytest.warns(UserWarning, match="exited unexpectedly"):
        assert _pump(sup, lambda: h.restarts == 1 and h.is_alive())
    assert h.last_error is None
    sup.join(2.0)


def test_failed_rehoming_hook_counts_as_another_death():
    attempts = []

    def target(ctx):
        raise RuntimeError("boom")

    def bad_rehome(ctx):
        attempts.append(ctx.generation)
        raise OSError("env factory down")

    def survivor(ctx):
        while not ctx.cancelled:
            ctx.beat()
            time.sleep(0.005)

    sup = Supervisor(max_restarts=1, backoff=0.01, escalation="degrade", lease_s=None)
    h = sup.spawn("w", target, on_restart=bad_rehome)
    sup.spawn("other", survivor)  # keeps the pool alive so degrade isn't zero-survivors
    with pytest.warns(UserWarning):
        assert _pump(sup, lambda: h.state == "degraded")
    assert attempts == [2]  # one restart attempt, whose re-homing failure exhausted the budget
    assert isinstance(h.last_error, OSError)
    sup.join(2.0)


# --------------------------------------------------------------------------- #
# hang detection (lease expiry)
# --------------------------------------------------------------------------- #


def test_hang_expires_lease_and_replaces_generation():
    woke = []

    def target(ctx):
        ctx.beat()
        if ctx.generation == 1:
            inject.fault_point("hangy.step")  # armed: hang well past the lease
            woke.append(ctx.cancelled)  # after waking, the verdict must be visible
            return
        while not ctx.cancelled:
            ctx.beat()
            time.sleep(0.005)

    inject.arm("hangy.step", action="hang", at=1, hang_s=30.0)
    sup = Supervisor(max_restarts=1, backoff=0.01, lease_s=0.05, grace_s=0.05)
    h = sup.spawn("hangy", target)
    with pytest.warns(UserWarning, match="hung"):
        assert _pump(sup, lambda: h.hangs == 1 and h.restarts == 1 and h.is_alive())
    assert isinstance(h.last_error, HungWorkerError)
    inject.release_hangs()  # wake the abandoned generation
    time.sleep(0.1)
    assert woke == [True]  # the stale generation saw cancelled=True on waking
    sup.join(2.0)


def test_beat_keeps_slow_worker_alive():
    def target(ctx):
        for _ in range(20):  # slow but heartbeating: must NOT be called hung
            ctx.beat()
            time.sleep(0.02)
        while not ctx.cancelled:
            ctx.beat()
            time.sleep(0.005)

    sup = Supervisor(max_restarts=0, backoff=0.01, lease_s=0.1, grace_s=0.1)
    h = sup.spawn("slow", target)
    assert not _pump(sup, lambda: h.deaths > 0, timeout=0.5)
    assert h.deaths == 0 and h.is_alive()
    sup.join(2.0)


def test_stale_generation_beat_cannot_refresh_live_lease():
    release = threading.Event()

    def target(ctx):
        ctx.beat()
        if ctx.generation == 1:
            release.wait(5.0)  # abandoned; beats AFTER replacement spawned
            for _ in range(50):
                ctx.beat()
                time.sleep(0.002)
            return
        # replacement: beat once, then go silent so only a STALE beat could save it
        time.sleep(30.0)

    sup = Supervisor(max_restarts=2, backoff=0.0, lease_s=0.08, grace_s=0.08)
    h = sup.spawn("w", target)
    with pytest.warns(UserWarning):
        assert _pump(sup, lambda: h.hangs == 1 and h.generation == 2)
        release.set()  # gen-1 now spams beat() while gen-2 is silent
        assert _pump(sup, lambda: h.hangs == 2)  # gen-2 still expires: stale beats ignored
    sup.join(0.2)


# --------------------------------------------------------------------------- #
# escalation ladder
# --------------------------------------------------------------------------- #


def _crasher(ctx):
    raise RuntimeError(f"gen {ctx.generation} down")


def test_degrade_drops_worker_and_survivors_continue():
    def survivor(ctx):
        while not ctx.cancelled:
            ctx.beat()
            time.sleep(0.005)

    sup = Supervisor(max_restarts=0, backoff=0.01, escalation="degrade", lease_s=None)
    bad = sup.spawn("bad", _crasher)
    good = sup.spawn("good", survivor)
    with pytest.warns(UserWarning, match="DEGRADED"):
        assert _pump(sup, lambda: bad.state == "degraded")
    sup.check()  # survivors keep the pool alive: no AllWorkersDeadError
    assert sup.alive_count() == 1 and good.is_alive()
    m = sup.metrics("Pipeline/", "actor")
    assert m["Pipeline/actor_deaths"] == 1
    assert m["Pipeline/actors_live"] == 1
    assert m["Pipeline/actors_degraded"] == 1
    sup.join(2.0)


def test_abort_escalation_raises_typed_error_naming_worker():
    sup = Supervisor(max_restarts=0, escalation="abort", lease_s=None)
    sup.spawn("doomed", _crasher)
    with pytest.raises(WorkerAbortError, match="doomed") as ei:
        assert _pump(sup, lambda: False, timeout=2.0)
    assert isinstance(ei.value.cause, RuntimeError)


def test_zero_survivors_raise_all_workers_dead():
    sup = Supervisor(max_restarts=0, backoff=0.01, escalation="degrade", lease_s=None)
    sup.spawn("a", _crasher)
    sup.spawn("b", _crasher)
    with pytest.warns(UserWarning):
        with pytest.raises(AllWorkersDeadError) as ei:
            _pump(sup, lambda: False, timeout=2.0)
    assert set(ei.value.errors) == {"a", "b"}


def test_restart_escalation_ignores_budget():
    def target(ctx):
        if ctx.generation <= 4:
            raise RuntimeError("again")
        while not ctx.cancelled:
            ctx.beat()
            time.sleep(0.005)

    sup = Supervisor(max_restarts=1, backoff=0.0, escalation="restart", lease_s=None)
    h = sup.spawn("w", target)
    with pytest.warns(UserWarning):
        assert _pump(sup, lambda: h.restarts == 4 and h.is_alive())
    sup.join(2.0)


def test_from_config_disabled_is_fail_fast():
    sup = Supervisor.from_config({"enabled": False, "max_restarts": 5})
    assert sup.max_restarts == 0 and sup.escalation == "abort"


def test_from_config_rejects_unknown_escalation():
    with pytest.raises(ValueError, match="escalation"):
        Supervisor.from_config({"escalation": "panic"})


# --------------------------------------------------------------------------- #
# shutdown join budget
# --------------------------------------------------------------------------- #


def test_join_abandons_hung_worker_by_name():
    def wedged(ctx):
        ctx.beat()
        inject.fault_point("wedged.step")  # hang far past any join budget

    def polite(ctx):
        while not ctx.cancelled:
            ctx.beat()
            time.sleep(0.005)

    inject.arm("wedged.step", action="hang", at=1, hang_s=60.0)
    sup = Supervisor(max_restarts=0, lease_s=None, join_s=0.2)
    sup.spawn("wedged-actor", wedged)
    sup.spawn("polite-actor", polite)
    time.sleep(0.05)
    with pytest.warns(UserWarning, match="wedged-actor"):
        abandoned = sup.join()
    assert abandoned == ["wedged-actor"]
    inject.release_hangs()


def test_retired_worker_exit_is_not_a_crash():
    """A worker whose OWNER stopped it through its own flag (scheduler.stop()
    without supervisor.request_stop()) retires itself: the dead thread must
    read as stopped — no respawn, no degraded pool, no AllWorkersDeadError."""
    owner_stop = threading.Event()

    def target(ctx):
        while not owner_stop.is_set() and not ctx.cancelled:
            ctx.beat()
            time.sleep(0.005)
        ctx.retire()

    sup = Supervisor(max_restarts=2, backoff=0.01, lease_s=None)
    h = sup.spawn("owned", target)
    owner_stop.set()
    assert _pump(sup, lambda: h.state == "stopped")
    sup.check()  # an all-retired pool is shut down, not dead
    assert h.deaths == 0 and h.restarts == 0
    m = sup.metrics()
    assert m["Pipeline/worker_deaths"] == 0 and m["Pipeline/workers_degraded"] == 0


def test_owner_retire_blocks_pending_respawn():
    """Owner-side handle.retire() during a crash's backoff window: the
    scheduled restart must be cancelled (state -> stopped), so an owner's
    standalone stop can never race a monitor respawn into its shutdown
    settlement."""
    sup = Supervisor(max_restarts=3, backoff=5.0, lease_s=None)  # long backoff window
    h = sup.spawn("w", _crasher)
    with pytest.warns(UserWarning, match="restarting"):
        assert _pump(sup, lambda: h.state == "backoff")
    h.retire()
    assert h.state == "stopped" and not h.live()
    sup.check()  # no respawn, no AllWorkersDeadError (retired == shut down)
    assert h.restarts == 0 and h.state == "stopped"


def test_monitor_thread_surfaces_fatal_instead_of_raising():
    sup = Supervisor(max_restarts=0, backoff=0.01, escalation="degrade", lease_s=None)
    sup.spawn("w", _crasher)
    with pytest.warns(UserWarning):
        sup.start_monitor(poll_s=0.01)
        deadline = time.monotonic() + 5.0
        while sup.fatal is None and time.monotonic() < deadline:
            time.sleep(0.01)
    assert isinstance(sup.fatal, AllWorkersDeadError)
    sup.stop_monitor()


# --------------------------------------------------------------------------- #
# deadline-guarded handoffs
# --------------------------------------------------------------------------- #


def test_handoff_deadline_raises_with_diagnostics():
    rq = RolloutQueue(2)
    with pytest.raises(_queue.Empty):
        rq.get(timeout=0.05, deadline_s=0.2)
    with pytest.raises(HandoffTimeoutError, match="actor-7: state=running"):
        for _ in range(10):
            try:
                rq.get(timeout=0.05, deadline_s=0.2, diagnose=lambda: "actor-7: state=running")
            except _queue.Empty:
                continue


def test_handoff_deadline_resets_on_delivery():
    rq = RolloutQueue(2)
    stop = threading.Event()

    def trickle():
        while not stop.is_set():
            rq.put({"x": 1})
            time.sleep(0.05)

    t = threading.Thread(target=trickle, daemon=True)
    t.start()
    try:
        for _ in range(10):  # slow producer stays under the deadline forever
            while True:
                try:
                    rq.get(timeout=0.03, deadline_s=0.5)
                    break
                except _queue.Empty:
                    continue
    finally:
        stop.set()
        t.join(timeout=2.0)


def test_queue_stall_injection_trips_deadline():
    """Arm the producer-side chaos point with a hang: the consumer's deadline
    guard must convert the stalled pipeline into a typed failure."""
    rq = RolloutQueue(2)
    inject.arm("pipeline.queue.put", action="hang", at=1, hang_s=30.0)
    t = threading.Thread(target=lambda: rq.put({"x": 1}), daemon=True)
    t.start()
    with pytest.raises(HandoffTimeoutError):
        for _ in range(20):
            try:
                rq.get(timeout=0.05, deadline_s=0.3)
            except _queue.Empty:
                continue
    inject.release_hangs()
    t.join(timeout=2.0)


def test_put_beats_while_backpressured():
    rq = RolloutQueue(1)
    rq.put({"x": 0})
    beats = []
    stop = threading.Event()
    t = threading.Thread(target=lambda: rq.put({"x": 1}, stop_event=stop, beat=lambda: beats.append(1)))
    t.start()
    time.sleep(0.2)
    assert beats  # a back-pressured producer keeps renewing its lease
    rq.get(timeout=1.0)
    t.join(timeout=2.0)
    stop.set()


# --------------------------------------------------------------------------- #
# chaos-harness primitives
# --------------------------------------------------------------------------- #


def test_kill_thread_action_escapes_except_exception():
    seen = []

    def victim():
        try:
            inject.fault_point("victim.step")
        except Exception:  # the routine handler a crash must NOT be absorbed by
            seen.append("caught")

    inject.arm("victim.step", action="kill-thread", at=1)
    t = threading.Thread(target=victim, daemon=True)
    t.start()
    t.join(timeout=2.0)
    assert seen == []  # ThreadKilled is a BaseException: it killed the thread


def test_hang_action_releasable():
    t0 = time.monotonic()
    inject.arm("h.step", action="hang", at=1, hang_s=30.0)
    t = threading.Thread(target=lambda: inject.fault_point("h.step"), daemon=True)
    t.start()
    time.sleep(0.05)
    inject.release_hangs()
    t.join(timeout=2.0)
    assert not t.is_alive() and time.monotonic() - t0 < 5.0


def test_arm_fires_on_nth_hit_only():
    inject.arm("nth.step", action="raise", at=3)
    inject.fault_point("nth.step")
    inject.fault_point("nth.step")
    with pytest.raises(inject.FaultInjected, match="hit 3"):
        inject.fault_point("nth.step")
    inject.fault_point("nth.step")  # past the firing hit: quiet again


def test_arm_from_cfg_seeded_ranges_are_deterministic():
    cfg = {
        "fault": {
            "chaos": {
                "enabled": True,
                "seed": 7,
                "events": ["a.step:raise:5-50", "b.step:hang:2:9.5"],
            }
        }
    }
    assert inject.arm_from_cfg(cfg) == 2
    first = dict(inject._armed)
    inject.reset()
    assert inject.arm_from_cfg(cfg) == 2
    assert dict(inject._armed) == first  # same seed -> same schedule
    a_at = first["a.step"][1]
    assert 5 <= a_at <= 50
    assert first["b.step"] == ("hang", 2, 9.5)
    inject.reset()
    cfg["fault"]["chaos"]["seed"] = 8
    inject.arm_from_cfg(cfg)
    # a different seed draws a different schedule with overwhelming likelihood;
    # equality of the full dict would make this flaky, so only assert range
    assert 5 <= inject._armed["a.step"][1] <= 50


def test_arm_from_cfg_disabled_is_noop():
    assert inject.arm_from_cfg({"fault": {"chaos": {"enabled": False, "events": ["x:raise:1"]}}}) == 0
    assert inject._armed == {}


def test_corrupt_checkpoint_arrays_rots_below_manifest(tmp_path):
    """The torn-publish model: manifest still calls the save complete, the
    load fails — exactly what the watcher quarantine exists for."""
    from sheeprl_tpu.fault.manager import CheckpointManager, latest_complete
    from sheeprl_tpu.utils.checkpoint import load_state

    ckpt_dir = tmp_path / "checkpoint"
    ckpt_dir.mkdir()
    path = ckpt_dir / "ckpt_10_0.ckpt"
    CheckpointManager().save(path, {"agent": {"w": np.ones((4, 4), np.float32)}}, step=10)
    assert latest_complete(ckpt_dir) == path
    assert inject.corrupt_checkpoint_arrays(path) > 0
    assert latest_complete(ckpt_dir) == path  # still "complete" by manifest
    with pytest.raises(Exception):
        load_state(path)
