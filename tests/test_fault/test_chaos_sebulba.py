"""Sebulba chaos e2es through the real CLI: an actor killed mid-run is
restarted and the run completes with the SAME final env-step counters as the
fault-free twin (acceptance proof (a)); a hung actor expires its lease and
the pool degrades to the survivors; zero survivors abort with a typed error;
the config-driven chaos schedule (``fault.chaos.events``) arms the same
drills from the CLI."""

import ast
import time

import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.fault import inject
from sheeprl_tpu.fault.supervisor import AllWorkersDeadError, WorkerAbortError

pytestmark = pytest.mark.chaos

# 3 actors over a small run: total_iters (=total_steps/num_envs) is a
# multiple of rollout_block, so every consumed item carries exactly `block`
# rows and the final counters are DETERMINISTIC — the fault-free twin and
# the chaos run must land on identical policy_steps.
SAC_CHAOS = [
    "exp=sac_sebulba",
    "env=dummy",
    "env.id=continuous_dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "buffer.size=128",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.per_rank_batch_size=8",
    "algo.hidden_size=16",
    "algo.mlp_keys.encoder=[state]",
    "algo.learning_starts=4",
    "algo.total_steps=64",
    "algo.sebulba.num_actor_threads=3",
    "algo.sebulba.rollout_block=4",
    "checkpoint.save_last=False",
    "checkpoint.every=0",
    "fabric.devices=1",
    "fault.supervisor.backoff=0.0",
]

PPO_CHAOS = [
    "exp=ppo_sebulba",
    "env=dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo.rollout_steps=8",
    "algo.per_rank_batch_size=4",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
    "algo.total_steps=96",
    "algo.sebulba.num_actor_threads=3",
    "checkpoint.save_last=False",
    "checkpoint.every=0",
    "fabric.devices=1",
    "fault.supervisor.backoff=0.0",
]


def _stats(capfd, tag):
    out, _err = capfd.readouterr()
    lines = [l for l in out.splitlines() if l.startswith(f"{tag} ")]
    assert lines, f"no {tag} line in output:\n{out[-2000:]}"
    return ast.literal_eval(lines[-1][len(tag) + 1 :])


@pytest.fixture()
def sebulba_debug(monkeypatch):
    monkeypatch.setenv("SHEEPRL_SEBULBA_DEBUG", "1")


def test_sac_sebulba_actor_killed_midrun_restarts_and_counters_match(tmp_path, sebulba_debug, capfd):
    """Acceptance proof (a): lose 1 of 3 actors mid-run -> the supervisor
    restarts it on fresh envs, the run completes, final env-step counters
    EQUAL the fault-free twin's, and Pipeline/actor_deaths == injected
    kills."""
    run(SAC_CHAOS + [f"log_root={tmp_path}/logs/clean"])
    clean = _stats(capfd, "SAC_SEBULBA_STATS")
    assert clean["Pipeline/actor_deaths"] == 0
    assert clean["Pipeline/actors_live"] == 3

    inject.arm("sac_sebulba.actor1.step", action="raise", at=10)
    with pytest.warns(UserWarning, match="sac-sebulba-actor-1.*restarting"):
        run(SAC_CHAOS + [f"log_root={tmp_path}/logs/chaos"])
    chaos = _stats(capfd, "SAC_SEBULBA_STATS")
    assert chaos["Pipeline/actor_deaths"] == 1  # == injected kills
    assert chaos["Pipeline/actor_restarts"] == 1
    assert chaos["Pipeline/actors_live"] == 3  # restarted, not degraded
    assert chaos["policy_steps"] == clean["policy_steps"]  # counters monotone AND equal
    assert chaos["Pipeline/env_steps_consumed"] == clean["Pipeline/env_steps_consumed"]


def test_sac_sebulba_hung_actor_lease_expires_and_pool_degrades(tmp_path, sebulba_debug, capfd):
    """A hang (not a crash): the actor goes silent past its lease, the
    supervisor abandons the generation; with no restart budget the pool
    degrades to the 2 survivors and the run still completes."""
    inject.arm("sac_sebulba.actor0.step", action="hang", at=8, hang_s=60.0)
    with pytest.warns(UserWarning, match="hung"):
        run(
            SAC_CHAOS
            + [
                "fault.supervisor.max_restarts=0",
                "fault.supervisor.escalation=degrade",
                "fault.supervisor.lease_s=0.3",
                "fault.supervisor.grace_s=0.3",
                f"log_root={tmp_path}/logs",
            ]
        )
    stats = _stats(capfd, "SAC_SEBULBA_STATS")
    assert stats["Pipeline/actor_hangs"] == 1
    assert stats["Pipeline/actor_deaths"] == 1
    assert stats["Pipeline/actors_live"] == 2
    assert stats["Pipeline/actors_degraded"] == 1
    inject.release_hangs()
    time.sleep(0.1)  # let the woken generation observe cancelled and exit


def test_sac_sebulba_zero_survivors_aborts_typed(tmp_path):
    """Every actor dead past the budget: the learner gets a TYPED error
    instead of spinning on an empty queue forever."""
    inject.arm("sac_sebulba.actor0.step", action="raise", at=6)
    with pytest.warns(UserWarning):
        with pytest.raises(AllWorkersDeadError, match="sac-sebulba-actor-0"):
            run(
                SAC_CHAOS
                + [
                    "algo.sebulba.num_actor_threads=1",
                    "fault.supervisor.max_restarts=0",
                    "fault.supervisor.escalation=degrade",
                    f"log_root={tmp_path}/logs",
                ]
            )


def test_sac_sebulba_supervision_disabled_fails_fast_named(tmp_path):
    """fault.supervisor.enabled=False = the pre-supervision fail-fast
    semantics, upgraded to a typed error NAMING the dead actor."""
    inject.arm("sac_sebulba.actor0.step", action="raise", at=6)
    with pytest.raises(WorkerAbortError, match="sac-sebulba-actor-0"):
        run(
            SAC_CHAOS
            + [
                "fault.supervisor.enabled=False",
                f"log_root={tmp_path}/logs",
            ]
        )


def test_ppo_sebulba_actor_killed_midrun_restarts(tmp_path, sebulba_debug, capfd):
    """Same drill on the on-policy pipeline: the killed actor is re-homed
    onto fresh envs and the run completes with the pool back at full
    strength."""
    inject.arm("ppo_sebulba.actor2.step", action="raise", at=12)
    with pytest.warns(UserWarning, match="sebulba-actor-2.*restarting"):
        run(PPO_CHAOS + [f"log_root={tmp_path}/logs"])
    stats = _stats(capfd, "SEBULBA_STATS")
    assert stats["Pipeline/actor_deaths"] == 1
    assert stats["Pipeline/actor_restarts"] == 1
    assert stats["Pipeline/actors_live"] == 3
    assert stats["Pipeline/rollouts_consumed"] >= 6  # 96 steps / (8*2) per item


def test_chaos_schedule_from_cli_config(tmp_path, sebulba_debug, capfd):
    """The SAME drill driven purely by config (`fault.chaos.events`): the
    deterministic schedule arms at startup, no in-process arm() needed —
    what a CLI chaos drill against a real deployment uses."""
    with pytest.warns(UserWarning, match="restarting"):
        run(
            SAC_CHAOS
            + [
                "fault.chaos.enabled=True",
                "fault.chaos.seed=3",
                "fault.chaos.events=['sac_sebulba.actor1.step:raise:8-16']",
                f"log_root={tmp_path}/logs",
            ]
        )
    stats = _stats(capfd, "SAC_SEBULBA_STATS")
    assert stats["Pipeline/actor_deaths"] == 1
    assert stats["Pipeline/actors_live"] == 3
