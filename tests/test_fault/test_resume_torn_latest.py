"""Checkpoint-integrity fallback for ``resume_from=latest`` (pod gang
restarts): a SIGKILL racing a mid-save leaves a checkpoint whose meta
committed but whose ``.arrays`` payload is TORN. The manifest's sidecar-size
marker must reject it — ``latest_complete`` / ``find_latest_run_checkpoint``
fall back to the previous complete save instead of handing a gang restart a
checkpoint that explodes at ``load_state``."""

import jax.numpy as jnp
import pytest

from sheeprl_tpu.fault import inject
from sheeprl_tpu.fault.manager import (
    CheckpointManager,
    find_latest_run_checkpoint,
    latest_complete,
    load_resume_state,
    read_manifest,
)


def _save_steps(d, steps):
    m = CheckpointManager()
    for s in steps:
        m.save(d / f"ckpt_{s}_0.ckpt", {"agent": {"w": jnp.full(3, float(s))}, "iter_num": s}, step=s)
    m.close()


def _arrays_payload(ckpt):
    """Largest file inside the checkpoint's orbax ``.arrays`` dir — the
    tensor payload a torn save truncates."""
    arrays = ckpt.parent / (ckpt.name + ".arrays")
    files = [p for p in arrays.rglob("*") if p.is_file() and p.stat().st_size > 0]
    assert files, f"no sidecar payload under {arrays}"
    return max(files, key=lambda p: p.stat().st_size)


def test_manifest_records_sidecar_sizes(tmp_path):
    _save_steps(tmp_path, [8])
    (entry,) = read_manifest(tmp_path)
    sizes = entry["sidecars"]
    assert sizes and all(int(v) > 0 for v in sizes.values())
    assert any(".arrays" in rel for rel in sizes)


def test_truncated_latest_arrays_falls_back_to_previous_save(tmp_path):
    """resume_from=latest with a torn newest ``.arrays`` payload: the size
    marker rejects it and discovery returns the previous COMPLETE save —
    and the bare ``*.ckpt`` scan (which only probes existence) must not
    resurrect the rejected entry."""
    _save_steps(tmp_path, [8, 16])
    assert latest_complete(tmp_path).name == "ckpt_16_0.ckpt"

    inject.truncate_file(_arrays_payload(tmp_path / "ckpt_16_0.ckpt"), keep_bytes=8)
    latest = latest_complete(tmp_path)
    assert latest is not None and latest.name == "ckpt_8_0.ckpt"
    # the fallback actually loads
    state = load_resume_state(latest)
    assert state["iter_num"] == 8


def test_torn_latest_across_version_dirs(tmp_path):
    """Pod launcher resume resolution scans ``*/version_*/checkpoint`` run
    dirs: when the newest version dir's only checkpoint is torn, resolution
    falls back to the previous version dir's complete save."""
    v0 = tmp_path / "run" / "version_0" / "checkpoint"
    v1 = tmp_path / "run" / "version_1" / "checkpoint"
    v0.mkdir(parents=True)
    v1.mkdir(parents=True)
    _save_steps(v0, [8, 16])
    _save_steps(v1, [24])
    assert find_latest_run_checkpoint(tmp_path) == v1 / "ckpt_24_0.ckpt"

    inject.truncate_file(_arrays_payload(v1 / "ckpt_24_0.ckpt"), keep_bytes=8)
    assert find_latest_run_checkpoint(tmp_path) == v0 / "ckpt_16_0.ckpt"


def test_grown_sidecar_is_also_rejected(tmp_path):
    """The marker is an exact-size check, not a floor: appended garbage
    (e.g. two generations racing one path) rejects the entry the same way."""
    _save_steps(tmp_path, [8, 16])
    payload = _arrays_payload(tmp_path / "ckpt_16_0.ckpt")
    with open(payload, "ab") as f:
        f.write(b"\0" * 64)
    assert latest_complete(tmp_path).name == "ckpt_8_0.ckpt"


def test_nothing_complete_returns_none(tmp_path):
    _save_steps(tmp_path, [8])
    inject.truncate_file(_arrays_payload(tmp_path / "ckpt_8_0.ckpt"), keep_bytes=8)
    assert latest_complete(tmp_path) is None
    assert find_latest_run_checkpoint(tmp_path) is None


def test_pre_marker_manifest_entries_still_pass(tmp_path):
    """Manifests written before the size marker existed (no ``sidecars``
    key) must keep resolving — existence is still probed, sizes are not."""
    import json

    _save_steps(tmp_path, [8])
    entries = read_manifest(tmp_path)
    for e in entries:
        e.pop("sidecars", None)
    (tmp_path / "manifest.json").write_text(json.dumps({"version": 1, "entries": entries}))
    assert latest_complete(tmp_path).name == "ckpt_8_0.ckpt"
