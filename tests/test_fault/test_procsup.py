"""ProcessSupervisor unit drills: SIGKILL detection distinct from hangs,
lease expiry over health-probe liveness, the restart→degrade→abort ladder at
process granularity, and the SIGTERM-grace-then-SIGKILL drain. Children are
tiny ``python -c`` processes — no serve stack, just lifecycle."""

import os
import signal
import subprocess
import sys
import time

import pytest

from sheeprl_tpu.fault.procsup import ProcessSupervisor
from sheeprl_tpu.fault.supervisor import AllWorkersDeadError, WorkerAbortError

SLEEPER = [sys.executable, "-c", "import time; time.sleep(120)"]
# exits rc=3 immediately: the crash (not kill) model
CRASHER = [sys.executable, "-c", "import sys; sys.exit(3)"]
# ignores SIGTERM: the drain straggler model
STUBBORN = [
    sys.executable,
    "-c",
    "import signal, time; signal.signal(signal.SIGTERM, signal.SIG_IGN); time.sleep(120)",
]


def _spawner(cmd, calls=None):
    def spawn():
        if calls is not None:
            calls.append(time.monotonic())
        return subprocess.Popen(cmd)

    return spawn


def _wait(predicate, timeout=10.0, poll=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll)
    return predicate()


@pytest.fixture()
def sup():
    s = ProcessSupervisor(lease_s=None, backoff=0.01, max_restarts=2, join_s=10.0)
    yield s
    s.request_stop()
    s.terminate_all(grace_s=5.0)


def test_sigkill_detected_as_kill_and_respawned(sup):
    """rc == -SIGKILL is an external kill (preemption/OOM/chaos): counted in
    ``kills`` with the signal NAMED, and the replica is respawned."""
    calls = []
    handle = sup.spawn("r0", _spawner(SLEEPER, calls))
    os.kill(handle.pid(), signal.SIGKILL)
    assert _wait(lambda: handle.proc.poll() is not None)
    with pytest.warns(UserWarning, match="killed by SIGKILL"):
        sup.check()
    assert handle.deaths == 1 and handle.kills == 1 and handle.hangs == 0
    assert handle.last_signal == "SIGKILL" and handle.last_rc == -signal.SIGKILL
    assert _wait(lambda: (sup.check() or handle.is_alive()))
    assert handle.restarts == 1 and len(calls) == 2


def test_plain_exit_is_a_death_not_a_kill(sup):
    """A child that exits rc != 0 on its own is a crash: ``deaths`` counts
    it, ``kills``/``hangs`` do not, and the rc is recorded."""
    handle = sup.spawn("r0", _spawner(CRASHER))
    assert _wait(lambda: handle.proc.poll() is not None)
    with pytest.warns(UserWarning, match="exited rc=3"):
        sup.check()
    assert handle.deaths == 1 and handle.kills == 0 and handle.hangs == 0
    assert handle.last_rc == 3 and handle.last_signal is None


def test_hang_lease_expiry_sigkills_and_counts_distinctly():
    """No probe beats inside the lease while the process is ALIVE: that is a
    HANG — counted in ``hangs`` (not ``kills``), the wedged process is
    SIGKILLed by the supervisor itself, and a fresh one is spawned."""
    sup = ProcessSupervisor(lease_s=0.15, grace_s=0.15, backoff=0.01, max_restarts=2)
    try:
        calls = []
        handle = sup.spawn("r0", _spawner(SLEEPER, calls))
        assert handle.is_alive()
        time.sleep(0.3)  # lease (and spawn grace) expired, no beats arrived
        with pytest.warns(UserWarning, match="hung: missed its 0.15s health-probe lease"):
            sup.check()
        assert handle.hangs == 1 and handle.kills == 0 and handle.deaths == 1
        assert _wait(lambda: (sup.check() or handle.is_alive()))
        assert handle.restarts == 1 and len(calls) == 2
    finally:
        sup.terminate_all(grace_s=5.0)


def test_beats_keep_a_silent_lease_alive():
    """Probe-success beats renew the lease: a replica that keeps answering
    its health probe is never declared hung."""
    sup = ProcessSupervisor(lease_s=0.15, grace_s=0.15, backoff=0.01)
    try:
        handle = sup.spawn("r0", _spawner(SLEEPER))
        for _ in range(6):
            time.sleep(0.05)
            sup.beat("r0")
            sup.check()
        assert handle.hangs == 0 and handle.deaths == 0 and handle.is_alive()
    finally:
        sup.terminate_all(grace_s=5.0)


def test_degrade_past_budget_then_all_dead_is_typed():
    """Budget 0 + degrade: the first death drops the replica; when every
    replica is degraded the pool raises AllWorkersDeadError (never a silent
    routing loop over nothing)."""
    sup = ProcessSupervisor(lease_s=None, backoff=0.01, max_restarts=0, escalation="degrade")
    try:
        h0 = sup.spawn("r0", _spawner(CRASHER))
        h1 = sup.spawn("r1", _spawner(CRASHER))
        assert _wait(lambda: h0.proc.poll() is not None and h1.proc.poll() is not None)
        with pytest.warns(UserWarning, match="DEGRADED"):
            with pytest.raises(AllWorkersDeadError):
                sup.check()
        assert h0.state == "degraded" and h1.state == "degraded"
        assert sup.alive_count() == 0
    finally:
        sup.terminate_all(grace_s=5.0)


def test_abort_escalation_names_the_replica():
    sup = ProcessSupervisor(lease_s=None, backoff=0.01, max_restarts=0, escalation="abort")
    try:
        handle = sup.spawn("bad-replica", _spawner(CRASHER))
        assert _wait(lambda: handle.proc.poll() is not None)
        with pytest.raises(WorkerAbortError, match="bad-replica"):
            sup.check()
    finally:
        sup.terminate_all(grace_s=5.0)


def test_restart_escalation_ignores_budget(sup):
    sup.escalation = "restart"
    sup.max_restarts = 0
    handle = sup.spawn("r0", _spawner(CRASHER))
    assert _wait(lambda: handle.proc.poll() is not None)
    with pytest.warns(UserWarning, match="respawning"):
        sup.check()
    assert handle.state == "backoff"


def test_on_restart_hook_runs_before_respawn(sup):
    order = []
    handle = sup.spawn(
        "r0",
        lambda: (order.append("spawn"), subprocess.Popen(SLEEPER))[1],
        on_restart=lambda name: order.append(f"rehome:{name}"),
    )
    os.kill(handle.pid(), signal.SIGKILL)
    assert _wait(lambda: handle.proc.poll() is not None)
    with pytest.warns(UserWarning, match="respawning"):
        sup.check()
    assert _wait(lambda: (sup.check() or handle.restarts == 1))
    assert order == ["spawn", "rehome:r0", "spawn"]


def test_terminate_all_sigterm_grace_then_sigkill_by_name():
    """Drain: a SIGTERM-honoring replica exits inside the grace; a stubborn
    one is SIGKILLed and NAMED."""
    sup = ProcessSupervisor(lease_s=None, backoff=0.01)
    good = sup.spawn("good", _spawner(SLEEPER))
    bad = sup.spawn("stubborn", _spawner(STUBBORN))
    assert _wait(lambda: good.is_alive() and bad.is_alive())
    time.sleep(0.2)  # let the stubborn child install its SIG_IGN handler
    with pytest.warns(UserWarning, match="SIGKILLed replica.*stubborn"):
        killed = sup.terminate_all(grace_s=2.0)
    assert killed == ["stubborn"]
    assert not good.is_alive() and not bad.is_alive()
    assert good.state == "stopped" and bad.state == "stopped"


def test_retired_replica_is_never_respawned(sup):
    handle = sup.spawn("r0", _spawner(SLEEPER))
    handle.retire()
    os.kill(handle.pid(), signal.SIGKILL)
    assert _wait(lambda: handle.proc.poll() is not None)
    sup.check()
    assert handle.state == "stopped" and handle.restarts == 0


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_budget_exactly_exhausted_degrades_not_aborts():
    """max_restarts=1: the SECOND death finds restarts == max_restarts (not
    <) and must DEGRADE, not respawn — the ladder's off-by-one edge."""
    sup = ProcessSupervisor(lease_s=None, backoff=0.0, max_restarts=1, escalation="degrade")
    try:
        handle = sup.spawn("r0", _spawner(CRASHER))
        assert _wait(lambda: handle.proc.poll() is not None)
        with pytest.warns(UserWarning, match="respawning"):
            sup.check()  # death 1: within budget, zero-backoff respawn
        assert _wait(lambda: handle.restarts == 1 or (sup.check() or False))
        assert handle.restarts == 1
        assert _wait(lambda: handle.proc.poll() is not None)
        # death 2: budget EXACTLY spent -> degrade (not abort); with the last
        # replica degraded the pool then raises the all-dead typed error
        with pytest.warns(UserWarning, match="DEGRADED"):
            with pytest.raises(AllWorkersDeadError):
                sup.check()
        assert handle.state == "degraded" and handle.restarts == 1
    finally:
        sup.terminate_all(grace_s=5.0)


def test_backoff_grows_exponentially_per_restart():
    """delay = backoff * 2^restarts: each consecutive respawn of the same
    replica waits twice as long (deterministic via an injected clock)."""
    clock = _FakeClock()
    sup = ProcessSupervisor(
        lease_s=None, backoff=1.0, max_restarts=5, escalation="restart", clock=clock
    )
    try:
        handle = sup.spawn("r0", _spawner(CRASHER))
        expected = [1.0, 2.0, 4.0]
        for restarts_so_far, delay in enumerate(expected):
            assert _wait(lambda: handle.proc.poll() is not None)
            with pytest.warns(UserWarning, match=f"respawning in {delay:g}s"):
                sup.check()
            assert handle._not_before == pytest.approx(clock.t + delay)
            clock.t += delay
            sup.check()  # due now: respawn (the crasher dies again)
            assert handle.restarts == restarts_so_far + 1
    finally:
        sup.terminate_all(grace_s=5.0)


def test_mixed_sigkill_then_sigstop_counts_kills_and_hangs_separately():
    """A SIGKILL death then a SIGSTOP hang on the SAME replica: kills and
    hangs each count once, deaths counts both, and the recorded last_error
    flips from the kill to the hang."""
    clock = _FakeClock()
    sup = ProcessSupervisor(lease_s=5.0, grace_s=5.0, backoff=0.0, max_restarts=4, clock=clock)
    try:
        handle = sup.spawn("r0", _spawner(SLEEPER))
        os.kill(handle.pid(), signal.SIGKILL)
        assert _wait(lambda: handle.proc.poll() is not None)
        with pytest.warns(UserWarning, match="killed by SIGKILL"):
            sup.check()
        assert handle.kills == 1 and handle.hangs == 0 and handle.deaths == 1
        assert _wait(lambda: (sup.check() or handle.is_alive()))
        assert handle.restarts == 1
        # generation 2 wedges: SIGSTOP freezes it, the lease expires silently
        os.kill(handle.pid(), signal.SIGSTOP)
        clock.t += 100.0
        with pytest.warns(UserWarning, match="hung: missed its 5s health-probe lease"):
            sup.check()
        assert handle.kills == 1 and handle.hangs == 1 and handle.deaths == 2
        assert "hung" in handle.last_error
        assert _wait(lambda: (sup.check() or (handle.restarts == 2 and handle.is_alive())))
    finally:
        sup.terminate_all(grace_s=5.0)


def test_from_config_knob_shape():
    """serve.fleet knob shape: explicit keys win over defaults; lease null
    disables hang detection — the fault.supervisor merge contract."""
    sup = ProcessSupervisor.from_config(
        {"max_restarts": 5, "escalation": "abort", "lease_s": 0, "grace_s": 7.0},
        backoff=0.125,
        name="serve-fleet",
    )
    assert sup.max_restarts == 5 and sup.escalation == "abort"
    assert sup.lease_s is None and sup.grace_s == 7.0
    assert sup.backoff == 0.125 and sup.name == "serve-fleet"
    with pytest.raises(ValueError, match="escalation"):
        ProcessSupervisor.from_config({"escalation": "explode"})
