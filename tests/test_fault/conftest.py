"""Shared fixtures for the fault-injection suite."""

import jax.numpy as jnp
import pytest

from sheeprl_tpu.fault import inject


@pytest.fixture(autouse=True)
def _inject_isolation():
    """Armed fault points and hit counters never leak across tests."""
    inject.reset()
    yield
    inject.reset()


@pytest.fixture()
def tiny_state():
    """A minimal checkpoint-state builder (arrays + scalars + None)."""

    def build(value: float = 1.0, iter_num: int = 1):
        return {
            "agent": {"w": jnp.full((3,), value), "b": jnp.zeros(2)},
            "scheduler": None,
            "iter_num": iter_num,
        }

    return build
