"""CheckpointManager: manifest publication, retention GC, orphan sweep,
corrupted-entry fallback, latest discovery and async save."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.fault import inject
from sheeprl_tpu.fault.manager import (
    CheckpointManager,
    find_latest_run_checkpoint,
    latest_complete,
    load_resume_state,
    read_manifest,
)
from sheeprl_tpu.utils.checkpoint import CheckpointError, load_state, save_state


def _save_steps(d, steps, keep_last=None, async_save=False):
    m = CheckpointManager(keep_last=keep_last, async_save=async_save)
    for s in steps:
        m.save(d / f"ckpt_{s}_0.ckpt", {"agent": {"w": jnp.full(3, float(s))}, "iter_num": s}, step=s)
    m.close()
    return m


def test_manifest_records_completed_saves(tmp_path, tiny_state):
    _save_steps(tmp_path, [8, 16])
    entries = read_manifest(tmp_path)
    assert [e["step"] for e in entries] == [8, 16]
    for e in entries:
        assert e["format_version"] == 2 and e["digest"] and e["time"] > 0


def test_keep_last_retention_and_orphan_gc(tmp_path):
    import time as _time

    # stray leftovers of a killed save: sidecar without meta + tmp litter.
    # Backdated past the orphan grace window — FRESH tmp/old artifacts are
    # deliberately left alone (they may belong to an in-flight sibling save).
    (tmp_path / "ckpt_99_0.ckpt.arrays").mkdir(parents=True)
    (tmp_path / "ckpt_99_0.ckpt.tmp").write_bytes(b"torn")
    stale = _time.time() - 3600
    for p in (tmp_path / "ckpt_99_0.ckpt.arrays", tmp_path / "ckpt_99_0.ckpt.tmp"):
        os.utime(p, (stale, stale))
    _save_steps(tmp_path, [8, 16, 24, 32, 40], keep_last=2)

    assert [e["step"] for e in read_manifest(tmp_path)] == [32, 40]
    kept = sorted(p.name for p in tmp_path.glob("*.ckpt"))
    assert kept == ["ckpt_32_0.ckpt", "ckpt_40_0.ckpt"]
    assert not (tmp_path / "ckpt_8_0.ckpt.arrays").exists()
    assert not (tmp_path / "ckpt_99_0.ckpt.arrays").exists()
    assert not list(tmp_path.glob("*.tmp"))


def test_latest_complete_skips_half_written_and_corrupt(tmp_path):
    _save_steps(tmp_path, [8, 16])
    # half-written newer step: arrays dir + staged meta, never committed
    (tmp_path / "ckpt_24_0.ckpt.arrays").mkdir()
    (tmp_path / "ckpt_24_0.ckpt.tmp").write_bytes(b"torn")
    assert latest_complete(tmp_path).name == "ckpt_16_0.ckpt"

    # corrupt the newest committed meta: discovery falls back to step 8
    inject.truncate_file(tmp_path / "ckpt_16_0.ckpt", keep_bytes=4)
    assert latest_complete(tmp_path).name == "ckpt_8_0.ckpt"


def test_corrupted_manifest_falls_back_to_scan(tmp_path):
    _save_steps(tmp_path, [8, 16])
    (tmp_path / "manifest.json").write_text("{ not json !")
    with pytest.warns(UserWarning, match="corrupted checkpoint manifest"):
        assert read_manifest(tmp_path) == []
    assert latest_complete(tmp_path).name == "ckpt_16_0.ckpt"
    # binary (non-UTF8) corruption falls back the same way
    (tmp_path / "manifest.json").write_bytes(b"\xff\xfe\x00garbage\x9c")
    with pytest.warns(UserWarning, match="corrupted checkpoint manifest"):
        assert read_manifest(tmp_path) == []
    assert latest_complete(tmp_path).name == "ckpt_16_0.ckpt"


def test_manifest_digest_mismatch_excludes_entry(tmp_path):
    _save_steps(tmp_path, [8, 16])
    # flip the newest entry's recorded digest: discovery must not trust it
    entries = read_manifest(tmp_path)
    entries[-1]["digest"] = "0" * 64
    import json as _json

    (tmp_path / "manifest.json").write_text(_json.dumps({"version": 1, "entries": entries}))
    # the bare-file scan would still accept it, but only because the file
    # itself is intact — the manifest-trusted path must reject first;
    # delete the file's scan eligibility by checking the manifest set only
    from sheeprl_tpu.fault.manager import _complete_entries

    manifest_paths = {p.name for _, _, p in _complete_entries(tmp_path)}
    assert "ckpt_16_0.ckpt" in manifest_paths  # rescued by the scan (file is fine)
    inject.truncate_file(tmp_path / "ckpt_16_0.ckpt", keep_bytes=4)
    assert latest_complete(tmp_path).name == "ckpt_8_0.ckpt"


def test_load_resume_state_falls_back_to_previous_entry(tmp_path):
    _save_steps(tmp_path, [8, 16, 24])
    inject.scramble_file(tmp_path / "ckpt_24_0.ckpt")
    with pytest.warns(UserWarning, match="resuming from older complete entry"):
        state = load_resume_state(tmp_path / "ckpt_24_0.ckpt")
    assert state["iter_num"] == 16


def test_load_resume_state_never_jumps_forward(tmp_path):
    """An explicitly requested OLDER checkpoint that is corrupt must fall
    back further back in time, never silently forward to a newer step."""
    _save_steps(tmp_path, [8, 16, 24])
    inject.scramble_file(tmp_path / "ckpt_16_0.ckpt")
    with pytest.warns(UserWarning, match="resuming from older complete entry"):
        state = load_resume_state(tmp_path / "ckpt_16_0.ckpt")
    assert state["iter_num"] == 8  # not 24


def test_load_resume_state_raises_when_nothing_complete(tmp_path):
    save_state(tmp_path / "ckpt_8_0.ckpt", {"iter_num": 1, "agent": {"w": jnp.ones(2)}})
    inject.scramble_file(tmp_path / "ckpt_8_0.ckpt")
    with pytest.raises(CheckpointError):
        load_resume_state(tmp_path / "ckpt_8_0.ckpt")


def test_find_latest_run_checkpoint_across_runs(tmp_path):
    a = tmp_path / "run_a" / "version_0" / "checkpoint"
    b = tmp_path / "run_b" / "version_0" / "checkpoint"
    a.mkdir(parents=True)
    b.mkdir(parents=True)
    _save_steps(a, [8, 16])
    _save_steps(b, [8])
    # run_b's entry is newest by wall-clock → wins even with a smaller step
    assert find_latest_run_checkpoint(tmp_path) == b / "ckpt_8_0.ckpt"
    assert find_latest_run_checkpoint(tmp_path / "does_not_exist") is None


def test_async_save_round_trip_and_error_surfacing(tmp_path):
    m = CheckpointManager(keep_last=3, async_save=True)
    for s in (8, 16):
        m.save(tmp_path / f"ckpt_{s}_0.ckpt", {"agent": {"w": jnp.full(2, float(s))}, "iter_num": s}, step=s)
    m.close()
    assert [e["step"] for e in read_manifest(tmp_path)] == [8, 16]
    np.testing.assert_array_equal(
        np.asarray(load_state(tmp_path / "ckpt_16_0.ckpt")["agent"]["w"]), np.full(2, 16.0)
    )

    # a failing background write surfaces on the next lifecycle call
    inject.arm("checkpoint.staged", action="raise", at=1)
    m2 = CheckpointManager(async_save=True)
    m2.save(tmp_path / "ckpt_24_0.ckpt", {"agent": {"w": jnp.ones(2)}, "iter_num": 24}, step=24)
    with pytest.raises(CheckpointError, match="Asynchronous checkpoint save failed"):
        m2.close()


def test_replay_buffer_sidecar_through_manager(tmp_path):
    from sheeprl_tpu.data.buffers import ReplayBuffer

    rb = ReplayBuffer(8, 2, obs_keys=("state",))
    rb.add(
        {
            "state": np.ones((1, 2, 3), np.float32),
            "terminated": np.zeros((1, 2, 1), np.float32),
            "truncated": np.zeros((1, 2, 1), np.float32),
        }
    )
    m = CheckpointManager(async_save=True)
    m.save(tmp_path / "ckpt_8_0.ckpt", {"iter_num": 1, "rb": rb}, step=8)
    # async contract: the buffer snapshot is taken before save() returns —
    # post-save mutation must not leak into the checkpoint
    rb["state"][0] = 7.0
    m.close()
    loaded = load_state(tmp_path / "ckpt_8_0.ckpt")
    np.testing.assert_array_equal(loaded["rb"]["state"][0], np.ones((2, 3), np.float32))
    assert read_manifest(tmp_path)[0]["has_rb"] is True
