"""dreamer_sebulba chaos e2e through the real CLI: an actor killed mid-run is
restarted by the supervisor (fresh envs, zeroed policy carry re-initialized
in-graph from a fresh snapshot) and the run completes with env/policy step
counters EQUAL to its fault-free twin — the async-Dreamer analogue of the
PR 10 sac_sebulba acceptance proof."""

import ast

import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.fault import inject

pytestmark = pytest.mark.chaos

# 3 actors over a small run: total_iters is a multiple of rollout_block, so
# every consumed item carries exactly `block` regular rows and the final
# counters are DETERMINISTIC — the fault-free twin and the chaos run must
# land on identical policy_steps.
DREAMER_CHAOS = [
    "exp=dreamer_sebulba",
    "env=dummy",
    "env.num_envs=1",
    "env.sync_env=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "buffer.size=256",
    "metric.log_level=0",
    "algo.run_test=False",
    "algo=dreamer_v3_XS",
    "algo.name=dreamer_sebulba",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=2",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=16",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.world_model.reward_model.bins=17",
    "algo.critic.bins=17",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "env.screen_size=64",
    "algo.learning_starts=4",
    "algo.total_steps=48",
    "algo.sebulba.num_actor_threads=3",
    "algo.sebulba.rollout_block=4",
    "checkpoint.save_last=False",
    "checkpoint.every=0",
    "fabric.devices=1",
    "fault.supervisor.backoff=0.0",
    # raise-kill injection is what this lane proves; a generous lease keeps a
    # slow-box cold compile from tripping hang detection in the CLEAN phase
    "fault.supervisor.lease_s=240",
]


def _stats(capfd):
    out, _err = capfd.readouterr()
    lines = [l for l in out.splitlines() if l.startswith("DREAMER_SEBULBA_STATS ")]
    assert lines, f"no DREAMER_SEBULBA_STATS line in output:\n{out[-2000:]}"
    return ast.literal_eval(lines[-1][len("DREAMER_SEBULBA_STATS "):])


@pytest.fixture()
def sebulba_debug(monkeypatch):
    monkeypatch.setenv("SHEEPRL_SEBULBA_DEBUG", "1")


def test_dreamer_sebulba_actor_killed_midrun_restarts_and_counters_match(
    tmp_path, sebulba_debug, capfd
):
    """Acceptance proof: lose 1 of 3 actors mid-run -> the supervisor
    restarts it on fresh envs, the run completes, final env/policy step
    counters EQUAL the fault-free twin's, and Pipeline/actor_deaths ==
    injected kills."""
    run(DREAMER_CHAOS + [f"log_root={tmp_path}/logs/clean"])
    clean = _stats(capfd)
    assert clean["Pipeline/actor_deaths"] == 0
    assert clean["Pipeline/actors_live"] == 3

    inject.arm("dreamer_sebulba.actor1.step", action="raise", at=10)
    try:
        with pytest.warns(UserWarning, match="dreamer-sebulba-actor-1.*restarting"):
            run(DREAMER_CHAOS + [f"log_root={tmp_path}/logs/chaos"])
    finally:
        inject.reset()
    chaos = _stats(capfd)
    assert chaos["Pipeline/actor_deaths"] == 1  # == injected kills
    assert chaos["Pipeline/actor_restarts"] == 1
    assert chaos["Pipeline/actors_live"] == 3  # restarted, not degraded
    assert chaos["policy_steps"] == clean["policy_steps"]  # counters monotone AND equal
    assert chaos["Pipeline/env_steps_consumed"] == clean["Pipeline/env_steps_consumed"]
