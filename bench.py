#!/usr/bin/env python
"""Benchmark harness: one lane per measured topology, one JSON line out.

Mirrors the reference benchmark conditions for the default lane
(``sheeprl/configs/exp/ppo_benchmarks.yaml``: 65536 total steps, 1 env, sync,
logging/checkpoints off; reference wall-clock 81.27 s on 4 CPUs → ~806
env-steps/s, see BASELINE.md).

``BENCH_METRIC`` selects the lane from the registry below (default ``host``
so the recorded trajectory stays comparable). Adding a lane = one
``@lane(...)``-decorated runner — the selection error message and the CI
matrix read the registry, nothing is hand-enumerated:

- ``host`` — ``ppo_cartpole_env_steps_per_sec``: the host-loop PPO
  (``exp=ppo_benchmarks``), one jitted policy dispatch per env step;
- ``ondevice`` — the Anakin path (``exp=ppo_anakin_benchmarks``) with the
  rollout fused in-graph (howto/on_device_rollout.md);
- ``sebulba`` — the decoupled actor/learner pipeline
  (``exp=ppo_sebulba_benchmarks``, howto/decoupled_training.md);
- ``replay`` — SAC grad-steps/s through the replay data path
  (``exp=sac_replay_benchmarks``; ``BENCH_REPLAY_MODE=device|host`` pairs
  the device-resident ring against host sampling, howto/device_replay.md);
- ``sac_sebulba`` — the async off-policy pipeline vs its coupled twin at an
  identical recipe (``BENCH_SAC_MODE=async|coupled``,
  howto/async_offpolicy.md);
- ``dreamer_sebulba`` — async DreamerV3 over the ragged per-env-head device
  sequence ring vs the coupled host loop at an identical recipe
  (``BENCH_DREAMER_MODE=sebulba|coupled``, howto/async_offpolicy.md);
- ``serve`` — the continuous-batching inference tier: p50/p99 latency +
  throughput at fixed offered loads, AOT bucketed engine
  (``BENCH_SERVE_MODE=aot``) vs naive per-request jit dispatch (``naive``),
  one hot weight swap per load (howto/serving.md; benchmarks/serve_bench.py);
- ``serve_fleet`` — replicated serving: N replica processes behind the
  FleetRouter vs a single replica on identical offered load, one replica
  SIGKILL per fleet rep, ``dropped == 0`` asserted in-lane
  (howto/serving.md; benchmarks/serve_fleet_bench.py);
- ``population`` — P-member population training on the Anakin path:
  ``BENCH_POP_MODE=vmapped`` trains all P members in ONE jitted dispatch
  (``exp=ppo_anakin_population_benchmarks``) vs ``sequential`` = P
  back-to-back ``ppo_anakin_benchmarks`` runs at the matched recipe;
  reports aggregate env-steps/s and the fused-block compile count
  (howto/population_training.md);
- ``scenario_matrix`` — the scenario axis of the population block:
  ``BENCH_SCENARIO_MODE=vmapped`` trains P CartPole pole-length variants in
  ONE dispatch (``algo.population.env_params``) vs ``sequential`` = P
  single-scenario size-1 runs at identical seeds/steps; reports aggregate
  env-steps/s, the block compile count from the tracecheck ledger (1 vs
  >= P) and the per-scenario fitness spread read back from the final
  checkpoints (howto/population_training.md);
- ``env_zoo`` — raw vmapped ``BatchedJaxEnv.step`` throughput per
  registered pure-JAX env at a fixed batch ladder (no agent, no learning:
  the env-side budget an Anakin rollout spends per step);
- ``kernels`` — the Pallas kernel tier microbench: every kernel in the
  ``ops.kernels`` registry timed pallas-vs-lax on identical inputs at 2-3
  call-site shapes (``BENCH_KERNEL=<name>|all``,
  ``BENCH_KERNEL_BACKEND=pallas|lax|both``; interpret-mode caveat in the
  payload, howto/kernels.md; benchmarks/kernel_bench.py);
- ``pod_restart`` — gang-restart MTTR of the fault-tolerant pod: real
  2-process pods with one seeded ``kill-host`` per rep, MTTR = SIGKILL ->
  first post-restart completed train iteration, every rep must converge to
  its configured ``total_steps`` (howto/fault_tolerance.md#pod-training;
  benchmarks/pod_bench.py).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Dict, List

BASELINE_STEPS_PER_SEC = 65536 / 81.27  # reference PPO benchmark (README.md:100-117)

#: lane name -> {"runner": fn, "aliases": (...)}; populated by @lane
LANES: Dict[str, Dict[str, object]] = {}


def lane(name: str, *aliases: str) -> Callable:
    """Register a bench lane under ``name`` (+ aliases, e.g. the metric id)."""

    def decorator(fn: Callable[[], None]) -> Callable[[], None]:
        LANES[name] = {"runner": fn, "aliases": (name, *aliases)}
        return fn

    return decorator


def resolve_lane(which: str) -> Callable[[], None]:
    for entry in LANES.values():
        if which in entry["aliases"]:
            return entry["runner"]  # type: ignore[return-value]
    raise SystemExit(f"Unknown BENCH_METRIC '{which}' (expected one of {sorted(LANES)})")


def _env_steps(default_steps: int) -> int:
    return int(os.environ.get("BENCH_TOTAL_STEPS", default_steps))


def _run_cli(exp: str, total_steps: int, extra: List[str] = (), keep_timer: bool = False) -> float:
    """Run one training CLI invocation under the shared bench conditions;
    returns the elapsed wall-clock seconds."""
    overrides = [
        f"exp={exp}",
        f"algo.total_steps={total_steps}",
        "env.capture_video=False",
        "buffer.memmap=False",
        "checkpoint.save_last=False",
        "metric.log_level=0",
        # keep_timer: the Time/* instrumentation stays alive so per-segment
        # seconds are readable after a log_level=0 run
        f"metric.disable_timer={'False' if keep_timer else 'True'}",
        *extra,
    ]
    from sheeprl_tpu.cli import run

    start = time.perf_counter()
    run(overrides)
    return time.perf_counter() - start


@lane("host", "", "default", "ppo_cartpole_env_steps_per_sec")
def _lane_host() -> None:
    total_steps = _env_steps(65536)
    elapsed = _run_cli("ppo_benchmarks", total_steps)
    steps_per_sec = total_steps / elapsed
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_env_steps_per_sec",
                "value": round(steps_per_sec, 2),
                "unit": "env-steps/s",
                "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 3),
            }
        )
    )


@lane("ondevice", "anakin", "ppo_cartpole_ondevice_env_steps_per_sec")
def _lane_ondevice() -> None:
    # The fused path retires 65536 steps in ~3s of loop time: at the host
    # metric's step count the measurement is interpreter/compile-bound, not
    # framework-bound. 16x the steps keeps the whole-wall convention while
    # the training loop dominates (still well under a minute).
    total_steps = _env_steps(1048576)
    elapsed = _run_cli("ppo_anakin_benchmarks", total_steps)
    steps_per_sec = total_steps / elapsed
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_ondevice_env_steps_per_sec",
                "value": round(steps_per_sec, 2),
                "unit": "env-steps/s",
                "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 3),
            }
        )
    )


@lane("sebulba", "ppo_cartpole_sebulba_env_steps_per_sec")
def _lane_sebulba() -> None:
    total_steps = _env_steps(65536)
    elapsed = _run_cli("ppo_sebulba_benchmarks", total_steps)
    steps_per_sec = total_steps / elapsed
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_sebulba_env_steps_per_sec",
                "value": round(steps_per_sec, 2),
                "unit": "env-steps/s",
                "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 3),
            }
        )
    )


@lane("replay", "sac_pendulum_replay_grad_steps_per_sec")
def _lane_replay() -> None:
    replay_mode = os.environ.get("BENCH_REPLAY_MODE", "device").strip().lower()
    if replay_mode not in ("device", "host"):
        raise SystemExit(f"Unknown BENCH_REPLAY_MODE '{replay_mode}' (expected 'device' or 'host')")
    total_steps = _env_steps(8192)
    exp = "sac_replay_benchmarks"
    elapsed = _run_cli(
        exp,
        total_steps,
        extra=[f"buffer.device_resident={'true' if replay_mode == 'device' else 'false'}"],
        keep_timer=True,
    )
    # Both modes execute the identical grant schedule (same Ratio, same
    # seeds), so per-mode throughput is directly comparable. Two views:
    # - end-to-end grad-steps/s (whole wall): on a CPU-only host the two
    #   modes tie — the gradient math dominates and there is no device
    #   boundary to cross;
    # - grad-steps per second of REPLAY-PATH time: the serialized host-side
    #   sample+stage segment each gradient step waits on — numpy sampling +
    #   device staging for the host tier vs one packed blob for the resident
    #   tier. This is exactly the host-in-the-loop cost the subsystem
    #   removes (and what a tunneled TPU multiplies by the wire latency), so
    #   it is the headline `value`.
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.utils.timer import timer as _timer

    cfg = compose([f"exp={exp}", f"algo.total_steps={total_steps}"])
    grad_steps = max(1, int(cfg.algo.replay_ratio * (total_steps - cfg.algo.learning_starts)))
    replay_path_s = _timer.compute().get("Time/replay_path_time", 0.0)
    value = grad_steps / replay_path_s if replay_path_s > 0 else 0.0
    print(
        json.dumps(
            {
                "metric": "sac_pendulum_replay_grad_steps_per_sec",
                "value": round(value, 2),
                "unit": "grad-steps per replay-path second",
                "mode": replay_mode,
                "grad_steps": grad_steps,
                "replay_path_s": round(replay_path_s, 3),
                "end_to_end_grad_steps_per_sec": round(grad_steps / elapsed, 2),
                "elapsed_s": round(elapsed, 2),
                # no vs_baseline: the PPO reference bar is env-steps/s —
                # dividing grad-steps/s by it would be a unit mismatch
            }
        )
    )


@lane("sac_sebulba", "sac_async", "sac_pendulum_sebulba_env_steps_per_sec")
def _lane_sac_sebulba() -> None:
    sac_mode = os.environ.get("BENCH_SAC_MODE", "async").strip().lower()
    if sac_mode not in ("async", "coupled"):
        raise SystemExit(f"Unknown BENCH_SAC_MODE '{sac_mode}' (expected 'async' or 'coupled')")
    # the coupled twin is a dedicated exp with the IDENTICAL recipe (model,
    # batch, replay ratio, env) so the ONLY difference between the two runs
    # is the topology
    exp = "sac_sebulba_benchmarks" if sac_mode == "async" else "sac_async_coupled_benchmarks"
    total_steps = _env_steps(8192)
    elapsed = _run_cli(exp, total_steps, keep_timer=True)
    # Both modes consume the identical grant schedule, so env-steps/s is
    # directly comparable. The replay-path seconds show WHERE the time went:
    # coupled = the serialized host sample+stage segment on the env-step
    # critical path; async = just the learner's append dispatch (packing +
    # transfer ride the actor threads).
    from sheeprl_tpu.utils.timer import timer as _timer

    timers = _timer.compute()
    print(
        json.dumps(
            {
                "metric": "sac_pendulum_sebulba_env_steps_per_sec",
                "value": round(total_steps / elapsed, 2),
                "unit": "env-steps/s",
                "mode": sac_mode,
                "elapsed_s": round(elapsed, 2),
                "replay_path_s": round(timers.get("Time/replay_path_time", 0.0), 3),
                "train_s": round(timers.get("Time/train_time", 0.0), 3),
                "env_interaction_s": round(timers.get("Time/env_interaction_time", 0.0), 3),
                # no vs_baseline: the PPO reference bar is a different
                # algorithm's env rate
            }
        )
    )


@lane("dreamer_sebulba", "dreamer_async", "dreamer_dummy_sebulba_env_steps_per_sec")
def _lane_dreamer_sebulba() -> None:
    dreamer_mode = os.environ.get("BENCH_DREAMER_MODE", "sebulba").strip().lower()
    if dreamer_mode not in ("sebulba", "coupled"):
        raise SystemExit(f"Unknown BENCH_DREAMER_MODE '{dreamer_mode}' (expected 'sebulba' or 'coupled')")
    # the coupled twin is a dedicated exp with the IDENTICAL recipe (model,
    # batch, sequence length, replay ratio, env) so the ONLY difference
    # between the two runs is the topology
    exp = "dreamer_sebulba_benchmarks" if dreamer_mode == "sebulba" else "dreamer_coupled_benchmarks"
    total_steps = _env_steps(4096)
    elapsed = _run_cli(exp, total_steps, keep_timer=True)
    # Both modes consume the identical grant schedule, so env-steps/s is
    # directly comparable. The per-segment seconds show WHERE the time went:
    # coupled = env + player inference + host window sampling + train, all
    # serialized per env step; sebulba = the learner's append + train only
    # (env/player/packing/transfer ride the actor threads).
    from sheeprl_tpu.utils.timer import timer as _timer

    timers = _timer.compute()
    print(
        json.dumps(
            {
                "metric": "dreamer_dummy_sebulba_env_steps_per_sec",
                "value": round(total_steps / elapsed, 2),
                "unit": "env-steps/s",
                "mode": dreamer_mode,
                "elapsed_s": round(elapsed, 2),
                "replay_path_s": round(timers.get("Time/replay_path_time", 0.0), 3),
                "train_s": round(timers.get("Time/train_time", 0.0), 3),
                "env_interaction_s": round(timers.get("Time/env_interaction_time", 0.0), 3),
                # no vs_baseline: the PPO reference bar is a different
                # algorithm's env rate
            }
        )
    )


@lane("population", "ppo_cartpole_population_env_steps_per_sec")
def _lane_population() -> None:
    pop_mode = os.environ.get("BENCH_POP_MODE", "vmapped").strip().lower()
    if pop_mode not in ("vmapped", "sequential"):
        raise SystemExit(f"Unknown BENCH_POP_MODE '{pop_mode}' (expected 'vmapped' or 'sequential')")
    pop_size = int(os.environ.get("BENCH_POP_SIZE", 8))
    # per-member steps, identical to the single-run ondevice recipe so the
    # pairing measures the topology (one dispatch vs P) and nothing else
    total_steps = _env_steps(65536)

    from sheeprl_tpu.analysis.tracecheck import tracecheck

    tracecheck.reset()
    if pop_mode == "vmapped":
        # seed-only population (hparams={} in the exp): every member runs the
        # EXACT recipe the sequential baseline runs
        elapsed = _run_cli(
            "ppo_anakin_population_benchmarks",
            total_steps,
            # hparams override: the exp's seed-only intent must survive the
            # algo default's lr grid through deep-merge at any BENCH_POP_SIZE
            extra=[f"algo.population.size={pop_size}", "algo.population.hparams={}"],
        )
        block_name = "ppo_anakin_pop.block"
    else:
        elapsed = 0.0
        for member in range(pop_size):
            elapsed += _run_cli("ppo_anakin_benchmarks", total_steps, extra=[f"seed={42 + member}"])
        block_name = "ppo_anakin.block"
    # compile counts come from the tracecheck dump payload — the SAME
    # artifact CI/`analysis tracecheck` read — not from scraping run logs
    ledger = tracecheck.dump(os.environ.get("BENCH_TRACECHECK_DUMP") or None)
    block = ledger["entries"].get(block_name, {})
    aggregate_steps = pop_size * total_steps
    # per-member rate = each member's own training rate: the vmapped members
    # share the whole wall-clock, a sequential member only its elapsed/P slice
    member_elapsed = elapsed if pop_mode == "vmapped" else elapsed / pop_size
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_population_env_steps_per_sec",
                "value": round(aggregate_steps / elapsed, 2),
                "unit": "aggregate env-steps/s",
                "mode": pop_mode,
                "population_size": pop_size,
                "per_member_env_steps_per_sec": round(total_steps / member_elapsed, 2),
                "block_compiles": int(block.get("compiles", 0)),
                "block_calls": int(block.get("calls", 0)),
                "elapsed_s": round(elapsed, 2),
                "vs_baseline": round((aggregate_steps / elapsed) / BASELINE_STEPS_PER_SEC, 3),
            }
        )
    )


@lane("scenario_matrix", "ppo_cartpole_scenario_matrix_env_steps_per_sec")
def _lane_scenario_matrix() -> None:
    scenario_mode = os.environ.get("BENCH_SCENARIO_MODE", "vmapped").strip().lower()
    if scenario_mode not in ("vmapped", "sequential"):
        raise SystemExit(
            f"Unknown BENCH_SCENARIO_MODE '{scenario_mode}' (expected 'vmapped' or 'sequential')"
        )
    pop_size = int(os.environ.get("BENCH_SCENARIO_SIZE", 8))
    # per-scenario steps, identical to the single-run ondevice recipe so the
    # pairing measures the topology (one dispatch vs P) and nothing else
    total_steps = _env_steps(65536)
    # the scenario ladder: P CartPole pole half-lengths spanning 0.25..1.0
    # (default 0.5) — genuinely different dynamics, same spaces/shapes
    lengths = [round(0.25 + i * 0.75 / max(1, pop_size - 1), 4) for i in range(pop_size)]

    import tempfile

    from sheeprl_tpu.analysis.tracecheck import tracecheck
    from sheeprl_tpu.fault.manager import find_latest_run_checkpoint
    from sheeprl_tpu.utils.checkpoint import load_state

    log_root = os.environ.get("BENCH_SCENARIO_LOG_ROOT") or tempfile.mkdtemp(prefix="scenario_bench_")

    def _fitness_of(run_root: str) -> List[float]:
        state = load_state(
            find_latest_run_checkpoint(os.path.join(run_root, "ppo_anakin_population", "CartPole-v1"))
        )
        return [round(float(v), 3) for v in state["fitness"]]

    tracecheck.reset()
    block_name = "ppo_anakin_pop.block"
    fitness: List[float] = []
    if scenario_mode == "vmapped":
        # seed-only hparams: every scenario trains the EXACT recipe the
        # sequential baseline runs; the env_params grid is the ONE axis
        ladder = "[" + ", ".join(str(v) for v in lengths) + "]"
        elapsed = _run_cli(
            "ppo_anakin_population_benchmarks",
            total_steps,
            extra=[
                f"algo.population.size={pop_size}",
                "algo.population.hparams={}",
                f"algo.population.env_params={{length: {ladder}}}",
                "seed=42",
                # save_last back on (the shared bench conditions disable it):
                # the per-scenario fitness is read from the final checkpoint
                "checkpoint.save_last=True",
                f"log_root={log_root}/vmapped",
            ],
        )
        fitness = _fitness_of(f"{log_root}/vmapped")
    else:
        elapsed = 0.0
        for i, length in enumerate(lengths):
            elapsed += _run_cli(
                "ppo_anakin_population_benchmarks",
                total_steps,
                extra=[
                    "algo.population.size=1",
                    "algo.population.hparams={}",
                    f"algo.population.env_params={{length: [{length}]}}",
                    "seed=42",
                    "checkpoint.save_last=True",
                    f"log_root={log_root}/seq_{i}",
                ],
            )
            fitness += _fitness_of(f"{log_root}/seq_{i}")
    # compile counts come from the tracecheck dump payload — the SAME
    # artifact CI/`analysis tracecheck` read — not from scraping run logs
    ledger = tracecheck.dump(os.environ.get("BENCH_TRACECHECK_DUMP") or None)
    block = ledger["entries"].get(block_name, {})
    aggregate_steps = pop_size * total_steps
    member_elapsed = elapsed if scenario_mode == "vmapped" else elapsed / pop_size
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_scenario_matrix_env_steps_per_sec",
                "value": round(aggregate_steps / elapsed, 2),
                "unit": "aggregate env-steps/s",
                "mode": scenario_mode,
                "population_size": pop_size,
                "scenario_lengths": lengths,
                "per_scenario_fitness": fitness,
                "fitness_spread": round(max(fitness) - min(fitness), 3) if fitness else None,
                # CartPole pays +1 per env-step under every pole length, so the
                # block fitness (rollout raw-reward mean) is structurally
                # rollout_steps for EVERY scenario: spread 0.0 is the
                # hand-computable expectation here and doubles as a ferry
                # check; cost-shaped envs (Pendulum g sweeps) show real spread
                "fitness_note": "CartPole raw-reward fitness == rollout_steps by construction",
                "per_member_env_steps_per_sec": round(total_steps / member_elapsed, 2),
                "block_compiles": int(block.get("compiles", 0)),
                "block_calls": int(block.get("calls", 0)),
                "elapsed_s": round(elapsed, 2),
                "vs_baseline": round((aggregate_steps / elapsed) / BASELINE_STEPS_PER_SEC, 3),
            }
        )
    )


@lane("env_zoo", "jax_env_zoo_env_steps_per_sec")
def _lane_env_zoo() -> None:
    # Raw env-side throughput: a jitted lax.scan of vmapped BatchedJaxEnv.step
    # (auto-reset included, traced default params, no agent in the loop) per
    # registered env across a batch ladder. This bounds what any Anakin
    # rollout can spend on env physics; compare against Sample Factory's
    # ~100k FPS full-training bar (arXiv 2006.11751) to see how far pure-JAX
    # env stepping is from being the bottleneck.
    import gymnasium as gym
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.envs.jax_envs import JAX_ENV_REGISTRY, BatchedJaxEnv, make_jax_env

    batches = [int(b) for b in os.environ.get("BENCH_ZOO_BATCHES", "128,1024,4096").split(",")]
    scan_len = int(os.environ.get("BENCH_ZOO_STEPS", 256))
    reps = int(os.environ.get("BENCH_ZOO_REPS", 3))

    per_env: Dict[str, Dict[str, float]] = {}
    for env_id in sorted(JAX_ENV_REGISTRY):
        env = make_jax_env(env_id)
        params = env.default_params()
        rates: Dict[str, float] = {}
        for batch in batches:
            benv = BatchedJaxEnv(env, batch)
            if isinstance(env.action_space, gym.spaces.Box):
                acts = jnp.zeros((batch, *env.action_space.shape), jnp.float32)
            else:
                acts = jnp.zeros((batch,), jnp.int32)

            def _rollout(state, _benv=benv, _acts=acts, _params=params):
                def _body(s, _):
                    s2, _, rew, _, _ = _benv.step(s, _acts, _params)
                    return s2, rew

                s, rews = jax.lax.scan(_body, state, None, length=scan_len)
                return s, rews.sum()

            roll = jax.jit(_rollout)
            state, _ = jax.jit(benv.reset)(jax.random.PRNGKey(0), params)
            state, warm = roll(state)  # compile outside the timed window
            warm.block_until_ready()
            start = time.perf_counter()
            for _ in range(reps):
                state, out = roll(state)
            out.block_until_ready()
            dt = time.perf_counter() - start
            rates[str(batch)] = round(batch * scan_len * reps / dt, 1)
        per_env[env_id] = rates
    top_batch = str(max(batches))
    print(
        json.dumps(
            {
                "metric": "jax_env_zoo_env_steps_per_sec",
                # headline: the SLOWEST registered env at the top of the
                # ladder — the conservative env-side budget
                "value": min(r[top_batch] for r in per_env.values()),
                "unit": "raw env-steps/s",
                "batch_ladder": batches,
                "scan_len": scan_len,
                "per_env": per_env,
                "note": (
                    "raw vmapped BatchedJaxEnv.step (auto-reset on, traced default params, no "
                    "agent); Sample Factory's ~100k-FPS bar (arXiv 2006.11751) is full training "
                    "throughput — these rates bound the env-physics share of an Anakin rollout"
                ),
            }
        )
    )


@lane("serve", "serve_policy_inference", "ppo_cartpole_serve_requests_per_sec")
def _lane_serve() -> None:
    # Offered-load latency/throughput SLO lane for the inference tier; all
    # knobs (BENCH_SERVE_MODE / _LOADS / _DURATION / _CLIENTS) documented in
    # benchmarks/serve_bench.py, results interpretation in howto/serving.md.
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    from serve_bench import main as serve_main

    serve_main()


@lane("serve_fleet", "fleet", "serve_fleet_requests_per_sec")
def _lane_serve_fleet() -> None:
    # Replicated-serving SLO lane: fleet (N=BENCH_FLEET_REPLICAS replica
    # PROCESSES behind the FleetRouter) vs single replica behind the same
    # router on identical offered load, with one replica SIGKILL per fleet
    # rep and dropped == 0 / errors == 0 asserted in-lane. Knobs in
    # benchmarks/serve_fleet_bench.py, interpretation in howto/serving.md.
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    from serve_fleet_bench import main as fleet_main

    fleet_main()


@lane("serve_flywheel", "flywheel", "serve_flywheel_rows_ingested_per_sec")
def _lane_serve_flywheel() -> None:
    # Production-loop SLO lane: closed-loop feedback clients against the
    # full flywheel topology (SAC server + spool transport + the REAL
    # `run --from-serve` learner subprocess under its supervisor), paired
    # learner-off vs learner-on phases on identical traffic, with
    # dropped == 0 / errors == 0 / rows_shed == 0 and nonzero learner ingest
    # asserted in-lane. Knobs (BENCH_FLYWHEEL_DURATION / _CLIENTS / _CKPT)
    # in benchmarks/serve_flywheel_bench.py, interpretation in
    # howto/serving.md#the-flywheel.
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    from serve_flywheel_bench import main as flywheel_main

    flywheel_main()


@lane("pod_restart", "pod", "pod_restart_mttr_s")
def _lane_pod_restart() -> None:
    # Gang-restart MTTR lane: real 2-process pods through the CLI with one
    # seeded kill-host injection per rep; MTTR = SIGKILL -> first
    # post-restart completed train iteration, and every rep must FINISH at
    # its configured total_steps (recovery that converges, not just
    # respawns). Knobs (BENCH_POD_WORKERS / _REPS / _KILL_AT / _TOTAL_STEPS
    # / _TIMEOUT) in benchmarks/pod_bench.py, interpretation in
    # howto/fault_tolerance.md#pod-training.
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    from pod_bench import main as pod_main

    pod_main()


@lane("kernels", "kernel", "kernel_tier_lax_over_pallas_median")
def _lane_kernels() -> None:
    # Pallas kernel tier microbench: every registered kernel timed through
    # its dispatch wrapper at 2-3 call-site shapes, pallas vs lax paired on
    # identical inputs (BENCH_KERNEL / BENCH_KERNEL_BACKEND / _REPS / _OUT in
    # benchmarks/kernel_bench.py). On a TPU-less host the pallas column is
    # interpret mode — a correctness vehicle, not a performance claim; see
    # the lane's in-payload note and howto/kernels.md.
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    from kernel_bench import main as kernel_main

    kernel_main()


@lane("serve_sessions", "sessions", "ppo_recurrent_serve_session_steps_per_sec")
def _lane_serve_sessions() -> None:
    # Stateful-session SLO lane: K closed-loop session clients against the
    # graft-sessions tier; BENCH_SESSIONS_MODE=batched|naive pairs the bucket
    # ladder against per-session dispatch on identical traffic. Knobs in
    # benchmarks/serve_sessions_bench.py, interpretation in howto/serving.md.
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    from serve_sessions_bench import main as sessions_main

    sessions_main()


def main() -> None:
    # Persistent XLA compilation cache: the PPO train/rollout programs cost
    # ~15s to compile; caching them across bench invocations measures the
    # framework, not the compiler.
    try:
        import jax

        # The reference PPO benchmark conditions are CPU (`fabric.accelerator:
        # cpu`); pin the whole platform so backend discovery never contacts a
        # remote accelerator — the tunneled chip can wedge for minutes and
        # this metric must not hang with it.
        from sheeprl_tpu.utils.utils import machine_keyed_cache_dir, pin_cpu_platform

        pin_cpu_platform("cpu")
        # The cache dir is keyed by host CPU features: XLA:CPU AOT entries
        # compiled on a different machine load with mismatch errors AND run
        # conservative code (−16% on this metric, BENCH_r04→r05) — a
        # feature-mismatched host must miss and recompile, not load poison.
        jax.config.update(
            "jax_compilation_cache_dir",
            machine_keyed_cache_dir(os.environ.get("BENCH_XLA_CACHE", "/root/repo/.xla_cache")),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    which = os.environ.get("BENCH_METRIC", "host").strip().lower()
    resolve_lane(which)()


if __name__ == "__main__":
    main()
