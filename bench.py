#!/usr/bin/env python
"""Benchmark harness: PPO CartPole env-steps/sec on the available accelerator.

Mirrors the reference benchmark conditions (``sheeprl/configs/exp/
ppo_benchmarks.yaml``: 65536 total steps, 1 env, sync, logging/checkpoints
off; reference wall-clock 81.27 s on 4 CPUs → ~806 env-steps/s, see
BASELINE.md).

``BENCH_METRIC`` selects the measured topology (default unchanged so the
recorded trajectory stays comparable):

- ``host`` (default) — ``ppo_cartpole_env_steps_per_sec``: the host-loop
  PPO (``exp=ppo_benchmarks``), one jitted policy dispatch per env step;
- ``ondevice`` — ``ppo_cartpole_ondevice_env_steps_per_sec``: the Anakin
  path (``exp=ppo_anakin_benchmarks``, same model/optim/data conditions)
  with the rollout fused in-graph over the pure-JAX CartPole
  (howto/on_device_rollout.md);
- ``sebulba`` — ``ppo_cartpole_sebulba_env_steps_per_sec``: the decoupled
  actor/learner pipeline (``exp=ppo_sebulba_benchmarks``, same
  model/optim/data conditions) with host env stepping, inference and
  learning overlapped (howto/decoupled_training.md);
- ``replay`` — ``sac_pendulum_replay_grad_steps_per_sec``: SAC
  gradient-steps/s through the replay data path
  (``exp=sac_replay_benchmarks``, replay-ratio-4 so sampling dominates).
  ``BENCH_REPLAY_MODE=device`` (default) runs the device-resident ring
  (``buffer.device_resident=true``, howto/device_replay.md);
  ``BENCH_REPLAY_MODE=host`` runs the host-sampling path — the paired
  driver compares the two on the same topology;
- ``sac_sebulba`` — ``sac_pendulum_sebulba_env_steps_per_sec``: the async
  off-policy pipeline (``exp=sac_sebulba_benchmarks``,
  howto/async_offpolicy.md) vs the coupled SAC host loop at an IDENTICAL
  recipe and replay ratio (``BENCH_SAC_MODE=async`` (default) | ``coupled``
  — the coupled twin is ``exp=sac_async_coupled_benchmarks``, whose
  per-env-step critical path serializes env step + inference + numpy
  sample + staging + train; the async run moves the first two onto actor
  threads and the sampling in-graph). Both report env-steps/s plus the
  Time/* split, so the serialized replay-path seconds the async topology
  removes from the env-step critical path are visible in the JSON.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_STEPS_PER_SEC = 65536 / 81.27  # reference PPO benchmark (README.md:100-117)


def main() -> None:
    # Persistent XLA compilation cache: the PPO train/rollout programs cost
    # ~15s to compile; caching them across bench invocations measures the
    # framework, not the compiler.
    try:
        import jax

        # The reference PPO benchmark conditions are CPU (`fabric.accelerator:
        # cpu`); pin the whole platform so backend discovery never contacts a
        # remote accelerator — the tunneled chip can wedge for minutes and
        # this metric must not hang with it.
        from sheeprl_tpu.utils.utils import machine_keyed_cache_dir, pin_cpu_platform

        pin_cpu_platform("cpu")
        # The cache dir is keyed by host CPU features: XLA:CPU AOT entries
        # compiled on a different machine load with mismatch errors AND run
        # conservative code (−16% on this metric, BENCH_r04→r05) — a
        # feature-mismatched host must miss and recompile, not load poison.
        jax.config.update(
            "jax_compilation_cache_dir",
            machine_keyed_cache_dir(os.environ.get("BENCH_XLA_CACHE", "/root/repo/.xla_cache")),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    which = os.environ.get("BENCH_METRIC", "host").strip().lower()
    if which in ("", "host", "default", "ppo_cartpole_env_steps_per_sec"):
        metric = "ppo_cartpole_env_steps_per_sec"
        exp = "ppo_benchmarks"
        default_steps = 65536
    elif which in ("ondevice", "anakin", "ppo_cartpole_ondevice_env_steps_per_sec"):
        metric = "ppo_cartpole_ondevice_env_steps_per_sec"
        exp = "ppo_anakin_benchmarks"
        # The fused path retires 65536 steps in ~3s of loop time: at the host
        # metric's step count the measurement is interpreter/compile-bound,
        # not framework-bound. 16x the steps keeps the whole-wall convention
        # while the training loop dominates (still well under a minute).
        default_steps = 1048576
    elif which in ("sebulba", "ppo_cartpole_sebulba_env_steps_per_sec"):
        metric = "ppo_cartpole_sebulba_env_steps_per_sec"
        exp = "ppo_sebulba_benchmarks"
        default_steps = 65536
    elif which in ("replay", "sac_pendulum_replay_grad_steps_per_sec"):
        metric = "sac_pendulum_replay_grad_steps_per_sec"
        exp = "sac_replay_benchmarks"
        default_steps = 8192
    elif which in ("sac_sebulba", "sac_async", "sac_pendulum_sebulba_env_steps_per_sec"):
        metric = "sac_pendulum_sebulba_env_steps_per_sec"
        sac_mode = os.environ.get("BENCH_SAC_MODE", "async").strip().lower()
        if sac_mode not in ("async", "coupled"):
            raise SystemExit(f"Unknown BENCH_SAC_MODE '{sac_mode}' (expected 'async' or 'coupled')")
        # the coupled twin is a dedicated exp with the IDENTICAL recipe
        # (model, batch, replay ratio, env) so the ONLY difference between
        # the two runs is the topology
        exp = "sac_sebulba_benchmarks" if sac_mode == "async" else "sac_async_coupled_benchmarks"
        default_steps = 8192
    else:
        raise SystemExit(
            f"Unknown BENCH_METRIC '{which}' (expected 'host', 'ondevice', 'sebulba', 'replay' "
            "or 'sac_sebulba')"
        )
    total_steps = int(os.environ.get("BENCH_TOTAL_STEPS", default_steps))
    overrides = [
        f"exp={exp}",
        f"algo.total_steps={total_steps}",
        "env.capture_video=False",
        "buffer.memmap=False",
        "checkpoint.save_last=False",
        "metric.log_level=0",
        "metric.disable_timer=True",
    ]
    if metric == "sac_pendulum_sebulba_env_steps_per_sec":
        # keep the Time/* instrumentation alive so the serialized replay-path
        # segment (coupled: numpy sample + staging; async: the learner's
        # append dispatch) is readable after the run
        overrides.remove("metric.disable_timer=True")
        overrides.append("metric.disable_timer=False")
    replay_mode = None
    if metric == "sac_pendulum_replay_grad_steps_per_sec":
        replay_mode = os.environ.get("BENCH_REPLAY_MODE", "device").strip().lower()
        if replay_mode not in ("device", "host"):
            raise SystemExit(f"Unknown BENCH_REPLAY_MODE '{replay_mode}' (expected 'device' or 'host')")
        overrides.append(f"buffer.device_resident={'true' if replay_mode == 'device' else 'false'}")
        # keep the Time/replay_path_time instrumentation alive: with
        # log_level=0 nothing ever resets it, so the accumulated sum is
        # readable after the run
        overrides.remove("metric.disable_timer=True")
        overrides.append("metric.disable_timer=False")
    from sheeprl_tpu.cli import run

    start = time.perf_counter()
    run(overrides)
    elapsed = time.perf_counter() - start
    if metric == "sac_pendulum_replay_grad_steps_per_sec":
        # Both modes execute the identical grant schedule (same Ratio, same
        # seeds), so per-mode throughput is directly comparable. Two views:
        # - end-to-end grad-steps/s (whole wall): on a CPU-only host the two
        #   modes tie — the gradient math dominates and there is no device
        #   boundary to cross;
        # - grad-steps per second of REPLAY-PATH time: the serialized
        #   host-side sample+stage segment each gradient step waits on —
        #   numpy sampling + device staging for the host tier vs one packed
        #   blob for the resident tier. This is exactly the host-in-the-loop
        #   cost the subsystem removes (and what a tunneled TPU multiplies
        #   by the wire latency), so it is the headline `value`.
        from sheeprl_tpu.config import compose
        from sheeprl_tpu.utils.timer import timer as _timer

        cfg = compose([f"exp={exp}", f"algo.total_steps={total_steps}"])
        grad_steps = max(1, int(cfg.algo.replay_ratio * (total_steps - cfg.algo.learning_starts)))
        replay_path_s = _timer.compute().get("Time/replay_path_time", 0.0)
        value = grad_steps / replay_path_s if replay_path_s > 0 else 0.0
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": round(value, 2),
                    "unit": "grad-steps per replay-path second",
                    "mode": replay_mode,
                    "grad_steps": grad_steps,
                    "replay_path_s": round(replay_path_s, 3),
                    "end_to_end_grad_steps_per_sec": round(grad_steps / elapsed, 2),
                    "elapsed_s": round(elapsed, 2),
                    # no vs_baseline: the PPO reference bar is env-steps/s —
                    # dividing grad-steps/s by it would be a unit mismatch
                }
            )
        )
        return
    if metric == "sac_pendulum_sebulba_env_steps_per_sec":
        # Both modes consume the identical grant schedule (same Ratio, same
        # recipe), so env-steps/s is directly comparable. The replay-path
        # seconds show WHERE the time went: for the coupled loop it is the
        # serialized host sample+stage segment on the env-step critical
        # path; for the async run it is just the learner's append dispatch
        # (packing + transfer ride the actor threads).
        from sheeprl_tpu.utils.timer import timer as _timer

        timers = _timer.compute()
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": round(total_steps / elapsed, 2),
                    "unit": "env-steps/s",
                    "mode": sac_mode,
                    "elapsed_s": round(elapsed, 2),
                    "replay_path_s": round(timers.get("Time/replay_path_time", 0.0), 3),
                    "train_s": round(timers.get("Time/train_time", 0.0), 3),
                    "env_interaction_s": round(timers.get("Time/env_interaction_time", 0.0), 3),
                    # no vs_baseline: the PPO reference bar is a different
                    # algorithm's env rate
                }
            )
        )
        return
    steps_per_sec = total_steps / elapsed
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(steps_per_sec, 2),
                "unit": "env-steps/s",
                "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
