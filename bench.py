#!/usr/bin/env python
"""Benchmark harness: PPO CartPole env-steps/sec on the available accelerator.

Mirrors the reference benchmark conditions (``sheeprl/configs/exp/
ppo_benchmarks.yaml``: 65536 total steps, 1 env, sync, logging/checkpoints
off; reference wall-clock 81.27 s on 4 CPUs → ~806 env-steps/s, see
BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_STEPS_PER_SEC = 65536 / 81.27  # reference PPO benchmark (README.md:100-117)


def main() -> None:
    # Persistent XLA compilation cache: the PPO train/rollout programs cost
    # ~15s to compile; caching them across bench invocations measures the
    # framework, not the compiler.
    try:
        import jax

        # The reference PPO benchmark conditions are CPU (`fabric.accelerator:
        # cpu`); pin the whole platform so backend discovery never contacts a
        # remote accelerator — the tunneled chip can wedge for minutes and
        # this metric must not hang with it.
        from sheeprl_tpu.utils.utils import pin_cpu_platform

        pin_cpu_platform("cpu")
        jax.config.update("jax_compilation_cache_dir", os.environ.get("BENCH_XLA_CACHE", "/root/repo/.xla_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    total_steps = int(os.environ.get("BENCH_TOTAL_STEPS", 65536))
    overrides = [
        "exp=ppo_benchmarks",
        f"algo.total_steps={total_steps}",
        "env.capture_video=False",
        "buffer.memmap=False",
        "checkpoint.save_last=False",
        "metric.log_level=0",
        "metric.disable_timer=True",
    ]
    from sheeprl_tpu.cli import run

    start = time.perf_counter()
    run(overrides)
    elapsed = time.perf_counter() - start
    steps_per_sec = total_steps / elapsed
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_env_steps_per_sec",
                "value": round(steps_per_sec, 2),
                "unit": "env-steps/s",
                "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
