"""Paired microbenchmarks for the Pallas kernel tier (howto/kernels.md).

Every registered kernel is timed through its PUBLIC dispatch wrapper at 2-3
realistic call-site shapes, once per backend, on identical inputs:

- ``lax`` — the plain-lax reference, i.e. exactly the inline graph every
  call site ran before the kernel tier existed;
- ``pallas`` — the ``custom_vjp``-wrapped Pallas kernel (compiled on TPU,
  interpret mode everywhere else).

Knobs:

- ``BENCH_KERNEL``           one kernel name, or ``all`` (default);
- ``BENCH_KERNEL_BACKEND``   ``pallas`` | ``lax`` | ``both`` (default);
- ``BENCH_KERNEL_REPS``      timed calls per case (default 30);
- ``BENCH_KERNEL_OUT``       also write the full JSON payload to this path.

CAVEAT — read before comparing columns: on a host without a TPU the Pallas
column measures INTERPRET MODE, a correctness/lowering vehicle with no
performance claim whatsoever — it is expected to LOSE to the fused XLA:CPU
reference, often by orders of magnitude. The paired CPU numbers exist to (a)
pin the reference cost of each call site and (b) catch interpret-mode
pathologies; the pallas-vs-lax verdict only means anything on a real TPU.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Tuple


def _cases() -> Dict[str, List[Tuple[str, Any]]]:
    """kernel name -> [(case label, thunk building (fn, args))]. Shapes
    mirror the real call sites: RSSM widths for the GRU gates, the Dreamer
    255-bucket return head, PPO ``(T, num_envs)`` rollouts, the SAC PER
    tree, Sebulba burst/sequence ring appends."""
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.ops import kernels as K

    key = jax.random.PRNGKey(0)

    def gru(batch, width):
        fused = jax.random.normal(key, (batch, 3 * width), jnp.float32)
        h = jax.random.normal(key, (batch, width), jnp.float32)
        return lambda backend: (lambda: K.gru_gates(fused, h, backend=backend))

    def loss(rows, buckets=255):
        logits = jax.nn.log_softmax(jax.random.normal(key, (rows, buckets), jnp.float32))
        value = jax.random.normal(key, (rows, 1), jnp.float32) * 5.0
        return lambda backend: (
            lambda: K.two_hot_symlog_loss(logits, value, backend=backend)
        )

    def decode(rows, buckets=255):
        logits = jax.random.normal(key, (rows, buckets), jnp.float32)
        return lambda backend: (lambda: K.two_hot_symexp_decode(logits, backend=backend))

    def gae(horizon, envs):
        r = jax.random.normal(key, (horizon, envs), jnp.float32)
        v = jax.random.normal(key, (horizon, envs), jnp.float32)
        d = (jax.random.uniform(key, (horizon, envs)) < 0.05).astype(jnp.float32)
        nv = jax.random.normal(key, (envs,), jnp.float32)
        return lambda backend: (lambda: K.gae(r, v, d, nv, 0.99, 0.95, backend=backend))

    def sumtree(leaves, batch):
        from sheeprl_tpu.replay import sumtree as st

        tree = st.init(leaves)
        pri = jax.random.uniform(key, (leaves,), jnp.float32) + 0.1
        tree = st.update(tree, jnp.arange(leaves), pri)
        u = jax.random.uniform(key, (batch,), jnp.float32)
        n_valid = jnp.asarray(leaves, jnp.int32)
        beta = jnp.float32(0.4)
        return lambda backend: (
            lambda: K.sumtree_sample(tree, u, n_valid, beta, backend=backend)
        )

    def scatter(capacity, envs, feat, slots):
        storage = jnp.zeros((capacity, envs, feat), jnp.float32)
        staged = jax.random.normal(key, (slots, envs, feat), jnp.float32)
        pos = jnp.arange(envs, dtype=jnp.int32) % capacity
        row = (pos[None, :] + jnp.arange(slots, dtype=jnp.int32)[:, None]) % capacity
        return lambda backend: (
            lambda: K.ragged_ring_scatter(storage, staged, row, pos, backend=backend)
        )

    return {
        "gru_gates": [
            ("b256_h512", gru(256, 512)),
            ("b1024_h512", gru(1024, 512)),
            ("b64_h1024", gru(64, 1024)),
        ],
        "two_hot_symlog_loss": [
            ("rows1024_k255", loss(1024)),
            ("rows4096_k255", loss(4096)),
        ],
        "two_hot_symexp_decode": [
            ("rows1024_k255", decode(1024)),
            ("rows4096_k255", decode(4096)),
        ],
        "gae": [
            ("t128_n16", gae(128, 16)),
            ("t128_n64", gae(128, 64)),
            ("t512_n16", gae(512, 16)),
        ],
        "sumtree_sample": [
            ("leaves4096_b256", sumtree(4096, 256)),
            ("leaves16384_b1024", sumtree(16384, 1024)),
        ],
        "ragged_ring_scatter": [
            ("c64_e8_f32_s4", scatter(64, 8, 32, 4)),
            ("c128_e16_f64_s8", scatter(128, 16, 64, 8)),
        ],
    }


def _time_case(thunk, reps: int) -> Dict[str, float]:
    import jax

    fn = jax.jit(lambda: thunk())
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return {
        "median_ms": round(samples[len(samples) // 2] * 1e3, 4),
        "best_ms": round(samples[0] * 1e3, 4),
        "compile_s": round(compile_s, 3),
    }


def main() -> None:
    import jax

    which = os.environ.get("BENCH_KERNEL", "all").strip().lower()
    backend_sel = os.environ.get("BENCH_KERNEL_BACKEND", "both").strip().lower()
    reps = int(os.environ.get("BENCH_KERNEL_REPS", 30))
    if backend_sel not in ("pallas", "lax", "both"):
        raise SystemExit(
            f"Unknown BENCH_KERNEL_BACKEND '{backend_sel}' (expected 'pallas', 'lax' or 'both')"
        )
    backends = ("pallas", "lax") if backend_sel == "both" else (backend_sel,)

    cases = _cases()
    if which != "all":
        if which not in cases:
            raise SystemExit(f"Unknown BENCH_KERNEL '{which}' (expected one of {sorted(cases)} or 'all')")
        cases = {which: cases[which]}

    on_tpu = jax.default_backend() == "tpu"
    results: Dict[str, Any] = {}
    ratios: List[float] = []
    for name, kernel_cases in cases.items():
        rows = {}
        for label, build in kernel_cases:
            row: Dict[str, Any] = {}
            for backend in backends:
                row[backend] = _time_case(build(backend), reps)
            if "pallas" in row and "lax" in row and row["pallas"]["median_ms"] > 0:
                row["lax_over_pallas"] = round(
                    row["lax"]["median_ms"] / row["pallas"]["median_ms"], 3
                )
                ratios.append(row["lax_over_pallas"])
            rows[label] = row
        results[name] = rows

    ratios.sort()
    payload = {
        "metric": "kernel_tier_lax_over_pallas_median",
        # headline: median over cases of lax_ms / pallas_ms — > 1 means the
        # Pallas tier wins; meaningful ONLY on a real TPU (see note)
        "value": ratios[len(ratios) // 2] if ratios else None,
        "unit": "x (lax median ms / pallas median ms)",
        "backend_mode": backend_sel,
        "jax_backend": jax.default_backend(),
        "pallas_execution": "compiled" if on_tpu else "interpret",
        "reps": reps,
        "kernels": results,
        "note": (
            "pallas column is compiled Mosaic on TPU but INTERPRET MODE on cpu/gpu hosts — "
            "interpret mode carries no performance claim and is expected to lose to the fused "
            "XLA reference there; on CPU read the lax column as the call-site cost baseline "
            "and treat the ratio as TPU-only signal"
        ),
    }
    out_path = os.environ.get("BENCH_KERNEL_OUT")
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
