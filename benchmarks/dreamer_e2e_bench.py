#!/usr/bin/env python
"""End-to-end Dreamer-V3 env-steps/s on real hardware.

Runs the ACTUAL training entry point (player loop + Ratio-granted train
steps, `sheeprl_tpu/algos/dreamer_v3/dreamer_v3.py`) on a real 64x64 pixel
environment and reports wall-clock env-frames/s — the flagship BASELINE.json
target (DreamerV3 Atari-100K env-steps/s >= 1.5x the V100 reference rate).

Atari/crafter aren't installable in this sandbox, so the default environment
is dm_control walker-walk from pixels via the named north-star overlay
(`exp=dreamer_v3_dmc_walker_walk`): same S model config, same 64x64x3 pixel
observation shape and replay machinery as the Atari-100K runs.

    python benchmarks/dreamer_e2e_bench.py [atari|dmc] [policy_steps] [overrides...]

``atari`` runs the Atari-100K shape on the deterministic ALE-protocol env
(exp=dreamer_v3_100k_atari_dummy): frame-skip 4, life-loss episode
structure, noop starts — the named benchmark's own dynamics. ``dmc`` (the
default) keeps the dm_control walker-walk analogue.

Reference context (BASELINE.md): DreamerV3 Crafter on a V100 does 1M frames
in 1d3h (~10.3 env-frames/s); MsPacman-100K on an RTX 3080 does 100K frames
in 14h (~2 env-frames/s). The 1.5x bar is therefore ~15.5 frames/s against
the V100 Crafter rate — the strictest reading.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    # `python benchmarks/<script>.py` puts benchmarks/ (not the repo root) at
    # sys.path[0]; make the package importable without an editable install.
    sys.path.insert(0, _REPO_ROOT)

V100_FRAMES_PER_S = 1_000_000 / (27 * 3600)  # Crafter, README.md:37-44


def main() -> None:
    args = sys.argv[1:]
    # usage: dreamer_e2e_bench.py [atari|dmc] [policy_steps] [overrides...]
    exp = "exp=dreamer_v3_dmc_walker_walk"
    if args and args[0] in ("atari", "dmc"):
        if args[0] == "atari":
            # Atari's own episode/reset dynamics (frame-skip 4, life-loss
            # resets, noop starts) on the deterministic ALE-protocol env —
            # the named Atari-100K shape rather than the walker analogue.
            exp = "exp=dreamer_v3_100k_atari_dummy"
        args = args[1:]
    policy_steps = int(args[0]) if args and args[0].isdigit() else 2000
    overrides = args[1:] if args and args[0].isdigit() else args

    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("BENCH_XLA_CACHE", os.path.join(_REPO_ROOT, ".xla_cache")),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from sheeprl_tpu.cli import check_configs, run_algorithm
    from sheeprl_tpu.config import compose

    cfg = compose(
        [
            exp,
            "env.num_envs=1",
            "env.capture_video=False",
            f"algo.total_steps={policy_steps}",
            "algo.learning_starts=260",
            "algo.run_test=False",
            # Atari-100K buffer shape; the walker overlay's 500K ring would
            # not leave HBM headroom for the XL-sized activations.
            "buffer.size=100000",
            "buffer.memmap=False",
            "buffer.checkpoint=False",
            "checkpoint.every=0",
            "checkpoint.save_last=False",
            "metric.log_every=1000000",
            "metric.log_level=0",
            "metric.disable_timer=True",
            *overrides,
        ]
    )
    action_repeat = int(cfg.env.action_repeat)
    total_frames = int(cfg.algo.total_steps) * action_repeat

    # the script dir is sys.path[0] when run as `python benchmarks/<script>.py`
    from calibration import calibration_verdict, device_calibration_ms, gate_quiet

    accel = str(cfg.fabric.get("accelerator", "auto"))
    calib_pre = gate_quiet(accel)
    tic = time.perf_counter()
    check_configs(cfg)
    run_algorithm(cfg)
    elapsed = time.perf_counter() - tic
    calib_post = device_calibration_ms(accel)

    frames_per_s = total_frames / elapsed
    print(
        json.dumps(
            {
                "benchmark": "dreamer_v3_e2e",
                "env": cfg.env.id,
                "policy_steps": int(cfg.algo.total_steps),
                "env_frames": total_frames,
                "elapsed_s": round(elapsed, 2),
                "env_frames_per_sec": round(frames_per_s, 2),
                "vs_v100_crafter_rate": round(frames_per_s / V100_FRAMES_PER_S, 2),
                **calibration_verdict(calib_pre, calib_post),
            }
        )
    )


if __name__ == "__main__":
    main()
