"""Fleet SLO bench for graft-fleet replicated serving.

Pairs a **fleet of N replica processes behind the FleetRouter** against a
**single replica behind the same router** on identical offered load, and —
because the fleet's whole claim is robustness — SIGKILLs one replica halfway
through every fleet repetition: the lane asserts ``dropped == 0`` and
``errors == 0`` (every submitted request got an answer; failovers and the
supervised respawn are invisible to clients) while reporting completed
throughput and client-observed p50/p99 round-trip latency.

Each replica is a REAL process: this script re-invokes itself with
``--replica --port P`` to build the same PPO CartPole policy as the
``BENCH_METRIC=serve`` lane (random init — latency/throughput do not care
about returns) and serve it through a full :class:`PolicyServer`.

Knobs (env vars): ``BENCH_FLEET_REPLICAS`` (default 3),
``BENCH_FLEET_LOADS`` (comma-separated offered req/s, default ``200``),
``BENCH_FLEET_DURATION`` (seconds per load, default 6),
``BENCH_FLEET_CLIENTS`` (client connections, default 4),
``BENCH_FLEET_BUCKETS`` (ladder, default ``1,8,32``),
``BENCH_FLEET_MODES`` (default ``fleet,single``).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List

import numpy as np

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def _build_policy():
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.envs.factory import make_env
    from sheeprl_tpu.parallel import Fabric
    from sheeprl_tpu.utils.registry import get_entrypoint, resolve_policy_builder

    cfg = compose(
        [
            "exp=ppo_benchmarks",
            "env.capture_video=False",
            "buffer.memmap=False",
            "metric.log_level=0",
            "metric.disable_timer=True",
            "checkpoint.save_last=False",
        ]
    )
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(cfg.seed)
    env = make_env(cfg, cfg.seed, 0, None, "serve_fleet_bench", vector_env_idx=0)()
    obs_space, act_space = env.observation_space, env.action_space
    env.close()
    builder = get_entrypoint(resolve_policy_builder(cfg.algo.name))
    return builder(fabric, cfg, obs_space, act_space, None)


def replica_main(port: int, buckets: List[int]) -> None:
    """One replica process: the bench policy behind a full PolicyServer."""
    from sheeprl_tpu.utils.utils import pin_cpu_platform

    pin_cpu_platform("cpu")
    from sheeprl_tpu.serve.server import PolicyServer, install_drain_handlers

    policy = _build_policy()
    drain = threading.Event()
    restore = install_drain_handlers(drain)
    server = PolicyServer(
        policy,
        {"buckets": buckets, "host": "127.0.0.1", "port": port, "max_wait_ms": 2.0, "supervisor": {"backoff": 0.05}},
    ).start()
    print(f"REPLICA_READY 127.0.0.1:{server.address[1]}", flush=True)
    try:
        while not drain.is_set():
            drain.wait(0.2)
    finally:
        server.stop()
        restore()


def _drive_load(addr, offered_rps: float, duration_s: float, n_clients: int) -> Dict[str, Any]:
    """n_clients paced connections through the router; per-request
    round-trip stamped client-side. Counted: sent, answered (== not
    dropped), action responses, error responses by kind."""
    per_client_interval = n_clients / max(offered_rps, 1e-9)
    results: Dict[str, Any] = {"sent": 0, "answered": 0, "ok": 0, "errors": [], "latencies": []}
    lock = threading.Lock()
    stop_at = time.perf_counter() + duration_s
    obs = {"state": [[0.1, -0.2, 0.05, 0.3]]}

    def client_loop(i: int) -> None:
        sock = socket.create_connection(addr, timeout=60.0)
        rfile = sock.makefile("rb")
        payload = (json.dumps({"obs": obs, "n": 1}) + "\n").encode()
        next_send = time.perf_counter() + (i / n_clients) * per_client_interval
        try:
            while True:
                now = time.perf_counter()
                if now >= stop_at:
                    return
                if now < next_send:
                    time.sleep(min(next_send - now, 0.005))
                    continue
                next_send += per_client_interval
                t0 = time.perf_counter()
                with lock:
                    results["sent"] += 1
                sock.sendall(payload)
                line = rfile.readline()
                if not line:
                    return  # connection lost: the sent request counts as dropped
                dt = time.perf_counter() - t0
                resp = json.loads(line.decode())
                with lock:
                    results["answered"] += 1
                    if "error" in resp:
                        results["errors"].append(resp["error"])
                    else:
                        results["ok"] += 1
                        results["latencies"].append(dt)
        finally:
            try:
                rfile.close()
                sock.close()
            except OSError:
                pass

    threads = [threading.Thread(target=client_loop, args=(i,)) for i in range(n_clients)]
    start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - start
    lat = np.sort(np.asarray(results["latencies"])) if results["latencies"] else np.asarray([0.0])
    return {
        "offered_rps": offered_rps,
        "completed_rps": round(results["ok"] / elapsed, 2),
        "sent": results["sent"],
        "answered": results["answered"],
        "dropped": results["sent"] - results["answered"],
        "errors": len(results["errors"]),
        "error_samples": results["errors"][:3],
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
        "elapsed_s": round(elapsed, 2),
    }


def _stand_up(n_replicas: int, buckets: List[int]):
    from sheeprl_tpu.fault.procsup import ProcessSupervisor
    from sheeprl_tpu.serve.fleet import FleetRouter, ReplicaEndpoint, free_port

    sup = ProcessSupervisor(lease_s=10.0, grace_s=600.0, backoff=0.1, max_restarts=3, name="bench-fleet")
    endpoints = []
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    for i in range(n_replicas):
        port = free_port()
        cmd = [
            sys.executable,
            os.path.abspath(__file__),
            "--replica",
            "--port",
            str(port),
            "--buckets",
            ",".join(str(b) for b in buckets),
        ]

        def spawn(cmd=cmd):
            return subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

        sup.spawn(f"replica-{i}", spawn)
        endpoints.append(ReplicaEndpoint(f"replica-{i}", "127.0.0.1", port, request_timeout_s=30.0))
    router = FleetRouter(
        endpoints,
        fleet_cfg={"health_poll_s": 0.1, "retry_budget": 3, "request_timeout_s": 30.0},
        procsup=sup,
        owns_replicas=True,
        port=0,
    ).start()
    if not router.wait_ready(timeout_s=600):
        router.stop()
        raise SystemExit("serve_fleet bench: replicas never became ready")
    return router, sup


def main() -> None:
    replicas = int(os.environ.get("BENCH_FLEET_REPLICAS", 3))
    loads = [float(x) for x in os.environ.get("BENCH_FLEET_LOADS", "200").split(",") if x.strip()]
    duration = float(os.environ.get("BENCH_FLEET_DURATION", 6))
    n_clients = int(os.environ.get("BENCH_FLEET_CLIENTS", 4))
    buckets = [int(b) for b in os.environ.get("BENCH_FLEET_BUCKETS", "1,8,32").split(",")]
    modes = [m.strip() for m in os.environ.get("BENCH_FLEET_MODES", "fleet,single").split(",") if m.strip()]

    for mode in modes:
        n = replicas if mode == "fleet" else 1
        router, sup = _stand_up(n, buckets)
        try:
            for offered in loads:
                killer = None
                if mode == "fleet":
                    # one replica kill per fleet rep, halfway through: the
                    # robustness claim measured, not assumed
                    def kill_one():
                        for h in sup.replicas():
                            if h.is_alive():
                                os.kill(h.pid(), signal.SIGKILL)
                                return

                    killer = threading.Timer(duration / 2.0, kill_one)
                    killer.start()
                rep = _drive_load(router.address, offered, duration, n_clients)
                if killer is not None:
                    killer.cancel()
                health = router.health()
                rep.update(
                    {
                        "metric": "serve_fleet_requests_per_sec",
                        "mode": mode,
                        "replicas": n,
                        "clients": n_clients,
                        "buckets": buckets,
                        "replica_kills": sum(h.kills for h in sup.replicas()) if mode == "fleet" else 0,
                        "replica_restarts": sum(h.restarts for h in sup.replicas()),
                        "router_retries": health["fleet"]["retries"],
                        "router_shed": health["fleet"]["shed"],
                        "sessions_rehomed": health["fleet"]["sessions_rehomed"],
                    }
                )
                print(json.dumps(rep), flush=True)
                # the lane's hard assertions: nothing dropped, nothing errored
                assert rep["dropped"] == 0, f"serve_fleet bench dropped {rep['dropped']} requests: {rep}"
                assert rep["errors"] == 0, f"serve_fleet bench errored requests: {rep['error_samples']}"
                if mode == "fleet":
                    assert rep["replica_kills"] >= 1, "fleet rep finished without its replica kill"
        finally:
            router.stop()


if __name__ == "__main__":
    if "--replica" in sys.argv:
        port = int(sys.argv[sys.argv.index("--port") + 1])
        raw = sys.argv[sys.argv.index("--buckets") + 1] if "--buckets" in sys.argv else "1,8,32"
        replica_main(port, [int(b) for b in raw.split(",")])
    else:
        main()
