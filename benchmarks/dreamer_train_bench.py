#!/usr/bin/env python
"""Dreamer-V3 train-step throughput on the available accelerator.

Measures the steady-state wall time of ONE fully-jitted gradient step
(dynamic-learning scan + imagination scan + actor/critic updates) at the
Atari-100K training shape — ``batch 16 x seq 64`` replayed frames — for a
chosen size config (default S, the Atari-100K config; see BASELINE.md).

Reports replayed-frames/s and the implied env-steps/s at ``replay_ratio``
(Atari-100K trains one gradient step per policy step: replay_ratio=1 over
batch*seq frames). Timing uses ``block_until_ready`` on device outputs —
no host pulls, so a tunneled chip measures the same as a local one.

    python benchmarks/dreamer_train_bench.py            # S size, 5 steps
    python benchmarks/dreamer_train_bench.py M 10
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    # `python benchmarks/<script>.py` puts benchmarks/ (not the repo root) at
    # sys.path[0]; make the package importable without an editable install.
    sys.path.insert(0, _REPO_ROOT)


def main() -> None:
    size = sys.argv[1] if len(sys.argv) > 1 else "S"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 5

    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("BENCH_XLA_CACHE", os.path.join(_REPO_ROOT, ".xla_cache")),
    )

    import gymnasium as gym
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.optim.builders import build_optimizer
    from sheeprl_tpu.parallel.fabric import Fabric

    cfg = compose(
        [
            "exp=dreamer_v3",
            f"algo=dreamer_v3_{size}",
            "env=dummy",
            "algo.per_rank_batch_size=16",
            "algo.per_rank_sequence_length=64",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "env.screen_size=64",
        ]
    )
    fabric = Fabric(devices=1)
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8)})
    n_act = 9  # MsPacman action set
    world_model, actor, critic, params, _ = build_agent(fabric, (n_act,), False, cfg, obs_space)
    txs = {
        "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
        "actor": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
    }
    opts = {
        "world": txs["world"].init(params["world_model"]),
        "actor": txs["actor"].init(params["actor"]),
        "critic": txs["critic"].init(params["critic"]),
    }
    opts = fabric.put_replicated(opts)
    moments = fabric.put_replicated(init_moments())
    train_fn = make_train_step(world_model, actor, critic, cfg, fabric.mesh, (n_act,), False, txs)

    G, T, B = 1, 64, 16
    rng = np.random.default_rng(0)
    sharding = NamedSharding(fabric.mesh, P(None, None, "dp"))
    data = {
        "rgb": rng.integers(0, 255, (G, T, B, 64, 64, 3)).astype(np.float32),
        "actions": np.eye(n_act, dtype=np.float32)[rng.integers(0, n_act, (G, T, B))],
        "rewards": rng.normal(size=(G, T, B, 1)).astype(np.float32),
        "terminated": np.zeros((G, T, B, 1), np.float32),
        "truncated": np.zeros((G, T, B, 1), np.float32),
        "is_first": np.zeros((G, T, B, 1), np.float32),
    }
    data = {k: jax.device_put(v, sharding) for k, v in data.items()}

    key = jax.random.PRNGKey(0)
    t0 = time.perf_counter()
    params, opts, moments, _ = train_fn(params, opts, moments, data, key, jnp.int32(0))
    jax.block_until_ready(params)
    compile_s = time.perf_counter() - t0

    # the script dir is sys.path[0] when run as `python benchmarks/<script>.py`
    from calibration import calibration_verdict, device_calibration_ms, gate_quiet

    calib_pre = gate_quiet()
    t0 = time.perf_counter()
    for i in range(steps):
        params, opts, moments, _ = train_fn(params, opts, moments, data, key, jnp.int32(i + 1))
    jax.block_until_ready(params)
    per_step = (time.perf_counter() - t0) / steps

    frames = T * B
    print(
        json.dumps(
            {
                "benchmark": f"dreamer_v3_{size}_train_step",
                "device": str(jax.devices()[0]),
                "batch": B,
                "seq_len": T,
                "compile_s": round(compile_s, 2),
                "train_step_ms": round(per_step * 1e3, 2),
                "replayed_frames_per_sec": round(frames / per_step, 1),
                **calibration_verdict(calib_pre, device_calibration_ms()),
            }
        )
    )


if __name__ == "__main__":
    main()
