#!/usr/bin/env python
"""Learning-evidence harness: run a REAL training entry point through the CLI
and record every finished-episode return the main logs.

The reference publishes trained-agent quality (``/root/reference/README.md:24-80``:
DreamerV3 Crafter 12.1, MsPacman 1542, ...). This harness is the repo's
equivalent evidence channel at sandbox-feasible scales: it spies on
``MetricAggregator.update`` / ``__contains__`` so every ``Rewards/rew_avg``
update the algorithm main emits (one per finished episode, in time order) is
captured, without requiring the exp config to declare the metric.

Usage::

    python benchmarks/learning_bench.py <tag> <threshold> <window> <override...>

    tag        label for the JSON line / artifact
    threshold  mean return over the last <window> episodes must reach this
    window     trailing-episode window for the final score
    overrides  passed verbatim to the CLI (first one usually ``exp=...``)

Prints one JSON line::

    {"tag", "episodes", "first_window_mean", "last_window_mean", "best_window_mean",
     "threshold", "passed", "elapsed_s", "returns": [...]}

Exit status 0 iff the threshold is met (so shell scripts can gate on it).
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    # When run as `python benchmarks/learning_bench.py` the script dir is
    # sys.path[0]; make the package importable without an editable install.
    sys.path.insert(0, _REPO_ROOT)


def capture_returns(overrides: list[str]) -> list[float]:
    """Run the CLI with the given overrides; return finished-episode returns in order."""
    import sheeprl_tpu.utils.metric as metric_mod

    returns: list[float] = []
    orig_update = metric_mod.MetricAggregator.update
    orig_contains = metric_mod.MetricAggregator.__contains__

    def spy_update(self, name, value):
        if name == "Rewards/rew_avg":
            try:
                v = float(value)
            except Exception:
                v = float("nan")
            returns.append(v)
        if name in self.metrics:
            orig_update(self, name, value)

    def spy_contains(self, name):
        if name == "Rewards/rew_avg":
            return True
        return orig_contains(self, name)

    metric_mod.MetricAggregator.update = spy_update
    metric_mod.MetricAggregator.__contains__ = spy_contains
    try:
        from sheeprl_tpu.cli import run

        run(list(overrides))
    finally:
        metric_mod.MetricAggregator.update = orig_update
        metric_mod.MetricAggregator.__contains__ = orig_contains
    return returns


def main() -> None:
    if len(sys.argv) < 4:
        print(__doc__)
        raise SystemExit(2)
    tag = sys.argv[1]
    threshold = float(sys.argv[2])
    window = int(sys.argv[3])
    if window < 1:
        print(f"window must be >= 1, got {window}")
        raise SystemExit(2)
    overrides = sys.argv[4:]

    # Same cache hygiene as bench.py: measure the framework, not the compiler
    # (keyed by host CPU features so AOT entries never cross machine types).
    try:
        import jax

        from sheeprl_tpu.utils.utils import machine_keyed_cache_dir

        jax.config.update(
            "jax_compilation_cache_dir",
            machine_keyed_cache_dir(os.environ.get("BENCH_XLA_CACHE", os.path.join(_REPO_ROOT, ".xla_cache"))),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    start = time.perf_counter()
    returns = capture_returns(overrides)
    elapsed = time.perf_counter() - start

    finite = [r for r in returns if math.isfinite(r)]
    w = min(window, max(len(finite), 1))
    first_mean = sum(finite[:w]) / w if finite else float("nan")
    last_mean = sum(finite[-w:]) / w if finite else float("nan")
    best_mean = float("nan")
    if finite:
        best_mean = max(
            sum(finite[i : i + w]) / w for i in range(0, max(len(finite) - w + 1, 1))
        )
    # The contract is "mean over the last <window> episodes" — a run that
    # finished fewer episodes than the window must not pass on a tiny sample.
    passed = len(finite) >= window and last_mean >= threshold

    print(
        json.dumps(
            {
                "tag": tag,
                "episodes": len(finite),
                "first_window_mean": round(first_mean, 2),
                "last_window_mean": round(last_mean, 2),
                "best_window_mean": round(best_mean, 2),
                "threshold": threshold,
                "passed": passed,
                "elapsed_s": round(elapsed, 1),
                "returns": [round(r, 2) for r in finite],
            }
        )
    )
    raise SystemExit(0 if passed else 1)


if __name__ == "__main__":
    main()
