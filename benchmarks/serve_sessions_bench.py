"""Offered-load SLO bench for graft-sessions: K concurrent stateful clients.

Builds a ppo_recurrent stateful policy (the LSTM-hidden session family),
stands up the full serving stack — session engine + cache, micro-batching
scheduler, versioned weight store — and drives it with K CLOSED-LOOP session
clients: each client is one user streaming sequential steps (a session can
only send step t+1 after receiving step t — that is what session traffic IS),
so the lane reports aggregate session-steps/s and p50/p99 step latency, with
one hot weight swap published mid-run (sessions must ride it live:
``sessions_reset == 0`` is asserted).

``BENCH_SESSIONS_MODE`` pairs the two dispatch disciplines on identical
traffic:

- ``batched`` (default) — the bucket ladder: concurrent sessions' states are
  gathered into ONE padded ``serve.session[N].step`` dispatch per admitted
  batch (GA3C's predictor queue, stateful);
- ``naive``  — per-session dispatch: ``session.buckets=[1]`` +
  ``max_batch=1``, every session step is its own bucket-1 program call — the
  per-user-model-replica discipline a session server without cross-session
  batching degenerates to.

Knobs (env vars): ``BENCH_SESSIONS`` (concurrent sessions, default 32),
``BENCH_SESSIONS_DURATION`` (seconds, default 6),
``BENCH_SESSIONS_BUCKETS`` (batched-mode ladder, default ``1,8,32``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List


def _build_policy():
    import gymnasium as gym
    import numpy as np

    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel import Fabric
    from sheeprl_tpu.utils.registry import get_entrypoint, resolve_policy_builder

    cfg = compose(
        [
            "exp=ppo_recurrent",
            "env=gym",
            "env.capture_video=False",
            "buffer.memmap=False",
            "fabric.devices=1",
            "metric.log_level=0",
            "checkpoint.save_last=False",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(cfg.seed)
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (4,), np.float32)})
    act_space = gym.spaces.Discrete(2)
    builder = get_entrypoint(resolve_policy_builder(cfg.algo.name))
    # fresh params: session-step latency/throughput does not care about returns
    return builder(fabric, cfg, obs_space, act_space, None)


def main() -> None:
    import numpy as np

    mode = os.environ.get("BENCH_SESSIONS_MODE", "batched").strip().lower()
    if mode not in ("batched", "naive"):
        raise SystemExit(f"Unknown BENCH_SESSIONS_MODE '{mode}' (expected 'batched' or 'naive')")
    n_sessions = int(os.environ.get("BENCH_SESSIONS", "32"))
    duration = float(os.environ.get("BENCH_SESSIONS_DURATION", "6"))
    buckets = [int(x) for x in os.environ.get("BENCH_SESSIONS_BUCKETS", "1,8,32").split(",") if x.strip()]

    from sheeprl_tpu.serve.server import PolicyServer

    policy = _build_policy()
    serve_cfg = {
        "mode": "greedy",
        "max_wait_ms": 2.0,
        "queue_bound": 1024,
        "port": None,
        "session": {"buckets": buckets, "max_sessions": max(64, 2 * n_sessions), "ttl_s": 600.0},
    }
    if mode == "naive":
        # per-session dispatch: no cross-session batching, one bucket-1
        # program call per step
        serve_cfg["session"]["buckets"] = [1]
        serve_cfg["max_batch"] = 1
        serve_cfg["max_wait_ms"] = 0.0
    server = PolicyServer(policy, serve_cfg)
    server.start(with_socket=False)

    stop_at = time.perf_counter() + duration
    latencies: List[float] = []
    lat_lock = threading.Lock()
    counters = {"steps": 0, "errors": 0}

    def client_loop(idx: int) -> None:
        rng = np.random.default_rng(idx)
        while time.perf_counter() < stop_at:
            obs = {"state": rng.standard_normal(4).astype(np.float32)}
            t0 = time.perf_counter()
            try:
                server.client.act(obs, session_id=f"user-{idx}", timeout=120.0)
            except Exception:
                with lat_lock:
                    counters["errors"] += 1
                continue
            with lat_lock:
                latencies.append(time.perf_counter() - t0)
                counters["steps"] += 1

    threads = [threading.Thread(target=client_loop, args=(i,), daemon=True) for i in range(n_sessions)]
    start = time.perf_counter()
    for t in threads:
        t.start()
    # one hot weight swap mid-run: sessions must ride it live
    time.sleep(duration / 2)
    import jax

    _, current = server.weights.pull()
    swap_version = server.weights.publish_params(jax.tree.map(lambda x: x + 1e-3, current))
    for t in threads:
        t.join(timeout=duration + 180.0)
    elapsed = time.perf_counter() - start
    sessions_snap = server.engine.cache.snapshot()
    engine_stats = server.engine.stats()
    server.stop()

    lat = np.sort(np.asarray(latencies)) if latencies else np.zeros(1)
    assert sessions_snap["resets"] == 0, "a weight swap reset live sessions"
    print(
        json.dumps(
            {
                "metric": "ppo_recurrent_serve_session_steps_per_sec",
                "value": round(counters["steps"] / elapsed, 1),
                "unit": "session-steps/s",
                "mode": mode,
                "sessions": n_sessions,
                "buckets": serve_cfg["session"]["buckets"],
                "duration_s": round(elapsed, 2),
                "steps": counters["steps"],
                "errors": counters["errors"],
                "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
                "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
                "swap_version": swap_version,
                "sessions_live": sessions_snap["live"],
                "sessions_reset": sessions_snap["resets"],
                "batch_fill_ratio": engine_stats["batch_fill_ratio"],
                "dispatches": engine_stats["dispatches"],
                "steps_per_dispatch": round(counters["steps"] / max(1, engine_stats["dispatches"]), 2),
            }
        )
    )


if __name__ == "__main__":
    main()
