"""Chip-quietness gate shared by every wall-clock benchmark.

The sandbox TPU is time-shared between tenants: the same jitted program has
been observed ~11x slower under co-tenant load (2.14 ms -> 24.6 ms in round
3 — both readings were later shown to carry the optimistic-mode timing
artifact, BENCH_NOTES "transport latency modes", but the relative swing is
real), and round 3's flagship number was silently re-measured 40% low
during a loud window (BENCH_NOTES.md "Measurement caveat"). A bench run is
therefore only a measurement if the chip was quiet when it started AND when
it ended — anything else is a load report.

``gate_quiet()`` probes a fixed ~1 GFLOP matmul chain, retries while the
chip is loud, and REFUSES (exit status 3) if it never quiets down; benches
stamp the pre/post readings plus a pass/fail verdict into their JSON line so
a number can never be quoted without its measurement conditions. When the
bench is pinned to the host CPU it also pins ``jax_platforms`` so backend
discovery can never touch the tunneled TPU (merely initializing it can hang
for hours when the tunnel is wedged).

Env knobs: ``BENCH_CALIB_THRESHOLD_MS`` (default 3.0 — the quiet v5e reads
~1 ms), ``BENCH_CALIB_RETRIES`` (default 10), ``BENCH_CALIB_WAIT_S``
(default 30), ``BENCH_ALLOW_LOUD=1`` to record a loud run anyway (stamped
as failed calibration).
"""

from __future__ import annotations

import json
import os
import sys
import time

__all__ = ["device_calibration_ms", "gate_quiet", "calibration_verdict", "PROBE_FAILED"]

THRESHOLD_MS = float(os.environ.get("BENCH_CALIB_THRESHOLD_MS", "3.0"))
# Sentinel for "the probe itself errored" — distinct from None (= CPU bench,
# not time-shared, nothing to gate). A failed probe can never certify a
# quiet chip, so it gates/stamps as a failure, not as a CPU run.
PROBE_FAILED = -1.0


def pin_platform_for(accelerator: "str | None") -> None:
    """Pin ``jax_platforms=cpu`` for CPU-pinned benches BEFORE any backend
    discovery. No-op for accelerator=auto/tpu."""
    from sheeprl_tpu.utils.utils import pin_cpu_platform

    pin_cpu_platform(accelerator)


def device_calibration_ms(accelerator: "str | None" = None) -> "float | None":
    """Marginal warm time of a fixed ~1 GFLOP matmul chain on the default
    accelerator, measured over a pipelined run of 50 chained dispatches.

    The marginal (pipelined) time is used — NOT per-call ``block_until_ready``
    latency — because the tunneled transport charges a ~100 ms round-trip per
    *synchronization* once the process has done any device→host pull (see
    BENCH_NOTES "transport latency modes"): a per-call-sync probe would read
    ~100 ms in any process that has trained, regardless of chip load. The
    marginal time excludes that constant and scales with actual co-tenant
    load (quiet v5e: ~1 ms; observed under load: 10-25 ms).

    Returns None for CPU benches (not time-shared, nothing to gate) and
    :data:`PROBE_FAILED` when the probe itself errors."""
    if accelerator is not None and str(accelerator).lower() == "cpu":
        return None
    try:
        import jax
        import jax.numpy as jnp

        if jax.default_backend() == "cpu":
            return None

        @jax.jit
        def chain(x):
            for _ in range(8):
                x = jnp.tanh(x @ x)
            return x

        import numpy as np

        x = jnp.ones((512, 512), jnp.bfloat16)
        # A tiny device→host pull first: before the first pull the transport
        # runs an optimistic completion mode whose timings are insensitive to
        # chip load (a fresh-process probe would read ~0.04 ms even under
        # load); the pull switches it to real syncs so pre- and post-run
        # readings measure the same thing.
        np.asarray(chain(x)[0, 0])
        chain(x).block_until_ready()  # compile + warm
        t0 = time.perf_counter()
        chain(x).block_until_ready()
        t_one = time.perf_counter() - t0  # one dispatch + one sync
        y = x
        t0 = time.perf_counter()
        for _ in range(50):
            y = chain(y)
        y.block_until_ready()
        t_fifty = time.perf_counter() - t0  # 50 dispatches + one sync
        marginal = max((t_fifty - t_one) / 49.0, t_fifty / 50.0 if t_fifty < t_one else 0.0)
        return round(marginal * 1e3, 2)
    except Exception:
        return PROBE_FAILED


def _quiet(reading: "float | None") -> bool:
    return reading is None or (reading != PROBE_FAILED and reading <= THRESHOLD_MS)


def gate_quiet(accelerator: "str | None" = None) -> "float | None":
    """Block until the chip is quiet; refuse if it never is.

    Pins the platform for CPU benches, then probes up to
    ``BENCH_CALIB_RETRIES`` + 1 times with ``BENCH_CALIB_WAIT_S`` sleeps.
    Returns the passing reading (None on CPU); on exhaustion prints the
    refusal and exits with status 3 unless ``BENCH_ALLOW_LOUD=1``.
    """
    pin_platform_for(accelerator)
    retries = int(os.environ.get("BENCH_CALIB_RETRIES", "10"))
    wait_s = float(os.environ.get("BENCH_CALIB_WAIT_S", "30"))
    reading = device_calibration_ms(accelerator)
    for attempt in range(retries + 1):
        if _quiet(reading):
            return reading
        if attempt == retries:
            break  # the last probe was checked — don't sleep again
        print(
            json.dumps(
                {
                    "calibration_wait": attempt + 1,
                    "device_calibration_ms": reading,
                    "threshold_ms": THRESHOLD_MS,
                }
            ),
            file=sys.stderr,
        )
        time.sleep(wait_s)
        reading = device_calibration_ms(accelerator)
    if os.environ.get("BENCH_ALLOW_LOUD") == "1":
        return reading
    print(
        f"chip never quieted: calibration {reading} ms > {THRESHOLD_MS} ms after {retries} retries "
        "(set BENCH_ALLOW_LOUD=1 to record a loud run anyway)",
        file=sys.stderr,
    )
    raise SystemExit(3)


def calibration_verdict(pre: "float | None", post: "float | None") -> dict:
    """The JSON fields every bench stamps next to its number."""
    if pre is None and post is None:
        return {"calibration": "cpu"}
    readings = [r for r in (pre, post) if r is not None]
    failed_probe = any(r == PROBE_FAILED for r in readings)
    ok = not failed_probe and all(r <= THRESHOLD_MS for r in readings)
    verdict = {
        "device_calibration_ms": [pre, post],
        "calibration_threshold_ms": THRESHOLD_MS,
        "calibration": "pass" if ok else "FAIL",
    }
    if failed_probe:
        verdict["calibration_probe_failed"] = True
    return verdict
