"""Flywheel SLO bench: does the production loop cost the serving tier?

Stands up the full graft-flywheel stack in its real process topology — a
SAC :class:`~sheeprl_tpu.serve.server.PolicyServer` serving a trained
checkpoint, the spool-backed :class:`~sheeprl_tpu.serve.flywheel.TrajectoryLog`
behind the resolve path, and the REAL learner subprocess (``run
--from-serve``) under its :class:`~sheeprl_tpu.serve.flywheel.LearnerSupervisor`
— and drives it with closed-loop feedback clients (every request grades the
previous action on its stream, so each turn completes a production
transition into the spool).

Two phases on identical traffic:

- ``learner-off`` — flywheel disabled entirely: the pure serving baseline;
- ``learner-on`` — flywheel spooling + live learner ingesting and
  publishing: the number an operator compares against the baseline.

Reported per phase: completed requests/s, p50/p99 request latency; for the
on-phase additionally rows-ingested/s (from the learner's status file),
learner grad steps, and the published step. Asserted IN-LANE: zero dropped
requests, zero request errors, zero shed rows, and a learner that actually
consumed production rows — a flywheel that silently sheds or a learner that
never ingests makes the lane FAIL, not emit a pretty number.

Knobs (env vars): ``BENCH_FLYWHEEL_DURATION`` (seconds per phase, default
6), ``BENCH_FLYWHEEL_CLIENTS`` (closed-loop client threads, default 4),
``BENCH_FLYWHEEL_CKPT`` (reuse an existing SAC checkpoint instead of
training a tiny one), ``BENCH_SERVE_BUCKETS`` (ladder, default ``1,4,8``).
Interpretation notes in ``howto/serving.md#the-flywheel``.
"""

from __future__ import annotations

import glob
import json
import os
import tempfile
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

SAC_TINY = [
    "exp=sac",
    "env=dummy",
    "env.id=continuous_dummy",
    "env.num_envs=2",
    "env.sync_env=True",
    "env.capture_video=False",
    "dry_run=True",
    "buffer.memmap=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "checkpoint.save_last=True",
    "algo.run_test=False",
    "algo.per_rank_batch_size=8",
    "algo.mlp_keys.encoder=[state]",
    "algo.hidden_size=16",
]


def _checkpoint(workdir: str) -> str:
    given = os.environ.get("BENCH_FLYWHEEL_CKPT", "").strip()
    if given:
        return given
    from sheeprl_tpu.cli import run

    run(SAC_TINY + [f"log_root={workdir}/train"])
    ckpts = sorted(glob.glob(f"{workdir}/train/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)
    if not ckpts:
        raise SystemExit("flywheel bench: tiny SAC train produced no checkpoint")
    return ckpts[-1]


def _build(ckpt: str):
    from sheeprl_tpu.cli import _merged_ckpt_cfg
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.envs.factory import make_env
    from sheeprl_tpu.fault.manager import load_state
    from sheeprl_tpu.parallel import Fabric
    from sheeprl_tpu.serve.server import resolve_builder_state
    from sheeprl_tpu.utils.registry import get_entrypoint, resolve_policy_builder

    serve_cfg = compose(
        [f"checkpoint_path={ckpt}", "fabric.accelerator=cpu"], config_name="serve_config"
    )
    cfg = _merged_ckpt_cfg(
        serve_cfg,
        "flywheel_bench",
        capture_video=False,
        # the learner subprocess reads its knobs from cfg.serve.flywheel
        extra={"serve": dict(serve_cfg.get("serve", {}) or {})},
    )
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(cfg.seed)
    state = load_state(ckpt)
    env = make_env(cfg, cfg.seed, 0, None, "flywheel_bench", vector_env_idx=0)()
    obs_space, act_space = env.observation_space, env.action_space
    env.close()
    builder = get_entrypoint(resolve_policy_builder(cfg.algo.name))
    agent_state, builder_kwargs = resolve_builder_state(builder, state, ckpt, str(cfg.algo.name))
    policy = builder(fabric, cfg, obs_space, act_space, agent_state, **builder_kwargs)
    return cfg, policy


def _drive_closed_loop(
    policy, scheduler, duration_s: float, n_clients: int
) -> Dict[str, Any]:
    """Closed-loop feedback clients: each thread is one production stream —
    request, wait for the action, grade it on the NEXT request. Latency is
    stamped at worker resolve time."""
    import numpy as np

    counters = {"submitted": 0, "errors": 0, "completed": 0}
    latencies: List[float] = []
    lock = threading.Lock()
    stop_at = time.perf_counter() + duration_s

    def client(idx: int) -> None:
        rng = np.random.default_rng(idx)
        turn = 0
        while time.perf_counter() < stop_at:
            obs = policy.prepare({"state": rng.standard_normal(10).astype(np.float32)}, 1)
            kw: Dict[str, Any] = {"stream": f"bench-client-{idx}"}
            if turn > 0:
                kw["reward"] = 1.0
                kw["done"] = 1.0 if turn % 16 == 0 else 0.0
            try:
                req = scheduler.submit(obs, timeout=60.0, **kw)
                with lock:
                    counters["submitted"] += 1
            except Exception:
                with lock:
                    counters["errors"] += 1
                continue
            if not req.event.wait(timeout=120.0) or req.error is not None:
                with lock:
                    counters["errors"] += 1
                continue
            with lock:
                counters["completed"] += 1
                latencies.append(req.latency_s)
            turn += 1

    threads = [threading.Thread(target=client, args=(i,), daemon=True) for i in range(n_clients)]
    start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 180.0)
    elapsed = time.perf_counter() - start
    lat = np.sort(np.asarray(latencies)) if latencies else np.zeros(1)
    return {
        "duration_s": round(elapsed, 2),
        "submitted": counters["submitted"],
        "completed": counters["completed"],
        "dropped": counters["submitted"] - counters["completed"],
        "errors": counters["errors"],
        "throughput_rps": round(counters["completed"] / elapsed, 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
    }


def _run_phase(
    cfg, policy, duration: float, n_clients: int, flywheel_dir: Optional[str]
) -> Dict[str, Any]:
    """One phase = one fresh PolicyServer (+ learner when ``flywheel_dir``)."""
    from sheeprl_tpu.serve.flywheel import LearnerSupervisor, read_learner_status
    from sheeprl_tpu.serve.server import PolicyServer

    serve_cfg: Dict[str, Any] = {
        "buckets": [int(x) for x in os.environ.get("BENCH_SERVE_BUCKETS", "1,4,8").split(",") if x.strip()],
        "mode": "greedy",
        "max_wait_ms": 1.0,
        "queue_bound": 1024,
        "port": None,
    }
    learner_sup = None
    if flywheel_dir is not None:
        serve_cfg["flywheel"] = {
            "enabled": True,
            "dir": flywheel_dir,
            "replica": "bench-replica",
            "block_rows": 64,
            "flush_s": 0.1,
        }
        cfg["serve"]["flywheel"] = {
            **dict(cfg["serve"].get("flywheel") or {}),
            "enabled": True,
            "dir": flywheel_dir,
            "poll_s": 0.1,
            "ingest_rows": 16,
            "grad_max": 4,
            "replay_ratio": 0.5,
            "learning_starts_rows": 64,
            "buffer_size": 4096,
            "publish_rows": 256,
        }
    server = PolicyServer(policy, serve_cfg)
    server.start(with_socket=False)
    ticker_stop = threading.Event()
    ticker = None
    try:
        if flywheel_dir is not None:
            learner_sup = LearnerSupervisor(cfg, flywheel_dir)

            def _tick() -> None:
                while not ticker_stop.is_set():
                    learner_sup.tick()
                    ticker_stop.wait(0.2)

            ticker = threading.Thread(target=_tick, daemon=True)
            ticker.start()
            # the phase measures steady state, not learner cold-start: wait
            # for the first ingested rows before opening the traffic window
            warm = {"deadline": time.monotonic() + 240.0}
            warm_sched = server.scheduler
            import numpy as np

            rng = np.random.default_rng(7)
            turn = 0
            while time.monotonic() < warm["deadline"]:
                obs = policy.prepare({"state": rng.standard_normal(10).astype(np.float32)}, 1)
                kw: Dict[str, Any] = {"stream": "bench-warmup"}
                if turn > 0:
                    kw["reward"] = 0.0
                    kw["done"] = 0.0
                req = warm_sched.submit(obs, timeout=60.0, **kw)
                req.event.wait(timeout=120.0)
                turn += 1
                status = read_learner_status(flywheel_dir) or {}
                if status.get("consumed_rows", 0) > 0:
                    break
                time.sleep(0.05)
            else:
                raise SystemExit("flywheel bench: learner never ingested a row during warmup")

        consumed_before = 0
        if flywheel_dir is not None:
            consumed_before = int((read_learner_status(flywheel_dir) or {}).get("consumed_rows", 0))
        result = _drive_closed_loop(policy, server.scheduler, duration, n_clients)
        if flywheel_dir is not None:
            # let the tail of the spool drain before reading the meter
            deadline = time.monotonic() + 30.0
            fl = server.flywheel.snapshot()
            while time.monotonic() < deadline:
                status = read_learner_status(flywheel_dir) or {}
                fl = server.flywheel.snapshot()
                if int(status.get("consumed_rows", 0)) >= fl["rows_spooled"]:
                    break
                time.sleep(0.25)
            status = read_learner_status(flywheel_dir) or {}
            result["rows_logged"] = int(fl["rows_logged"])
            result["rows_shed"] = int(fl["rows_shed"])
            result["flywheel_errors"] = int(fl["errors"])
            result["rows_ingested"] = int(status.get("consumed_rows", 0)) - consumed_before
            result["rows_ingested_per_sec"] = round(result["rows_ingested"] / result["duration_s"], 1)
            result["learner_grad_steps"] = int(status.get("grad_steps", 0))
            result["learner_published_step"] = int(status.get("published_step", -1))
    finally:
        ticker_stop.set()
        if ticker is not None:
            ticker.join(timeout=10.0)
        server.stop()
        if learner_sup is not None:
            learner_sup.stop()
    return result


def main() -> None:
    duration = float(os.environ.get("BENCH_FLYWHEEL_DURATION", "6"))
    n_clients = int(os.environ.get("BENCH_FLYWHEEL_CLIENTS", "4"))

    with tempfile.TemporaryDirectory(prefix="flywheel_bench_") as workdir:
        ckpt = _checkpoint(workdir)
        cfg, policy = _build(ckpt)
        off = _run_phase(cfg, policy, duration, n_clients, flywheel_dir=None)
        flywheel_dir = str(Path(workdir) / "flywheel")
        on = _run_phase(cfg, policy, duration, n_clients, flywheel_dir=flywheel_dir)

    # the lane's contract, not a hint: the loop must close without loss
    for name, phase in (("learner-off", off), ("learner-on", on)):
        if phase["dropped"] != 0:
            raise SystemExit(f"flywheel bench: {phase['dropped']} dropped requests in {name} phase")
        if phase["errors"] != 0:
            raise SystemExit(f"flywheel bench: {phase['errors']} request errors in {name} phase")
    if on["rows_shed"] != 0:
        raise SystemExit(f"flywheel bench: {on['rows_shed']} production rows shed under bench load")
    if on["flywheel_errors"] != 0:
        raise SystemExit(f"flywheel bench: {on['flywheel_errors']} trajectory-log errors")
    if on["rows_ingested"] <= 0:
        raise SystemExit("flywheel bench: learner ingested zero rows during the measured window")

    print(
        json.dumps(
            {
                "metric": "serve_flywheel_rows_ingested_per_sec",
                # headline: sustained production-ingest rate with the live learner
                "value": on["rows_ingested_per_sec"],
                "unit": "rows/s",
                "clients": n_clients,
                "duration_s": duration,
                "learner_off": off,
                "learner_on": on,
                # the isolation claim as a ratio: on-phase p99 over baseline
                "p99_on_over_off": round(on["p99_ms"] / max(off["p99_ms"], 1e-9), 3),
            }
        )
    )


if __name__ == "__main__":
    main()
