"""Offered-load SLO bench for the graft-serve inference tier.

Builds a PPO CartPole policy (the same model/conditions as the
``ppo_benchmarks`` lane), stands up the full serving stack — engine,
micro-batching scheduler, versioned weight store — and drives it with
open-loop client threads at fixed offered request rates. Per load it reports
completed throughput and p50/p99 request latency; halfway through each load
one hot weight swap is published, and the lane asserts zero
dropped/errored requests around it.

``BENCH_SERVE_MODE`` pairs the two engines on identical traffic:

- ``aot`` (default) — :class:`~sheeprl_tpu.serve.engine.BucketEngine`:
  continuous batching into AOT bucket-compiled programs;
- ``naive`` — :class:`~sheeprl_tpu.serve.engine.JitEngine` behind a
  ``max_batch=1`` scheduler: every request is its own ``jax.jit`` dispatch,
  the GA3C-without-a-predictor-queue baseline every per-actor policy call
  effectively is today.

Knobs (env vars): ``BENCH_SERVE_LOADS`` (comma-separated offered req/s,
default ``500,4000``), ``BENCH_SERVE_DURATION`` (seconds per load, default
6), ``BENCH_SERVE_CLIENTS`` (client threads, default 8),
``BENCH_SERVE_BUCKETS`` (ladder, default ``1,8,32,128``).

Open-loop arrivals with a bounded queue degrade gracefully: past capacity
the submit path backpressures and the measured throughput is the tier's
sustainable rate at that load — exactly the SLO number an operator needs.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List


def _build_policy():
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.envs.factory import make_env
    from sheeprl_tpu.parallel import Fabric
    from sheeprl_tpu.utils.registry import get_entrypoint, resolve_policy_builder

    cfg = compose(
        [
            "exp=ppo_benchmarks",
            "env.capture_video=False",
            "buffer.memmap=False",
            "metric.log_level=0",
            "metric.disable_timer=True",
            "checkpoint.save_last=False",
        ]
    )
    fabric = Fabric(devices=1, accelerator="cpu")
    fabric.seed_everything(cfg.seed)
    env = make_env(cfg, cfg.seed, 0, None, "serve_bench", vector_env_idx=0)()
    obs_space, act_space = env.observation_space, env.action_space
    env.close()
    builder = get_entrypoint(resolve_policy_builder(cfg.algo.name))
    # fresh params: serving latency/throughput does not care about returns
    return builder(fabric, cfg, obs_space, act_space, None), obs_space


def _drive_load(
    policy,
    scheduler,
    store,
    offered_rps: float,
    duration_s: float,
    n_generators: int,
) -> Dict[str, Any]:
    """Open-loop: generator threads pace ``scheduler.submit`` calls at the
    offered rate (prepared single-row obs; submission is cheap and does NOT
    wait for results, so generation capacity far exceeds engine capacity and
    saturation is the ENGINE's, not the client harness's); a collector
    thread drains the futures in submit order. Latency is stamped by the
    worker at resolve time, so collector lag can't inflate it. Past the
    queue bound the generators block (backpressure) — measured throughput is
    then the tier's sustainable rate at that load."""
    import collections

    import numpy as np

    counters = {"submitted": 0, "errors": 0}
    pending: "collections.deque" = collections.deque()
    pend_lock = threading.Lock()
    gen_done = threading.Event()
    stop_at = time.perf_counter() + duration_s
    period = n_generators / offered_rps  # per-thread inter-arrival

    def generator(idx: int) -> None:
        rng = np.random.default_rng(idx)
        next_t = time.perf_counter() + (idx / n_generators) * period  # phase-spread
        while True:
            now = time.perf_counter()
            if now >= stop_at:
                return
            if now < next_t:
                time.sleep(min(next_t - now, stop_at - now))
                continue
            next_t += period
            obs = policy.prepare({"state": rng.standard_normal(4).astype(np.float32)}, 1)
            try:
                req = scheduler.submit(obs, timeout=60.0)
                with pend_lock:
                    counters["submitted"] += 1
                    pending.append(req)
            except Exception:
                with pend_lock:
                    counters["errors"] += 1

    latencies: List[float] = []
    served: List[tuple] = []  # (t_resolve, version)
    collected = {"n": 0, "errors": 0}

    def collector() -> None:
        while True:
            with pend_lock:
                req = pending.popleft() if pending else None
            if req is None:
                if gen_done.is_set():
                    with pend_lock:
                        if not pending:
                            return
                    continue
                time.sleep(0.0005)
                continue
            if not req.event.wait(timeout=120.0) or req.error is not None:
                collected["errors"] += 1
                continue
            latencies.append(req.latency_s)
            served.append((req.t_resolve, req.version))
            collected["n"] += 1

    gens = [threading.Thread(target=generator, args=(i,), daemon=True) for i in range(n_generators)]
    col = threading.Thread(target=collector, daemon=True)
    start = time.perf_counter()
    for t in gens:
        t.start()
    col.start()
    # one hot weight swap mid-load: zero dropped/torn requests is the claim
    time.sleep(duration_s / 2)
    import jax

    _, current = store.pull()
    swap_version = store.publish_params(jax.tree.map(lambda x: x + 1e-3, current))
    for t in gens:
        t.join(timeout=duration_s + 120.0)
    gen_done.set()
    col.join(timeout=180.0)
    elapsed = time.perf_counter() - start
    lat = np.sort(np.asarray(latencies)) if latencies else np.zeros(1)
    # versions must be monotone in SERVE order (the generator-append order
    # races across threads and proves nothing)
    versions = [v for _, v in sorted(served)]
    monotone = all(a <= b for a, b in zip(versions, versions[1:]))
    return {
        "offered_rps": offered_rps,
        "duration_s": round(elapsed, 2),
        "submitted": counters["submitted"],
        "completed": collected["n"],
        "dropped": counters["submitted"] - collected["n"] - collected["errors"],
        "errors": counters["errors"] + collected["errors"],
        "throughput_rps": round(collected["n"] / elapsed, 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
        "swap_version": swap_version,
        "max_version_served": max(versions) if versions else -1,
        "versions_monotone": monotone,
    }


def main() -> None:
    mode = os.environ.get("BENCH_SERVE_MODE", "aot").strip().lower()
    if mode not in ("aot", "naive"):
        raise SystemExit(f"Unknown BENCH_SERVE_MODE '{mode}' (expected 'aot' or 'naive')")
    loads = [float(x) for x in os.environ.get("BENCH_SERVE_LOADS", "2000,16000").split(",") if x.strip()]
    duration = float(os.environ.get("BENCH_SERVE_DURATION", "6"))
    n_clients = int(os.environ.get("BENCH_SERVE_CLIENTS", "8"))
    buckets = [int(x) for x in os.environ.get("BENCH_SERVE_BUCKETS", "1,8,32,128").split(",") if x.strip()]

    from sheeprl_tpu.serve.server import PolicyServer

    policy, _ = _build_policy()
    serve_cfg = {
        "buckets": buckets,
        "mode": "greedy",
        "max_wait_ms": 2.0,
        "queue_bound": 1024,
        "port": None,
    }
    if mode == "naive":
        # the per-request baseline: no batching, one jit dispatch per request
        serve_cfg["max_batch"] = 1
        serve_cfg["max_wait_ms"] = 0.0
    server = PolicyServer(policy, serve_cfg, engine="aot" if mode == "aot" else "naive")
    server.start(with_socket=False)
    try:
        results = [
            _drive_load(policy, server.scheduler, server.weights, rps, duration, n_clients) for rps in loads
        ]
    finally:
        server.stop()
    snap = server.stats.snapshot()
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_serve_requests_per_sec",
                # headline: sustained throughput at the highest offered load
                "value": results[-1]["throughput_rps"],
                "unit": "requests/s",
                "mode": mode,
                "buckets": buckets if mode == "aot" else [],
                "max_wait_ms": serve_cfg["max_wait_ms"],
                "clients": n_clients,
                "loads": results,
                "swap_count": snap["Serve/swap_count"],
                "batch_fill_ratio": server.engine.stats()["batch_fill_ratio"],
                "dispatches": server.engine.stats()["dispatches"],
            }
        )
    )


if __name__ == "__main__":
    main()
