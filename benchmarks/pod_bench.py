"""MTTR bench for graft-pod gang-restart training.

Runs REAL 2-process pods (``sheeprl_tpu run --pod N``) with one seeded
``kill-host`` chaos injection per repetition and reports the launcher's
measured MTTR — injected SIGKILL → first post-restart completed train
iteration (the heartbeat-content signal) — per rep, plus the recovery
bookkeeping (fences, restarts, kills) that proves the pod came back from the
newest complete checkpoint and not from scratch whenever one existed.

Each rep asserts the run FINISHED (the chaos run converges to its configured
``total_steps``) — an MTTR number from a run that never recovered would be
meaningless.

Knobs (env vars): ``BENCH_POD_WORKERS`` (default 2), ``BENCH_POD_REPS``
(default 3), ``BENCH_POD_TOTAL_STEPS`` (default 160), ``BENCH_POD_KILL_AT``
(``train.pod.step`` beat of the injection — the Nth observed heartbeat step
advance, progress-keyed so it lands mid-run regardless of compile-cache
warmth; default 6 ≈ iteration 3 of 10), ``BENCH_POD_TIMEOUT`` (seconds per
rep, default 560).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def _overrides(total_steps: int, log_root: str, kill_at: int) -> List[str]:
    return [
        "exp=ppo",
        "env=dummy",
        "env.id=discrete_dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "metric.log_level=0",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=1",
        "algo.mlp_keys.encoder=[state]",
        f"algo.total_steps={total_steps}",
        "checkpoint.every=16",
        "algo.run_test=False",
        "seed=11",
        "fabric.pod.backoff=0.1",
        "fabric.pod.lease_s=20",
        "fabric.pod.grace_s=120",
        f"log_root={log_root}",
        "fault.chaos.enabled=True",
        f"fault.chaos.events=[train.pod.step:kill-host:{kill_at}]",
    ]


def _one_rep(workers: int, total_steps: int, kill_at: int, timeout: float) -> Dict[str, Any]:
    tmp = tempfile.mkdtemp(prefix="pod-bench-")
    try:
        cmd = [
            sys.executable,
            "-m",
            "sheeprl_tpu",
            "run",
            "--pod",
            str(workers),
            *_overrides(total_steps, os.path.join(tmp, "logs"), kill_at),
        ]
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        tic = time.perf_counter()
        proc = subprocess.run(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env, timeout=timeout
        )
        elapsed = time.perf_counter() - tic
        lines = [l for l in proc.stdout.splitlines() if l.startswith("POD_SUMMARY ")]
        if proc.returncode != 0 or not lines:
            raise RuntimeError(
                f"pod rep failed rc={proc.returncode}:\n{proc.stdout[-4000:]}"
            )
        summary = json.loads(lines[-1][len("POD_SUMMARY ") :])
        if not summary["finished"]:
            raise RuntimeError(f"pod rep did not finish: {summary}")
        if summary["pod_restarts"] < 1 or not summary["restarts"]:
            raise RuntimeError(
                f"chaos kill never produced a gang restart (kill_at={kill_at} may be past "
                f"the end of the run): {summary}"
            )
        return {
            "elapsed_s": round(elapsed, 2),
            "pod_restarts": summary["pod_restarts"],
            "kills": summary["kills"],
            "hangs": summary["hangs"],
            "fences": summary["fences"],
            "mttr_s": [round(float(r["mttr_s"]), 3) for r in summary["restarts"]],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    workers = int(os.environ.get("BENCH_POD_WORKERS", 2))
    reps = int(os.environ.get("BENCH_POD_REPS", 3))
    total_steps = int(os.environ.get("BENCH_POD_TOTAL_STEPS", 160))
    kill_at = int(os.environ.get("BENCH_POD_KILL_AT", 6))
    timeout = float(os.environ.get("BENCH_POD_TIMEOUT", 560))

    rep_results = [_one_rep(workers, total_steps, kill_at, timeout) for _ in range(reps)]
    mttrs = [m for r in rep_results for m in r["mttr_s"]]
    result = {
        "benchmark": "pod_restart_mttr",
        "workers": workers,
        "reps": reps,
        "total_steps": total_steps,
        "kill_at_step_beat": kill_at,
        "mttr_s": mttrs,
        "mttr_mean_s": round(sum(mttrs) / len(mttrs), 3),
        "mttr_min_s": round(min(mttrs), 3),
        "mttr_max_s": round(max(mttrs), 3),
        "rep_detail": rep_results,
        "note": (
            "MTTR = injected SIGKILL of one pod worker -> first post-restart completed train "
            "iteration (heartbeat-content signal); every rep must FINISH at its configured "
            "total_steps, proving gang restart + resume_from=latest converge, not just respawn"
        ),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
