#!/usr/bin/env python
"""Wall-clock benchmark harness (reference: ``benchmarks/benchmark.py``).

The reference toggles commented argument blocks; here the algorithm is the
first CLI argument and everything after is passed through as overrides::

    python benchmarks/benchmark.py ppo
    python benchmarks/benchmark.py sac fabric.devices=2 env.num_envs=8
    python benchmarks/benchmark.py dreamer_v3

Prints the elapsed wall-clock seconds and an env-steps/s JSON line. Uses the
same persistent XLA compilation cache as ``bench.py`` so repeated runs
measure the framework, not the compiler.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

KNOWN = ("ppo", "a2c", "sac", "dreamer_v1", "dreamer_v2", "dreamer_v3")


def main() -> None:
    if len(sys.argv) < 2 or sys.argv[1] not in KNOWN:
        raise SystemExit(f"usage: benchmark.py <{'|'.join(KNOWN)}> [overrides...]")
    algo = sys.argv[1]
    overrides = sys.argv[2:]

    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("BENCH_XLA_CACHE", os.path.join(_REPO_ROOT, ".xla_cache")),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from sheeprl_tpu.cli import check_configs, run_algorithm
    from sheeprl_tpu.config import compose

    cfg = compose([f"exp={algo}_benchmarks", *overrides])
    total_steps = int(cfg.algo.total_steps)

    calib_pre = _device_calibration()
    tic = time.perf_counter()
    check_configs(cfg)
    run_algorithm(cfg)
    elapsed = time.perf_counter() - tic
    calib_post = _device_calibration()
    result = {
        "benchmark": algo,
        "elapsed_s": round(elapsed, 2),
        "env_steps_per_sec": round(total_steps / elapsed, 2),
    }
    # Bracketing probes: a long run is only a clean measurement if the chip
    # was quiet both when it started and when it ended.
    if calib_pre is not None:
        result["device_calibration_ms"] = [calib_pre, calib_post]
    print(json.dumps(result))


def _device_calibration() -> "float | None":
    """Warm time of a fixed ~1 GFLOP matmul chain on the default accelerator.

    The sandbox TPU is time-shared between tenants (a program measured at
    2.14 ms has been observed at 24.6 ms under external load), so wall-clock
    results are only comparable at similar calibration readings. Quiet-chip
    reference for this probe on the v5e: ~1 ms.
    """
    try:
        import jax
        import jax.numpy as jnp

        if jax.default_backend() == "cpu":
            return None

        @jax.jit
        def chain(x):
            for _ in range(8):
                x = jnp.tanh(x @ x)
            return x

        x = jnp.ones((512, 512), jnp.bfloat16)
        chain(x).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(5):
            chain(x).block_until_ready()
        return round((time.perf_counter() - t0) / 5 * 1e3, 2)
    except Exception:
        return None


if __name__ == "__main__":
    main()
