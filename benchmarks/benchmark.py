#!/usr/bin/env python
"""Wall-clock benchmark harness (reference: ``benchmarks/benchmark.py``).

The reference toggles commented argument blocks; here the algorithm is the
first CLI argument and everything after is passed through as overrides::

    python benchmarks/benchmark.py ppo
    python benchmarks/benchmark.py sac fabric.devices=2 env.num_envs=8
    python benchmarks/benchmark.py dreamer_v3

Prints the elapsed wall-clock seconds and an env-steps/s JSON line. Uses the
same persistent XLA compilation cache as ``bench.py`` so repeated runs
measure the framework, not the compiler.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    # `python benchmarks/<script>.py` puts benchmarks/ (not the repo root) at
    # sys.path[0]; make the package importable without an editable install.
    sys.path.insert(0, _REPO_ROOT)

KNOWN = ("ppo", "a2c", "sac", "dreamer_v1", "dreamer_v2", "dreamer_v3")


def main() -> None:
    if len(sys.argv) < 2 or sys.argv[1] not in KNOWN:
        raise SystemExit(f"usage: benchmark.py <{'|'.join(KNOWN)}> [overrides...]")
    algo = sys.argv[1]
    overrides = sys.argv[2:]

    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("BENCH_XLA_CACHE", os.path.join(_REPO_ROOT, ".xla_cache")),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from sheeprl_tpu.cli import check_configs, run_algorithm
    from sheeprl_tpu.config import compose

    cfg = compose([f"exp={algo}_benchmarks", *overrides])
    total_steps = int(cfg.algo.total_steps)

    # the script dir is sys.path[0] when run as `python benchmarks/<script>.py`
    from calibration import calibration_verdict, device_calibration_ms, gate_quiet

    # Refuse to measure a loud chip; stamp pre/post readings + verdict so a
    # number can never be quoted without its measurement conditions.
    accel = str(cfg.fabric.get("accelerator", "auto"))
    calib_pre = gate_quiet(accel)
    tic = time.perf_counter()
    check_configs(cfg)
    run_algorithm(cfg)
    elapsed = time.perf_counter() - tic
    calib_post = device_calibration_ms(accel)
    result = {
        "benchmark": algo,
        "elapsed_s": round(elapsed, 2),
        "env_steps_per_sec": round(total_steps / elapsed, 2),
        **calibration_verdict(calib_pre, calib_post),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
