#!/usr/bin/env python
"""Make the multi-chip scaling claim falsifiable from one chip.

Compiles the REAL PPO and Dreamer-V3 train steps over dp=8 and dp=64
virtual meshes, walks the optimized HLO for every collective op
(all-reduce / all-gather / reduce-scatter / collective-permute /
all-to-all), accounts the exact bytes each moves per step, and derives a
v5e ICI roofline bound on data-parallel scaling efficiency:

    t_coll(ring all-reduce of B bytes over n chips) = 2*B*(n-1)/n / ICI_BW
    efficiency_bound = t_compute / (t_compute + t_coll)

with ``t_compute`` taken from the measured quiet-chip step time (the
BENCH_NOTES numbers) — so the claim is a checkable arithmetic consequence
of (a) the byte counts printed here, (b) the public v5e ICI bandwidth, and
(c) a measured single-chip step time, not an extrapolated wall-clock.

Run (CPU-only, no TPU needed):

    python benchmarks/collective_analysis.py          # both algos, dp=8,64
    python benchmarks/collective_analysis.py ppo 8    # one row

Each row prints one JSON line; the summary lines carry the roofline.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Public v5e specs (Google Cloud TPU docs / the scaling-book numbers):
# 197 bf16 TFLOP/s per chip; 1600 Gbps (= 200 GB/s) aggregate ICI per chip.
V5E_ICI_BYTES_PER_S = 200e9
# Measured quiet-chip step times from BENCH_NOTES.md (single chip).
# dreamer_v3: the batch-16 x seq-64 S step measured 35.23 ms
# (dreamer_train_bench, calibration-passed) — the analysis meshes carry the
# same batch-16 PER DEVICE (weak scaling), so this is the per-device compute
# at every dp. The 2.14 ms recorded in round 3 was an artifact of the
# transport's pre-pull optimistic mode, where block_until_ready returns
# without a real device sync (BENCH_NOTES "transport latency modes") — it
# under-read the step ~16x and with it the collective/compute ratio.
# ppo: 512-batch CPU proxy scaled (measured on the CPU backend, which has
# no optimistic-mode artifact).
MEASURED_STEP_S = {"dreamer_v3": 35.23e-3, "ppo": 16.0e-3 / 20}


# ONE lowering/HLO-walk path shared with the graft-audit gate
# (sheeprl_tpu/analysis/hlo.py): the bench's byte accounting and the audit's
# collective budgets can never drift apart.
sys.path.insert(0, _REPO_ROOT)
from sheeprl_tpu.analysis.hlo import account_collectives  # noqa: E402


def _analyze_body(algo: str, n_devices: int, reduce_dtype: str = "float32") -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sys.path.insert(0, _REPO_ROOT)
    import __graft_entry__ as ge

    from sheeprl_tpu.optim.builders import build_optimizer
    from sheeprl_tpu.parallel.comm import set_grad_reduce_dtype
    from sheeprl_tpu.parallel.fabric import Fabric

    set_grad_reduce_dtype(reduce_dtype)

    if algo == "ppo":
        from sheeprl_tpu.algos.ppo.ppo import make_train_step

        cfg, agent, params, obs = ge._ppo_setup()
        fabric = Fabric(devices=n_devices, mesh_axes=("dp",))
        tx = optax.inject_hyperparams(
            lambda learning_rate: build_optimizer(
                {**cfg.algo.optimizer, "lr": learning_rate}, max_grad_norm=cfg.algo.max_grad_norm
            )
        )(learning_rate=float(cfg.algo.optimizer.lr))
        opt_state = tx.init(params)
        B = 8 * n_devices
        train_fn = make_train_step(agent, tx, cfg, fabric.mesh, B // n_devices)
        rng = np.random.default_rng(0)
        data = {
            "state": jnp.asarray(rng.normal(size=(B, 4)), dtype=jnp.float32),
            "actions": jnp.asarray(rng.integers(0, 2, size=(B, 2)), dtype=jnp.float32),
            "logprobs": jnp.zeros((B, 1), jnp.float32),
            "values": jnp.zeros((B, 1), jnp.float32),
            "returns": jnp.zeros((B, 1), jnp.float32),
            "advantages": jnp.zeros((B, 1), jnp.float32),
            "rewards": jnp.zeros((B, 1), jnp.float32),
            "dones": jnp.zeros((B, 1), jnp.uint8),
        }
        data = fabric.shard_data(data)
        p = fabric.put_replicated(params)
        o = fabric.put_replicated(opt_state)
        lowered = train_fn.lower(p, o, data, jax.random.PRNGKey(0), jnp.float32(0.2), jnp.float32(0.0))
    else:
        # The REAL flagship shape: dreamer_v3_S at the measured batch-16 x
        # seq-64 per-device load (weak scaling: global batch = 16 * dp).
        # Data is passed as ShapeDtypeStructs — AOT lowering needs shapes +
        # shardings, not 3 GB of concrete pixels at dp=64.
        import gymnasium as gym

        from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
        from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
        from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
        from sheeprl_tpu.config import compose

        per_dev_batch = 16
        cfg = compose(
            [
                "exp=dreamer_v3",
                "algo=dreamer_v3_S",
                "env=dummy",
                f"algo.per_rank_batch_size={per_dev_batch * n_devices}",
                "algo.per_rank_sequence_length=64",
                "algo.cnn_keys.encoder=[rgb]",
                "algo.mlp_keys.encoder=[]",
                "algo.mlp_keys.decoder=[]",
                "env.screen_size=64",
            ]
        )
        fabric = Fabric(devices=n_devices, mesh_axes=("dp",))
        obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8)})
        world_model, actor, critic, params, _ = build_agent(fabric, (18,), False, cfg, obs_space)
        txs = {
            "world": build_optimizer(cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients),
            "actor": build_optimizer(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
            "critic": build_optimizer(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
        }
        opts = {
            "world": txs["world"].init(params["world_model"]),
            "actor": txs["actor"].init(params["actor"]),
            "critic": txs["critic"].init(params["critic"]),
        }
        train_fn = make_train_step(world_model, actor, critic, cfg, fabric.mesh, (18,), False, txs)
        G, T, B = 1, 64, per_dev_batch * n_devices
        sharding = NamedSharding(fabric.mesh, P(None, None, "dp"))
        shapes = {
            "rgb": (G, T, B, 64, 64, 3),
            "actions": (G, T, B, 18),
            "rewards": (G, T, B, 1),
            "terminated": (G, T, B, 1),
            "truncated": (G, T, B, 1),
            "is_first": (G, T, B, 1),
        }
        data = {k: jax.ShapeDtypeStruct(v, jnp.float32, sharding=sharding) for k, v in shapes.items()}
        p = fabric.put_replicated(params)
        o = fabric.put_replicated(opts)
        m = fabric.put_replicated(init_moments())
        lowered = train_fn.lower(p, o, m, data, jax.random.PRNGKey(0), jnp.int32(0))

    compiled = lowered.compile()
    hlo = compiled.as_text()
    table = account_collectives(hlo)
    cost = (compiled.cost_analysis() or [{}])
    cost = cost[0] if isinstance(cost, (list, tuple)) else cost
    flops = float(cost.get("flops", 0.0))
    total_bytes = sum(v["bytes"] for v in table.values())
    print(
        json.dumps(
            {
                "algo": algo,
                "dp": n_devices,
                "grad_reduce_dtype": reduce_dtype,
                "collectives": table,
                "collective_bytes_per_step": total_bytes,
                "hlo_flops_per_device": flops,
            }
        )
    )


def roofline(algo: str, rows: list) -> dict:
    """v5e ring-all-reduce roofline from the measured step time + byte count.

    DP collective volume is gradient-sized — independent of n up to the
    ring factor 2(n-1)/n — so the dp=8/dp=64 rows cross-check that the
    compiler didn't introduce extra resharding as the mesh widens."""
    t_comp = MEASURED_STEP_S[algo]
    out = {"algo": algo, "t_compute_s": t_comp, "assumed_ici_bytes_per_s": V5E_ICI_BYTES_PER_S}
    for row in rows:
        n = row["dp"]
        b = row["collective_bytes_per_step"]
        if row.get("grad_reduce_dtype") == "bfloat16":
            # Both collectives ride the wire dtype under bfloat16: gradients
            # via pmean_grads, the Moments percentile gather via
            # all_gather_wire. XLA:CPU promotes BOTH back to f32 during
            # lowering (no native host bf16 collectives — the feeding
            # converts are visible in HLO, tests/test_utils/test_comm.py), so
            # the CPU-accounted bytes are halved analytically; on TPU the
            # collectives keep bf16 on the wire.
            b = b // 2
            out["cpu_hlo_promotes_bf16_collectives"] = True
        t_coll = 2 * b * (n - 1) / n / V5E_ICI_BYTES_PER_S
        out[f"dp{n}"] = {
            "collective_bytes": b,
            "t_collective_s": round(t_coll, 6),
            "efficiency_bound": round(t_comp / (t_comp + t_coll), 4),
        }
    return out


def main() -> None:
    if len(sys.argv) >= 3:  # worker: one (algo, dp[, reduce_dtype]) row
        _analyze_body(sys.argv[1], int(sys.argv[2]), sys.argv[3] if len(sys.argv) > 3 else "float32")
        return
    results: dict = {}
    jobs = [("ppo", 8, "float32"), ("ppo", 64, "float32")] + [
        ("dreamer_v3", n, dt) for dt in ("float32", "bfloat16") for n in (8, 64)
    ]
    for algo, n, dtype in jobs:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count={n}").strip()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), algo, str(n), dtype],
            env=env, capture_output=True, text=True, timeout=1800, cwd=_REPO_ROOT,
        )
        if proc.returncode != 0:
            raise RuntimeError(f"{algo} dp={n} {dtype} failed:\n{proc.stderr[-3000:]}")
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        results.setdefault((algo, dtype), []).append(row)
        print(json.dumps(row))
    for (algo, dtype), rows in results.items():
        print(json.dumps({"roofline": {**roofline(algo, rows), "grad_reduce_dtype": dtype}}))


if __name__ == "__main__":
    main()
