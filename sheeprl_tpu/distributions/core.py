"""Distributions as pure JAX functions/objects.

Capability parity with the reference's distribution toolbox
(``sheeprl/utils/distribution.py:25-414``) re-designed for XLA: every method
is traceable, sampling takes an explicit PRNG key, and reparameterized
sampling is the default (``rsample`` ≡ ``sample`` — gradients flow unless the
caller stops them). Instances are created and consumed inside jitted train
steps; nothing here touches the host.

Conventions: ``event_dims``-style batching is handled by :class:`Independent`,
matching ``torch.distributions.Independent`` semantics used throughout the
reference's algorithms.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.ops.core import symexp, symlog, two_hot_decoder, two_hot_encoder

__all__ = [
    "Distribution",
    "Normal",
    "Independent",
    "Categorical",
    "OneHotCategorical",
    "OneHotCategoricalStraightThrough",
    "TanhNormal",
    "TruncatedNormal",
    "SymlogDistribution",
    "MSEDistribution",
    "TwoHotEncodingDistribution",
    "BernoulliSafeMode",
    "kl_divergence",
    "set_validate_args",
]


class Distribution:
    """Minimal traceable distribution protocol.

    ``validate_args`` (reference: ``cfg.distribution.validate_args`` gating
    torch's eager validation) enables STATIC argument checking — shapes,
    dtypes, broadcastability — which is everything checkable under ``jit``
    tracing; value-level checks (NaNs, simplex membership) have no
    trace-time analogue. Toggle globally via :func:`set_validate_args`
    (wired from the config by the CLI).
    """

    validate_args: bool = False

    @staticmethod
    def _check_broadcastable(name: str, *arrays: Any) -> None:
        if not Distribution.validate_args:
            return
        try:
            jnp.broadcast_shapes(*(jnp.shape(a) for a in arrays))
        except ValueError as e:
            raise ValueError(f"{name}: arguments are not broadcastable: "
                             f"{[jnp.shape(a) for a in arrays]}") from e

    @staticmethod
    def _check_floating(name: str, **arrays: Any) -> None:
        if not Distribution.validate_args:
            return
        for arg, a in arrays.items():
            dtype = jnp.result_type(a)
            if not jnp.issubdtype(dtype, jnp.floating):
                raise ValueError(f"{name}: '{arg}' must be floating point, got {dtype}")

    def sample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        raise NotImplementedError

    def rsample(self, key: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jax.Array:
        return self.sample(key, sample_shape)

    def log_prob(self, value: jax.Array) -> jax.Array:
        raise NotImplementedError

    def entropy(self) -> jax.Array:
        raise NotImplementedError

    @property
    def mean(self) -> jax.Array:
        raise NotImplementedError

    @property
    def mode(self) -> jax.Array:
        raise NotImplementedError


# ---------------------------------------------------------------------------


def set_validate_args(enabled: bool) -> None:
    """Globally toggle static distribution-argument validation
    (reference: ``cfg.distribution.validate_args``)."""
    Distribution.validate_args = bool(enabled)


def _lift(x: Any) -> Any:
    """Promote sub-f32 floating parameters (bf16-mixed trunk outputs) to f32.

    Mixed-precision policy shared by every distribution here: matmuls/convs
    run in the fabric compute dtype (``Precision.compute_dtype``), but
    distribution math — softmax normalizers, log-probs, KLs, entropies —
    runs in f32, because sub-f32 logsumexp/log arithmetic visibly degrades
    DreamerV3's KL-balanced losses. Samples are cast back to the
    pre-promotion dtype (kept as ``_sample_dtype`` on each instance) so
    ``lax.scan`` carries built from samples keep their bf16 avals. No-op
    for f32 parameters, so pure-f32 configs are bit-identical.
    """
    if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) and x.dtype.itemsize < 4:
        return x.astype(jnp.float32)
    return x


class Normal(Distribution):
    def __init__(self, loc: jax.Array, scale: jax.Array):
        self._check_broadcastable("Normal", loc, scale)
        self._check_floating("Normal", loc=loc, scale=scale)
        self._sample_dtype = jnp.result_type(loc)
        self.loc = _lift(loc)
        self.scale = _lift(scale)

    def sample(self, key, sample_shape=()):
        shape = tuple(sample_shape) + jnp.broadcast_shapes(jnp.shape(self.loc), jnp.shape(self.scale))
        eps = jax.random.normal(key, shape, dtype=jnp.result_type(self.loc))
        return (self.loc + self.scale * eps).astype(self._sample_dtype)

    def log_prob(self, value):
        var = self.scale**2
        return -((value - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi)

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale) + jnp.zeros_like(self.loc)

    @property
    def mean(self):
        # Same dtype as sample(): greedy (mode/mean) and sampled action paths
        # must produce identical avals or the policy jit retraces on eval.
        return jnp.broadcast_to(self.loc, jnp.broadcast_shapes(jnp.shape(self.loc), jnp.shape(self.scale))).astype(
            self._sample_dtype
        )

    @property
    def mode(self):
        return self.mean

    @property
    def stddev(self):
        # Same dtype contract as mean/mode/sample (uniform _sample_dtype
        # surface): a bf16 carry built from stddev must not retrace against
        # the sampled path (ADVICE r4).
        return jnp.broadcast_to(
            self.scale, jnp.broadcast_shapes(jnp.shape(self.loc), jnp.shape(self.scale))
        ).astype(self._sample_dtype)


class Independent(Distribution):
    """Reinterpret the rightmost ``reinterpreted_batch_ndims`` batch dims as
    event dims (sums log-probs/entropies over them)."""

    def __init__(self, base: Distribution, reinterpreted_batch_ndims: int = 1):
        self.base = base
        self.ndims = reinterpreted_batch_ndims

    def _reduce(self, x: jax.Array) -> jax.Array:
        if self.ndims == 0:
            return x
        return jnp.sum(x, axis=tuple(range(-self.ndims, 0)))

    def sample(self, key, sample_shape=()):
        return self.base.sample(key, sample_shape)

    def rsample(self, key, sample_shape=()):
        return self.base.rsample(key, sample_shape)

    def log_prob(self, value):
        return self._reduce(self.base.log_prob(value))

    def entropy(self):
        return self._reduce(self.base.entropy())

    @property
    def mean(self):
        return self.base.mean

    @property
    def mode(self):
        return self.base.mode


class Categorical(Distribution):
    """Integer-valued categorical over the last axis of ``logits``."""

    def __init__(self, logits: jax.Array):
        logits = _lift(logits)
        self.logits = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, key, sample_shape=()):
        return jax.random.categorical(key, self.logits, axis=-1, shape=tuple(sample_shape) + self.logits.shape[:-1])

    def log_prob(self, value):
        value = value.astype(jnp.int32)
        return jnp.take_along_axis(self.logits, value[..., None], axis=-1)[..., 0]

    def entropy(self):
        p = self.probs
        return -jnp.sum(p * self.logits, axis=-1)

    @property
    def mode(self):
        return jnp.argmax(self.logits, axis=-1)

    @property
    def mean(self):  # pragma: no cover - undefined for categorical; parity shim
        return self.mode


def _unimix_logits(logits: jax.Array, unimix: float) -> jax.Array:
    """Mix the categorical with a uniform (DreamerV3's 1% unimix,
    reference: ``sheeprl/algos/dreamer_v3/agent.py`` _uniform_mix)."""
    if unimix <= 0:
        return logits
    probs = jax.nn.softmax(logits, axis=-1)
    uniform = jnp.ones_like(probs) / probs.shape[-1]
    probs = (1 - unimix) * probs + unimix * uniform
    return jnp.log(probs)


class OneHotCategorical(Distribution):
    """One-hot-valued categorical (reference: ``distribution.py:281-340``)."""

    def __init__(self, logits: jax.Array, unimix: float = 0.0):
        if Distribution.validate_args and jnp.ndim(logits) < 1:
            raise ValueError(f"OneHotCategorical: logits must have at least 1 dim, got {jnp.ndim(logits)}")
        self._sample_dtype = jnp.result_type(logits)
        logits = _unimix_logits(_lift(logits), unimix)
        self.logits = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    @property
    def num_classes(self) -> int:
        return self.logits.shape[-1]

    def sample(self, key, sample_shape=()):
        idx = jax.random.categorical(key, self.logits, axis=-1, shape=tuple(sample_shape) + self.logits.shape[:-1])
        sample = jax.nn.one_hot(idx, self.num_classes, dtype=self._sample_dtype)
        return jax.lax.stop_gradient(sample)

    def log_prob(self, value):
        return jnp.sum(value * self.logits, axis=-1)

    def entropy(self):
        return -jnp.sum(self.probs * self.logits, axis=-1)

    @property
    def mode(self):
        return jax.nn.one_hot(jnp.argmax(self.logits, axis=-1), self.num_classes, dtype=self._sample_dtype)

    @property
    def mean(self):
        # Same _sample_dtype contract as mode/sample (see Normal.mean): the
        # probs are f32 math internally but the surface dtype must match the
        # sampled path or a carry built from mean retraces under bf16.
        return self.probs.astype(self._sample_dtype)


class OneHotCategoricalStraightThrough(OneHotCategorical):
    """Straight-through gradient sampling (reference: ``distribution.py:341-372``):
    forward draws a hard one-hot; backward flows through the probabilities."""

    def rsample(self, key, sample_shape=()):
        hard = super().sample(key, sample_shape)
        # The straight-through pass-through rides the sample dtype (the f32
        # probs would otherwise promote the sample and break carry avals).
        probs = self.probs.astype(self._sample_dtype)
        return hard + probs - jax.lax.stop_gradient(probs)

    def sample(self, key, sample_shape=()):
        return self.rsample(key, sample_shape)


class TanhNormal(Distribution):
    """tanh-squashed diagonal Gaussian (SAC actor; the reference builds this
    inline: ``sheeprl/algos/sac/agent.py:57-144``)."""

    def __init__(self, loc: jax.Array, scale: jax.Array):
        self.base = Normal(loc, scale)

    def _pre_sample(self, key, sample_shape=()):
        # f32 pre-squash draw: ``base.sample`` would cast back to the bf16
        # sample dtype, where tanh saturates to exactly ±1 for |pre| ≳ 3.3
        # and the log1p(-action²) correction below returns -inf.
        b = self.base
        shape = tuple(sample_shape) + jnp.broadcast_shapes(jnp.shape(b.loc), jnp.shape(b.scale))
        eps = jax.random.normal(key, shape, dtype=jnp.result_type(b.loc))
        return b.loc + b.scale * eps

    def sample(self, key, sample_shape=()):
        return jnp.tanh(self._pre_sample(key, sample_shape)).astype(self.base._sample_dtype)

    def sample_and_log_prob(self, key, sample_shape=()):
        pre = self._pre_sample(key, sample_shape)
        action = jnp.tanh(pre)
        log_prob = self.base.log_prob(pre) - jnp.log1p(-action**2 + 1e-6)
        return action.astype(self.base._sample_dtype), log_prob

    def log_prob(self, value):
        value = jnp.clip(_lift(value), -1 + 1e-6, 1 - 1e-6)
        pre = jnp.arctanh(value)
        return self.base.log_prob(pre) - jnp.log1p(-value**2 + 1e-6)

    @property
    def mean(self):
        return jnp.tanh(_lift(self.base.mean)).astype(self.base._sample_dtype)

    @property
    def mode(self):
        return jnp.tanh(_lift(self.base.mode)).astype(self.base._sample_dtype)


# -- truncated normal --------------------------------------------------------

_SQRT2 = math.sqrt(2.0)


def _ndtr(x):
    return 0.5 * (1 + jax.lax.erf(x / _SQRT2))


def _log_ndtr(x):
    return jax.scipy.special.log_ndtr(x)


class TruncatedNormal(Distribution):
    """Normal truncated to ``[low, high]``
    (reference: ``sheeprl/utils/distribution.py:25-151``).

    Sampling uses inverse-CDF reparameterization like the reference
    (uniform → icdf), keeping gradients w.r.t. loc/scale.
    """

    def __init__(self, loc, scale, low=-1.0, high=1.0, eps: float = 1e-6):
        if Distribution.validate_args:
            self._check_broadcastable("TruncatedNormal", loc, scale)
            if not (float(low) < float(high)):
                raise ValueError(f"TruncatedNormal: low ({low}) must be < high ({high})")
        self._sample_dtype = jnp.result_type(loc)
        loc, scale = _lift(loc), _lift(scale)
        self.loc = loc
        self.scale = scale
        self.low = low
        self.high = high
        self.eps = eps
        self._alpha = (low - loc) / scale
        self._beta = (high - loc) / scale
        self._phi_alpha = _ndtr(self._alpha)
        self._phi_beta = _ndtr(self._beta)
        self._Z = jnp.clip(self._phi_beta - self._phi_alpha, 1e-8, None)
        self._log_Z = jnp.log(self._Z)

    def _big_phi_inv(self, p):
        return jax.scipy.special.ndtri(jnp.clip(p, 1e-7, 1 - 1e-7))

    def sample(self, key, sample_shape=()):
        shape = tuple(sample_shape) + jnp.broadcast_shapes(jnp.shape(self.loc), jnp.shape(self.scale))
        u = jax.random.uniform(key, shape, dtype=jnp.result_type(self.loc))
        p = self._phi_alpha + u * self._Z
        x = self.loc + self.scale * self._big_phi_inv(p)
        return jnp.clip(x, self.low + self.eps, self.high - self.eps).astype(self._sample_dtype)

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        log_unnorm = -0.5 * z**2 - 0.5 * math.log(2 * math.pi) - jnp.log(self.scale)
        inside = (value >= self.low) & (value <= self.high)
        return jnp.where(inside, log_unnorm - self._log_Z, -jnp.inf)

    def entropy(self):
        # H = log(sqrt(2πe) σ Z) + (α φ(α) − β φ(β)) / (2Z)
        phi = lambda x: jnp.exp(-0.5 * x**2) / math.sqrt(2 * math.pi)  # noqa: E731
        a, b = self._alpha, self._beta
        return (
            0.5 * math.log(2 * math.pi * math.e)
            + jnp.log(self.scale)
            + self._log_Z
            + (a * phi(a) - b * phi(b)) / (2 * self._Z)
        )

    @property
    def mean(self):
        phi = lambda x: jnp.exp(-0.5 * x**2) / math.sqrt(2 * math.pi)  # noqa: E731
        return (self.loc + self.scale * (phi(self._alpha) - phi(self._beta)) / self._Z).astype(self._sample_dtype)

    @property
    def mode(self):
        return jnp.clip(self.loc, self.low, self.high).astype(self._sample_dtype)


# -- Dreamer decoder heads ---------------------------------------------------
# (no _lift on the stored mode: log_prob subtracts against f32 targets, which
# promotes the arithmetic anyway — lifting would materialize the full-pixel
# recon tensor in f32 for nothing)


class SymlogDistribution(Distribution):
    """"Distribution" whose log-prob is the negative MSE in symlog space
    (reference: ``distribution.py:152-195``)."""

    def __init__(self, mode: jax.Array, dims: int, agg: str = "sum"):
        self._mode = mode
        self.dims = dims
        self.agg = agg

    def log_prob(self, value):
        distance = -((self._mode - symlog(value)) ** 2)
        if self.agg == "mean":
            return jnp.mean(distance, axis=tuple(range(-self.dims, 0)))
        return jnp.sum(distance, axis=tuple(range(-self.dims, 0)))

    @property
    def mode(self):
        return symexp(self._mode)

    @property
    def mean(self):
        return symexp(self._mode)


class MSEDistribution(Distribution):
    """Negative-MSE log-prob (reference: ``distribution.py:196-223``)."""

    def __init__(self, mode: jax.Array, dims: int, agg: str = "sum"):
        self._mode = mode
        self.dims = dims
        self.agg = agg

    def log_prob(self, value):
        distance = -((self._mode - value) ** 2)
        if self.agg == "mean":
            return jnp.mean(distance, axis=tuple(range(-self.dims, 0)))
        return jnp.sum(distance, axis=tuple(range(-self.dims, 0)))

    @property
    def mode(self):
        return self._mode

    @property
    def mean(self):
        return self._mode


class TwoHotEncodingDistribution(Distribution):
    """Two-hot categorical over a symexp support
    (reference: ``distribution.py:224-277``). ``dims`` rightmost dims of
    ``logits`` are event dims (always 1 in practice: the bucket axis)."""

    def __init__(
        self,
        logits: jax.Array,
        dims: int = 1,
        low: float = -20.0,
        high: float = 20.0,
        transfwd=symlog,
        transbwd=symexp,
    ):
        logits = _lift(logits)
        self.logits = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
        self.dims = dims
        self.low = low
        self.high = high
        self.fwd = transfwd
        self.bwd = transbwd
        self.bins = jnp.linspace(low, high, logits.shape[-1], dtype=logits.dtype)

    def _default_transforms(self) -> bool:
        # The fused kernels bake in symlog/symexp and a single event dim; any
        # custom transform keeps the inline jnp path below.
        return self.dims == 1 and self.fwd is symlog and self.bwd is symexp

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    @property
    def mean(self):
        if self._default_transforms():
            from sheeprl_tpu.ops.kernels import two_hot_symexp_decode

            return two_hot_symexp_decode(self.logits, self.low, self.high)
        return self.bwd(jnp.sum(self.probs * self.bins, axis=-1, keepdims=True))

    @property
    def mode(self):
        return self.mean

    def log_prob(self, value):
        if self._default_transforms():
            from sheeprl_tpu.ops.kernels import two_hot_symlog_loss

            return two_hot_symlog_loss(self.logits, value, self.low, self.high)
        x = self.fwd(value)
        num_buckets = self.logits.shape[-1]
        # twohot of x over self.bins
        below = jnp.sum((self.bins <= x).astype(jnp.int32), axis=-1, keepdims=True) - 1
        above = num_buckets - jnp.sum((self.bins > x).astype(jnp.int32), axis=-1, keepdims=True)
        below = jnp.clip(below, 0, num_buckets - 1)
        above = jnp.clip(above, 0, num_buckets - 1)
        equal = below == above
        dist_to_below = jnp.where(equal, 1.0, jnp.abs(self.bins[below] - x))
        dist_to_above = jnp.where(equal, 1.0, jnp.abs(self.bins[above] - x))
        total = dist_to_below + dist_to_above
        weight_below = dist_to_above / total
        weight_above = dist_to_below / total
        target = (
            jax.nn.one_hot(below[..., 0], num_buckets, dtype=self.logits.dtype) * weight_below
            + jax.nn.one_hot(above[..., 0], num_buckets, dtype=self.logits.dtype) * weight_above
        )
        log_pred = self.logits
        return jnp.sum(target * log_pred, axis=tuple(range(-self.dims, 0)))


class BernoulliSafeMode(Distribution):
    """Bernoulli whose mode is well-defined at p == 0.5
    (reference: ``distribution.py:407-414``)."""

    def __init__(self, logits: jax.Array):
        self._sample_dtype = jnp.result_type(logits)
        self.logits = _lift(logits)

    @property
    def probs(self):
        return jax.nn.sigmoid(self.logits)

    def sample(self, key, sample_shape=()):
        shape = tuple(sample_shape) + jnp.shape(self.logits)
        u = jax.random.uniform(key, shape)
        return (u < self.probs).astype(self._sample_dtype)

    def log_prob(self, value):
        return -_binary_cross_entropy_with_logits(self.logits, value)

    def entropy(self):
        p = self.probs
        return -(p * jnp.log(p + 1e-8) + (1 - p) * jnp.log(1 - p + 1e-8))

    @property
    def mode(self):
        return (self.probs > 0.5).astype(self._sample_dtype)

    @property
    def mean(self):
        return self.probs


def _binary_cross_entropy_with_logits(logits, labels):
    return jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))


# -- KL ----------------------------------------------------------------------


def kl_divergence(p: Distribution, q: Distribution) -> jax.Array:
    """KL(p‖q) for the pairs the reference registers
    (reference: ``distribution.py:373-405`` + torch built-ins)."""
    if isinstance(p, Independent) and isinstance(q, Independent):
        if p.ndims != q.ndims:
            raise ValueError("Independent KL requires matching event ndims")
        return p._reduce(kl_divergence(p.base, q.base))
    if isinstance(p, OneHotCategorical) and isinstance(q, OneHotCategorical):
        return jnp.sum(p.probs * (p.logits - q.logits), axis=-1)
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    raise NotImplementedError(f"KL not implemented for {type(p).__name__} ‖ {type(q).__name__}")
