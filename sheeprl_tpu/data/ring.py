"""Device-resident sequence ring for burst training (TPU-native; no
reference counterpart).

The reference samples replay windows on the host and ships every batch to the
accelerator (``sheeprl/data/buffers.py:395-528`` feeding the Dreamer train
loops). On a tunneled TPU that is one full wire round-trip per gradient step
plus the batch upload (batch 16 x seq 64 of 64x64 pixels is ~12.6 MB). The
burst design inverts it: raw transitions stream to a device uint8 ring with
per-env write heads, windows are sampled ON device with the
``SequentialReplayBuffer`` validity rule, and a whole chunk of granted
gradient steps runs per dispatch.

Shared by the Dreamer-V1/V2/V3 burst paths; the index math is unit-tested in
``tests/test_algos/test_dreamer_ring.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_append_rows", "ring_sample_windows", "build_burst_train_step"]


def ring_append_rows(pos, valid_n, staged_mask, capacity: int):
    """Per-env ragged ring-append indices (burst mode).

    Slot ``i`` writes env ``e`` iff ``staged_mask[i, e]``; each env's rows
    pack densely from its own write head (mirrors
    ``EnvIndependentReplayBuffer``'s ragged adds). Returns the ``(S, E)``
    row indices (``capacity`` marks dropped/padded slots), the new per-env
    write heads and the new per-env valid counts.
    """
    counts = jnp.cumsum(staged_mask.astype(jnp.int32), axis=0)  # (S, E)
    row = (pos[None, :] + counts - 1) % capacity
    row = jnp.where(staged_mask > 0, row, capacity)
    new_pos = (pos + counts[-1]) % capacity
    new_valid = jnp.minimum(valid_n + counts[-1], capacity)
    return row, new_pos, new_valid


def ring_sample_windows(key, env_idx, pos, valid_n, capacity: int, seq_len: int):
    """Uniform sequence-window starts with the ``SequentialReplayBuffer``
    validity rule: a window never crosses its env's write head (the
    oldest→newest data boundary once the ring is full). Returns ``(T, B)``
    time indices for the given per-element env choices."""
    vn = valid_n[env_idx]
    full = vn >= capacity
    n_starts = jnp.where(full, capacity - seq_len + 1, jnp.maximum(vn - seq_len + 1, 1))
    base = jnp.where(full, pos[env_idx], 0)
    u = jax.random.uniform(key, env_idx.shape)
    start = (base + (u * n_starts).astype(jnp.int32)) % capacity
    return (start[None, :] + jnp.arange(seq_len)[:, None]) % capacity


def build_burst_train_step(
    gradient_step: Callable[[Any, Any], Any],
    mesh,
    ring: Dict[str, Any],
    compiler_options: Dict[str, Any] | None = None,
):
    """Wrap an algo's per-gradient-step update into a ring-owning burst step.

    ``gradient_step(carry, (batch, key)) -> (carry, metrics)`` is the same
    scan body the algo's host-sampled path uses; ``carry`` is an arbitrary
    pytree (params/opts/… — Dreamer-V1 carries 2 leaves groups, V2/V3 add a
    cumulative-step counter and V3 the Moments state). The returned jitted
    function has signature::

        burst_fn(carry, rb, staged, staged_mask, pos, valid_n, key, valid)
            -> (carry, rb, metrics)

    with ``rb`` the device ring dict (donated), ``staged`` the
    ``(S, E, ...)`` host rows, ``staged_mask`` ``(S, E)`` env write masks,
    ``pos``/``valid_n`` the per-env heads, and ``valid`` a
    ``(grad_chunk,)`` 0/1 mask of granted steps (padding steps skip all
    work via ``lax.cond``).
    """
    capacity = int(ring["capacity"])
    ring_envs = int(ring["n_envs"])
    grad_chunk = int(ring["grad_chunk"])
    ring_seq = int(ring["seq_len"])
    ring_batch = int(ring["batch_size"])
    n_dev = mesh.devices.size

    def local_burst(carry, rb, staged, staged_mask, pos, valid_n, key, valid):
        # -- per-env ring append. Slot i writes env e iff staged_mask[i, e];
        # each env's rows pack densely from its own write head (ragged adds).
        row, new_pos, new_valid = ring_append_rows(pos, valid_n, staged_mask, capacity)
        cols = jnp.broadcast_to(jnp.arange(ring_envs)[None, :], row.shape)
        rb = {k: rb[k].at[row, cols].set(staged[k], mode="drop") for k in rb}
        # No env may be shorter than a sample window yet (the host buffer
        # raises in that case); until then every step is a no-op append.
        valid = valid * jnp.all(new_valid >= ring_seq).astype(valid.dtype)

        def sampled_step(c, xs):
            k, valid_flag = xs

            # Padding steps beyond the granted chunk skip EVERYTHING — the
            # window sampling and ring gather live inside the taken branch
            # (lax.cond executes one branch; operands computed outside it
            # would still run unconditionally).
            def _run(c):
                k_env, k_start, k_grad = jax.random.split(k, 3)
                B = ring_batch // n_dev
                env_idx = jax.random.randint(k_env, (B,), 0, ring_envs)
                t_idx = ring_sample_windows(
                    k_start, env_idx, new_pos, new_valid, capacity, ring_seq
                )  # (T, B)
                batch = {kk: rb[kk][t_idx, env_idx[None, :]] for kk in rb}
                nc, m = gradient_step(c, (batch, k_grad))
                # Metrics may be a tuple (Dreamers) or a dict (P2E) — keep
                # the structure, normalize the dtype for the masked mean.
                return nc, jax.tree.map(lambda x: x.astype(jnp.float32), m)

            # Zero metrics derived from the true branch's structure, so the
            # two cond branches can never drift apart.
            metrics_shape = jax.eval_shape(_run, c)[1]
            zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), metrics_shape)
            new_carry, metrics = jax.lax.cond(valid_flag > 0, _run, lambda cc: (cc, zeros), c)
            return new_carry, metrics

        key = jax.random.fold_in(key, jax.lax.axis_index("dp"))
        keys = jax.random.split(key, grad_chunk)
        carry, metrics = jax.lax.scan(sampled_step, carry, (keys, valid))
        # Average over the GRANTED steps only (padding contributes zeros).
        denom = jnp.maximum(valid.sum(), 1.0)
        metrics = jax.tree.map(lambda x: jax.lax.pmean((x * valid).sum() / denom, "dp"), metrics)
        return carry, rb, metrics

    shard_burst = jax.shard_map(
        local_burst,
        mesh=mesh,
        in_specs=(P(),) * 8,
        out_specs=(P(),) * 3,
        check_vma=False,
    )
    # Only the ring is donated: the carry handles (params/opts/...) are read
    # by the main thread (checkpoints) while a burst may be in flight —
    # donation would hand it deleted buffers.
    return jax.jit(shard_burst, donate_argnums=(1,), compiler_options=compiler_options)
